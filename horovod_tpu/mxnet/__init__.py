"""MXNet binding: Horovod's mxnet API over the TPU-native eager runtime.

Reference equivalents: ``horovod/mxnet/__init__.py`` (DistributedOptimizer
rescaling + allreduce-in-update :40-77, gluon DistributedTrainer :85-105,
broadcast_parameters with deferred-init handling :109-154) and
``horovod/mxnet/mpi_ops.py`` (ctypes op surface :52-120).

TPU-native redesign: like the torch binding, MXNet arrays live in host
memory (the TPU compute path is JAX/XLA) and ride the eager TCP plane via
numpy; the optimizer/trainer/broadcast semantics match the reference so a
Horovod-MXNet user changes only the import.

Runtime evidence: MXNet is not installable in this image (archived
upstream, no py>=3.12 wheel), so CI executes this binding end-to-end
under a live 2-rank launcher job against ``tests/mxnet_api_shim.py`` —
an API-faithful numpy-backed stand-in (the same pattern as the pyspark
shim): DistributedOptimizer single+grouped updates, DistributedTrainer
steps, and broadcast_parameters incl. the deferred-init hook all run for
real (``tests/distributed/test_mxnet_binding.py``).  With real mxnet on
the path (opt-in py3.11 Docker stage, docs/docker.md) the shim steps
aside and the same suite runs against it unchanged.
"""

from __future__ import annotations

import numpy as np

try:
    import mxnet as mx
except ImportError as _e:  # pragma: no cover - exercised only sans mxnet
    raise ImportError(
        "horovod_tpu.mxnet requires mxnet (pip install mxnet); the JAX, "
        "PyTorch, TensorFlow and Keras bindings have no such dependency"
    ) from _e

from horovod_tpu import basics
from horovod_tpu.basics import (  # noqa: F401  (API parity re-exports)
    init, shutdown, is_initialized, rank, size, local_rank, local_size,
    cross_rank, cross_size, mpi_threads_supported, mpi_built, mpi_enabled,
    gloo_built, gloo_enabled, nccl_built, ddl_built, mlsl_built,
    tpu_built, tpu_enabled,
)
from horovod_tpu.ops import collective as _c
from horovod_tpu.ops.collective import (  # noqa: F401
    Average, Sum, Adasum, Min, Max,
)


def _to_numpy(tensor) -> np.ndarray:
    return tensor.asnumpy()


def _from_numpy(arr: np.ndarray, like):
    out = mx.nd.array(np.ascontiguousarray(arr), dtype=arr.dtype)
    if like is not None and like.context is not None:
        out = out.as_in_context(like.context)
    return out


# ---------------------------------------------------------------------------
# Collectives on NDArrays (reference mxnet/mpi_ops.py:52-120)
# ---------------------------------------------------------------------------

def allreduce(tensor, average=None, name=None, op=None,
              prescale_factor=1.0, postscale_factor=1.0):
    basics._check_initialized()
    rop = _c._resolve_op(op, average)
    nm = _c._auto_name("allreduce", name)
    out = _c._eager_allreduce(_to_numpy(tensor), rop, nm, prescale_factor,
                              postscale_factor)
    return _from_numpy(out, tensor)


def allreduce_(tensor, average=None, name=None, op=None):
    """In-place variant (reference ``hvd.allreduce_``)."""
    out = allreduce(tensor, average=average, name=name, op=op)
    tensor[:] = out
    return tensor


def allgather(tensor, name=None):
    basics._check_initialized()
    nm = _c._auto_name("allgather", name)
    return _from_numpy(_c._eager_allgather(_to_numpy(tensor), nm), tensor)


def broadcast(tensor, root_rank, name=None):
    basics._check_initialized()
    nm = _c._auto_name("broadcast", name)
    return _from_numpy(
        _c._eager_broadcast(_to_numpy(tensor), root_rank, nm), tensor)


def broadcast_(tensor, root_rank, name=None):
    out = broadcast(tensor, root_rank, name=name)
    tensor[:] = out
    return tensor


def alltoall(tensor, splits=None, name=None):
    basics._check_initialized()
    nm = _c._auto_name("alltoall", name)
    if splits is not None and isinstance(splits, mx.nd.NDArray):
        splits = splits.asnumpy()
    out, received = _c._eager_alltoall(_to_numpy(tensor), splits, nm)
    if splits is not None:
        return _from_numpy(out, tensor), mx.nd.array(received,
                                                     dtype="int64")
    return _from_numpy(out, tensor)


def broadcast_object(obj, root_rank=0, name=None):
    return _c.broadcast_object(obj, root_rank=root_rank, name=name)


def allgather_object(obj, name=None):
    return _c.allgather_object(obj, name=name)


# ---------------------------------------------------------------------------
# DistributedOptimizer / DistributedTrainer (reference mxnet/__init__.py)
# ---------------------------------------------------------------------------

class DistributedOptimizer(mx.optimizer.Optimizer):
    """Wrap an mxnet optimizer: gradients are summed across ranks inside
    ``update`` and ``rescale_grad`` is divided by the world size so the
    result is the cross-rank mean (reference ``mxnet/__init__.py:40-77``)."""

    def __init__(self, optimizer):
        self._optimizer = optimizer
        # Reference divides rescale_grad by size so sum-allreduce == mean.
        self._optimizer.rescale_grad /= basics.size()

    def __getattr__(self, item):
        return getattr(self._optimizer, item)

    def _do_allreduce(self, index, grad):
        if basics.size() == 1:
            return
        if isinstance(index, (tuple, list)):
            for i in range(len(index)):
                allreduce_(grad[i], op=Sum,
                           name=f"allreduce.grad.{index[i]}")
        else:
            allreduce_(grad, op=Sum, name=f"allreduce.grad.{index}")

    def update(self, index, weight, grad, state):
        self._do_allreduce(index, grad)
        self._optimizer.update(index, weight, grad, state)

    def update_multi_precision(self, index, weight, grad, state):
        self._do_allreduce(index, grad)
        self._optimizer.update_multi_precision(index, weight, grad, state)

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    def set_lr_mult(self, args_lr_mult):
        self._optimizer.set_lr_mult(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self._optimizer.set_wd_mult(args_wd_mult)


class DistributedTrainer(mx.gluon.Trainer):
    """Gluon trainer that averages gradients across ranks (reference
    ``mxnet/__init__.py:85-105``)."""

    def __init__(self, params, optimizer, optimizer_params=None):
        if isinstance(optimizer, DistributedOptimizer):
            optimizer = optimizer._optimizer
        super().__init__(params, optimizer, optimizer_params,
                         kvstore=None)
        # Reference scales rescale_grad here too (Trainer bypasses
        # Optimizer.update's wrapping).
        self._scale /= basics.size()

    def _allreduce_grads(self):
        if basics.size() == 1:
            return
        for i, param in enumerate(self._params):
            if param.grad_req != "null":
                for grad in param.list_grad():
                    allreduce_(grad, op=Sum,
                               name=f"allreduce.trainer.{i}")


def broadcast_parameters(params, root_rank=0):
    """Broadcast gluon/module parameters from ``root_rank``, in place.

    Accepts a ``dict`` of NDArrays (Module ``arg_params``/``aux_params``)
    or a gluon ``ParameterDict``.  Deferred-initialization parameters are
    broadcast lazily the moment their data materializes, mirroring the
    reference's deferred-init wrapper (``mxnet/__init__.py:109-154``)."""
    if params is None:
        return
    tensors = {}
    deferred = []
    if isinstance(params, dict):
        tensors = {k: v for k, v in sorted(params.items())}
    else:  # gluon ParameterDict
        for name, p in sorted(params.items()):
            try:
                tensors[name] = p.data()
            except mx.gluon.parameter.DeferredInitializationError:
                deferred.append((name, p))
    for name, t in tensors.items():
        broadcast_(t, root_rank, name=f"broadcast_parameters.{name}")
    for name, p in deferred:
        # Wrap the parameter's init so the broadcast fires right after the
        # data shape is known on every rank.
        orig = p._finish_deferred_init

        def wrapped(_p=p, _name=name, _orig=orig):
            _orig()
            for d in _p.list_data():
                broadcast_(d, root_rank,
                           name=f"broadcast_parameters.{_name}")
        p._finish_deferred_init = wrapped

"""TensorFlow 2 binding: Horovod's TF API over the TPU-native eager runtime.

Reference equivalents: ``horovod/tensorflow/mpi_ops.cc`` (async kernels
:276-463), ``horovod/tensorflow/mpi_ops.py`` (op wrappers + registered
gradients :85-180), ``horovod/tensorflow/__init__.py`` (``allreduce`` with
IndexedSlices path :38-83, ``broadcast_variables`` :104-117,
``BroadcastGlobalVariablesHook`` :159-192, ``_DistributedOptimizer``
:230-320, ``DistributedGradientTape`` :323-376).

TPU-native redesign: the reference registers custom TF kernels that enqueue
into the MPI background thread.  Here TF tensors ride the eager plane (the
native TCP runtime) through ``tf.py_function`` — which executes eagerly even
inside a ``tf.function`` graph, giving one code path for both eager and
graph mode — and gradients are attached with ``tf.custom_gradient`` rather
than ``ops.RegisterGradient``.  The TPU compute path proper is JAX/XLA
(``horovod_tpu`` SPMD API); this binding exists so TF user code keeps
working unchanged, same contract as the torch binding.
"""

from __future__ import annotations

import numpy as np
import tensorflow as tf

from horovod_tpu import basics
from horovod_tpu.basics import (  # noqa: F401  (API parity re-exports)
    init, shutdown, is_initialized, rank, size, local_rank, local_size,
    cross_rank, cross_size, mpi_threads_supported, mpi_built, mpi_enabled,
    gloo_built, gloo_enabled, nccl_built, ddl_built, mlsl_built,
    tpu_built, tpu_enabled,
)
from horovod_tpu.ops import collective as _c
from horovod_tpu.ops.collective import (  # noqa: F401
    Average, Sum, Adasum, Min, Max, join,
)


class Compression:
    """Gradient wire compression (reference ``tensorflow/compression.py``)."""

    class none:
        @staticmethod
        def compress(tensor):
            return tensor, None

        @staticmethod
        def decompress(tensor, ctx):
            return tensor

    class fp16:
        @staticmethod
        def compress(tensor):
            if tensor.dtype in (tf.float32, tf.float64):
                return tf.cast(tensor, tf.float16), tensor.dtype
            return tensor, None

        @staticmethod
        def decompress(tensor, ctx):
            return tensor if ctx is None else tf.cast(tensor, ctx)


import re as _re

# Grappler's dependency optimizer prunes the control-dependency chain that
# _py_collective builds between collectives (verified: with it enabled the
# chain exists in the traced FuncGraph but runtime execution interleaves;
# with only this pass off, ordering holds).  Without the chain, two ranks
# can block inside *different* collectives and deadlock — see
# _py_collective's docstring.  Scoped to the process, set at import like
# the reference sets graph-level options in its op library load.
tf.config.optimizer.set_experimental_options(
    {"dependency_optimization": False})

def _tf_node_name(name):
    """Wire names (dots, ':0' variable suffixes) → valid TF op names."""
    return _re.sub(r"[^A-Za-z0-9_.\-/>]", "_", name.replace(".", "_"))


import threading as _threading

_tokens: dict = {}
_tokens_lock = _threading.Lock()
_tokens_next = [0]


def _use_async_graph():
    """Async (enqueue node + sync node) is safe where EVERY traced node
    executes: tf.function FuncGraphs auto-execute stateful ops.  A TF1
    session prunes nodes outside the fetch closure, so TF1 graphs keep
    the serialized single-node path by default (as does
    HOROVOD_TF_SYNC_COLLECTIVES=1).

    ``HOROVOD_TF1_ASYNC=1`` opts TF1 session graphs into the async path:
    pruning is harmless there once two facts line up — (a) fetches are
    rank-SYMMETRIC (the same contract Horovod already imposes on op
    order), so a pruned enqueue is pruned on every rank and a surviving
    enqueue negotiates + executes on every rank (the wire name leaves
    the native table at execution, not at the sync's wait); (b) the
    handle a pruned sync never waits is reclaimed by stale-token
    reaping at the NEXT enqueue of the same wire name
    (:func:`_pop_stale`).  See docs/frameworks.md."""
    import os
    if tf.executing_eagerly():
        return False
    if os.environ.get("HOROVOD_TF_SYNC_COLLECTIVES", "0") == "1":
        return False
    try:
        from tensorflow.python.framework.func_graph import FuncGraph
        if isinstance(tf.compat.v1.get_default_graph(), FuncGraph):
            return True
    except ImportError:   # private-API drift: fail safe (serialized)
        return False
    return os.environ.get("HOROVOD_TF1_ASYNC", "0") == "1"


def _unique_wire_name(name):
    """Wire names must be unique among IN-FLIGHT tensors.  The async path
    has a whole step's enqueues outstanding at once, so a user-supplied
    name appearing twice in one traced step (e.g. gradient accumulation
    calling the wrapper twice) would hit the native duplicate guard.
    Deduplicate at TRACE time per graph — deterministic across ranks
    (same trace order), stable across executions (fixed in the graph)."""
    graph = tf.compat.v1.get_default_graph()
    used = getattr(graph, "_hvd_wire_names", None)
    if used is None:
        used = graph._hvd_wire_names = set()
    if name not in used:
        used.add(name)
        return name
    i = 2
    while f"{name}.~{i}" in used:
        i += 1
    uname = f"{name}.~{i}"
    used.add(uname)
    return uname


def _wire_name(kind, name):
    """Resolve the wire name at trace time; in async graph mode also
    deduplicate within the graph (see _unique_wire_name)."""
    nm = _c._auto_name(kind, name)
    if _use_async_graph():
        nm = _unique_wire_name(nm)
    return nm


def _py_collective(submit, finish, inputs, out_dtype, name):
    """Run a numpy-plane collective as a TF op pair.

    ``submit(*np_arrays) -> token`` performs the NON-BLOCKING native
    enqueue (``hvd_enqueue``, microseconds); ``finish(token) -> result``
    blocks in ``hvd_wait`` (GIL released) and reads the output.

    Graph mode traces TWO py_function nodes per collective — the
    reference's async-kernel design (``tensorflow/mpi_ops.cc:276-281``)
    expressed in py_functions:

    * the **enqueue** node runs ``submit`` and passes an integer key for
      the token.  Enqueue nodes are chained with control dependencies in
      trace order — free (non-blocking) and it pins a deterministic
      cross-rank submission order.
    * the **sync** node data-depends on the key and runs ``finish``.

    The TF executor can therefore run EVERY enqueue as soon as its
    gradient is ready; the native background loop sees many tensors per
    cycle and batches their negotiation + transfers (fusion), instead of
    one blocking round trip per gradient.  Measured on the allreduce
    burst microbench: ~3.7x over the serialized path at 2 ranks.
    ``HOROVOD_TF_SYNC_COLLECTIVES=1`` restores the serialized fallback.
    Eager mode stays synchronous per call (as the reference's eager
    path does)."""
    fused = lambda *vs: finish(submit(*vs))
    if not _use_async_graph():
        return _py_collective_sync(fused, inputs, out_dtype, name)

    assert len(inputs) == 1
    hid = _py_enqueue_node(submit, inputs[0], name)

    def wait(h):
        with _tokens_lock:
            tok = _tokens.pop(int(h.numpy()))
        return finish(tok)

    out = tf.py_function(wait, [hid], Tout=out_dtype,
                         name=_tf_node_name(name))
    return out


def _py_collective_sync(fn, inputs, out_dtype, name):
    """One blocking py_function per collective, chained in trace order (the
    graph executor runs exactly one collective at a time — no fusion).
    The pre-r3 behavior; kept as a debugging fallback and for A/B
    measurement (HOROVOD_TF_SYNC_COLLECTIVES=1)."""
    if tf.executing_eagerly():
        return tf.py_function(fn, inputs, Tout=out_dtype,
                              name=_tf_node_name(name))
    graph = tf.compat.v1.get_default_graph()
    prev = getattr(graph, "_hvd_collective_chain", None)
    if prev is not None:
        with tf.control_dependencies([prev]):
            out = tf.py_function(fn, inputs, Tout=out_dtype,
                                 name=_tf_node_name(name))
    else:
        out = tf.py_function(fn, inputs, Tout=out_dtype,
                             name=_tf_node_name(name))
    graph._hvd_collective_chain = out[0] if isinstance(out, list) else out
    return out


def _allreduce(tensor, name=None, op=None, prescale_factor=1.0,
               postscale_factor=1.0):
    """Low-level allreduce on a dense tf.Tensor (reference
    ``tensorflow/mpi_ops.py:62-100``).  Gradient of a sum-allreduce is a
    sum-allreduce of the upstream gradient (``mpi_ops.py:89-100``)."""
    basics._check_initialized()
    rop = _c._resolve_op(op, None) if op is not None else Sum
    nm = _wire_name("allreduce", name)

    @tf.custom_gradient
    def fn(x):
        submit = lambda v: _c._eager_allreduce_submit(
            v.numpy(), rop, nm, prescale_factor)
        finish = lambda tok: tf.convert_to_tensor(
            _c._eager_allreduce_finish(tok, rop, postscale_factor))
        out = _py_collective(submit, finish, [x], x.dtype, nm)
        out.set_shape(x.shape)

        def grad(dy):
            return _allreduce(dy, name=nm + ".grad", op=Sum)

        return out, grad

    return fn(tf.convert_to_tensor(tensor))


def allreduce(tensor, average=True, device_dense='', device_sparse='',
              compression=Compression.none, name=None, op=None,
              prescale_factor=1.0, postscale_factor=1.0):
    """Allreduce a tf.Tensor or tf.IndexedSlices (reference
    ``tensorflow/__init__.py:38-83``): IndexedSlices becomes an allgather of
    values+indices; dense rides compression → allreduce → decompress, with
    the average applied after the sum.  ``device_*`` args are accepted for
    API parity and ignored (placement is XLA's job on TPU)."""
    if isinstance(tensor, tf.IndexedSlices):
        values = allgather(tensor.values)
        indices = allgather(tensor.indices)
        if average:
            values = values / tf.cast(size(), values.dtype)
        return tf.IndexedSlices(values, indices,
                                dense_shape=tensor.dense_shape)
    tensor = tf.convert_to_tensor(tensor)
    if op is None and average:
        op = Average
    if op is Average:
        # Sum on the wire, divide locally — same math as the reference
        # (divide after _allreduce, tensorflow/__init__.py:82).
        summed = allreduce(tensor, average=False, compression=compression,
                           name=name, op=Sum,
                           prescale_factor=prescale_factor,
                           postscale_factor=postscale_factor)
        return summed / tf.cast(size(), tensor.dtype)
    compressed, ctx = compression.compress(tensor)
    out = _allreduce(compressed, name=name, op=op or Sum,
                     prescale_factor=prescale_factor,
                     postscale_factor=postscale_factor)
    return compression.decompress(out, ctx)


def grouped_allreduce(tensors, average=True, name=None, op=None,
                      compression=Compression.none, prescale_factor=1.0,
                      postscale_factor=1.0):
    """Allreduce a LIST of dense tensors as one group: every tensor is
    async-enqueued (its own non-blocking native enqueue, chained in trace
    order) and a SINGLE sync node waits for the whole group — so all N
    negotiations are in flight together and the runtime batches them into
    shared cycles (fusion), at ~half the py_function dispatch cost of N
    independent allreduces.  This is the op the gradient-aggregation
    wrappers use; one sync barrier per step, as the reference achieves
    with its truly-async kernels (``tensorflow/mpi_ops.cc:276-281``)."""
    basics._check_initialized()
    if not tensors:
        return []
    rop = _c._resolve_op(op, None) if op is not None else (
        Average if average else Sum)
    nm = _wire_name("grouped_allreduce", name)
    n = basics.size()
    wire_op = Sum if rop is Average else rop   # sum on wire, divide local

    compressed, ctxs = zip(*[compression.compress(tf.convert_to_tensor(t))
                             for t in tensors])

    @tf.custom_gradient
    def fn(*xs):
        # Same safety gate as _py_collective: the async enqueue+sync pair
        # is only valid in FuncGraphs (a TF1 session could prune the sync
        # node and wedge the native tensor table), and _use_async_graph is
        # also where the wire-name dedup contract lives.
        sync = not _use_async_graph()
        dtypes = [x.dtype for x in xs]
        if sync:
            outs = [_allreduce(x, name=f"{nm}.{i}", op=wire_op,
                               prescale_factor=prescale_factor,
                               postscale_factor=postscale_factor)
                    for i, x in enumerate(xs)]
        else:
            keys = [_py_enqueue_node(
                lambda v, i=i: _c._eager_allreduce_submit(
                    v.numpy(), wire_op, f"{nm}.{i}", prescale_factor),
                x, f"{nm}.{i}") for i, x in enumerate(xs)]

            def wait_all(*ks):
                # Pop every token up front: if finish(k) raises, the
                # remaining handles must still be waited/released or
                # their wire names wedge the native tensor table and
                # every later step fails with DuplicateNameError.
                with _tokens_lock:
                    toks = [_tokens.pop(int(k.numpy())) for k in ks]
                res, first_err = [], None
                for tok in toks:
                    try:
                        res.append(tf.convert_to_tensor(
                            _c._eager_allreduce_finish(
                                tok, wire_op, postscale_factor)))
                    except Exception as e:   # drain the rest, then raise
                        if first_err is None:
                            first_err = e
                if first_err is not None:
                    raise first_err
                return res

            outs = tf.py_function(wait_all, keys, Tout=dtypes,
                                  name=_tf_node_name(nm) + "_sync")
            if not isinstance(outs, (list, tuple)):
                outs = [outs]
            for o, x in zip(outs, xs):
                o.set_shape(x.shape)

        def grad(*dys):
            return grouped_allreduce(list(dys), name=nm + ".grad", op=Sum)

        return list(outs), grad

    outs = fn(*compressed)
    outs = [compression.decompress(o, c) for o, c in zip(outs, ctxs)]
    if rop is Average:
        outs = [o / tf.cast(n, o.dtype) for o in outs]
    return outs


_inflight_by_name: dict = {}


def _pop_stale(name):
    """Pop the previous token for ``name`` if its sync node never ran
    (TF1 fetch-closure pruning).  Returns the stale native handle (or
    None).  In FuncGraph mode syncs always run, the key is gone, and
    this is a no-op.  Caller holds the tokens lock."""
    key = _inflight_by_name.pop(name, None)
    if key is None or key not in _tokens:
        return None
    return _tokens.pop(key)[0]


def _py_enqueue_node(submit, x, name):
    """Trace one non-blocking enqueue py_function (chained) returning the
    token key tensor.  The chain head lives on the FuncGraph itself: a
    side dict keyed by graph would pin every retraced graph forever (the
    stored output tensor strongly references its graph)."""
    def enqueue(v):
        with _tokens_lock:
            stale = _pop_stale(name)
        if stale is not None:
            # The pruned predecessor completed on every rank (enqueues
            # are rank-symmetric, session.run synchronous): wait it out
            # and free its buffer + table entry before reusing the name.
            basics.runtime().discard(stale)
        tok = submit(v)
        with _tokens_lock:
            key = _tokens_next[0]
            _tokens_next[0] += 1
            _tokens[key] = tok
            _inflight_by_name[name] = key
        return np.int64(key)

    graph = tf.compat.v1.get_default_graph()
    prev = getattr(graph, "_hvd_collective_chain", None)
    if prev is not None:
        with tf.control_dependencies([prev]):
            hid = tf.py_function(enqueue, [x], Tout=tf.int64,
                                 name=_tf_node_name(name) + "_enqueue")
    else:
        hid = tf.py_function(enqueue, [x], Tout=tf.int64,
                             name=_tf_node_name(name) + "_enqueue")
    graph._hvd_collective_chain = hid
    return hid


def allgather(tensor, name=None):
    """Concatenate tensors from all ranks on dim 0; dim 0 may differ per
    rank (reference ``tensorflow/mpi_ops.py:103-145``).  Gradient:
    allreduce the upstream gradient, then slice out this rank's rows."""
    basics._check_initialized()
    nm = _wire_name("allgather", name)

    @tf.custom_gradient
    def fn(x):
        submit = lambda v: _c._eager_allgather_submit(v.numpy(), nm)
        finish = lambda tok: tf.convert_to_tensor(
            _c._eager_allgather_finish(tok))
        out = _py_collective(submit, finish, [x], x.dtype, nm)
        out.set_shape(tf.TensorShape([None]).concatenate(x.shape[1:]))

        def grad(dy):
            summed = _allreduce(dy, name=nm + ".grad", op=Sum)
            # Per-rank dim-0 sizes, exchanged over the wire (reference
            # mpi_ops.py:122-145 gathers d0 and splits).
            d0 = tf.shape(x)[0:1]
            sizes = allgather(tf.cast(d0, tf.int32), name=nm + ".grad.sizes")
            sizes = tf.reshape(sizes, [size()])
            splits = tf.split(summed, num_or_size_splits=sizes, axis=0)
            return splits[rank()]

        return out, grad

    return fn(tf.convert_to_tensor(tensor))


def broadcast(tensor, root_rank, name=None):
    """Broadcast from ``root_rank`` (reference
    ``tensorflow/mpi_ops.py:148-180``).  Gradient: allreduce to the root;
    zero elsewhere."""
    basics._check_initialized()
    nm = _wire_name("broadcast", name)

    @tf.custom_gradient
    def fn(x):
        submit = lambda v: _c._eager_broadcast_submit(v.numpy(), root_rank,
                                                      nm)
        finish = lambda tok: tf.convert_to_tensor(
            _c._eager_broadcast_finish(tok))
        out = _py_collective(submit, finish, [x], x.dtype, nm)
        out.set_shape(x.shape)

        def grad(dy):
            reduced = _allreduce(dy, name=nm + ".grad", op=Sum)
            if rank() != root_rank:
                return reduced * 0
            return reduced

        return out, grad

    return fn(tf.convert_to_tensor(tensor))


def alltoall(tensor, splits=None, name=None):
    """Scatter slices of ``tensor`` to every rank and gather theirs
    (beyond-reference op; the reference era had no alltoall)."""
    basics._check_initialized()
    nm = _wire_name("alltoall", name)
    tensor = tf.convert_to_tensor(tensor)

    submit = lambda v: _c._eager_alltoall_submit(v.numpy(), splits, nm)
    if splits is not None:
        # Later-Horovod contract: (output, received_splits) with splits —
        # a two-output py_function so graph mode threads both through.
        def finish2(tok):
            out, received = _c._eager_alltoall_finish(tok)
            return tf.convert_to_tensor(out), tf.convert_to_tensor(received)

        out, received = _py_collective(submit, finish2, [tensor],
                                       [tensor.dtype, tf.int64], nm)
        out.set_shape(tf.TensorShape([None]).concatenate(tensor.shape[1:]))
        received.set_shape([basics.size()])
        return out, received

    def finish(tok):
        out, _ = _c._eager_alltoall_finish(tok)
        return tf.convert_to_tensor(out)

    out = _py_collective(submit, finish, [tensor], tensor.dtype, nm)
    out.set_shape(tf.TensorShape([None]).concatenate(tensor.shape[1:]))
    return out


def reducescatter(tensor, op=None, name=None):
    basics._check_initialized()
    rop = _c._resolve_op(op, None)
    nm = _wire_name("reducescatter", name)
    tensor = tf.convert_to_tensor(tensor)

    submit = lambda v: _c._eager_reducescatter_submit(v.numpy(), rop, nm)
    finish = lambda tok: tf.convert_to_tensor(
        _c._eager_reducescatter_finish(tok, rop))
    out = _py_collective(submit, finish, [tensor], tensor.dtype, nm)
    out.set_shape(tf.TensorShape([None]).concatenate(tensor.shape[1:]))
    return out


def broadcast_object(obj, root_rank=0, name=None):
    return _c.broadcast_object(obj, root_rank=root_rank, name=name)


def allgather_object(obj, name=None):
    return _c.allgather_object(obj, name=name)


# ---------------------------------------------------------------------------
# Variable broadcast (reference tensorflow/__init__.py:88-192)
# ---------------------------------------------------------------------------

def broadcast_variables(variables, root_rank=0):
    """Assign every variable the root rank's value (reference
    ``broadcast_variables``, ``tensorflow/__init__.py:104-117``).  Used for
    consistent init and checkpoint-restore fan-out (§5.4 of the survey)."""
    for i, var in enumerate(variables):
        vname = getattr(var, "name", None) or f"var.{i}"
        var.assign(broadcast(var, root_rank,
                             name=f"broadcast_variables.{vname}"))


def broadcast_global_variables(root_rank=0):
    """TF1-compat: broadcast the default graph's global variables
    (reference ``tensorflow/__init__.py:125-140``)."""
    if tf.executing_eagerly():
        raise RuntimeError(
            "hvd.broadcast_global_variables() does not support eager "
            "execution. Please use `hvd.broadcast_variables(<model/optimizer "
            "variables>)` instead.")
    return broadcast_variables(tf.compat.v1.global_variables(), root_rank)


try:
    _SessionRunHook = tf.compat.v1.train.SessionRunHook
except AttributeError:  # estimator surface removed in a future TF
    _SessionRunHook = None

if _SessionRunHook is not None:
    class BroadcastGlobalVariablesHook(_SessionRunHook):
        """SessionRunHook broadcasting global variables once after session
        creation (reference ``tensorflow/__init__.py:159-192``)."""

        def __init__(self, root_rank=0, device=''):
            super().__init__()
            self.root_rank = root_rank
            self.device = device  # parity-only; placement is XLA's job
            self.bcast_op = None

        def begin(self):
            if (not self.bcast_op or
                    self.bcast_op.graph != tf.compat.v1.get_default_graph()):
                self.bcast_op = broadcast_global_variables(self.root_rank)

        def after_create_session(self, session, coord):
            session.run(self.bcast_op)


# ---------------------------------------------------------------------------
# Gradient aggregation wrappers (reference tensorflow/__init__.py:195-376)
# ---------------------------------------------------------------------------

def _make_allreduce_grads_fn(name, compression, sparse_as_dense):
    """Shared grads→averaged-grads transform (reference
    ``_make_allreduce_grads_fn``, ``tensorflow/__init__.py:195-216``)."""
    def allreduce_grads(grads):
        with tf.name_scope(name + "_Allreduce"):
            if sparse_as_dense:
                grads = [tf.convert_to_tensor(g)
                         if g is not None and isinstance(g, tf.IndexedSlices)
                         else g for g in grads]
            # Dense gradients ride ONE grouped allreduce (async enqueues +
            # a single sync barrier, so the runtime fuses the step's
            # negotiations); sparse/None keep their per-tensor paths.
            dense_ix = [i for i, g in enumerate(grads)
                        if g is not None and
                        not isinstance(g, tf.IndexedSlices)]
            reduced = list(grads)
            if dense_ix:
                outs = grouped_allreduce(
                    [grads[i] for i in dense_ix], average=True,
                    name=f"{name}.grads", compression=compression)
                for i, o in zip(dense_ix, outs):
                    reduced[i] = o
            for i, g in enumerate(grads):
                if g is not None and isinstance(g, tf.IndexedSlices):
                    reduced[i] = allreduce(g, compression=compression,
                                           name=f"{name}.grad.{i}")
            return reduced
    return allreduce_grads


class _DistributedGradientTape(tf.GradientTape):
    def __init__(self, tape, compression, sparse_as_dense,
                 persistent=False, watch_accessed_variables=True):
        super(self.__class__, self).__init__(persistent,
                                             watch_accessed_variables)
        self._tape = tape
        self._allreduce_grads = _make_allreduce_grads_fn(
            "DistributedGradientTape", compression, sparse_as_dense)

    def gradient(self, target, sources, output_gradients=None):
        gradients = super(self.__class__, self).gradient(
            target, sources, output_gradients)
        if size() > 1:
            return self._allreduce_grads(gradients)
        return gradients


def DistributedGradientTape(gradtape, device_dense='', device_sparse='',
                            compression=Compression.none,
                            sparse_as_dense=False):
    """Wrap a ``tf.GradientTape`` so ``gradient()`` returns cross-rank
    averages (reference ``tensorflow/__init__.py:323-376``; same dynamic
    subclassing trick so user ``isinstance`` checks keep working)."""
    cls = type(gradtape.__class__.__name__, (gradtape.__class__,),
               dict(_DistributedGradientTape.__dict__))
    if hasattr(gradtape, '_watch_accessed_variables'):
        return cls(gradtape._tape, compression, sparse_as_dense,
                   gradtape._persistent, gradtape._watch_accessed_variables)
    return cls(gradtape._tape, compression, sparse_as_dense,
               gradtape._persistent)


try:
    _LegacyOptimizer = tf.compat.v1.train.Optimizer
except AttributeError:
    _LegacyOptimizer = None

if _LegacyOptimizer is not None:
    class _DistributedOptimizer(_LegacyOptimizer):
        """TF1-style optimizer wrapper: ``compute_gradients`` also
        allreduces (reference ``tensorflow/__init__.py:230-320``)."""

        def __init__(self, optimizer, name=None, use_locking=False,
                     device_dense='', device_sparse='',
                     compression=Compression.none, sparse_as_dense=False):
            if name is None:
                name = "Distributed{}".format(type(optimizer).__name__)
            super(_DistributedOptimizer, self).__init__(
                name=name, use_locking=use_locking)
            self._optimizer = optimizer
            self._allreduce_grads = _make_allreduce_grads_fn(
                name, compression, sparse_as_dense)

        def compute_gradients(self, *args, **kwargs):
            gradients = self._optimizer.compute_gradients(*args, **kwargs)
            if size() > 1:
                grads, variables = zip(*gradients)
                avg_grads = self._allreduce_grads(grads)
                return list(zip(avg_grads, variables))
            return gradients

        def apply_gradients(self, *args, **kwargs):
            return self._optimizer.apply_gradients(*args, **kwargs)

        def get_slot(self, *args, **kwargs):
            return self._optimizer.get_slot(*args, **kwargs)

        def get_slot_names(self, *args, **kwargs):
            return self._optimizer.get_slot_names(*args, **kwargs)

        def variables(self, *args, **kwargs):
            return self._optimizer.variables(*args, **kwargs)


def DistributedOptimizer(optimizer, name=None, use_locking=False,
                         device_dense='', device_sparse='',
                         compression=Compression.none,
                         sparse_as_dense=False):
    """Wrap a TF1 legacy or Keras optimizer (reference
    ``tensorflow/__init__.py:278-320`` dispatch)."""
    if _LegacyOptimizer is not None and isinstance(optimizer,
                                                   _LegacyOptimizer):
        return _DistributedOptimizer(optimizer, name, use_locking,
                                     device_dense, device_sparse,
                                     compression, sparse_as_dense)
    try:
        import keras
        is_keras = isinstance(optimizer, keras.optimizers.Optimizer)
    except ImportError:
        is_keras = False
    if is_keras:
        from horovod_tpu import keras as hvd_keras
        return hvd_keras.DistributedOptimizer(
            optimizer, name=name, compression=compression,
            sparse_as_dense=sparse_as_dense)
    raise ValueError(
        "Provided optimizer doesn't inherit from either legacy TensorFlow "
        "or Keras optimizer: %s" % optimizer)

"""Keras callbacks (reference ``horovod/keras/callbacks.py``): thin
keras.callbacks.Callback shells over the shared impls in
``horovod_tpu/_keras/callbacks.py``."""

from __future__ import annotations

import keras

from horovod_tpu._keras import callbacks as _impl


class BroadcastGlobalVariablesCallback(
        _impl.BroadcastGlobalVariablesCallbackImpl, keras.callbacks.Callback):
    """Broadcast initial model/optimizer state from ``root_rank`` on the
    first batch (reference ``keras/callbacks.py:28-48``)."""

    def __init__(self, root_rank=0, device=''):
        super().__init__(root_rank, device)


class MetricAverageCallback(_impl.MetricAverageCallbackImpl,
                            keras.callbacks.Callback):
    """Average epoch metrics across ranks before other callbacks (e.g.
    checkpointing/early stopping) see them (reference
    ``keras/callbacks.py:51-65``)."""

    def __init__(self, device=''):
        super().__init__(device)


class LearningRateScheduleCallback(_impl.LearningRateScheduleCallbackImpl,
                                   keras.callbacks.Callback):
    """Epoch/step LR schedule with momentum correction (reference
    ``keras/callbacks.py:68-107``)."""

    def __init__(self, multiplier, start_epoch=0, end_epoch=None,
                 staircase=True, momentum_correction=True,
                 steps_per_epoch=None):
        super().__init__(multiplier, start_epoch, end_epoch, staircase,
                         momentum_correction, steps_per_epoch)


class LearningRateWarmupCallback(_impl.LearningRateWarmupCallbackImpl,
                                 keras.callbacks.Callback):
    """Linear LR warmup from lr/size to lr (reference
    ``keras/callbacks.py:110-159``)."""

    def __init__(self, warmup_epochs=5, momentum_correction=True,
                 steps_per_epoch=None, verbose=0):
        super().__init__(warmup_epochs, momentum_correction,
                         steps_per_epoch, verbose)

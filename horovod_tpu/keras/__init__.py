"""Keras binding (reference ``horovod/keras/__init__.py``).

Public surface matches the reference: ``init/rank/size/...``,
``DistributedOptimizer``, ``Compression``, ``load_model``, eager helpers
``allreduce/allgather/broadcast`` on plain values, and the callbacks
submodule.  Built for Keras 3 (see ``horovod_tpu/_keras/__init__.py`` for
the apply_gradients-interception rationale).
"""

from __future__ import annotations

import keras

from horovod_tpu import _keras as _impl
from horovod_tpu.basics import (  # noqa: F401
    init, shutdown, is_initialized, rank, size, local_rank, local_size,
    cross_rank, cross_size, mpi_threads_supported, mpi_built, mpi_enabled,
    gloo_built, gloo_enabled, nccl_built, ddl_built, mlsl_built,
    tpu_built, tpu_enabled,
)
from horovod_tpu.ops import collective as _c
from horovod_tpu.keras import callbacks  # noqa: F401

try:
    from horovod_tpu.tensorflow import Compression
except ImportError:  # JAX-backend Keras without TF installed
    from horovod_tpu.ops.compression import Compression


def DistributedOptimizer(optimizer, name=None, device_dense='',
                         device_sparse='', compression=Compression.none,
                         sparse_as_dense=False):
    """Wrap a keras optimizer so ``apply_gradients`` averages gradients
    across ranks first (reference ``keras/__init__.py:34-114``)."""
    return _impl.create_distributed_optimizer(
        keras, optimizer, name=name, compression=compression,
        sparse_as_dense=sparse_as_dense)


def load_model(filepath, custom_optimizers=None, custom_objects=None,
               compression=Compression.none, **kwargs):
    """Load a model saved with a DistributedOptimizer, re-wrapping the
    deserialized optimizer (reference ``keras/__init__.py:117-150``)."""
    def wrap_optimizer(cls):
        return _impl.make_distributed_optimizer_class(
            keras, cls, compression=compression)
    return _impl.load_model(keras, wrap_optimizer, filepath,
                            custom_optimizers, custom_objects, **kwargs)


def allreduce(value, name=None, average=True):
    """Average a plain value (np array / scalar) across ranks (reference
    ``keras/__init__.py:153-163``)."""
    import numpy as np
    op = _c.Average if average else _c.Sum
    return _c._eager_allreduce(np.asarray(value), op,
                               _c._auto_name("keras.allreduce", name),
                               1.0, 1.0)


def allgather(value, name=None):
    import numpy as np
    return _c._eager_allgather(np.asarray(value),
                               _c._auto_name("keras.allgather", name))


def broadcast(value, root_rank=0, name=None):
    import numpy as np
    return _c._eager_broadcast(np.asarray(value), root_rank,
                               _c._auto_name("keras.broadcast", name))

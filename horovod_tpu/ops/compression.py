"""Gradient compression (reference ``horovod/tensorflow/compression.py:20-75``
and the torch/mxnet twins): wire codecs that shrink what a collective moves.

Two layers live here:

1. The **legacy per-tensor API** (:class:`Compressor` / :class:`Compression`)
   — API parity with the reference: ``compress(tensor) -> (tensor, ctx)``
   before the wire, ``decompress(tensor, ctx)`` after.  Cast-only, stateless;
   used by the eager plane and the replicated allreduce path.

2. The **bucket codec layer** (:class:`BucketCodec` and friends) — the
   TPU-native subsystem: codecs that operate on the flat fusion buckets of a
   :class:`horovod_tpu.ops.fusion.ReduceScatterPlan`, compressing BOTH phases
   of the sharded-update wire format (reduce-scatter of gradients,
   all-gather of updates).  Quantizing codecs carry **error-feedback
   residuals** (Seide et al. 2014 1-bit SGD; Karimireddy et al. 2019 EF-SGD)
   as rank-local state — the quantization error of step *t* is added back
   into the transmission of step *t+1*, so the *cumulative* applied update
   converges to the uncompressed trajectory even though each individual
   step is lossy.  The low-rank codec follows PowerSGD (Vogels et al. 2019):
   rank-R factor power iteration with a warm-started right factor.

Available codecs (``HOROVOD_COMPRESSION=none|bf16|fp16|int8|powersgd[:rank]``
or the ``compression=`` kwargs):

========== =========== ======= ====================================
codec      wire bytes  state   mechanism
========== =========== ======= ====================================
none       1x          --      pass-through (bit-exact)
bf16       1/2x        --      bfloat16 cast (TPU-idiomatic)
fp16       1/2x        --      float16 cast, clamped to +-65504
int8       ~1/4x       EF      per-bucket affine uint8 quantization
powersgd   ~R(m+n)/mn  EF + Q  rank-R power iteration (2-D leaves)
========== =========== ======= ====================================

TPU-native note: on TPU the natural cast dtype is **bfloat16** (MXU-native,
same exponent range as fp32 — no loss-scale gymnastics), so
``Compression.bf16`` is provided alongside the reference's ``fp16``.

Design invariants:

* **User dtypes stay untouched** — codecs cast/quantize on the wire and
  decode back to the bucket dtype; parameters, gradients and optimizer
  state keep their dtypes.
* **Checkpoints stay untouched** — residual state is rank-local and
  layout-dependent bookkeeping, deliberately EXCLUDED from the portable
  checkpoint layout (:func:`horovod_tpu.parallel.zero.gather_full_state`);
  a restore starts with zero residuals, which only delays error feedback
  by one step.  Elastic world-size changes instead go through
  :meth:`BucketCodec.reshard_state`, which preserves the pending error.
* **Replicated consistency** — every rank decodes the *identical*
  transmitted bytes (the all-to-all exchange / gathered shards), so
  decoded means and gathered updates are bit-identical across ranks and
  parameters never drift.
"""

from __future__ import annotations

import dataclasses
import os
import re
import time
from typing import ClassVar, List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from horovod_tpu import telemetry
from horovod_tpu.ops import fusion
from horovod_tpu.utils.logging import get_logger

log = get_logger(__name__)

# Largest finite float16 value: fp32 -> fp16 casts of anything bigger give
# inf, which a single rank then spreads through the whole allreduce.
FP16_MAX = 65504.0

HOROVOD_COMPRESSION_VAR = "HOROVOD_COMPRESSION"

_warned_bad_env = False


# ---------------------------------------------------------------------------
# Legacy per-tensor API (reference compression.py:20-75).
# ---------------------------------------------------------------------------

class Compressor:
    """Interface (reference compression.py:20-33)."""

    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    """Pass-through (reference compression.py:36-44)."""

    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class _CastCompressor(Compressor):
    wire_dtype = None

    @classmethod
    def _clip(cls, tensor):
        return tensor

    @classmethod
    def compress(cls, tensor):
        dtype = tensor.dtype
        if jnp.issubdtype(dtype, jnp.floating) and dtype != cls.wire_dtype:
            return cls._clip(tensor).astype(cls.wire_dtype), dtype
        return tensor, None

    @classmethod
    def decompress(cls, tensor, ctx):
        return tensor if ctx is None else tensor.astype(ctx)


class FP16Compressor(_CastCompressor):
    """Cast fp32/fp64 → fp16 on the wire (reference compression.py:46-63).

    Values outside fp16's finite range are CLAMPED to ±65504 before the
    cast: an unclamped cast maps them to inf, and one rank's inf poisons
    every rank's reduced tensor.  The clamp loses magnitude information a
    float16 wire could never carry anyway."""
    wire_dtype = jnp.float16

    @classmethod
    def _clip(cls, tensor):
        lim = jnp.asarray(FP16_MAX, tensor.dtype)
        return jnp.clip(tensor, -lim, lim)


class BF16Compressor(_CastCompressor):
    """TPU-idiomatic: bfloat16 on the wire (same exponent range as fp32,
    so no clamp is needed)."""
    wire_dtype = jnp.bfloat16


class Compression:
    """Optional wire compression algorithms (reference compression.py:66-75)."""
    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor


# ---------------------------------------------------------------------------
# Error-feedback state: one pytree per codec instance x plan.
# ---------------------------------------------------------------------------

@jax.tree_util.register_pytree_node_class
class CodecState:
    """Rank-local wire-codec state for one :class:`ReduceScatterPlan`.

    Per bucket ``b`` (``None`` where the codec keeps nothing):

    * ``rs[b]`` — reduce-scatter error-feedback residual.  GLOBAL shape
      ``(axis_size * padded_size(b),)``, sharded ``P(axis)`` so the local
      view is this rank's own ``(padded_size(b),)`` residual over the full
      bucket (every rank's gradient contribution is distinct).  fp32.
    * ``ag[b]`` — all-gather residual.  GLOBAL shape ``(padded_size(b),)``,
      sharded ``P(axis)``: each rank owns the residual of the shard it
      transmits.  fp32.
    * ``factors[b]`` — the PowerSGD right factor ``Q`` of shape
      ``(n, rank)``, REPLICATED (every rank iterates the same subspace).

    Like :class:`horovod_tpu.parallel.zero.ZeroShardedState` this layout is
    global-array friendly: ``shard_map`` in/out specs from
    :meth:`BucketCodec.state_specs` place the residuals 1/N per rank.
    """

    def __init__(self, rs, ag, factors):
        self.rs = tuple(rs)
        self.ag = tuple(ag)
        self.factors = tuple(factors)

    def tree_flatten(self):
        return (self.rs, self.ag, self.factors), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        del aux
        return cls(*children)

    def __repr__(self):
        live = sum(x is not None for x in self.rs + self.ag + self.factors)
        return f"CodecState(buckets={len(self.rs)}, live_leaves={live})"


def zero_residuals(state: Optional[CodecState]) -> Optional[CodecState]:
    """Zero every error-feedback residual (sharding-preserving) while
    keeping the PowerSGD factors — the ``residual_drop`` chaos hook's
    payload, and the state a checkpoint restore starts from."""
    if state is None:
        return None

    def z(group):
        return tuple(None if a is None else a * jnp.zeros((), a.dtype)
                     for a in group)

    return CodecState(z(state.rs), z(state.ag), state.factors)


# ---------------------------------------------------------------------------
# Affine uint8 quantization helpers (per-bucket scale/offset).
# ---------------------------------------------------------------------------

def _affine_qparams(m):
    """Per-bucket scale/offset over [0, 255].  A constant bucket (span 0)
    quantizes exactly: scale falls back to 1 and every code is 0 == lo."""
    lo = m.min().astype(jnp.float32)
    span = m.max().astype(jnp.float32) - lo
    scale = jnp.where(span > 0, span / 255.0, jnp.float32(1.0))
    return scale, lo


def _affine_encode(m, scale, lo):
    q = jnp.round((m.astype(jnp.float32) - lo) / scale)
    return jnp.clip(q, 0.0, 255.0).astype(jnp.uint8)


def _affine_decode(q, scale, lo):
    return q.astype(jnp.float32) * scale + lo


# ---------------------------------------------------------------------------
# Bucket codecs.
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class BucketCodec:
    """Base class: a hashable (static-arg friendly) per-bucket wire codec.

    Subclasses implement ``reduce_scatter_bucket`` / ``all_gather_bucket``
    for one flat padded bucket INSIDE ``shard_map`` (axis bound), plus the
    plan/state hooks.  The driver functions below loop the plan's buckets
    and keep the telemetry honest.
    """

    name: ClassVar[str] = "none"
    stateful: ClassVar[bool] = False

    # -- plan hooks ---------------------------------------------------------
    def solo_leaf(self, shape: Tuple[int, ...], dtype) -> bool:
        """True to claim a whole leaf as a dedicated (never-chunked) bucket
        — the PowerSGD codec needs 2-D leaves intact."""
        del shape, dtype
        return False

    # -- state tracking predicates (drive init/specs/reshard generically) --
    def _tracks_rs(self, b: int, plan) -> bool:
        del b, plan
        return False

    def _tracks_ag(self, b: int, plan) -> bool:
        del b, plan
        return False

    def _init_factor(self, b: int, plan):
        del b, plan
        return None

    # -- state lifecycle ----------------------------------------------------
    def init_state(self, plan) -> Optional[CodecState]:
        """Fresh (zero-residual) state in the GLOBAL layout; shard it with
        :meth:`state_specs` (or let the training step's ``shard_map``
        in_specs shard it on entry)."""
        if not self.stateful:
            return None
        nb = len(plan.buckets)
        n = plan.axis_size
        rs = tuple(
            jnp.zeros((n * plan.padded_size(b),), jnp.float32)
            if self._tracks_rs(b, plan) else None for b in range(nb))
        ag = tuple(
            jnp.zeros((plan.padded_size(b),), jnp.float32)
            if self._tracks_ag(b, plan) else None for b in range(nb))
        factors = tuple(self._init_factor(b, plan) for b in range(nb))
        return CodecState(rs, ag, factors)

    def state_specs(self, plan, axis_name: str) -> Optional[CodecState]:
        """PartitionSpec tree congruent to :meth:`init_state`'s output:
        residuals sharded over ``axis_name``, factors replicated."""
        if not self.stateful:
            return None
        from jax.sharding import PartitionSpec as P
        nb = len(plan.buckets)
        rs = tuple(P(axis_name) if self._tracks_rs(b, plan) else None
                   for b in range(nb))
        ag = tuple(P(axis_name) if self._tracks_ag(b, plan) else None
                   for b in range(nb))
        factors = tuple(P() if self._init_factor(b, plan) is not None
                        else None for b in range(nb))
        return CodecState(rs, ag, factors)

    def reshard_state(self, state: Optional[CodecState], old_plan,
                      new_plan) -> Optional[CodecState]:
        """Re-bucket residual state for a DIFFERENT axis size (elastic warm
        restart), preserving the PENDING error feedback.

        In mean units the pending reduce-scatter error is
        ``sum_r rs[r] / N``: the per-rank residuals are summed to one
        per-leaf pending vector, scaled by ``N_new / N_old`` so the new
        world's ``sum_r rs'[r] / N_new`` is unchanged, and assigned to rank
        0 of the new layout.  The all-gather residual is already one global
        vector in update units — it only needs re-bucketing.  PowerSGD
        factors carry over by leaf (eligibility is shape-based, so a leaf's
        low-rank status survives the reshard)."""
        if not self.stateful:
            return None
        if state is None:
            return self.init_state(new_plan)
        n_old, n_new = old_plan.axis_size, new_plan.axis_size
        nb_old, nb_new = len(old_plan.buckets), len(new_plan.buckets)

        # pending reduce-scatter error, per leaf, in SUM units
        pend = [state.rs[b].reshape(n_old, -1).sum(0).astype(jnp.float32)
                if state.rs[b] is not None
                else jnp.zeros((old_plan.padded_size(b),), jnp.float32)
                for b in range(nb_old)]
        pend_leaves = [l.astype(jnp.float32) * (n_new / n_old)
                       for l in old_plan.split(pend)]
        new_rs_rows = new_plan.concat(pend_leaves)

        ag = [state.ag[b].astype(jnp.float32) if state.ag[b] is not None
              else jnp.zeros((old_plan.padded_size(b),), jnp.float32)
              for b in range(nb_old)]
        new_ag_flats = new_plan.concat(old_plan.split(ag))

        old_factor_by_leaf = {
            old_plan.buckets[b][0][0]: state.factors[b]
            for b in range(nb_old) if state.factors[b] is not None}

        rs, ag_out, factors = [], [], []
        for b in range(nb_new):
            if self._tracks_rs(b, new_plan):
                row0 = new_rs_rows[b].astype(jnp.float32)
                rest = jnp.zeros(((n_new - 1) * new_plan.padded_size(b),),
                                 jnp.float32)
                rs.append(jnp.concatenate([row0, rest]) if n_new > 1
                          else row0)
            else:
                rs.append(None)
            ag_out.append(new_ag_flats[b].astype(jnp.float32)
                          if self._tracks_ag(b, new_plan) else None)
            fresh = self._init_factor(b, new_plan)
            if fresh is not None:
                carried = old_factor_by_leaf.get(new_plan.buckets[b][0][0])
                factors.append(carried if carried is not None
                               and tuple(carried.shape) == tuple(fresh.shape)
                               else fresh)
            else:
                factors.append(None)
        return CodecState(rs, ag_out, factors)

    # -- wire ops (inside shard_map) ----------------------------------------
    def reduce_scatter_bucket(self, b: int, flat, plan, axis_name,
                              mean: bool, residual, factor):
        """One bucket's compressed reduce-scatter.  Returns
        ``(shard, new_residual, new_factor, wire_bytes)``."""
        raise NotImplementedError

    def all_gather_bucket(self, b: int, shard, plan, axis_name, residual):
        """One bucket's compressed all-gather.  Returns
        ``(full_flat, new_residual, wire_bytes)``."""
        raise NotImplementedError


@dataclasses.dataclass(frozen=True)
class NoneCodec(BucketCodec):
    """Bit-exact pass-through: the drivers delegate straight to
    :func:`fusion.fused_reduce_scatter` / :func:`fusion.fused_all_gather`
    (today's path, byte for byte)."""

    name: ClassVar[str] = "none"
    stateful: ClassVar[bool] = False


@dataclasses.dataclass(frozen=True)
class CastCodec(BucketCodec):
    """Stateless dtype cast on the wire (bf16 or clamped fp16): 2x fewer
    bytes for fp32 buckets, reduction runs at wire precision."""

    wire: str = "bfloat16"
    stateful: ClassVar[bool] = False

    @property
    def name(self) -> str:  # type: ignore[override]
        return "bf16" if self.wire == "bfloat16" else "fp16"

    @property
    def wire_dtype(self):
        return jnp.dtype(self.wire)

    def _to_wire(self, x):
        if x.dtype == self.wire_dtype or not jnp.issubdtype(
                x.dtype, jnp.floating):
            return x
        if self.wire_dtype == jnp.float16:
            lim = jnp.asarray(FP16_MAX, x.dtype)
            x = jnp.clip(x, -lim, lim)
        return x.astype(self.wire_dtype)

    def reduce_scatter_bucket(self, b, flat, plan, axis_name, mean,
                              residual, factor):
        dtype = flat.dtype
        w = self._to_wire(flat)
        shard = lax.psum_scatter(w, axis_name, scatter_dimension=0,
                                 tiled=True).astype(dtype)
        if mean:
            shard = shard * jnp.asarray(1.0 / plan.axis_size, dtype)
        return (shard, None, None,
                plan.padded_size(b) * w.dtype.itemsize)

    def all_gather_bucket(self, b, shard, plan, axis_name, residual):
        dtype = shard.dtype
        w = self._to_wire(shard)
        full = lax.all_gather(w, axis_name, axis=0,
                              tiled=True).astype(dtype)
        return full, None, plan.padded_size(b) * w.dtype.itemsize


@dataclasses.dataclass(frozen=True)
class Int8Codec(BucketCodec):
    """Per-bucket affine uint8 quantization with error feedback, BOTH
    phases compressed (~4x for fp32 buckets).

    Reduce-scatter: each rank quantizes its full (residual-corrected)
    bucket to uint8 and the ranks exchange shards with ``all_to_all`` —
    the same per-rank wire volume a ring reduce-scatter moves, at 1/4 the
    width — plus one tiny ``(scale, offset)`` pair per rank.  Each rank
    then dequantizes the N received source shards at their own qparams and
    sums: the reduction runs in fp32, so quantization error does NOT
    compound across ranks and the residual (what the uint8 round dropped)
    is fed back next step.

    All-gather: each rank quantizes its update shard, shards are gathered
    as uint8 and every rank decodes the identical bytes — parameters stay
    replicated-consistent — with the shard-owner keeping the round-off as
    the all-gather residual.

    Integer buckets (no meaningful quantization) pass through uncompressed.
    """

    name: ClassVar[str] = "int8"
    stateful: ClassVar[bool] = True

    def _tracks_rs(self, b, plan):
        return jnp.issubdtype(plan.bucket_dtype(b), jnp.floating)

    def _tracks_ag(self, b, plan):
        return jnp.issubdtype(plan.bucket_dtype(b), jnp.floating)

    def reduce_scatter_bucket(self, b, flat, plan, axis_name, mean,
                              residual, factor):
        dtype = flat.dtype
        n = plan.axis_size
        if residual is None:  # non-float bucket: uncompressed
            shard = lax.psum_scatter(flat, axis_name, scatter_dimension=0,
                                     tiled=True)
            if mean:
                shard = shard * jnp.asarray(1.0 / n, dtype)
            return shard, None, None, plan.padded_size(b) * dtype.itemsize
        m = flat.astype(jnp.float32) + residual
        scale, lo = _affine_qparams(m)
        q = _affine_encode(m, scale, lo)
        new_res = m - _affine_decode(q, scale, lo)
        s = plan.shard_size(b)
        # exchange: row i of ``ex`` is source rank i's uint8 shard for us
        ex = lax.all_to_all(q.reshape(n, s), axis_name, 0, 0)
        prm = lax.all_gather(jnp.stack([scale, lo]), axis_name, axis=0)
        tot = (ex.astype(jnp.float32) * prm[:, 0:1] + prm[:, 1:2]).sum(0)
        if mean:
            tot = tot / n
        return tot.astype(dtype), new_res, None, plan.padded_size(b) + 8

    def all_gather_bucket(self, b, shard, plan, axis_name, residual):
        dtype = shard.dtype
        n = plan.axis_size
        if residual is None:
            full = lax.all_gather(shard, axis_name, axis=0, tiled=True)
            return full, None, plan.padded_size(b) * dtype.itemsize
        m = shard.astype(jnp.float32) + residual
        scale, lo = _affine_qparams(m)
        q = _affine_encode(m, scale, lo)
        new_res = m - _affine_decode(q, scale, lo)
        qs = lax.all_gather(q, axis_name, axis=0, tiled=True)
        prm = lax.all_gather(jnp.stack([scale, lo]), axis_name, axis=0)
        full = (qs.astype(jnp.float32).reshape(n, -1) * prm[:, 0:1]
                + prm[:, 1:2]).reshape(-1)
        return full.astype(dtype), new_res, plan.padded_size(b) + 8 * n


@dataclasses.dataclass(frozen=True)
class PowerSGDCodec(BucketCodec):
    """PowerSGD-style low-rank transport (Vogels et al. 2019) for 2-D LM
    weight gradients; bf16 cast everywhere else.

    Eligible leaves (2-D, both dims >= 2*rank) get dedicated whole-leaf
    buckets (``plan.lowrank``).  Per step, with ``M_r`` the rank's
    residual-corrected (m, n) gradient and ``Q`` the warm-started (n, R)
    right factor: ``P = mean_r(M_r Q)`` (one small psum), orthonormalize
    ``P`` by QR, ``Q' = mean_r(M_r^T P_hat)`` (second small psum), decode
    ``P_hat Q'^T ~= mean_r M_r`` identically on every rank, keep
    ``M_r - decoded`` as the residual and ``Q'`` as next step's factor —
    wire cost R(m+n) floats instead of m*n.  The all-gather phase (update
    shards have no low-rank structure) rides the bf16 cast.
    """

    rank: int = 4
    name: ClassVar[str] = "powersgd"
    stateful: ClassVar[bool] = True

    def __post_init__(self):
        if self.rank < 1:
            raise ValueError(f"powersgd rank must be >= 1, got {self.rank}")

    @property
    def _cast(self) -> CastCodec:
        return CastCodec("bfloat16")

    def solo_leaf(self, shape, dtype):
        return (len(shape) == 2 and jnp.issubdtype(dtype, jnp.floating)
                and min(shape) >= 2 * self.rank)

    def _tracks_rs(self, b, plan):
        return b in plan.lowrank

    def _init_factor(self, b, plan):
        if b not in plan.lowrank:
            return None
        _, n_cols = plan.bucket_leaf_shape(b)
        key = jax.random.PRNGKey(0x9D + 31 * b)
        return jax.random.normal(key, (n_cols, self.rank), jnp.float32)

    def reduce_scatter_bucket(self, b, flat, plan, axis_name, mean,
                              residual, factor):
        if b not in plan.lowrank:
            return self._cast.reduce_scatter_bucket(
                b, flat, plan, axis_name, mean, None, None)
        dtype = flat.dtype
        n_ranks = plan.axis_size
        m_rows, n_cols = plan.bucket_leaf_shape(b)
        size = m_rows * n_cols
        mat = (flat[:size].astype(jnp.float32)
               + residual[:size]).reshape(m_rows, n_cols)
        p = lax.psum(mat @ factor, axis_name) / n_ranks
        p_hat, _ = jnp.linalg.qr(p)
        q_new = lax.psum(mat.T @ p_hat, axis_name) / n_ranks
        decoded = (p_hat @ q_new.T).reshape(-1)          # mean_r M_r, f32
        new_res = jnp.concatenate([
            mat.reshape(-1) - decoded,
            jnp.zeros((plan.pad_elems(b),), jnp.float32)]) \
            if plan.pad_elems(b) else mat.reshape(-1) - decoded
        full = decoded if mean else decoded * n_ranks
        if plan.pad_elems(b):
            full = jnp.concatenate(
                [full, jnp.zeros((plan.pad_elems(b),), jnp.float32)])
        shard = plan.shard_slice(b, full.astype(dtype),
                                 lax.axis_index(axis_name))
        wire = (m_rows + n_cols) * self.rank * 4
        return shard, new_res, q_new, wire

    def all_gather_bucket(self, b, shard, plan, axis_name, residual):
        return self._cast.all_gather_bucket(b, shard, plan, axis_name, None)


# ---------------------------------------------------------------------------
# Codec resolution: kwargs, legacy Compression classes, HOROVOD_COMPRESSION.
# ---------------------------------------------------------------------------

_CODEC_SPEC = re.compile(r"powersgd:(\d+)")


def parse_codec(spec: str) -> BucketCodec:
    """``"none"|"bf16"|"fp16"|"int8"|"powersgd"|"powersgd:R"`` -> codec."""
    s = str(spec).strip().lower()
    if s in ("", "none"):
        return NoneCodec()
    if s == "bf16":
        return CastCodec("bfloat16")
    if s == "fp16":
        return CastCodec("float16")
    if s == "int8":
        return Int8Codec()
    if s == "powersgd":
        return PowerSGDCodec()
    m = _CODEC_SPEC.fullmatch(s)
    if m:
        return PowerSGDCodec(rank=int(m.group(1)))
    raise ValueError(
        f"unknown compression codec {spec!r}: expected none, bf16, fp16, "
        f"int8, powersgd or powersgd:<rank>")


_LEGACY_TO_CODEC = {}  # populated below; class identity -> factory


def resolve_codec(compression=None) -> BucketCodec:
    """Normalize every accepted ``compression=`` form to a
    :class:`BucketCodec`: codec instances pass through, strings are
    parsed, the legacy :class:`Compression` classes map to their codec
    twins, and the DEFAULT forms — ``None`` and ``Compression.none`` —
    consult ``HOROVOD_COMPRESSION``.  An explicit codec (instance or
    string, even ``"none"``) always wins over the env.  An unparseable
    env value warns once and falls back to none — a typo must not surface
    as a ValueError deep inside a jit trace."""
    global _warned_bad_env
    c = compression
    consult_env = (compression is None
                   or (isinstance(compression, type)
                       and issubclass(compression, NoneCompressor)))
    if isinstance(c, BucketCodec):
        pass
    elif isinstance(c, str):
        c = parse_codec(c)
    elif c is None:
        c = NoneCodec()
    elif isinstance(c, type) and issubclass(c, Compressor):
        if issubclass(c, FP16Compressor):
            c = CastCodec("float16")
        elif issubclass(c, BF16Compressor):
            c = CastCodec("bfloat16")
        elif issubclass(c, NoneCompressor):
            c = NoneCodec()
        else:
            raise TypeError(
                f"custom Compressor subclass {c.__name__} has no bucket-"
                f"codec equivalent; pass a BucketCodec instance instead")
    else:
        raise TypeError(
            f"compression must be a BucketCodec, a codec name string, or "
            f"one of the Compression.* classes; got {c!r}")
    if consult_env and isinstance(c, NoneCodec):
        env = os.environ.get(HOROVOD_COMPRESSION_VAR, "").strip()
        if env:
            try:
                c = parse_codec(env)
            except ValueError as e:
                if not _warned_bad_env:
                    _warned_bad_env = True
                    log.warning("%s=%r ignored: %s",
                                HOROVOD_COMPRESSION_VAR, env, e)
    return c


_LINK_LEVELS = ("flat", "local", "cross")
_warned_bad_link_env = False


def link_codec(level: str, compression=None) -> BucketCodec:
    """The codec for one transport link level (``flat``, ``local`` or
    ``cross``), consulting ``HOROVOD_TRANSPORT_CODECS``.

    The transport plane moves intra-host traffic over shm rings and
    cross-host traffic over (striped) sockets; their bandwidths differ by
    orders of magnitude, so one global codec is the wrong trade on one of
    the two.  ``HOROVOD_TRANSPORT_CODECS="cross:fp16,local:none"``
    overrides per level; levels it does not name (and any parse error)
    fall back to :func:`resolve_codec`'s answer for ``compression`` —
    i.e. the global ``HOROVOD_COMPRESSION`` path.  Every rank sees the
    same environment under hvdrun, so per-level selection stays
    rank-agreed the same way the global codec does."""
    global _warned_bad_link_env
    base = resolve_codec(compression)
    if level not in _LINK_LEVELS:
        raise ValueError(
            f"unknown link level {level!r}: expected one of {_LINK_LEVELS}")
    spec = os.environ.get("HOROVOD_TRANSPORT_CODECS", "").strip()
    if not spec:
        return base
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        lvl, sep, codec_spec = part.partition(":")
        if not sep or lvl.strip() not in _LINK_LEVELS:
            if not _warned_bad_link_env:
                _warned_bad_link_env = True
                log.warning(
                    "HOROVOD_TRANSPORT_CODECS=%r ignored entry %r: "
                    "expected level:codec with level in %s",
                    spec, part, _LINK_LEVELS)
            continue
        if lvl.strip() == level:
            try:
                return parse_codec(codec_spec)
            except ValueError as e:
                if not _warned_bad_link_env:
                    _warned_bad_link_env = True
                    log.warning("HOROVOD_TRANSPORT_CODECS=%r ignored: %s",
                                spec, e)
                return base
    return base


def as_legacy(codec: BucketCodec):
    """The legacy per-tensor :class:`Compressor` equivalent of a stateless
    codec (for the eager / replicated-allreduce paths), or ``None`` when
    the codec has no per-tensor form (int8/powersgd need bucket state)."""
    if isinstance(codec, NoneCodec):
        return NoneCompressor
    if isinstance(codec, CastCodec):
        return (FP16Compressor if codec.wire_dtype == jnp.float16
                else BF16Compressor)
    return None


# ---------------------------------------------------------------------------
# Driver functions: the plan-wide compressed wire (inside shard_map).
# ---------------------------------------------------------------------------

def _record_compression(codec_name: str, bytes_in: int, bytes_out: int,
                        seconds: float) -> None:
    """Trace-time codec accounting (hvd_compression_*): byte counters are
    trace-time decisions like the fusion series; encode_seconds is the
    HOST time spent building the compressed collective at trace time."""
    if not telemetry.enabled() or not bytes_in:
        return
    telemetry.counter(
        "hvd_compression_bytes_in_total",
        "Uncompressed payload bytes entering wire codecs (trace-time)",
        codec=codec_name).inc(bytes_in)
    telemetry.counter(
        "hvd_compression_bytes_out_total",
        "Compressed payload bytes leaving wire codecs (trace-time)",
        codec=codec_name).inc(bytes_out)
    telemetry.gauge(
        "hvd_compression_ratio",
        "bytes_in / bytes_out of the most recent codec application",
        codec=codec_name).set(bytes_in / max(bytes_out, 1))
    telemetry.counter(
        "hvd_compression_encode_seconds_total",
        "Host seconds spent building compressed collectives (trace-time)",
        codec=codec_name).inc(max(seconds, 0.0))


def compressed_reduce_scatter(leaves, axis_name, codec: BucketCodec, *,
                              plan, state: Optional[CodecState] = None,
                              mean: bool = True):
    """Codec-aware twin of :func:`fusion.fused_reduce_scatter` over a
    prebuilt plan: compress each bucket on the wire, return ``(shards,
    new_state)``.  Must run inside ``shard_map`` with ``axis_name`` bound.
    The none codec delegates to the fused path bit-exactly."""
    codec = codec if codec is not None else NoneCodec()
    if isinstance(codec, NoneCodec):
        shards, _ = fusion.fused_reduce_scatter(leaves, axis_name,
                                                mean=mean, plan=plan)
        return shards, state
    t0 = time.perf_counter()
    flats = plan.concat(list(leaves))
    nb = len(plan.buckets)
    rs = list(state.rs) if state is not None else [None] * nb
    factors = list(state.factors) if state is not None else [None] * nb
    ag = tuple(state.ag) if state is not None else (None,) * nb
    shards: List = []
    bytes_in = bytes_out = 0
    for b, flat in enumerate(flats):
        shard, new_r, new_f, wire = codec.reduce_scatter_bucket(
            b, flat, plan, axis_name, mean, rs[b], factors[b])
        shards.append(shard)
        if new_r is not None:
            rs[b] = new_r
        if new_f is not None:
            factors[b] = new_f
        bytes_in += plan.padded_size(b) * plan.bucket_dtype(b).itemsize
        bytes_out += wire
    fusion._record_plan("reduce_scatter", plan)
    fusion.record_collective_bytes("reduce_scatter", codec.name, bytes_out)
    _record_compression(codec.name, bytes_in, bytes_out,
                        time.perf_counter() - t0)
    new_state = (CodecState(rs, ag, factors) if codec.stateful else None)
    return shards, new_state


def compressed_all_gather(shards, plan, axis_name, codec: BucketCodec,
                          state: Optional[CodecState] = None):
    """Codec-aware twin of :func:`fusion.fused_all_gather`: compress each
    update shard on the wire, gather, decode identically on every rank.
    Returns ``(leaves, new_state)``."""
    codec = codec if codec is not None else NoneCodec()
    if isinstance(codec, NoneCodec):
        return fusion.fused_all_gather(shards, plan, axis_name), state
    shards = list(shards)
    if len(shards) != len(plan.buckets):
        raise ValueError(f"plan has {len(plan.buckets)} buckets, got "
                         f"{len(shards)} shards")
    t0 = time.perf_counter()
    nb = len(plan.buckets)
    ag = list(state.ag) if state is not None else [None] * nb
    fulls: List = []
    bytes_in = bytes_out = 0
    for b, shard in enumerate(shards):
        full, new_r, wire = codec.all_gather_bucket(
            b, shard, plan, axis_name, ag[b])
        fulls.append(full)
        if new_r is not None:
            ag[b] = new_r
        bytes_in += plan.padded_size(b) * plan.bucket_dtype(b).itemsize
        bytes_out += wire
    fusion.record_collective_bytes("all_gather", codec.name, bytes_out)
    _record_compression(codec.name, bytes_in, bytes_out,
                        time.perf_counter() - t0)
    leaves = plan.split(fulls)
    new_state = (CodecState(state.rs if state is not None else (None,) * nb,
                            ag,
                            state.factors if state is not None
                            else (None,) * nb)
                 if codec.stateful else None)
    return leaves, new_state


def cross_level_psum(x, axis_name, codec=None):
    """``lax.psum(x, axis_name)`` with an optional stateless wire codec —
    the per-level codec hook of the hierarchical plane ("int8 on DCN, none
    on ICI").  Accepts ``None``/``"none"``, ``"bf16"``, ``"fp16"`` or
    ``"int8"`` (or the equivalent codec instances).

    The int8 form quantizes against a *shared* scale (``pmax`` of the
    per-rank absmax, one scalar on the wire) so every rank decodes
    identically, reduces in int32 so up to 2^23 ranks of ±127 cannot
    overflow, and rescales once.  Stateful codecs (powersgd) are rejected:
    error feedback belongs to the intra-level plan state
    (:func:`compressed_reduce_scatter`), not a single psum hop.
    """
    codec = resolve_codec(codec if codec is not None else "none")
    esize = jnp.dtype(x.dtype).itemsize
    if isinstance(codec, NoneCodec):
        fusion.record_collective_bytes("cross_psum", "none",
                                       x.size * esize, level="dcn")
        return lax.psum(x, axis_name)
    if isinstance(codec, CastCodec):
        wire = jnp.dtype(codec.wire_dtype)
        fusion.record_collective_bytes("cross_psum", codec.name,
                                       x.size * wire.itemsize, level="dcn")
        return lax.psum(x.astype(wire), axis_name).astype(x.dtype)
    if isinstance(codec, Int8Codec):
        absmax = jnp.max(jnp.abs(x)).astype(jnp.float32)
        scale = lax.pmax(absmax, axis_name) / 127.0
        safe = jnp.where(scale > 0, scale, 1.0)
        q = jnp.clip(jnp.round(x.astype(jnp.float32) / safe),
                     -127, 127).astype(jnp.int8)
        total = lax.psum(q.astype(jnp.int32), axis_name)
        fusion.record_collective_bytes("cross_psum", codec.name,
                                       x.size, level="dcn")
        return (total.astype(jnp.float32) * safe).astype(x.dtype)
    raise ValueError(
        f"cross_level_psum supports stateless codecs (none/bf16/fp16/int8); "
        f"got {codec.name!r} — stateful codecs need plan-level error "
        f"feedback, use compressed_reduce_scatter instead")


def compressed_allreduce(leaves, axis_name, codec: BucketCodec, *,
                         plan, state: Optional[CodecState] = None,
                         mean: bool = True):
    """Full compressed allreduce — the reduce-scatter / all-gather pair
    back to back (the replicated-update path of
    :func:`horovod_tpu.parallel.data.make_training_step` with a stateful
    codec).  Returns ``(leaves, new_state)``."""
    shards, state = compressed_reduce_scatter(
        leaves, axis_name, codec, plan=plan, state=state, mean=mean)
    return compressed_all_gather(shards, plan, axis_name, codec, state)

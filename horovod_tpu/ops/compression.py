"""Gradient compression (reference ``horovod/tensorflow/compression.py:20-75``
and the torch/mxnet twins): an algorithm that casts tensors before the wire
and restores them after.

TPU-native note: on TPU the natural wire dtype is **bfloat16** (MXU-native,
same exponent range as fp32 — no loss-scale gymnastics), so ``Compression.bf16``
is provided alongside the reference's ``fp16``.
"""

from __future__ import annotations

import jax.numpy as jnp


class Compressor:
    """Interface (reference compression.py:20-33)."""

    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    """Pass-through (reference compression.py:36-44)."""

    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class _CastCompressor(Compressor):
    wire_dtype = None

    @classmethod
    def compress(cls, tensor):
        dtype = tensor.dtype
        if jnp.issubdtype(dtype, jnp.floating) and dtype != cls.wire_dtype:
            return tensor.astype(cls.wire_dtype), dtype
        return tensor, None

    @classmethod
    def decompress(cls, tensor, ctx):
        return tensor if ctx is None else tensor.astype(ctx)


class FP16Compressor(_CastCompressor):
    """Cast fp32/fp64 → fp16 on the wire (reference compression.py:46-63)."""
    wire_dtype = jnp.float16


class BF16Compressor(_CastCompressor):
    """TPU-idiomatic: bfloat16 on the wire."""
    wire_dtype = jnp.bfloat16


class Compression:
    """Optional wire compression algorithms (reference compression.py:66-75)."""
    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor

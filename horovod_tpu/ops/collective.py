"""Collective operations: allreduce / allgather / broadcast / reducescatter /
alltoall, in both SPMD (jit) and eager (async, name-negotiated) forms.

Horovod equivalents: the op kernels in ``horovod/tensorflow/mpi_ops.cc:276-463``
and ``horovod/torch/mpi_ops_v2.cc:52-235``, the enqueue API
``EnqueueTensorAllreduce/Allgather/Broadcast``
(``horovod/common/operations.cc:736-843``) and the handle/poll model of
``horovod/torch/handle_manager.{h,cc}``.

TPU-native redesign — the two planes
------------------------------------
* **SPMD plane** (the performance path): when a collective is called on a
  *traced* value — inside ``jit`` / ``shard_map`` / ``pmap`` with a mesh axis
  in scope — it lowers directly to the XLA collective
  (``lax.psum`` / ``lax.all_gather`` / ``lax.psum_scatter`` /
  ``lax.all_to_all``).  No queue, no negotiation, no fusion buffer: XLA
  guarantees identical program order on every device, which is the invariant
  Horovod's whole controller exists to establish (design rationale at
  reference ``operations.cc:281-300``).
* **Eager plane** (the compatibility path): on concrete arrays in a
  multi-process job, ops are enqueued by *name* to the native runtime — a C++
  background thread with a TCP controller that negotiates readiness across
  ranks, fuses small tensors, and executes — the faithful heir of
  ``BackgroundThreadLoop``/``ComputeResponseList``
  (``operations.cc:303-550``, ``controller.cc:54-298``).  In a single-process
  job the eager collectives are local arithmetic (a 1-rank ring), matching
  Horovod's 1-process behavior.

Both planes share one user API; ``hvd.allreduce`` does the right thing in
either context.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from horovod_tpu import basics, faults, telemetry
from horovod_tpu.utils.logging import get_logger

log = get_logger(__name__)


# ---------------------------------------------------------------------------
# Telemetry for the 1-process local fast path.  Multi-process eager ops are
# recorded at the native-runtime choke point (native/runtime.py::_wait_read),
# which every route — sync, async, split submit/finish — flows through; the
# rt-is-None branches below bypass the runtime entirely, so they record
# here.  The two sites are mutually exclusive: nothing is double-counted.
# ---------------------------------------------------------------------------

def _tstart() -> float:
    """Timestamp ops only when some telemetry consumer exists — the
    disabled path must not even read the clock."""
    if (telemetry.enabled() or telemetry.timeline() is not None
            or telemetry.spans() is not None):
        return telemetry.clock()
    return 0.0


def _record_local(kind: str, name: str, arr, t0: float) -> None:
    if not t0:
        return
    t1 = telemetry.clock()
    nbytes = int(arr.nbytes)
    telemetry.observe_op(kind, max(t1 - t0, 1e-9), nbytes)
    tl = telemetry.timeline()
    if tl is not None:
        tl.record_op(name, kind, t0, t1, t1, nbytes)
    sp = telemetry.spans()
    if sp is not None:
        # Single-process execution: the whole op is one in-process span.
        # The occurrence counter still ticks per name so repeated steps
        # of the same tensor stay distinguishable in the merged trace.
        sp.record(name, "exec", sp.next_seq(name), t0, t1, nbytes)


# ---------------------------------------------------------------------------
# Reduction ops (reference message.h / later horovod.common Average/Sum/Adasum)
# ---------------------------------------------------------------------------

class ReduceOp:
    def __init__(self, name: str, code: int):
        self.name = name
        self.code = code

    def __repr__(self):
        return f"ReduceOp.{self.name}"


Average = ReduceOp("Average", 0)
Sum = ReduceOp("Sum", 1)
# Real Adasum on the eager plane (scaled-projection butterfly in
# native/cc/src/data_plane.cc; Maleki et al. 2020): identical gradients
# combine to themselves, orthogonal ones add.  The SPMD plane raises —
# a mesh-collective Adasum needs a different design than psum, and
# silently substituting the mean would change training semantics.
Adasum = ReduceOp("Adasum", 2)
Min = ReduceOp("Min", 3)
Max = ReduceOp("Max", 4)

# ---------------------------------------------------------------------------
# Process sets (later-Horovod; the v0.18 reference had only the single
# global group, basics.py:29-61 "rank subset" init).  A ProcessSet is a
# simultaneous sub-communicator: collectives with `process_set=ps` involve
# only its member ranks, negotiated and executed concurrently with global
# (and other sets') traffic on the eager plane.  SPMD-plane code should
# build a sub-mesh instead (jax.sharding.Mesh over a device subset).
# ---------------------------------------------------------------------------

class ProcessSet:
    """A registered subset of ranks (reference: later-Horovod
    ``hvd.ProcessSet``).  Create via :func:`add_process_set`."""

    def __init__(self, ranks, set_id=None):
        self.ranks = sorted(int(r) for r in ranks)
        self.id = set_id   # None until registered

    def included(self) -> bool:
        return basics.rank() in self.ranks

    def size(self) -> int:
        return len(self.ranks)

    def rank(self) -> int:
        """This process's position within the set (its "set rank")."""
        try:
            return self.ranks.index(basics.rank())
        except ValueError:
            raise RuntimeError(
                f"rank {basics.rank()} is not a member of process set "
                f"{self.ranks}")

    def __repr__(self):
        return f"ProcessSet(ranks={self.ranks}, id={self.id})"


class _GlobalProcessSet(ProcessSet):
    """The implicit set of all ranks (id 0); size tracks hvd.size()."""

    def __init__(self):
        self.id = 0

    @property
    def ranks(self):
        return list(range(basics.size()))

    def included(self) -> bool:
        return True

    def size(self) -> int:
        return basics.size()

    def rank(self) -> int:
        return basics.rank()


global_process_set = _GlobalProcessSet()


def add_process_set(ranks) -> ProcessSet:
    """Collectively register a new process set; EVERY rank of the job must
    call this with the same ranks (later-Horovod ``add_process_set``
    contract — registration is a collective over the global set).
    Registering an already-registered member list returns a set with its
    existing id."""
    basics._check_initialized()
    ps = ranks if isinstance(ranks, ProcessSet) else ProcessSet(ranks)
    if ps.id == 0:
        return global_process_set
    rt = basics.runtime()
    if rt is None:
        if ps.ranks != [0]:
            raise ValueError(
                f"process set {ps.ranks} is invalid for a 1-process job")
        ps.id = 0
        return ps
    ps.id = rt.add_process_set(ps.ranks)
    return ps


def _reject_spmd_process_set(process_set, ax):
    """SPMD plane has no process sets — a subset request under a bound
    mesh axis must fail loudly, never silently involve the whole axis."""
    if process_set is not None and process_set.id != 0 and _axis_bound(ax):
        raise ValueError(
            "process_set is an eager-plane concept; under shard_map build "
            "a sub-mesh (jax.sharding.Mesh over the member devices) "
            "instead")


def _set_args(process_set):
    """(set_id, set_size) for the eager plane; validates membership."""
    if process_set is None or process_set.id == 0:
        return 0, basics.size()
    if process_set.id is None:
        raise ValueError(
            f"process set {process_set.ranks} is not registered; call "
            "hvd.add_process_set(...) on every rank first")
    if not process_set.included():
        raise RuntimeError(
            f"rank {basics.rank()} is not a member of process set "
            f"{process_set.ranks} and cannot submit collectives on it")
    return process_set.id, process_set.size()


# Error-message contract (reference horovod/common/common.h:155-158).
DUPLICATE_NAME_ERROR_FMT = (
    "Requested to %s a tensor with the same name as another tensor that is "
    "currently being processed.  If you want to request another tensor, use "
    "a different tensor name. Tensor name: %s"
)


def _is_traced(x) -> bool:
    return isinstance(x, jax.core.Tracer)


def _axis_bound(axis_name: str) -> bool:
    """True when ``axis_name`` is a live mesh axis in the current trace
    (i.e. we are under ``shard_map``/``pmap``) — the condition under which
    collectives lower to XLA ops instead of the eager runtime."""
    try:
        lax.axis_size(axis_name)
        return True
    except Exception:
        return False


def _plain_jit_fallback(tensor, kind: str):
    """A tracer with no bound mesh axis: user code under plain ``jit``.
    With one process this degenerates to local semantics (identical to the
    eager 1-rank result); with more we cannot reach the runtime from inside
    a traced program, so fail loudly rather than silently not reducing."""
    basics._check_initialized()
    if basics.size() > 1:
        raise RuntimeError(
            f"hvd.{kind} was traced inside jit without a mesh axis in scope "
            f"in a {basics.size()}-process job. Wrap the computation in "
            f"jax.shard_map over hvd.mesh() (SPMD plane), or call {kind} on "
            f"concrete arrays outside jit (eager plane).")
    return tensor


def _resolve_op(op, average):
    """Reconcile the v0.18 ``average=`` bool with the op enum."""
    if op is not None:
        return op
    if average is None or average:
        return Average
    return Sum


def _default_axis(axis_name):
    return "data" if axis_name is None else axis_name


# ---------------------------------------------------------------------------
# Handle manager for the async eager API
# (reference horovod/torch/handle_manager.{h,cc}: int handle -> Status table)
# ---------------------------------------------------------------------------

class _Handle:
    __slots__ = ("id", "name", "event", "result", "error")

    def __init__(self, hid: int, name: str):
        self.id = hid
        self.name = name
        self.event = threading.Event()
        self.result = None
        self.error: Optional[Exception] = None


class HandleManager:
    def __init__(self):
        self._lock = threading.Lock()
        self._next = 0
        self._handles: Dict[int, _Handle] = {}
        self._inflight_names: set = set()

    def allocate(self, name: str, op_kind: str) -> _Handle:
        with self._lock:
            if name in self._inflight_names:
                raise ValueError(DUPLICATE_NAME_ERROR_FMT % (op_kind, name))
            self._inflight_names.add(name)
            h = _Handle(self._next, name)
            self._next += 1
            self._handles[h.id] = h
        telemetry.gauge("hvd_eager_handle_queue_depth",
                        "Async eager handles allocated and not yet "
                        "completed").inc()
        return h

    def complete(self, h: _Handle, result=None, error: Optional[Exception] = None):
        with self._lock:
            h.result = result
            h.error = error
            self._inflight_names.discard(h.name)
        telemetry.gauge("hvd_eager_handle_queue_depth",
                        "Async eager handles allocated and not yet "
                        "completed").dec()
        h.event.set()

    def get(self, hid) -> _Handle:
        if isinstance(hid, _Handle):
            return hid
        with self._lock:
            h = self._handles.get(hid)
        if h is None:
            raise ValueError(f"Handle {hid} was not created or has been cleared")
        return h

    def clear(self, h: _Handle):
        with self._lock:
            self._handles.pop(h.id, None)


_handles = HandleManager()

_name_lock = threading.Lock()
_name_counter = 0


def _auto_name(kind: str, name: Optional[str]) -> str:
    # Reference: ops get node-name-derived names in TF, handle-derived in
    # torch (mpi_ops.py:58-90); we use a per-process counter.
    global _name_counter
    if name is not None:
        return name
    with _name_lock:
        n = _name_counter
        _name_counter += 1
    return f"{kind}.noname.{n}"


def poll(handle) -> bool:
    """Non-blocking completion check (reference ``horovod_torch_poll``,
    ``torch/mpi_ops_v2.cc:222-226``)."""
    return _handles.get(handle).event.is_set()


def synchronize(handle):
    """Block until the async op completes and return its output (reference
    ``torch/mpi_ops.py:429-445`` → ``wait_and_clear``)."""
    h = _handles.get(handle)
    h.event.wait()
    _handles.clear(h)
    if h.error is not None:
        raise h.error
    return h.result


# ---------------------------------------------------------------------------
# Eager execution (concrete arrays)
# ---------------------------------------------------------------------------

def _check_adasum_dtype(arr) -> None:
    """Adasum's projection is defined for floating tensors only; validate
    at the Python layer so the failure is identical at every world size
    (the native plane re-checks, but a size-1 job short-circuits before
    reaching it)."""
    kind = getattr(arr.dtype, "kind", "")
    if kind != "f" and "float" not in str(arr.dtype):  # bf16 has kind 'V'
        raise NotImplementedError(
            f"Adasum is defined for floating-point tensors only "
            f"(got dtype {arr.dtype})")


def _eager_allreduce(x, op: ReduceOp, name: str, prescale_factor,
                     postscale_factor, set_id=0, set_size=None):
    faults.inject("allreduce", name)
    t0 = _tstart()
    rt = basics.runtime()
    arr = np.asarray(x)
    if op is Adasum:
        _check_adasum_dtype(arr)
    if prescale_factor != 1.0:
        arr = arr * prescale_factor
    if rt is None:
        out = arr.copy()
        _record_local("allreduce", name, arr, t0)
    else:
        out = rt.allreduce(name, arr, op.code, set_id=set_id)
    # Adasum's result is the combined vector itself (the native butterfly
    # already applied the projection coefficients) — no divide.
    if op is Average:
        out = out / (set_size if set_size else basics.size())
    if postscale_factor != 1.0:
        out = out * postscale_factor
    return faults.corrupt_output("allreduce", out, name)


# --- split submit/finish pairs (graph-async bindings: submit is the
#     non-blocking native enqueue; finish blocks in hvd_wait.  The token
#     is (native_token_or_None, fallback_result)). -------------------------

def _eager_allreduce_submit(x, op: ReduceOp, name: str, prescale_factor,
                            set_id=0):
    faults.inject("allreduce", name)
    t0 = _tstart()
    rt = basics.runtime()
    arr = np.asarray(x)
    if op is Adasum:
        _check_adasum_dtype(arr)
    if prescale_factor != 1.0:
        arr = arr * prescale_factor
    if rt is None:
        _record_local("allreduce", name, arr, t0)
        return (None, arr.copy())
    return (rt.allreduce_submit(name, arr, op.code, set_id=set_id), None)


def _eager_allreduce_finish(tok, op: ReduceOp, postscale_factor,
                            set_size=None):
    native, done = tok
    out = done if native is None else basics.runtime().allreduce_finish(
        native)
    if op is Average:  # Adasum: combined vector as-is (see _eager_allreduce)
        out = out / (set_size if set_size else basics.size())
    if postscale_factor != 1.0:
        out = out * postscale_factor
    return faults.corrupt_output("allreduce", out)


def _eager_allgather_submit(x, name: str, set_id=0):
    faults.inject("allgather", name)
    t0 = _tstart()
    rt = basics.runtime()
    arr = np.asarray(x)
    if rt is None:
        _record_local("allgather", name, arr, t0)
        return (None, arr.copy())
    return (rt.allgather_submit(name, arr, set_id=set_id), None)


def _eager_allgather_finish(tok):
    native, done = tok
    out = done if native is None else basics.runtime().allgather_finish(
        native)
    return faults.corrupt_output("allgather", out)


def _eager_broadcast_submit(x, root_rank: int, name: str, set_id=0):
    faults.inject("broadcast", name)
    t0 = _tstart()
    rt = basics.runtime()
    arr = np.asarray(x)
    if rt is None:
        if root_rank != 0:
            raise ValueError(
                f"broadcast root_rank {root_rank} out of range for size 1")
        _record_local("broadcast", name, arr, t0)
        return (None, arr.copy())
    return (rt.broadcast_submit(name, arr, root_rank, set_id=set_id), None)


def _eager_broadcast_finish(tok):
    native, done = tok
    out = done if native is None else basics.runtime().broadcast_finish(
        native)
    return faults.corrupt_output("broadcast", out)


def _eager_alltoall_submit(x, splits, name: str, set_id=0):
    faults.inject("alltoall", name)
    rt = basics.runtime()
    if rt is None:
        return (None, _eager_alltoall(x, splits, name, set_id=set_id))
    arr = np.asarray(x)
    return (rt.alltoall_submit(name, arr, splits, set_id=set_id), None)


def _eager_alltoall_finish(tok):
    """Returns (output, received_splits)."""
    native, done = tok
    if native is None:
        return done  # local path already went through corrupt_output
    out, received = basics.runtime().alltoall_finish(native)
    return faults.corrupt_output("alltoall", out), received


def _check_reducescatter_op(op: ReduceOp) -> None:
    """Choke point for EVERY reducescatter route (incl. the torch/TF
    bindings that bypass :func:`reducescatter`): the native plane's ring
    reduce phase would execute Adasum/Min/Max chunks as Sum — fail loudly
    instead of silently substituting (same contract as the reference's
    Sum/Average-only reducescatter)."""
    if op is not Average and op is not Sum:
        raise NotImplementedError(
            f"reducescatter supports op=Average/Sum only (got {op})")


def _eager_reducescatter_submit(x, op: ReduceOp, name: str, set_id=0):
    faults.inject("reducescatter", name)
    t0 = _tstart()
    _check_reducescatter_op(op)
    rt = basics.runtime()
    arr = np.asarray(x)
    if rt is None:
        _record_local("reducescatter", name, arr, t0)
        return (None, arr.copy())
    return (rt.reducescatter_submit(name, arr, op.code, set_id=set_id),
            None)


def _eager_reducescatter_finish(tok, op: ReduceOp, set_size=None):
    native, done = tok
    out = (done if native is None
           else basics.runtime().reducescatter_finish(native))
    if op is Average:
        out = out / (set_size or basics.size())
    return faults.corrupt_output("reducescatter", out)


def _eager_allgather(x, name: str, set_id=0):
    faults.inject("allgather", name)
    t0 = _tstart()
    rt = basics.runtime()
    arr = np.asarray(x)
    if rt is None:
        _record_local("allgather", name, arr, t0)
        return faults.corrupt_output("allgather", arr.copy(), name)
    return faults.corrupt_output(
        "allgather", rt.allgather(name, arr, set_id=set_id), name)


def _eager_broadcast(x, root_rank: int, name: str, set_id=0):
    faults.inject("broadcast", name)
    t0 = _tstart()
    rt = basics.runtime()
    arr = np.asarray(x)
    if rt is None:
        if root_rank != 0:
            raise ValueError(
                f"broadcast root_rank {root_rank} out of range for size 1")
        _record_local("broadcast", name, arr, t0)
        return faults.corrupt_output("broadcast", arr.copy(), name)
    return faults.corrupt_output(
        "broadcast", rt.broadcast(name, arr, root_rank, set_id=set_id),
        name)


def _eager_alltoall(x, splits, name: str, set_id=0):
    """Returns ``(output, received_splits)``; received_splits[r] = dim-0
    rows that came from rank r (later-Horovod alltoall contract)."""
    faults.inject("alltoall", name)
    t0 = _tstart()
    rt = basics.runtime()
    arr = np.asarray(x)
    if rt is None:
        if arr.ndim == 0:
            arr = arr.reshape(1)
        rows = arr.shape[0] if arr.ndim else 1
        if splits is not None:
            sp = np.asarray(splits, np.int64).ravel()
            if sp.size != 1 or sp.sum() != rows:
                raise ValueError(
                    f"alltoall splits {sp.tolist()} do not match first "
                    f"dimension {rows} for size-1 job")
        _record_local("alltoall", name, arr, t0)
        return (faults.corrupt_output("alltoall", arr.copy(), name),
                np.array([rows], np.int64))
    out, received = rt.alltoall(name, arr, splits, set_id=set_id)
    return faults.corrupt_output("alltoall", out, name), received


def _eager_reducescatter(x, op: ReduceOp, name: str, set_id=0,
                         set_size=None):
    faults.inject("reducescatter", name)
    t0 = _tstart()
    _check_reducescatter_op(op)
    rt = basics.runtime()
    arr = np.asarray(x)
    if rt is None:
        _record_local("reducescatter", name, arr, t0)
        out = (arr / (set_size or basics.size()) if op is Average
               else arr.copy())
        return faults.corrupt_output("reducescatter", out, name)
    out = rt.reducescatter(name, arr, op.code, set_id=set_id)
    if op is Average:
        out = out / (set_size or basics.size())
    return faults.corrupt_output("reducescatter", out, name)


_executor = None
_executor_lock = threading.Lock()


def _get_executor():
    """A small shared pool, not thread-per-op: the moral equivalent of the
    single background thread servicing the queue in the reference
    (``operations.cc:303-498``).  A few workers let independent named tensors
    overlap, mirroring multi-stream dispatch."""
    global _executor
    with _executor_lock:
        if _executor is None:
            from concurrent.futures import ThreadPoolExecutor
            _executor = ThreadPoolExecutor(
                max_workers=4, thread_name_prefix="hvd-eager")
        return _executor


def _async_dispatch(fn, kind: str, name: str, to_jnp=True):
    """Submit ``fn`` to the eager worker pool, completing a handle — the
    Python face of the enqueue-with-callback contract (reference
    ``operations.cc:736-843``: enqueue returns immediately, callback fires
    from the background loop)."""
    h = _handles.allocate(name, kind)

    def work():
        try:
            out = fn()
            _handles.complete(h, jnp.asarray(out) if to_jnp else out)
        except Exception as e:  # delivered via synchronize(), like statuses
            _handles.complete(h, error=e)

    _get_executor().submit(work)
    return h


# ---------------------------------------------------------------------------
# Public collectives
# ---------------------------------------------------------------------------

def allreduce(tensor, average=None, name=None, op=None,
              prescale_factor=1.0, postscale_factor=1.0,
              compression=None, axis_name=None, process_set=None):
    """Allreduce across all workers/devices.

    SPMD plane: ``lax.psum``/``pmean`` over ``axis_name`` (default ``'data'``).
    Eager plane: name-negotiated runtime allreduce
    (reference ``EnqueueTensorAllreduce``, ``operations.cc:736-775``).

    ``compression`` (see :class:`horovod_tpu.ops.compression.Compression`)
    casts before the wire and back after, as in reference
    ``tensorflow/__init__.py:38-83``.
    """
    rop = _resolve_op(op, average)
    if compression is not None:
        tensor, ctx = compression.compress(tensor)
    else:
        ctx = None
    ax = _default_axis(axis_name)
    _reject_spmd_process_set(process_set, ax)
    if _axis_bound(ax):
        t = tensor * prescale_factor if prescale_factor != 1.0 else tensor
        if rop is Adasum:
            raise NotImplementedError(
                "op=Adasum is implemented on the eager plane only (native "
                "scaled-projection butterfly); inside an SPMD axis use "
                "op=Average, or run the Adasum reduction through the "
                "eager hvd.allreduce path")
        if rop is Average:
            out = lax.pmean(t, ax)
        elif rop is Sum:
            out = lax.psum(t, ax)
        elif rop is Min:
            out = lax.pmin(t, ax)
        elif rop is Max:
            out = lax.pmax(t, ax)
        else:
            raise ValueError(f"unknown op {rop}")
        if postscale_factor != 1.0:
            out = out * postscale_factor
    elif _is_traced(tensor):
        out = _plain_jit_fallback(tensor, "allreduce")
        scale = prescale_factor * postscale_factor
        if scale != 1.0:
            out = out * scale
    else:
        basics._check_initialized()
        set_id, set_size = _set_args(process_set)
        nm = _auto_name("allreduce", name)
        out = jnp.asarray(_eager_allreduce(
            tensor, rop, nm, prescale_factor, postscale_factor,
            set_id=set_id, set_size=set_size))
    if ctx is not None:
        out = compression.decompress(out, ctx)
    return out


def allreduce_(tensor, average=None, name=None, op=None, **kw):
    """In-place-flavored alias.  JAX arrays are immutable, so this returns the
    reduced value; kept for API parity with reference ``torch/mpi_ops.py``."""
    return allreduce(tensor, average=average, name=name, op=op, **kw)


def allreduce_async(tensor, average=None, name=None, op=None,
                    prescale_factor=1.0, postscale_factor=1.0):
    """Asynchronous eager allreduce returning a handle for
    :func:`synchronize`/:func:`poll` (reference ``torch/mpi_ops.py:58-116``)."""
    basics._check_initialized()
    rop = _resolve_op(op, average)
    nm = _auto_name("allreduce", name)
    return _async_dispatch(
        lambda: _eager_allreduce(np.asarray(tensor), rop, nm,
                                 prescale_factor, postscale_factor),
        "allreduce", nm)


def allreduce_async_(tensor, average=None, name=None, op=None, **kw):
    return allreduce_async(tensor, average=average, name=name, op=op, **kw)


def grouped_allreduce(tensors, average=None, name=None, op=None, axis_name=None,
                      prescale_factor=1.0, postscale_factor=1.0,
                      process_set=None):
    """Reduce a list of tensors as one logical request.  SPMD plane: a single
    fused ``psum`` over the flattened concatenation (the moral equivalent of
    the fusion buffer, reference ``fusion_buffer_manager.{h,cc}``).

    ``prescale_factor``/``postscale_factor``/``process_set`` follow
    :func:`allreduce`: scaling is applied inside the fused path (once per
    flat bucket, around the wire reduction); process sets are an eager-plane
    concept and are rejected inside an SPMD axis exactly like ``allreduce``.
    """
    rop = _resolve_op(op, average)
    if not tensors:
        return []
    ax = _default_axis(axis_name)
    _reject_spmd_process_set(process_set, ax)
    if _axis_bound(ax):
        if rop is Adasum:
            raise NotImplementedError(
                "op=Adasum is implemented on the eager plane only; see "
                "hvd.allreduce")
        from horovod_tpu.ops.fusion import fused_psum
        return fused_psum(tensors, ax, mean=rop is Average,
                          prescale_factor=prescale_factor,
                          postscale_factor=postscale_factor)
    if any(_is_traced(t) for t in tensors):
        out = [_plain_jit_fallback(t, "grouped_allreduce") for t in tensors]
        scale = prescale_factor * postscale_factor
        if scale != 1.0:
            out = [t * scale for t in out]
        return out
    return [allreduce(t, name=f"{_auto_name('grouped', name)}.{i}", op=rop,
                      prescale_factor=prescale_factor,
                      postscale_factor=postscale_factor,
                      process_set=process_set)
            for i, t in enumerate(tensors)]


def allgather(tensor, name=None, axis_name=None, process_set=None):
    """Concatenate each worker's tensor along dim 0 (reference TF op shape fn
    ``tensorflow/mpi_ops.cc:369-391``: first dims may differ, others must
    match).  SPMD plane: ``lax.all_gather(..., tiled=True)``."""
    ax = _default_axis(axis_name)
    _reject_spmd_process_set(process_set, ax)
    if _axis_bound(ax):
        return lax.all_gather(tensor, ax, axis=0, tiled=True)
    if _is_traced(tensor):
        return _plain_jit_fallback(tensor, "allgather")
    basics._check_initialized()
    set_id, _ = _set_args(process_set)
    nm = _auto_name("allgather", name)
    return jnp.asarray(_eager_allgather(tensor, nm, set_id=set_id))


def allgather_async(tensor, name=None):
    basics._check_initialized()
    nm = _auto_name("allgather", name)
    return _async_dispatch(lambda: _eager_allgather(np.asarray(tensor), nm),
                           "allgather", nm)


def allgather_object(obj, name=None):
    """Pickle-based object allgather (parity with later-Horovod
    ``allgather_object``; built on the same variable-dim-0 gather)."""
    import pickle
    basics._check_initialized()
    data = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
    nm = _auto_name("allgather_object", name)
    sizes = _eager_allgather(np.array([data.size], np.int64), nm + ".size")
    gathered = _eager_allgather(data, nm)
    out, off = [], 0
    for s in np.asarray(sizes).ravel():
        out.append(pickle.loads(gathered[off:off + int(s)].tobytes()))
        off += int(s)
    return out


def broadcast(tensor, root_rank=0, name=None, axis_name=None,
              process_set=None):
    """Broadcast from ``root_rank`` to all (reference
    ``EnqueueTensorBroadcast``, ``operations.cc:806-843``).

    SPMD plane: implemented as a masked ``psum`` (``lax`` has no explicit
    collective-broadcast primitive).  Cost note: a ring all-reduce moves
    ~2N bytes per link where an optimal broadcast moves ~N, so this is at
    most 2x the optimal wire cost; in SPMD training broadcast appears
    only at initialization/restore (params are replicated thereafter), so
    the one-time factor is irrelevant in practice, and inside ``jit``
    XLA may simplify the select further.  Steady-state broadcast traffic
    belongs on the eager plane, whose native fan-out broadcast is
    wire-optimal (``data_plane.cc``)."""
    ax = _default_axis(axis_name)
    _reject_spmd_process_set(process_set, ax)
    if _axis_bound(ax):
        idx = lax.axis_index(ax)
        masked = jnp.where(idx == root_rank, tensor,
                           jnp.zeros_like(tensor))
        # psum promotes bool -> int32; restore the caller's dtype so the
        # result aval matches the input (donation/apply_updates safety).
        return lax.psum(masked, ax).astype(jnp.asarray(tensor).dtype)
    if _is_traced(tensor):
        return _plain_jit_fallback(tensor, "broadcast")
    basics._check_initialized()
    set_id, _ = _set_args(process_set)
    nm = _auto_name("broadcast", name)
    return jnp.asarray(_eager_broadcast(tensor, root_rank, nm,
                                        set_id=set_id))


def broadcast_(tensor, root_rank=0, name=None, **kw):
    return broadcast(tensor, root_rank=root_rank, name=name, **kw)


def broadcast_async(tensor, root_rank=0, name=None):
    basics._check_initialized()
    nm = _auto_name("broadcast", name)
    return _async_dispatch(
        lambda: _eager_broadcast(np.asarray(tensor), root_rank, nm),
        "broadcast", nm)


def broadcast_async_(tensor, root_rank=0, name=None):
    return broadcast_async(tensor, root_rank=root_rank, name=name)


def broadcast_object(obj, root_rank=0, name=None):
    """Pickle-based broadcast, used for optimizer state / RNG / config
    (reference ``torch/__init__.py:287-403`` wraps scalars in tensors; we
    ship pickled bytes with a size prologue)."""
    import pickle
    basics._check_initialized()
    nm = _auto_name("broadcast_object", name)
    if basics.rank() == root_rank:
        data = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
        sz = np.array([data.size], np.int64)
    else:
        data = np.zeros(0, np.uint8)
        sz = np.zeros(1, np.int64)
    sz = _eager_broadcast(sz, root_rank, nm + ".size")
    n = int(np.asarray(sz).ravel()[0])
    if basics.rank() != root_rank:
        data = np.zeros(n, np.uint8)
    data = _eager_broadcast(data, root_rank, nm)
    return pickle.loads(np.asarray(data).tobytes())


def reducescatter(tensor, op=None, name=None, axis_name=None,
                  process_set=None):
    """Reduce then scatter along dim 0.  SPMD plane: ``lax.psum_scatter``.
    Not in the v0.18 reference (its collectives are only
    allreduce/allgather/broadcast, ``message.h:47-49``) but the clean
    collective layer exposes it since XLA provides it natively."""
    rop = _resolve_op(op, None)
    if rop not in (Average, Sum):
        raise ValueError(f"reducescatter supports Average/Sum, got {rop}")
    ax = _default_axis(axis_name)
    _reject_spmd_process_set(process_set, ax)
    if _axis_bound(ax):
        out = lax.psum_scatter(tensor, ax, scatter_dimension=0, tiled=True)
        if rop is Average:
            out = out / lax.axis_size(ax)
        return out
    if _is_traced(tensor):
        return _plain_jit_fallback(tensor, "reducescatter")
    basics._check_initialized()
    set_id, set_size = _set_args(process_set)
    nm = _auto_name("reducescatter", name)
    return jnp.asarray(_eager_reducescatter(tensor, rop, nm, set_id=set_id,
                                            set_size=set_size))


def alltoall(tensor, splits=None, name=None, axis_name=None,
             process_set=None):
    """Exchange dim-0 chunks between workers (the EP/MoE primitive; absent
    from the v0.18 reference, present in later Horovod).  SPMD plane:
    ``lax.all_to_all(tiled=True)`` with equal splits."""
    ax = _default_axis(axis_name)
    _reject_spmd_process_set(process_set, ax)
    if _axis_bound(ax):
        if splits is not None:
            raise NotImplementedError(
                "uneven splits under jit need a STATIC output capacity "
                "(XLA shapes); use hvd.alltoall_ragged(tensor, splits, "
                "output_size) which returns (padded output, received "
                "counts), or the eager path outside jit")
        return lax.all_to_all(tensor, ax, split_axis=0, concat_axis=0,
                              tiled=True)
    if _is_traced(tensor):
        out = _plain_jit_fallback(tensor, "alltoall")
        if splits is not None:
            # Keep the tuple contract under a plain-jit trace too (size-1
            # identity: everything came from self).
            return out, jnp.asarray(np.asarray([out.shape[0]], np.int64))
        return out
    basics._check_initialized()
    set_id, _ = _set_args(process_set)
    nm = _auto_name("alltoall", name)
    out, received = _eager_alltoall(tensor, splits, nm, set_id=set_id)
    if splits is not None:
        # Later-Horovod contract: with explicit splits the caller gets the
        # received row counts back (needed to slice the uneven output).
        return jnp.asarray(out), jnp.asarray(received)
    return jnp.asarray(out)


def alltoall_ragged(tensor, splits, output_size: int, axis_name=None,
                    use_primitive=None):
    """Uneven (ragged) all-to-all INSIDE the SPMD plane — the MoE/EP
    exchange with per-destination row counts, jit-compatible via a
    STATIC output capacity (closes the sharp edge the plain
    ``alltoall(splits=...)`` guard documents; later-Horovod has only the
    eager equivalent, ``horovod/common/ops/...alltoall``).

    ``tensor``: ``[N, ...]`` this shard's rows, grouped by destination
    (rows for peer 0 first, then peer 1, ...).  ``splits``: ``[S]`` rows
    to send to each peer (may be traced).  ``output_size``: static row
    capacity of the result — the caller's bound on ``sum(received)``
    (e.g. MoE capacity x experts); rows beyond it are DROPPED, matching
    a capacity-factor router's semantics.  Returns ``(out, received)``:
    ``out[output_size, ...]`` holds each source's rows concatenated in
    source order (unwritten tail rows are zeros), ``received[S]`` is the
    per-source row count each peer SENT (pre-drop; ``min`` it against
    the remaining capacity to count what landed).

    Routing follows the flash-kernel pattern: on a TPU mesh the XLA
    ``ragged-all-to-all`` primitive moves exactly the ragged bytes; on
    CPU/virtual meshes (where XLA has no such HLO) an exact dense twin —
    pad-to-N regular all_to_all + scatter-compact — computes the same
    answer, so tests and the dryrun certify the semantics everywhere.

    Differentiation: the dense twin has full AD support with the
    expected semantics (rows that land somewhere receive their
    cotangent, dropped/slack rows receive zero — gated by
    ``test_alltoall_ragged_gradient``); the primitive path's AD follows
    jax's ``lax.ragged_all_to_all`` — pass ``use_primitive=False`` under
    ``grad`` if your jax version lacks its transpose rule.
    """
    ax = _default_axis(axis_name)
    if not _axis_bound(ax):
        raise ValueError(
            "alltoall_ragged is the SPMD-plane API (call it inside "
            "shard_map with the axis bound); the eager plane's "
            "hvd.alltoall(tensor, splits=...) already supports uneven "
            "splits directly")
    size = lax.axis_size(ax)
    me = lax.axis_index(ax)
    sp = jnp.asarray(splits, jnp.int32)
    n = tensor.shape[0]
    trailing = tensor.shape[1:]

    in_off = jnp.concatenate([jnp.zeros(1, jnp.int32),
                              jnp.cumsum(sp)[:-1].astype(jnp.int32)])
    # ONE metadata collective serves both routes: m[s, d] = rows s -> d.
    m = lax.all_gather(sp, ax, axis=0).astype(jnp.int32)   # [S, S]
    recv = m[:, me]                                        # rows j -> me

    primitive = (use_primitive if use_primitive is not None
                 else _exec_on_tpu_spmd(tensor))
    if primitive:
        # Sender-side offsets into each RECEIVER's buffer: my block lands
        # after every lower-ranked sender's contribution to that peer.
        mask = (jnp.arange(size) < me)[:, None]
        out_off = jnp.sum(m * mask, axis=0).astype(jnp.int32)
        # Enforce the capacity-drop contract on the WIRE: clamp each
        # block to the room left at its receiver (every rank derives the
        # same clamps from the same gathered matrix), so the primitive
        # never updates past the static buffer.  `recv` is still the
        # PRE-clamp per-source count (callers min with capacity).
        send_sz = jnp.clip(output_size - out_off, 0, sp)
        off_at_me = jnp.concatenate(
            [jnp.zeros(1, jnp.int32),
             jnp.cumsum(recv)[:-1].astype(jnp.int32)])
        recv_sz = jnp.clip(output_size - off_at_me, 0, recv)
        out = jnp.zeros((output_size,) + trailing, tensor.dtype)
        out = lax.ragged_all_to_all(
            tensor, out, in_off, send_sz,
            jnp.minimum(out_off, output_size), recv_sz, axis_name=ax)
        return out, recv

    # Dense twin: pad each destination block to N rows (worst case: one
    # peer gets everything), exchange, scatter-compact into the capacity
    # buffer.  Moves S x the ragged bytes — fine for the CPU/test plane,
    # which is why the TPU mesh takes the primitive above.
    idx = jnp.arange(n)
    cum = jnp.cumsum(sp)
    dest = jnp.searchsorted(cum, idx, side="right").astype(jnp.int32)
    slot = idx - in_off[jnp.clip(dest, 0, size - 1)]
    valid_in = idx < cum[-1]
    buf = jnp.zeros((size, n) + trailing, tensor.dtype)
    # Rows beyond sum(splits) scatter to an out-of-bounds destination and
    # are dropped (mode="drop") — never overwriting a real slot.
    buf = buf.at[jnp.where(valid_in, dest, size), slot].set(
        tensor, mode="drop")
    ex = lax.all_to_all(buf, ax, split_axis=0, concat_axis=0)  # [S, n, ...]
    cum_recv = jnp.concatenate([jnp.zeros(1, jnp.int32),
                                jnp.cumsum(recv)[:-1].astype(jnp.int32)])
    pos = cum_recv[:, None] + jnp.arange(n)[None, :]
    valid = jnp.arange(n)[None, :] < recv[:, None]
    pos = jnp.where(valid, pos, output_size)        # overflow/pad -> dump
    out = jnp.zeros((output_size + 1,) + trailing, tensor.dtype)
    out = out.at[pos.reshape(-1)].set(
        ex.reshape((size * n,) + trailing), mode="drop")[:output_size]
    return out, recv


def _exec_on_tpu_spmd(x) -> bool:
    from horovod_tpu.topology import exec_on_tpu
    return exec_on_tpu(x)


def barrier(name=None, process_set=None) -> None:
    """Block until every member has arrived (later-Horovod ``hvd.barrier``;
    the negotiation round itself is the barrier on the eager plane)."""
    basics._check_initialized()
    rt = basics.runtime()
    nm = _auto_name("barrier", name)
    faults.inject("barrier", nm)
    if rt is None:
        return
    set_id, _ = _set_args(process_set)
    rt.barrier(nm, set_id=set_id)


def join() -> int:
    """Signal this rank has no more work; returns the last joining rank.
    (Parity with later-Horovod ``join``; the v0.18 reference instead shuts
    down via the shutdown bit, ``message.h:110-122``.)"""
    basics._check_initialized()
    rt = basics.runtime()
    if rt is None:
        return 0
    return rt.join()

"""Pallas flash attention — the TPU kernel for the transformer hot path.

The reference has no attention machinery at all (SURVEY §5.7: Horovod
predates it); this framework makes long-context training first-class, and
the innermost single-device attention is where the FLOPs and the memory
blowup live.  The lax implementation (``parallel/sequence.py
local_attention``) materializes the [B, H, T, T] score matrix in HBM —
O(T^2) memory and two full HBM round trips.  This kernel computes the
same exact attention blockwise in VMEM with online softmax (Dao et al.
2022, FlashAttention), never materializing scores: memory is O(T·D) in
HBM and O(block·D) in VMEM, so sequence length is bounded by HBM, not by
the ~16 MB VMEM.

Layout: ``[B, T, H, D]`` (the repo convention) is folded to
``[B·H, T, D]``; the grid walks (batch·head, query-block, key-block) —
the innermost grid dimension streams one K/V tile at a time through
VMEM (Mosaic double-buffers the fetches), while fp32 accumulators and
the online-softmax m/l state persist across the inner dimension in VMEM
scratch.  Causal masking skips the compute of key blocks strictly above
the diagonal (``pl.when``).  The backward pass is the standard flash
recomputation: a per key-block kernel for dK/dV streaming query tiles,
and a per query-block kernel for dQ streaming key tiles, using the saved
row max/denominator.

``interpret=True`` (or ``HOROVOD_FLASH_INTERPRET=1``) runs the kernels
in the Pallas interpreter — exact same code path, CPU-executable — which
is how the CI oracle tests run without a TPU.
"""

from __future__ import annotations

import functools
import os

import numpy as np
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = float("-inf")


def _exec_on_tpu(x) -> bool:
    """Executing-mesh platform answer — shared helper, see
    :func:`horovod_tpu.topology.exec_on_tpu` (lives there because the
    collective layer needs the same gate)."""
    from horovod_tpu.topology import exec_on_tpu
    return exec_on_tpu(x)


def _interpret_default(x=None) -> bool:
    """Interpret-mode default for the kernel: the explicit debug env
    knob wins; otherwise interpret iff the computation does NOT execute
    on TPU — judged from the operand's executing mesh when one is given
    (see :func:`_exec_on_tpu`), else from the host's default backend."""
    if os.environ.get("HOROVOD_FLASH_INTERPRET") == "1":
        return True
    if x is not None:
        return not _exec_on_tpu(x)
    try:
        return jax.default_backend() != "tpu"
    except Exception:  # pragma: no cover
        return True


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def _out_vma(*arrays):
    """vma set for pallas out_shapes: inside a check_vma=True shard_map,
    outputs vary over every axis the inputs vary over (ShapeDtypeStructs
    with vma=None are rejected there); frozenset() outside shard_map."""
    from horovod_tpu.parallel._vma import vma_of
    out = set()
    for a in arrays:
        out |= vma_of(a)
    return frozenset(out)


def _mask_scores(s, qi, kj, block_q, block_k, causal, qseg_ref,
                 kseg_ref=None):
    """Apply causal and/or segment (sequence-packing) masks to a score
    block.  Segment ids ride a [B, 1, T] layout like the m/l rows; tokens
    attend only within their own segment.  ``kseg_ref`` defaults to the
    q-side ref (self-attention); ring attention passes the ROTATED
    K-side ids separately."""
    if causal:
        qpos = qi * block_q + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        kpos = kj * block_k + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        s = jnp.where(qpos >= kpos, s, NEG_INF)
    if qseg_ref is not None:
        if kseg_ref is None:
            kseg_ref = qseg_ref
        qseg = qseg_ref[0, 0, pl.dslice(qi * block_q, block_q)]
        kseg = kseg_ref[0, 0, pl.dslice(kj * block_k, block_k)]
        s = jnp.where(qseg[:, None] == kseg[None, :], s, NEG_INF)
    return s


def _fwd_kernel(q_ref, k_ref, v_ref, *rest,
                block_q: int, block_k: int, num_k: int, causal: bool,
                scale: float, segments: bool):
    if segments:
        qseg_ref, kseg_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        o_ref, m_ref, l_ref, acc_ref = rest
        qseg_ref = kseg_ref = None
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    rows = pl.dslice(qi * block_q, block_q)

    @pl.when(kj == 0)
    def _init():
        m_ref[0, 0, rows] = jnp.full((block_q,), NEG_INF, jnp.float32)
        l_ref[0, 0, rows] = jnp.zeros((block_q,), jnp.float32)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    def compute():
        q = q_ref[0].astype(jnp.float32)                 # [bq, D]
        k_blk = k_ref[0].astype(jnp.float32)             # [bk, D]
        v_blk = v_ref[0].astype(jnp.float32)
        m = m_ref[0, 0, rows]
        l = l_ref[0, 0, rows]
        acc = acc_ref[...]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [bq, bk]
        s = _mask_scores(s, qi, kj, block_q, block_k, causal, qseg_ref,
                         kseg_ref)
        m_blk = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m, m_blk)
        safe_m = jnp.where(m_new == NEG_INF, 0.0, m_new)
        p = jnp.exp(s - safe_m[:, None])
        p = jnp.where(s == NEG_INF, 0.0, p)
        corr = jnp.where(m == NEG_INF, 0.0, jnp.exp(m - safe_m))
        m_ref[0, 0, rows] = m_new
        l_ref[0, 0, rows] = l * corr + jnp.sum(p, axis=-1)
        acc_ref[...] = acc * corr[:, None] + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        # Key blocks strictly above the diagonal contribute nothing.
        pl.when(kj * block_k < (qi + 1) * block_q)(compute)
    else:
        compute()

    @pl.when(kj == num_k - 1)
    def _finalize():
        l = l_ref[0, 0, rows]
        denom = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[...] / denom[:, None]).astype(o_ref.dtype)


# ---------------------------------------------------------------------------
# Backward — standard flash recomputation
#   D_i  = rowsum(dO ⊙ O)
#   P    = exp(QKᵀ·scale − m) / l          (recomputed per block)
#   dV  += Pᵀ dO
#   dP   = dO Vᵀ
#   dS   = P ⊙ (dP − D_i)
#   dQ  += dS K · scale ;  dK += dSᵀ Q · scale
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, m_ref, l_ref,
                   *rest, block_q: int, block_k: int,
                   num_k: int, causal: bool, scale: float,
                   segments: bool):
    if segments:
        qseg_ref, kseg_ref, dq_ref, acc_ref = rest
    else:
        dq_ref, acc_ref = rest
        qseg_ref = kseg_ref = None
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    rows = pl.dslice(qi * block_q, block_q)

    @pl.when(kj == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    def compute():
        q = q_ref[0].astype(jnp.float32)
        o = o_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        m = m_ref[0, 0, rows]
        l = l_ref[0, 0, rows]
        safe_m = jnp.where(m == NEG_INF, 0.0, m)
        denom = jnp.where(l == 0.0, 1.0, l)
        di = jnp.sum(do * o, axis=-1)                    # [bq]
        k_blk = k_ref[0].astype(jnp.float32)             # [bk, D]
        v_blk = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        s = _mask_scores(s, qi, kj, block_q, block_k, causal, qseg_ref,
                         kseg_ref)
        p = jnp.where(s == NEG_INF, 0.0,
                      jnp.exp(s - safe_m[:, None])) / denom[:, None]
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)          # [bq, bk]
        ds = p * (dp - di[:, None])
        acc_ref[...] += jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    if causal:
        pl.when(kj * block_k < (qi + 1) * block_q)(compute)
    else:
        compute()

    @pl.when(kj == num_k - 1)
    def _finalize():
        dq_ref[0] = acc_ref[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, m_ref, l_ref,
                    *rest, block_q: int, block_k: int, num_q: int,
                    causal: bool, scale: float, segments: bool):
    if segments:
        (qseg_ref, kseg_ref, dk_ref, dv_ref, dk_acc_ref,
         dv_acc_ref) = rest
    else:
        dk_ref, dv_ref, dk_acc_ref, dv_acc_ref = rest
        qseg_ref = kseg_ref = None
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    rows = pl.dslice(qi * block_q, block_q)

    @pl.when(qi == 0)
    def _init():
        dk_acc_ref[...] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[...] = jnp.zeros_like(dv_acc_ref)

    def compute():
        k = k_ref[0].astype(jnp.float32)                 # [bk, D]
        v = v_ref[0].astype(jnp.float32)
        q_blk = q_ref[0].astype(jnp.float32)             # [bq, D]
        o_blk = o_ref[0].astype(jnp.float32)
        do_blk = do_ref[0].astype(jnp.float32)
        m_blk = m_ref[0, 0, rows]
        l_blk = l_ref[0, 0, rows]
        safe_m = jnp.where(m_blk == NEG_INF, 0.0, m_blk)
        denom = jnp.where(l_blk == 0.0, 1.0, l_blk)
        di = jnp.sum(do_blk * o_blk, axis=-1)
        s = jax.lax.dot_general(
            q_blk, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [bq, bk]
        s = _mask_scores(s, qi, ki, block_q, block_k, causal, qseg_ref,
                         kseg_ref)
        p = jnp.where(s == NEG_INF, 0.0,
                      jnp.exp(s - safe_m[:, None])) / denom[:, None]
        dv_acc_ref[...] += jax.lax.dot_general(
            p, do_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # [bk, D]
        dp = jax.lax.dot_general(
            do_blk, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - di[:, None])
        dk_acc_ref[...] += jax.lax.dot_general(
            ds, q_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    if causal:
        # Query blocks strictly left of this key block see none of it.
        pl.when((qi + 1) * block_q > ki * block_k)(compute)
    else:
        compute()

    @pl.when(qi == num_q - 1)
    def _finalize():
        dk_ref[0] = dk_acc_ref[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc_ref[...].astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# pallas_call plumbing
# ---------------------------------------------------------------------------

def _causal_kv_map(block_q, block_k):
    # Last key block with any unmasked entry for query block i.
    return lambda bh_, i, j: (
        bh_, jnp.minimum(j, ((i + 1) * block_q - 1) // block_k), 0)


def _causal_q_map(block_q, block_k):
    # First query block that sees key block j.
    return lambda bh_, j, i: (
        bh_, jnp.maximum(i, (j * block_k) // block_q), 0)


def _check_shapes(q, k, v, block_q, block_k):
    if q.shape != k.shape or q.shape != v.shape:
        raise ValueError(f"q/k/v shapes must match, got {q.shape} "
                         f"{k.shape} {v.shape}")
    b, t, h, d = q.shape
    if t % block_q != 0 or t % block_k != 0:
        raise ValueError(
            f"sequence length {t} must be divisible by block_q={block_q} "
            f"and block_k={block_k} (pad the sequence)")
    return b, t, h, d


def _fold(x):
    # [B, T, H, D] -> [B*H, T, D]
    b, t, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, t, d)


def _unfold(x, b, h):
    bh, t, d = x.shape
    return x.reshape(b, h, t, d).transpose(0, 2, 1, 3)


def _seg_spec(t, h):
    # Segment ids ride a [B, 1, T] layout (same tiling story as m/l);
    # the index map folds the batch*head grid dim back to batch.
    return pl.BlockSpec((1, 1, t), lambda bh_, i, j: (bh_ // h, 0, 0))


def _fwd_parts(qf, kf, vf, qsegf, ksegf, h, causal, scale, block_q,
               block_k, interpret):
    """Folded-layout forward: (of, m, l) with m/l the [bh, 1, T] online
    softmax state — the raw pieces ring attention merges across steps.
    ``qsegf``/``ksegf`` are [B, 1, T] (pass the same array for
    self-attention)."""
    bh, t, d = qf.shape
    num_k = t // block_k
    grid = (bh, t // block_q, num_k)
    kernel = functools.partial(_fwd_kernel, block_q=block_q,
                               block_k=block_k, num_k=num_k, causal=causal,
                               scale=scale, segments=qsegf is not None)
    # Causal: masked steps (above the diagonal) clamp the K/V block index
    # to the last live block — same index as the preceding step, so Mosaic
    # elides the DMA instead of fetching a tile whose work pl.when skips.
    kv_map = (_causal_kv_map(block_q, block_k) if causal
              else (lambda bh_, i, j: (bh_, j, 0)))
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda bh_, i, j: (bh_, i, 0)),
        pl.BlockSpec((1, block_k, d), kv_map),
        pl.BlockSpec((1, block_k, d), kv_map),
    ]
    operands = [qf, kf, vf]
    if qsegf is not None:
        in_specs += [_seg_spec(t, h), _seg_spec(t, h)]
        operands += [qsegf, ksegf]
    vma = _out_vma(*operands)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh_, i, j: (bh_, i, 0)),
            # TPU tiling: the last two block dims must be (8k, 128k) or
            # equal the array dims — a [bh, 1, T] layout with full
            # (1, 1, T) blocks satisfies that for any block_q.  The m/l
            # rows double as the online-softmax running state across the
            # key-block grid dimension (the block is revisited, so it
            # stays resident in VMEM).
            pl.BlockSpec((1, 1, t), lambda bh_, i, j: (bh_, 0, 0)),
            pl.BlockSpec((1, 1, t), lambda bh_, i, j: (bh_, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), qf.dtype, vma=vma),
            jax.ShapeDtypeStruct((bh, 1, t), jnp.float32, vma=vma),
            jax.ShapeDtypeStruct((bh, 1, t), jnp.float32, vma=vma),
        ],
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(*operands)


def _fwd(q, k, v, seg, causal, scale, block_q, block_k, interpret):
    b, t, h, d = _check_shapes(q, k, v, block_q, block_k)
    if seg is not None:
        if seg.shape != (b, t):
            raise ValueError(
                f"segment_ids must be [B, T] = {(b, t)} matching q/k/v, "
                f"got {seg.shape} (pad segment ids with the sequence)")
        if not jnp.issubdtype(seg.dtype, jnp.integer):
            raise ValueError(
                f"segment_ids must be integer, got {seg.dtype}")
    qf, kf, vf = _fold(q), _fold(k), _fold(v)
    segf = seg.reshape(b, 1, t) if seg is not None else None
    o, m, l = _fwd_parts(qf, kf, vf, segf, segf, h, causal, scale,
                         block_q, block_k, interpret)
    return _unfold(o, b, h), (qf, kf, vf, o, m, l, seg, b, h)


def _bwd_parts(qf, kf, vf, of, dof, m, l, qsegf, ksegf, h, causal, scale,
               block_q, block_k, interpret):
    """Folded-layout backward: (dqf, dkf, dvf) from the GLOBAL (m, l)
    rows.  Ring attention calls this per rotating block with the final
    accumulated m/l — the per-block contributions are then the exact
    global-softmax gradients (p recomputed as exp(s − m)/l)."""
    bh, t, d = qf.shape
    num_k = t // block_k
    num_q = t // block_q
    segments = qsegf is not None
    kernel_dq = functools.partial(_bwd_dq_kernel, block_q=block_q,
                                  block_k=block_k, num_k=num_k,
                                  causal=causal, scale=scale,
                                  segments=segments)
    kv_map = (_causal_kv_map(block_q, block_k) if causal
              else (lambda bh_, i, j: (bh_, j, 0)))
    dq_specs = [
        pl.BlockSpec((1, block_q, d), lambda bh_, i, j: (bh_, i, 0)),
        pl.BlockSpec((1, block_k, d), kv_map),
        pl.BlockSpec((1, block_k, d), kv_map),
        pl.BlockSpec((1, block_q, d), lambda bh_, i, j: (bh_, i, 0)),
        pl.BlockSpec((1, block_q, d), lambda bh_, i, j: (bh_, i, 0)),
        pl.BlockSpec((1, 1, t), lambda bh_, i, j: (bh_, 0, 0)),
        pl.BlockSpec((1, 1, t), lambda bh_, i, j: (bh_, 0, 0)),
    ]
    dq_operands = [qf, kf, vf, of, dof, m, l]
    if segments:
        dq_specs += [_seg_spec(t, h), _seg_spec(t, h)]
        dq_operands += [qsegf, ksegf]
    vma = _out_vma(*dq_operands)
    dq = pl.pallas_call(
        kernel_dq,
        grid=(bh, num_q, num_k),
        in_specs=dq_specs,
        out_specs=pl.BlockSpec((1, block_q, d),
                               lambda bh_, i, j: (bh_, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, t, d), qf.dtype, vma=vma),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(*dq_operands)

    kernel_dkv = functools.partial(_bwd_dkv_kernel, block_q=block_q,
                                   block_k=block_k, num_q=num_q,
                                   causal=causal, scale=scale,
                                   segments=segments)
    q_map = (_causal_q_map(block_q, block_k) if causal
             else (lambda bh_, j, i: (bh_, i, 0)))
    dkv_specs = [
        pl.BlockSpec((1, block_q, d), q_map),
        pl.BlockSpec((1, block_k, d), lambda bh_, j, i: (bh_, j, 0)),
        pl.BlockSpec((1, block_k, d), lambda bh_, j, i: (bh_, j, 0)),
        pl.BlockSpec((1, block_q, d), q_map),
        pl.BlockSpec((1, block_q, d), q_map),
        pl.BlockSpec((1, 1, t), lambda bh_, j, i: (bh_, 0, 0)),
        pl.BlockSpec((1, 1, t), lambda bh_, j, i: (bh_, 0, 0)),
    ]
    dkv_operands = [qf, kf, vf, of, dof, m, l]
    if segments:
        dkv_specs += [_seg_spec(t, h), _seg_spec(t, h)]
        dkv_operands += [qsegf, ksegf]
    vma = _out_vma(*dkv_operands)
    dk, dv = pl.pallas_call(
        kernel_dkv,
        grid=(bh, num_k, num_q),
        in_specs=dkv_specs,
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda bh_, j, i: (bh_, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh_, j, i: (bh_, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), qf.dtype, vma=vma),
            jax.ShapeDtypeStruct((bh, t, d), qf.dtype, vma=vma),
        ],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        interpret=interpret,
    )(*dkv_operands)
    return dq, dk, dv


def _bwd(causal, scale, block_q, block_k, interpret, res, do):
    qf, kf, vf, of, m, l, seg, b, h = res
    bh, t, d = qf.shape
    dof = _fold(do)
    segf = seg.reshape(b, 1, t) if seg is not None else None
    dq, dk, dv = _bwd_parts(qf, kf, vf, of, dof, m, l, segf, segf, h,
                            causal, scale, block_q, block_k, interpret)
    dseg = (np.zeros(seg.shape, jax.dtypes.float0)
            if seg is not None else None)
    return (_unfold(dq, b, h), _unfold(dk, b, h), _unfold(dv, b, h),
            dseg)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q, k, v, causal: bool = True,
                    scale: Optional[float] = None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    interpret: Optional[bool] = None, segment_ids=None):
    """Exact attention, flash-style, as a Pallas TPU kernel.

    q/k/v: ``[B, T, H, D]``; returns ``[B, T, H, D]``.  ``T`` must be a
    multiple of the block sizes (pad the sequence).  Numerically matches
    ``parallel/sequence.local_attention`` (the lax oracle) to fp32
    accumulation tolerance, forward and backward.

    ``block_q``/``block_k`` default to AUTO: the largest power of two
    ≤ 1024 dividing ``T`` (≤ 512 when ``D > 128`` — the 1024 sweep only
    covered head dims ≤ 128, and bigger heads roughly double the bwd
    kernel's VMEM pressure).  Swept on a real v5e (docs/kernels.md): 512
    blocks run the fwd+bwd pair 2.7× faster than 128 blocks at T=2048
    and 4.2× at T=8192, and 1024 another 1.13–1.33× over 512 (r4 sweep;
    bigger tiles amortize the grid/DMA overhead and feed the MXU longer
    contractions; 1024×1024 f32 scores ≈ 4 MB of the ~16 MB VMEM, still
    comfortable next to the tile operands).

    ``segment_ids`` ([B, T] int32) enables sequence packing: tokens
    attend only within their own segment (composes with ``causal``) —
    the block-sparse masking XLA's fused attention cannot express, and
    the reason the kernel scaffold exists (docs/kernels.md).
    """
    out, _ = _flash_fwd(q, k, v, causal, scale, block_q, block_k,
                        interpret, segment_ids)
    return out


def _auto_block(t: int, head_dim: Optional[int] = None) -> int:
    if t < 128:
        # Short sequences (interpret mode / tests): old clamp behavior.
        for b in (64, 32, 16, 8):
            if t % b == 0:
                return b
        raise ValueError(
            f"sequence length {t} must be divisible by 8 for the flash "
            f"kernel (pad the sequence)")
    # Floor at 128: tinier auto blocks (e.g. 8 for T=1992) would explode
    # the grid and run orders of magnitude slower than the error is
    # annoying — same contract as the old fixed-128 default.
    # 1024 preferred over 512 since r4: measured fwd+bwd 1.33x at T=2048
    # (B4 H32 D128), 1.13x at T=4096/8192 (docs/kernels.md table);
    # 1024x1024 f32 scores = 4 MB of VMEM, still comfortable.  The 1024
    # preference was swept at head_dim<=128 only; larger head dims
    # roughly double the dkv kernel's operand + f32 score/p VMEM
    # pressure, so cap the auto choice at 512 there (explicit
    # block_q/block_k still override).
    prefs = (512, 256, 128) if (head_dim or 0) > 128 else (1024, 512,
                                                           256, 128)
    for b in prefs:
        if t % b == 0:
            return b
    raise ValueError(
        f"sequence length {t} must be divisible by 128 for auto block "
        f"sizing (pad the sequence, or pass explicit block_q/block_k)")


def _eff_blocks(t, block_q, block_k, head_dim=None):
    # None = auto (largest power of two <= 1024 dividing T — capped at
    # 512 when head_dim > 128, see _auto_block — measured fastest);
    # explicit blocks are clamped to T so e.g. T=64 works with block
    # 128 (divisibility still enforced after clamping).
    bq = _auto_block(t, head_dim) if block_q is None else min(block_q, t)
    bk = _auto_block(t, head_dim) if block_k is None else min(block_k, t)
    return bq, bk


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret,
               segment_ids=None):
    d = q.shape[-1]
    scale_ = (d ** -0.5) if scale is None else scale
    interp = _interpret_default(q) if interpret is None else interpret
    bq, bk = _eff_blocks(q.shape[1], block_q, block_k, d)
    return _fwd(q, k, v, segment_ids, causal, scale_, bq, bk, interp)


def _flash_bwd(causal, scale, block_q, block_k, interpret, res, do):
    t, d = res[0].shape[1], res[0].shape[-1]
    scale_ = (d ** -0.5) if scale is None else scale
    interp = _interpret_default(res[0]) if interpret is None else interpret
    bq, bk = _eff_blocks(t, block_q, block_k, d)
    return _bwd(causal, scale_, bq, bk, interp, res, do)


flash_attention.defvjp(_flash_fwd, _flash_bwd)

"""Fused ResNet stem tail: ``maxpool3x3/s2(relu(x*scale + offset))``.

The per-HLO profile (docs/benchmarks.md) shows the stem's BN-apply/relu
output — a 411 MB bf16 tensor at 112² — materialized between the
batch-norm and the max-pool.  This op computes the whole tail in one
VMEM pass per batch element (Pallas kernel), eliminating that HBM
round-trip; it is the "one untried idea" named in the roofline
irreducibility analysis, bounded there at ~2 ms (~+2%) of the 99 ms
step.

Status: built and gated OFF by default (``ResNet(stem="s2d_fused")``
opts in).  Correctness is proven everywhere — an exact lax twin runs on
CPU/virtual meshes and in interpret mode, and the kernel matches
``nn.max_pool(relu(bn))`` bitwise at f32 — but the ~2 ms claim is
PENDING on-chip measurement (the build host's tunneled chip was down
when this landed; see docs/benchmarks.md).

Backward: a ``jax.custom_vjp`` whose bwd recomputes the cheap
elementwise tail via the lax twin and lets XLA differentiate it — the
forward saves only ``x``/``scale``/``offset`` (x is the conv output,
already materialized), so the kernel's HBM saving is not paid back in
residuals.

Pooling identity used by the kernel (window 3, stride 2, pad 1, even H):
``out[i] = max(y[2i-1], y[2i], y[2i+1])`` = ``max(odd[i-1], pair[i])``
where ``pair[i] = max(y[2i], y[2i+1])`` and ``odd[i] = y[2i+1]`` — both
obtained from a CONTIGUOUS [H/2, 2] reshape, so the kernel needs no
strided slicing (Mosaic-friendly); same trick per axis.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

NEG = -jnp.inf


def _pool_axis(y, axis):
    """max(window 3, stride 2, pad 1) along ``axis`` (even length) via
    the contiguous pair/odd identity above.  Shared by kernel and twin
    so the arithmetic is identical."""
    h = y.shape[axis]
    new = y.shape[:axis] + (h // 2, 2) + y.shape[axis + 1:]
    yr = y.reshape(new)
    pair = yr.max(axis=axis + 1)                       # [.., h/2, ..]
    odd = lax.index_in_dim(yr, 1, axis=axis + 1, keepdims=False)
    shifted = jnp.concatenate(
        [jnp.full(lax.slice_in_dim(odd, 0, 1, axis=axis).shape, NEG,
                  y.dtype),
         lax.slice_in_dim(odd, 0, h // 2 - 1, axis=axis)], axis=axis)
    return jnp.maximum(shifted, pair)


def _tail(x, scale, offset):
    """The exact computation, in plain lax: relu(x*scale+offset) then
    3x3/s2/pad1 maxpool over H and W.  x: [B, H, W, C]."""
    y = jax.nn.relu(x * scale + offset)
    y = _pool_axis(y, 1)
    return _pool_axis(y, 2)


def _kernel(x_ref, s_ref, b_ref, o_ref):
    x = x_ref[0]                                       # [H, W, C]
    y = jax.nn.relu(x * s_ref[...] + b_ref[...])
    y = _pool_axis(y, 0)
    y = _pool_axis(y, 1)
    o_ref[0] = y


@functools.partial(jax.custom_vjp, nondiff_argnums=())
def fused_bn_relu_maxpool(x, scale, offset):
    """``maxpool3x3/s2/pad1(relu(x*scale + offset))`` in one fused pass.

    x: ``[B, H, W, C]`` with even H, W; scale/offset: ``[C]`` (fold BN's
    gamma/beta/mean/var into them).  Returns ``[B, H/2, W/2, C]``.
    Kernel on TPU meshes, exact lax twin elsewhere (the flash-kernel
    routing pattern, :func:`horovod_tpu.topology.exec_on_tpu`).
    """
    return _fwd(x, scale, offset)[0]


def _use_kernel(x) -> bool:
    import os
    if os.environ.get("HOROVOD_FUSED_STEM_INTERPRET") == "1":
        return True
    from horovod_tpu.topology import exec_on_tpu
    return exec_on_tpu(x)


def _fwd(x, scale, offset):
    b, h, w, c = x.shape
    if h % 2 or w % 2:
        raise ValueError(f"fused stem pool needs even H, W; got {(h, w)}")
    # Residuals keep the PRE-cast scale/offset so backward cotangents
    # come back in the caller's dtypes (f32 BN coefficients).
    scale0, offset0 = scale, offset
    scale = scale.astype(x.dtype)
    offset = offset.astype(x.dtype)
    if _use_kernel(x):
        import os
        interp = os.environ.get("HOROVOD_FUSED_STEM_INTERPRET") == "1"
        out = pl.pallas_call(
            _kernel,
            grid=(b,),
            in_specs=[
                pl.BlockSpec((1, h, w, c), lambda i: (i, 0, 0, 0)),
                pl.BlockSpec((c,), lambda i: (0,)),
                pl.BlockSpec((c,), lambda i: (0,)),
            ],
            out_specs=pl.BlockSpec((1, h // 2, w // 2, c),
                                   lambda i: (i, 0, 0, 0)),
            out_shape=jax.ShapeDtypeStruct((b, h // 2, w // 2, c),
                                           x.dtype),
            interpret=interp,
        )(x, scale, offset)
    else:
        out = _tail(x, scale, offset)
    return out, (x, scale0, offset0)


def _bwd(res, g):
    # Recompute the cheap elementwise+pool tail with the lax twin and
    # differentiate THAT: x is the conv output (already materialized by
    # the producer), so nothing extra is saved for backward.  The
    # astype lives INSIDE the differentiated function so each cotangent
    # arrives in its primal's dtype.
    x, scale0, offset0 = res

    def tail(x_, s_, b_):
        return _tail(x_, s_.astype(x_.dtype), b_.astype(x_.dtype))

    _, vjp = jax.vjp(tail, x, scale0, offset0)
    return vjp(g)


fused_bn_relu_maxpool.defvjp(_fwd, _bwd)

"""Tensor fusion for the SPMD plane.

Horovod equivalent: the fusion buffer
(``horovod/common/fusion_buffer_manager.{h,cc}``: persistent 64 MB scratch,
``operations.cc:379`` default threshold; ``FUSION_BUFFER_ATOMIC_UNIT=64``,
``common.h:92``) plus ``FuseResponses`` (``controller.cc:551-672``) which
batches small tensors into one collective to amortize latency.

TPU-native redesign: under XLA the *latency* motivation partially disappears
(the compiler fuses and schedules collectives), but launching one big
``psum`` over a flat buffer instead of hundreds of tiny ones still wins on
real meshes — fewer collective launches, full ICI payloads.  Because shapes
are static at trace time, fusion here is *ahead-of-time bucketing* of a
gradient pytree: group leaves by dtype into buckets up to the threshold,
concatenate into one flat vector per bucket, one ``psum`` per bucket,
then split back.  No runtime buffer management is needed — XLA owns memory.

Fusion v2 adds the sharded-update wire format (ZeRO-1, Rajbhandari et al.
SC'20; Xu et al. 2020 automatic weight-update sharding): the same bucketing
walk, but each flat bucket is padded to an axis-size multiple and
**reduce-scattered** (``lax.psum_scatter``) so every rank keeps only its
1/N shard — same ring wire bytes as an allreduce's reduce-scatter phase —
and re-materialized later with ``lax.all_gather`` + unpad/split
(:func:`fused_all_gather`).  :mod:`horovod_tpu.parallel.zero` builds the
sharded optimizer update on top of exactly this pair.
"""

from __future__ import annotations

import dataclasses
import os
import re
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from horovod_tpu import telemetry
from horovod_tpu.utils.logging import get_logger

log = get_logger(__name__)

# Reference default: 64 MB (operations.cc:379); same env knob name.
DEFAULT_FUSION_THRESHOLD = 64 * 1024 * 1024

# Reduce-scatter buckets are additionally CHUNKED at this cap: BENCH_eager
# measured a bandwidth cliff at 64 MB payloads (0.8 -> 0.2 GB/s), so plans
# split any bucket above the cap into several pipeline-friendly chunks.
# 0 disables chunking.
DEFAULT_MAX_BUCKET_BYTES = 32 * 1024 * 1024

_SIZE_SUFFIXES = {
    "": 1, "b": 1,
    "k": 1024, "kb": 1024, "kib": 1024,
    "m": 1024 ** 2, "mb": 1024 ** 2, "mib": 1024 ** 2,
    "g": 1024 ** 3, "gb": 1024 ** 3, "gib": 1024 ** 3,
}

_warned_bad_threshold = False
_warned_bad_cap = False

# Live fusion-threshold provider (adaptive control plane): the native
# runtime registers a callable returning the latest autotuned threshold
# so bucketing follows the tuner instead of freezing the env value at
# import.  None (no provider, or provider returns None) falls back to
# the HOROVOD_FUSION_THRESHOLD env / default path below.
#
# CONTRACT: the provider must return a RANK-AGREED value — the same
# number on every rank at the same point of the (SPMD) Python program.
# Bucketing runs on framework threads at trace time; if two ranks read
# different thresholds they trace DIFFERENT fused programs, which
# desynchronizes the collective streams and hangs the job rather than
# erroring.  ``native.runtime.Runtime`` honors this by serving a value
# latched only inside ``Runtime.sync_tuned_config()`` (a collective),
# never the raw tuner atomic that each rank updates at its own cycle
# tick.
_live_threshold_provider = None


def set_live_threshold_provider(provider) -> None:
    """Register (or clear, with ``None``) the live-threshold source.

    Called by ``native.runtime.Runtime`` on start/stop; anything else
    supplying a dynamic threshold (tests, notebooks) may use it too —
    but every registered provider must honor the rank-agreement
    contract documented on ``_live_threshold_provider``."""
    global _live_threshold_provider
    _live_threshold_provider = provider


def parse_size_bytes(value: str) -> Optional[int]:
    """``"64mb"`` / ``"32MiB"`` / ``"67108864"`` -> bytes, or None when the
    string is not a size.  Decimal multipliers are intentionally absent:
    Horovod's knob has always been binary (64 MB == 2**26)."""
    m = re.fullmatch(r"\s*(\d+(?:\.\d+)?)\s*([a-zA-Z]*)\s*", str(value))
    if not m:
        return None
    mult = _SIZE_SUFFIXES.get(m.group(2).lower())
    if mult is None:
        return None
    return int(float(m.group(1)) * mult)


def fusion_threshold_bytes() -> int:
    """The live fusion bucket limit: the rank-agreed autotuned value when
    a native runtime registered a provider (set_live_threshold_provider)
    and has latched one via ``Runtime.sync_tuned_config()``, else
    ``HOROVOD_FUSION_THRESHOLD`` (bytes, or with a ``kb``/``mb``/``MiB``-style
    binary suffix).  An unparseable env value falls back to the 64 MB
    default with a one-time warning — a typo in an env var must not
    surface as a ``ValueError`` deep inside a jit trace."""
    global _warned_bad_threshold
    if _live_threshold_provider is not None:
        try:
            live = _live_threshold_provider()
        except Exception:
            live = None   # a dying runtime must not break bucketing
        if live is not None and live > 0:
            return int(live)
    v = os.environ.get("HOROVOD_FUSION_THRESHOLD")
    if not v:
        return DEFAULT_FUSION_THRESHOLD
    parsed = parse_size_bytes(v)
    if parsed is None:
        if not _warned_bad_threshold:
            _warned_bad_threshold = True
            log.warning(
                "HOROVOD_FUSION_THRESHOLD=%r is not a byte size (expected "
                "e.g. 67108864, 64mb or 32MiB); using the default %d bytes",
                v, DEFAULT_FUSION_THRESHOLD)
        return DEFAULT_FUSION_THRESHOLD
    return parsed


def max_bucket_bytes() -> int:
    """The reduce-scatter bucket chunking cap from
    ``HOROVOD_MAX_BUCKET_BYTES`` (same size grammar as the fusion
    threshold; ``0`` disables chunking).  Unparseable values fall back to
    the 32 MB default with a one-time warning."""
    global _warned_bad_cap
    v = os.environ.get("HOROVOD_MAX_BUCKET_BYTES")
    if not v:
        return DEFAULT_MAX_BUCKET_BYTES
    parsed = parse_size_bytes(v)
    if parsed is None:
        if not _warned_bad_cap:
            _warned_bad_cap = True
            log.warning(
                "HOROVOD_MAX_BUCKET_BYTES=%r is not a byte size (expected "
                "e.g. 33554432, 32mb or 16MiB); using the default %d bytes",
                v, DEFAULT_MAX_BUCKET_BYTES)
        return DEFAULT_MAX_BUCKET_BYTES
    return parsed


def record_collective_bytes(kind: str, codec: str, nbytes: int,
                            level: Optional[str] = None) -> None:
    """Trace-time wire accounting for SPMD collectives: the LOGICAL payload
    bytes a collective moves per invocation (per rank), labeled by the wire
    codec that produced them.  Like all fusion telemetry this counts
    trace-time decisions — per-step traffic is trace counts x payload — so
    two runs of the same program are directly comparable: the none-codec /
    int8 ratio of ``hvd_collective_bytes_total`` IS the wire compression
    ratio.  ``level`` ("ici"/"dcn") labels the leg of a two-level
    hierarchical collective; flat collectives omit it."""
    if nbytes and telemetry.enabled():
        labels = dict(plane="spmd", kind=kind, codec=codec)
        if level is not None:
            labels["level"] = level
        telemetry.counter(
            "hvd_collective_bytes_total",
            "Logical wire payload bytes of SPMD collectives (trace-time)",
            **labels).inc(int(nbytes))


def _vma_key(leaf):
    """Sorted tuple of mesh axes the (traced) leaf varies over.

    Fusion buckets must be vma-homogeneous: concatenating a TP-sharded
    gradient (varying over 'model') with a replicated one would pvary the
    whole bucket and the replicated leaf could no longer be returned
    through a P() out_spec."""
    try:
        return tuple(sorted(jax.typeof(leaf).vma))
    except AttributeError:
        return ()


def _bucket_leaves(leaves, threshold: int):
    """Group leaf indices into buckets: same dtype + same vma, cumulative
    nbytes under threshold (mirrors the dtype-homogeneous fusion walk with
    look-ahead in ``controller.cc:551-672``; we sort by (dtype, vma)
    instead of looking ahead)."""
    keys = [(str(leaves[i].dtype), _vma_key(leaves[i]))
            for i in range(len(leaves))]
    order = sorted(range(len(leaves)), key=lambda i: (keys[i], i))
    buckets: List[List[int]] = []
    cur: List[int] = []
    cur_bytes = 0
    cur_key = None
    for i in order:
        leaf = leaves[i]
        nbytes = int(np.prod(leaf.shape)) * leaf.dtype.itemsize
        if cur and (keys[i] != cur_key or cur_bytes + nbytes > threshold):
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_key = keys[i]
        cur_bytes += nbytes
    if cur:
        buckets.append(cur)
    return buckets


def _record_buckets(kind: str, tensors, buckets, pad_bytes: int = 0):
    """Trace-time fusion telemetry.  Bucketing happens when the step is
    TRACED (shapes are static under jit), so these count fusion DECISIONS,
    not per-step traffic — per-step wire volume is trace counts x bucket
    bytes."""
    if not telemetry.enabled():
        return
    telemetry.counter(
        "hvd_fusion_requests_total",
        "Fusion walks (trace-time bucketing decisions)", kind=kind).inc()
    telemetry.counter(
        "hvd_fusion_buckets_total",
        "Fusion buckets produced across all fusion walks", kind=kind).inc(
        len(buckets))
    telemetry.counter(
        "hvd_fusion_tensors_total",
        "Tensors routed through the fusion walks", kind=kind).inc(
        len(tensors))
    hist = telemetry.histogram(
        "hvd_fusion_bucket_bytes",
        "Per-bucket payload size produced by the fusion walk",
        bounds=telemetry.DEFAULT_BYTE_BUCKETS)
    for bucket in buckets:
        hist.observe(float(sum(
            int(np.prod(tensors[i].shape)) * tensors[i].dtype.itemsize
            for i in bucket)))
    if pad_bytes:
        telemetry.counter(
            "hvd_fusion_pad_bytes_total",
            "Bytes of axis-size padding added to reduce-scatter buckets "
            "(padding waste)", kind=kind).inc(pad_bytes)


def _record_plan(kind: str, plan: "ReduceScatterPlan") -> None:
    """Plan-based twin of :func:`_record_buckets` for the span wire format."""
    if not telemetry.enabled():
        return
    telemetry.counter(
        "hvd_fusion_requests_total",
        "Fusion walks (trace-time bucketing decisions)", kind=kind).inc()
    telemetry.counter(
        "hvd_fusion_buckets_total",
        "Fusion buckets produced across all fusion walks", kind=kind).inc(
        len(plan.buckets))
    telemetry.counter(
        "hvd_fusion_tensors_total",
        "Tensors routed through the fusion walks", kind=kind).inc(
        plan.n_leaves)
    hist = telemetry.histogram(
        "hvd_fusion_bucket_bytes",
        "Per-bucket payload size produced by the fusion walk",
        bounds=telemetry.DEFAULT_BYTE_BUCKETS)
    for b in range(len(plan.buckets)):
        hist.observe(float(plan.bucket_size(b) *
                           plan.bucket_dtype(b).itemsize))
    pad = plan.total_pad_bytes()
    if pad:
        telemetry.counter(
            "hvd_fusion_pad_bytes_total",
            "Bytes of axis-size padding added to reduce-scatter buckets "
            "(padding waste)", kind=kind).inc(pad)


def fused_psum(tensors: Sequence[jax.Array], axis_name,
               mean: bool = True, threshold: int | None = None,
               prescale_factor: float = 1.0, postscale_factor: float = 1.0):
    """Allreduce a list of (traced) tensors with bucketed fusion.

    Returns reduced tensors in the original order.  ``prescale_factor`` /
    ``postscale_factor`` are applied to the flat bucket around the wire
    reduction (one multiply per bucket, not per leaf) — the fused rendition
    of ``allreduce``'s scaling knobs.
    """
    tensors = list(tensors)
    if not tensors:
        return []
    threshold = fusion_threshold_bytes() if threshold is None else threshold
    buckets = _bucket_leaves(tensors, threshold)
    _record_buckets("psum", tensors, buckets)
    record_collective_bytes("psum", "none", sum(
        int(np.prod(t.shape)) * t.dtype.itemsize for t in tensors))
    reduce = lax.pmean if mean else lax.psum
    out: List = [None] * len(tensors)
    for bucket in buckets:
        if len(bucket) == 1:
            i = bucket[0]
            t = tensors[i]
            if prescale_factor != 1.0:
                t = t * prescale_factor
            r = reduce(t, axis_name)
            if postscale_factor != 1.0:
                r = r * postscale_factor
            out[i] = r
            continue
        # One 1-D reshape per leaf, one concat, one reduce, ONE split at
        # precomputed offsets — K reshapes instead of K dynamic-slice-shaped
        # gathers in the emitted trace.
        sizes = [int(np.prod(tensors[i].shape)) for i in bucket]
        offsets = np.cumsum(sizes[:-1]).tolist()
        flat = jnp.concatenate([tensors[i].reshape(-1) for i in bucket])
        if prescale_factor != 1.0:
            flat = flat * prescale_factor
        red = reduce(flat, axis_name)
        if postscale_factor != 1.0:
            red = red * postscale_factor
        for i, part in zip(bucket, jnp.split(red, offsets)):
            out[i] = part.reshape(tensors[i].shape)
    return out


def fused_pytree_mean(tree, axis_name, threshold: int | None = None):
    """Average a gradient pytree across ``axis_name`` with fusion — the core
    of :class:`horovod_tpu.parallel.data.DistributedOptimizer`'s jit path."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    reduced = fused_psum(leaves, axis_name, mean=True, threshold=threshold)
    return jax.tree_util.tree_unflatten(treedef, reduced)


# ---------------------------------------------------------------------------
# Fusion v2: the reduce-scatter / all-gather pair (the sharded-update wire
# format).  A ring allreduce IS reduce-scatter + all-gather; splitting the
# two phases apart lets the optimizer update run on the 1/N shard in
# between (ZeRO-1) for the same total wire bytes.
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class ReduceScatterPlan:
    """Static (hashable) description of one fusion walk over a fixed leaf
    list, including the per-bucket padding to an axis-size multiple.

    Built once at trace (or setup) time from leaf shapes; the plan is what
    makes ``fused_reduce_scatter`` -> ``fused_all_gather`` a lossless round
    trip, and what :mod:`horovod_tpu.parallel.zero` uses to keep gradient
    shards, parameter shards and optimizer-state shards aligned.

    Bucket membership is expressed as **spans** ``(leaf, start, stop)`` —
    element ranges of the flattened leaf — so one oversized leaf (or one
    oversized multi-leaf bucket) can be CHUNKED across several buckets
    (``HOROVOD_MAX_BUCKET_BYTES``).  ``lowrank`` marks bucket indices the
    requesting wire codec claimed as whole-leaf low-rank buckets
    (:mod:`horovod_tpu.ops.compression`); those are never chunked.
    """
    buckets: Tuple[Tuple[Tuple[int, int, int], ...], ...]  # spans per bucket
    shapes: Tuple[Tuple[int, ...], ...]        # per-leaf shapes
    dtypes: Tuple[str, ...]                    # per-leaf dtype names
    axis_size: int
    lowrank: Tuple[int, ...] = ()              # codec-claimed bucket indices

    # -- static geometry ---------------------------------------------------
    def leaf_size(self, i: int) -> int:
        return int(np.prod(self.shapes[i]))

    def bucket_size(self, b: int) -> int:
        """Unpadded element count of bucket ``b``."""
        return sum(stop - start for _, start, stop in self.buckets[b])

    def padded_size(self, b: int) -> int:
        """Bucket size rounded up to a multiple of ``axis_size``."""
        n, a = self.bucket_size(b), self.axis_size
        return -(-n // a) * a if n else a  # empty bucket still scatters

    def shard_size(self, b: int) -> int:
        return self.padded_size(b) // self.axis_size

    def pad_elems(self, b: int) -> int:
        return self.padded_size(b) - self.bucket_size(b)

    def bucket_dtype(self, b: int):
        return jnp.dtype(self.dtypes[self.buckets[b][0][0]])

    def bucket_leaf_shape(self, b: int) -> Optional[Tuple[int, ...]]:
        """The original leaf shape when bucket ``b`` is exactly one WHOLE
        leaf (the low-rank codec needs the 2-D geometry back), else None."""
        spans = self.buckets[b]
        if len(spans) != 1:
            return None
        i, start, stop = spans[0]
        if start != 0 or stop != self.leaf_size(i):
            return None
        return self.shapes[i]

    @property
    def n_leaves(self) -> int:
        return len(self.shapes)

    def total_pad_bytes(self) -> int:
        return sum(self.pad_elems(b) * self.bucket_dtype(b).itemsize
                   for b in range(len(self.buckets)))

    def total_padded_bytes(self) -> int:
        """Per-rank logical payload of one reduce-scatter (or all-gather)
        pass over every bucket at wire dtype == bucket dtype."""
        return sum(self.padded_size(b) * self.bucket_dtype(b).itemsize
                   for b in range(len(self.buckets)))

    # -- flat-buffer plumbing ---------------------------------------------
    def concat(self, leaves) -> List[jax.Array]:
        """Leaves -> one padded 1-D buffer per bucket (trace-safe)."""
        if len(leaves) != self.n_leaves:
            raise ValueError(f"plan describes {self.n_leaves} leaves, got "
                             f"{len(leaves)}")
        flats = []
        for b, spans in enumerate(self.buckets):
            parts = []
            for i, start, stop in spans:
                flat_leaf = leaves[i].reshape(-1)
                parts.append(flat_leaf if stop - start == self.leaf_size(i)
                             else flat_leaf[start:stop])
            pad = self.pad_elems(b)
            if pad or not parts:
                parts.append(jnp.zeros((pad if parts else self.padded_size(b),),
                                       self.bucket_dtype(b)))
            flats.append(parts[0] if len(parts) == 1
                         else jnp.concatenate(parts))
        return flats

    def split(self, flats) -> List[jax.Array]:
        """Padded per-bucket 1-D buffers -> leaves in ORIGINAL order."""
        if len(flats) != len(self.buckets):
            raise ValueError(f"plan has {len(self.buckets)} buckets, got "
                             f"{len(flats)} buffers")
        pieces: List[List[Tuple[int, jax.Array]]] = [
            [] for _ in range(self.n_leaves)]
        for b, spans in enumerate(self.buckets):
            flat = flats[b][:self.bucket_size(b)]
            sizes = [stop - start for _, start, stop in spans]
            offsets = np.cumsum(sizes[:-1]).tolist()
            for (i, start, _), part in zip(spans, jnp.split(flat, offsets)):
                pieces[i].append((start, part))
        out: List = []
        for i, segs in enumerate(pieces):
            segs = [part for _, part in sorted(segs, key=lambda t: t[0])]
            flat = segs[0] if len(segs) == 1 else jnp.concatenate(segs)
            out.append(flat.reshape(self.shapes[i]))
        return out

    def shard_slice(self, b: int, flat, index):
        """This rank's shard of bucket ``b``'s full padded buffer (``index``
        may be a traced ``lax.axis_index``)."""
        s = self.shard_size(b)
        return lax.dynamic_slice_in_dim(flat, index * s, s, axis=0)


def _resolve_axis_size(axis_name, axis_size: Optional[int]) -> int:
    if axis_size is not None:
        return int(axis_size)
    names = (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)
    return int(np.prod([lax.axis_size(a) for a in names]))


def _chunk_spans(spans, itemsize: int, cap: int):
    """Split one bucket's span list into chunks of at most ``cap`` bytes
    (element-granular: a span larger than the cap is cut mid-leaf)."""
    cap_elems = max(1, cap // itemsize)
    chunks, cur, cur_elems = [], [], 0
    for leaf, start, stop in spans:
        pos = start
        while pos < stop:
            take = min(stop - pos, cap_elems - cur_elems)
            cur.append((leaf, pos, pos + take))
            pos += take
            cur_elems += take
            if cur_elems == cap_elems:
                chunks.append(cur)
                cur, cur_elems = [], 0
    if cur:
        chunks.append(cur)
    return chunks or [list(spans)]


def make_reduce_scatter_plan(leaves, axis_size: int,
                             threshold: int | None = None,
                             codec=None,
                             cap: int | None = None) -> ReduceScatterPlan:
    """Run the fusion bucketing walk over ``leaves`` (arrays or
    ShapeDtypeStructs) and freeze it, with per-bucket padding geometry for
    an ``axis_size``-way reduce-scatter.

    Buckets larger than ``cap`` bytes (``HOROVOD_MAX_BUCKET_BYTES``,
    default 32 MB, 0 disables) are chunked into multiple buckets — the
    64 MB payload cliff in BENCH_eager.json means several medium
    collectives pipeline better than one giant one.  ``codec`` (a
    :class:`horovod_tpu.ops.compression.BucketCodec`-shaped object) may
    claim whole leaves as dedicated low-rank buckets via its
    ``solo_leaf(shape, dtype)`` hook; claimed buckets are exempt from
    chunking and listed in ``plan.lowrank``.
    """
    leaves = list(leaves)
    threshold = fusion_threshold_bytes() if threshold is None else threshold
    cap = max_bucket_bytes() if cap is None else cap
    solo = [i for i, l in enumerate(leaves)
            if codec is not None
            and codec.solo_leaf(tuple(int(d) for d in l.shape),
                                jnp.dtype(l.dtype))]
    rest = [l for i, l in enumerate(leaves) if i not in solo]
    rest_idx = [i for i in range(len(leaves)) if i not in solo]
    walk = _bucket_leaves(rest, threshold)
    span_buckets = [[(rest_idx[j], 0, int(np.prod(leaves[rest_idx[j]].shape)))
                     for j in bucket] for bucket in walk]
    chunked = 0
    if cap:
        out_buckets = []
        for spans in span_buckets:
            itemsize = jnp.dtype(leaves[spans[0][0]].dtype).itemsize
            nbytes = sum((stop - start) * itemsize for _, start, stop in spans)
            if nbytes > cap:
                chunks = _chunk_spans(spans, itemsize, cap)
                if len(chunks) > 1:
                    chunked += 1
                out_buckets.extend(chunks)
            else:
                out_buckets.append(spans)
        span_buckets = out_buckets
    if chunked and telemetry.enabled():
        telemetry.counter(
            "hvd_fusion_chunked_buckets_total",
            "Fusion buckets split because they exceeded "
            "HOROVOD_MAX_BUCKET_BYTES").inc(chunked)
    lowrank = tuple(range(len(span_buckets), len(span_buckets) + len(solo)))
    for i in solo:
        span_buckets.append([(i, 0, int(np.prod(leaves[i].shape)))])
    return ReduceScatterPlan(
        buckets=tuple(tuple(b) for b in span_buckets),
        shapes=tuple(tuple(int(d) for d in l.shape) for l in leaves),
        dtypes=tuple(str(jnp.dtype(l.dtype)) for l in leaves),
        axis_size=int(axis_size),
        lowrank=lowrank)


def fused_reduce_scatter(tensors: Sequence[jax.Array], axis_name,
                         mean: bool = True, threshold: int | None = None,
                         plan: Optional[ReduceScatterPlan] = None,
                         axis_size: Optional[int] = None):
    """Reduce-scatter a list of (traced) tensors with bucketed fusion.

    Each dtype/vma-homogeneous bucket is flattened, padded to an axis-size
    multiple and ``lax.psum_scatter``-ed, so the caller keeps only this
    rank's ``1/axis_size`` shard of each bucket — half of a ring allreduce,
    wire-byte-wise.  Returns ``(shards, plan)``; feed both to
    :func:`fused_all_gather` to re-materialize the full tensors (the other
    half), or run a sharded optimizer update in between
    (:mod:`horovod_tpu.parallel.zero`).

    ``mean=True`` divides by the axis size (applied on the 1/N shard, where
    it is N-times cheaper than on the full buffer).
    """
    tensors = list(tensors)
    if plan is None:
        n = _resolve_axis_size(axis_name, axis_size)
        plan = make_reduce_scatter_plan(tensors, n, threshold)
    if not tensors:
        return [], plan
    _record_plan("reduce_scatter", plan)
    record_collective_bytes("reduce_scatter", "none",
                            plan.total_padded_bytes())
    shards = []
    inv = 1.0 / plan.axis_size
    for b, flat in enumerate(plan.concat(tensors)):
        shard = lax.psum_scatter(flat, axis_name, scatter_dimension=0,
                                 tiled=True)
        if mean:
            shard = shard * jnp.asarray(inv, shard.dtype)
        shards.append(shard)
    return shards, plan


def fused_hierarchical_reduce_scatter(
        tensors: Sequence[jax.Array], ici_axis: str, dcn_axis: str,
        mean: bool = True, threshold: int | None = None,
        plan: Optional[ReduceScatterPlan] = None,
        axis_size: Optional[int] = None):
    """Two-level reduce-scatter: intra-slice ``psum_scatter`` over
    ``ici_axis`` then a ``psum`` of the 1/ici shard over ``dcn_axis``, so
    the DCN leg carries 1/ici_size of every bucket's bytes (the mesh twin
    of ``NCCLHierarchicalAllreduce``'s local-RS + cross-allreduce prefix).

    The plan is built over the ICI axis size only — shards stay
    ici-sharded, replicated over DCN — so the returned ``(shards, plan)``
    pair feeds :func:`fused_all_gather` with ``axis_name=ici_axis`` (an
    intra-slice gather; no DCN traffic on the way back).  That makes this
    a drop-in for :func:`fused_reduce_scatter` in ZeRO-1: optimizer state
    is partitioned 1/ici-way per slice, and only the reduce leg crosses
    hosts.  ``mean=True`` folds the full two-level divide into one
    ``1/(ici*dcn)`` multiply on the shard.
    """
    tensors = list(tensors)
    ici = _resolve_axis_size(ici_axis, axis_size)
    dcn = _resolve_axis_size(dcn_axis, None)
    if plan is None:
        plan = make_reduce_scatter_plan(tensors, ici, threshold)
    if not tensors:
        return [], plan
    _record_plan("hier_reduce_scatter", plan)
    record_collective_bytes("hier_reduce_scatter", "none",
                            plan.total_padded_bytes(), level="ici")
    record_collective_bytes("hier_reduce_scatter", "none",
                            plan.total_padded_bytes() // max(ici, 1),
                            level="dcn")
    shards = []
    inv = 1.0 / (plan.axis_size * dcn)
    for b, flat in enumerate(plan.concat(tensors)):
        shard = lax.psum_scatter(flat, ici_axis, scatter_dimension=0,
                                 tiled=True)
        shard = lax.psum(shard, dcn_axis)
        if mean:
            shard = shard * jnp.asarray(inv, shard.dtype)
        shards.append(shard)
    return shards, plan


def fused_all_gather(shards: Sequence[jax.Array],
                     plan: ReduceScatterPlan, axis_name):
    """Inverse of :func:`fused_reduce_scatter`: all-gather every bucket's
    per-rank shard back to the full padded buffer, strip the padding and
    split back into tensors in the ORIGINAL leaf order."""
    shards = list(shards)
    if len(shards) != len(plan.buckets):
        raise ValueError(f"plan has {len(plan.buckets)} buckets, got "
                         f"{len(shards)} shards")
    record_collective_bytes("all_gather", "none", plan.total_padded_bytes())
    flats = [lax.all_gather(s, axis_name, axis=0, tiled=True)
             for s in shards]
    return plan.split(flats)

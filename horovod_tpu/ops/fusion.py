"""Tensor fusion for the SPMD plane.

Horovod equivalent: the fusion buffer
(``horovod/common/fusion_buffer_manager.{h,cc}``: persistent 64 MB scratch,
``operations.cc:379`` default threshold; ``FUSION_BUFFER_ATOMIC_UNIT=64``,
``common.h:92``) plus ``FuseResponses`` (``controller.cc:551-672``) which
batches small tensors into one collective to amortize latency.

TPU-native redesign: under XLA the *latency* motivation partially disappears
(the compiler fuses and schedules collectives), but launching one big
``psum`` over a flat buffer instead of hundreds of tiny ones still wins on
real meshes — fewer collective launches, full ICI payloads.  Because shapes
are static at trace time, fusion here is *ahead-of-time bucketing* of a
gradient pytree: group leaves by dtype into buckets up to the threshold,
concatenate into one flat vector per bucket, one ``psum`` per bucket,
then split back.  No runtime buffer management is needed — XLA owns memory.

Fusion v2 adds the sharded-update wire format (ZeRO-1, Rajbhandari et al.
SC'20; Xu et al. 2020 automatic weight-update sharding): the same bucketing
walk, but each flat bucket is padded to an axis-size multiple and
**reduce-scattered** (``lax.psum_scatter``) so every rank keeps only its
1/N shard — same ring wire bytes as an allreduce's reduce-scatter phase —
and re-materialized later with ``lax.all_gather`` + unpad/split
(:func:`fused_all_gather`).  :mod:`horovod_tpu.parallel.zero` builds the
sharded optimizer update on top of exactly this pair.
"""

from __future__ import annotations

import dataclasses
import os
import re
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from horovod_tpu import telemetry
from horovod_tpu.utils.logging import get_logger

log = get_logger(__name__)

# Reference default: 64 MB (operations.cc:379); same env knob name.
DEFAULT_FUSION_THRESHOLD = 64 * 1024 * 1024

_SIZE_SUFFIXES = {
    "": 1, "b": 1,
    "k": 1024, "kb": 1024, "kib": 1024,
    "m": 1024 ** 2, "mb": 1024 ** 2, "mib": 1024 ** 2,
    "g": 1024 ** 3, "gb": 1024 ** 3, "gib": 1024 ** 3,
}

_warned_bad_threshold = False


def parse_size_bytes(value: str) -> Optional[int]:
    """``"64mb"`` / ``"32MiB"`` / ``"67108864"`` -> bytes, or None when the
    string is not a size.  Decimal multipliers are intentionally absent:
    Horovod's knob has always been binary (64 MB == 2**26)."""
    m = re.fullmatch(r"\s*(\d+(?:\.\d+)?)\s*([a-zA-Z]*)\s*", str(value))
    if not m:
        return None
    mult = _SIZE_SUFFIXES.get(m.group(2).lower())
    if mult is None:
        return None
    return int(float(m.group(1)) * mult)


def fusion_threshold_bytes() -> int:
    """The fusion bucket limit from ``HOROVOD_FUSION_THRESHOLD`` (bytes, or
    with a ``kb``/``mb``/``MiB``-style binary suffix).  An unparseable value
    falls back to the 64 MB default with a one-time warning — a typo in an
    env var must not surface as a ``ValueError`` deep inside a jit trace."""
    global _warned_bad_threshold
    v = os.environ.get("HOROVOD_FUSION_THRESHOLD")
    if not v:
        return DEFAULT_FUSION_THRESHOLD
    parsed = parse_size_bytes(v)
    if parsed is None:
        if not _warned_bad_threshold:
            _warned_bad_threshold = True
            log.warning(
                "HOROVOD_FUSION_THRESHOLD=%r is not a byte size (expected "
                "e.g. 67108864, 64mb or 32MiB); using the default %d bytes",
                v, DEFAULT_FUSION_THRESHOLD)
        return DEFAULT_FUSION_THRESHOLD
    return parsed


def _vma_key(leaf):
    """Sorted tuple of mesh axes the (traced) leaf varies over.

    Fusion buckets must be vma-homogeneous: concatenating a TP-sharded
    gradient (varying over 'model') with a replicated one would pvary the
    whole bucket and the replicated leaf could no longer be returned
    through a P() out_spec."""
    try:
        return tuple(sorted(jax.typeof(leaf).vma))
    except AttributeError:
        return ()


def _bucket_leaves(leaves, threshold: int):
    """Group leaf indices into buckets: same dtype + same vma, cumulative
    nbytes under threshold (mirrors the dtype-homogeneous fusion walk with
    look-ahead in ``controller.cc:551-672``; we sort by (dtype, vma)
    instead of looking ahead)."""
    keys = [(str(leaves[i].dtype), _vma_key(leaves[i]))
            for i in range(len(leaves))]
    order = sorted(range(len(leaves)), key=lambda i: (keys[i], i))
    buckets: List[List[int]] = []
    cur: List[int] = []
    cur_bytes = 0
    cur_key = None
    for i in order:
        leaf = leaves[i]
        nbytes = int(np.prod(leaf.shape)) * leaf.dtype.itemsize
        if cur and (keys[i] != cur_key or cur_bytes + nbytes > threshold):
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_key = keys[i]
        cur_bytes += nbytes
    if cur:
        buckets.append(cur)
    return buckets


def _record_buckets(kind: str, tensors, buckets, pad_bytes: int = 0):
    """Trace-time fusion telemetry.  Bucketing happens when the step is
    TRACED (shapes are static under jit), so these count fusion DECISIONS,
    not per-step traffic — per-step wire volume is trace counts x bucket
    bytes."""
    if not telemetry.enabled():
        return
    telemetry.counter(
        "hvd_fusion_requests_total",
        "Fusion walks (trace-time bucketing decisions)", kind=kind).inc()
    telemetry.counter(
        "hvd_fusion_buckets_total",
        "Fusion buckets produced across all fusion walks", kind=kind).inc(
        len(buckets))
    telemetry.counter(
        "hvd_fusion_tensors_total",
        "Tensors routed through the fusion walks", kind=kind).inc(
        len(tensors))
    hist = telemetry.histogram(
        "hvd_fusion_bucket_bytes",
        "Per-bucket payload size produced by the fusion walk",
        bounds=telemetry.DEFAULT_BYTE_BUCKETS)
    for bucket in buckets:
        hist.observe(float(sum(
            int(np.prod(tensors[i].shape)) * tensors[i].dtype.itemsize
            for i in bucket)))
    if pad_bytes:
        telemetry.counter(
            "hvd_fusion_pad_bytes_total",
            "Bytes of axis-size padding added to reduce-scatter buckets "
            "(padding waste)", kind=kind).inc(pad_bytes)


def fused_psum(tensors: Sequence[jax.Array], axis_name,
               mean: bool = True, threshold: int | None = None,
               prescale_factor: float = 1.0, postscale_factor: float = 1.0):
    """Allreduce a list of (traced) tensors with bucketed fusion.

    Returns reduced tensors in the original order.  ``prescale_factor`` /
    ``postscale_factor`` are applied to the flat bucket around the wire
    reduction (one multiply per bucket, not per leaf) — the fused rendition
    of ``allreduce``'s scaling knobs.
    """
    tensors = list(tensors)
    if not tensors:
        return []
    threshold = fusion_threshold_bytes() if threshold is None else threshold
    buckets = _bucket_leaves(tensors, threshold)
    _record_buckets("psum", tensors, buckets)
    reduce = lax.pmean if mean else lax.psum
    out: List = [None] * len(tensors)
    for bucket in buckets:
        if len(bucket) == 1:
            i = bucket[0]
            t = tensors[i]
            if prescale_factor != 1.0:
                t = t * prescale_factor
            r = reduce(t, axis_name)
            if postscale_factor != 1.0:
                r = r * postscale_factor
            out[i] = r
            continue
        # One 1-D reshape per leaf, one concat, one reduce, ONE split at
        # precomputed offsets — K reshapes instead of K dynamic-slice-shaped
        # gathers in the emitted trace.
        sizes = [int(np.prod(tensors[i].shape)) for i in bucket]
        offsets = np.cumsum(sizes[:-1]).tolist()
        flat = jnp.concatenate([tensors[i].reshape(-1) for i in bucket])
        if prescale_factor != 1.0:
            flat = flat * prescale_factor
        red = reduce(flat, axis_name)
        if postscale_factor != 1.0:
            red = red * postscale_factor
        for i, part in zip(bucket, jnp.split(red, offsets)):
            out[i] = part.reshape(tensors[i].shape)
    return out


def fused_pytree_mean(tree, axis_name, threshold: int | None = None):
    """Average a gradient pytree across ``axis_name`` with fusion — the core
    of :class:`horovod_tpu.parallel.data.DistributedOptimizer`'s jit path."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    reduced = fused_psum(leaves, axis_name, mean=True, threshold=threshold)
    return jax.tree_util.tree_unflatten(treedef, reduced)


# ---------------------------------------------------------------------------
# Fusion v2: the reduce-scatter / all-gather pair (the sharded-update wire
# format).  A ring allreduce IS reduce-scatter + all-gather; splitting the
# two phases apart lets the optimizer update run on the 1/N shard in
# between (ZeRO-1) for the same total wire bytes.
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class ReduceScatterPlan:
    """Static (hashable) description of one fusion walk over a fixed leaf
    list, including the per-bucket padding to an axis-size multiple.

    Built once at trace (or setup) time from leaf shapes; the plan is what
    makes ``fused_reduce_scatter`` -> ``fused_all_gather`` a lossless round
    trip, and what :mod:`horovod_tpu.parallel.zero` uses to keep gradient
    shards, parameter shards and optimizer-state shards aligned.
    """
    buckets: Tuple[Tuple[int, ...], ...]       # leaf indices per bucket
    shapes: Tuple[Tuple[int, ...], ...]        # per-leaf shapes
    dtypes: Tuple[str, ...]                    # per-leaf dtype names
    axis_size: int

    # -- static geometry ---------------------------------------------------
    def leaf_size(self, i: int) -> int:
        return int(np.prod(self.shapes[i]))

    def bucket_size(self, b: int) -> int:
        """Unpadded element count of bucket ``b``."""
        return sum(self.leaf_size(i) for i in self.buckets[b])

    def padded_size(self, b: int) -> int:
        """Bucket size rounded up to a multiple of ``axis_size``."""
        n, a = self.bucket_size(b), self.axis_size
        return -(-n // a) * a if n else a  # empty bucket still scatters

    def shard_size(self, b: int) -> int:
        return self.padded_size(b) // self.axis_size

    def pad_elems(self, b: int) -> int:
        return self.padded_size(b) - self.bucket_size(b)

    def bucket_dtype(self, b: int):
        return jnp.dtype(self.dtypes[self.buckets[b][0]])

    @property
    def n_leaves(self) -> int:
        return len(self.shapes)

    def total_pad_bytes(self) -> int:
        return sum(self.pad_elems(b) * self.bucket_dtype(b).itemsize
                   for b in range(len(self.buckets)))

    # -- flat-buffer plumbing ---------------------------------------------
    def concat(self, leaves) -> List[jax.Array]:
        """Leaves -> one padded 1-D buffer per bucket (trace-safe)."""
        if len(leaves) != self.n_leaves:
            raise ValueError(f"plan describes {self.n_leaves} leaves, got "
                             f"{len(leaves)}")
        flats = []
        for b, bucket in enumerate(self.buckets):
            parts = [leaves[i].reshape(-1) for i in bucket]
            pad = self.pad_elems(b)
            if pad or not parts:
                parts.append(jnp.zeros((pad if parts else self.padded_size(b),),
                                       self.bucket_dtype(b)))
            flats.append(parts[0] if len(parts) == 1
                         else jnp.concatenate(parts))
        return flats

    def split(self, flats) -> List[jax.Array]:
        """Padded per-bucket 1-D buffers -> leaves in ORIGINAL order."""
        if len(flats) != len(self.buckets):
            raise ValueError(f"plan has {len(self.buckets)} buckets, got "
                             f"{len(flats)} buffers")
        out: List = [None] * self.n_leaves
        for b, bucket in enumerate(self.buckets):
            flat = flats[b][:self.bucket_size(b)]
            sizes = [self.leaf_size(i) for i in bucket]
            offsets = np.cumsum(sizes[:-1]).tolist()
            for i, part in zip(bucket, jnp.split(flat, offsets)):
                out[i] = part.reshape(self.shapes[i])
        return out

    def shard_slice(self, b: int, flat, index):
        """This rank's shard of bucket ``b``'s full padded buffer (``index``
        may be a traced ``lax.axis_index``)."""
        s = self.shard_size(b)
        return lax.dynamic_slice_in_dim(flat, index * s, s, axis=0)


def _resolve_axis_size(axis_name, axis_size: Optional[int]) -> int:
    if axis_size is not None:
        return int(axis_size)
    names = (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)
    return int(np.prod([lax.axis_size(a) for a in names]))


def make_reduce_scatter_plan(leaves, axis_size: int,
                             threshold: int | None = None
                             ) -> ReduceScatterPlan:
    """Run the fusion bucketing walk over ``leaves`` (arrays or
    ShapeDtypeStructs) and freeze it, with per-bucket padding geometry for
    an ``axis_size``-way reduce-scatter."""
    leaves = list(leaves)
    threshold = fusion_threshold_bytes() if threshold is None else threshold
    buckets = _bucket_leaves(leaves, threshold)
    return ReduceScatterPlan(
        buckets=tuple(tuple(b) for b in buckets),
        shapes=tuple(tuple(int(d) for d in l.shape) for l in leaves),
        dtypes=tuple(str(jnp.dtype(l.dtype)) for l in leaves),
        axis_size=int(axis_size))


def fused_reduce_scatter(tensors: Sequence[jax.Array], axis_name,
                         mean: bool = True, threshold: int | None = None,
                         plan: Optional[ReduceScatterPlan] = None,
                         axis_size: Optional[int] = None):
    """Reduce-scatter a list of (traced) tensors with bucketed fusion.

    Each dtype/vma-homogeneous bucket is flattened, padded to an axis-size
    multiple and ``lax.psum_scatter``-ed, so the caller keeps only this
    rank's ``1/axis_size`` shard of each bucket — half of a ring allreduce,
    wire-byte-wise.  Returns ``(shards, plan)``; feed both to
    :func:`fused_all_gather` to re-materialize the full tensors (the other
    half), or run a sharded optimizer update in between
    (:mod:`horovod_tpu.parallel.zero`).

    ``mean=True`` divides by the axis size (applied on the 1/N shard, where
    it is N-times cheaper than on the full buffer).
    """
    tensors = list(tensors)
    if plan is None:
        n = _resolve_axis_size(axis_name, axis_size)
        plan = make_reduce_scatter_plan(tensors, n, threshold)
    if not tensors:
        return [], plan
    _record_buckets("reduce_scatter", tensors, plan.buckets,
                    pad_bytes=plan.total_pad_bytes())
    shards = []
    inv = 1.0 / plan.axis_size
    for b, flat in enumerate(plan.concat(tensors)):
        shard = lax.psum_scatter(flat, axis_name, scatter_dimension=0,
                                 tiled=True)
        if mean:
            shard = shard * jnp.asarray(inv, shard.dtype)
        shards.append(shard)
    return shards, plan


def fused_all_gather(shards: Sequence[jax.Array],
                     plan: ReduceScatterPlan, axis_name):
    """Inverse of :func:`fused_reduce_scatter`: all-gather every bucket's
    per-rank shard back to the full padded buffer, strip the padding and
    split back into tensors in the ORIGINAL leaf order."""
    shards = list(shards)
    if len(shards) != len(plan.buckets):
        raise ValueError(f"plan has {len(plan.buckets)} buckets, got "
                         f"{len(shards)} shards")
    flats = [lax.all_gather(s, axis_name, axis=0, tiled=True)
             for s in shards]
    return plan.split(flats)

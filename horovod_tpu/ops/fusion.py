"""Tensor fusion for the SPMD plane.

Horovod equivalent: the fusion buffer
(``horovod/common/fusion_buffer_manager.{h,cc}``: persistent 64 MB scratch,
``operations.cc:379`` default threshold; ``FUSION_BUFFER_ATOMIC_UNIT=64``,
``common.h:92``) plus ``FuseResponses`` (``controller.cc:551-672``) which
batches small tensors into one collective to amortize latency.

TPU-native redesign: under XLA the *latency* motivation partially disappears
(the compiler fuses and schedules collectives), but launching one big
``psum`` over a flat buffer instead of hundreds of tiny ones still wins on
real meshes — fewer collective launches, full ICI payloads.  Because shapes
are static at trace time, fusion here is *ahead-of-time bucketing* of a
gradient pytree: group leaves by dtype into buckets up to the threshold,
concatenate into one flat vector per bucket, one ``psum`` per bucket,
then split back.  No runtime buffer management is needed — XLA owns memory.
"""

from __future__ import annotations

import os
from typing import List, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from horovod_tpu import telemetry

# Reference default: 64 MB (operations.cc:379); same env knob name.
DEFAULT_FUSION_THRESHOLD = 64 * 1024 * 1024


def fusion_threshold_bytes() -> int:
    v = os.environ.get("HOROVOD_FUSION_THRESHOLD")
    return int(v) if v else DEFAULT_FUSION_THRESHOLD


def _vma_key(leaf):
    """Sorted tuple of mesh axes the (traced) leaf varies over.

    Fusion buckets must be vma-homogeneous: concatenating a TP-sharded
    gradient (varying over 'model') with a replicated one would pvary the
    whole bucket and the replicated leaf could no longer be returned
    through a P() out_spec."""
    try:
        return tuple(sorted(jax.typeof(leaf).vma))
    except AttributeError:
        return ()


def _bucket_leaves(leaves, threshold: int):
    """Group leaf indices into buckets: same dtype + same vma, cumulative
    nbytes under threshold (mirrors the dtype-homogeneous fusion walk with
    look-ahead in ``controller.cc:551-672``; we sort by (dtype, vma)
    instead of looking ahead)."""
    keys = [(str(leaves[i].dtype), _vma_key(leaves[i]))
            for i in range(len(leaves))]
    order = sorted(range(len(leaves)), key=lambda i: (keys[i], i))
    buckets: List[List[int]] = []
    cur: List[int] = []
    cur_bytes = 0
    cur_key = None
    for i in order:
        leaf = leaves[i]
        nbytes = int(np.prod(leaf.shape)) * leaf.dtype.itemsize
        if cur and (keys[i] != cur_key or cur_bytes + nbytes > threshold):
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_key = keys[i]
        cur_bytes += nbytes
    if cur:
        buckets.append(cur)
    return buckets


def fused_psum(tensors: Sequence[jax.Array], axis_name: str,
               mean: bool = True, threshold: int | None = None):
    """Allreduce a list of (traced) tensors with bucketed fusion.

    Returns reduced tensors in the original order.
    """
    tensors = list(tensors)
    if not tensors:
        return []
    threshold = fusion_threshold_bytes() if threshold is None else threshold
    buckets = _bucket_leaves(tensors, threshold)
    if telemetry.enabled():
        # Bucketing happens at TRACE time (shapes are static under jit),
        # so these count fusion DECISIONS, not per-step traffic — the
        # per-step wire volume is trace counts x bucket bytes.
        telemetry.counter(
            "hvd_fusion_requests_total",
            "fused_psum calls (trace-time bucketing decisions)").inc()
        telemetry.counter(
            "hvd_fusion_buckets_total",
            "Fusion buckets produced across all fused_psum calls").inc(
            len(buckets))
        telemetry.counter(
            "hvd_fusion_tensors_total",
            "Tensors routed through fused_psum").inc(len(tensors))
        hist = telemetry.histogram(
            "hvd_fusion_bucket_bytes",
            "Per-bucket payload size produced by the fusion walk",
            bounds=telemetry.DEFAULT_BYTE_BUCKETS)
        for bucket in buckets:
            hist.observe(float(sum(
                int(np.prod(tensors[i].shape)) * tensors[i].dtype.itemsize
                for i in bucket)))
    out: List = [None] * len(tensors)
    for bucket in buckets:
        if len(bucket) == 1:
            i = bucket[0]
            r = lax.pmean(tensors[i], axis_name) if mean \
                else lax.psum(tensors[i], axis_name)
            out[i] = r
            continue
        flat = jnp.concatenate([tensors[i].reshape(-1) for i in bucket])
        red = lax.pmean(flat, axis_name) if mean else lax.psum(flat, axis_name)
        off = 0
        for i in bucket:
            n = int(np.prod(tensors[i].shape))
            out[i] = red[off:off + n].reshape(tensors[i].shape)
            off += n
    return out


def fused_pytree_mean(tree, axis_name: str, threshold: int | None = None):
    """Average a gradient pytree across ``axis_name`` with fusion — the core
    of :class:`horovod_tpu.parallel.data.DistributedOptimizer`'s jit path."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    reduced = fused_psum(leaves, axis_name, mean=True, threshold=threshold)
    return jax.tree_util.tree_unflatten(treedef, reduced)

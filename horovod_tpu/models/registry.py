"""Name -> model-constructor registry, so the benchmark scripts can select a
model by flag exactly like the reference's ``--model`` argument
(``examples/tensorflow2_synthetic_benchmark.py:18`` resolves any
``tf.keras.applications`` attribute by name)."""

from __future__ import annotations

from typing import Callable, Dict

_REGISTRY: Dict[str, Callable] = {}


def register(name: str, ctor: Callable) -> None:
    _REGISTRY[name.lower()] = ctor


def get_model(name: str, **kwargs):
    try:
        ctor = _REGISTRY[name.lower()]
    except KeyError:
        raise ValueError(
            f"unknown model {name!r}; available: {sorted(_REGISTRY)}")
    return ctor(**kwargs)


def list_models():
    return sorted(_REGISTRY)


def _register_defaults():
    from horovod_tpu.models import inception, resnet, vgg
    register("resnet18", resnet.ResNet18)
    register("resnet34", resnet.ResNet34)
    register("resnet50", resnet.ResNet50)
    register("resnet101", resnet.ResNet101)
    register("resnet152", resnet.ResNet152)
    register("vgg11", vgg.VGG11)
    register("vgg13", vgg.VGG13)
    register("vgg16", vgg.VGG16)
    register("vgg19", vgg.VGG19)
    register("inception3", inception.InceptionV3)
    register("inceptionv3", inception.InceptionV3)


_register_defaults()

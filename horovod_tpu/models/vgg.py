"""VGG in flax, TPU-first.

VGG-16 is one of the reference's three headline scaling models (68%
scaling efficiency on 512 GPUs, reference ``README.rst:75``,
``docs/benchmarks.rst:14``; benchmarked via ``tf_cnn_benchmarks`` and
selectable by ``--model`` in ``examples/tensorflow2_synthetic_benchmark.py:24-30``).

Design notes (same conventions as :mod:`horovod_tpu.models.resnet`):

* NHWC, bfloat16 compute / float32 params — conv stacks feed the MXU.
* The batch-normalized variant (VGG-BN, as in ``torchvision.models.vgg16_bn``):
  the plain 1989-style network needs careful init to train at all, BN makes
  it robust and gives the harness its ``batch_stats`` collection like every
  other model here.
* The classifier head follows modern practice (global average pool + one
  dense layer) instead of the original 224-locked 25088->4096->4096 FC
  stack: it keeps the network shape-polymorphic in image size the way the
  rest of the zoo is, and the conv stack — where >99% of the FLOPs live —
  is exactly VGG.  Set ``classic_head=True`` for the original FC head
  (fp32-heavy, 224x224 only).
* VGG is intentionally kept *conv-dominated*: it is the memory-bandwidth
  stress model of the trio (large activations, no residual reuse), which is
  why the reference's scaling efficiency drops to 68% on it — gradient
  volume is ~550 MB/step.
"""

from __future__ import annotations

import functools
from typing import Any, Optional, Sequence

import flax.linen as nn
import jax.numpy as jnp

# Stage configs: number of 3x3 convs per stage x output channels.
_CFGS = {
    "vgg11": ((1, 64), (1, 128), (2, 256), (2, 512), (2, 512)),
    "vgg13": ((2, 64), (2, 128), (2, 256), (2, 512), (2, 512)),
    "vgg16": ((2, 64), (2, 128), (3, 256), (3, 512), (3, 512)),
    "vgg19": ((2, 64), (2, 128), (4, 256), (4, 512), (4, 512)),
}


class VGG(nn.Module):
    """VGG-BN over NHWC inputs."""

    stage_sizes: Sequence          # ((n_convs, channels), ...)
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    axis_name: Optional[str] = None   # sync-BN across replicas if set
    classic_head: bool = False        # original 4096-4096 FC classifier

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(nn.Conv, kernel_size=(3, 3), use_bias=False,
                                 dtype=self.dtype, param_dtype=jnp.float32)
        norm = functools.partial(
            nn.BatchNorm, use_running_average=not train, momentum=0.9,
            epsilon=1e-5, dtype=self.dtype, param_dtype=jnp.float32,
            axis_name=self.axis_name if train else None)

        x = x.astype(self.dtype)
        for n_convs, channels in self.stage_sizes:
            for _ in range(n_convs):
                x = conv(channels)(x)
                x = norm()(x)
                x = nn.relu(x)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        if self.classic_head:
            x = x.reshape((x.shape[0], -1))
            for _ in range(2):
                x = nn.Dense(4096, dtype=self.dtype,
                             param_dtype=jnp.float32)(x)
                x = nn.relu(x)
        else:
            x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype,
                     param_dtype=jnp.float32, name="head")(x)
        return x.astype(jnp.float32)


VGG11 = functools.partial(VGG, stage_sizes=_CFGS["vgg11"])
VGG13 = functools.partial(VGG, stage_sizes=_CFGS["vgg13"])
VGG16 = functools.partial(VGG, stage_sizes=_CFGS["vgg16"])
VGG19 = functools.partial(VGG, stage_sizes=_CFGS["vgg19"])

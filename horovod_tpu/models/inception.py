"""Inception V3 in flax, TPU-first.

Inception V3 is one of the reference's three headline scaling models (90%
scaling efficiency on 512 GPUs, reference ``README.rst:75``,
``docs/benchmarks.rst:13-14``; selectable in the synthetic benchmark like
every ``tf.keras.applications`` model,
``examples/tensorflow2_synthetic_benchmark.py:24-30``).

Architecture follows Szegedy et al. 2015 ("Rethinking the Inception
Architecture"): stem -> 3x InceptionA -> reduction B -> 4x InceptionC
(factorized 7x7) -> reduction D -> 2x InceptionE -> global pool -> dense.
The auxiliary classifier is omitted (inference-irrelevant and typically
disabled in benchmark harnesses).

TPU design notes (same conventions as :mod:`horovod_tpu.models.resnet`):

* NHWC, bfloat16 compute / float32 params+stats — every conv is
  conv+BN+relu, which XLA fuses into single MXU-feeding kernels.
* All branches of a block are independent convs over the same input; XLA
  schedules them back-to-back on the MXU and fuses each one's BN/relu —
  no manual branch fusion needed.
* Shape-polymorphic in image size (canonical 299x299; any size that
  survives the stem's three stride-2 reductions works, e.g. 224).
* 1x1 convs dominate the op count: they are pure matmuls on the MXU, the
  best-case op for TPUs — which is why Inception's scaling efficiency tops
  the reference's table (tiny activations, compute-dense).
"""

from __future__ import annotations

import functools
from typing import Any, Optional, Tuple

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class ConvBN(nn.Module):
    """conv -> BN -> relu, the universal Inception building unit."""

    features: int
    kernel: Tuple[int, int] = (1, 1)
    strides: Tuple[int, int] = (1, 1)
    padding: Any = "SAME"
    dtype: Any = jnp.bfloat16
    axis_name: Optional[str] = None
    train: bool = True

    @nn.compact
    def __call__(self, x):
        x = nn.Conv(self.features, self.kernel, self.strides,
                    padding=self.padding, use_bias=False, dtype=self.dtype,
                    param_dtype=jnp.float32)(x)
        x = nn.BatchNorm(use_running_average=not self.train, momentum=0.9,
                         epsilon=1e-3, dtype=self.dtype,
                         param_dtype=jnp.float32,
                         axis_name=self.axis_name if self.train else None)(x)
        return nn.relu(x)


def _avg_pool_same(x):
    return nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")


class InceptionA(nn.Module):
    """35x35 block: 1x1 / 5x5 / double-3x3 / pool branches."""

    pool_features: int
    conv: ModuleDef

    @nn.compact
    def __call__(self, x):
        b1 = self.conv(64)(x)
        b2 = self.conv(48)(x)
        b2 = self.conv(64, kernel=(5, 5))(b2)
        b3 = self.conv(64)(x)
        b3 = self.conv(96, kernel=(3, 3))(b3)
        b3 = self.conv(96, kernel=(3, 3))(b3)
        b4 = self.conv(self.pool_features)(_avg_pool_same(x))
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class InceptionB(nn.Module):
    """Grid reduction 35x35 -> 17x17."""

    conv: ModuleDef

    @nn.compact
    def __call__(self, x):
        b1 = self.conv(384, kernel=(3, 3), strides=(2, 2),
                       padding="VALID")(x)
        b2 = self.conv(64)(x)
        b2 = self.conv(96, kernel=(3, 3))(b2)
        b2 = self.conv(96, kernel=(3, 3), strides=(2, 2),
                       padding="VALID")(b2)
        b3 = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        return jnp.concatenate([b1, b2, b3], axis=-1)


class InceptionC(nn.Module):
    """17x17 block with factorized 7x7 (1x7 + 7x1) branches."""

    channels_7x7: int
    conv: ModuleDef

    @nn.compact
    def __call__(self, x):
        c7 = self.channels_7x7
        b1 = self.conv(192)(x)
        b2 = self.conv(c7)(x)
        b2 = self.conv(c7, kernel=(1, 7))(b2)
        b2 = self.conv(192, kernel=(7, 1))(b2)
        b3 = self.conv(c7)(x)
        b3 = self.conv(c7, kernel=(7, 1))(b3)
        b3 = self.conv(c7, kernel=(1, 7))(b3)
        b3 = self.conv(c7, kernel=(7, 1))(b3)
        b3 = self.conv(192, kernel=(1, 7))(b3)
        b4 = self.conv(192)(_avg_pool_same(x))
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class InceptionD(nn.Module):
    """Grid reduction 17x17 -> 8x8."""

    conv: ModuleDef

    @nn.compact
    def __call__(self, x):
        b1 = self.conv(192)(x)
        b1 = self.conv(320, kernel=(3, 3), strides=(2, 2),
                       padding="VALID")(b1)
        b2 = self.conv(192)(x)
        b2 = self.conv(192, kernel=(1, 7))(b2)
        b2 = self.conv(192, kernel=(7, 1))(b2)
        b2 = self.conv(192, kernel=(3, 3), strides=(2, 2),
                       padding="VALID")(b2)
        b3 = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        return jnp.concatenate([b1, b2, b3], axis=-1)


class InceptionE(nn.Module):
    """8x8 block with split 1x3/3x1 branch expansions."""

    conv: ModuleDef

    @nn.compact
    def __call__(self, x):
        b1 = self.conv(320)(x)
        b2 = self.conv(384)(x)
        b2 = jnp.concatenate([self.conv(384, kernel=(1, 3))(b2),
                              self.conv(384, kernel=(3, 1))(b2)], axis=-1)
        b3 = self.conv(448)(x)
        b3 = self.conv(384, kernel=(3, 3))(b3)
        b3 = jnp.concatenate([self.conv(384, kernel=(1, 3))(b3),
                              self.conv(384, kernel=(3, 1))(b3)], axis=-1)
        b4 = self.conv(192)(_avg_pool_same(x))
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class InceptionV3(nn.Module):
    """Inception V3 over NHWC inputs."""

    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    axis_name: Optional[str] = None   # sync-BN across replicas if set

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(ConvBN, dtype=self.dtype,
                                 axis_name=self.axis_name, train=train)
        x = x.astype(self.dtype)
        # Stem: 299 -> 35 spatial (three stride-2 reductions).
        x = conv(32, kernel=(3, 3), strides=(2, 2), padding="VALID")(x)
        x = conv(32, kernel=(3, 3), padding="VALID")(x)
        x = conv(64, kernel=(3, 3))(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        x = conv(80)(x)
        x = conv(192, kernel=(3, 3), padding="VALID")(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")

        x = InceptionA(pool_features=32, conv=conv)(x)
        x = InceptionA(pool_features=64, conv=conv)(x)
        x = InceptionA(pool_features=64, conv=conv)(x)
        x = InceptionB(conv=conv)(x)
        for c7 in (128, 160, 160, 192):
            x = InceptionC(channels_7x7=c7, conv=conv)(x)
        x = InceptionD(conv=conv)(x)
        x = InceptionE(conv=conv)(x)
        x = InceptionE(conv=conv)(x)

        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype,
                     param_dtype=jnp.float32, name="head")(x)
        return x.astype(jnp.float32)

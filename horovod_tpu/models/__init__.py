"""Model zoo for benchmarks and examples.

The reference has no model code of its own — it benchmarks with
``tf.keras.applications`` / ``torchvision`` models pulled in by the example
scripts (reference ``examples/tensorflow2_synthetic_benchmark.py:24-30``,
``examples/pytorch_synthetic_benchmark.py:28-35``).  A standalone TPU
framework cannot lean on those, so the models live here, written
TPU-first (NHWC, bfloat16 matmuls/convs on the MXU, fp32 accumulation).
"""

from horovod_tpu.models.inception import InceptionV3
from horovod_tpu.models.resnet import (
    ResNet,
    ResNet18,
    ResNet34,
    ResNet50,
    ResNet101,
    ResNet152,
)
from horovod_tpu.models.registry import get_model, list_models
from horovod_tpu.models.vgg import VGG, VGG11, VGG13, VGG16, VGG19

"""ResNet v1.5 in flax, TPU-first.

This is the flagship benchmark model (the reference benchmarks ResNet-50 via
``tf.keras.applications.ResNet50`` in
``examples/tensorflow2_synthetic_benchmark.py:30`` and
``torchvision.models.resnet50`` in ``examples/pytorch_synthetic_benchmark.py:33``;
published scaling numbers are ResNet-101, ``docs/benchmarks.rst:26-43``).

TPU design choices
------------------
* **NHWC** layout — what XLA:TPU prefers for convolutions feeding the MXU.
* **bfloat16 compute, float32 parameters/statistics** — MXU-native wire and
  matmul dtype with fp32 accumulation (XLA accumulates bf16 matmuls in fp32
  on TPU by default); no loss-scaling needed, unlike fp16 on GPUs.
* **Static shapes everywhere**; stride-2 convs instead of pooling where v1.5
  specifies, so the whole network is one fusible XLA program.
* BatchNorm keeps **per-replica statistics** (exactly the reference's
  data-parallel semantics: Horovod averages gradients, never BN statistics —
  see reference ``docs/concepts.rst``); pass ``axis_name`` to opt into
  cross-replica (synchronized) BN, which rides a tiny ``psum`` on ICI.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

ModuleDef = Any


def space_to_depth(x, block: int = 2):
    """Pack ``block x block`` spatial patches into channels (NHWC).

    ``[B, H, W, C] -> [B, H/b, W/b, b*b*C]`` with channel index
    ``(dy*b + dx)*C + c``.  This is the TPU input-pipeline layout for the
    ResNet stem: the 7x7/s2 conv on 224x224x3 reads 3-channel pixels —
    3 of 128 vector lanes — while the packed equivalent reads 12-channel
    super-pixels.  Do this ONCE in the input pipeline (it is a pure
    relayout); `conv7_to_s2d_weights` maps stem weights so the packed
    conv computes bit-identical math.
    """
    b, h, w, c = x.shape
    x = x.reshape(b, h // block, block, w // block, block, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(b, h // block, w // block, block * block * c)


def conv7_to_s2d_weights(w7):
    """Exact reparameterization of 7x7/s2 stem weights for the s2d stem.

    Returns ``w4[4, 4, 4*C, O]`` such that ``conv(s2d(x), w4, stride 1,
    pad [(2,1),(2,1)]) == conv(x, w7, stride 2, pad 3)``: output pixel i
    reads original rows ``2i-3 .. 2i+3``, i.e. packed rows ``i-2 .. i+1``
    — a 4x4 window over 2x2-packed super-pixels.  15 of the 64 packed
    taps correspond to no original tap and stay zero (they exist — and
    train — in the packed model; the packed family is a strict superset).
    """
    kh, kw, c, o = w7.shape
    assert (kh, kw) == (7, 7), w7.shape
    w4 = np.zeros((4, 4, 4 * c, o), dtype=np.asarray(w7).dtype)
    for ky in range(7):
        for kx in range(7):
            ku, dy = (ky - 3) // 2 + 2, (ky - 3) % 2
            kv, dx = (kx - 3) // 2 + 2, (kx - 3) % 2
            w4[ku, kv, (dy * 2 + dx) * c:(dy * 2 + dx + 1) * c, :] = \
                np.asarray(w7[ky, kx])
    return w4


def _act(fn, y):
    """Activation tagged for remat policies: under ``remat="lean"`` the
    post-BN/relu tensors are NOT saved for backward — they are recomputed
    elementwise from the (saved) conv outputs, which XLA fuses into the
    consuming backward ops, trading negligible VPU work for one full
    activation write+read of HBM traffic per conv (the step is
    bandwidth-bound, see docs/benchmarks.md)."""
    from jax.ad_checkpoint import checkpoint_name
    return checkpoint_name(fn(y), "act")


class BottleneckBlock(nn.Module):
    """1x1 -> 3x3 -> 1x1 bottleneck with expansion 4 (ResNet-50/101/152)."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = _act(self.act, y)
        # v1.5: the stride lives on the 3x3, not the 1x1.
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = _act(self.act, y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1),
                                 self.strides, name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return _act(self.act, residual + y)


class BasicBlock(nn.Module):
    """3x3 -> 3x3 residual block (ResNet-18/34)."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = _act(self.act, y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1),
                                 self.strides, name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return _act(self.act, residual + y)


class FusedStemNorm(nn.Module):
    """BatchNorm whose APPLY is folded into the fused stem tail
    (``ops/fused_stem.fused_bn_relu_maxpool``): statistics exactly as
    flax's BatchNorm (f32 fast-variance, clip, pmean-synced mean+E[x²]
    over ``axis_name``, 0.9-momentum running update, same param/stat
    names so checkpoints interchange with ``stem="s2d"``), then the
    BN-scale/offset, relu and 3x3/s2 maxpool run as ONE pass.  The apply
    itself computes in x.dtype with f32-folded coefficients (the
    strict-bf16 recipe from the LM work, docs/benchmarks.md)."""

    use_running_average: bool
    axis_name: Optional[str] = None
    momentum: float = 0.9
    epsilon: float = 1e-5

    @nn.compact
    def __call__(self, x):
        from jax import lax as _lax

        from horovod_tpu.ops.fused_stem import fused_bn_relu_maxpool

        c = x.shape[-1]
        ra_mean = self.variable("batch_stats", "mean",
                                lambda: jnp.zeros(c, jnp.float32))
        ra_var = self.variable("batch_stats", "var",
                               lambda: jnp.ones(c, jnp.float32))
        gamma = self.param("scale", nn.initializers.ones, (c,),
                           jnp.float32)
        beta = self.param("bias", nn.initializers.zeros, (c,),
                          jnp.float32)
        if self.use_running_average:
            mean, var = ra_mean.value, ra_var.value
        else:
            xf = x.astype(jnp.float32)
            mean = xf.mean(axis=(0, 1, 2))
            mean2 = (xf * xf).mean(axis=(0, 1, 2))
            if self.axis_name is not None and not self.is_initializing():
                con = _lax.pmean(jnp.concatenate([mean, mean2]),
                                 self.axis_name)
                mean, mean2 = jnp.split(con, 2)
            var = jnp.maximum(mean2 - mean * mean, 0.0)
            if not self.is_initializing():
                m = self.momentum
                ra_mean.value = m * ra_mean.value + (1 - m) * mean
                ra_var.value = m * ra_var.value + (1 - m) * var
        a = gamma * _lax.rsqrt(var + self.epsilon)
        b = beta - mean * a
        return fused_bn_relu_maxpool(x, a, b)


class ResNet(nn.Module):
    """ResNet v1.5 over NHWC inputs."""

    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    axis_name: Optional[str] = None   # set to sync BN stats across replicas
    # "conv7": canonical 7x7/s2 stem on [B,224,224,3].  "s2d": equivalent
    # 4x4/s1 stem on space_to_depth-packed [B,112,112,12] input (exact
    # reparameterization, see conv7_to_s2d_weights) — the TPU-friendly
    # form: 12 input channels instead of 3 fill vector lanes 4x denser.
    stem: str = "conv7"
    # None: save whatever AD saves.  "lean": per-block jax.checkpoint that
    # saves everything EXCEPT post-BN/relu activations (recomputed
    # elementwise in backward, fused — trades VPU flops for HBM traffic).
    # "full": save only block inputs (minimum memory, recompute convs).
    remat: Optional[str] = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(nn.Conv, use_bias=False, dtype=self.dtype,
                                 param_dtype=jnp.float32)
        norm = functools.partial(
            nn.BatchNorm, use_running_average=not train, momentum=0.9,
            epsilon=1e-5, dtype=self.dtype, param_dtype=jnp.float32,
            axis_name=self.axis_name if train else None)

        x = x.astype(self.dtype)
        if self.stem not in ("conv7", "s2d", "s2d_fused"):
            raise ValueError(
                f"stem={self.stem!r}: expected 'conv7', 's2d' or "
                f"'s2d_fused'")
        if self.stem in ("s2d", "s2d_fused"):
            x = conv(self.num_filters, (4, 4), (1, 1),
                     padding=[(2, 1), (2, 1)], name="conv_init")(x)
        else:
            x = conv(self.num_filters, (7, 7), (2, 2),
                     padding=[(3, 3), (3, 3)], name="conv_init")(x)
        if self.stem == "s2d_fused":
            # One fused VMEM pass for BN-apply+relu+maxpool (Pallas on
            # TPU meshes, exact lax twin elsewhere) — checkpoint-
            # compatible with the flax BN above (same param/stat names).
            x = FusedStemNorm(use_running_average=not train,
                              axis_name=self.axis_name if train else None,
                              momentum=0.9, epsilon=1e-5,  # keep in
                              # lockstep with the flax norm partial above
                              name="norm_init")(x)
        else:
            x = norm(name="norm_init")(x)
            x = nn.relu(x)
            x = nn.max_pool(x, (3, 3), strides=(2, 2),
                            padding=((1, 1), (1, 1)))
        block_cls = self.block_cls
        if self.remat is not None:
            if self.remat not in ("lean", "full"):
                raise ValueError(
                    f"remat={self.remat!r}: expected None, 'lean' or 'full'")
            import jax
            # "lean": save anything EXCEPT the tagged post-BN/relu
            # activations (NOT save_any_names_but_these, which saves only
            # named values — i.e. nothing here — and degenerates to full
            # per-block remat).
            policy = (jax.checkpoint_policies
                      .save_anything_except_these_names("act")
                      if self.remat == "lean" else None)
            block_cls = nn.remat(block_cls, policy=policy,
                                 prevent_cse=False)
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = block_cls(self.num_filters * 2 ** i,
                              conv=conv, norm=norm, act=nn.relu,
                              strides=strides)(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype,
                     param_dtype=jnp.float32, name="head")(x)
        return x.astype(jnp.float32)


ResNet18 = functools.partial(ResNet, stage_sizes=[2, 2, 2, 2],
                             block_cls=BasicBlock)
ResNet34 = functools.partial(ResNet, stage_sizes=[3, 4, 6, 3],
                             block_cls=BasicBlock)
ResNet50 = functools.partial(ResNet, stage_sizes=[3, 4, 6, 3],
                             block_cls=BottleneckBlock)
ResNet101 = functools.partial(ResNet, stage_sizes=[3, 4, 23, 3],
                              block_cls=BottleneckBlock)
ResNet152 = functools.partial(ResNet, stage_sizes=[3, 8, 36, 3],
                              block_cls=BottleneckBlock)

"""ResNet v1.5 in flax, TPU-first.

This is the flagship benchmark model (the reference benchmarks ResNet-50 via
``tf.keras.applications.ResNet50`` in
``examples/tensorflow2_synthetic_benchmark.py:30`` and
``torchvision.models.resnet50`` in ``examples/pytorch_synthetic_benchmark.py:33``;
published scaling numbers are ResNet-101, ``docs/benchmarks.rst:26-43``).

TPU design choices
------------------
* **NHWC** layout — what XLA:TPU prefers for convolutions feeding the MXU.
* **bfloat16 compute, float32 parameters/statistics** — MXU-native wire and
  matmul dtype with fp32 accumulation (XLA accumulates bf16 matmuls in fp32
  on TPU by default); no loss-scaling needed, unlike fp16 on GPUs.
* **Static shapes everywhere**; stride-2 convs instead of pooling where v1.5
  specifies, so the whole network is one fusible XLA program.
* BatchNorm keeps **per-replica statistics** (exactly the reference's
  data-parallel semantics: Horovod averages gradients, never BN statistics —
  see reference ``docs/concepts.rst``); pass ``axis_name`` to opt into
  cross-replica (synchronized) BN, which rides a tiny ``psum`` on ICI.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class BottleneckBlock(nn.Module):
    """1x1 -> 3x3 -> 1x1 bottleneck with expansion 4 (ResNet-50/101/152)."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        # v1.5: the stride lives on the 3x3, not the 1x1.
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1),
                                 self.strides, name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class BasicBlock(nn.Module):
    """3x3 -> 3x3 residual block (ResNet-18/34)."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1),
                                 self.strides, name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class ResNet(nn.Module):
    """ResNet v1.5 over NHWC inputs."""

    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    axis_name: Optional[str] = None   # set to sync BN stats across replicas

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(nn.Conv, use_bias=False, dtype=self.dtype,
                                 param_dtype=jnp.float32)
        norm = functools.partial(
            nn.BatchNorm, use_running_average=not train, momentum=0.9,
            epsilon=1e-5, dtype=self.dtype, param_dtype=jnp.float32,
            axis_name=self.axis_name if train else None)

        x = x.astype(self.dtype)
        x = conv(self.num_filters, (7, 7), (2, 2),
                 padding=[(3, 3), (3, 3)], name="conv_init")(x)
        x = norm(name="norm_init")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(self.num_filters * 2 ** i,
                                   conv=conv, norm=norm, act=nn.relu,
                                   strides=strides)(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype,
                     param_dtype=jnp.float32, name="head")(x)
        return x.astype(jnp.float32)


ResNet18 = functools.partial(ResNet, stage_sizes=[2, 2, 2, 2],
                             block_cls=BasicBlock)
ResNet34 = functools.partial(ResNet, stage_sizes=[3, 4, 6, 3],
                             block_cls=BasicBlock)
ResNet50 = functools.partial(ResNet, stage_sizes=[3, 4, 6, 3],
                             block_cls=BottleneckBlock)
ResNet101 = functools.partial(ResNet, stage_sizes=[3, 4, 23, 3],
                              block_cls=BottleneckBlock)
ResNet152 = functools.partial(ResNet, stage_sizes=[3, 8, 36, 3],
                              block_cls=BottleneckBlock)

"""Decoder-only Transformer LM with composable data / tensor / sequence
parallelism — the long-context flagship.

No reference equivalent (Horovod ships no models; SURVEY §2.5/§5.7 shows no
TP/SP anywhere) — this model exists to exercise the framework's mesh axes
the way its CNN benchmark exercises DP.  Written functionally (explicit
param pytree, manual-SPMD forward) so it drops straight into ``shard_map``:

* data axis   — batch sharded, gradients averaged (fused pmean)
* model axis  — Megatron-style TP: qkv/up-proj column-parallel, out/down
  row-parallel, boundaries via :mod:`horovod_tpu.parallel.tensor`
* seq axis    — ring attention over contiguous sequence chunks
  (:mod:`horovod_tpu.parallel.sequence`)

bf16 matmuls / fp32 params+softmax, MXU-friendly dims.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from horovod_tpu.ops.flash_attention import flash_attention
from horovod_tpu.parallel import sequence as seq_mod
from horovod_tpu.parallel import tensor as tp


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_heads: int = 8
    n_layers: int = 4
    d_ff: int = 2048
    max_seq: int = 2048
    dtype: object = jnp.bfloat16

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


def init_params(rng, cfg: TransformerConfig):
    """GLOBAL-shape parameters; shard with :func:`param_specs` +
    ``jax.device_put`` before use."""
    keys = jax.random.split(rng, 2 + cfg.n_layers)
    d, f, v = cfg.d_model, cfg.d_ff, cfg.vocab_size

    def dense(key, shape, scale=None):
        scale = scale if scale is not None else (shape[0] ** -0.5)
        return (jax.random.normal(key, shape, jnp.float32) * scale)

    layers = []
    for i in range(cfg.n_layers):
        k = jax.random.split(keys[2 + i], 6)
        layers.append({
            "ln1_scale": jnp.ones((d,), jnp.float32),
            "ln2_scale": jnp.ones((d,), jnp.float32),
            "wq": dense(k[0], (d, d)),
            "wk": dense(k[1], (d, d)),
            "wv": dense(k[2], (d, d)),
            "wo": dense(k[3], (d, d)),
            "w1": dense(k[4], (d, f)),
            "w2": dense(k[5], (f, d)),
        })
    return {
        "embed": dense(keys[0], (v, d), scale=0.02),
        "pos": dense(keys[1], (cfg.max_seq, d), scale=0.02),
        "ln_f_scale": jnp.ones((d,), jnp.float32),
        "layers": layers,
    }


def param_specs(cfg: TransformerConfig, model_axis: Optional[str]):
    """PartitionSpec tree matching :func:`init_params` output: Megatron TP
    sharding over ``model_axis`` (column-parallel outputs, row-parallel
    inputs), everything else replicated."""
    m = model_axis
    col = P(None, m)     # split output dim
    row = P(m, None)     # split input dim
    layer = {
        "ln1_scale": P(), "ln2_scale": P(),
        "wq": col, "wk": col, "wv": col, "wo": row,
        "w1": col, "w2": row,
    }
    return {
        "embed": P(),
        "pos": P(),
        "ln_f_scale": P(),
        "layers": [dict(layer) for _ in range(cfg.n_layers)],
    }


def _rmsnorm(x, scale):
    # Stats in f32; output in the INPUT dtype.  The scale param is f32,
    # and without the cast it silently promoted every rmsnorm output —
    # and therefore every qkv/mlp matmul INPUT — to f32: measured 63.5%
    # -> 72.2% MFU on the d3584/L6 LM config from this one cast (r4).
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return ((x * jax.lax.rsqrt(var + 1e-6)).astype(x.dtype) *
            scale.astype(x.dtype))


def _mlp_block(x, layer, dt, model_axis):
    """rmsnorm -> gelu MLP -> row-parallel psum -> residual (shared by the
    training forward and the KV-cache decode so the two cannot drift)."""
    h = _rmsnorm(x, layer["ln2_scale"])
    hi = tp.region_input(h, model_axis) if model_axis else h
    u = jax.nn.gelu(hi @ layer["w1"].astype(dt))
    dn = u @ layer["w2"].astype(dt)
    if model_axis:
        dn = lax.psum(dn, model_axis)
    return x + dn


def _qkv_proj(x, layer, dt, model_axis, head_dim):
    """rmsnorm -> q/k/v projections -> head split (shared by forward,
    decode_step and forward_pipelined so the projection math cannot
    drift).  Returns q, k, v with a trailing [heads, head_dim] split."""
    h = _rmsnorm(x, layer["ln1_scale"])
    hi = tp.region_input(h, model_axis) if model_axis else h
    q = hi @ layer["wq"].astype(dt)
    k = hi @ layer["wk"].astype(dt)
    v = hi @ layer["wv"].astype(dt)
    dh = q.shape[-1]
    split = q.shape[:-1] + (dh // head_dim, head_dim)
    return q.reshape(split), k.reshape(split), v.reshape(split), dh


def _attn_out(o_flat, x, layer, dt, model_axis):
    """Output projection (row-parallel psum under TP) + residual."""
    o = o_flat @ layer["wo"].astype(dt)
    if model_axis:
        o = lax.psum(o, model_axis)
    return x + o


_flash_declined_shapes: set = set()


def _flash_profitable(t: int) -> bool:
    """``attention="auto"``'s flash-vs-lax decision, made at TRACE time
    from the (static) sequence length.  With the kernel's auto block
    sizes (r3 sweep, docs/kernels.md table): measured fwd-only PARITY at
    T=1024 and measured wins from T=2048 up (fwd-only and fwd+bwd), so
    1024 is the safe default threshold — at worst a tie; override with
    HOROVOD_FLASH_AUTO_MIN_T.  Auto also refuses lengths the compiled
    kernel cannot tile (indivisible by the 128-lane block) and falls
    back to the lax path — ``auto`` NEVER raises on shape; only an
    explicit ``attention="flash"`` may (the user asked for the kernel).
    """
    import os
    min_t = int(os.environ.get("HOROVOD_FLASH_AUTO_MIN_T", "1024"))
    if t >= min_t and t % 128 != 0:
        if t not in _flash_declined_shapes:   # one-time per length
            _flash_declined_shapes.add(t)
            import logging
            logging.getLogger("horovod_tpu").debug(
                "attention='auto': T=%d is not divisible by 128; using "
                "the lax attention path (pad the sequence to enable the "
                "flash kernel)", t)
        return False
    return t >= min_t


def _logits_head(x, params, dt):
    """Final rmsnorm + tied-embedding projection (shared fwd/decode)."""
    x = _rmsnorm(x, params["ln_f_scale"])
    return (x @ params["embed"].T.astype(dt)).astype(jnp.float32)


def _remat_wrap(body, remat: str):
    """Wrap a per-layer block in ``jax.checkpoint`` per the ``remat``
    policy — the HBM-for-FLOPs trade that makes compute-bound LM configs
    fit (docs/benchmarks.md):

    * ``"none"``  — save every intermediate (XLA default).
    * ``"dots"``  — save matmul outputs only, recompute elementwise
      (``checkpoint_dots``): the usual sweet spot, cheap recompute.
    * ``"full"``  — save only layer inputs, recompute the whole block in
      the backward: O(L) fewer activation bytes, ~1.3x fwd FLOPs.
    """
    if remat == "none":
        return body
    if remat == "dots":
        return jax.checkpoint(
            body, policy=jax.checkpoint_policies.checkpoint_dots)
    if remat == "full":
        return jax.checkpoint(body)
    raise ValueError(f"remat={remat!r}: expected 'none', 'dots' or 'full'")


def forward(params, tokens, cfg: TransformerConfig,
            model_axis: Optional[str] = None,
            seq_axis: Optional[str] = None,
            attention: str = "ring",
            segment_ids=None, remat: str = "none"):
    """tokens: [B, T_local] int32 -> logits [B, T_local, vocab] fp32.

    Inside shard_map, weight leaves arrive as LOCAL shards (per
    :func:`param_specs`); outside (single device) they are global and the
    axis args must be None.

    ``segment_ids`` ([B, T] int32, sequence packing) is supported on
    every attention route; under a ``seq_axis`` pass this shard's slice
    (sharded exactly like ``tokens``) — ring attention rotates the
    K-side ids with the K/V blocks, Ulysses all-gathers them (int32 per
    token) after its head scatter.
    """
    dt = cfg.dtype
    t_local = tokens.shape[1]
    pos_offset = (lax.axis_index(seq_axis) * t_local) if seq_axis else 0
    x = (params["embed"][tokens] +
         lax.dynamic_slice_in_dim(params["pos"], pos_offset, t_local,
                                  axis=0)[None]).astype(dt)

    def layer_block(x, layer, segment_ids):
        # --- attention block ---
        q, k, v, dh = _qkv_proj(x, layer, dt, model_axis, cfg.head_dim)
        b, t = q.shape[:2]
        if seq_axis is not None:
            if attention == "ring_flash" or (attention == "auto" and
                                             _flash_profitable(t)):
                # Ring attention with the flash kernel as the per-step
                # block math: auto upgrades when the LOCAL chunk length
                # clears the kernel's measured crossover.
                o = seq_mod.ring_flash_attention(
                    q, k, v, seq_axis, True, None, None, segment_ids)
            elif attention in ("ring", "auto"):
                o = seq_mod.ring_attention(q, k, v, seq_axis, causal=True,
                                           segment_ids=segment_ids)
            elif attention == "ulysses":
                o = seq_mod.ulysses_attention(q, k, v, seq_axis, causal=True,
                                              segment_ids=segment_ids)
            else:
                # The single-device flash kernel route makes no sense
                # under a sequence axis; K/V blocks arrive over ICI and
                # the blockwise math lives in ring[_flash]_attention.
                # Never silently substitute a different algorithm.
                raise ValueError(
                    f"attention={attention!r} is not available with a "
                    f"sequence axis; choose 'ring', 'ring_flash' or "
                    f"'ulysses'")
        elif attention in ("flash", "ring_flash") or (
                attention == "auto" and _flash_profitable(t)):
            # Pallas flash kernel (ops/flash_attention.py): same exact
            # math blockwise in VMEM; requires T divisible by its blocks.
            # 'ring_flash' without a seq axis degenerates to exactly
            # this kernel (a 1-ring's only step is the diagonal one) —
            # the user still measures the algorithm they selected.
            o = flash_attention(q, k, v, True, segment_ids=segment_ids)
        else:
            o = seq_mod.local_attention(q, k, v, causal=True,
                                        segment_ids=segment_ids)
        x = _attn_out(o.reshape(b, t, dh), x, layer, dt, model_axis)
        return _mlp_block(x, layer, dt, model_axis)

    layer_block = _remat_wrap(layer_block, remat)
    for layer in params["layers"]:
        x = layer_block(x, layer, segment_ids)

    return _logits_head(x, params, dt)


def xent(logits, labels):
    """Mean next-token cross-entropy (the one loss formula — shared by
    the plain and pipelined training steps and the oracle tests)."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    return -jnp.mean(ll)


def loss_fn(params, tokens, labels, cfg: TransformerConfig,
            model_axis=None, seq_axis=None, attention="ring",
            segment_ids=None, remat="none"):
    """Mean next-token cross-entropy over the LOCAL shard (callers pmean
    over data/seq axes)."""
    return xent(forward(params, tokens, cfg, model_axis, seq_axis,
                        attention, segment_ids, remat), labels)


def make_train_step(cfg: TransformerConfig, optimizer, mesh,
                    data_axis: str = "data",
                    model_axis: Optional[str] = None,
                    seq_axis: Optional[str] = None,
                    attention: str = "ring",
                    donate: bool = True,
                    packed: bool = False,
                    remat: str = "none",
                    steps_per_call: int = 1,
                    shard_optimizer: bool = False,
                    compression=None):
    """Jitted SPMD training step over dp x tp x sp.

    Returns ``step(params, opt_state, tokens, labels) ->
    (params, opt_state, loss)`` plus the param spec tree (for placing
    params with ``jax.device_put``).  ``packed=True`` adds a trailing
    ``segment_ids`` argument ([B, T] int32, sharded like tokens) so
    sequence packing reaches the jitted step on every attention route,
    including the sequence-parallel ones (see :func:`forward`).

    ``remat`` selects the per-layer rematerialization policy (see
    :func:`_remat_wrap`); ``steps_per_call > 1`` runs that many steps
    inside one compiled program via ``lax.scan`` on the SAME batch —
    the benchmark's dispatch-amortization shape (the ResNet harness's
    rationale at ``benchmark.make_train_step``; not for real training,
    which wants a fresh batch per step).

    ``shard_optimizer=True`` runs the ZeRO-1 sharded update
    (:mod:`horovod_tpu.parallel.zero`): reduce-scatter gradients over the
    data axis, optimizer step on this rank's 1/N flat shard, all-gather
    the updates.  Pure data parallelism only (params must be replicated,
    so ``model_axis``/``seq_axis`` must be ``None``).  The returned step
    additionally carries ``step.init`` (build the sharded-layout state
    from params) and ``step.optimizer`` (the ``ShardedOptimizer``).

    ``compression`` selects the gradient wire codec (name string, codec
    instance, or ``None`` → ``HOROVOD_COMPRESSION``; see
    :func:`horovod_tpu.ops.compression.resolve_codec`).  It rides the
    ZeRO reduce-scatter/all-gather wire, so a non-``none`` codec
    requires ``shard_optimizer=True``.
    """
    from horovod_tpu.ops.fusion import fused_pytree_mean

    specs = param_specs(cfg, model_axis)
    grad_axes = tuple(a for a in (data_axis, seq_axis) if a)

    from horovod_tpu.ops import compression as compression_mod
    codec = compression_mod.resolve_codec(compression)

    zopt = None
    if shard_optimizer:
        if model_axis or seq_axis:
            raise NotImplementedError(
                "shard_optimizer=True composes with pure data parallelism "
                "only (ZeRO-1 slices replicated params); got "
                f"model_axis={model_axis!r}, seq_axis={seq_axis!r}")
        from horovod_tpu.parallel import zero
        zopt = zero.sharded_optimizer(
            optimizer, data_axis, axis_size=int(mesh.shape[data_axis]),
            compression=codec)
    elif not isinstance(codec, compression_mod.NoneCodec):
        raise NotImplementedError(
            f"compression={codec.name!r} rides the ZeRO reduce-scatter "
            f"wire; pass shard_optimizer=True (the plain path's fused "
            f"pmean has no per-bucket wire to compress)")

    def _one_step(params, opt_state, tokens, labels, segment_ids=None):
        from horovod_tpu import resilience
        loss, grads = jax.value_and_grad(loss_fn)(
            params, tokens, labels, cfg, model_axis, seq_axis, attention,
            segment_ids, remat)

        def do_update():
            if zopt is not None:
                # ZeRO-1: the mean happens on the reduce-scattered 1/N
                # shard inside the sharded update — no separate fused
                # pmean pass.
                updates, new_opt = zopt.update(grads, opt_state, params)
            else:
                # DP gradient averaging (fused psum) over data (+seq)
                # axes; TP/f-op already settled the model axis.
                g = fused_pytree_mean(grads, grad_axes)
                updates, new_opt = optimizer.update(g, opt_state, params)
            new_params = jax.tree_util.tree_map(lambda p, u: p + u,
                                                params, updates)
            return new_params, new_opt

        (new_params, new_opt), mean_loss = resilience.apply_step_guard(
            do_update, loss=loss, grads=grads,
            old_state=(params, opt_state), axes=grad_axes,
            # agreement must also settle the TP axis: model-sharded
            # leaves would otherwise disagree on the select.
            agree_axes=tuple(a for a in (data_axis, seq_axis, model_axis)
                             if a))
        return new_params, new_opt, mean_loss

    if steps_per_call > 1:
        def _step(params, opt_state, tokens, labels, segment_ids=None):
            def body(carry, _):
                p, o = carry
                p, o, loss = _one_step(p, o, tokens, labels, segment_ids)
                return (p, o), loss
            (params, opt_state), losses = lax.scan(
                body, (params, opt_state), None, length=steps_per_call)
            return params, opt_state, losses[-1]
    else:
        _step = _one_step

    # Param-like opt-state leaves (momenta etc.) inherit the matching
    # param's spec; everything else (step counters, empty states) is
    # replicated.  tree_map_params aligns by optimizer structure, so
    # distinct params that happen to share a shape cannot be confused.
    # In sharded mode the param-like leaves are flat bucket vectors
    # partitioned 1/N over the data axis instead.
    import optax
    if zopt is not None:
        opt_state_shapes = jax.eval_shape(zopt.init, init_abstract(cfg))
        opt_specs = zopt.state_specs(opt_state_shapes)
    else:
        opt_state_shapes = jax.eval_shape(optimizer.init, init_abstract(cfg))
        opt_specs = optax.tree_map_params(
            optimizer, lambda _leaf, spec: spec, opt_state_shapes, specs,
            transform_non_params=lambda _leaf: P())

    data_spec = P(data_axis, seq_axis) if seq_axis else P(data_axis)
    in_specs = (specs, opt_specs, data_spec, data_spec)
    if packed:
        in_specs = in_specs + (data_spec,)
    step = jax.shard_map(
        _step, mesh=mesh,
        in_specs=in_specs,
        out_specs=(specs, opt_specs, P()),
        # The ZeRO path's axis_index-dependent slicing + psum_scatter do
        # not type under the vma checker; the plain path keeps it on.
        check_vma=zopt is None)
    jitted = jax.jit(step, donate_argnums=(0, 1) if donate else ())
    if zopt is not None:
        @functools.wraps(jitted)
        def wrapped(*a, **kw):
            return jitted(*a, **kw)
        wrapped.lower = jitted.lower
        wrapped.jitted = jitted
        wrapped.init = zopt.init
        wrapped.optimizer = zopt
        wrapped.state_shardings = functools.partial(zopt.state_shardings,
                                                    mesh)
        return wrapped, specs, opt_specs
    return jitted, specs, opt_specs


def init_abstract(cfg: TransformerConfig):
    """ShapeDtypeStructs of the params (for spec derivation without
    materializing weights)."""
    return jax.eval_shape(lambda: init_params(jax.random.PRNGKey(0), cfg))


# ---------------------------------------------------------------------------
# Inference: KV-cache decode + greedy generation (reference docs/inference
# topic; Horovod itself ships no inference machinery — this is the
# TPU-idiomatic decode loop: static shapes, lax.scan, cache updates via
# dynamic_update_slice so the whole generation compiles to one program).
# ---------------------------------------------------------------------------

def init_kv_cache(cfg: TransformerConfig, batch: int, max_len: int,
                  model_axis_size: int = 1):
    """Per-layer K/V caches of shape [B, max_len, H_local, head_dim]
    (H_local = n_heads / model_axis_size under tensor parallelism)."""
    h_local = cfg.n_heads // model_axis_size
    z = lambda: jnp.zeros((batch, max_len, h_local, cfg.head_dim),
                          cfg.dtype)
    return [{"k": z(), "v": z()} for _ in range(cfg.n_layers)]


def decode_step(params, token, cache, pos, cfg: TransformerConfig,
                model_axis: Optional[str] = None):
    """One-token decode.  token: [B] int32, pos: scalar int32 position.

    Returns (logits [B, vocab] fp32, updated cache).  Attention runs over
    the full static cache length with a position mask (TPU-friendly: no
    dynamic shapes), so cost is O(max_len) per step.
    """
    dt = cfg.dtype
    hd = cfg.head_dim
    x = (params["embed"][token] +
         lax.dynamic_slice_in_dim(params["pos"], pos, 1, axis=0)[0]
         ).astype(dt)                                    # [B, D]
    new_cache = []
    for layer, c in zip(params["layers"], cache):
        q, k, v, dh = _qkv_proj(x, layer, dt, model_axis, hd)
        b = q.shape[0]
        # Defensive cast: the cache is cfg.dtype forever; any future
        # dtype drift upstream (the r4 rmsnorm f32-scale promotion was
        # exactly such a leak) must not change the cache layout.
        ck = lax.dynamic_update_slice_in_dim(
            c["k"], k[:, None].astype(c["k"].dtype), pos, axis=1)
        cv = lax.dynamic_update_slice_in_dim(
            c["v"], v[:, None].astype(c["v"].dtype), pos, axis=1)
        new_cache.append({"k": ck, "v": cv})
        # Scores in fp32: a one-token decode is latency-bound, not
        # MXU-bound, so the extra precision over local_attention's
        # input-dtype scores is free (identical under fp32 configs,
        # which is what the decode==forward oracle test runs).
        s = jnp.einsum("bhd,bthd->bht", q.astype(jnp.float32),
                       ck.astype(jnp.float32)) * (hd ** -0.5)
        mask = jnp.arange(ck.shape[1]) <= pos              # [T]
        s = jnp.where(mask[None, None, :], s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bht,bthd->bhd", p,
                       cv.astype(jnp.float32)).astype(dt)
        x = _attn_out(o.reshape(b, dh), x, layer, dt, model_axis)
        x = _mlp_block(x, layer, dt, model_axis)
    return _logits_head(x, params, dt), new_cache


def generate(params, prompt, total_len: int, cfg: TransformerConfig,
             model_axis: Optional[str] = None):
    """Greedy decode to ``total_len`` tokens, teacher-forcing ``prompt``.

    prompt: [B, P] int32 (P >= 1).  Returns [B, total_len] int32 whose
    first P entries are the prompt.  One ``lax.scan`` — a single compiled
    program regardless of length.
    """
    b, p_len = prompt.shape
    if total_len > cfg.max_seq:
        raise ValueError(
            f"total_len={total_len} exceeds the positional table "
            f"(max_seq={cfg.max_seq})")
    if p_len > total_len:
        raise ValueError(
            f"prompt length {p_len} exceeds total_len={total_len}; the "
            f"output must contain the whole prompt")
    cache = init_kv_cache(
        cfg, b, total_len,
        lax.axis_size(model_axis) if model_axis else 1)

    def body(carry, pos):
        token, cache = carry
        logits, cache = decode_step(params, token, cache, pos, cfg,
                                    model_axis)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        # Teacher-force while still inside the prompt.
        nxt = jnp.where(pos + 1 < p_len, prompt[:, jnp.minimum(
            pos + 1, p_len - 1)], nxt)
        return (nxt, cache), nxt

    (last, _), toks = lax.scan(body, (prompt[:, 0], cache),
                               jnp.arange(total_len - 1))
    return jnp.concatenate([prompt[:, :1], toks.T], axis=1)


# ---------------------------------------------------------------------------
# Pipeline-parallel forward: the transformer over a 'pipe' mesh axis
# (parallel/pipeline.py GPipe schedule; no reference equivalent).
# ---------------------------------------------------------------------------

def stack_layer_params(params, n_stages: int):
    """Re-layout the per-layer param list for pipelining.

    Returns a dict of leaves shaped [n_stages, layers_per_stage, ...] —
    shard the leading dim over the pipe axis (device p holds stage p).
    """
    layers = params["layers"]
    if len(layers) % n_stages:
        raise ValueError(f"{len(layers)} layers not divisible into "
                         f"{n_stages} stages")
    from horovod_tpu.parallel.pipeline import stack_stage_params
    lps = len(layers) // n_stages
    return stack_stage_params(
        [stack_stage_params(layers[s * lps:(s + 1) * lps])
         for s in range(n_stages)])


def stack_layer_params_interleaved(params, n_devices: int, virtual: int):
    """Round-robin (Megatron-interleave) re-layout: leaves
    [n_devices·virtual, layers_per_chunk, ...] ordered so that sharding
    the leading dim over the pipe axis hands device p local slot k =
    global chunk ``k·n_devices + p`` (global row ``j = p·v + k`` holds
    chunk ``(j % v)·P + j // v``)."""
    layers = params["layers"]
    n_chunks = n_devices * virtual
    if len(layers) % n_chunks:
        raise ValueError(f"{len(layers)} layers not divisible into "
                         f"{n_chunks} virtual chunks")
    from horovod_tpu.parallel.pipeline import stack_stage_params
    lpc = len(layers) // n_chunks
    chunk = lambda c: stack_stage_params(layers[c * lpc:(c + 1) * lpc])
    order = [(j % virtual) * n_devices + j // virtual
             for j in range(n_chunks)]
    return stack_stage_params([chunk(c) for c in order])


def stacked_layer_specs(pipe_axis: str):
    """PartitionSpec for every stacked-layer leaf: stage dim over pipe."""
    return P(pipe_axis)


def forward_pipelined(params, stacked_layers, tokens,
                      cfg: TransformerConfig, pipe_axis: str = "pipe",
                      n_microbatches: int = 2, virtual: int = 1):
    """Forward pass with the layer stack pipelined over ``pipe_axis``.

    ``params`` supplies embed/pos/ln_f (replicated); ``stacked_layers``
    comes from :func:`stack_layer_params` with its stage dim sharded over
    the pipe axis (inside shard_map each device sees a [1, lps, ...]
    slice).  The batch is split into ``n_microbatches`` and flows through
    :func:`horovod_tpu.parallel.pipeline.pipeline_apply`; embedding and
    logits head are computed replicated (they are cheap relative to the
    layer stack, which is where PP's memory win lives).  Attention is
    local causal (compose PP with DP via a 2-D mesh; TP/SP composition
    belongs on the model/seq axes of the non-pipelined forward).
    """
    from horovod_tpu.parallel.pipeline import (pipeline_apply,
                                               pipeline_apply_interleaved)

    b, t = tokens.shape
    mb = _embed_microbatches(params, tokens, cfg, n_microbatches)
    if virtual > 1:
        # Round-robin virtual chunks (stack_layer_params_interleaved):
        # the fill shrinks to (P-1)/v chunk-ticks — see
        # pipeline_apply_interleaved for the schedule derivation.
        y = pipeline_apply_interleaved(_pipe_stage_fn(cfg), stacked_layers,
                                       mb, axis_name=pipe_axis,
                                       virtual=virtual)
    else:
        y = pipeline_apply(_pipe_stage_fn(cfg), stacked_layers, mb,
                           axis_name=pipe_axis)
    x = y.reshape(b, t, cfg.d_model)
    return _logits_head(x, params, cfg.dtype)


def _embed_microbatches(base, tokens, cfg: TransformerConfig,
                        n_microbatches: int):
    """Embedding prologue shared by both pipeline schedules:
    tokens [B, T] -> microbatched activations [M, B/M, T, D]."""
    b, t = tokens.shape
    if b % n_microbatches:
        raise ValueError(f"batch {b} not divisible by "
                         f"{n_microbatches} microbatches")
    x = (base["embed"][tokens] +
         base["pos"][None, :t]).astype(cfg.dtype)          # [B, T, D]
    return x.reshape(n_microbatches, b // n_microbatches, t, cfg.d_model)


def _pipe_stage_fn(cfg: TransformerConfig):
    """stage_fn for the pipeline schedules: scan this device's layer
    slice (leaves [1, lps, ...]) over the activation."""
    dt, hd = cfg.dtype, cfg.head_dim

    def one_layer(x, lp):
        q, k, v, dh = _qkv_proj(x, lp, dt, None, hd)
        bb, tt = q.shape[:2]
        o = seq_mod.local_attention(q, k, v, causal=True)
        x = _attn_out(o.reshape(bb, tt, dh), x, lp, dt, None)
        x = _mlp_block(x, lp, dt, None)
        # attention computes in f32; pin the carried activation to the
        # model dtype so the layer scan (and the pipeline's microbatch
        # buffers) keep a stable, bf16-safe type
        return x.astype(dt), None

    def stage_fn(stage_params, act):
        # stage_params leaves: [1, lps, ...] — this device's stage.  A
        # local stage dim > 1 means n_stages exceeded the pipe axis size;
        # silently running only slice 0 would drop layers, so refuse.
        lead = {l.shape[0] for l in
                jax.tree_util.tree_leaves(stage_params)}
        if lead != {1}:
            raise ValueError(
                f"each device must hold exactly one stage; got local "
                f"stage dims {sorted(lead)} — n_stages passed to "
                f"stack_layer_params must equal the pipe axis size")
        local = jax.tree_util.tree_map(lambda l: l[0], stage_params)
        out, _ = lax.scan(one_layer, act, local)
        return out

    return stage_fn


def split_pipeline_params(params, n_stages: int, virtual: int = 1):
    """Re-layout :func:`init_params` output for the pipelined step: the
    one canonical base/stacked split (used by the example and tests).
    ``virtual > 1`` uses the round-robin interleaved chunk layout
    (``n_stages`` is then the PIPE AXIS size, not the chunk count)."""
    base = {k: v for k, v in params.items() if k != "layers"}
    if virtual > 1:
        return {"base": base,
                "stacked": stack_layer_params_interleaved(
                    params, n_stages, virtual)}
    return {"base": base, "stacked": stack_layer_params(params, n_stages)}


def make_train_step_pipelined(cfg: TransformerConfig, optimizer, mesh,
                              data_axis: Optional[str] = "data",
                              pipe_axis: str = "pipe",
                              n_microbatches: int = 2,
                              donate: bool = True,
                              schedule: str = "gpipe",
                              virtual: int = 2):
    """Jitted DP x PP training step.

    ``schedule="gpipe"``: differentiation happens OUTSIDE the shard_map
    (jit-of-shard_map): JAX transposes the GPipe schedule (scan +
    ppermute) into the exact backward pipeline, and GSPMD handles the
    data-axis gradient averaging because the loss is a global-batch
    mean — verified exact against the plain forward's gradients
    (tests/test_parallel.py).

    ``schedule="1f1b"``: the hand-scheduled one-forward-one-backward
    pipeline (:func:`horovod_tpu.parallel.pipeline.pipeline_1f1b`) —
    same exact gradients (same oracle), but peak activation state is
    O(pipe) instead of O(n_microbatches) saved microbatches per stage:
    choose it when many microbatches of residuals don't fit HBM.  On a
    lockstep SPMD mesh its bubble is NOT smaller than GPipe's — see
    docs/parallelism.md for the measured comparison.

    ``schedule="interleaved"``: Megatron-style virtual stages
    (:func:`horovod_tpu.parallel.pipeline.pipeline_apply_interleaved`)
    with ``virtual`` round-robin chunks per device — the fill/drain
    bubble divides by ``virtual`` (GPipe-class activation memory;
    params from ``split_pipeline_params(params, P, virtual)``).
    Requires ``n_microbatches % pipe == 0``.

    ``schedule="interleaved_1f1b"``: the FULL Megatron schedule
    (:func:`horovod_tpu.parallel.pipeline.pipeline_1f1b_interleaved`):
    virtual-stage round-robin + hand-scheduled 1F1B with a fwd-packed
    warmup and bwd drain — bubble ÷ v at O(pipe) activation memory
    (2v·P saved chunk inputs).  Same exact gradients; same params
    layout as "interleaved"; requires ``n_microbatches % pipe == 0``
    and ``n_microbatches >= pipe``.

    Params layout: :func:`split_pipeline_params` output
    (``{"base": embed/pos/ln_f (replicated), "stacked":
    stack_layer_params(...) (stage dim over pipe)}``).
    Returns ``(step, shardings)`` where ``step(params, opt_state, tokens,
    labels) -> (params, opt_state, loss)`` and ``shardings(params) ->
    (param_shardings, opt_state_shardings)`` (place both trees).
    """
    from jax.sharding import NamedSharding

    n_stages = mesh.shape[pipe_axis]
    v_eff = (virtual if schedule in ("interleaved", "interleaved_1f1b")
             else 1)
    if cfg.n_layers % (n_stages * v_eff):
        raise ValueError(f"{cfg.n_layers} layers not divisible over "
                         f"{n_stages * v_eff} pipe chunks")
    sspec_one = stacked_layer_specs(pipe_axis)
    data_spec = P(data_axis) if data_axis else P()

    def smapped(base, stacked, tokens):
        bspec = {k: P() for k in base}
        sspec = {k: sspec_one for k in stacked}
        return jax.shard_map(
            lambda b_, s_, t_: forward_pipelined(
                dict(b_, layers=[]), s_, t_, cfg, pipe_axis,
                n_microbatches, virtual=v_eff),
            mesh=mesh, in_specs=(bspec, sspec, data_spec),
            out_specs=data_spec, check_vma=False)(base, stacked, tokens)

    if schedule in ("1f1b", "interleaved_1f1b"):
        from horovod_tpu.parallel.pipeline import make_pipeline_1f1b_loss

        def head_loss(y, tgt, base):
            return xent(_logits_head(y, base, cfg.dtype), tgt)

        # microbatches/targets: [M, mb, T, ...] with the microbatch dim
        # sharded over data (GSPMD reshards the embedded activations once
        # per step; semantics are unchanged — the loss is a global mean).
        mb_spec = P(None, data_axis) if data_axis else P()

        def _loss(params, tokens, labels):
            f = make_pipeline_1f1b_loss(
                _pipe_stage_fn(cfg), head_loss, mesh,
                stage_spec={k: sspec_one for k in params["stacked"]},
                mb_spec=mb_spec,
                aux_spec={k: P() for k in params["base"]},
                axis_name=pipe_axis,
                data_axes=(data_axis,) if data_axis else (),
                virtual=v_eff)
            base = params["base"]
            b, t = tokens.shape
            mb = _embed_microbatches(base, tokens, cfg, n_microbatches)
            tgt = labels.reshape(n_microbatches, b // n_microbatches, t)
            return f(params["stacked"], base, mb, tgt)
    elif schedule in ("gpipe", "interleaved"):
        # Both differentiate through the scanned schedule (jit of
        # shard_map); interleaved just runs the virtual-chunk scan.
        def _loss(params, tokens, labels):
            return xent(smapped(params["base"], params["stacked"], tokens),
                        labels)
    else:
        raise ValueError(f"schedule={schedule!r}: expected 'gpipe', "
                         f"'1f1b', 'interleaved' or 'interleaved_1f1b'")

    def _step(params, opt_state, tokens, labels):
        loss, grads = jax.value_and_grad(_loss)(params, tokens, labels)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = jax.tree_util.tree_map(lambda p, u: p + u, params,
                                        updates)
        return params, opt_state, loss

    def shardings(params):
        """(param_shardings, opt_state_shardings) for ``params``.

        Opt-state momenta inherit the matching param's sharding; scalar
        leaves (schedule counts) are replicated — place BOTH trees before
        training or a checkpoint restore brings scalars back committed
        to one device and jit rejects the mixed placement.
        """
        import optax
        p_sh = {
            "base": {k: NamedSharding(mesh, P()) for k in params["base"]},
            "stacked": {k: NamedSharding(mesh, sspec_one)
                        for k in params["stacked"]},
        }
        o_sh = optax.tree_map_params(
            optimizer, lambda _l, s_: s_,
            jax.eval_shape(optimizer.init, params), p_sh,
            transform_non_params=lambda _l: NamedSharding(mesh, P()))
        return p_sh, o_sh

    step = jax.jit(_step, donate_argnums=(0, 1) if donate else ())
    return step, shardings

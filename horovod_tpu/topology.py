"""Device-mesh construction — the TPU replacement for communicators.

Horovod's communicator topology is GLOBAL / LOCAL (intra-node) / CROSS
(one-rank-per-node) (reference ``horovod/common/common.h:105-109``,
``mpi_context.h:78-87``), built from MPI ``COMM_TYPE_SHARED`` splits
(``mpi_controller.cc:25-81``).  On TPU the same hierarchy is *mesh axes*:
the fast axis rides ICI within a slice, the slow axis rides DCN across
slices/hosts.  XLA then lowers ``psum`` over either axis to the right
interconnect — the explicit two-level dance of
``NCCLHierarchicalAllreduce`` (``nccl_operations.cc:151-346``) becomes a
sharding annotation.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh


def build_mesh(axes: Sequence[str] = ("data",),
               shape: Optional[Tuple[int, ...]] = None,
               devices=None) -> Mesh:
    """Build a :class:`jax.sharding.Mesh` over ``devices``.

    * 1 axis, no shape: all devices on one axis (GLOBAL communicator).
    * N axes + shape: reshape devices into that grid.  For real multi-slice
      TPU jobs ``mesh_utils.create_hybrid_device_mesh`` is used so the
      leading axis maps to DCN and trailing axes to ICI.
    """
    devices = list(jax.devices()) if devices is None else list(devices)
    n = len(devices)
    axes = tuple(axes)
    if shape is None:
        if axes == ("dcn", "ici"):
            # Derive the hybrid shape from the launcher-discovered
            # topology: dcn = number of hosts, ici = devices per host.
            # hvd.topology() falls back to a single host when the job was
            # not launched through hvdrun, which degenerates to (1, n) —
            # a flat mesh with a unit DCN axis, still valid for the
            # hierarchical collectives (the dcn psum is a no-op).
            from horovod_tpu import basics as _basics
            topo = _basics._topology_unchecked()
            dcn = max(topo.num_hosts, 1)
            if n % dcn != 0:
                raise ValueError(
                    f"cannot derive ('dcn', 'ici') mesh shape: {n} devices "
                    f"do not divide evenly over {dcn} hosts "
                    f"({topo.hosts}); pass shape= explicitly")
            shape = (dcn, n // dcn)
        elif len(axes) != 1:
            raise ValueError(f"shape required for multi-axis mesh {axes}")
        else:
            shape = (n,)
    want = int(np.prod(shape))
    if want < n:
        # Underfilled meshes take a device prefix — the launcher's rank
        # order is contiguous, so a prefix is the natural sub-communicator
        # (mirrors the reference's rank-subset init, ``basics.py:29-61``).
        # Warn loudly: an accidental undersized shape would silently
        # exclude devices from gradient averaging.
        import warnings
        warnings.warn(
            f"build_mesh: shape {shape} covers {want} of {n} available "
            f"devices; using the first {want} (rank-order prefix)",
            stacklevel=2)
        devices = devices[:want]
        n = want
    if want != n:
        raise ValueError(
            f"mesh shape {shape} does not cover {n} devices")

    if len(axes) > 1 and jax.process_count() > 1:
        try:
            dev_array = mesh_utils.create_hybrid_device_mesh(
                mesh_shape=shape[1:], dcn_mesh_shape=(shape[0],) + (1,) * (len(shape) - 1))
            return Mesh(dev_array, axes)
        except Exception:  # heterogeneous/virtual platforms: fall through
            pass
    try:
        dev_array = mesh_utils.create_device_mesh(shape, devices=devices)
    except Exception:
        # Virtual CPU meshes (forced host platform count) lack topology
        # info; a plain reshape preserves the launcher's rank order.
        dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, axes)


def data_axis(mesh: Mesh) -> str:
    """The axis gradients are averaged over (the GLOBAL communicator
    equivalent): by convention the axis named 'data', else the last axis."""
    return "data" if "data" in mesh.axis_names else mesh.axis_names[-1]


def mesh_size(mesh: Mesh, axis=None) -> int:
    if axis is None:
        return int(np.prod(list(mesh.shape.values())))
    if isinstance(axis, (tuple, list)):
        return int(np.prod([mesh.shape[a] for a in axis]))
    return mesh.shape[axis]


_warned_no_abstract_device = False


def exec_on_tpu(x) -> bool:
    """Whether the mesh actually EXECUTING this computation is TPU.

    ``jax.default_backend()`` is the wrong question inside shard_map: on
    a TPU host driving a CPU/virtual mesh it answers "tpu" and would
    select a TPU-only lowering (Pallas kernel, ragged-all-to-all HLO)
    for a CPU computation.  The abstract mesh attached to the tracer's
    sharding carries the real device kind of the mesh the shard_map runs
    on.  Shared by the flash-attention kernel gates and
    ``alltoall_ragged``'s primitive/dense-twin routing.
    """
    global _warned_no_abstract_device
    try:
        # abstract_device is None on eager/concrete arrays (normal: fall
        # through to the backend answer, silently); it is internal
        # surface, so a MISSING attribute means a JAX upgrade renamed it
        # — say so once instead of silently reverting to the
        # host-backend answer this helper exists to avoid.
        ad = jax.typeof(x).sharding.mesh.abstract_device
        if ad is not None and ad.device_kind is not None:
            return "tpu" in str(ad.device_kind).lower()
    except AttributeError:
        if not _warned_no_abstract_device:
            _warned_no_abstract_device = True
            import logging
            logging.getLogger(__name__).warning(
                "AbstractMesh.abstract_device.device_kind unavailable on "
                "this JAX; falling back to jax.default_backend() for the "
                "executing-mesh platform gate")
    try:  # outside shard_map / no mesh info: fall back to the backend
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover
        return False

"""Per-rank distributed span recorder (``HOROVOD_TRACE``).

Every collective (and serving request) gets a correlation key
``(trace_id, span_id)`` that is identical on every rank WITHOUT any wire
change: the collective-schedule contract guarantees every rank submits
the same tensor names in the same order, so the pair
``(tensor name, per-name occurrence index)`` already names one logical
step of one collective globally.  ``trace_id`` is a deterministic hash
of that pair — two ranks recording spans for occurrence 17 of
``grad/dense0`` compute the same id with zero coordination, and the
launcher's merger correlates them by value.

The recorder is a bounded append-only buffer guarded by one lock taken
only on the *enabled* path; the disabled path is the telemetry no-op
contract — ``telemetry.spans()`` returns ``None`` and call sites are
written as::

    sp = telemetry.spans()
    if sp is not None:
        sp.record(name, "wait", seq, t0, t1, nbytes)

so tracing off costs one function call and an identity test (asserted by
``tests/test_spans.py``).  Sampling (``HOROVOD_TRACE_SAMPLE=N``) keeps
every Nth occurrence *per tensor name* — the decision is a pure function
of the occurrence index, so every rank samples the same steps and the
merged trace never shows half a collective.

Timestamps are ``time.monotonic()`` seconds.  The native plane's
``steady_clock`` is the same CLOCK_MONOTONIC domain on Linux, so drained
native spans interleave directly with Python spans per host; cross-host
correction happens at collection time via the launcher's RTT-halving
time-sync handshake (``runner/rpc.py:measure_clock_offset``), whose
result rides in the exported document as ``clock_offset``.
"""

from __future__ import annotations

import json
import os
import socket
import threading
from typing import Dict, List, Optional, Tuple

SCHEMA = "horovod_tpu.trace.v1"

# Request-scoped spans (serving, RPC) have no occurrence stream — they
# correlate by unique name alone and use this fixed sequence number.
REQUEST_SEQ = 0

_FNV_OFFSET = 0xCBF29CE484222325
_FNV_PRIME = 0x100000001B3
_MASK64 = (1 << 64) - 1
# Fibonacci multiplier spreads small sequence numbers across the id
# space so trace ids never collide on low bits alone.
_SEQ_MIX = 0x9E3779B97F4A7C15


def trace_id(name: str, seq: int) -> str:
    """Deterministic 64-bit correlation id for occurrence ``seq`` of
    tensor ``name`` — identical on every rank by construction (FNV-1a of
    the name xor the mixed occurrence index)."""
    h = _FNV_OFFSET
    for b in name.encode("utf-8", "replace"):
        h = ((h ^ b) * _FNV_PRIME) & _MASK64
    return f"{(h ^ ((seq * _SEQ_MIX) & _MASK64)) & _MASK64:016x}"


class SpanRecorder:
    """Bounded, thread-safe span buffer for one rank."""

    def __init__(self, rank: int = 0, sample: int = 1,
                 capacity: int = 65536):
        self.rank = rank
        self.sample = max(int(sample), 1)
        self.capacity = max(int(capacity), 1)
        self.dropped = 0
        self.clock_offset: Optional[float] = None
        self.clock_rtt: Optional[float] = None
        self._lock = threading.Lock()
        self._seq: Dict[str, int] = {}
        # (name, phase, seq, t0, t1, bytes) tuples; dict-ified at export.
        self._spans: List[Tuple[str, str, int, float, float, int]] = []
        self._closed = False

    # -- hot path ----------------------------------------------------------

    def next_seq(self, name: str) -> int:
        """Allocate the next occurrence index for ``name`` (0-based).
        Counts EVERY occurrence, sampled or not, so the stream stays
        aligned with the other ranks' counters."""
        with self._lock:
            s = self._seq.get(name, -1) + 1
            self._seq[name] = s
        return s

    def sampled(self, seq: int) -> bool:
        """Record occurrence ``seq``?  Pure function of the index, hence
        identical on every rank (HOROVOD_TRACE_SAMPLE=N keeps seq%N==0)."""
        return self.sample <= 1 or (seq % self.sample) == 0

    def record(self, name: str, phase: str, seq: int, t0: float,
               t1: float, nbytes: int = 0) -> None:
        """Append one span; silently dropped (and counted) past
        capacity, after close, or when the occurrence is sampled out."""
        if self._closed or not self.sampled(seq):
            return
        with self._lock:
            if self._closed:
                return
            if len(self._spans) >= self.capacity:
                self.dropped += 1
                return
            self._spans.append((str(name), str(phase), int(seq),
                                float(t0), float(t1), int(nbytes)))

    def event(self, name: str, phase: str, t0: float, t1: float,
              nbytes: int = 0) -> None:
        """Request-scoped span: correlated by unique name alone (serving
        requests, RPC rounds), recorded under :data:`REQUEST_SEQ`."""
        self.record(name, phase, REQUEST_SEQ, t0, t1, nbytes)

    # -- export ------------------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)

    def document(self) -> dict:
        """The rank's span log (``horovod_tpu.trace.v1``): every span
        with its computed correlation ids, plus the attribution and
        clock metadata the merger needs."""
        with self._lock:
            spans = list(self._spans)
            dropped = self.dropped
        spans.sort(key=lambda s: s[3])
        return {
            "schema": SCHEMA,
            "rank": self.rank,
            "size": int(os.environ.get("HOROVOD_SIZE", "1") or 1),
            "host": socket.gethostname(),
            "pid": os.getpid(),
            "clock": "monotonic",
            # launcher_clock - rank_clock seconds (None = unmeasured;
            # merger treats it as 0, which is exact for same-host jobs).
            "clock_offset": self.clock_offset,
            "clock_sync_rtt": self.clock_rtt,
            "sample": self.sample,
            "dropped": dropped,
            "spans": [
                {"name": n, "phase": ph, "seq": sq,
                 "trace_id": trace_id(n, sq), "span_id": i,
                 "t0": t0, "t1": t1, "bytes": b}
                for i, (n, ph, sq, t0, t1, b) in enumerate(spans)
            ],
        }

    def close(self) -> None:
        with self._lock:
            self._closed = True


# ---------------------------------------------------------------------------
# At-exit export (mirrors the metrics exporter's push + file fallback)
# ---------------------------------------------------------------------------

def rank_log_path(dir_path: str, rank: int) -> str:
    return os.path.join(dir_path, f"spans.rank{rank}.json")


def write_rank_log(recorder: SpanRecorder, dir_path: str) -> str:
    """Atomic per-rank span-log dump (the launcher's fallback source for
    ranks whose RPC push never arrived)."""
    os.makedirs(dir_path, exist_ok=True)
    path = rank_log_path(dir_path, recorder.rank)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(recorder.document(), f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return path


def push_to_launcher(recorder: SpanRecorder, endpoint: str) -> bool:
    """Push the span log to ``hvdrun``'s trace collector over the
    authenticated RPC plane.  Collection failures are swallowed — the
    file fallback (and the job's exit code) must survive a dead
    launcher."""
    try:
        from horovod_tpu.runner import rpc
        addr, port = endpoint.rsplit(":", 1)
        key = rpc.job_key_bytes(os.environ.get("HOROVOD_SECRET_KEY"))
        reply = rpc.rpc_call(addr, int(port),
                             {"kind": "trace_report",
                              "report": recorder.document()},
                             key, timeout=10.0, retries=1)
        return bool(isinstance(reply, dict) and reply.get("ok"))
    except Exception:
        return False


def export_at_exit(recorder: SpanRecorder) -> None:
    """The recorder's exit hook: measure this rank's clock offset
    against the launcher (RTT-halving handshake), mirror the recorder
    totals into telemetry counters, push the span log over RPC, and
    always leave the file fallback behind."""
    from horovod_tpu import telemetry

    endpoint = os.environ.get("HOROVOD_TRACE_RPC", "").strip()
    if endpoint:
        try:
            from horovod_tpu.runner import rpc
            addr, port = endpoint.rsplit(":", 1)
            key = rpc.job_key_bytes(os.environ.get("HOROVOD_SECRET_KEY"))
            sync = rpc.measure_clock_offset(addr, int(port), key)
            if sync is not None:
                recorder.clock_offset, recorder.clock_rtt = sync
        except Exception:
            pass
    if telemetry.enabled():
        n = len(recorder)
        if n:
            telemetry.counter(
                "hvd_trace_spans_total",
                "Span records captured by this rank's trace recorder",
            ).inc(n)
        if recorder.dropped:
            telemetry.counter(
                "hvd_trace_spans_dropped_total",
                "Span records dropped at the recorder's capacity bound",
            ).inc(recorder.dropped)
    pushed = endpoint and push_to_launcher(recorder, endpoint)
    dir_path = os.environ.get("HOROVOD_TRACE_DIR", "").strip()
    if dir_path:
        try:
            write_rank_log(recorder, dir_path)
        except OSError:
            pass  # exit path: an unwritable target must not mask the rc
    elif not pushed:
        pass  # nowhere to export; the in-process document remains readable
    recorder.close()


def configured_recorder() -> Optional[SpanRecorder]:
    """Build a recorder from the environment, or None when tracing is
    off (the telemetry front door calls this once at configure time)."""
    enabled = os.environ.get("HOROVOD_TRACE", "").strip() not in (
        "", "0", "false")
    if not (enabled or os.environ.get("HOROVOD_TRACE_DIR", "").strip()
            or os.environ.get("HOROVOD_TRACE_RPC", "").strip()):
        return None
    try:
        sample = int(os.environ.get("HOROVOD_TRACE_SAMPLE", "1") or 1)
    except ValueError:
        sample = 1
    try:
        cap = int(os.environ.get("HOROVOD_TRACE_BUFFER", "65536") or 65536)
    except ValueError:
        cap = 65536
    return SpanRecorder(
        rank=int(os.environ.get("HOROVOD_RANK", "0") or 0),
        sample=sample, capacity=cap)


__all__ = ["SCHEMA", "REQUEST_SEQ", "SpanRecorder", "trace_id",
           "rank_log_path", "write_rank_log", "push_to_launcher",
           "export_at_exit", "configured_recorder"]

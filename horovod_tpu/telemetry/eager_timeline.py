"""Eager-plane Chrome-tracing timeline (``HOROVOD_EAGER_TIMELINE``).

The native plane already writes a host-side timeline from the C++ cycle
loop (``native/cc/src/timeline.cc``, reference ``common/timeline.cc``) —
but only rank 0's coordinator sees those events, and a single-process
job (where the eager collectives are local arithmetic) never starts the
native runtime at all.  This writer closes that gap from the Python
boundary: every rank can emit per-tensor SUBMIT / WAIT / FINISH rows in
the same ``chrome://tracing`` JSON dialect the native writer uses
(file opens with ``[``, one event object per line, per-tensor ``tid``
rows named via ``thread_name`` metadata, microsecond timestamps), so the
artifacts are drop-in comparable in Perfetto.

Format notes (mirroring ``timeline.cc``):

* The event stream is a valid JSON array; like Chrome's own tracer we
  keep a trailing ``]`` optional — viewers accept a truncated file from
  a crashed rank (``close()`` writes the terminator when reached).
* ``pid`` is the Horovod rank (the native writer runs only on rank 0 and
  hardcodes 0); ``tid`` is a small integer allocated per tensor name,
  announced with a ``thread_name`` metadata event.
* Phases: ``X`` (complete, with ``dur``) for SUBMIT and WAIT spans,
  ``i`` (instant) for FINISH, all in microseconds from the writer epoch.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, Optional


class EagerTimelineWriter:
    """Append-only, thread-safe Chrome-tracing writer for eager ops."""

    def __init__(self, path: str, rank: int = 0):
        self.path = path
        self.rank = rank
        self._lock = threading.Lock()
        self._tids: Dict[str, int] = {}
        self._next_tid = 1
        self._epoch = time.monotonic()
        self._file = open(path, "w", buffering=1)
        self._closed = False
        self._file.write("[\n")
        self._emit({"name": "process_name", "ph": "M", "pid": rank,
                    "args": {"name": f"eager rank {rank}"}})

    # -- low level ---------------------------------------------------------

    def _emit(self, event: dict) -> None:
        # Caller holds the lock (or is the constructor, pre-sharing).
        self._file.write(json.dumps(event) + ",\n")

    def _tid_for(self, tensor: str) -> int:
        tid = self._tids.get(tensor)
        if tid is None:
            tid = self._next_tid
            self._next_tid += 1
            self._tids[tensor] = tid
            self._emit({"name": "thread_name", "ph": "M", "pid": self.rank,
                        "tid": tid, "args": {"name": tensor}})
        return tid

    def _us(self, t_monotonic: float) -> int:
        return int((t_monotonic - self._epoch) * 1e6)

    # -- op rows -----------------------------------------------------------

    def span(self, tensor: str, name: str, t0: float, t1: float,
             args: Optional[dict] = None) -> None:
        """A complete (``ph=X``) event on the tensor's row; ``t0``/``t1``
        are ``time.monotonic()`` seconds."""
        if self._closed:
            return
        with self._lock:
            if self._closed:
                return
            tid = self._tid_for(tensor)
            ev = {"name": name, "ph": "X", "pid": self.rank, "tid": tid,
                  "ts": self._us(t0),
                  "dur": max(self._us(t1) - self._us(t0), 1)}
            if args:
                ev["args"] = args
            self._emit(ev)

    def instant(self, tensor: str, name: str, t: float,
                args: Optional[dict] = None) -> None:
        if self._closed:
            return
        with self._lock:
            if self._closed:
                return
            tid = self._tid_for(tensor)
            ev = {"name": name, "ph": "i", "pid": self.rank, "tid": tid,
                  "ts": self._us(t), "s": "t"}
            if args:
                ev["args"] = args
            self._emit(ev)

    def record_op(self, tensor: str, op: str, t_submit: float,
                  t_wait: float, t_done: float, nbytes: int = 0) -> None:
        """The canonical submit/wait/finish triple for one eager op.

        ``t_submit``: enqueue began; ``t_wait``: enqueue returned / wait
        began; ``t_done``: result available.  For a local (1-rank) op the
        three collapse — the SUBMIT span covers the whole computation.
        """
        upper = op.upper()
        self.span(tensor, f"SUBMIT_{upper}", t_submit, t_wait,
                  args={"op": op, "bytes": nbytes})
        if t_done > t_wait:
            self.span(tensor, f"WAIT_{upper}", t_wait, t_done)
        self.instant(tensor, "FINISH", t_done, args={"op": op})

    # -- lifecycle ---------------------------------------------------------

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            # Terminator matching the native writer's shutdown record
            # (timeline.cc writes a SHUTDOWN instant, then "]").
            self._file.write(json.dumps(
                {"name": "SHUTDOWN", "ph": "i", "pid": self.rank, "tid": 0,
                 "ts": self._us(time.monotonic()), "s": "g"}) + "\n]\n")
            self._file.close()


def per_rank_path(path: str) -> str:
    """De-conflict the artifact path in a multi-process job: each rank
    appends ``.rank<k>`` before the extension unless the caller (or the
    launcher) already embedded a rank marker."""
    rank = int(os.environ.get("HOROVOD_RANK", "0") or 0)
    size = int(os.environ.get("HOROVOD_SIZE", "1") or 1)
    if size <= 1 or f".rank{rank}" in os.path.basename(path):
        return path
    root, ext = os.path.splitext(path)
    return f"{root}.rank{rank}{ext or '.json'}"

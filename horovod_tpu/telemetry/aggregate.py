"""Cross-rank metric aggregation for the launcher's merged summary.

The launcher collects one ``MetricsRegistry.snapshot()`` per rank (over
the RPC plane, falling back to the ranks' ``HOROVOD_METRICS_FILE`` JSON
dumps) and merges them into a single per-rank-attributed document:

* counters: summed across ranks;
* histograms: bucket-wise sums (every rank shares the fixed bounds —
  the registry forbids dynamic buckets exactly for this), plus summed
  ``sum``/``count``;
* gauges: point-in-time values don't sum meaningfully across ranks, so
  the merge keeps ``min``/``max``/``mean``.

The merged document never discards the per-rank snapshots — operators
debugging a skewed rank need the attribution, not just the totals.
"""

from __future__ import annotations

from typing import Dict, List, Optional


def estimate_percentiles(buckets: Dict[str, int],
                         qs=(0.5, 0.95, 0.99)) -> Dict[str, float]:
    """Percentile estimates from a merged (non-cumulative) bucket map.

    Linear interpolation within each fixed bucket — the standard
    histogram-quantile estimate: observations are assumed uniform
    between a bucket's lower and upper bound, so the q-th rank inside a
    bucket lands a proportional fraction of the way through it.  The
    ``+Inf`` bucket has no upper bound; ranks landing there report the
    last finite bound (a deliberate under-estimate, matching Prometheus
    ``histogram_quantile``).  Returns ``{"p50": ..., ...}`` keyed by the
    requested quantiles; empty dict for an empty histogram.
    """
    finite = sorted((float(b), int(n)) for b, n in buckets.items()
                    if b not in ("+Inf", "inf", "Inf"))
    inf_n = sum(int(n) for b, n in buckets.items()
                if b in ("+Inf", "inf", "Inf"))
    total = sum(n for _, n in finite) + inf_n
    if total <= 0:
        return {}
    out: Dict[str, float] = {}
    last_finite = finite[-1][0] if finite else 0.0
    for q in qs:
        target = q * total
        seen = 0.0
        lo = 0.0
        value = last_finite
        for bound, n in finite:
            if seen + n >= target and n > 0:
                frac = (target - seen) / n
                value = lo + (bound - lo) * frac
                break
            seen += n
            lo = bound
        else:
            value = last_finite   # target fell in +Inf
        out[f"p{q * 100:g}"] = value
    return out


def _merge_values(kind: str, entries: List[dict]) -> dict:
    """Merge same-labels children from several ranks into one entry."""
    out: dict = {"labels": entries[0]["labels"]}
    if kind == "histogram":
        buckets: Dict[str, int] = {}
        for e in entries:
            for bound, n in e.get("buckets", {}).items():
                buckets[bound] = buckets.get(bound, 0) + n
        out["sum"] = sum(e.get("sum", 0.0) for e in entries)
        out["count"] = sum(e.get("count", 0) for e in entries)
        out["buckets"] = buckets
        pct = estimate_percentiles(buckets)
        if pct:
            out["percentiles"] = pct
    elif kind == "gauge":
        vals = [e.get("value", 0.0) for e in entries]
        out["min"] = min(vals)
        out["max"] = max(vals)
        out["mean"] = sum(vals) / len(vals)
    else:
        out["value"] = sum(e.get("value", 0.0) for e in entries)
    return out


def merge_snapshots(snapshots: Dict[str, dict]) -> dict:
    """Merge ``{rank_label: snapshot}`` into one aggregate snapshot.

    ``rank_label`` keys are informational ("0", "1", "launcher", ...);
    the result has the same shape as a single registry snapshot, with
    gauge entries replaced by min/max/mean summaries.
    """
    merged: Dict[str, dict] = {}
    collation: Dict[str, Dict[tuple, List[dict]]] = {}
    kinds: Dict[str, str] = {}
    helps: Dict[str, str] = {}
    for snap in snapshots.values():
        if not isinstance(snap, dict):
            continue
        for name, fam in snap.items():
            kinds.setdefault(name, fam.get("type", "counter"))
            helps.setdefault(name, fam.get("help", ""))
            by_labels = collation.setdefault(name, {})
            for entry in fam.get("values", []):
                key = tuple(sorted(entry.get("labels", {}).items()))
                by_labels.setdefault(key, []).append(entry)
    for name in sorted(collation):
        merged[name] = {
            "type": kinds[name],
            "help": helps[name],
            "values": [_merge_values(kinds[name], entries)
                       for _, entries in sorted(collation[name].items())],
        }
    return merged


def counter_total(snapshot: dict, name: str,
                  labels: Optional[Dict[str, str]] = None) -> float:
    """Sum of a counter family's values, optionally filtered to entries
    whose labels include every pair in ``labels`` (validation helper for
    tests and the CI telemetry gate)."""
    fam = snapshot.get(name)
    if not fam:
        return 0.0
    total = 0.0
    for entry in fam.get("values", []):
        got = entry.get("labels", {})
        if labels and any(got.get(k) != v for k, v in labels.items()):
            continue
        total += entry.get("value", 0.0)
    return total

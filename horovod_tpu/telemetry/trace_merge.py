"""Merge per-rank span logs into one skew-corrected Chrome/Perfetto trace.

The launcher (``hvdrun --trace``) collects one ``horovod_tpu.trace.v1``
document per rank (RPC push, file fallback for dead ranks) and this
module folds them into a single ``chrome://tracing`` JSON file: ``pid``
is the rank, ``tid`` is a per-(rank, tensor) row announced with
``thread_name`` metadata, and every event carries the cross-rank
``trace_id`` in its args so clicking occurrence 17 of ``grad/dense0`` on
rank 0 finds the same id on rank 3.

Skew correction: each document carries ``clock_offset`` — launcher
monotonic clock minus the rank's, measured by the RTT-halving handshake
(``runner/rpc.py:measure_clock_offset``) — so adding it maps every
rank's timestamps onto the launcher's clock.  Same-host ranks share
CLOCK_MONOTONIC and measure ~0; cross-host offsets are bounded by half
the handshake RTT.

The loader side is deliberately tolerant: the eager/native timeline
dialect keeps the trailing ``]`` optional (a crashed rank truncates
mid-line), so :func:`tolerant_load_events` falls back to per-line
parsing when the strict ``json.load`` fails.
"""

from __future__ import annotations

import importlib
import json
import os
from typing import Dict, Iterable, List, Optional

# importlib, not ``from horovod_tpu.telemetry import spans``: the
# package's ``spans()`` accessor shadows the submodule attribute, so the
# attribute-based import form would return the function.
spans_mod = importlib.import_module("horovod_tpu.telemetry.spans")


def tolerant_load_events(path: str) -> List[dict]:
    """Load a Chrome-tracing JSON file, surviving truncation.

    Accepts the three shapes in the wild: a plain event array, the
    ``{"traceEvents": [...]}`` wrapper, and the streaming one-object-
    per-line dialect of ``eager_timeline.py``/``timeline.cc`` (leading
    ``[``, trailing comma per line, terminator optional).  A final line
    cut mid-object is dropped, not fatal.
    """
    with open(path) as f:
        text = f.read()
    try:
        doc = json.loads(text)
        if isinstance(doc, dict):
            return list(doc.get("traceEvents", []))
        return list(doc)
    except ValueError:
        pass
    events: List[dict] = []
    for line in text.splitlines():
        line = line.strip().rstrip(",")
        if not line or line in ("[", "]"):
            continue
        try:
            ev = json.loads(line)
        except ValueError:
            continue   # truncated tail of a crashed writer
        if isinstance(ev, dict):
            events.append(ev)
    return events


def spans_doc_to_events(doc: dict, apply_offset: bool = True,
                        tid_base: Optional[Dict[str, int]] = None
                        ) -> List[dict]:
    """One rank's ``trace.v1`` document as Chrome events.

    ``ts``/``dur`` are microseconds on the launcher clock (rank clock
    plus the document's measured ``clock_offset``; unmeasured = 0, which
    is exact for same-host jobs).
    """
    rank = int(doc.get("rank", 0))
    offset = float(doc.get("clock_offset") or 0.0) if apply_offset else 0.0
    host = doc.get("host", "")
    events: List[dict] = [{
        "name": "process_name", "ph": "M", "pid": rank,
        "args": {"name": f"rank {rank}" + (f" ({host})" if host else "")},
    }]
    tids: Dict[str, int] = dict(tid_base or {})
    next_tid = max(tids.values(), default=0) + 1
    for s in doc.get("spans", []):
        name = s.get("name", "?")
        tid = tids.get(name)
        if tid is None:
            tid = next_tid
            next_tid += 1
            tids[name] = tid
            events.append({"name": "thread_name", "ph": "M", "pid": rank,
                           "tid": tid, "args": {"name": name}})
        t0 = float(s.get("t0", 0.0)) + offset
        t1 = float(s.get("t1", t0)) + offset
        events.append({
            "name": f"{name}:{s.get('phase', '?')}",
            "ph": "X", "pid": rank, "tid": tid,
            "ts": int(t0 * 1e6),
            "dur": max(int((t1 - t0) * 1e6), 1),
            "args": {"trace_id": s.get("trace_id"),
                     "phase": s.get("phase"), "seq": s.get("seq"),
                     "bytes": s.get("bytes", 0)},
        })
    return events


def merge_span_docs(docs: Iterable[dict]) -> List[dict]:
    """Merge several ranks' documents into one event list, sorted by
    corrected timestamp (metadata events first, as viewers expect)."""
    meta: List[dict] = []
    body: List[dict] = []
    for doc in docs:
        for ev in spans_doc_to_events(doc):
            (meta if ev.get("ph") == "M" else body).append(ev)
    body.sort(key=lambda e: e.get("ts", 0))
    return meta + body


def merge_chrome_traces(paths: Iterable[str],
                        offsets: Optional[Dict[int, float]] = None
                        ) -> List[dict]:
    """Merge per-rank Chrome-tracing files (eager/native timelines) into
    one event list, shifting each event by its ``pid``'s offset from
    ``offsets`` (seconds to ADD — e.g. the measured launcher-minus-rank
    clock offset).  Events keep their pid (already the rank in both
    writer dialects)."""
    offsets = offsets or {}
    meta: List[dict] = []
    body: List[dict] = []
    for path in paths:
        for ev in tolerant_load_events(path):
            if ev.get("ph") == "M":
                meta.append(ev)
                continue
            off = offsets.get(int(ev.get("pid", 0)))
            if off and "ts" in ev:
                ev = dict(ev)
                ev["ts"] = int(ev["ts"] + off * 1e6)
            body.append(ev)
    body.sort(key=lambda e: e.get("ts", 0))
    return meta + body


def write_chrome(events: List[dict], path: str) -> str:
    """Atomic write in the ``traceEvents`` wrapper (loads in Perfetto
    and chrome://tracing alike)."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f,
                  indent=None, separators=(",", ":"))
        f.write("\n")
    os.replace(tmp, path)
    return path


def load_rank_docs(dir_path: str) -> Dict[int, dict]:
    """The per-rank ``spans.rank<k>.json`` fallback files of a trace
    directory, keyed by rank (skipping unparsable ones)."""
    docs: Dict[int, dict] = {}
    try:
        names = sorted(os.listdir(dir_path))
    except OSError:
        return docs
    for name in names:
        if not (name.startswith("spans.rank") and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(dir_path, name)) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(doc, dict) and doc.get("schema") == spans_mod.SCHEMA:
            docs[int(doc.get("rank", 0))] = doc
    return docs


__all__ = ["tolerant_load_events", "spans_doc_to_events",
           "merge_span_docs", "merge_chrome_traces", "write_chrome",
           "load_rank_docs"]

"""Unified telemetry: metrics registry, exporters, eager timeline.

The observability base the reference never had (its story was the rank-0
Chrome timeline plus stall warnings): every layer of this rebuild — the
eager collectives, the native wait paths, the fusion bucketer, the RPC
plane, the elastic launcher and the checkpointer — records counters,
gauges and latency histograms here, and three export paths read them:

* ``HOROVOD_METRICS_PORT=9090`` — Prometheus text format on a stdlib
  HTTP server (per-rank port = base + local rank);
* ``HOROVOD_METRICS_FILE=/path/m.json`` — at-exit JSON dump per rank;
  under ``hvdrun`` the launcher also collects every rank's snapshot over
  the RPC plane and writes one merged, per-rank-attributed summary;
* ``hvd.metrics_snapshot()`` — the in-process API.

Separately, ``HOROVOD_EAGER_TIMELINE=/path/t.json`` enables the
eager-plane Chrome-tracing writer (per-tensor SUBMIT/WAIT/FINISH rows,
same dialect as the native timeline — see ``eager_timeline.py``).

The no-op contract
------------------
With every telemetry variable unset, instrumented hot paths must cost
one function call and a boolean test — nothing else.  Call sites are
written as::

    if telemetry.enabled():
        telemetry.counter("hvd_eager_ops_total", op="allreduce").inc()

and :func:`counter`/:func:`gauge`/:func:`histogram` additionally return
the shared :data:`NOOP` object when disabled, so even an unguarded call
allocates nothing and mutates nothing (asserted by
``tests/test_telemetry.py::test_disabled_path_is_noop``).
``HOROVOD_METRICS=1`` turns collection on without any export path (for
``hvd.metrics_snapshot()`` users).
"""

from __future__ import annotations

import atexit
import os
import time
from typing import Dict, Optional

from horovod_tpu.telemetry.registry import (  # noqa: F401  (re-export)
    DEFAULT_BANDWIDTH_BUCKETS,
    DEFAULT_BYTE_BUCKETS,
    DEFAULT_TIME_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)

clock = time.monotonic   # one clock for every duration metric + timeline

_ENV_VARS = ("HOROVOD_METRICS", "HOROVOD_METRICS_PORT",
             "HOROVOD_METRICS_FILE", "HOROVOD_METRICS_RPC")
# Span tracing (HOROVOD_TRACE / _DIR / _RPC) is configured alongside but
# independently of metrics, like the eager timeline: telemetry.spans()
# returns None when every trace variable is unset.


class _Noop:
    """Shared do-nothing metric: accepts every mutator of Counter, Gauge
    and Histogram.  Identity-comparable (``is telemetry.NOOP``) so tests
    can assert the disabled path was taken."""

    __slots__ = ()

    def inc(self, amount: float = 1.0) -> None:
        pass

    def dec(self, amount: float = 1.0) -> None:
        pass

    def set(self, value: float) -> None:
        pass

    def observe(self, value: float) -> None:
        pass


NOOP = _Noop()

_registry = MetricsRegistry()
_enabled = False
_timeline = None          # EagerTimelineWriter or None
_spans = None             # spans.SpanRecorder or None
_span_flush_hooks = []    # callables draining foreign span buffers
_metrics_flush_hooks = []  # callables mirroring foreign counters in
_http_server = None
_configured = False


def _env_truthy(name: str) -> bool:
    return os.environ.get(name, "").strip() not in ("", "0", "false")


def _configure_from_env() -> None:
    """Resolve enablement and export paths from the environment.  Runs
    once at first import (i.e. before any instrumented op can fire);
    :func:`reset_for_tests` re-runs it after monkeypatching."""
    global _enabled, _timeline, _http_server, _configured, _spans
    _configured = True
    # HOROVOD_METRICS is a boolean toggle ("0"/"false" disable); the
    # export-path variables enable whenever non-empty — including
    # HOROVOD_METRICS_PORT=0, which binds an ephemeral scrape port.
    _enabled = _env_truthy("HOROVOD_METRICS") or any(
        os.environ.get(v, "").strip()
        for v in _ENV_VARS if v != "HOROVOD_METRICS")

    port = os.environ.get("HOROVOD_METRICS_PORT", "").strip()
    if port and _http_server is None:
        from horovod_tpu.telemetry import exporter
        _http_server = exporter.start_http_server(
            exporter.resolve_metrics_port(int(port)),
            _registry.render_prometheus, _registry.snapshot)

    tl_path = os.environ.get("HOROVOD_EAGER_TIMELINE", "").strip()
    if tl_path and _timeline is None:
        from horovod_tpu.telemetry.eager_timeline import (
            EagerTimelineWriter, per_rank_path)
        _timeline = EagerTimelineWriter(
            per_rank_path(tl_path),
            rank=int(os.environ.get("HOROVOD_RANK", "0") or 0))

    if _spans is None:
        # importlib, not ``from ... import spans``: the :func:`spans`
        # accessor below shadows the submodule as a package attribute,
        # so an attribute-based import would grab the function.
        import importlib
        _spans = importlib.import_module(
            "horovod_tpu.telemetry.spans").configured_recorder()


def _at_exit() -> None:
    """Flush every export path.  File/RPC targets are re-read from the
    environment HERE (not at configure time) so the launcher's per-rank
    overrides and late ``os.environ`` edits are honored."""
    global _timeline, _spans
    if _timeline is not None:
        _timeline.close()
        _timeline = None
    if _spans is not None:
        # Upstream planes (the native runtime's C++ buffer) flush into
        # the recorder first: this atexit handler can run BEFORE
        # basics.shutdown() (LIFO — basics registers its hook earlier,
        # at import), so without the explicit flush the native spans
        # would drain into an already-closed recorder and vanish.
        for hook in list(_span_flush_hooks):
            try:
                hook()
            except Exception:
                pass
        # Span export runs BEFORE the metrics push so the recorder's
        # hvd_trace_* totals land in this rank's metrics snapshot.
        # (importlib: the spans() accessor shadows the submodule.)
        import importlib
        spans_mod = importlib.import_module("horovod_tpu.telemetry.spans")
        try:
            spans_mod.export_at_exit(_spans)
        except Exception:
            pass  # exit path: tracing must never mask the job's rc
        _spans = None
    if not _enabled:
        return
    # Foreign metric planes (the native runtime's counter matrices)
    # mirror into the registry NOW: this handler can run before
    # basics.shutdown() (LIFO), so without the explicit flush a short
    # job's final deltas would miss the snapshot below.
    for hook in list(_metrics_flush_hooks):
        try:
            hook()
        except Exception:
            pass
    from horovod_tpu.telemetry import exporter
    endpoint = os.environ.get("HOROVOD_METRICS_RPC", "").strip()
    if endpoint:
        # Satellite of the trace plane that works even with tracing off:
        # measure this rank's monotonic-clock offset against the
        # launcher over the same collector the metrics push targets, so
        # the merged summary can attribute cross-host skew.
        skew = exporter.measure_launcher_offset(endpoint)
        if skew is not None:
            gauge("hvd_clock_skew_seconds",
                  "Monotonic-clock offset vs the launcher (launcher "
                  "minus rank, RTT-halving estimate)").set(skew[0])
        exporter.push_to_launcher(endpoint, _registry.snapshot)
    path = os.environ.get("HOROVOD_METRICS_FILE", "").strip()
    if path:
        try:
            from horovod_tpu.telemetry.eager_timeline import per_rank_path
            exporter.write_json(per_rank_path(path), _registry.snapshot)
        except OSError:
            pass  # exit path: an unwritable target must not mask the rc


atexit.register(_at_exit)
_configure_from_env()


# ---------------------------------------------------------------------------
# Hot-path API
# ---------------------------------------------------------------------------

def enabled() -> bool:
    """The one branch every instrumentation site tests first."""
    return _enabled


def timeline():
    """The eager timeline writer, or None when HOROVOD_EAGER_TIMELINE is
    unset (the timeline's own no-op guard, independent of metrics).
    Named ``timeline`` — not ``eager_timeline`` — because that attribute
    is the submodule holding the writer class."""
    return _timeline


def spans():
    """The distributed span recorder, or None when tracing is off (the
    tracing plane's own no-op guard, independent of metrics — see
    ``spans.py``)."""
    return _spans


def register_span_flush_hook(fn) -> None:
    """Register a callable that moves buffered spans from another plane
    (the native runtime's C++ buffer) into the recorder.  Hooks run
    right before the at-exit span export, which can precede
    ``basics.shutdown()`` in atexit order."""
    if fn not in _span_flush_hooks:
        _span_flush_hooks.append(fn)


def unregister_span_flush_hook(fn) -> None:
    try:
        _span_flush_hooks.remove(fn)
    except ValueError:
        pass


def register_metrics_flush_hook(fn) -> None:
    """Register a callable that mirrors another plane's counters (the
    native runtime's transport/hier matrices) into the registry.  Hooks
    run at exit right before the metrics push/dump, which can precede
    ``basics.shutdown()`` in atexit order — without them a short job's
    final deltas would never land in the snapshot."""
    if fn not in _metrics_flush_hooks:
        _metrics_flush_hooks.append(fn)


def unregister_metrics_flush_hook(fn) -> None:
    try:
        _metrics_flush_hooks.remove(fn)
    except ValueError:
        pass


def counter(name: str, help_text: str = "", **labels: str):
    if not _enabled:
        return NOOP
    return _registry.counter(name, help_text, labels or None)


def gauge(name: str, help_text: str = "", **labels: str):
    if not _enabled:
        return NOOP
    return _registry.gauge(name, help_text, labels or None)


def histogram(name: str, help_text: str = "", bounds=None, **labels: str):
    if not _enabled:
        return NOOP
    return _registry.histogram(name, help_text, labels or None,
                               bounds=bounds)


def observe_op(op: str, seconds: float, nbytes: int = 0) -> None:
    """One-call recorder for a completed eager collective: count,
    latency histogram, byte counter, effective-bandwidth histogram."""
    if not _enabled:
        return
    counter("hvd_eager_ops_total",
            "Completed eager-plane collective operations", op=op).inc()
    histogram("hvd_eager_op_seconds",
              "Eager collective latency, submit to completion (seconds)",
              bounds=DEFAULT_TIME_BUCKETS, op=op).observe(seconds)
    if nbytes:
        counter("hvd_eager_bytes_total",
                "Payload bytes submitted to eager collectives",
                op=op).inc(nbytes)
        histogram("hvd_eager_bandwidth_bytes_per_second",
                  "Effective eager collective bandwidth (payload bytes / "
                  "op latency)", bounds=DEFAULT_BANDWIDTH_BUCKETS,
                  op=op).observe(nbytes / max(seconds, 1e-9))


# ---------------------------------------------------------------------------
# Snapshot / lifecycle API
# ---------------------------------------------------------------------------

def registry() -> MetricsRegistry:
    return _registry


def metrics_snapshot() -> Dict[str, dict]:
    """The current registry contents (``hvd.metrics_snapshot()``).
    Empty when telemetry never ran — enable collection with any metrics
    env var or :func:`configure`."""
    return _registry.snapshot()


def render_prometheus() -> str:
    return _registry.render_prometheus()


def configure(enabled_flag: Optional[bool] = None) -> None:
    """Programmatic enable/disable (the launcher turns its own registry
    on with this when ``--metrics-file`` is passed; libraries embedding
    horovod_tpu can do the same without env vars)."""
    global _enabled
    if enabled_flag is not None:
        _enabled = bool(enabled_flag)


def flush() -> None:
    """Write every configured export target now (normally runs at
    interpreter exit; explicit for long-lived drivers and tests)."""
    _at_exit()


def reset_for_tests() -> None:
    """Clear the registry and re-resolve the environment.  Test-only:
    tears down the timeline writer (without terminator) and forgets a
    previously started HTTP server reference (daemon thread; freed at
    process exit)."""
    global _timeline, _http_server, _enabled, _spans
    if _timeline is not None:
        _timeline.close()
        _timeline = None
    if _spans is not None:
        _spans.close()
        _spans = None
    if _http_server is not None:
        _http_server.shutdown()
        _http_server = None
    _registry.clear()
    _configure_from_env()

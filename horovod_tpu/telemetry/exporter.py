"""Export paths for the metrics registry.

Three surfaces, all zero-dependency:

* :func:`start_http_server` — a stdlib ``ThreadingHTTPServer`` serving
  Prometheus text format on ``/metrics`` (and the raw JSON snapshot on
  ``/metrics.json``), the scrape endpoint ``HOROVOD_METRICS_PORT``
  enables.  Multiple ranks on one host offset the port by
  ``HOROVOD_LOCAL_RANK`` so every rank is scrapeable.
* :func:`write_json` — the ``HOROVOD_METRICS_FILE`` at-exit dump: one
  self-describing ``horovod_tpu.metrics.v1`` document per rank.
* :func:`push_to_launcher` — ships the same document to the launcher's
  metrics collector over the existing authenticated RPC plane
  (``runner/rpc.py``); ``hvdrun --metrics-file`` merges the per-rank
  reports into one summary (``telemetry/aggregate.py``).
"""

from __future__ import annotations

import json
import os
import socket
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional


def snapshot_document(snapshot_fn: Callable[[], dict]) -> dict:
    """The per-rank JSON payload: snapshot plus attribution envelope."""
    return {
        "schema": "horovod_tpu.metrics.v1",
        "rank": int(os.environ.get("HOROVOD_RANK", "0") or 0),
        "size": int(os.environ.get("HOROVOD_SIZE", "1") or 1),
        "host": socket.gethostname(),
        "pid": os.getpid(),
        "restart_attempt": int(
            os.environ.get("HOROVOD_RESTART_ATTEMPT", "0") or 0),
        "metrics": snapshot_fn(),
    }


def write_json(path: str, snapshot_fn: Callable[[], dict]) -> str:
    """Atomically write the per-rank document (write + rename so a
    crash mid-dump never leaves a half-written file for the launcher's
    merge pass to choke on)."""
    doc = snapshot_document(snapshot_fn)
    tmp = f"{path}.tmp.{os.getpid()}"
    dirname = os.path.dirname(os.path.abspath(path))
    os.makedirs(dirname, exist_ok=True)
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return path


def push_to_launcher(endpoint: str, snapshot_fn: Callable[[], dict],
                     timeout: float = 5.0) -> bool:
    """Report this rank's metrics to the launcher's collector
    (``HOROVOD_METRICS_RPC=host:port``), authenticated with the job
    secret.  Failures are swallowed — this runs on the interpreter-exit
    path, where the launcher may already be tearing the job down; the
    launcher falls back to the rank's JSON file."""
    from horovod_tpu.runner import rpc
    try:
        host, port = endpoint.rsplit(":", 1)
        key = rpc.job_key_bytes(os.environ.get("HOROVOD_SECRET_KEY"))
        resp = rpc.rpc_call(
            host, int(port),
            {"kind": "metrics_report",
             "report": snapshot_document(snapshot_fn)},
            key, timeout=timeout, retries=1)
        return bool(resp)
    except Exception:  # noqa: BLE001 — best-effort exit-path reporting
        return False


def measure_launcher_offset(endpoint: str):
    """This rank's monotonic-clock offset against the launcher's
    collector (``host:port``): ``(offset_seconds, rtt_seconds)`` from
    the RTT-halving handshake in ``runner/rpc.py``, or None when the
    collector is unreachable or predates the ``time_sync`` kind.  Runs
    on the exit path, so every failure is swallowed."""
    try:
        from horovod_tpu.runner import rpc
        host, port = endpoint.rsplit(":", 1)
        key = rpc.job_key_bytes(os.environ.get("HOROVOD_SECRET_KEY"))
        return rpc.measure_clock_offset(host, int(port), key)
    except Exception:  # noqa: BLE001 — best-effort exit-path handshake
        return None


class _MetricsHandler(BaseHTTPRequestHandler):
    # Class attributes injected by start_http_server via type().
    render_prometheus: Callable[[], str]
    snapshot_fn: Callable[[], dict]

    def do_GET(self):  # noqa: N802 — http.server API
        if self.path in ("/", "/metrics"):
            body = self.render_prometheus().encode()
            ctype = "text/plain; version=0.0.4; charset=utf-8"
        elif self.path == "/metrics.json":
            body = (json.dumps(snapshot_document(self.snapshot_fn),
                               indent=1, sort_keys=True) + "\n").encode()
            ctype = "application/json"
        else:
            self.send_error(404)
            return
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *args):  # scrapes must not spam stderr
        del fmt, args


def start_http_server(port: int, render_prometheus: Callable[[], str],
                      snapshot_fn: Callable[[], dict],
                      bind: str = "0.0.0.0") -> ThreadingHTTPServer:
    """Serve the registry on ``bind:port`` from a daemon thread; returns
    the server (``server.server_address[1]`` is the bound port — pass
    ``port=0`` for an ephemeral one in tests).

    With several ranks per host the caller offsets ``port`` by
    ``HOROVOD_LOCAL_RANK`` (see ``telemetry/__init__.py``); a bind
    failure raises so a misconfigured job fails loudly rather than
    silently serving no metrics.
    """
    handler = type("Handler", (_MetricsHandler,), {
        "render_prometheus": staticmethod(render_prometheus),
        "snapshot_fn": staticmethod(snapshot_fn),
    })
    try:
        server = ThreadingHTTPServer((bind, port), handler)
    except OSError as e:
        job = os.environ.get("HOROVOD_FLEET_JOB", "")
        local_rank = os.environ.get("HOROVOD_LOCAL_RANK", "0")
        raise OSError(
            f"metrics exporter cannot bind {bind}:{port} "
            f"(local rank {local_rank}"
            + (f", fleet job {job!r}" if job else "")
            + f"): {e}. Two jobs sharing a host must use distinct "
            f"HOROVOD_METRICS_PORT bases — under hvdfleet set "
            f"--metrics-port-base/--port-stride so per-job ranges "
            f"(base + job_index*stride + local_rank) cannot overlap."
        ) from e
    server.daemon_threads = True
    thread = threading.Thread(target=server.serve_forever,
                              name="hvd-metrics-http", daemon=True)
    thread.start()
    return server


def resolve_metrics_port(base_port: int) -> int:
    """Per-rank scrape port: base + local rank (documented in
    docs/metrics.md so operators can enumerate scrape targets)."""
    local_rank = int(os.environ.get("HOROVOD_LOCAL_RANK", "0") or 0)
    return base_port + local_rank

"""Metrics registry: counters, gauges, fixed-bucket histograms.

The reference has no numeric metrics layer at all — its observability is
the Chrome timeline (``common/timeline.cc``) plus stall warnings
(``common/stall_inspector.cc``).  This registry is the missing half: a
zero-dependency, thread-safe store an operator can scrape (Prometheus
text format), dump (JSON), or read in-process (``hvd.metrics_snapshot``).

Design constraints:

* **Zero dependencies** — stdlib only, importable on every rank and in
  the launcher process.
* **Thread-safe** — the eager worker pool, the native wait paths, the
  RPC server threads and the watchdog all record concurrently; every
  mutation happens under a per-metric lock.
* **Fixed buckets** — histograms take their bucket bounds at creation
  (Prometheus ``le`` semantics: a bucket counts observations ``<= bound``,
  with an implicit ``+Inf``).  No dynamic resizing: cross-rank
  aggregation (``horovod_tpu/telemetry/aggregate.py``) needs every rank's
  histogram of a given name to share bounds.
* **Labels** are plain ``str -> str`` dicts; a (name, label-set) pair
  identifies a child time series, as in the Prometheus client data model.

The no-op fast path when telemetry is disabled lives one level up, in
``horovod_tpu/telemetry/__init__.py`` — this module is always "on"; the
package front door decides whether call sites ever reach it.
"""

from __future__ import annotations

import bisect
import threading
from typing import Dict, List, Optional, Sequence, Tuple

# Default latency buckets (seconds): spans sub-millisecond eager completions
# through multi-second stalls.  Shared by every *_seconds histogram so
# cross-rank merges always line up.
DEFAULT_TIME_BUCKETS = (
    0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 60.0)

# Default byte-size buckets: 256 B .. 1 GiB in ~16x steps.
DEFAULT_BYTE_BUCKETS = (
    256.0, 4096.0, 65536.0, 1048576.0, 16777216.0, 268435456.0, 1073741824.0)

# Default bandwidth buckets (bytes/second): 1 MB/s .. 100 GB/s.
DEFAULT_BANDWIDTH_BUCKETS = (
    1e6, 1e7, 1e8, 1e9, 1e10, 1e11)

_LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Optional[Dict[str, str]]) -> _LabelKey:
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class Counter:
    """Monotonically increasing value."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"counter increments must be >= 0, got {amount}")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Value that can go up and down (queue depths, inflight counts)."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value -= amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """Fixed-bucket histogram with Prometheus ``le`` (<=) semantics.

    ``bucket_counts[i]`` counts observations ``<= bounds[i]``
    (NON-cumulative internally; the Prometheus renderer cumulates).  The
    final slot counts the ``+Inf`` overflow.
    """

    __slots__ = ("_lock", "bounds", "_counts", "_sum", "_count")

    def __init__(self, bounds: Sequence[float]):
        bounds = tuple(float(b) for b in bounds)
        if not bounds:
            raise ValueError("histogram needs at least one bucket bound")
        if list(bounds) != sorted(bounds):
            raise ValueError(f"bucket bounds must be ascending: {bounds}")
        self._lock = threading.Lock()
        self.bounds = bounds
        self._counts = [0] * (len(bounds) + 1)   # + the +Inf slot
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float) -> None:
        # bisect_left gives the first bound >= value, i.e. the Prometheus
        # "le" bucket; values beyond every bound land in the +Inf slot.
        idx = bisect.bisect_left(self.bounds, value)
        with self._lock:
            self._counts[idx] += 1
            self._sum += value
            self._count += 1

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    def buckets(self) -> Dict[str, int]:
        """Non-cumulative per-bucket counts keyed by upper bound (the JSON
        form; ``+Inf`` key for the overflow slot)."""
        with self._lock:
            counts = list(self._counts)
        out = {repr(b): counts[i] for i, b in enumerate(self.bounds)}
        out["+Inf"] = counts[-1]
        return out


class _Family:
    """All children (label sets) of one metric name."""

    __slots__ = ("kind", "help", "bounds", "children")

    def __init__(self, kind: str, help_text: str,
                 bounds: Optional[Sequence[float]] = None):
        self.kind = kind
        self.help = help_text
        self.bounds = tuple(bounds) if bounds else None
        self.children: Dict[_LabelKey, object] = {}


_VALID_NAME = __import__("re").compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")


class MetricsRegistry:
    """Thread-safe registry of metric families.

    ``counter``/``gauge``/``histogram`` are get-or-create: the first call
    for a (name, labels) pair creates the child, later calls return the
    same object — call sites can therefore re-resolve on the hot path
    without caching (one dict lookup under the registry lock).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._families: Dict[str, _Family] = {}

    def _get(self, kind: str, name: str, help_text: str,
             labels: Optional[Dict[str, str]],
             bounds: Optional[Sequence[float]] = None):
        if not _VALID_NAME.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        key = _label_key(labels)
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = _Family(kind, help_text, bounds)
                self._families[name] = fam
            elif fam.kind != kind:
                raise ValueError(
                    f"metric {name!r} already registered as {fam.kind}, "
                    f"cannot re-register as {kind}")
            child = fam.children.get(key)
            if child is None:
                if kind == "counter":
                    child = Counter()
                elif kind == "gauge":
                    child = Gauge()
                else:
                    child = Histogram(fam.bounds or DEFAULT_TIME_BUCKETS)
                fam.children[key] = child
            return child

    def counter(self, name: str, help_text: str = "",
                labels: Optional[Dict[str, str]] = None) -> Counter:
        return self._get("counter", name, help_text, labels)

    def gauge(self, name: str, help_text: str = "",
              labels: Optional[Dict[str, str]] = None) -> Gauge:
        return self._get("gauge", name, help_text, labels)

    def histogram(self, name: str, help_text: str = "",
                  labels: Optional[Dict[str, str]] = None,
                  bounds: Optional[Sequence[float]] = None) -> Histogram:
        return self._get("histogram", name, help_text, labels, bounds)

    def clear(self) -> None:
        with self._lock:
            self._families.clear()

    # -- export ------------------------------------------------------------

    def snapshot(self) -> Dict[str, dict]:
        """JSON-able dict of every family and child.

        Shape (the ``horovod_tpu.metrics.v1`` per-rank payload)::

            {name: {"type": ..., "help": ...,
                    "values": [{"labels": {...}, "value": v}            # counter/gauge
                               | {"labels": {...}, "sum": s, "count": c,
                                  "buckets": {"0.001": n, ..., "+Inf": m}}]}}
        """
        with self._lock:
            families = {n: (f, dict(f.children))
                        for n, f in self._families.items()}
        out: Dict[str, dict] = {}
        for name in sorted(families):
            fam, children = families[name]
            values: List[dict] = []
            for key in sorted(children):
                child = children[key]
                entry: dict = {"labels": dict(key)}
                if fam.kind == "histogram":
                    entry["sum"] = child.sum
                    entry["count"] = child.count
                    entry["buckets"] = child.buckets()
                else:
                    entry["value"] = child.value
                values.append(entry)
            out[name] = {"type": fam.kind, "help": fam.help,
                         "values": values}
        return out

    def render_prometheus(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        lines: List[str] = []
        snap = self.snapshot()
        for name, fam in snap.items():
            if fam["help"]:
                lines.append(f"# HELP {name} {_escape_help(fam['help'])}")
            lines.append(f"# TYPE {name} {fam['type']}")
            for entry in fam["values"]:
                labels = entry["labels"]
                if fam["type"] == "histogram":
                    # Cumulate the per-bucket counts for the wire format.
                    cum = 0
                    buckets = entry["buckets"]
                    for bound in sorted((b for b in buckets if b != "+Inf"),
                                        key=float):
                        cum += buckets[bound]
                        lines.append(_sample(
                            name + "_bucket",
                            dict(labels, le=_format_bound(bound)), cum))
                    cum += buckets["+Inf"]
                    lines.append(_sample(name + "_bucket",
                                         dict(labels, le="+Inf"), cum))
                    lines.append(_sample(name + "_sum", labels,
                                         entry["sum"]))
                    lines.append(_sample(name + "_count", labels,
                                         entry["count"]))
                else:
                    lines.append(_sample(name, labels, entry["value"]))
        return "\n".join(lines) + ("\n" if lines else "")


def _escape_help(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(value: str) -> str:
    return (value.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _format_bound(bound: str) -> str:
    # repr(float) round-trips exactly; Prometheus just wants a float token.
    f = float(bound)
    return repr(int(f)) + ".0" if f == int(f) else repr(f)


def _format_value(v) -> str:
    if isinstance(v, float) and v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v) if isinstance(v, float) else str(v)


def _sample(name: str, labels: Dict[str, str], value) -> str:
    if labels:
        inner = ",".join(f'{k}="{_escape_label(str(v))}"'
                         for k, v in sorted(labels.items()))
        return f"{name}{{{inner}}} {_format_value(value)}"
    return f"{name} {_format_value(value)}"

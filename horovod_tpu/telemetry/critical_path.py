"""Per-step critical-path analysis and straggler attribution.

Input: the per-rank ``horovod_tpu.trace.v1`` span documents the
launcher collected (``hvdrun --trace``; ``tools/hvdtrace`` offline).
Every collective span carries the cross-rank correlation id
``trace_id = f(name, occurrence)``, so one logical step of one
collective is simply the group of spans sharing a ``trace_id`` across
all documents.

For each step the analysis computes, on the launcher-corrected clock:

* per-rank wall time (last span end minus first span start on that
  rank) — the rank's total involvement in the step;
* the **slowest rank** (the critical path runs through it) and every
  other rank's **slack** (how long it waited on the straggler);
* the **dominant phase** on the slowest rank — which of
  negotiate / fuse / local / cross / wait the straggler actually spent
  its time in, bucketing the fine-grained span phases
  (``local_rs``/``local_ag`` -> ``local``, ``cross_ring`` -> ``cross``,
  ...);
* the step's **attributable delay**: slowest wall minus second-slowest
  wall — the wall-clock the job would save if the straggler matched the
  runner-up.  Attribution accumulates per ``(rank, phase)`` pair, so
  the report's top line reads "rank 3 loses 1.2 s in cross".

Request-scoped spans (``rpc``/``route``/``decode``/``broadcast``) are
excluded from step grouping — they have no occurrence stream — but the
serving/RPC planes still appear in the merged trace itself.

Gauge emission lives HERE (inside ``horovod_tpu/``, not the
``tools/hvdtrace`` CLI) so the hvdlint metrics-drift rule verifies the
``hvd_critical_path_*`` series against ``docs/metrics.md``.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Tuple

from horovod_tpu import telemetry
from horovod_tpu.telemetry import aggregate

# Fine-grained span phase -> report bucket.  ``submit`` stays its own
# bucket (Python-side enqueue cost); ``exec`` is the single-process
# whole-op span and books as cross (it IS the transport there).
PHASE_BUCKET = {
    "submit": "submit",
    "negotiate": "negotiate",
    "coord": "negotiate",
    "fuse": "fuse",
    "local_rs": "local",
    "local_ag": "local",
    "cross_ring": "cross",
    "cross": "cross",
    "exec": "cross",
    "transport": "transport",
    "wait": "wait",
}

# Request-scoped phases: correlated by unique name, not by occurrence —
# never part of a collective step.
REQUEST_PHASES = frozenset({"rpc", "route", "decode", "broadcast"})


def analyze(reports: Dict[int, dict], top_k: int = 5) -> dict:
    """Critical-path summary over ``{rank: trace.v1 document}``.

    Returns a plain dict (JSON-ready): per-step details, per-rank slack
    and slowest counts, per-phase attributed seconds, the top-K
    ``(rank, phase)`` straggler attribution, and step-wall percentiles
    estimated through :func:`aggregate.estimate_percentiles` over the
    standard time buckets (the same estimator the merged metrics
    summary uses).
    """
    # trace_id -> rank -> [(t0, t1, phase)] on the corrected clock.
    steps: Dict[str, Dict[int, List[Tuple[float, float, str]]]] = {}
    names: Dict[str, Tuple[str, int]] = {}
    for rank, doc in reports.items():
        offset = float(doc.get("clock_offset") or 0.0)
        for s in doc.get("spans", []):
            phase = s.get("phase", "")
            if phase in REQUEST_PHASES:
                continue
            tid = s.get("trace_id")
            if not tid:
                continue
            t0 = float(s.get("t0", 0.0)) + offset
            t1 = float(s.get("t1", t0)) + offset
            steps.setdefault(tid, {}).setdefault(int(rank), []).append(
                (t0, t1, phase))
            names.setdefault(tid, (s.get("name", "?"),
                                   int(s.get("seq", 0))))

    ranks = sorted(int(r) for r in reports)
    slowest_counts: Dict[int, int] = {r: 0 for r in ranks}
    slack_seconds: Dict[int, float] = {r: 0.0 for r in ranks}
    phase_seconds: Dict[str, float] = {}
    attribution: Dict[Tuple[int, str], Dict[str, float]] = {}
    step_rows: List[dict] = []
    wall_buckets: Dict[str, int] = {}

    for tid, by_rank in steps.items():
        walls = {r: max(t1 for _, t1, _ in spans)
                 - min(t0 for t0, _, _ in spans)
                 for r, spans in by_rank.items()}
        slowest = max(walls, key=lambda r: walls[r])
        ordered = sorted(walls.values(), reverse=True)
        second = ordered[1] if len(ordered) > 1 else ordered[0]
        delay = max(walls[slowest] - second, 0.0)
        # Dominant phase: where the straggler's time actually went.
        by_bucket: Dict[str, float] = {}
        for t0, t1, phase in by_rank[slowest]:
            b = PHASE_BUCKET.get(phase, phase or "?")
            by_bucket[b] = by_bucket.get(b, 0.0) + max(t1 - t0, 0.0)
        dominant = max(by_bucket, key=lambda b: by_bucket[b]) \
            if by_bucket else "?"

        slowest_counts[slowest] = slowest_counts.get(slowest, 0) + 1
        for r, w in walls.items():
            slack_seconds[r] = slack_seconds.get(r, 0.0) + \
                max(walls[slowest] - w, 0.0)
        phase_seconds[dominant] = phase_seconds.get(dominant, 0.0) + delay
        a = attribution.setdefault((slowest, dominant),
                                   {"seconds": 0.0, "steps": 0})
        a["seconds"] += delay
        a["steps"] += 1

        step_wall = walls[slowest]
        # Bucket the wall for the shared percentile estimator.
        placed = False
        for bound in telemetry.DEFAULT_TIME_BUCKETS:
            if step_wall <= bound:
                key = repr(float(bound))
                wall_buckets[key] = wall_buckets.get(key, 0) + 1
                placed = True
                break
        if not placed:
            wall_buckets["+Inf"] = wall_buckets.get("+Inf", 0) + 1

        name, seq = names[tid]
        step_rows.append({
            "trace_id": tid, "name": name, "seq": seq,
            "wall_seconds": step_wall, "slowest_rank": slowest,
            "dominant_phase": dominant, "delay_seconds": delay,
            "ranks": sorted(walls),
        })

    step_rows.sort(key=lambda s: s["delay_seconds"], reverse=True)
    top = sorted(
        ({"rank": r, "phase": p, "seconds": v["seconds"],
          "steps": int(v["steps"])}
         for (r, p), v in attribution.items()),
        key=lambda a: a["seconds"], reverse=True)[:top_k]
    return {
        "schema": "horovod_tpu.critical_path.v1",
        "steps": len(steps),
        "ranks": ranks,
        "slowest_counts": {str(r): n for r, n in
                           sorted(slowest_counts.items())},
        "slack_seconds": {str(r): v for r, v in
                          sorted(slack_seconds.items())},
        "phase_seconds": dict(sorted(phase_seconds.items())),
        "attribution": top,
        "step_wall_percentiles": aggregate.estimate_percentiles(
            wall_buckets),
        "slowest_steps": step_rows[:max(top_k, 5)],
    }


def publish_gauges(result: dict) -> None:
    """Mirror the analysis into ``hvd_critical_path_*`` /
    ``hvd_trace_step_seconds`` gauges on the CALLING process's registry
    (the launcher, before it writes the merged metrics summary)."""
    if not telemetry.enabled():
        return
    telemetry.gauge(
        "hvd_critical_path_steps",
        "Collective steps covered by the critical-path analysis",
    ).set(float(result.get("steps", 0)))
    for r, n in result.get("slowest_counts", {}).items():
        telemetry.gauge(
            "hvd_critical_path_slowest_steps",
            "Steps on which this rank was the critical path",
            rank=str(r)).set(float(n))
    for r, v in result.get("slack_seconds", {}).items():
        telemetry.gauge(
            "hvd_critical_path_slack_seconds",
            "Total time this rank spent waiting on slower ranks",
            rank=str(r)).set(float(v))
    for p, v in result.get("phase_seconds", {}).items():
        telemetry.gauge(
            "hvd_critical_path_phase_seconds",
            "Attributable straggler delay by dominant phase",
            phase=str(p)).set(float(v))
    for q, v in result.get("step_wall_percentiles", {}).items():
        telemetry.gauge(
            "hvd_trace_step_seconds",
            "Critical-path step wall time percentile estimate",
            q=str(q)).set(float(v))


def format_report(result: dict, top_k: int = 5) -> str:
    """Human-readable straggler report for the hvdrun/hvdtrace CLI."""
    lines = [
        f"critical path: {result.get('steps', 0)} steps across ranks "
        f"{result.get('ranks', [])}"]
    pct = result.get("step_wall_percentiles") or {}
    if pct:
        lines.append("  step wall: " + "  ".join(
            f"{q}={v * 1e3:.2f}ms" for q, v in sorted(pct.items())))
    counts = result.get("slowest_counts") or {}
    if counts:
        worst = max(counts, key=lambda r: counts[r])
        lines.append(
            f"  slowest rank: {worst} (critical on {counts[worst]} of "
            f"{result.get('steps', 0)} steps)")
    slack = result.get("slack_seconds") or {}
    if slack:
        lines.append("  slack: " + "  ".join(
            f"rank{r}={v * 1e3:.2f}ms" for r, v in sorted(
                slack.items(), key=lambda kv: int(kv[0]))))
    top = (result.get("attribution") or [])[:top_k]
    if top:
        lines.append("  top straggler attribution:")
        for a in top:
            lines.append(
                f"    rank {a['rank']} / {a['phase']}: "
                f"{a['seconds'] * 1e3:.2f}ms over {a['steps']} steps")
    for s in (result.get("slowest_steps") or [])[:top_k]:
        lines.append(
            f"    worst step {s['name']}#{s['seq']}: "
            f"wall {s['wall_seconds'] * 1e3:.2f}ms on rank "
            f"{s['slowest_rank']} ({s['dominant_phase']}, "
            f"+{s['delay_seconds'] * 1e3:.2f}ms vs runner-up)")
    return "\n".join(lines)


__all__ = ["PHASE_BUCKET", "REQUEST_PHASES", "analyze",
           "publish_gauges", "format_report"]

"""Host reachability checks for the launcher.

Reference equivalents: ``run/run.py:59-112`` (parallel ssh probe of every
host before launching, so a dead host fails fast with a named error
instead of a mid-rendezvous hang) and ``run/util/cache.py`` (a ~/.horovod
JSON cache with 60-minute staleness so repeated launches skip the probe).
"""

from __future__ import annotations

import json
import os
import subprocess
import threading
import time
from typing import Callable, Dict, List, Optional

CACHE_STALENESS_SECS = 60 * 60   # reference: 60 minutes (cache.py)


def _default_cache_path() -> str:
    return os.path.join(os.path.expanduser("~"), ".horovod_tpu",
                        "reachability.json")


def _load_cache(path: str) -> Dict[str, float]:
    try:
        with open(path) as f:
            return {str(k): float(v) for k, v in json.load(f).items()}
    except (OSError, ValueError):
        return {}


def _store_cache(path: str, cache: Dict[str, float]) -> None:
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            json.dump(cache, f)
    except OSError:
        pass  # cache is an optimization, never a failure


def _default_ssh_builder(host: str) -> List[str]:
    ssh = os.environ.get("HOROVOD_SSH_CMD", "ssh")
    return [ssh, "-o", "StrictHostKeyChecking=no",
            "-o", "ConnectTimeout=10", host, "true"]


def probe_hosts(
        hosts: List[str],
        ssh_builder: Callable[[str], List[str]] = _default_ssh_builder,
        timeout: float = 30.0) -> Dict[str, bool]:
    """Parallel ssh probe of every host; never raises, never caches.

    This is the re-check the elastic restart loop runs between attempts:
    a host that just dropped a rank may be mid-reboot, and the hour-long
    success cache of :func:`check_hosts_reachable` would answer
    "reachable" from before the failure — exactly the stale answer the
    re-probe exists to avoid."""
    results: Dict[str, bool] = {}

    def probe(host: str) -> None:
        try:
            rc = subprocess.run(ssh_builder(host), timeout=timeout,
                                capture_output=True).returncode
            results[host] = rc == 0
        except (OSError, subprocess.TimeoutExpired):
            results[host] = False

    threads = [threading.Thread(target=probe, args=(h,)) for h in hosts]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return results


def check_hosts_reachable(
        hosts: List[str],
        ssh_builder: Callable[[str], List[str]] = _default_ssh_builder,
        cache_path: Optional[str] = None,
        timeout: float = 30.0) -> None:
    """Probe every host in parallel; raise listing the unreachable ones.

    Successful probes are cached for an hour keyed by host (reference
    run.py:59-112 + cache.py), so back-to-back launches don't pay an ssh
    round trip per host."""
    cache_path = cache_path or _default_cache_path()
    cache = _load_cache(cache_path)
    now = time.time()
    to_probe = [h for h in hosts
                if now - cache.get(h, 0.0) > CACHE_STALENESS_SECS]
    if not to_probe:
        return

    results = probe_hosts(to_probe, ssh_builder=ssh_builder,
                          timeout=timeout)

    dead = sorted(h for h, ok in results.items() if not ok)
    if dead:
        raise RuntimeError(
            f"host(s) not reachable over ssh: {', '.join(dead)}. "
            "Launch requires passwordless ssh to every remote host "
            "(reference horovodrun has the same contract).")
    for h in to_probe:
        cache[h] = now
    _store_cache(cache_path, cache)

"""Host list parsing and rank allocation.

Reference equivalents: ``run/run.py:590-622`` (host/hostfile parsing) and
``run/gloo_run.py:56-114`` (``_allocate``: rank / local_rank / cross_rank
assignment from ``host:slots`` pairs).
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from horovod_tpu import telemetry

logger = logging.getLogger(__name__)


@dataclass
class HostSlots:
    hostname: str
    slots: int


@dataclass
class RankInfo:
    rank: int
    size: int
    local_rank: int
    local_size: int
    cross_rank: int
    cross_size: int
    hostname: str


def parse_hosts(hosts: str) -> List[HostSlots]:
    """Parse ``"h1:2,h2:2"`` (reference run.py:590-607)."""
    out = []
    for part in hosts.split(","):
        part = part.strip()
        if not part:
            continue
        if ":" in part:
            name, slots = part.rsplit(":", 1)
            out.append(HostSlots(name, int(slots)))
        else:
            out.append(HostSlots(part, 1))
    if not out:
        raise ValueError(f"no hosts found in {hosts!r}")
    return out


def parse_hostfile(path: str) -> List[HostSlots]:
    """Parse a hostfile of ``hostname slots=N`` lines (reference
    run.py:609-622)."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.split("#")[0].strip()
            if not line:
                continue
            fields = line.split()
            name = fields[0]
            slots = 1
            for fld in fields[1:]:
                if fld.startswith("slots="):
                    slots = int(fld[len("slots="):])
            out.append(HostSlots(name, slots))
    if not out:
        raise ValueError(f"no hosts found in hostfile {path}")
    return out


def allocate(hosts: List[HostSlots], np_: int) -> List[RankInfo]:
    """Assign ranks host-major (reference _allocate, gloo_run.py:56-114):
    consecutive ranks fill a host before moving to the next; local_rank is
    the slot index, cross_rank the host index."""
    total = sum(h.slots for h in hosts)
    if total < np_:
        raise ValueError(
            f"requested -np {np_} but hosts only provide {total} slots")
    infos: List[RankInfo] = []
    rank = 0
    cross_size = 0
    for host_idx, h in enumerate(hosts):
        if rank >= np_:
            break
        cross_size += 1
        use = min(h.slots, np_ - rank)
        for slot in range(use):
            infos.append(RankInfo(
                rank=rank, size=np_, local_rank=slot, local_size=use,
                cross_rank=host_idx, cross_size=0, hostname=h.hostname))
            rank += 1
    for info in infos:
        info.cross_size = cross_size
    return infos


def topology_string(infos: List[RankInfo]) -> str:
    """Serialize an allocation back to the ``"h1:2,h2:2"`` dialect of
    :func:`parse_hosts`, in rank order — the value the launcher exports as
    ``HOROVOD_TOPOLOGY`` so every rank can reconstruct the host→slots map
    (``hvd.topology()``: hosts, leaders, local group) without a collective.
    Built from the ACTIVE allocation, not the user's ``-H`` argument, so an
    elastic restart or fleet resize that shrinks the world re-serializes
    the topology the surviving ranks actually have."""
    hosts: List[HostSlots] = []
    for info in infos:   # rank order == host-major order (allocate())
        if hosts and hosts[-1].hostname == info.hostname:
            hosts[-1].slots += 1
        else:
            hosts.append(HostSlots(info.hostname, 1))
    return ",".join(f"{h.hostname}:{h.slots}" for h in hosts)


def promote_host(host_list: List[HostSlots],
                 hostname: str) -> List[HostSlots]:
    """Reorder ``host_list`` so ``hostname`` leads.  Rank assignment is
    host-major (:func:`allocate`), so the promoted host's first slot
    becomes rank 0 — this is how the launcher pins the elected
    coordinator host after a failover.  The relative order of the other
    hosts is preserved; an unknown hostname returns the list unchanged.
    """
    head = [h for h in host_list if h.hostname == hostname]
    if not head:
        return list(host_list)
    return head + [h for h in host_list if h.hostname != hostname]


def free_slots(hosts: List[HostSlots],
               used: Dict[str, int]) -> List[HostSlots]:
    """Remaining per-host capacity after subtracting ``used`` (hostname →
    slots held by running jobs).  Hosts with nothing left are dropped so
    the result feeds straight into :func:`allocate`; order is preserved
    because rank assignment is host-major and the fleet wants jobs packed
    onto the same prefix of the pool."""
    out: List[HostSlots] = []
    for h in hosts:
        left = h.slots - used.get(h.hostname, 0)
        if left > 0:
            out.append(HostSlots(hostname=h.hostname, slots=left))
    return out


class HostBlacklist:
    """Launcher-side record of hosts demoted after rank failures.

    Reference equivalent: ``run/elastic/discovery.py:30-77``
    (``HostState.blacklist`` + ``HostManager`` pruning blacklisted hosts
    from the working set).  Here the launcher owns the list: a host whose
    rank crashed or that stopped answering probes is demoted, and the
    next elastic restart attempt allocates around it.

    ``cooldown`` is seconds until a demoted host becomes eligible again
    (None = demoted for the life of the job); ``clock`` is a
    monotonic-seconds callable, injectable so tests step time instead of
    sleeping.
    """

    def __init__(self, cooldown: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic):
        self._cooldown = cooldown
        self._clock = clock
        self._entries: Dict[str, Tuple[float, str]] = {}

    def demote(self, hostname: str, reason: str = "") -> None:
        telemetry.counter(
            "hvd_blacklisted_hosts_total",
            "Host demotions recorded by the launcher blacklist").inc()
        self._entries[hostname] = (self._clock(), reason)

    def forgive(self, hostname: str) -> None:
        self._entries.pop(hostname, None)

    def is_blacklisted(self, hostname: str) -> bool:
        entry = self._entries.get(hostname)
        if entry is None:
            return False
        if (self._cooldown is not None and
                self._clock() - entry[0] > self._cooldown):
            # Cooldown elapsed: the host gets another chance.  If it is
            # still broken the next failure re-demotes it.
            del self._entries[hostname]
            telemetry.counter(
                "hvd_blacklist_expirations_total",
                "Blacklist cooldowns that expired, re-admitting the "
                "host").inc()
            logger.info("blacklist cooldown expired for %s; host is "
                        "eligible again", hostname)
            return False
        return True

    def filter(self, host_list: List[HostSlots]) -> List[HostSlots]:
        """The usable subset of ``host_list``, preserving order."""
        return [h for h in host_list if not self.is_blacklisted(h.hostname)]

    def summary(self) -> str:
        """Human-readable account of every active demotion, for the
        fail-fast report when capacity drops below the floor."""
        parts = []
        for host in sorted(self._entries):
            if not self.is_blacklisted(host):   # may expire an entry
                continue
            reason = self._entries[host][1]
            parts.append(f"{host} ({reason})" if reason else host)
        return ", ".join(parts) or "<none>"

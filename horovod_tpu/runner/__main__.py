"""``python -m horovod_tpu.runner`` = hvdrun; the ``fleet`` subcommand
(``python -m horovod_tpu.runner fleet ...``) = hvdfleet."""
import sys


def main() -> int:
    if len(sys.argv) > 1 and sys.argv[1] == "fleet":
        from horovod_tpu.runner.fleet import main as fleet_main
        return fleet_main(sys.argv[2:])
    from horovod_tpu.runner.run import main as run_main
    return run_main()


sys.exit(main())

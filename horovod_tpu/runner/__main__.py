"""``python -m horovod_tpu.runner`` = hvdrun."""
import sys

from horovod_tpu.runner.run import main

sys.exit(main())

"""``hvdrun`` — the launcher (reference ``horovodrun``, ``run/run.py``)."""

from horovod_tpu.runner.run import main, run_command  # noqa: F401

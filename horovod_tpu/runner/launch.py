"""Process spawn / supervision for ``hvdrun``.

Reference equivalents: ``run/gloo_run.py:165-262`` (threaded per-rank launch,
stdout/stderr capture with rank prefixes or per-rank files, kill fan-out on
failure or signal) and ``run/common/util/safe_shell_exec.py`` (process-group
kill).  Local ranks run via subprocess in their own process group; remote
hosts ride ssh exactly like the reference's gloo path.
"""

from __future__ import annotations

import os
import shlex
import signal
import socket
import subprocess
import sys
import threading
import time
from typing import Callable, Dict, List, Optional

from horovod_tpu import config

from horovod_tpu import faults, telemetry
from horovod_tpu.resilience import PREEMPTION_RC
from horovod_tpu.runner.hosts import RankInfo

# Seconds between SIGTERM fan-out and the SIGKILL hammer.  Tunable: ranks
# flushing checkpoints or closing remote filesystems may need more than
# the default 10 s; chaos tests want far less.
DEFAULT_TERMINATE_GRACE_SECONDS = 10.0


def _terminate_grace_seconds() -> float:
    v = config.env_str("HOROVOD_TERMINATE_GRACE_SECONDS", "")
    try:
        return float(v) if v else DEFAULT_TERMINATE_GRACE_SECONDS
    except ValueError:
        sys.stderr.write(
            f"hvdrun: ignoring non-numeric HOROVOD_TERMINATE_GRACE_"
            f"SECONDS={v!r}; using {DEFAULT_TERMINATE_GRACE_SECONDS}\n")
        return DEFAULT_TERMINATE_GRACE_SECONDS


def find_free_port() -> int:
    s = socket.socket()
    s.bind(("0.0.0.0", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def is_local(hostname: str) -> bool:
    return hostname in ("localhost", "127.0.0.1", socket.gethostname())


class RankProcess:
    def __init__(self, info: RankInfo, command: List[str],
                 env: Dict[str, str], output_dir: Optional[str],
                 prefix_output: bool, label: Optional[str] = None):
        self.info = info
        self.command = command
        self.env = env
        self.output_dir = output_dir
        self.prefix_output = prefix_output
        self.label = label
        self.proc: Optional[subprocess.Popen] = None
        self._pump: Optional[threading.Thread] = None
        self.terminated_by_launcher = False

    def start(self) -> None:
        faults.inject("spawn", self.info.hostname, rank=self.info.rank)
        self._stdin_secret = None   # set only on the ssh path
        if is_local(self.info.hostname):
            cmd = self.command
            env = self.env
        else:
            # Remote spawn over ssh with env inlined (reference
            # gloo_run.py:211-254 builds the same kind of command line) —
            # EXCEPT the job secret: anything on the command line is
            # world-readable via ps on both ends, which would defeat the
            # auth handshake exactly in the multi-host case it exists
            # for.  The secret travels over ssh stdin instead.
            exports = " ".join(
                f"{k}={shlex.quote(v)}" for k, v in sorted(self.env.items())
                if k != "HOROVOD_SECRET_KEY" and
                k.startswith(("HOROVOD_", "PYTHONPATH", "PATH", "XLA_",
                              "JAX_")))
            self._stdin_secret = self.env.get("HOROVOD_SECRET_KEY")
            read_key = ("IFS= read -r HOROVOD_SECRET_KEY; "
                        "export HOROVOD_SECRET_KEY; "
                        if self._stdin_secret else "")
            remote = read_key + \
                f"cd {shlex.quote(os.getcwd())} && env {exports} " + \
                " ".join(shlex.quote(c) for c in self.command)
            # HOROVOD_SSH_CMD: override for tests and exotic transports
            # (reference horovodrun has no override; its ssh path is
            # untested for the same reason ours would otherwise be).
            ssh = config.env_str("HOROVOD_SSH_CMD", "ssh")
            cmd = [ssh, "-o", "StrictHostKeyChecking=no",
                   self.info.hostname, remote]
            env = dict(os.environ)

        stdin_target = subprocess.PIPE if self._stdin_secret else None
        stdout_target = subprocess.PIPE
        if self.output_dir:
            rank_dir = os.path.join(self.output_dir,
                                    f"rank.{self.info.rank}")
            os.makedirs(rank_dir, exist_ok=True)
            self._stdout_f = open(os.path.join(rank_dir, "stdout"), "wb")
            self._stderr_f = open(os.path.join(rank_dir, "stderr"), "wb")
            self.proc = subprocess.Popen(
                cmd, env=env, stdin=stdin_target, stdout=self._stdout_f,
                stderr=self._stderr_f, start_new_session=True)
            self._feed_secret()
            return
        self.proc = subprocess.Popen(
            cmd, env=env, stdin=stdin_target, stdout=stdout_target,
            stderr=subprocess.STDOUT, start_new_session=True)
        self._feed_secret()
        self._pump = threading.Thread(target=self._pump_output, daemon=True)
        self._pump.start()

    def _feed_secret(self) -> None:
        if self._stdin_secret and self.proc.stdin is not None:
            try:
                self.proc.stdin.write(self._stdin_secret.encode() + b"\n")
                self.proc.stdin.flush()
            except (BrokenPipeError, OSError):
                pass  # rank died at spawn; the supervisor will notice
            finally:
                self.proc.stdin.close()

    def _pump_output(self) -> None:
        tag = (f"{self.label}:{self.info.rank}" if self.label
               else f"{self.info.rank}")
        prefix = f"[{tag}]<stdout>:" if self.prefix_output else ""
        for line in iter(self.proc.stdout.readline, b""):
            sys.stdout.write(prefix + line.decode(errors="replace"))
            sys.stdout.flush()

    def terminate(self) -> None:
        # Mark BEFORE signalling: a -SIGTERM exit after this point is
        # collateral teardown, not a failure of this rank.
        self.terminated_by_launcher = True
        if self.proc is None or self.proc.poll() is not None:
            return
        try:
            os.killpg(self.proc.pid, signal.SIGTERM)
        except (ProcessLookupError, PermissionError):
            pass

    def kill(self) -> None:
        if self.proc is None or self.proc.poll() is not None:
            return
        try:
            os.killpg(self.proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass


class JobControl:
    """Steering handle for a job supervised OFF the main thread.

    The fleet controller (``runner/fleet.py``) runs each job's
    :func:`launch_job` in a worker thread, where ``signal.signal`` would
    raise — so instead of POSIX signals the controller talks to the
    supervisor through this object.  Two verbs:

    * :meth:`preempt` — deliver SIGTERM to every rank's process group
      WITHOUT marking the processes launcher-terminated.  Ranks that
      installed :func:`horovod_tpu.resilience.install_preemption_handler`
      save and exit rc 75; ranks that did not die of the signal.  Either
      way the exits are attributed to *preemption* (no host blame, no
      blacklist) because this flag is set.
    * :meth:`stop` — operator-stop semantics, identical to the launcher's
      own SIGINT/SIGTERM handler: tear everything down, report rc 130,
      blame nothing.

    Signal delivery is inherently LOCAL: for a remote rank the spawned
    process is its ssh client, so ``killpg`` would tear the transport
    down under the remote process mid-save instead of preempting it —
    the rank may linger on its host holding TPU devices and ports while
    the controller reuses its slots.  When ``remote_preempt`` is given
    (the fleet wires it to the per-job heartbeat health plane's
    ``request_preempt``), :meth:`preempt` leaves remote ranks' ssh
    clients alive and invokes the hook instead: the preemption rides the
    authenticated RPC plane end-to-end, the remote rank saves and exits
    rc 75, and ssh propagates that exit status back to the supervisor.
    Without the hook (no ``--heartbeat-interval``), remote ranks only
    get their transport torn down — coordinated-save preemption is then
    guaranteed for local ranks only.

    Both verbs are safe to call before the ranks have spawned (the
    request is latched and applied at attach time) and are idempotent.
    """

    def __init__(self, remote_preempt: Optional[Callable[[], None]]
                 = None) -> None:
        self._lock = threading.Lock()
        self._procs: Optional[List[RankProcess]] = None
        self.remote_preempt = remote_preempt
        self.preempt_requested = threading.Event()
        self.stop_requested = threading.Event()

    def _attach(self, procs: List[RankProcess]) -> None:
        with self._lock:
            self._procs = procs
        # A verb that arrived before the ranks existed applies now.
        if self.stop_requested.is_set():
            self.stop()
        elif self.preempt_requested.is_set():
            self.preempt()

    def preempt(self) -> None:
        self.preempt_requested.set()
        with self._lock:
            procs = list(self._procs or ())
        any_remote = False
        for p in procs:
            # NOT p.terminate(): that would mark the exit as launcher
            # teardown and hide the rc-75 / -SIGTERM preemption outcome.
            if p.proc is None or p.proc.poll() is not None:
                continue
            if self.remote_preempt is not None and \
                    not is_local(p.info.hostname):
                # SIGTERM here would only hit the local ssh client —
                # the health plane delivers the preemption to the rank
                # itself; ssh relays its rc-75 exit back.
                any_remote = True
                continue
            try:
                os.killpg(p.proc.pid, signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                pass
        if any_remote:
            self.remote_preempt()

    def stop(self) -> None:
        self.stop_requested.set()
        with self._lock:
            procs = list(self._procs or ())
        for p in procs:
            p.terminate()


def launch_job(rank_infos: List[RankInfo], command: List[str],
               env_per_rank: List[Dict[str, str]],
               output_dir: Optional[str] = None,
               prefix_output: bool = True,
               start_timeout: Optional[float] = None,
               report: Optional[dict] = None,
               watchdog: Optional[Callable[[], list]] = None,
               install_signal_handlers: bool = True,
               control: Optional[JobControl] = None,
               label: Optional[str] = None,
               reform: Optional[Callable[
                   [RankInfo, int, List[RankInfo]], bool]] = None) -> int:
    """Run all ranks; on any non-zero exit terminate the rest (reference
    gloo_run.py:256-262).  Returns the job exit code.

    ``report``, when given, is filled in place for the elastic caller:
    ``report["failed"]`` = list of ``(rank, hostname, exit_code)`` for
    every rank that exited non-zero on its own (operator-stop SIGTERMs
    excluded — those are not host failures), ``report["signalled"]`` =
    True when the launcher's own SIGINT/SIGTERM handler fired.

    ``reform``, when given, is the fail-in-place hook
    (HOROVOD_ON_RANK_FAILURE=shrink|shrink-then-restart): called with
    ``(dead_info, exit_code, survivor_infos)`` when a rank dies on its
    own (crash / watchdog SIGKILL; never preemption or operator stop).
    Returning True means the death was absorbed — the survivors reform
    the collective world in-process, supervision continues over them,
    and the dead rank is reported under ``report["reformed"]`` instead
    of ``report["failed"]`` (a non-restart event: no teardown fan-out,
    no host blame).  Returning False falls through to the normal
    terminate-everyone path.

    ``watchdog``, when given, is polled in the supervision loop and
    returns ``(rank, reason)`` pairs for ranks the health plane declared
    dead (heartbeats gone) or hung (heartbeats alive, step stalled).
    Those ranks are SIGKILLed — deliberately via :meth:`RankProcess.kill`
    and not ``terminate()``, so the exit is attributed to the rank like
    any crash and flows through the normal blame / soft-demotion /
    elastic-restart machinery instead of being excused as launcher
    teardown.

    ``install_signal_handlers=False`` + ``control`` is the fleet path:
    the supervisor runs off the main thread (``signal.signal`` would
    raise there), so operator stop and preemption arrive through the
    :class:`JobControl` instead of SIGINT/SIGTERM.  ``label`` prefixes
    rank output as ``[label:rank]`` so interleaved jobs stay readable."""
    procs = [RankProcess(info, command, env, output_dir, prefix_output,
                         label=label)
             for info, env in zip(rank_infos, env_per_rank)]

    stop = threading.Event()
    signalled = threading.Event()   # the OPERATOR stopped the job

    def handle_signal(signum, frame):
        del frame
        signalled.set()
        stop.set()
        for p in procs:
            p.terminate()

    old_int = old_term = None
    if install_signal_handlers:
        old_int = signal.signal(signal.SIGINT, handle_signal)
        old_term = signal.signal(signal.SIGTERM, handle_signal)
    if control is not None:
        control._attach(procs)
    try:
        # start_timeout bounds LAUNCHING only (spawning every rank — ssh may
        # block on remote hosts), never a healthy running job; rendezvous
        # hangs are bounded by the runtime's own connect timeouts.
        launch_deadline = (time.monotonic() + start_timeout
                           if start_timeout else None)
        for p in procs:
            if launch_deadline and time.monotonic() > launch_deadline:
                sys.stderr.write("hvdrun: start timeout exceeded while "
                                 "launching ranks\n")
                for q in procs:
                    q.terminate()
                return 1
            p.start()
        exit_code = 0
        running = set(range(len(procs)))
        by_rank = {p.info.rank: p for p in procs}
        reformed = []            # (rank, hostname, exit_code) absorbed
        reformed_ranks = set()   # global ranks excluded from blame below
        while running and not stop.is_set():
            if control is not None and control.stop_requested.is_set():
                signalled.set()
                stop.set()
                for p in procs:
                    p.terminate()
                break
            if watchdog is not None:
                for bad_rank, reason in watchdog():
                    victim = by_rank.get(bad_rank)
                    if victim is None or victim.proc.poll() is not None:
                        continue
                    sys.stderr.write(
                        f"hvdrun: health plane: rank {bad_rank} {reason}; "
                        f"killing it to trigger a restart\n")
                    telemetry.counter(
                        "hvd_watchdog_kills_total",
                        "Ranks SIGKILLed by the health-plane watchdog "
                        "(dead or hung)").inc()
                    victim.kill()
            for i in sorted(running):
                rc = procs[i].proc.poll()
                if rc is None:
                    continue
                running.discard(i)
                if rc != 0:
                    # Fail-in-place: offer the death to the reform hook
                    # before the teardown fan-out.  Only genuine solo
                    # deaths qualify — preemption, operator stop and
                    # launcher teardown keep their existing semantics.
                    if (reform is not None and rc != PREEMPTION_RC and
                            not procs[i].terminated_by_launcher and
                            not signalled.is_set() and
                            not (control is not None and
                                 control.preempt_requested.is_set())):
                        survivors = [procs[j].info for j in sorted(running)]
                        if survivors and reform(procs[i].info, rc,
                                                survivors):
                            dead = procs[i].info
                            sys.stderr.write(
                                f"hvdrun: rank {dead.rank} exited with "
                                f"code {rc}; absorbed by in-process "
                                f"reformation ({len(survivors)} "
                                f"survivor(s) continue).\n")
                            reformed.append((dead.rank, dead.hostname, rc))
                            reformed_ranks.add(dead.rank)
                            continue
                    exit_code = rc
                    if rc == PREEMPTION_RC:
                        sys.stderr.write(
                            f"hvdrun: rank {procs[i].info.rank} exited "
                            f"with preemption code {rc}; terminating "
                            f"remaining ranks for reschedule.\n")
                    else:
                        sys.stderr.write(
                            f"hvdrun: rank {procs[i].info.rank} exited "
                            f"with code {rc}; terminating remaining "
                            f"ranks.\n")
                    if control is not None and \
                            control.preempt_requested.is_set():
                        # Controller-requested preemption: every rank
                        # already has the request (SIGTERM locally, the
                        # health plane remotely), so re-signalling here
                        # would mark peers launcher-terminated (hiding
                        # their rc-75 outcome) and kill remote ranks'
                        # ssh clients mid-coordinated-save.  The grace /
                        # hard-kill phase below still bounds laggards.
                        pass
                    else:
                        for j in sorted(running):
                            procs[j].terminate()
                    stop.set()
                break
            time.sleep(0.05)
        # Grace period (HOROVOD_TERMINATE_GRACE_SECONDS), then hard kill,
        # logging which ranks needed the hammer — a rank that regularly
        # outlives its grace is hiding a shutdown bug.
        grace = _terminate_grace_seconds()
        t0 = time.monotonic()
        while any(p.proc.poll() is None for p in procs):
            if time.monotonic() - t0 > grace:
                laggards = sorted(p.info.rank for p in procs
                                  if p.proc.poll() is None)
                sys.stderr.write(
                    f"hvdrun: rank(s) {laggards} still running "
                    f"{grace:g}s after SIGTERM; sending SIGKILL\n")
                telemetry.counter(
                    "hvd_hard_killed_ranks_total",
                    "Ranks that outlived the SIGTERM grace period and "
                    "took a SIGKILL").inc(len(laggards))
                for p in procs:
                    p.kill()
                break
            time.sleep(0.05)
        failed = []
        preempted = []
        preempt_req = (control is not None and
                       control.preempt_requested.is_set())
        for p in procs:
            p.proc.wait()
            rc = p.proc.returncode
            if p.info.rank in reformed_ranks:
                # Absorbed by in-process reformation: the survivors'
                # exits define the job outcome; the dead rank neither
                # sets the exit code nor blames its host.
                continue
            if rc not in (0, None) and exit_code == 0:
                exit_code = rc
            if rc not in (0, None) and not p.terminated_by_launcher:
                if rc == PREEMPTION_RC or (preempt_req and
                                           rc == -signal.SIGTERM):
                    # A preempted rank is not a failure and not its
                    # host's fault: no blame, no blacklist — the elastic
                    # caller reschedules immediately (runner/run.py).
                    # Under a controller-requested preemption a rank
                    # that never installed the preemption handler dies
                    # of the raw SIGTERM (-15); that is still the
                    # controller's doing, not the host's.
                    preempted.append((p.info.rank, p.info.hostname, rc))
                    continue
                # Genuine rank failure: it failed BEFORE the launcher
                # began tearing the job down.  Anything after terminate()
                # is collateral — including positive exit codes, since a
                # SIGTERMed rank racing its peer's death often dies of
                # "peer closed connection" instead of the signal, and
                # blaming ITS host would demote a healthy machine.
                failed.append((p.info.rank, p.info.hostname, rc))
        if preempt_req and not failed and preempted and \
                exit_code in (0, -signal.SIGTERM, PREEMPTION_RC):
            # The whole gang went down under a requested preemption:
            # surface the canonical preemption code even if the first
            # observed exit was a handler-less rank's -SIGTERM, so the
            # caller's rc-75 requeue path fires uniformly.
            exit_code = PREEMPTION_RC
        if signalled.is_set():
            # Operator stop: ALWAYS 130, even though the SIGTERMed ranks
            # report -15 — callers (elastic restarts) distinguish "the
            # operator stopped the job" from "a rank crashed" by this
            # code, and success must never be reported either.
            exit_code = 130
            failed = []     # nothing to blame a host for
            preempted = []
        if failed:
            telemetry.counter(
                "hvd_rank_failures_total",
                "Ranks that exited non-zero before launcher teardown "
                "began").inc(len(failed))
        if preempted:
            telemetry.counter(
                "hvd_preempted_ranks_total",
                "Ranks that exited with the preemption code (saved and "
                "asked for a reschedule)").inc(len(preempted))
        if report is not None:
            report["failed"] = failed
            report["preempted"] = preempted
            report["signalled"] = signalled.is_set()
            report["reformed"] = reformed
        return exit_code
    finally:
        if install_signal_handlers:
            signal.signal(signal.SIGINT, old_int)
            signal.signal(signal.SIGTERM, old_term)

"""``hvdrun`` CLI (reference ``horovodrun``, ``run/run.py:374-587``).

Usage::

    hvdrun -np 4 python train.py
    hvdrun -np 8 -H host1:4,host2:4 python train.py
    python -m horovod_tpu.runner -np 2 pytest -q tests/

Replaces the reference's mpirun/ssh-gloo dispatch with direct process
spawn + the native TCP rendezvous; on TPU pods one rank per host is the
typical layout (each process drives all local chips through SPMD).
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import socket
import sys
import tempfile
import time
from typing import Callable, List, Optional

import horovod_tpu
from horovod_tpu import config, telemetry
from horovod_tpu.resilience import PREEMPTION_RC
from horovod_tpu.runner import config_parser, hosts, launch


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="hvdrun",
        description="Launch a horovod_tpu distributed job.")
    p.add_argument("-v", "--version", action="version",
                   version=horovod_tpu.__version__)
    p.add_argument("-np", "--num-proc", dest="np", type=int,
                   help="Total number of processes to launch.")
    p.add_argument("-H", "--hosts",
                   help="Comma-separated host:slots pairs "
                        "(default: localhost with -np slots).")
    p.add_argument("--hostfile",
                   help="Hostfile with 'hostname slots=N' lines.")
    p.add_argument("--output-filename",
                   help="Redirect per-rank output to "
                        "<dir>/rank.N/stdout|stderr.")
    p.add_argument("--start-timeout", type=float, default=None,
                   help="Seconds to wait for the job to finish launching.")
    p.add_argument("--verbose", action="store_true")
    p.add_argument("--config-file",
                   help="YAML config file; CLI flags take precedence.")
    p.add_argument("--check-build", action="store_true",
                   help="Print build capabilities and exit.")
    p.add_argument("--rendezvous-port", type=int, default=0,
                   help="Fixed controller rendezvous port (default: pick "
                        "a free port).")
    p.add_argument("--elastic-restarts", type=int, default=0,
                   help="Relaunch the WHOLE job up to N times after a "
                        "failure (full-restart elasticity: each attempt "
                        "gets a fresh rendezvous; pair with "
                        "hvd.checkpoint save/restore so training resumes "
                        "from the latest step — docs/fault_tolerance.md). "
                        "Ranks see HOROVOD_RESTART_ATTEMPT=k.")
    p.add_argument("--min-np", dest="min_np", type=int, default=None,
                   help="Smallest world size an elastic restart may run "
                        "with.  When hosts are blacklisted after "
                        "failures, restart attempts re-allocate ranks "
                        "onto the surviving hosts and accept any world "
                        "size >= this floor (default: -np, i.e. never "
                        "shrink).")
    p.add_argument("--blacklist-cooldown", dest="blacklist_cooldown",
                   type=float, default=None,
                   help="Seconds until a blacklisted host becomes "
                        "eligible for re-allocation again (default: "
                        "demoted for the life of the job).")
    p.add_argument("--heartbeat-interval", dest="heartbeat_interval",
                   type=float, default=None,
                   help="Enable the heartbeat health plane: every rank "
                        "reports (step, progress_ts) to the launcher "
                        "every N seconds over the authenticated RPC "
                        "plane.  A rank silent past "
                        "HOROVOD_HEARTBEAT_DEADLINE (default 5x the "
                        "interval) is declared dead and killed for "
                        "restart; with --hang-deadline, a rank whose "
                        "heartbeats arrive but whose step stalls is "
                        "killed proactively instead of waiting for the "
                        "eager collective timeout.  Defaults to "
                        "HOROVOD_HEARTBEAT_INTERVAL when set "
                        "(docs/fault_tolerance.md).")
    p.add_argument("--hang-deadline", dest="hang_deadline", type=float,
                   default=None,
                   help="Seconds a rank's training step may stall (while "
                        "its heartbeats stay alive) before the launcher "
                        "restarts it.  Requires --heartbeat-interval. "
                        "Defaults to HOROVOD_HANG_DEADLINE; 0 disables "
                        "hang detection.")
    p.add_argument("--on-rank-failure", dest="on_rank_failure",
                   choices=["restart", "shrink", "shrink-then-restart"],
                   default=None,
                   help="Policy when a rank dies mid-job (docs/"
                        "fault_tolerance.md, 'Fail-in-place').  restart "
                        "(default): today's whole-job elastic restart.  "
                        "shrink: survivors reform the collective world "
                        "IN-PROCESS — in-flight collectives drain with a "
                        "retryable membership-changed status, the "
                        "launcher delivers each survivor's new rank over "
                        "the heartbeat plane, and training resumes via "
                        "resilience.reform_world() with no relaunch.  "
                        "shrink-then-restart: try the in-process path, "
                        "fall back to the elastic restart budget when "
                        "reformation fails or would drop below --min-np. "
                        "Shrink modes require --heartbeat-interval.  "
                        "Defaults to HOROVOD_ON_RANK_FAILURE.")
    p.add_argument("--network-interface", dest="network_interface",
                   default=None,
                   help="Comma-separated NIC name(s), in preference "
                        "order, for the controller rendezvous and TCP "
                        "data plane on every host (reference "
                        "horovodrun --network-interface): each rank "
                        "binds its listeners to the first matching "
                        "interface's IPv4 address and advertises it. "
                        "Per-host overrides: HOROVOD_NETWORK_INTERFACE "
                        "or HOROVOD_HOSTNAME in that host's env.")
    p.add_argument("--jax-distributed", action="store_true", default=False,
                   help="Bootstrap jax.distributed in every rank "
                        "(multi-process SPMD: each process drives its "
                        "local devices, jax.devices() is the global "
                        "set).  Sets HOROVOD_JAX_DISTRIBUTED=1 and "
                        "HOROVOD_COORDINATOR_ADDR to rank 0's host; "
                        "hvd.init() then calls "
                        "jax.distributed.initialize before any backend "
                        "init.")
    p.add_argument("--jax-coordinator-port", type=int, default=0,
                   help="Fixed port for the jax.distributed coordinator "
                        "on rank 0's host (default: pick a free port; "
                        "for multi-host jobs pass a port known open on "
                        "rank 0's host).")

    tune = p.add_argument_group("tunables")
    tune.add_argument("--fusion-threshold-mb", type=float, default=None)
    tune.add_argument("--cycle-time-ms", type=float, default=None)
    tune.add_argument("--cache-capacity", type=int, default=None)
    tune.add_argument("--autotune", action="store_true", default=False,
                      help="Online Bayesian autotuning of the control "
                           "plane (cycle time, fusion threshold, transport "
                           "chunk size, response cache): explores, pins "
                           "the best config, then keeps monitoring and "
                           "re-opens tuning when throughput drifts.  "
                           "Progress lands in hvd_autotune_* gauges "
                           "(--metrics-file) and the --autotune-log-file "
                           "CSV; see docs/performance.md, 'Adaptive "
                           "control plane'.")
    tune.add_argument("--autotune-log-file", default=None,
                      help="Per-trial CSV from the rank-0 tuner (one row "
                           "per trial; phase column marks pinned/reopen "
                           "transitions).")

    timeline = p.add_argument_group("timeline")
    timeline.add_argument("--timeline-filename", default=None)
    timeline.add_argument("--timeline-mark-cycles", action="store_true",
                          default=False)

    metrics = p.add_argument_group("metrics")
    metrics.add_argument("--metrics-file", dest="metrics_file", default=None,
                         help="Write a merged cross-rank metrics summary "
                              "here after the job; each rank also dumps "
                              "its own <base>.rank<k>.json. Defaults to "
                              "HOROVOD_METRICS_FILE when set "
                              "(docs/metrics.md).")

    tracing = p.add_argument_group("tracing")
    tracing.add_argument("--trace", dest="trace_dir", default=None,
                         metavar="DIR",
                         help="Distributed tracing: every rank records "
                              "per-collective spans (HOROVOD_TRACE) and "
                              "the launcher merges them into DIR/"
                              "trace.json (skew-corrected Perfetto/Chrome "
                              "trace) plus DIR/critical_path.json with a "
                              "straggler report. Defaults to "
                              "HOROVOD_TRACE_DIR when set; sampling via "
                              "HOROVOD_TRACE_SAMPLE (docs/timeline.md).")

    stall = p.add_argument_group("stall detection")
    stall.add_argument("--stall-check-time-seconds", type=float, default=None)
    stall.add_argument("--stall-shutdown-time-seconds", type=float,
                       default=None)

    logg = p.add_argument_group("logging")
    logg.add_argument("--log-level", default=None,
                      choices=["trace", "debug", "info", "warning", "error",
                               "fatal"])
    logg.add_argument("--log-hide-timestamp", action="store_true",
                      default=False)

    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="Command to run on every rank.")
    return p


def check_build() -> str:
    import horovod_tpu as hvd
    yes, no = "[X]", "[ ]"
    lines = [
        f"horovod_tpu v{horovod_tpu.__version__}:",
        "",
        "Available backends:",
        f"    {yes if hvd.tpu_built() else no} TPU/XLA (SPMD plane)",
        f"    {yes} TCP eager runtime",
        f"    {no} MPI",
        f"    {no} Gloo",
        f"    {no} NCCL",
        "",
        "Available frameworks:",
        "    [X] JAX",
        f"    {_torch_mark()} PyTorch",
    ]
    return "\n".join(lines)


def _torch_mark() -> str:
    try:
        import torch  # noqa: F401
        return "[X]"
    except ImportError:
        return "[ ]"


def shm_base_dir() -> str:
    """Base directory for per-job shm transport namespaces: tmpfs when
    the host has one (ring files there are true shared memory), else the
    regular temp dir (still mmap-shareable, just page-cache backed)."""
    return "/dev/shm" if os.path.isdir("/dev/shm") else \
        tempfile.gettempdir()


def provision_shm_dir(base: Optional[str] = None) -> str:
    """Create this job's shm namespace (``hvd-shm-<pid>-*``) and stamp
    it with an ``owner.pid`` marker so :func:`sweep_orphan_shm_dirs`
    can prove the owning launcher is gone before reclaiming it."""
    base = base or shm_base_dir()
    path = tempfile.mkdtemp(prefix=f"hvd-shm-{os.getpid()}-", dir=base)
    with open(os.path.join(path, "owner.pid"), "w") as f:
        f.write(f"{os.getpid()}\n")
    return path


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True   # exists, just not ours to signal
    except OSError:
        return False
    return True


def sweep_orphan_shm_dirs(base: Optional[str] = None) -> int:
    """Reclaim ``hvd-shm-*`` namespaces whose owning launcher is dead
    (SIGKILL leaves no chance to run the ``finally`` cleanup — the NEXT
    launch on the host sweeps instead).  A dir whose ``owner.pid`` names
    a live process is left alone; one with a missing or unreadable
    marker is treated as orphaned.  Returns the number removed."""
    base = base or shm_base_dir()
    swept = 0
    try:
        entries = os.listdir(base)
    except OSError:
        return 0
    for name in entries:
        if not name.startswith("hvd-shm-"):
            continue
        path = os.path.join(base, name)
        if not os.path.isdir(path):
            continue
        try:
            with open(os.path.join(path, "owner.pid")) as f:
                pid = int(f.read().strip())
        except (OSError, ValueError):
            pid = None
        if pid is not None and _pid_alive(pid):
            continue
        shutil.rmtree(path, ignore_errors=True)
        swept += 1
    return swept


def wipe_shm_dir(path: str) -> None:
    """Drop every ring file in the namespace but keep the dir and its
    ``owner.pid`` marker — used between elastic restart attempts so the
    fresh attempt's shm handshake never attaches to a dead ring."""
    try:
        names = os.listdir(path)
    except OSError:
        return
    for name in names:
        if name == "owner.pid":
            continue
        try:
            os.unlink(os.path.join(path, name))
        except OSError:
            pass


def run_command(args) -> int:
    """Resolved-args entry, shared with tests."""
    if args.hostfile:
        host_list = hosts.parse_hostfile(args.hostfile)
    elif args.hosts:
        host_list = hosts.parse_hosts(args.hosts)
    else:
        if not args.np:
            raise ValueError("either -np or -H/--hostfile is required")
        host_list = [hosts.HostSlots("localhost", args.np)]
    np_ = args.np or sum(h.slots for h in host_list)

    infos = hosts.allocate(host_list, np_)
    extra_env = config_parser.env_from_args(args)
    # One shared secret per job unless the caller pinned one (e.g. to join
    # an externally coordinated job).
    extra_env.setdefault(
        "HOROVOD_SECRET_KEY",
        config.env_raw("HOROVOD_SECRET_KEY") or config_parser.job_secret())

    # The coordinator lives on rank 0's host.  Only an all-local job may use
    # loopback: with remote ranks in the mix they must reach rank 0 by its
    # real hostname.
    all_local = all(launch.is_local(i.hostname) for i in infos)
    if not all_local:
        # Fail fast on dead hosts before any rank spawns (reference
        # run.py:59-112 cached ssh reachability check).
        from horovod_tpu.runner import network
        remote = sorted({i.hostname for i in infos
                         if not launch.is_local(i.hostname)})
        network.check_hosts_reachable(remote)
    # The rendezvous itself lives in THIS launcher process, so its
    # address never changes across restart attempts even when rank 0 is
    # re-allocated to a different host.
    addr = "127.0.0.1" if all_local else infos[0].hostname
    restarts = max(0, getattr(args, "elastic_restarts", 0) or 0)
    min_np = getattr(args, "min_np", None) or np_
    if min_np > np_:
        raise ValueError(f"--min-np {min_np} exceeds the requested "
                         f"world size -np {np_}")
    blacklist = hosts.HostBlacklist(
        cooldown=getattr(args, "blacklist_cooldown", None))
    metrics_file = (getattr(args, "metrics_file", None) or
                    config.env_str("HOROVOD_METRICS_FILE", "").strip() or
                    None)
    collector = None
    if metrics_file:
        # The launcher writes the MERGED summary to this path itself, so
        # its own at-exit dump must not clobber it (each rank gets an
        # explicit <base>.rank<k>.json injected in _launch_once).
        os.environ.pop("HOROVOD_METRICS_FILE", None)
        telemetry.configure(enabled_flag=True)
        collector = _MetricsCollector(extra_env["HOROVOD_SECRET_KEY"])
    trace_dir = (getattr(args, "trace_dir", None) or
                 config.env_str("HOROVOD_TRACE_DIR", "").strip() or
                 None)
    tracer = None
    if trace_dir:
        # The launcher must not record spans itself (it runs no
        # collectives) — the env vars are injected per rank in
        # _launch_once.  Telemetry is enabled so the critical-path
        # gauges land in the launcher snapshot of --metrics-file.
        os.environ.pop("HOROVOD_TRACE_DIR", None)
        telemetry.configure(enabled_flag=True)
        tracer = _TraceCollector(extra_env["HOROVOD_SECRET_KEY"])
    # Heartbeat health plane (docs/fault_tolerance.md "Warm restart"):
    # active only when an interval is configured, so launch paths (and
    # tests) that stub _launch_once keep their historical signature.
    hb_interval = getattr(args, "heartbeat_interval", None)
    if hb_interval is None:
        raw = config.env_str("HOROVOD_HEARTBEAT_INTERVAL", "").strip()
        hb_interval = float(raw) if raw else None
    health = None
    if hb_interval:
        deadline = float(
            config.env_str("HOROVOD_HEARTBEAT_DEADLINE", "").strip()
            or 5.0 * hb_interval)
        hang = getattr(args, "hang_deadline", None)
        if hang is None:
            hang = float(
                config.env_str("HOROVOD_HANG_DEADLINE", "").strip() or 0.0)
        health = _HealthPlane(extra_env["HOROVOD_SECRET_KEY"],
                              hb_interval, deadline, hang)
    coord = _CoordinationPlane(
        config.env_float("HOROVOD_COORD_LEASE_SECONDS"))
    if health is not None:
        health.coord = coord
    # Rank-failure policy (docs/fault_tolerance.md "Fail-in-place").
    # The default — restart — keeps today's behavior untouched: the env
    # var is NOT injected and no reform hook is armed, so ranks and
    # native runtime run the exact pre-policy code paths.
    on_rank_failure = (getattr(args, "on_rank_failure", None) or
                      config.env_str("HOROVOD_ON_RANK_FAILURE", "").strip()
                      or "restart")
    if on_rank_failure not in ("restart", "shrink", "shrink-then-restart"):
        print(f"hvdrun: unknown HOROVOD_ON_RANK_FAILURE="
              f"{on_rank_failure!r}; using 'restart'",
              file=sys.stderr, flush=True)
        on_rank_failure = "restart"
    if on_rank_failure != "restart" and health is None:
        # The reform spec travels in heartbeat replies and dead-rank
        # detection leans on the keepalive monitor — without the health
        # plane the in-process path cannot work.
        print(f"hvdrun: --on-rank-failure {on_rank_failure} requires the "
              f"heartbeat health plane (--heartbeat-interval); falling "
              f"back to 'restart'", file=sys.stderr, flush=True)
        on_rank_failure = "restart"
    if on_rank_failure != "restart":
        # Ranks (and the native runtime through them) must see the same
        # policy so a dead peer drains in-flight collectives with the
        # retryable membership-changed status instead of a fatal abort.
        extra_env["HOROVOD_ON_RANK_FAILURE"] = on_rank_failure
    # Warm-restart spill scratch dir: one per JOB, stable across elastic
    # restart attempts so a new attempt's ranks find the old attempt's
    # spills.  A user-provided HOROVOD_SPILL_DIR is respected (and never
    # deleted); otherwise the launcher owns a temp dir for the job.
    # Shared-memory transport namespace (docs/performance.md "Transport
    # backends"): sweep orphans left by SIGKILLed launchers first, then
    # provision one per-job dir with an owner.pid marker so the NEXT
    # launcher can tell a live job's namespace from a dead one's.  A
    # user-provided HOROVOD_SHM_DIR is respected (and never deleted).
    swept = sweep_orphan_shm_dirs()
    if swept:
        print(f"hvdrun: swept {swept} orphaned shm transport "
              f"namespace(s) from dead jobs", file=sys.stderr, flush=True)
    owned_shm_dir = None
    shm_dir = config.env_str("HOROVOD_SHM_DIR", "").strip()
    if not shm_dir:
        owned_shm_dir = provision_shm_dir()
        shm_dir = owned_shm_dir
    extra_env["HOROVOD_SHM_DIR"] = shm_dir
    owned_spill_dir = None
    spill_scratch = config.env_str("HOROVOD_SPILL_DIR", "").strip()
    if (restarts > 0 or on_rank_failure != "restart") and not spill_scratch:
        # Name the job in the prefix when running under the fleet
        # controller so two jobs' scratch dirs are tellable apart on a
        # shared host (the fleet normally provisions HOROVOD_SPILL_DIR
        # itself; this is the fallback path).
        job = config.env_str("HOROVOD_FLEET_JOB", "").strip()
        prefix = f"hvd-spill-{job}-" if job else "hvd-spill-"
        owned_spill_dir = tempfile.mkdtemp(prefix=prefix)
        spill_scratch = owned_spill_dir
    if spill_scratch:
        extra_env["HOROVOD_SPILL_DIR"] = spill_scratch
    prev_np = None
    rc = 1
    try:
        for attempt in range(restarts + 1):
            if attempt > 0:
                telemetry.counter(
                    "hvd_elastic_restarts_total",
                    "Whole-job elastic restart attempts").inc()
                if owned_shm_dir is not None:
                    # Stale ring files from the dead attempt must not
                    # collide with the fresh attempt's shm handshake.
                    wipe_shm_dir(owned_shm_dir)
                if rc == PREEMPTION_RC:
                    # Preemption: the ranks checkpointed and asked to be
                    # rescheduled — no backoff (the host is healthy, the
                    # scheduler is just reclaiming it) and nothing gets
                    # blacklisted below (launch_job already keeps
                    # preempted ranks out of report["failed"]).
                    telemetry.counter(
                        "hvd_preemptions_total",
                        "Whole-job reschedules after rank preemption "
                        "(coordinated save + rc "
                        f"{PREEMPTION_RC})").inc()
                    print(f"hvdrun: job preempted (rc={rc}); immediate "
                          f"reschedule {attempt}/{restarts} with a fresh "
                          f"rendezvous", file=sys.stderr, flush=True)
                else:
                    # Brief backoff so a persistently broken launch (host
                    # mid-reboot, dead binary) doesn't burn the whole
                    # restart budget in a second — the budget targets
                    # transient failures.
                    delay = min(2.0 ** attempt, 30.0)
                    print(f"hvdrun: job failed (rc={rc}); elastic "
                          f"restart {attempt}/{restarts} in {delay:.0f}s "
                          f"with a fresh rendezvous",
                          file=sys.stderr, flush=True)
                    time.sleep(delay)
                # Re-probe surviving remote hosts RIGHT BEFORE the
                # attempt — the pre-launch check's hour-long cache would
                # answer from before the failure.  A host that stopped
                # answering is demoted unconditionally: spawning a rank
                # there can only hang the rendezvous.
                from horovod_tpu.runner import network
                candidates = sorted({
                    h.hostname for h in host_list
                    if not launch.is_local(h.hostname) and
                    not blacklist.is_blacklisted(h.hostname)})
                if candidates:
                    for host, ok in sorted(
                            network.probe_hosts(candidates).items()):
                        if not ok:
                            blacklist.demote(host, "unreachable over ssh")
                            print(f"hvdrun: host {host} is unreachable; "
                                  f"blacklisting", file=sys.stderr,
                                  flush=True)
            usable = coord.ensure_coordinator(blacklist.filter(host_list))
            capacity = sum(h.slots for h in usable)
            cur_np = min(np_, capacity)
            if cur_np < min_np:
                print(f"hvdrun: cannot continue: surviving hosts provide "
                      f"{capacity} slot(s) but the job needs at least "
                      f"{min_np} (--min-np). Blacklisted: "
                      f"{blacklist.summary()}", file=sys.stderr, flush=True)
                return rc or 1
            if cur_np < np_:
                print(f"hvdrun: restarting with a smaller world: "
                      f"{cur_np}/{np_} ranks on surviving hosts "
                      f"(blacklisted: {blacklist.summary()})",
                      file=sys.stderr, flush=True)
            infos = hosts.allocate(usable, cur_np)
            extra_env["HOROVOD_RESTART_ATTEMPT"] = str(attempt)
            extra_env.update(coord.env())
            if prev_np is not None and prev_np != cur_np:
                # World size changed across the restart: workers use this
                # to rescale the learning rate / accumulate so the global
                # batch keeps its semantics (parallel.data.elastic_transition).
                extra_env["HOROVOD_ELASTIC_PREV_SIZE"] = str(prev_np)
            else:
                extra_env.pop("HOROVOD_ELASTIC_PREV_SIZE", None)
            prev_np = cur_np
            report: dict = {}
            # Metrics kwargs only when active: callers (and tests) that
            # stub _launch_once with the historical 5-arg signature stay
            # compatible on the metrics-off path.
            mkw = ({"metrics_file": metrics_file, "collector": collector}
                   if collector is not None else {})
            if health is not None:
                mkw["health"] = health
            if on_rank_failure != "restart":
                mkw["on_rank_failure"] = on_rank_failure
                mkw["min_np"] = min_np
            if tracer is not None:
                mkw["trace_dir"] = trace_dir
                mkw["tracer"] = tracer
            rc = _launch_once(args, infos, addr, extra_env, report=report,
                              **mkw)
            if rc == 0:
                return 0
            if rc in (130, 143):
                # The OPERATOR stopped the job (launch_job returns 130
                # whenever ITS OWN SIGINT/SIGTERM handler fired,
                # regardless of the SIGTERMed ranks' -15s) — relaunching
                # would race them with another Ctrl-C.  A NEGATIVE code
                # here is a rank killed by a signal the launcher never
                # received (OOM SIGKILL, SIGSEGV): a crash, exactly what
                # the restart budget is for.
                return rc
            if attempt < restarts:
                # Demotion only matters if another attempt will allocate;
                # on the final failure it would just add noise to the
                # report.
                _demote_failed_hosts(blacklist, host_list,
                                     report.get("failed", ()), min_np)
        return rc
    finally:
        if health is not None:
            health.shutdown()
        if owned_spill_dir is not None:
            shutil.rmtree(owned_spill_dir, ignore_errors=True)
        if owned_shm_dir is not None:
            # Covers every exit path, including the rc-75 preemption
            # return: the shm namespace dies with the job.
            shutil.rmtree(owned_shm_dir, ignore_errors=True)
        if tracer is not None:
            # BEFORE the metrics summary: publish_gauges lands the
            # hvd_critical_path_* series in the launcher registry the
            # summary snapshots.
            try:
                _write_trace_outputs(trace_dir, tracer, np_)
            except OSError as e:
                print(f"hvdrun: could not write trace outputs to "
                      f"{trace_dir}: {e}", file=sys.stderr, flush=True)
            tracer.shutdown()
        if collector is not None:
            try:
                _write_metrics_summary(metrics_file, collector, np_, rc)
            except OSError as e:
                print(f"hvdrun: could not write metrics summary to "
                      f"{metrics_file}: {e}", file=sys.stderr, flush=True)
            collector.shutdown()


class _HealthPlane:
    """Launcher-side heartbeat sink + watchdog (the driver half of the
    elastic warm-restart health plane).

    Rides the same authenticated RPC plane as :class:`_MetricsCollector`:
    each rank's :class:`horovod_tpu.resilience.HeartbeatSender` pushes
    ``{"kind": "heartbeat", rank, step, progress_ts}`` to
    ``HOROVOD_HEALTH_RPC`` every ``interval`` seconds, and the
    :class:`~horovod_tpu.runner.rpc.KeepaliveMonitor` underneath
    distinguishes *dead* ranks (silent past ``deadline``) from *hung*
    ones (heartbeats alive, step stalled past ``hang_deadline``).
    A rank that never sent a single heartbeat is never declared dead
    here — start-up and first-compile stalls belong to the rendezvous
    timeouts, not the health plane."""

    def __init__(self, secret: str, interval: float, deadline: float,
                 hang_deadline: float):
        from horovod_tpu.runner import rpc
        self.interval = float(interval)
        self.deadline = float(deadline)
        self.hang_deadline = float(hang_deadline)
        self.monitor = rpc.KeepaliveMonitor(timeout=self.deadline,
                                            hang_deadline=self.hang_deadline)
        self._killed: set = set()
        self._preempt = False
        self._last_gauge = 0.0
        self.coord: Optional["_CoordinationPlane"] = None
        # Fail-in-place state (docs/fault_tolerance.md): the membership
        # epoch of the CURRENT attempt's world, pending reform specs
        # keyed by OLD rank, and the new->old rank alias so watchdog
        # verdicts on the reformed world map back to the launcher's
        # process table (which stays keyed by launch-time ranks).
        self.world_epoch = 0
        self._reform_specs: dict = {}
        self._rank_alias: dict = {}
        self._current_to_launch: dict = {}
        self._server = rpc.RpcServer(rpc.job_key_bytes(secret),
                                     self._handle)

    def _handle(self, req):
        if isinstance(req, dict) and req.get("kind") == "heartbeat":
            try:
                rank = int(req.get("rank", -1))
                epoch = int(req.get("epoch", 0))
            except (TypeError, ValueError):
                return {"ok": False}
            if self.coord is not None and epoch < self.coord.epoch:
                # A straggler from before the failover: its heartbeat
                # must not resurrect the dead epoch's liveness state.
                return {"ok": False, "stale_epoch": True}
            try:
                wepoch = int(req.get("world_epoch", 0))
            except (TypeError, ValueError):
                wepoch = 0
            if wepoch < self.world_epoch and not self._reform_specs:
                # Pre-reformation straggler after the handover finished:
                # its OLD rank number now names a different process.
                return {"ok": False, "stale_epoch": True}
            if self._reform_specs and wepoch < self.world_epoch:
                # Reformation in flight and this heartbeat still carries
                # the old world's numbering: deliver the rank's slice of
                # the new world but keep it OUT of the liveness monitor
                # (its old rank number will fall silent by design the
                # moment it re-inits, and must not read as a death).
                spec = self._reform_specs.get(
                    self._current_to_launch.get(rank, rank))
                return ({"ok": True, "reform": spec} if spec
                        else {"ok": True})
            if self._reform_specs:
                # First heartbeat from a reformed rank: its slice of the
                # handover is done.  (The rank-side epoch guard makes a
                # late duplicate delivery harmless, so dropping the spec
                # here — rather than on delivery — doubles as the retry
                # path for lost replies.)
                self._reform_specs.pop(self._rank_alias.get(rank, rank),
                                       None)
            try:
                self.monitor.progress(rank, int(req.get("step", -1)))
            except (TypeError, ValueError):
                return {"ok": False}
            if rank == 0 and self.coord is not None:
                # Rank 0's heartbeat doubles as the coordinator lease
                # renewal (docs/control_plane.md).
                self.coord.renew()
            return {"ok": True, "preempt": self._preempt}
        return {"ok": False}

    def request_preempt(self) -> None:
        """Ask every heartbeating rank to preempt (coordinated save +
        rc 75): subsequent heartbeat responses carry ``preempt: True``
        and the rank-side :class:`~horovod_tpu.resilience.HeartbeatSender`
        raises the deferred preemption flag.  This is the delivery path
        that reaches REMOTE ranks — the launcher's SIGTERM can only hit
        local process groups (for a remote rank, its ssh client)."""
        self._preempt = True

    @property
    def port(self) -> int:
        return self._server.port

    def begin_attempt(self, ranks) -> None:
        """Reset tracking for a fresh (re)launch — silence from the
        previous attempt's ranks is no longer a failure (after a shrink
        the old world's higher ranks must not haunt the monitor)."""
        del ranks  # the atomic clear covers old and new worlds alike
        self.monitor.forget_all()
        self._killed.clear()
        self._preempt = False   # the new attempt starts unpreempted
        # Fresh processes start at membership epoch 0 (reformations are
        # in-process events scoped to one attempt).
        self.world_epoch = 0
        self._reform_specs = {}
        self._rank_alias = {}
        self._current_to_launch = {}

    def request_reform(self, specs: dict, alias: dict,
                       epoch: int) -> None:
        """Arm an in-process world reformation: pending per-LAUNCH-rank
        specs ride out in heartbeat replies, the liveness monitor is
        wiped (old-rank silence during the handover is expected, not
        death — ranks re-register under their new numbers as they
        re-init), and watchdog verdicts translate through ``alias``
        (new rank -> launch-time rank) from here on."""
        self.monitor.forget_all()
        self._killed.clear()
        # Survivors still heartbeat under the numbering of the world
        # being torn down; after a SECOND reformation that numbering is
        # the previous alias's "new" side, not the launch ranks the
        # specs are keyed by.
        self._current_to_launch = dict(self._rank_alias)
        self._reform_specs = dict(specs)
        self._rank_alias = dict(alias)
        self.world_epoch = int(epoch)

    def watchdog(self) -> list:
        """``(rank, reason)`` pairs newly declared dead or hung since the
        last call; each rank is reported once per attempt (it is about to
        be killed).  Also refreshes the ``hvd_worker_step_lag`` straggler
        gauges, throttled to one update per heartbeat interval."""
        now = time.monotonic()
        if now - self._last_gauge >= self.interval:
            self._last_gauge = now
            for r, lag in sorted(self.monitor.step_lags().items()):
                telemetry.gauge(
                    "hvd_worker_step_lag",
                    "Steps this worker trails the fastest worker "
                    "(heartbeat health plane)", rank=str(r)).set(float(lag))
        out = []
        for r in self.monitor.dead_tasks():
            if r not in self._killed:
                self._killed.add(r)
                out.append((self._rank_alias.get(r, r),
                            f"sent no heartbeat for > "
                            f"{self.deadline:g}s"))
        for r in self.monitor.hung_tasks():
            if r not in self._killed:
                self._killed.add(r)
                out.append((self._rank_alias.get(r, r),
                            f"is hung: heartbeats alive but the step "
                            f"stalled > {self.hang_deadline:g}s"))
        return out

    def shutdown(self) -> None:
        self._server.shutdown()


class _CoordinationPlane:
    """Launcher half of coordinator failover (docs/control_plane.md).

    The coordinator lease IS the heartbeat stream from rank 0: every
    rank-0 heartbeat renews it, so the existing health-plane deadline
    doubles as lease expiry.  When the coordinator's host drops out of
    the usable set (watchdog kill, crash, unreachable), the next
    attempt runs the deterministic election — the first healthy host in
    host-major order (the "lowest healthy leader" of
    :func:`horovod_tpu.coordination.elect`) is promoted to the front of
    the list, its first slot becomes the new rank 0, and the epoch
    bumps.  The rendezvous itself lives in the launcher process, so
    re-pointing the gang is just the fresh attempt's allocation; ranks
    learn the epoch from ``HOROVOD_COORD_EPOCH`` and discard any
    in-flight control state from the dead epoch."""

    def __init__(self, lease_term: float,
                 clock: Callable[[], float] = time.monotonic):
        from horovod_tpu import coordination
        self._clock = clock
        self.lease = coordination.LeaseState(lease_term, holder=0,
                                             now=clock())
        self.coordinator_host: Optional[str] = None
        self.epoch = 0
        self.elections = 0

    def renew(self) -> None:
        """A rank-0 heartbeat arrived: the coordinator host lives."""
        self.lease.renew(self._clock(), holder=0, epoch=self.epoch)

    def ensure_coordinator(self, usable):
        """Pin the coordinator host for the coming attempt, electing a
        replacement when the incumbent is gone.  Returns the (possibly
        reordered) host list."""
        names = [h.hostname for h in usable]
        if not names:
            return usable
        if self.coordinator_host is None:
            self.coordinator_host = names[0]
        elif self.coordinator_host not in names:
            dead = self.coordinator_host
            self.epoch += 1
            self.elections += 1
            # Host-major order makes names[0] the lowest healthy
            # leader — the same deterministic rule coordination.elect
            # applies to leader ranks.
            self.coordinator_host = names[0]
            self.lease.renew(self._clock(), holder=0, epoch=self.epoch)
            telemetry.counter(
                "hvd_coord_elections_total",
                "Coordinator re-elections after lease expiry").inc()
            print(f"hvdrun: coordinator lease expired (host {dead} "
                  f"gone); elected host {self.coordinator_host} as "
                  f"coordinator epoch={self.epoch}",
                  file=sys.stderr, flush=True)
        telemetry.gauge(
            "hvd_coord_epoch",
            "Coordinator lease epoch (bumps on each re-election)"
        ).set(float(self.epoch))
        return hosts.promote_host(usable, self.coordinator_host)

    def env(self) -> dict:
        """Per-attempt env injection: ranks stamp control messages with
        the epoch and surface it in stall reports."""
        return {"HOROVOD_COORD_EPOCH": str(self.epoch),
                "HOROVOD_COORD_RANK": "0",
                "HOROVOD_COORD_ELECTIONS": str(self.elections)}


class _MetricsCollector:
    """Launcher-side sink for the ranks' at-exit metrics reports.

    Rides the existing authenticated RPC plane (``runner/rpc.py``): each
    rank's telemetry exit hook pushes its ``horovod_tpu.metrics.v1``
    document to ``HOROVOD_METRICS_RPC``, and the launcher merges the
    collected reports (falling back to the ranks' JSON files for any
    rank whose push never arrived — SIGKILLed ranks don't push).
    Reports are keyed by rank, so an elastic restart's fresh attempt
    simply overwrites the previous attempt's rows."""

    def __init__(self, secret: str):
        from horovod_tpu.runner import rpc
        self.reports: dict = {}
        self._server = rpc.RpcServer(rpc.job_key_bytes(secret),
                                     self._handle)

    def _handle(self, req):
        if isinstance(req, dict) and req.get("kind") == "metrics_report":
            report = req.get("report")
            if isinstance(report, dict):
                self.reports[str(report.get("rank", "?"))] = report
                return {"ok": True}
        if isinstance(req, dict) and req.get("kind") == "time_sync":
            # Clock-skew handshake (rpc.measure_clock_offset): answered
            # here too — hvd_clock_skew_seconds rides the metrics plane
            # even when --trace is off.
            from horovod_tpu.runner import rpc
            return rpc.time_sync_reply()
        return {"ok": False}

    @property
    def port(self) -> int:
        return self._server.port

    def shutdown(self) -> None:
        self._server.shutdown()


class _TraceCollector:
    """Launcher-side sink for the ranks' at-exit span logs
    (``hvdrun --trace``) plus the time-sync responder of the clock-skew
    handshake.  Same authenticated RPC plane and rank-keyed overwrite
    semantics as :class:`_MetricsCollector`; ranks whose push never
    arrives fall back to their ``spans.rank<k>.json`` files."""

    def __init__(self, secret: str):
        from horovod_tpu.runner import rpc
        self._rpc = rpc
        self.reports: dict = {}
        self._server = rpc.RpcServer(rpc.job_key_bytes(secret),
                                     self._handle)

    def _handle(self, req):
        if isinstance(req, dict):
            kind = req.get("kind")
            if kind == "time_sync":
                return self._rpc.time_sync_reply()
            if kind == "trace_report":
                report = req.get("report")
                if isinstance(report, dict):
                    self.reports[int(report.get("rank", 0))] = report
                    return {"ok": True}
        return {"ok": False}

    @property
    def port(self) -> int:
        return self._server.port

    def shutdown(self) -> None:
        self._server.shutdown()


def _per_rank_metrics_path(base: str, rank: int) -> str:
    root, ext = os.path.splitext(base)
    return f"{root}.rank{rank}{ext or '.json'}"


def _write_metrics_summary(path: str, collector: "_MetricsCollector",
                           world_size: int, exit_code: int) -> None:
    """Merge the per-rank reports into one attributed summary document
    (``horovod_tpu.metrics.summary.v1``) at the ``--metrics-file`` path."""
    from horovod_tpu.telemetry import aggregate
    ranks = dict(collector.reports)
    for rank in range(world_size):
        if str(rank) in ranks:
            continue
        try:
            with open(_per_rank_metrics_path(path, rank)) as f:
                ranks[str(rank)] = json.load(f)
        except (OSError, ValueError):
            pass  # rank died before dumping; it is simply absent
    snapshots = {k: r.get("metrics") or {} for k, r in ranks.items()}
    snapshots["launcher"] = telemetry.metrics_snapshot()
    doc = {
        "schema": "horovod_tpu.metrics.summary.v1",
        "world_size": world_size,
        "exit_code": exit_code,
        "launcher": {
            "host": socket.gethostname(),
            "pid": os.getpid(),
            "metrics": telemetry.metrics_snapshot(),
        },
        "ranks": ranks,
        "merged": aggregate.merge_snapshots(snapshots),
    }
    dirname = os.path.dirname(os.path.abspath(path))
    os.makedirs(dirname, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    missing = sorted(r for r in range(world_size) if str(r) not in ranks)
    print(f"hvdrun: metrics summary ({len(ranks)}/{world_size} ranks"
          + (f"; missing {missing}" if missing else "")
          + f") written to {path}", file=sys.stderr, flush=True)
    # Headline latency distribution: the merged eager-op histogram's
    # estimated percentiles (aggregate.estimate_percentiles).
    for entry in doc["merged"].get(
            "hvd_eager_op_seconds", {}).get("values", []):
        pct = entry.get("percentiles")
        if pct:
            op = (entry.get("labels") or {}).get("op", "?")
            print(f"hvdrun: {op} latency estimate: " + "  ".join(
                f"{q}={v * 1e3:.2f}ms" for q, v in sorted(pct.items())),
                file=sys.stderr, flush=True)
    # Per-rank clock offsets measured by the time-sync handshake — the
    # operator-visible skew bound for cross-rank timeline comparison.
    skew = doc["merged"].get("hvd_clock_skew_seconds", {})
    for entry in skew.get("values", []):
        print(f"hvdrun: rank clock skew vs launcher: "
              f"min {entry.get('min', 0.0) * 1e3:.3f}ms / "
              f"max {entry.get('max', 0.0) * 1e3:.3f}ms",
              file=sys.stderr, flush=True)


def _write_trace_outputs(dir_path: str, tracer: "_TraceCollector",
                         world_size: int) -> None:
    """Merge the collected span logs into ``DIR/trace.json`` (skew-
    corrected Chrome/Perfetto trace), write the critical-path analysis
    to ``DIR/critical_path.json``, mirror it into the launcher's
    ``hvd_critical_path_*`` gauges, and print the straggler report."""
    from horovod_tpu.telemetry import critical_path, trace_merge
    reports = dict(tracer.reports)
    for rank, doc in trace_merge.load_rank_docs(dir_path).items():
        reports.setdefault(rank, doc)   # RPC push wins over the file
    if not reports:
        print(f"hvdrun: trace requested but no rank delivered a span "
              f"log (dir {dir_path})", file=sys.stderr, flush=True)
        return
    os.makedirs(dir_path, exist_ok=True)
    events = trace_merge.merge_span_docs(
        reports[r] for r in sorted(reports))
    merged_path = trace_merge.write_chrome(
        events, os.path.join(dir_path, "trace.json"))
    result = critical_path.analyze(reports)
    cp_path = os.path.join(dir_path, "critical_path.json")
    tmp = f"{cp_path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(result, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, cp_path)
    critical_path.publish_gauges(result)
    print(f"hvdrun: merged trace ({len(events)} events, "
          f"{len(reports)}/{world_size} ranks) written to {merged_path}",
          file=sys.stderr, flush=True)
    print(critical_path.format_report(result), file=sys.stderr,
          flush=True)


def _demote_failed_hosts(blacklist, host_list, failed, min_np) -> None:
    """Soft demotion after rank failures: blame the host of each crashed
    rank, but only while the surviving capacity still covers --min-np.
    (A single-host job therefore never blacklists its only host — the
    crash is a process problem, and relaunching in place is strictly
    better than refusing to.)  Unreachability, by contrast, is a HARD
    demotion in the re-probe above: a dead host can serve no world size.
    """
    for rank, hostname, code in failed:
        if code == PREEMPTION_RC:
            # Defense in depth: launch_job already files preempted ranks
            # under report["preempted"], but a preemption must never
            # blacklist a host even if one leaks through here.
            continue
        if blacklist.is_blacklisted(hostname):
            continue
        remaining = sum(
            h.slots for h in host_list
            if h.hostname != hostname and
            not blacklist.is_blacklisted(h.hostname))
        if remaining >= min_np:
            blacklist.demote(hostname,
                             f"rank {rank} exited with code {code}")
            print(f"hvdrun: blacklisting host {hostname} (rank {rank} "
                  f"exited with code {code})", file=sys.stderr, flush=True)
        else:
            print(f"hvdrun: keeping host {hostname} despite rank {rank} "
                  f"exiting with code {code}: demoting it would leave "
                  f"{remaining} slot(s) < --min-np {min_np}",
                  file=sys.stderr, flush=True)


def _plan_reformation(survivors, addr, port, epoch):
    """Contiguous re-ranking of the survivors: per-OLD-rank reform
    specs plus the new->old rank alias.

    Survivor order is launch-rank order, which keeps ranks host-major-
    contiguous (hosts.allocate is host-major and removal preserves
    order), so per-host local/cross coordinates and the topology string
    recompute directly from the ordered hostname sequence."""
    ordered = sorted(survivors, key=lambda i: i.rank)
    new_size = len(ordered)
    local_size = {}
    for info in ordered:
        local_size[info.hostname] = local_size.get(info.hostname, 0) + 1
    host_order = list(dict.fromkeys(i.hostname for i in ordered))
    topology = hosts.topology_string(ordered)
    specs, alias = {}, {}
    local_rank = {}
    for new_rank, info in enumerate(ordered):
        lr = local_rank.get(info.hostname, 0)
        local_rank[info.hostname] = lr + 1
        specs[info.rank] = {
            "epoch": epoch,
            "rank": new_rank,
            "size": new_size,
            "local_rank": lr,
            "local_size": local_size[info.hostname],
            "cross_rank": host_order.index(info.hostname),
            "cross_size": len(host_order),
            "rendezvous_addr": addr,
            "rendezvous_port": port,
            "topology": topology,
            # One death per reformation event: the world being torn
            # down had exactly one more rank (RankInfo.size would be
            # stale after a SECOND reformation in the same attempt).
            "prev_size": new_size + 1,
        }
        alias[new_rank] = info.rank
    return specs, alias


def _launch_once(args, infos, addr, extra_env, report=None,
                 metrics_file=None, collector=None, health=None,
                 trace_dir=None, tracer=None, on_rank_failure=None,
                 min_np=None) -> int:
    port = args.rendezvous_port or launch.find_free_port()
    if getattr(args, "jax_distributed", False):
        # The jax.distributed coordinator runs INSIDE rank 0 (unlike the
        # controller rendezvous, which lives in this launcher process),
        # so the port must be free on rank 0's host.  A launcher-side
        # free-port probe is only authoritative when rank 0 is local;
        # multi-host jobs should pin --jax-coordinator-port.
        jport = args.jax_coordinator_port or launch.find_free_port()
        extra_env["HOROVOD_JAX_DISTRIBUTED"] = "1"
        extra_env["HOROVOD_COORDINATOR_ADDR"] = f"{addr}:{jport}"
    multi_host = len({i.hostname for i in infos}) > 1
    # Serialized host→slots map for hvd.topology() (recomputed per attempt,
    # so elastic/fleet resizes re-export the surviving allocation).
    extra_env["HOROVOD_TOPOLOGY"] = hosts.topology_string(infos)
    env_per_rank = [
        config_parser.runtime_env(info, addr, port, extra_env,
                                  multi_host=multi_host)
        for info in infos
    ]
    if metrics_file and collector is not None:
        # Per-rank dump paths are assigned HERE (not left to the ranks'
        # own per_rank_path de-confliction) so the launcher knows exactly
        # which files to fall back to when a rank's RPC push never lands.
        for info, env in zip(infos, env_per_rank):
            env["HOROVOD_METRICS_FILE"] = _per_rank_metrics_path(
                metrics_file, info.rank)
            env["HOROVOD_METRICS_RPC"] = f"{addr}:{collector.port}"
    if trace_dir and tracer is not None:
        # Tracing rides its own env triple: the flag arms the recorders
        # (Python + native), the RPC endpoint is the push/time-sync
        # target, and the dir is each rank's file fallback.
        for env in env_per_rank:
            env["HOROVOD_TRACE"] = "1"
            env["HOROVOD_TRACE_DIR"] = trace_dir
            env["HOROVOD_TRACE_RPC"] = f"{addr}:{tracer.port}"
    watchdog = None
    if health is not None:
        for env in env_per_rank:
            env["HOROVOD_HEALTH_RPC"] = f"{addr}:{health.port}"
            env["HOROVOD_HEARTBEAT_INTERVAL"] = str(health.interval)
        health.begin_attempt([i.rank for i in infos])
        watchdog = health.watchdog
    if args.verbose:
        for info in infos:
            print(f"hvdrun: rank {info.rank} -> {info.hostname} "
                  f"(local {info.local_rank}/{info.local_size}, "
                  f"cross {info.cross_rank}/{info.cross_size})")
    reform = None
    if health is not None and on_rank_failure in ("shrink",
                                                  "shrink-then-restart"):
        def reform(dead_info, rc, survivors):
            floor = min_np or 1
            if len(survivors) < floor:
                print(f"hvdrun: not reforming in-process: "
                      f"{len(survivors)} survivor(s) < --min-np {floor}",
                      file=sys.stderr, flush=True)
                return False
            epoch = health.world_epoch + 1
            # Fresh rendezvous port: the dead world's listener may
            # linger in TIME_WAIT and survivors must not rejoin it.
            new_port = launch.find_free_port()
            ordered = sorted(survivors, key=lambda i: i.rank)
            new_addr = ("127.0.0.1"
                        if all(launch.is_local(i.hostname)
                               for i in ordered)
                        else ordered[0].hostname)
            specs, alias = _plan_reformation(ordered, new_addr,
                                             new_port, epoch)
            health.request_reform(specs, alias, epoch)
            # Booked ONCE, launcher-side, so the merged metrics count
            # each reformation event exactly once regardless of how
            # many ranks survive it.
            telemetry.counter(
                "hvd_failinplace_reformations_total",
                "In-process world reformations after a rank death "
                "(fail-in-place shrink, no elastic restart)").inc()
            telemetry.gauge(
                "hvd_failinplace_world_epoch",
                "Membership epoch of the running attempt's world "
                "(0 = never reformed)").set(float(epoch))
            print(f"hvdrun: fail-in-place: rank {dead_info.rank} "
                  f"(host {dead_info.hostname}) died with code {rc}; "
                  f"reforming the world in-process as epoch {epoch} "
                  f"with {len(ordered)} rank(s)",
                  file=sys.stderr, flush=True)
            return True
    # Keyword only when armed: callers (and tests) that stub launch_job
    # with the historical signature stay compatible on the default path.
    lkw = {"reform": reform} if reform is not None else {}
    return launch.launch_job(
        infos, args.command, env_per_rank,
        output_dir=args.output_filename,
        start_timeout=args.start_timeout,
        report=report,
        watchdog=watchdog,
        **lkw)


def main(argv: List[str] = None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    if args.check_build:
        print(check_build())
        return 0
    config_parser.apply_config_file(args, parser)
    if args.command and args.command[0] == "--":
        args.command = args.command[1:]
    if not args.command:
        parser.error("no command given")
    return run_command(args)


if __name__ == "__main__":
    sys.exit(main())

"""CLI args <-> HOROVOD_* environment, plus YAML config-file support.

Reference equivalent: ``run/common/util/config_parser.py`` (arg->env
``set_env_from_args``) and the ``--config-file`` handling with CLI-override
precedence (``run/run.py:581-585``).
"""

from __future__ import annotations

import os
from typing import Dict

# arg attribute -> env var (reference config_parser.py constants).
_ARG_ENV = {
    "fusion_threshold_mb": "HOROVOD_FUSION_THRESHOLD",   # scaled to bytes
    "cycle_time_ms": "HOROVOD_CYCLE_TIME",
    "cache_capacity": "HOROVOD_CACHE_CAPACITY",
    "timeline_filename": "HOROVOD_TIMELINE",
    "timeline_mark_cycles": "HOROVOD_TIMELINE_MARK_CYCLES",
    "stall_check_time_seconds": "HOROVOD_STALL_CHECK_TIME_SECONDS",
    "stall_shutdown_time_seconds": "HOROVOD_STALL_SHUTDOWN_TIME_SECONDS",
    "autotune": "HOROVOD_AUTOTUNE",
    "autotune_log_file": "HOROVOD_AUTOTUNE_LOG",
    "log_level": "HOROVOD_LOG_LEVEL",
    "log_hide_timestamp": "HOROVOD_LOG_HIDE_TIME",
    "network_interface": "HOROVOD_NETWORK_INTERFACE",
}

# config-file YAML key -> arg attribute (reference run.py:374-587 arg names).
_CONFIG_ARGS = {
    "fusion-threshold-mb": "fusion_threshold_mb",
    "cycle-time-ms": "cycle_time_ms",
    "cache-capacity": "cache_capacity",
    "timeline-filename": "timeline_filename",
    "timeline-mark-cycles": "timeline_mark_cycles",
    "metrics-file": "metrics_file",
    "stall-check-time-seconds": "stall_check_time_seconds",
    "stall-shutdown-time-seconds": "stall_shutdown_time_seconds",
    "autotune": "autotune",
    "autotune-log-file": "autotune_log_file",
    "verbose": "verbose",
    "min-np": "min_np",
    "blacklist-cooldown": "blacklist_cooldown",
    "log-level": "log_level",
    "log-hide-timestamp": "log_hide_timestamp",
    "network-interface": "network_interface",
}


def env_from_args(args) -> Dict[str, str]:
    """Build the HOROVOD_* env dict from parsed launcher args (reference
    ``set_env_from_args``)."""
    env: Dict[str, str] = {}
    for attr, var in _ARG_ENV.items():
        v = getattr(args, attr, None)
        if v is None or v is False:
            continue
        if attr == "fusion_threshold_mb":
            env[var] = str(int(float(v) * 1024 * 1024))
        elif isinstance(v, bool):
            env[var] = "1"
        else:
            env[var] = str(v)
    return env


def apply_config_file(args, parser) -> None:
    """Overlay YAML config values onto args, CLI flags winning (reference
    run.py:581-585, tested by test_run.py:161-212)."""
    if not getattr(args, "config_file", None):
        return
    import yaml

    with open(args.config_file) as f:
        config = yaml.safe_load(f) or {}
    # Flags explicitly given on the CLI take precedence: compare against the
    # parser defaults to detect explicit settings.
    defaults = {a.dest: a.default for a in parser._actions}
    for key, value in config.items():
        attr = _CONFIG_ARGS.get(key)
        if attr is None:
            raise ValueError(
                f"unknown config file key {key!r}; valid keys: "
                f"{sorted(_CONFIG_ARGS)}")
        if getattr(args, attr, None) == defaults.get(attr):
            setattr(args, attr, value)


def runtime_env(info, rendezvous_addr: str, rendezvous_port: int,
                extra: Dict[str, str],
                multi_host: bool = False) -> Dict[str, str]:
    """Per-rank environment (reference gloo_run.py:211-254 env contract).

    When HOROVOD_NETWORK_INTERFACE is in the rank's env (from the
    ``--network-interface`` flag, the launcher's inherited env, or a
    per-host override), the launcher's generic per-host name is NOT
    injected: it would shadow the resolved interface address the runtime
    advertises.  An explicit user HOROVOD_HOSTNAME survives (it is the
    advertise-only override, docs/running.md) — except on MULTI-host
    jobs when it merely leaked in from the launcher's shell: one
    job-wide advertise address would point every rank at one machine, so
    the per-host name wins there (with a warning).
    """
    env = dict(os.environ)
    env.update(extra)
    if multi_host and "HOROVOD_HOSTNAME" not in extra and \
            os.environ.get("HOROVOD_HOSTNAME"):
        if info.rank == 0:
            import sys
            print("hvdrun: ignoring HOROVOD_HOSTNAME="
                  f"{os.environ['HOROVOD_HOSTNAME']} inherited from the "
                  "launcher's environment: a single advertise address is "
                  "wrong for a multi-host job (set it per host, or use "
                  "--network-interface)", file=sys.stderr)
        del env["HOROVOD_HOSTNAME"]
    env.update({
        "HOROVOD_RANK": str(info.rank),
        "HOROVOD_SIZE": str(info.size),
        "HOROVOD_LOCAL_RANK": str(info.local_rank),
        "HOROVOD_LOCAL_SIZE": str(info.local_size),
        "HOROVOD_CROSS_RANK": str(info.cross_rank),
        "HOROVOD_CROSS_SIZE": str(info.cross_size),
        "HOROVOD_RENDEZVOUS_ADDR": rendezvous_addr,
        "HOROVOD_RENDEZVOUS_PORT": str(rendezvous_port),
        "HOROVOD_CONTROLLER": "tcp",
        "HOROVOD_CPU_OPERATIONS": "tcp",
    })
    if not env.get("HOROVOD_NETWORK_INTERFACE") and \
            not env.get("HOROVOD_HOSTNAME"):
        # Inject the generic per-host name only when the operator pinned
        # neither knob: an explicit HOROVOD_HOSTNAME is the documented
        # advertise-only override and must survive (note it is job-wide
        # when exported on the launcher — per-HOST advertise addresses
        # come from hostfile names or per-host HOROVOD_NETWORK_INTERFACE).
        env["HOROVOD_HOSTNAME"] = info.hostname
    return env


def job_secret() -> str:
    """Fresh per-job shared secret for the runtime's connection
    authentication (reference ``run/common/util/secret.py`` — an HMAC key
    generated by the launcher and distributed to every rank).  The native
    controller/data plane run a mutual HMAC-SHA256 handshake with it on
    every connection, so arbitrary processes cannot claim a rank."""
    import base64
    import secrets
    return base64.urlsafe_b64encode(secrets.token_bytes(32)).decode()

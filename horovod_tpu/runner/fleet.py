"""``hvdfleet`` — priority gang-scheduling fleet controller.

One controller owns a host pool and arbitrates many jobs over it — the
production shape of the reference's driver layer (the Spark driver/task
plane orchestrating many tasks over one cluster) generalized beyond
Spark.  Two bare ``hvdrun`` invocations pointed at the same hosts
collide on slots, rendezvous ports, metrics ports and spill dirs; the
fleet controller is the arbiter that makes concurrent jobs safe:

* **Gang admission** — a job starts only when a full gang of at least
  ``min_np`` slots is free, and takes up to ``max_np`` when capacity
  allows.  Admission is strictly priority-ordered (no backfill): a
  small low-priority job never jumps a queued high-priority one,
  because that is exactly the inversion the fleet exists to prevent.
* **Preemption** — when the head queued job has starved past
  ``--starvation-deadline`` and lower-priority jobs hold its slots,
  the controller preempts the lowest-priority running jobs through the
  existing SIGTERM → coordinated-save → rc-75 path
  (:mod:`horovod_tpu.resilience`): victims save, exit
  :data:`~horovod_tpu.resilience.PREEMPTION_RC`, requeue WITHOUT host
  blame, and resume from their save when capacity frees.
* **Elastic resize** — spare capacity with nothing admissible queued
  grows a running job toward ``max_np`` (a controlled preempt +
  re-admit, riding the PR-5 warm-restart plane with
  ``HOROVOD_ELASTIC_PREV_SIZE`` continuity); capacity loss (host
  demotion, a bigger job's admission) shrinks it the same way, never
  below ``min_np``.
* **Serving autoscaling** — ``type=serving`` jobs (the inference plane,
  :mod:`horovod_tpu.serving`) admit at ``min_np`` and are resized by
  queue-depth / p99-latency telemetry their router publishes through a
  per-job stats file (``HOROVOD_SERVING_STATS``): pressure grows them
  toward ``max_np`` — preempting lower-priority batch training when no
  slots are free — and sustained calm shrinks them back, returning the
  capacity (``--serving-scale-up-depth``, ``--serving-scale-down-idle``).
* **Shared blame** — one :class:`~horovod_tpu.runner.hosts.HostBlacklist`
  spans all jobs: a host demoted under job A is avoided by job B.
* **Isolation** — per job: fresh secret, own rendezvous port, own spill
  dir (stable across requeues, so warm restart finds its peers' state),
  own metrics files, and an own metrics-port base
  (``--metrics-port-base`` + job-index × ``--port-stride``) so two
  jobs' ranks on one host never fight over an exporter port.

Scheduling is a deterministic tick loop (``tick()``), injectable clock
and job runner included, so unit tests drive episodes without spawning
processes.  Chaos hooks: :func:`horovod_tpu.faults.fleet_chaos`
(``preempt_storm`` / ``host_flap``, site ``fleet``).
"""

from __future__ import annotations

import argparse
import json
import os
import shlex
import signal
import socket
import sys
import tempfile
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from horovod_tpu import faults, telemetry
from horovod_tpu.resilience import PREEMPTION_RC
from horovod_tpu.runner import config_parser, hosts, launch

# Job lifecycle.  PREEMPTING covers both scheduler preemptions and
# controlled resizes — the job was asked to save and exit; its slots
# free at reap time.
QUEUED = "queued"
RUNNING = "running"
PREEMPTING = "preempting"
DONE = "done"
FAILED = "failed"
STOPPED = "stopped"

_LIVE_STATES = (QUEUED, RUNNING, PREEMPTING)


@dataclass
class JobSpec:
    """One job line: ``name priority min_np[:max_np] [key=val ...] --
    command ...``."""
    name: str
    priority: int
    min_np: int
    max_np: int
    command: List[str]
    after: float = 0.0        # submit delay (seconds from fleet start)
    restarts: int = 2         # failure-restart budget (preemptions free)
    type: str = "batch"       # "batch" | "serving" (autoscaled replicas)
    env: Dict[str, str] = field(default_factory=dict)


def parse_job_spec(line: str) -> JobSpec:
    """Parse one job line.

    Grammar: ``name priority min_np[:max_np] [after=S] [restarts=N]
    [env:KEY=VAL ...] -- command args...``.  The ``--`` separator is
    mandatory so job metadata can grow without ever being confused for
    the command.
    """
    tokens = shlex.split(line)
    if "--" not in tokens:
        raise ValueError(
            f"job spec {line!r} has no ' -- ' separating metadata from "
            f"the command")
    sep = tokens.index("--")
    meta, command = tokens[:sep], tokens[sep + 1:]
    if len(meta) < 3:
        raise ValueError(
            f"job spec {line!r} needs at least 'name priority "
            f"min_np[:max_np]' before ' -- '")
    if not command:
        raise ValueError(f"job spec {line!r} has an empty command")
    name = meta[0]
    if not name or any(c in name for c in "/\\ \t"):
        raise ValueError(f"bad job name {name!r} (used for directories "
                         f"and metric labels)")
    try:
        priority = int(meta[1])
    except ValueError:
        raise ValueError(f"job {name}: priority {meta[1]!r} is not an int")
    np_spec = meta[2]
    lo, _, hi = np_spec.partition(":")
    try:
        min_np = int(lo)
        max_np = int(hi) if hi else min_np
    except ValueError:
        raise ValueError(
            f"job {name}: np spec {np_spec!r} is not min_np[:max_np]")
    if min_np < 1 or max_np < min_np:
        raise ValueError(
            f"job {name}: need 1 <= min_np <= max_np (got {np_spec!r})")
    spec = JobSpec(name=name, priority=priority, min_np=min_np,
                   max_np=max_np, command=command)
    for extra in meta[3:]:
        key, eq, value = extra.partition("=")
        if not eq:
            raise ValueError(
                f"job {name}: metadata {extra!r} is not key=value")
        if key == "after":
            spec.after = float(value)
        elif key == "restarts":
            spec.restarts = int(value)
        elif key == "type":
            if value not in ("batch", "serving"):
                raise ValueError(
                    f"job {name}: unknown job type {value!r} (valid: "
                    f"batch, serving)")
            spec.type = value
        elif key.startswith("env:") and len(key) > 4:
            spec.env[key[4:]] = value
        else:
            raise ValueError(
                f"job {name}: unknown metadata key {key!r} (valid: "
                f"after=, restarts=, type=, env:KEY=)")
    return spec


class _Job:
    """Controller-side state for one spec across its whole lifetime
    (admissions, preemptions, resizes, restarts)."""

    def __init__(self, spec: JobSpec, index: int, fleet_dir: str):
        self.spec = spec
        self.index = index          # submission order; also port offset
        self.state = QUEUED
        self.dir = os.path.join(fleet_dir, "jobs", spec.name)
        self.spill_dir = os.path.join(self.dir, "spill")
        self.metrics_base = os.path.join(self.dir, "metrics.json")
        self.stats_path = os.path.join(self.dir, "serving_stats.json")
        self.secret = config_parser.job_secret()
        self.queued_at = 0.0        # set on (re)queue by the controller
        self.eligible_at = 0.0
        self.started_at = 0.0
        self.preempt_at = 0.0
        self.attempt = 0            # launch counter (HOROVOD_RESTART_ATTEMPT)
        self.restarts_left = spec.restarts
        self.np = 0                 # current world size (0 = not running)
        self.prev_np: Optional[int] = None   # last world size, for PREV_SIZE
        self.preempted = False      # queued-for-resume (vs never-started)
        self.resizing = False       # current PREEMPTING is a resize, not
                                    # a scheduler/chaos preemption
        self.target_np = None       # autoscaler-chosen size for the next
                                    # admission (serving resizes only)
        self.calm_since = 0.0       # start of the current low-pressure
                                    # window (serving scale-down timer)
        self.preemptions = 0
        self.rc: Optional[int] = None
        self.infos: List[hosts.RankInfo] = []
        self.control: Optional[launch.JobControl] = None
        self.health = None          # per-job _HealthPlane, if enabled
        self.chaos_kills: List = [] # pending (rank, reason) kill orders
                                    # from fleet-site rank_kill chaos,
                                    # drained by the job's watchdog
        self.thread: Optional[threading.Thread] = None
        self.result = None          # (rc, report) set by the job thread
        self.starve_logged = False

    @property
    def name(self) -> str:
        return self.spec.name

    @property
    def priority(self) -> int:
        return self.spec.priority


class FleetController:
    """The scheduler.  ``tick()`` is one deterministic pass (reap →
    chaos → starvation/preemption → admission → grow → gauges);
    ``run()`` loops it.  ``clock``/``sleep``/``job_runner`` are
    injectable so tests drive whole episodes synchronously.

    ``job_runner(job, infos, env_per_rank, control, report) -> rc``
    replaces process spawning in unit tests; the default runs
    :func:`horovod_tpu.runner.launch.launch_job` in a worker thread with
    ``install_signal_handlers=False`` and a
    :class:`~horovod_tpu.runner.launch.JobControl`.
    """

    def __init__(self, pool: List[hosts.HostSlots], specs: List[JobSpec],
                 *, starvation_deadline: float = 30.0,
                 tick_interval: float = 0.25,
                 grow_after: float = 15.0,
                 serving_scale_up_depth: float = 8.0,
                 serving_scale_down_idle: float = 10.0,
                 blacklist: Optional[hosts.HostBlacklist] = None,
                 blacklist_cooldown: Optional[float] = None,
                 fleet_dir: Optional[str] = None,
                 metrics_file: Optional[str] = None,
                 metrics_port_base: int = 0,
                 port_stride: int = 64,
                 output_dir: Optional[str] = None,
                 heartbeat_interval: float = 0.0,
                 hang_deadline: float = 0.0,
                 start_timeout: Optional[float] = None,
                 extra_env: Optional[Dict[str, str]] = None,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep,
                 job_runner=None,
                 verbose: bool = False):
        if not pool:
            raise ValueError("fleet needs a non-empty host pool")
        if not specs:
            raise ValueError("fleet needs at least one job spec")
        names = [s.name for s in specs]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate job names in {names}")
        self.pool = list(pool)
        self.starvation_deadline = float(starvation_deadline)
        self.tick_interval = float(tick_interval)
        self.grow_after = float(grow_after)
        self.serving_scale_up_depth = float(serving_scale_up_depth)
        self.serving_scale_down_idle = float(serving_scale_down_idle)
        self.blacklist = blacklist or hosts.HostBlacklist(
            cooldown=blacklist_cooldown)
        self._permanent_blacklist = (blacklist is None and
                                     blacklist_cooldown is None)
        self.fleet_dir = fleet_dir or tempfile.mkdtemp(prefix="hvd-fleet-")
        self.metrics_file = metrics_file
        self.metrics_port_base = int(metrics_port_base or 0)
        self.port_stride = int(port_stride)
        self.output_dir = output_dir
        self.heartbeat_interval = float(heartbeat_interval or 0.0)
        self.hang_deadline = float(hang_deadline or 0.0)
        self.start_timeout = start_timeout
        self.extra_env = dict(extra_env or {})
        self._clock = clock
        self._sleep = sleep
        self._job_runner = job_runner or self._run_job_process
        self.verbose = verbose
        self._stopping = False
        self._used: Dict[str, int] = {}
        self._flapped: set = set()  # hosts chaos host_flap will restore
        self.jobs = [_Job(s, i, self.fleet_dir)
                     for i, s in enumerate(specs)]
        self._t0 = self._clock()
        total = sum(h.slots for h in self.pool)
        for job in self.jobs:
            job.queued_at = self._t0
            job.eligible_at = self._t0 + job.spec.after
            if job.spec.min_np > total:
                job.state = FAILED
                job.rc = 1
                self._log(f"job {job.name} can never fit: min_np "
                          f"{job.spec.min_np} > pool capacity {total}")

    # -- plumbing ----------------------------------------------------------

    def _log(self, msg: str) -> None:
        print(f"hvdfleet: {msg}", file=sys.stderr, flush=True)

    def _usable_pool(self) -> List[hosts.HostSlots]:
        return self.blacklist.filter(self.pool)

    def _free_hosts(self) -> List[hosts.HostSlots]:
        return hosts.free_slots(self._usable_pool(), self._used)

    def _queued(self) -> List[_Job]:
        """Eligible queued jobs in admission order: priority first, then
        longest-waiting, then submission order."""
        now = self._clock()
        out = [j for j in self.jobs
               if j.state == QUEUED and now >= j.eligible_at]
        out.sort(key=lambda j: (-j.priority, j.queued_at, j.index))
        return out

    def _running(self) -> List[_Job]:
        return [j for j in self.jobs if j.state == RUNNING]

    def alive(self) -> bool:
        return any(j.state in _LIVE_STATES for j in self.jobs)

    # -- scheduling pass ---------------------------------------------------

    def tick(self) -> bool:
        """One scheduling pass; returns True while any job is live."""
        self._reap()
        if not self._stopping:
            self._apply_chaos()
            self._autoscale_serving()
            self._check_starvation()
            self._admit()
            self._maybe_grow()
            self._fail_unsatisfiable()
        self._update_gauges()
        return self.alive()

    # -- reaping -----------------------------------------------------------

    def _release(self, job: _Job) -> None:
        for info in job.infos:
            left = self._used.get(info.hostname, 0) - 1
            if left > 0:
                self._used[info.hostname] = left
            else:
                self._used.pop(info.hostname, None)
        job.infos = []

    def _requeue(self, job: _Job, *, preempted: bool) -> None:
        job.prev_np = job.np
        job.np = 0
        job.state = QUEUED
        job.queued_at = self._clock()
        job.eligible_at = job.queued_at
        job.preempted = preempted
        job.starve_logged = False

    def _reap(self) -> None:
        for job in self.jobs:
            if job.state not in (RUNNING, PREEMPTING):
                continue
            if job.thread is not None and job.thread.is_alive():
                # A preempted job whose ranks ignore SIGTERM would pin
                # its slots forever; past twice the terminate grace the
                # controller escalates to the operator-stop teardown
                # (SIGTERM-as-launcher + SIGKILL hammer).
                if job.state == PREEMPTING and job.preempt_at and \
                        self._clock() - job.preempt_at > \
                        2.0 * launch._terminate_grace_seconds():
                    self._log(f"job {job.name} ignored preemption for "
                              f"too long; hard-stopping it")
                    job.preempt_at = 0.0  # escalate once
                    job.control.stop()
                continue
            if job.thread is not None:
                job.thread.join()
                job.thread = None
            rc, report = job.result if job.result else (1, {})
            job.result = None
            was_resize = job.resizing
            job.resizing = False
            job.preempt_at = 0.0
            self._release(job)
            if job.health is not None:
                job.health.shutdown()
                job.health.monitor.forget_all()
                job.health = None
            failed = report.get("failed") or []
            if failed:
                self._blame(failed)
            preempt_req = (job.control is not None and
                           job.control.preempt_requested.is_set())
            job.rc = rc
            if rc == 0:
                job.state = DONE
                self._log(f"job {job.name} finished ok")
            elif self._stopping:
                job.state = STOPPED
                self._log(f"job {job.name} stopped (fleet shutdown)")
            elif rc == PREEMPTION_RC or preempt_req:
                self._requeue(job, preempted=True)
                if was_resize:
                    self._log(f"job {job.name} paused for resize "
                              f"(rc {rc}) — re-queued")
                else:
                    self._log(f"job {job.name} preempted (rc {rc}) — "
                              f"re-queued for resume, host not blamed")
            elif report.get("signalled"):
                job.state = STOPPED
                self._log(f"job {job.name} stopped by operator (rc {rc})")
            else:
                telemetry.counter(
                    "hvd_fleet_job_restarts_total",
                    "Per-job failure restarts consumed under the fleet "
                    "controller", job=job.name).inc()
                if job.restarts_left > 0:
                    job.restarts_left -= 1
                    self._requeue(job, preempted=False)
                    self._log(f"job {job.name} failed (rc {rc}); "
                              f"re-queued ({job.restarts_left} restarts "
                              f"left)")
                else:
                    job.state = FAILED
                    self._log(f"job {job.name} failed (rc {rc}); restart "
                              f"budget exhausted")

    def _blame(self, failed) -> None:
        """Shared soft demotion: blame crashed ranks' hosts for EVERY
        job, but keep enough capacity for the smallest live job."""
        floor = min((j.spec.min_np for j in self.jobs
                     if j.state in _LIVE_STATES), default=1)
        for rank, hostname, code in failed:
            if code == PREEMPTION_RC or \
                    self.blacklist.is_blacklisted(hostname):
                continue
            remaining = sum(
                h.slots for h in self.pool
                if h.hostname != hostname and
                not self.blacklist.is_blacklisted(h.hostname))
            if remaining >= floor:
                self.blacklist.demote(
                    hostname, f"rank {rank} exited with code {code}")
                self._log(f"blacklisting host {hostname} (rank {rank} "
                          f"exited with code {code}) for ALL jobs")
            else:
                self._log(f"NOT blacklisting {hostname} despite rank "
                          f"{rank} rc {code}: remaining capacity "
                          f"{remaining} < smallest live min_np {floor}")

    # -- chaos -------------------------------------------------------------

    def _apply_chaos(self) -> None:
        if not self._running() and not self._flapped:
            # Don't burn injection budget on an empty fleet: a storm
            # with no victims (e.g. the tick before first admission)
            # would silently consume its count and the gate it was
            # meant to exercise would never fire.  A pending host_flap
            # is the exception — its forgive half must still fire even
            # while every job sits queued waiting for that host.
            return
        for kind in faults.fleet_chaos():
            if kind == "preempt_storm":
                victims = self._running()
                if not victims:
                    continue
                victim = min(victims,
                             key=lambda j: (j.priority, -j.started_at))
                self._preempt(victim, "chaos preempt_storm")
            elif kind == "rank_kill":
                # Fleet-site rank death: SIGKILL one rank of the lowest-
                # priority running job through its watchdog — the same
                # kill path a heartbeat death takes, so the job's
                # configured rank-failure policy (restart budget or
                # fail-in-place shrink) handles the aftermath.
                victims = self._running()
                if not victims:
                    continue
                victim = min(victims,
                             key=lambda j: (j.priority, -j.started_at))
                rank = max((i.rank for i in victim.infos), default=None)
                if rank is None:
                    continue
                victim.chaos_kills.append(
                    (rank, "chaos rank_kill (fleet fault injection)"))
                self._log(f"chaos rank_kill: killing rank {rank} of "
                          f"job {victim.name}")
            elif kind == "host_flap":
                host = self.pool[-1].hostname
                if host in self._flapped:
                    self.blacklist.forgive(host)
                    self._flapped.discard(host)
                    self._log(f"chaos host_flap: host {host} back in "
                              f"the pool")
                elif self.blacklist.is_blacklisted(host):
                    # Demoted for genuine rank failures, not by a prior
                    # flap — forgiving it here would resurrect a
                    # legitimately bad host mid-episode.
                    self._log(f"chaos host_flap: host {host} is "
                              f"blacklisted for real failures; leaving "
                              f"it demoted")
                else:
                    self.blacklist.demote(host, "chaos host_flap")
                    self._flapped.add(host)
                    self._log(f"chaos host_flap: host {host} demoted")
                    for job in self._running():
                        if any(i.hostname == host for i in job.infos):
                            self._preempt(
                                job, f"chaos host_flap on {host}")

    # -- preemption --------------------------------------------------------

    def _preempt(self, job: _Job, reason: str, *,
                 resize: bool = False) -> None:
        if job.state != RUNNING:
            return
        job.state = PREEMPTING
        job.resizing = resize
        job.preempt_at = self._clock()
        if not resize:
            job.preemptions += 1
            telemetry.counter(
                "hvd_fleet_preemptions_total",
                "Jobs preempted by the fleet controller (SIGTERM -> "
                "coordinated save -> rc 75 -> requeue)").inc()
            telemetry.counter(
                "hvd_fleet_job_preemptions_total",
                "Preemptions of this job by the fleet controller",
                job=job.name).inc()
        self._log(f"preempting job {job.name} (priority {job.priority}, "
                  f"np={job.np}): {reason}")
        job.control.preempt()
        if job.health is not None:
            # SIGTERM only reaches local process groups (for a remote
            # rank, its ssh client) — the health plane carries the
            # preemption to every heartbeating rank end-to-end.
            job.health.request_preempt()

    # -- serving autoscaler ------------------------------------------------

    def _read_serving_stats(self, job: _Job) -> Optional[dict]:
        """The job's router stats snapshot (written atomically by
        :meth:`horovod_tpu.serving.router.Router.write_stats` to the
        ``HOROVOD_SERVING_STATS`` path this controller injected), or
        None before the first publish.  Staleness across attempts is a
        non-issue: :meth:`_start_job` deletes the file on every
        (re)admission."""
        try:
            with open(job.stats_path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _autoscale_serving(self) -> None:
        """Elastic replica autoscaling for ``type=serving`` jobs, driven
        by the router's queue-depth / p99-latency telemetry:

        * **pressure** (queue depth >= ``serving_scale_up_depth``, or
          p99 over the job's SLO) grows the job toward ``max_np`` via
          the resize path; with no free slots it preempts the lowest
          strictly-lower-priority running job first — latency-sensitive
          serving takes capacity from batch training during a spike;
        * **calm** for ``serving_scale_down_idle`` continuous seconds
          shrinks back toward ``min_np``, returning the capacity.

        One resize in flight fleet-wide (the `_maybe_grow` invariant);
        the grown-toward slots are reserved against lower-priority
        admission by :meth:`_reserved_slots` while the resize is in
        flight."""
        for job in self._running():
            if job.spec.type != "serving":
                continue
            stats = self._read_serving_stats(job)
            if stats is None:
                continue
            depth = float(stats.get("queue_depth", 0) or 0)
            p99 = float(stats.get("p99_ms", 0) or 0)
            slo = float(stats.get("slo_ms", 0) or 0)
            telemetry.gauge(
                "hvd_fleet_serving_queue_depth",
                "Router queue depth last reported by this serving job",
                job=job.name).set(depth)
            telemetry.gauge(
                "hvd_fleet_serving_p99_ms",
                "Router p99 request latency (ms) last reported by this "
                "serving job", job=job.name).set(p99)
            pressure = depth >= self.serving_scale_up_depth or \
                (slo > 0.0 and p99 > slo)
            now = self._clock()
            resize_busy = any(j.state == PREEMPTING for j in self.jobs)
            if pressure:
                job.calm_since = 0.0
                if job.np >= job.spec.max_np or resize_busy:
                    continue
                free = sum(h.slots for h in self._free_hosts())
                if free > 0:
                    target = min(job.spec.max_np, job.np + free)
                    job.target_np = target
                    telemetry.counter(
                        "hvd_fleet_serving_scale_events_total",
                        "Serving autoscaler resize decisions",
                        job=job.name, direction="grow").inc()
                    self._preempt(
                        job,
                        f"serving scale-up {job.np}->{target} (queue "
                        f"depth {depth:g}, p99 {p99:g}ms)", resize=True)
                else:
                    victims = [j for j in self._running()
                               if j.priority < job.priority]
                    if not victims:
                        continue
                    victim = min(victims,
                                 key=lambda j: (j.priority,
                                                -j.started_at))
                    self._log(f"serving job {job.name} under pressure "
                              f"(queue depth {depth:g}, p99 {p99:g}ms) "
                              f"with no free slots")
                    self._preempt(
                        victim,
                        f"serving job {job.name} needs capacity (queue "
                        f"depth {depth:g})")
            else:
                if job.calm_since == 0.0:
                    job.calm_since = now
                    continue
                if now - job.calm_since < self.serving_scale_down_idle:
                    continue
                if job.np <= job.spec.min_np or resize_busy:
                    continue
                job.target_np = job.spec.min_np
                job.calm_since = 0.0
                telemetry.counter(
                    "hvd_fleet_serving_scale_events_total",
                    "Serving autoscaler resize decisions",
                    job=job.name, direction="shrink").inc()
                self._preempt(
                    job,
                    f"serving scale-down {job.np}->{job.spec.min_np} "
                    f"(calm {self.serving_scale_down_idle:g}s)",
                    resize=True)

    def _check_starvation(self) -> None:
        queue = self._queued()
        if not queue:
            return
        head = queue[0]
        free = sum(h.slots for h in self._free_hosts())
        if free >= head.spec.min_np:
            return  # admission will take it this tick
        now = self._clock()
        waited = now - max(head.queued_at, head.eligible_at)
        if waited <= self.starvation_deadline:
            return
        # Slots held by jobs already saving for preemption free at reap
        # time; counting them as pending frees keeps the deficit from
        # being recomputed from scratch every tick while a victim spends
        # several ticks in its coordinated save — which would preempt
        # extra victims beyond what the head job needs.
        pending = sum(j.np for j in self.jobs if j.state == PREEMPTING)
        deficit = head.spec.min_np - free - pending
        if deficit <= 0:
            return
        victims = [j for j in self._running()
                   if j.priority < head.priority]
        if not victims:
            if not head.starve_logged:
                head.starve_logged = True
                self._log(f"job {head.name} starved {waited:.1f}s but no "
                          f"lower-priority job is running to preempt")
            return
        # Lowest priority first; among equals the most recently started
        # (least sunk work) goes first.
        victims.sort(key=lambda j: (j.priority, -j.started_at))
        freed = 0
        for victim in victims:
            if freed >= deficit:
                break
            self._preempt(
                victim,
                f"job {head.name} (priority {head.priority}) starved "
                f"{waited:.1f}s past the {self.starvation_deadline:g}s "
                f"deadline")
            freed += victim.np

    # -- admission ---------------------------------------------------------

    def _reserved_slots(self, job: _Job) -> int:
        """Slots a grow-resize in flight will need on re-admission, held
        back from equal-or-lower-priority queued jobs so the grown job
        doesn't bounce back at its old size."""
        return sum(
            max(0, j.target_np - j.np) for j in self.jobs
            if j is not job and j.target_np is not None
            and j.state == PREEMPTING and j.priority >= job.priority)

    def _admit_np(self, job: _Job, cap: int) -> int:
        """World size to admit ``job`` at given ``cap`` free slots.
        Batch jobs stretch to ``max_np`` (elastic; `_maybe_grow` resizes
        them up later).  Serving jobs start at ``min_np`` — the
        autoscaler owns their size — unless a resize set ``target_np``
        or a prior attempt already ran wider."""
        if job.spec.type == "serving":
            want = job.target_np or job.prev_np or job.spec.min_np
            want = min(want, job.spec.max_np)
        else:
            want = job.spec.max_np
        return min(want, cap)

    def _admit(self) -> None:
        for job in self._queued():
            free_list = self._free_hosts()
            cap = sum(h.slots for h in free_list)
            cap -= self._reserved_slots(job)
            if cap < job.spec.min_np:
                # Strict priority: nothing behind this job may backfill
                # past it, or small low-priority jobs would starve it
                # forever — the exact inversion the fleet exists to stop.
                break
            self._start_job(job, self._admit_np(job, cap), free_list)

    def _start_job(self, job: _Job, np_: int,
                   free_list: List[hosts.HostSlots]) -> None:
        now = self._clock()
        infos = hosts.allocate(free_list, np_)
        for info in infos:
            self._used[info.hostname] = self._used.get(info.hostname, 0) + 1
        wait = max(0.0, now - max(job.queued_at, job.eligible_at))
        telemetry.histogram(
            "hvd_fleet_queue_wait_seconds",
            "Seconds a job waited in the fleet queue before admission",
            bounds=(0.5, 1, 2, 5, 10, 30, 60, 120, 300, 600),
            job=job.name).observe(wait)
        telemetry.counter(
            "hvd_fleet_admissions_total",
            "Job admissions (first launches, resumes and resizes)").inc()
        if job.prev_np and job.prev_np != np_:
            telemetry.counter(
                "hvd_fleet_resizes_total",
                "Job world-size changes across fleet re-admissions",
                job=job.name,
                direction="grow" if np_ > job.prev_np else "shrink").inc()
        job.state = RUNNING
        job.np = np_
        job.infos = infos
        job.started_at = now
        if job.spec.type == "serving":
            # Fresh telemetry epoch: a stale stats file from the
            # pre-resize attempt would re-trigger (or mask) pressure.
            job.target_np = None
            job.calm_since = 0.0
            try:
                os.remove(job.stats_path)
            except OSError:
                pass
        remote_preempt = None
        if self.heartbeat_interval:
            # Resolved at call time: job.health is created in
            # _build_env, after the control.  Lets JobControl.preempt
            # spare remote ranks' ssh clients and deliver the preemption
            # over heartbeat responses instead.
            def remote_preempt(j=job):
                if j.health is not None:
                    j.health.request_preempt()
        job.control = launch.JobControl(remote_preempt=remote_preempt)
        host_summary = ",".join(
            f"{h}:{n}" for h, n in _host_counts(infos).items())
        self._log(f"admit job {job.name} np={np_} priority="
                  f"{job.priority} attempt={job.attempt} "
                  f"wait={wait:.1f}s hosts={host_summary}"
                  + (f" prev_np={job.prev_np}"
                     if job.prev_np and job.prev_np != np_ else "")
                  + (" (resume)" if job.preempted else ""))
        env_per_rank = self._build_env(job, infos)
        job.attempt += 1
        watchdog = self._make_watchdog(job)
        thread = threading.Thread(
            target=self._job_thread,
            args=(job, infos, env_per_rank, job.control, watchdog),
            name=f"hvdfleet-{job.name}", daemon=True)
        job.thread = thread
        thread.start()

    def _build_env(self, job: _Job,
                   infos: List[hosts.RankInfo]) -> List[Dict[str, str]]:
        os.makedirs(job.spill_dir, exist_ok=True)
        hostnames = {i.hostname for i in infos}
        all_local = all(launch.is_local(h) for h in hostnames)
        addr = "127.0.0.1" if all_local else infos[0].hostname
        port = launch.find_free_port()
        extra = dict(self.extra_env)
        extra["HOROVOD_SECRET_KEY"] = job.secret
        extra["HOROVOD_SPILL_DIR"] = job.spill_dir
        extra["HOROVOD_FLEET_JOB"] = job.name
        extra["HOROVOD_RESTART_ATTEMPT"] = str(job.attempt)
        if job.spec.type == "serving":
            # Stats handshake: the job's router publishes queue depth /
            # p99 here (serving.router.Router.serve), the autoscaler
            # reads it each tick (_autoscale_serving).
            extra["HOROVOD_SERVING_STATS"] = job.stats_path
        if job.prev_np and job.prev_np != job.np:
            extra["HOROVOD_ELASTIC_PREV_SIZE"] = str(job.prev_np)
        else:
            extra.pop("HOROVOD_ELASTIC_PREV_SIZE", None)
        if self.metrics_port_base:
            # Per-job exporter base; ranks add their local_rank on top
            # (telemetry/exporter.py resolve_metrics_port), so the
            # stride must exceed the largest per-host slot count.
            extra["HOROVOD_METRICS_PORT"] = str(
                self.metrics_port_base + job.index * self.port_stride)
        if self.heartbeat_interval:
            from horovod_tpu.runner.run import _HealthPlane
            job.health = _HealthPlane(
                job.secret, self.heartbeat_interval,
                5.0 * self.heartbeat_interval, self.hang_deadline)
            extra["HOROVOD_HEALTH_RPC"] = f"{addr}:{job.health.port}"
            extra["HOROVOD_HEARTBEAT_INTERVAL"] = str(
                self.heartbeat_interval)
            job.health.begin_attempt([i.rank for i in infos])
        extra.update(job.spec.env)
        env_per_rank = []
        for info in infos:
            env = config_parser.runtime_env(
                info, addr, port, extra, multi_host=len(hostnames) > 1)
            if self.metrics_file:
                from horovod_tpu.runner.run import _per_rank_metrics_path
                env["HOROVOD_METRICS_FILE"] = _per_rank_metrics_path(
                    job.metrics_base, info.rank)
            env_per_rank.append(env)
        return env_per_rank

    def _make_watchdog(self, job: _Job):
        health = job.health
        control = job.control

        def watchdog() -> list:
            # Once this job is being preempted its ranks are busy with
            # the coordinated save — killing a "hung" rank now would
            # sabotage the very save the preemption asked for.
            if control.preempt_requested.is_set() or \
                    control.stop_requested.is_set():
                return []
            out: list = []
            if job.chaos_kills:
                # Swap-then-drain: the controller tick appends, this
                # (job-thread) side consumes — no partial reads.
                pending, job.chaos_kills = job.chaos_kills, []
                out.extend(pending)
            if health is not None:
                out.extend(health.watchdog())
            return out

        return watchdog

    def _job_thread(self, job, infos, env_per_rank, control,
                    watchdog) -> None:
        report: dict = {}
        try:
            rc = self._job_runner(job, infos, env_per_rank, control,
                                  report, watchdog)
        except Exception as e:                        # noqa: BLE001
            self._log(f"job {job.name} launch error: {e}")
            rc = 1
        job.result = (rc, report)

    def _run_job_process(self, job, infos, env_per_rank, control,
                         report, watchdog) -> int:
        out_dir = (os.path.join(self.output_dir, job.name)
                   if self.output_dir else None)
        return launch.launch_job(
            infos, job.spec.command, env_per_rank,
            output_dir=out_dir,
            prefix_output=True,
            start_timeout=self.start_timeout,
            report=report,
            watchdog=watchdog,
            install_signal_handlers=False,
            control=control,
            label=job.name)

    # -- elastic grow ------------------------------------------------------

    def _maybe_grow(self) -> None:
        if self._queued():
            return  # queued work has first claim on free slots
        if any(j.state == PREEMPTING for j in self.jobs):
            # A job mid-resize (or mid-preemption) is neither queued nor
            # running, so the queue looks empty and the slot it was
            # grown toward still looks free — growing another candidate
            # now would double-book that slot and force a needless
            # preemption once both re-admit.  One resize in flight at a
            # time, across ticks as well as within one.
            return
        free = sum(h.slots for h in self._free_hosts())
        if free <= 0:
            return
        now = self._clock()
        candidates = [
            j for j in self._running()
            if j.spec.type != "serving" and  # autoscaler owns serving size
            j.np < j.spec.max_np and
            now - j.started_at >= self.grow_after
        ]
        if not candidates:
            return
        # Highest priority grows first; one resize per tick keeps the
        # pool observable between moves.
        job = max(candidates, key=lambda j: (j.priority, -j.index))
        target = min(job.spec.max_np, job.np + free)
        self._log(f"growing job {job.name} {job.np}->{target} "
                  f"({free} free slot(s), nothing queued)")
        self._preempt(job, f"grow to np={target}", resize=True)

    def _fail_unsatisfiable(self) -> None:
        """A queued job whose min_np exceeds what the pool can EVER
        offer again must fail, not hang the fleet: with nothing running
        and a permanent blacklist there is no future event that frees
        capacity."""
        if not self._permanent_blacklist:
            return  # cooldown expiry can still restore capacity
        if self._flapped:
            return  # chaos host_flap will forgive these hosts itself
        if any(j.state in (RUNNING, PREEMPTING) for j in self.jobs):
            return
        usable = sum(h.slots for h in self._usable_pool())
        for job in list(self._queued()):
            if job.spec.min_np > usable:
                job.state = FAILED
                job.rc = 1
                self._log(f"job {job.name} unsatisfiable: min_np "
                          f"{job.spec.min_np} > usable capacity {usable} "
                          f"(blacklist is permanent, nothing running)")

    # -- telemetry / lifecycle ---------------------------------------------

    def _update_gauges(self) -> None:
        states = [j.state for j in self.jobs]
        telemetry.gauge(
            "hvd_fleet_jobs_running",
            "Jobs currently running (or saving for preemption) under "
            "the fleet controller").set(
            float(states.count(RUNNING) + states.count(PREEMPTING)))
        telemetry.gauge(
            "hvd_fleet_jobs_queued",
            "Jobs waiting for a full gang of min_np slots").set(
            float(states.count(QUEUED)))
        telemetry.gauge(
            "hvd_fleet_jobs_preempted",
            "Preempted jobs currently queued for resume").set(
            float(sum(1 for j in self.jobs
                      if j.state == QUEUED and j.preempted)))
        telemetry.gauge(
            "hvd_fleet_slots_total",
            "Slots in the fleet pool (before blacklist)").set(
            float(sum(h.slots for h in self.pool)))
        telemetry.gauge(
            "hvd_fleet_slots_free",
            "Unassigned, non-blacklisted slots").set(
            float(sum(h.slots for h in self._free_hosts())))

    def stop(self) -> None:
        """Operator stop: tear every job down with rc-130 semantics and
        let run() drain."""
        self._stopping = True
        for job in self.jobs:
            if job.state in (RUNNING, PREEMPTING) and \
                    job.control is not None:
                job.control.stop()
            elif job.state == QUEUED:
                # A queued job has no process to tear down, but it still
                # counts as live — with scheduling disabled under
                # _stopping nothing would ever move it to a terminal
                # state and run() would drain forever (e.g. an
                # oversubscribed fleet, or a preempted job waiting to
                # resume).
                job.state = STOPPED
                job.rc = 130
        self._log("stop requested; tearing down running jobs")

    def run(self) -> int:
        """Tick until every job reached a terminal state; returns 0 when
        all jobs finished, 130 on operator stop, 1 otherwise."""
        while self.tick():
            self._sleep(self.tick_interval)
        if self.metrics_file:
            try:
                self._write_summary()
            except Exception as e:                    # noqa: BLE001
                self._log(f"failed to write fleet summary to "
                          f"{self.metrics_file}: {e}")
        states = {j.name: j.state for j in self.jobs}
        self._log(f"all jobs terminal: {states}")
        if self._stopping:
            return 130
        return 0 if all(s == DONE for s in states.values()) else 1

    def _write_summary(self) -> None:
        """Merged fleet summary (``horovod_tpu.fleet.summary.v1``):
        controller metrics plus each job's per-rank at-exit reports,
        merged with the PR-2 aggregator."""
        from horovod_tpu.runner.run import _per_rank_metrics_path
        from horovod_tpu.telemetry import aggregate
        jobs_doc = {}
        for job in self.jobs:
            ranks = {}
            # A job may have run at several world sizes; collect every
            # per-rank file that exists up to max_np.
            for rank in range(job.spec.max_np):
                path = _per_rank_metrics_path(job.metrics_base, rank)
                try:
                    with open(path) as f:
                        ranks[str(rank)] = json.load(f)
                except (OSError, ValueError):
                    pass
            snapshots = {k: r.get("metrics") or {}
                         for k, r in ranks.items()}
            jobs_doc[job.name] = {
                "state": job.state,
                "type": job.spec.type,
                "priority": job.priority,
                "min_np": job.spec.min_np,
                "max_np": job.spec.max_np,
                "final_np": job.np or job.prev_np,
                "attempts": job.attempt,
                "preemptions": job.preemptions,
                "restarts_left": job.restarts_left,
                "exit_code": job.rc,
                "ranks_reported": sorted(ranks, key=int),
                "merged": aggregate.merge_snapshots(snapshots),
            }
        doc = {
            "schema": "horovod_tpu.fleet.summary.v1",
            "pool": [{"hostname": h.hostname, "slots": h.slots}
                     for h in self.pool],
            "blacklist": self.blacklist.summary(),
            "controller": {
                "host": socket.gethostname(),
                "pid": os.getpid(),
                "metrics": telemetry.metrics_snapshot(),
            },
            "jobs": jobs_doc,
        }
        path = self.metrics_file
        dirname = os.path.dirname(os.path.abspath(path))
        os.makedirs(dirname, exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)
        self._log(f"fleet summary written to {path}")


def _host_counts(infos: List[hosts.RankInfo]) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for info in infos:
        out[info.hostname] = out.get(info.hostname, 0) + 1
    return out


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="hvdfleet",
        description="Priority gang-scheduling fleet controller: run many "
                    "jobs over one host pool with preemption and elastic "
                    "capacity sharing (docs/fleet.md).")
    p.add_argument("-H", "--hosts", default=None,
                   help="comma-separated host:slots pool (hvdrun syntax)")
    p.add_argument("--hostfile", default=None,
                   help="file with one 'host slots=N' per line")
    p.add_argument("--job", action="append", default=[], metavar="SPEC",
                   help="job spec: 'name priority min_np[:max_np] "
                        "[after=S] [restarts=N] [type=T] [env:K=V ...] "
                        "-- cmd...' (repeatable)")
    p.add_argument("--jobs-file", default=None,
                   help="file with one job spec per line (# comments ok)")
    p.add_argument("--starvation-deadline", type=float, default=30.0,
                   help="seconds the head queued job may starve before "
                        "the controller preempts lower-priority jobs "
                        "(default 30)")
    p.add_argument("--tick-interval", type=float, default=0.25,
                   help="scheduler pass interval in seconds")
    p.add_argument("--grow-after", type=float, default=15.0,
                   help="seconds a job must run undisturbed before spare "
                        "capacity may grow it toward max_np (default 15)")
    p.add_argument("--serving-scale-up-depth", type=float, default=8.0,
                   help="router queue depth at which a type=serving job "
                        "scales up (default 8)")
    p.add_argument("--serving-scale-down-idle", type=float, default=10.0,
                   help="seconds a type=serving job must stay calm before "
                        "it shrinks back to min_np (default 10)")
    p.add_argument("--blacklist-cooldown", type=float, default=None,
                   help="seconds until a demoted host re-enters the "
                        "shared pool (default: demoted for good)")
    p.add_argument("--metrics-file", default=None,
                   help="write a merged fleet summary here and collect "
                        "per-rank metrics under the fleet dir")
    p.add_argument("--metrics-port-base", type=int, default=0,
                   help="base port for per-job Prometheus exporters; "
                        "job i serves at base + i*stride + local_rank")
    p.add_argument("--port-stride", type=int, default=64,
                   help="port distance between jobs' exporter ranges "
                        "(must exceed the largest per-host slot count)")
    p.add_argument("--fleet-dir", default=None,
                   help="scratch root for per-job spill/metrics dirs "
                        "(default: a fresh temp dir)")
    p.add_argument("--output-filename", default=None,
                   help="per-rank stdout/stderr under "
                        "<dir>/<job>/rank.<r>/ (hvdrun semantics)")
    p.add_argument("--start-timeout", type=float, default=None,
                   help="per-launch rank spawn timeout in seconds")
    p.add_argument("--heartbeat-interval", type=float, default=None,
                   help="enable the per-job heartbeat health plane at "
                        "this interval (seconds)")
    p.add_argument("--hang-deadline", type=float, default=None,
                   help="declare a rank hung after its step stalls this "
                        "long with heartbeats alive (needs "
                        "--heartbeat-interval)")
    p.add_argument("--verbose", action="store_true")
    return p


def _load_specs(args) -> List[JobSpec]:
    lines = list(args.job)
    if args.jobs_file:
        with open(args.jobs_file) as f:
            for raw in f:
                line = raw.strip()
                if line and not line.startswith("#"):
                    lines.append(line)
    if not lines:
        raise ValueError("no jobs: pass --job and/or --jobs-file")
    return [parse_job_spec(line) for line in lines]


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.hostfile:
        pool = hosts.parse_hostfile(args.hostfile)
    elif args.hosts:
        pool = hosts.parse_hosts(args.hosts)
    else:
        print("hvdfleet: need -H/--hosts or --hostfile", file=sys.stderr)
        return 2
    try:
        specs = _load_specs(args)
    except ValueError as e:
        print(f"hvdfleet: {e}", file=sys.stderr)
        return 2
    if args.metrics_file:
        # The controller writes the merged summary itself; an inherited
        # HOROVOD_METRICS_FILE would make ITS at-exit dump clobber it.
        os.environ.pop("HOROVOD_METRICS_FILE", None)
        telemetry.configure(enabled_flag=True)
    controller = FleetController(
        pool, specs,
        starvation_deadline=args.starvation_deadline,
        tick_interval=args.tick_interval,
        grow_after=args.grow_after,
        serving_scale_up_depth=args.serving_scale_up_depth,
        serving_scale_down_idle=args.serving_scale_down_idle,
        blacklist_cooldown=args.blacklist_cooldown,
        fleet_dir=args.fleet_dir,
        metrics_file=args.metrics_file,
        metrics_port_base=args.metrics_port_base,
        port_stride=args.port_stride,
        output_dir=args.output_filename,
        heartbeat_interval=args.heartbeat_interval or 0.0,
        hang_deadline=args.hang_deadline or 0.0,
        start_timeout=args.start_timeout,
        verbose=args.verbose,
    )

    def handle_signal(signum, frame):
        del frame
        controller._log(f"caught signal {signum}")
        controller.stop()

    old_int = signal.signal(signal.SIGINT, handle_signal)
    old_term = signal.signal(signal.SIGTERM, handle_signal)
    try:
        return controller.run()
    finally:
        signal.signal(signal.SIGINT, old_int)
        signal.signal(signal.SIGTERM, old_term)


if __name__ == "__main__":
    sys.exit(main())

"""HMAC-authenticated pickle-RPC for launcher/driver services.

Reference equivalent: ``horovod/run/common/network.py:50-84`` — the
``Wire`` class wraps every message in an HMAC digest keyed by the job
secret so arbitrary processes cannot inject commands into the driver/task
services, plus ``service/{driver,task}_service.py`` request dispatch.

Used by ``horovod_tpu.spark`` (task registration / rank assignment) and
available to any future driver-side discovery service.  The eager
runtime's own connections authenticate in C++ (``native/cc/src/auth.cc``)
with the same per-job secret.
"""

from __future__ import annotations

import hashlib
import hmac
import pickle
import random
import socket
import socketserver
import struct
import threading
import time
from typing import Any, Callable, Optional

from horovod_tpu import config, faults, telemetry


class AuthError(RuntimeError):
    pass


def _send_msg(sock: socket.socket, payload: bytes, key: bytes) -> None:
    digest = hmac.new(key, payload, hashlib.sha256).digest()
    sock.sendall(struct.pack("!Q", len(payload)) + digest + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


def _recv_msg(sock: socket.socket, key: bytes,
              max_len: int = 64 << 20) -> bytes:
    (length,) = struct.unpack("!Q", _recv_exact(sock, 8))
    if length > max_len:
        raise AuthError(f"message length {length} exceeds sanity cap")
    digest = _recv_exact(sock, 32)
    payload = _recv_exact(sock, length)
    want = hmac.new(key, payload, hashlib.sha256).digest()
    if not hmac.compare_digest(digest, want):
        raise AuthError("message digest mismatch — wrong or missing "
                        "HOROVOD_SECRET_KEY")
    return payload


class RpcServer:
    """Threaded TCP server dispatching authenticated pickled requests.

    ``handler(request) -> response`` runs under a lock by default
    (launcher services mutate shared registration state).  Pass
    ``serialize=False`` for handlers that do their own finer-grained
    locking and must stay responsive to probes while a slow request
    runs — the serving replica's decode path
    (:mod:`horovod_tpu.serving.replica`) is the canonical user.
    Unauthenticated or malformed requests are dropped without a reply;
    the connection is one-shot (request → response → close), matching
    the reference's usage pattern.
    """

    def __init__(self, key: bytes, handler: Callable[[Any], Any],
                 bind: str = "0.0.0.0", serialize: bool = True):
        self._key = key
        self._handler = handler
        self._lock = threading.Lock() if serialize else None
        outer = self

        class _Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    req = pickle.loads(_recv_msg(self.request, outer._key))
                except (AuthError, ConnectionError, pickle.PickleError,
                        struct.error):
                    return  # drop silently: scanner resilience
                if outer._lock is not None:
                    with outer._lock:
                        resp = outer._handler(req)
                else:
                    resp = outer._handler(req)
                _send_msg(self.request, pickle.dumps(resp), outer._key)

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = _Server((bind, 0), _Handler)
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def shutdown(self) -> None:
        self._server.shutdown()
        self._server.server_close()


def connect_with_retry(addr: str, port: int, timeout: float = 30.0,
                       retries: int = 4, base_delay: float = 0.2,
                       max_delay: float = 3.0,
                       sleep: Callable[[float], None] = time.sleep,
                       rng: Callable[[], float] = random.random,
                       deadline: Optional[float] = None,
                       clock: Callable[[], float] = time.monotonic
                       ) -> socket.socket:
    """``socket.create_connection`` with jittered exponential backoff.

    Retries CONNECTION ESTABLISHMENT only — never a request that may
    already have been delivered — so it composes with non-idempotent
    RPCs.  Backoff is ``min(max_delay, base_delay * 2**attempt)`` scaled
    by a uniform [0.5, 1.5) jitter, so a herd of ranks re-dialing a
    restarting driver doesn't re-arrive in lockstep (the failure mode
    the reference's fixed-interval retry loops invite).

    ``deadline`` caps the TOTAL elapsed time across every attempt
    (default ``HOROVOD_RPC_CONNECT_DEADLINE``).  Per-attempt bounds
    alone don't bound the call: each dial may burn its full ``timeout``
    against a black-holed address, so 5 attempts at 30 s plus backoff
    could hold a coordination step hostage for minutes.  ``sleep``/
    ``rng``/``clock`` are injection hooks for tests."""
    if deadline is None:
        deadline = config.env_float("HOROVOD_RPC_CONNECT_DEADLINE")
    started = clock()
    last_err: Optional[OSError] = None
    attempts = 0
    for attempt in range(retries + 1):
        budget = deadline - (clock() - started)
        if budget <= 0:
            last_err = last_err or OSError("connect deadline exhausted")
            break
        attempts += 1
        try:
            return socket.create_connection((addr, port),
                                            timeout=min(timeout, budget))
        except OSError as e:
            last_err = e
            if attempt >= retries:
                break
            delay = (min(max_delay, base_delay * (2.0 ** attempt))
                     * (0.5 + rng()))
            if clock() - started + delay >= deadline:
                break
            telemetry.counter(
                "hvd_rpc_connect_retries_total",
                "RPC dial attempts that failed and were retried with "
                "backoff").inc()
            sleep(delay)
    telemetry.counter(
        "hvd_rpc_connect_failures_total",
        "RPC dials that exhausted every retry").inc()
    raise ConnectionError(
        f"could not connect to {addr}:{port} after {attempts} attempts "
        f"within {deadline:.1f}s: {last_err}")


def rpc_call(addr: str, port: int, request: Any, key: bytes,
             timeout: float = 30.0, retries: int = 4) -> Any:
    """One authenticated request/response round trip.  The dial retries
    with jittered backoff (``retries=0`` restores single-shot)."""
    faults.inject("rpc", str(request.get("kind"))
                  if isinstance(request, dict) else None)
    kind = (str(request.get("kind")) if isinstance(request, dict)
            else "raw")
    telemetry.counter("hvd_rpc_calls_total",
                      "Authenticated RPC round trips issued",
                      kind=kind).inc()
    # Request-scoped span: the RPC plane's round trips show up in the
    # merged trace next to the collectives they interleave with.  The
    # time-sync probe is excluded — it runs during span export, and a
    # span recorded mid-probe would land in some documents but not
    # others depending on push-vs-file timing.
    sp = telemetry.spans() if kind != "time_sync" else None
    t0 = time.monotonic() if sp is not None else 0.0
    with connect_with_retry(addr, port, timeout=timeout,
                            retries=retries) as sock:
        _send_msg(sock, pickle.dumps(request), key)
        reply = pickle.loads(_recv_msg(sock, key))
    if sp is not None:
        sp.event(f"rpc/{kind}", "rpc", t0, time.monotonic())
    return reply


def time_sync_reply() -> dict:
    """The server half of the time-sync handshake: collectors answer a
    ``{"kind": "time_sync"}`` request with their monotonic clock read as
    close to the reply as possible."""
    return {"ok": True, "server_time": time.monotonic()}


def measure_clock_offset(addr: str, port: int, key: bytes,
                         samples: int = 5,
                         timeout: float = 5.0) -> Optional[tuple]:
    """Estimate this process's monotonic-clock offset against the
    server at ``addr:port`` (Cristian's algorithm): each probe reads the
    local clock before (t0) and after (t1) a ``time_sync`` round trip
    and assumes the server stamped its clock at the midpoint, so
    ``offset = server_time - (t0 + t1) / 2``.  The estimate from the
    minimum-RTT probe wins — queueing delay only ever inflates RTT, so
    the fastest round trip bounds the error tightest (error <= rtt/2).

    Returns ``(offset_seconds, rtt_seconds)`` with offset =
    server_clock - local_clock, or None when the server is unreachable
    or does not answer the handshake (a pre-tracing launcher).  On one
    host all ranks share CLOCK_MONOTONIC, so the offset is ~0 and the
    result doubles as a sanity check on the estimator itself.
    """
    best: Optional[tuple] = None
    for _ in range(max(samples, 1)):
        t0 = time.monotonic()
        try:
            reply = rpc_call(addr, port, {"kind": "time_sync"}, key,
                             timeout=timeout, retries=0)
        except Exception:
            continue
        t1 = time.monotonic()
        if not isinstance(reply, dict) or "server_time" not in reply:
            return None   # collector predates the handshake; stop probing
        rtt = t1 - t0
        offset = float(reply["server_time"]) - (t0 + t1) / 2.0
        if best is None or rtt < best[1]:
            best = (offset, rtt)
    return best


def control_call(addr: str, port: int, request: dict, key: bytes,
                 *, epoch: int = 0, seq: int = 0,
                 retries: Optional[int] = None,
                 deadline: Optional[float] = None,
                 timeout: float = 5.0,
                 sleep: Callable[[float], None] = time.sleep,
                 rng: Callable[[], float] = random.random,
                 clock: Callable[[], float] = time.monotonic) -> Any:
    """Coordination-plane round trip: :func:`rpc_call` hardened per
    docs/control_plane.md.

    The request is stamped with ``(epoch, seq)`` so the receiver can
    discard stale-epoch traffic and dedup retransmits — which is what
    makes retrying the WHOLE round trip safe here, where plain
    :func:`rpc_call` may only retry the dial.  Retransmits use jittered
    exponential backoff, bounded by ``HOROVOD_COORD_MSG_RETRIES``
    attempts and the ``HOROVOD_COORD_MSG_DEADLINE`` total budget.  The
    ``faults.py`` site ``control`` injects here on the live wire with
    the same kinds the simulator's virtual network honors."""
    from horovod_tpu.coordination import RetryPolicy
    if retries is None:
        retries = config.env_int("HOROVOD_COORD_MSG_RETRIES")
    if deadline is None:
        deadline = config.env_float("HOROVOD_COORD_MSG_DEADLINE")
    policy = RetryPolicy(retries=retries, deadline=deadline)
    request = dict(request, epoch=int(epoch), seq=int(seq))
    kind = str(request.get("kind"))
    started = clock()
    attempt = 0
    last_err: Optional[Exception] = None
    while not policy.give_up(attempt, clock() - started):
        send_copies = 1
        try:
            for fault_kind, arg in faults.control_chaos():
                if fault_kind == "msg_drop":
                    raise ConnectionError("chaos: control message dropped")
                if fault_kind == "msg_dup":
                    send_copies = 2
                elif fault_kind == "msg_delay":
                    sleep(float(arg) / 1000.0 if arg is not None else 0.1)
                elif fault_kind == "partition":
                    raise ConnectionError("chaos: control partition")
            resp = None
            for _ in range(send_copies):
                with connect_with_retry(addr, port, timeout=timeout,
                                        retries=0, deadline=timeout,
                                        clock=clock) as sock:
                    _send_msg(sock, pickle.dumps(request), key)
                    resp = pickle.loads(_recv_msg(sock, key))
            return resp
        except (OSError, AuthError, pickle.PickleError) as e:
            last_err = e
            attempt += 1
            telemetry.counter(
                "hvd_coord_msg_retries_total",
                "Control-plane messages retransmitted after a failed "
                "round trip", kind=kind).inc()
            sleep(policy.backoff(attempt - 1, rng))
    raise ConnectionError(
        f"control message kind={kind} epoch={epoch} seq={seq} to "
        f"{addr}:{port} failed after {attempt} attempts: {last_err}")


def probe_reachable(host: str, port: int, timeout: float = 3.0) -> bool:
    """TCP reachability probe (the role of the reference's cached ssh
    check, ``run/run.py:59-112``, minus the shell)."""
    try:
        with socket.create_connection((host, port), timeout=timeout):
            return True
    except OSError:
        return False


def local_addresses() -> list:
    """Routable local interface addresses (reference NIC discovery probes
    each host's interfaces ring-wise, ``run.py:195-265``; here the task
    side reports its addresses and the driver intersects)."""
    addrs = set()
    hostname = socket.gethostname()
    try:
        for info in socket.getaddrinfo(hostname, None,
                                       family=socket.AF_INET):
            addrs.add(info[4][0])
    except socket.gaierror:
        pass
    # The address used to reach an external network (no traffic is sent).
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.connect(("10.255.255.255", 1))
        addrs.add(s.getsockname()[0])
        s.close()
    except OSError:
        pass
    addrs.discard("127.0.0.1")
    return sorted(addrs) or ["127.0.0.1"]


class KeepaliveMonitor:
    """Driver-side liveness bookkeeping: tasks ping periodically; a task
    silent past ``timeout`` is reported dead (the failure-detection half
    of the reference's task services).

    Pings may carry a training step (:meth:`progress` — the heartbeat
    health plane), which lets the monitor distinguish two very different
    failures: a *dead* task (socket gone, pings stopped —
    :meth:`dead_tasks`) and a *hung* one (pings keep arriving but the
    step has not advanced past ``hang_deadline`` seconds —
    :meth:`hung_tasks`).  The distinction matters because a hung worker
    holds every peer hostage inside a collective: waiting for the
    collective's own timeout wastes minutes the health plane can save.

    ``clock`` is a monotonic-seconds callable, injectable so tests step
    time instead of sleeping.  Call :meth:`forget` when a task finishes
    cleanly — a completed task stops pinging and must not be mistaken
    for a dead one."""

    def __init__(self, timeout: float = 60.0,
                 clock: Callable[[], float] = time.monotonic,
                 hang_deadline: float = 0.0):
        self._clock = clock
        self._timeout = timeout
        self._hang_deadline = hang_deadline
        self._last: dict = {}
        self._steps: dict = {}          # task_id -> (step, last_advance_ts)
        self._reported_dead: set = set()
        self._reported_hung: set = set()
        self._lock = threading.Lock()

    def ping(self, task_id) -> None:
        with self._lock:
            self._last[task_id] = self._clock()
            # A task that pings again was a network blip, not a loss.
            self._reported_dead.discard(task_id)

    def progress(self, task_id, step: int) -> None:
        """A heartbeat carrying the task's training step.  Counts as a
        ping; the hang clock restarts only when the step ADVANCES."""
        with self._lock:
            now = self._clock()
            self._last[task_id] = now
            self._reported_dead.discard(task_id)
            prev = self._steps.get(task_id)
            if prev is None or step > prev[0]:
                self._steps[task_id] = (int(step), now)
                self._reported_hung.discard(task_id)

    def forget(self, task_id) -> None:
        """Stop tracking a task (it reported its result or was removed
        from the job); silence from it is no longer a failure."""
        with self._lock:
            self._last.pop(task_id, None)
            self._steps.pop(task_id, None)
            self._reported_dead.discard(task_id)
            self._reported_hung.discard(task_id)

    def forget_all(self) -> None:
        """Atomically stop tracking every task.

        Tearing a per-job monitor down mid-episode (fleet preemption, a
        new elastic attempt) must not race a concurrent watchdog sweep
        into reporting half-forgotten ranks: a sweep observes either the
        full pre-teardown set or nothing.  Looping :meth:`forget` over
        :meth:`tracked` cannot give that guarantee — an RPC handler can
        insert between the snapshot and the per-id pops, and a sweep can
        run mid-loop against a partially cleared map."""
        with self._lock:
            self._last.clear()
            self._steps.clear()
            self._reported_dead.clear()
            self._reported_hung.clear()

    def dead_tasks(self) -> list:
        now = self._clock()
        with self._lock:
            dead = [t for t, ts in self._last.items()
                    if now - ts > self._timeout]
            fresh = [t for t in dead if t not in self._reported_dead]
            self._reported_dead.update(fresh)
        if fresh:
            # Counted once per silence episode, not per poll.
            telemetry.counter(
                "hvd_rpc_keepalive_losses_total",
                "Tasks whose keepalive pings went silent past the "
                "timeout").inc(len(fresh))
        return dead

    def hung_tasks(self) -> list:
        """Tasks whose heartbeats still arrive but whose step has been
        stalled longer than ``hang_deadline`` (0 disables).  Reported
        once per stall episode — a step advance re-arms the detector.
        Disjoint from :meth:`dead_tasks`: a silent task is dead, not
        hung."""
        if not self._hang_deadline:
            return []
        now = self._clock()
        with self._lock:
            hung = [
                t for t, (step, advance_ts) in self._steps.items()
                if now - advance_ts > self._hang_deadline
                and now - self._last.get(t, 0.0) <= self._timeout
            ]
            fresh = [t for t in hung if t not in self._reported_hung]
            self._reported_hung.update(fresh)
        if fresh:
            telemetry.counter(
                "hvd_heartbeat_hangs_total",
                "Tasks whose heartbeats stayed alive while the training "
                "step stalled past the hang deadline").inc(len(fresh))
        return fresh

    def tracked(self) -> list:
        """Every task id with any recorded state (ping or step)."""
        with self._lock:
            return sorted(set(self._last) | set(self._steps))

    def step_lags(self) -> dict:
        """Per-task straggler lag: ``max(step) - step`` over every task
        that has reported a step.  Empty until the first progress ping."""
        with self._lock:
            if not self._steps:
                return {}
            top = max(step for step, _ in self._steps.values())
            return {t: top - step for t, (step, _) in self._steps.items()}


def find_free_port(bind: str = "") -> int:
    with socket.socket() as s:
        s.bind((bind, 0))
        return s.getsockname()[1]


def job_key_bytes(env_value: Optional[str]) -> bytes:
    """Normalize HOROVOD_SECRET_KEY to raw bytes (urlsafe base64 with raw
    fallback, mirroring the native runtime's JobKey)."""
    if not env_value:
        return b""
    import base64
    try:
        return base64.urlsafe_b64decode(env_value.encode())
    except Exception:  # noqa: BLE001
        return env_value.encode()

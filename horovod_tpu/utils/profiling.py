"""Device-side profiling: per-op time aggregation from jax.profiler traces.

The reference ships a host-side timeline (chrome tracing of the
negotiation/collective state machine — ``timeline.cc`` here matches it);
this module is the DEVICE half the reference never had: run a traced
step, parse the trace-viewer JSON, and aggregate XLA op durations by
fusion category and by model layer (from HLO metadata `op_name`).  Used
by ``python -m horovod_tpu.benchmark --profile`` and by
``tools/profile_fusions.py`` (which layers a per-fusion byte analysis on
top of the same parse); it is how round 3's roofline analysis
(docs/benchmarks.md) was produced.

Works on any backend whose PJRT plugin supports ``jax.profiler``
(verified on the axon-tunneled TPU and standard CPU).
"""

from __future__ import annotations

import collections
import glob
import gzip
import json
import re
import tempfile
from typing import Callable, Dict, Optional, Tuple


def trace_once(run: Callable[[], None], trace_dir: Optional[str] = None):
    """Run ``run()`` under ``jax.profiler.trace``; returns the path of the
    trace-viewer ``*.trace.json.gz`` it produced."""
    import jax

    trace_dir = trace_dir or tempfile.mkdtemp(prefix="hvd_trace_")
    jax.profiler.start_trace(trace_dir)
    try:
        run()
    finally:
        jax.profiler.stop_trace()
    files = sorted(glob.glob(
        trace_dir + "/plugins/profile/*/*.trace.json.gz"))
    if not files:
        raise RuntimeError(
            f"no trace produced under {trace_dir} (profiler unsupported "
            f"on this backend?)")
    return files[-1]


def device_op_durations(trace_file: str) -> Dict[str, Tuple[float, int]]:
    """Parse a trace-viewer JSON: {op_name: (total_us, count)} for ops on
    ONE device track (host-side events are excluded; on a multi-chip SPMD
    mesh every device runs the same program, so a single track is the
    per-step time — summing all tracks would inflate by the chip
    count)."""
    with gzip.open(trace_file) as f:
        tr = json.load(f)
    pids = {e["pid"]: e["args"].get("name", "")
            for e in tr["traceEvents"]
            if e.get("ph") == "M" and e.get("name") == "process_name"}
    dev_pids = sorted(p for p, n in pids.items()
                      if "TPU" in n or "GPU" in n or "/device:" in n)
    if not dev_pids:
        raise RuntimeError(
            f"trace has no device track (processes: {sorted(pids.values())})"
            f" — XLA:CPU emits host events only; per-op breakdowns need an "
            f"accelerator backend")
    dev_pid = dev_pids[0]
    agg: Dict[str, list] = collections.defaultdict(lambda: [0.0, 0])
    for e in tr["traceEvents"]:
        if e.get("ph") == "X" and e.get("pid") == dev_pid:
            name = e["name"]
            if name == "0" or name.startswith(("jit_", "while")):
                continue   # container frames, not ops
            a = agg[name]
            a[0] += e.get("dur", 0.0)
            a[1] += 1
    return {k: (v[0], v[1]) for k, v in agg.items()}


def by_category(durs: Dict[str, Tuple[float, int]]):
    """Aggregate op durations by fusion category (name minus trailing
    numeric suffix): [(category, total_us)] sorted descending."""
    agg: Dict[str, float] = collections.defaultdict(float)
    for name, (us, _) in durs.items():
        agg[re.sub(r"\.\d+$", "", name)] += us
    return sorted(agg.items(), key=lambda kv: -kv[1])


DEFAULT_LAYER_PATTERN = (
    # ResNet blocks/stem, VGG/generic flax Conv/Dense, Inception modules,
    # transformer layers — first match in the HLO op_name wins.
    r"(BottleneckBlock_\d+|BasicBlock_\d+|Inception[A-E]_?\d*|"
    r"Reduction[A-B]_?\d*|conv_init|norm_init|head|layers_\d+|"
    r"Conv_\d+|Dense_\d+|reduce_window_max|select_and_scatter)")


def by_layer(durs: Dict[str, Tuple[float, int]], hlo_text: str,
             pattern: str = DEFAULT_LAYER_PATTERN):
    """Aggregate op durations by model layer using the optimized HLO's
    ``op_name`` metadata: [((layer, direction), total_us)] sorted
    descending.  ``direction`` is fwd/bwd (bwd = inside a transpose)."""
    rx = re.compile(pattern)
    meta: Dict[str, Tuple[str, str]] = {}
    for m in re.finditer(
            r"%([\w.-]+) = .*?op_name=\"([^\"]*)\"", hlo_text):
        name, op_name = m.group(1), m.group(2)
        lay = rx.search(op_name)
        direction = "bwd" if "transpose(" in op_name else "fwd"
        meta[name] = (lay.group(1) if lay else "other", direction)
    agg: Dict[Tuple[str, str], float] = collections.defaultdict(float)
    for name, (us, _) in durs.items():
        agg[meta.get(name, ("untracked", "?"))] += us
    return sorted(agg.items(), key=lambda kv: -kv[1])


def print_profile(trace_file: str, hlo_text: Optional[str] = None,
                  steps: int = 1, top: int = 20) -> None:
    """Human-readable summary: top fusion categories (and layers when the
    optimized HLO is supplied), normalized per step."""
    durs = device_op_durations(trace_file)
    total = sum(us for us, _ in durs.values())
    if total == 0:
        # Every op row had zero/absent duration (e.g. a trace captured
        # before any step ran, or a backend emitting bare markers) — the
        # percentage columns below would divide by zero.
        print(f"device time: 0.00 ms/step — trace {trace_file} contains "
              f"no timed device ops ({len(durs)} op rows, all with zero "
              f"duration); capture the trace around at least one "
              f"executed step")
        return
    print(f"device time: {total / steps / 1e3:.2f} ms/step "
          f"({len(durs)} distinct ops)")
    print("-- by fusion category --")
    for cat, us in by_category(durs)[:top]:
        print(f"  {us / steps / 1e3:9.3f} ms  {100 * us / total:5.1f}%  "
              f"{cat}")
    if hlo_text:
        print("-- by model layer (fwd/bwd) --")
        for (lay, d), us in by_layer(durs, hlo_text)[:top]:
            print(f"  {us / steps / 1e3:9.3f} ms  {100 * us / total:5.1f}%  "
                  f"{lay} [{d}]")

"""Logging, mirroring the reference's env-controlled logger.

Horovod equivalent: ``horovod/common/logging.{h,cc}`` — ``LOG(severity)``
stream macros with level from ``HOROVOD_LOG_LEVEL`` and a timestamp toggle
``HOROVOD_LOG_HIDE_TIME`` (reference ``logging.h:10-60``).  The native C++
runtime has its own copy of this scheme; this module is the Python face.
"""

from __future__ import annotations

import logging
import os
import sys

# A real TRACE severity below DEBUG (reference logging.h has TRACE as its
# lowest level; stock python does not).  High-frequency telemetry lines —
# per-op completions in the native wait path, per-call RPC records — go
# through ``log.trace`` so HOROVOD_LOG_LEVEL=debug stays readable while
# HOROVOD_LOG_LEVEL=trace turns on the firehose.
TRACE = 5
logging.addLevelName(TRACE, "TRACE")


def _trace(self, msg, *args, **kwargs):
    if self.isEnabledFor(TRACE):
        self._log(TRACE, msg, args, **kwargs)


if not hasattr(logging.Logger, "trace"):
    logging.Logger.trace = _trace

_LEVELS = {
    "trace": TRACE,
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "fatal": logging.CRITICAL,
    "none": logging.CRITICAL + 10,
}

_configured = False


def _configure() -> None:
    global _configured
    if _configured:
        return
    level_name = os.environ.get("HOROVOD_LOG_LEVEL", "warning").lower()
    level = _LEVELS.get(level_name, logging.WARNING)
    hide_time = os.environ.get("HOROVOD_LOG_HIDE_TIME", "0") == "1"
    fmt = "[%(levelname).1s %(name)s] %(message)s" if hide_time else \
          "[%(asctime)s.%(msecs)03d %(levelname).1s %(name)s] %(message)s"
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(logging.Formatter(fmt, datefmt="%Y-%m-%d %H:%M:%S"))
    root = logging.getLogger("horovod_tpu")
    root.addHandler(handler)
    root.setLevel(level)
    root.propagate = False
    _configured = True


def get_logger(name: str) -> logging.Logger:
    _configure()
    if not name.startswith("horovod_tpu"):
        name = "horovod_tpu." + name
    return logging.getLogger(name)

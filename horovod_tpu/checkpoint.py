"""Checkpoint save/restore with rank-0-writes + broadcast consistency.

The reference has no checkpoint subsystem; its convention (SURVEY §5.4)
is "rank 0 writes framework checkpoints; on start, restore on rank 0 and
broadcast state to all ranks" — ``BroadcastGlobalVariablesHook``
(reference ``tensorflow/__init__.py:159-192``), torch
``broadcast_parameters``/``broadcast_optimizer_state``
(``torch/__init__.py:255-403``), and every example gates ``checkpoint_dir``
on ``hvd.rank() == 0`` (``examples/tensorflow_mnist.py:144``).

This module makes that convention a first-class API for JAX/flax/optax
training state, backed by orbax (the TPU-ecosystem checkpointer):

    state = {"params": params, "opt_state": opt_state, "step": step}
    hvd.checkpoint.save(ckpt_dir, state, step=step)       # rank 0 only
    state = hvd.checkpoint.restore(ckpt_dir, state)       # restore+broadcast

``restore`` reads on rank 0 and broadcasts every leaf over the eager
plane, so all ranks resume bit-identical even if their local filesystems
diverge — the same consistency guarantee the reference gets from
``BroadcastGlobalVariablesCallback``.
"""

from __future__ import annotations

import atexit
import os
import threading
from typing import Any, Optional

import jax
import numpy as np

from horovod_tpu import basics, telemetry
from horovod_tpu.ops import collective as _c
from horovod_tpu.utils.logging import get_logger

log = get_logger(__name__)


def _tree_broadcast(tree: Any, root_rank: int, name_prefix: str) -> Any:
    """Broadcast every array leaf of a pytree from ``root_rank``, keyed by
    its tree path so wire names agree across ranks."""
    leaves_with_paths = jax.tree_util.tree_flatten_with_path(tree)[0]
    treedef = jax.tree_util.tree_structure(tree)
    out_leaves = []
    for path, leaf in leaves_with_paths:
        key = name_prefix + jax.tree_util.keystr(path)
        arr = np.asarray(leaf)
        out = _c._eager_broadcast(arr, root_rank, key)
        # preserve jax vs numpy leaf type and dtype
        if isinstance(leaf, jax.Array):
            import jax.numpy as jnp
            out = jnp.asarray(out, dtype=leaf.dtype)
        else:
            out = np.asarray(out, dtype=arr.dtype)
        out_leaves.append(out)
    return jax.tree_util.tree_unflatten(treedef, out_leaves)


def _gather_zero(state: Any) -> Any:
    """Replace every ZeRO-1 sharded optimizer state in ``state`` with the
    equivalent REPLICATED optax state (full per-leaf pytree).

    Checkpoints are written in this layout, so they are independent of the
    mesh the run happened to use: a 8-way-sharded run's checkpoint restores
    into a 32-way (or replicated) run unchanged."""
    from horovod_tpu.parallel import zero
    return jax.tree_util.tree_map(
        lambda x: zero.gather_full_state(x) if zero.is_zero_state(x) else x,
        state, is_leaf=zero.is_zero_state)


def _scatter_zero(state: Any, template: Any) -> Any:
    """Inverse of :func:`_gather_zero` on restore: wherever ``template``
    holds a ZeRO-1 sharded state, re-shard the restored replicated-layout
    subtree into the template's flat-bucket layout (the template — the
    freshly ``init``-ed state — supplies the bucketing plan for THIS
    mesh, which may differ from the mesh that saved)."""
    from horovod_tpu.parallel import zero
    leaves = jax.tree_util.tree_leaves(template, is_leaf=zero.is_zero_state)
    if not any(zero.is_zero_state(l) for l in leaves):
        return state
    return jax.tree_util.tree_map(
        lambda t, s: zero.scatter_full_state(s, like=t)
        if zero.is_zero_state(t) else s,
        template, state, is_leaf=zero.is_zero_state)


def _valid_steps(ckpt_dir: str) -> list:
    """Step numbers with a finalized checkpoint directory, ascending.

    A rank 0 killed mid-save (exactly what elastic restarts recover
    from) leaves orbax's temporary directory behind — the atomic-rename
    commit never happened.  Those leftovers, and finalized step dirs
    that lost their payload, are skipped with a warning: a restart must
    resume from the newest INTACT checkpoint, not die on the debris of
    the crash it is recovering from."""
    try:
        entries = os.listdir(ckpt_dir)
    except OSError:
        return []
    steps = []
    for entry in sorted(entries):
        path = os.path.join(ckpt_dir, entry)
        if not os.path.isdir(path):
            continue
        if not entry.isdigit():
            if "tmp" in entry:
                log.warning(
                    "skipping half-written checkpoint %s (temporary "
                    "directory left by an interrupted save)", path)
            continue
        try:
            empty = not os.listdir(path)
        except OSError:
            empty = True
        if empty:
            log.warning("skipping corrupt checkpoint %s: directory is "
                        "empty", path)
            continue
        steps.append(int(entry))
    return sorted(steps)


def save(ckpt_dir: str, state: Any, step: int = 0,
         max_to_keep: Optional[int] = None) -> Optional[str]:
    """Write ``state`` (a pytree) to ``ckpt_dir/<step>``; rank 0 writes,
    every other rank waits on a success-flag broadcast so no rank races
    ahead and reads a half-written checkpoint.  Returns the checkpoint
    path on rank 0 when the write succeeded, None elsewhere / on failure.

    The flag broadcast *replaces* the old barrier and fixes its deadlock:
    if rank 0's orbax write raises, peers used to wait forever in
    ``rt.barrier`` — now the exception is caught, counted
    (``hvd_checkpoint_save_failures_total``), broadcast as ``ok=0``, and
    everyone continues (degrade, don't deadlock — the next save retries).

    ZeRO-1 sharded optimizer states (``shard_optimizer=True`` /
    ``hvd.sharded_optimizer``) are gathered to the replicated per-leaf
    layout before writing, so checkpoints stay layout-independent — see
    :func:`_gather_zero`.  Any in-flight :func:`save_async` write is
    drained first."""
    wait_for_async_save()
    path = None
    ok = np.zeros(1, np.int32)
    if basics.rank() == 0:
        try:
            import orbax.checkpoint as ocp
            state = _gather_zero(state)
            ckpt_dir = os.path.abspath(ckpt_dir)
            t0 = telemetry.clock()
            with ocp.CheckpointManager(
                    ckpt_dir,
                    options=ocp.CheckpointManagerOptions(
                        max_to_keep=max_to_keep)) as mgr:
                mgr.save(step, args=ocp.args.StandardSave(state))
            if telemetry.enabled():
                telemetry.counter("hvd_checkpoint_saves_total",
                                  "Checkpoints written by rank 0").inc()
                telemetry.histogram(
                    "hvd_checkpoint_save_seconds",
                    "Wall time of a rank-0 checkpoint save").observe(
                    telemetry.clock() - t0)
            path = os.path.join(ckpt_dir, str(step))
            ok[0] = 1
            log.info("checkpoint step %d written to %s", step, path)
        except Exception as e:  # noqa: BLE001 — degrade, don't deadlock
            log.error("checkpoint save step %d to %s FAILED (%s: %s); "
                      "continuing without a checkpoint", step, ckpt_dir,
                      type(e).__name__, e)
            if telemetry.enabled():
                telemetry.counter(
                    "hvd_checkpoint_save_failures_total",
                    "rank-0 checkpoint writes that raised").inc()
    if basics.size() > 1:
        ok = _c._eager_broadcast(ok, 0, f"hvd.checkpoint.save.ok.{step}")
    return path if int(np.asarray(ok)[0]) else None


class _AsyncSave:
    """One in-flight background checkpoint write (rank 0 only)."""

    __slots__ = ("thread", "step", "path", "error")

    def __init__(self, step: int):
        self.thread = None
        self.step = step
        self.path = None
        self.error = None


_async_lock = threading.Lock()
_async_current: Optional[_AsyncSave] = None
_async_atexit_registered = False


def save_async(ckpt_dir: str, state: Any, step: int = 0,
               max_to_keep: Optional[int] = None) -> Optional[str]:
    """CheckFreq-style asynchronous save: snapshot ``state`` to host
    memory *now* (the only part that blocks the step — a device pull),
    then write it with orbax on a background thread.  Returns the
    eventual checkpoint path on rank 0, None elsewhere.

    At most one write is in flight: a previous one is drained first
    (:func:`wait_for_async_save` — also registered atexit, so a job that
    exits right after ``save_async`` never loses the checkpoint).  No
    cross-rank barrier or flag is needed, unlike :func:`save`: only
    rank 0 touches the directory, readers are protected by orbax's
    atomic rename plus :func:`_valid_steps`' intact-directory filter,
    and a background failure is logged + counted
    (``hvd_ckpt_async_failures_total``) when drained, never raised."""
    global _async_current, _async_atexit_registered
    wait_for_async_save()
    if basics.rank() != 0:
        return None
    t0 = telemetry.clock()
    state = _gather_zero(state)
    leaves, treedef = jax.tree_util.tree_flatten(state)
    for leaf in leaves:
        copy_async = getattr(leaf, "copy_to_host_async", None)
        if copy_async is not None:
            copy_async()
    snapshot = jax.tree_util.tree_unflatten(
        treedef, [np.asarray(leaf) for leaf in leaves])
    if telemetry.enabled():
        telemetry.histogram(
            "hvd_ckpt_async_snapshot_seconds",
            "device->host snapshot time per async save (the only part "
            "that blocks the step)").observe(telemetry.clock() - t0)
    ckpt_dir = os.path.abspath(ckpt_dir)
    record = _AsyncSave(step)

    def _write():
        t1 = telemetry.clock()
        try:
            import orbax.checkpoint as ocp
            with ocp.CheckpointManager(
                    ckpt_dir,
                    options=ocp.CheckpointManagerOptions(
                        max_to_keep=max_to_keep)) as mgr:
                mgr.save(step, args=ocp.args.StandardSave(snapshot))
            record.path = os.path.join(ckpt_dir, str(step))
            if telemetry.enabled():
                telemetry.counter(
                    "hvd_ckpt_async_saves_total",
                    "background checkpoint writes completed").inc()
                telemetry.histogram(
                    "hvd_ckpt_async_write_seconds",
                    "background orbax write time per async save").observe(
                    telemetry.clock() - t1)
            log.info("async checkpoint step %d written to %s", step,
                     record.path)
        except Exception as e:  # noqa: BLE001 — reported at drain time
            record.error = e
            if telemetry.enabled():
                telemetry.counter(
                    "hvd_ckpt_async_failures_total",
                    "background checkpoint writes that raised").inc()

    record.thread = threading.Thread(
        target=_write, name=f"hvd-ckpt-async-{step}", daemon=True)
    with _async_lock:
        _async_current = record
        if not _async_atexit_registered:
            atexit.register(wait_for_async_save)
            _async_atexit_registered = True
    record.thread.start()
    return os.path.join(ckpt_dir, str(step))


def wait_for_async_save(timeout: Optional[float] = None) -> Optional[str]:
    """Drain the in-flight :func:`save_async` write, if any.  Returns
    the written path, or None (no write in flight / it failed / timed
    out).  A background failure is logged here — log-and-continue, the
    deadlock-free degradation contract of :func:`save`."""
    global _async_current
    with _async_lock:
        record, _async_current = _async_current, None
    if record is None or record.thread is None:
        return None
    record.thread.join(timeout)
    if record.thread.is_alive():
        # Put it back: still running, someone may drain it later.
        with _async_lock:
            if _async_current is None:
                _async_current = record
        log.warning("async checkpoint step %d still writing after "
                    "%.1fs wait", record.step, timeout or 0.0)
        return None
    if record.error is not None:
        log.error("async checkpoint save step %d FAILED (%s: %s); "
                  "continuing without it", record.step,
                  type(record.error).__name__, record.error)
        return None
    return record.path


def restore(ckpt_dir: str, state_template: Any,
            step: Optional[int] = None, root_rank: int = 0) -> Any:
    """Restore the latest (or ``step``-th) checkpoint on ``root_rank`` and
    broadcast it to every rank.  ``state_template`` supplies the pytree
    structure/shapes/dtypes (pass the freshly-initialized state).

    ZeRO-1 sharded optimizer states in the template are restored from the
    checkpoint's replicated per-leaf layout and re-sharded into the
    template's flat-bucket layout for THIS mesh (see :func:`_scatter_zero`)
    — a checkpoint saved N-way-sharded (or replicated) restores into any
    mesh size.  Re-place the result (``step.state_shardings`` /
    ``jax.device_put``) before training."""
    # Restore + broadcast run in the layout-independent replicated format;
    # conversion back to the sharded layout happens once at the end.
    portable_template = _gather_zero(state_template)
    state = portable_template
    found = np.zeros(1, np.int32)
    t0 = telemetry.clock()
    if basics.rank() == root_rank:
        import orbax.checkpoint as ocp
        ckpt_dir = os.path.abspath(ckpt_dir)
        # Newest first; an explicitly pinned step is tried alone (falling
        # back to a DIFFERENT step than the one asked for would be
        # silently wrong).
        candidates = ([step] if step is not None
                      else list(reversed(_valid_steps(ckpt_dir))))
        for use_step in candidates:
            try:
                with ocp.CheckpointManager(ckpt_dir) as mgr:
                    state = mgr.restore(
                        use_step,
                        args=ocp.args.StandardRestore(portable_template))
                found[0] = 1
                log.info("restored checkpoint step %s from %s",
                         use_step, ckpt_dir)
                break
            except Exception as e:  # noqa: BLE001 — skip-and-warn contract
                state = portable_template
                log.warning(
                    "skipping unrestorable checkpoint step %s in %s "
                    "(%s: %s); %s", use_step, ckpt_dir,
                    type(e).__name__, e,
                    "trying the next older step" if step is None
                    else "starting fresh")
    if basics.size() > 1:
        found = _c._eager_broadcast(found, root_rank,
                                    "hvd.checkpoint.restore.found")
        if int(found[0]):
            state = _tree_broadcast(state, root_rank,
                                    "hvd.checkpoint.restore")
    state = _scatter_zero(state, state_template)
    if telemetry.enabled():
        telemetry.counter(
            "hvd_checkpoint_restores_total",
            "Checkpoint restore attempts (including broadcast)",
            found=str(bool(int(found[0])))).inc()
        telemetry.histogram(
            "hvd_checkpoint_restore_seconds",
            "Wall time of restore + cross-rank broadcast").observe(
            telemetry.clock() - t0)
    return state


def load_local(ckpt_dir: str, state_template: Any,
               step: Optional[int] = None):
    """Restore the latest (or ``step``-th) intact checkpoint from local
    disk WITHOUT any collective — the serving-replica half of the
    checkpoint plane (:func:`horovod_tpu.serving.replica
    .load_replica_model`), where every process reads its own copy
    instead of rank 0 broadcasting one.

    Returns ``(state, used_step)``; ``used_step`` is None (and ``state``
    is the template, unchanged) when nothing restorable exists.  Only
    replicated states round-trip here: ZeRO-sharded training states are
    ``restore``'s job — it owns the gather/scatter relayout, which needs
    the training mesh this path deliberately runs without.  Shares
    :func:`restore`'s skip-and-warn contract for half-written or corrupt
    step directories."""
    if not os.path.isdir(ckpt_dir):
        return state_template, None
    import orbax.checkpoint as ocp
    ckpt_dir = os.path.abspath(ckpt_dir)
    candidates = ([step] if step is not None
                  else list(reversed(_valid_steps(ckpt_dir))))
    for use_step in candidates:
        try:
            with ocp.CheckpointManager(ckpt_dir) as mgr:
                state = mgr.restore(
                    use_step,
                    args=ocp.args.StandardRestore(state_template))
            log.info("loaded checkpoint step %s locally from %s",
                     use_step, ckpt_dir)
            return state, int(use_step)
        except Exception as e:  # noqa: BLE001 — skip-and-warn contract
            log.warning(
                "skipping unrestorable checkpoint step %s in %s "
                "(%s: %s); %s", use_step, ckpt_dir,
                type(e).__name__, e,
                "trying the next older step" if step is None
                else "starting fresh")
    return state_template, None


def latest_step(ckpt_dir: str) -> Optional[int]:
    """Highest INTACT checkpoint step present in ``ckpt_dir`` (local
    read; no collective).  Half-written or corrupt step directories are
    skipped with a warning, never raised on — see :func:`_valid_steps`."""
    if not os.path.isdir(ckpt_dir):
        return None
    steps = _valid_steps(ckpt_dir)
    return steps[-1] if steps else None

"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference has NO sequence machinery (SURVEY §5.7: Horovod predates it;
its closest primitive is dim-0 allgather).  A TPU-native framework makes
long-context training first-class: the sequence axis is a mesh axis, K/V
blocks ride ICI with ``lax.ppermute`` (ring attention, Liu et al. 2023) or
heads/sequence are exchanged with ``lax.all_to_all`` (DeepSpeed-Ulysses,
Jacobs et al. 2023).

Both run inside ``shard_map`` with tensors laid out ``[batch, seq_local,
heads, head_dim]``; sequence shards are contiguous chunks in rank order
(shard i owns global positions [i*T, (i+1)*T)).

Ring attention overlaps compute with the ICI transfer of the next K/V
block and keeps memory at O(seq_local^2-per-block) via online (flash-style)
softmax accumulation, so sequence length scales linearly with the number
of chips.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def _block_attention(q, k, v, m, l, o, *, q_offset, k_offset, causal, scale,
                     q_seg=None, k_seg=None):
    """One q-block x k-block update of the online-softmax state.

    q: [B, Tq, H, D]; k, v: [B, Tk, H, D]
    m, l: [B, H, Tq] running max / denominator; o: [B, Tq, H, D] running
    numerator.  ``q_seg``/``k_seg`` ([B, Tq]/[B, Tk]) mask cross-segment
    pairs for sequence packing.  Returns updated (m, l, o).
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale  # [B, H, Tq, Tk]
    if causal:
        tq, tk = q.shape[1], k.shape[1]
        qpos = q_offset + jnp.arange(tq)[:, None]
        kpos = k_offset + jnp.arange(tk)[None, :]
        s = jnp.where(qpos >= kpos, s, -jnp.inf)
    if q_seg is not None:
        s = jnp.where(q_seg[:, None, :, None] == k_seg[:, None, None, :],
                      s, -jnp.inf)
    m_blk = jnp.max(s, axis=-1)                       # [B, H, Tq]
    m_new = jnp.maximum(m, m_blk)
    # Guard fully-masked rows: exp(-inf - -inf) -> nan without the select.
    safe_m = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
    p = jnp.exp(s - safe_m[..., None])                # [B, H, Tq, Tk]
    p = jnp.where(jnp.isneginf(s), 0.0, p)
    corr = jnp.exp(jnp.where(jnp.isneginf(m), m_new, m) - safe_m)
    corr = jnp.where(jnp.isneginf(m), 0.0, corr)
    l_new = l * corr + jnp.sum(p, axis=-1)
    o_new = (o * corr.transpose(0, 2, 1)[..., None] +
             jnp.einsum("bhqk,bkhd->bqhd", p, v))
    return m_new, l_new, o_new


def ring_attention(q, k, v, axis_name: str = "seq", causal: bool = True,
                   scale: Optional[float] = None, segment_ids=None):
    """Exact attention over a sequence sharded across ``axis_name``.

    q/k/v: [B, T_local, H, D] (this shard's chunk).  K/V blocks rotate
    around the ring via ``ppermute`` while each device accumulates its
    queries' online softmax; after axis_size steps every query has seen
    every key.  Returns [B, T_local, H, D].

    ``segment_ids`` ([B, T_local] int32, THIS shard's slice of the global
    packing layout) restricts attention to same-segment pairs: the K-side
    ids rotate around the ring with their K/V block, and the block mask is
    segment equality — the same composition the flash kernel uses.  The
    online-softmax state already tolerates fully-masked blocks (m stays
    -inf, l stays 0), so segments that live entirely on other shards cost
    only the masked matmul.
    """
    size = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b, t, h, d = q.shape
    scale = (d ** -0.5) if scale is None else scale

    m = jnp.full((b, h, t), -jnp.inf, q.dtype)
    l = jnp.zeros((b, h, t), q.dtype)
    o = jnp.zeros_like(q)

    # The scan carry's vma type must be stable: after one step the online
    # state varies over EVERY axis q/k/v vary over (e.g. 'model' too when
    # composed with tensor parallelism), not just the ring axis.  Pcast the
    # initial zeros up to the union of the inputs' vma sets.
    from horovod_tpu.parallel._vma import pin_to, vma_of
    _match_vma = pin_to(vma_of(q) | vma_of(k) | vma_of(v) | {axis_name})

    m, l, o = _match_vma(m), _match_vma(l), _match_vma(o)
    q_offset = idx * t
    perm = [(i, (i + 1) % size) for i in range(size)]

    def step(carry, s):
        if segment_ids is None:
            m, l, o, k_blk, v_blk = carry
            k_seg = None
        else:
            m, l, o, k_blk, v_blk, k_seg = carry
        # Block s arrived from rank (idx - s) mod size.
        k_offset = ((idx - s) % size) * t
        m, l, o = _block_attention(q, k_blk, v_blk, m, l, o,
                                   q_offset=q_offset, k_offset=k_offset,
                                   causal=causal, scale=scale,
                                   q_seg=segment_ids, k_seg=k_seg)
        # Rotate K/V (and their segment ids) to the right neighbor (ICI).
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        if segment_ids is None:
            return (m, l, o, k_blk, v_blk), None
        k_seg = lax.ppermute(k_seg, axis_name, perm)
        return (m, l, o, k_blk, v_blk, k_seg), None

    init = ((m, l, o, k, v) if segment_ids is None
            else (m, l, o, k, v, segment_ids))
    out = lax.scan(step, init, jnp.arange(size))[0]
    m, l, o = out[0], out[1], out[2]
    denom = jnp.where(l == 0.0, 1.0, l).transpose(0, 2, 1)[..., None]
    return o / denom


def ulysses_attention(q, k, v, axis_name: str = "seq", causal: bool = True,
                      scale: Optional[float] = None, segment_ids=None):
    """DeepSpeed-Ulysses: all-to-all from sequence-sharded to head-sharded,
    full local attention, all-to-all back.  Heads must divide axis size.

    q/k/v: [B, T_local, H, D] -> returns [B, T_local, H, D].

    ``segment_ids`` ([B, T_local], this shard's slice) enables sequence
    packing: after the all-to-all each device holds the FULL sequence for
    its head subset, so the ids are all-gathered over the seq axis once
    (tiny: int32 per token) and applied as a dense segment-equality mask.
    """
    size = lax.axis_size(axis_name)
    b, t, h, d = q.shape
    if h % size != 0:
        raise ValueError(f"heads ({h}) must be divisible by axis size "
                         f"({size}) for Ulysses attention")

    def scatter_heads(x):
        # [B, T_local, H, D] -> [B, T_global, H_local, D]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def gather_heads(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qg, kg, vg = scatter_heads(q), scatter_heads(k), scatter_heads(v)
    scale_ = (d ** -0.5) if scale is None else scale
    s = jnp.einsum("bqhd,bkhd->bhqk", qg, kg) * scale_
    tg = qg.shape[1]
    allowed = None
    if causal:
        allowed = jnp.tril(jnp.ones((tg, tg), bool))[None, None]
    if segment_ids is not None:
        seg_g = lax.all_gather(segment_ids, axis_name, axis=1, tiled=True)
        seg_ok = seg_g[:, None, :, None] == seg_g[:, None, None, :]
        allowed = seg_ok if allowed is None else (allowed & seg_ok)
    if allowed is not None:
        s = jnp.where(allowed, s, -jnp.inf)
    if segment_ids is not None:
        # Pre-softmax guard for fully-masked rows: zeros with zero
        # gradients (see local_attention).
        row_valid = allowed.any(axis=-1, keepdims=True)
        s = jnp.where(row_valid, s, 0.0)
        p = jnp.where(row_valid, jax.nn.softmax(s, axis=-1), 0.0)
    else:
        p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, vg)
    return gather_heads(out)


def local_attention(q, k, v, causal: bool = True,
                    scale: Optional[float] = None, segment_ids=None):
    """Plain single-device attention (the no-SP reference path; also the
    numerical oracle the SP tests compare against).

    ``segment_ids`` ([B, T] int32) enables sequence packing: tokens
    attend only within their own segment (composes with ``causal``).
    """
    d = q.shape[-1]
    scale = (d ** -0.5) if scale is None else scale
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    t = q.shape[1]
    allowed = None
    if causal:
        allowed = jnp.tril(jnp.ones((t, t), bool))[None, None]
    if segment_ids is not None:
        seg_ok = (segment_ids[:, None, :, None] ==
                  segment_ids[:, None, None, :])
        allowed = seg_ok if allowed is None else (allowed & seg_ok)
    if allowed is not None:
        s = jnp.where(allowed, s, -jnp.inf)
    if segment_ids is not None:
        # Fully-masked rows (possible only with exotic segment layouts
        # under causal=False) must yield zeros with zero GRADIENTS: guard
        # BEFORE the softmax (softmax of an all -inf row is NaN in both
        # forward and backward; a post-hoc isnan patch fixes only the
        # forward), matching the flash kernel's l==0 denominator handling.
        row_valid = allowed.any(axis=-1, keepdims=True)   # [B,1,T,1]
        s = jnp.where(row_valid, s, 0.0)
        p = jnp.where(row_valid, jax.nn.softmax(s, axis=-1), 0.0)
    else:
        p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)

"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference has NO sequence machinery (SURVEY §5.7: Horovod predates it;
its closest primitive is dim-0 allgather).  A TPU-native framework makes
long-context training first-class: the sequence axis is a mesh axis, K/V
blocks ride ICI with ``lax.ppermute`` (ring attention, Liu et al. 2023) or
heads/sequence are exchanged with ``lax.all_to_all`` (DeepSpeed-Ulysses,
Jacobs et al. 2023).

Both run inside ``shard_map`` with tensors laid out ``[batch, seq_local,
heads, head_dim]``; sequence shards are contiguous chunks in rank order
(shard i owns global positions [i*T, (i+1)*T)).

Ring attention overlaps compute with the ICI transfer of the next K/V
block and keeps memory at O(seq_local^2-per-block) via online (flash-style)
softmax accumulation, so sequence length scales linearly with the number
of chips.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def _block_attention(q, k, v, m, l, o, *, q_offset, k_offset, causal, scale,
                     q_seg=None, k_seg=None):
    """One q-block x k-block update of the online-softmax state.

    q: [B, Tq, H, D]; k, v: [B, Tk, H, D]
    m, l: [B, H, Tq] running max / denominator; o: [B, Tq, H, D] running
    numerator.  ``q_seg``/``k_seg`` ([B, Tq]/[B, Tk]) mask cross-segment
    pairs for sequence packing.  Returns updated (m, l, o).
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale  # [B, H, Tq, Tk]
    if causal:
        tq, tk = q.shape[1], k.shape[1]
        qpos = q_offset + jnp.arange(tq)[:, None]
        kpos = k_offset + jnp.arange(tk)[None, :]
        s = jnp.where(qpos >= kpos, s, -jnp.inf)
    if q_seg is not None:
        s = jnp.where(q_seg[:, None, :, None] == k_seg[:, None, None, :],
                      s, -jnp.inf)
    m_blk = jnp.max(s, axis=-1)                       # [B, H, Tq]
    m_new = jnp.maximum(m, m_blk)
    # Guard fully-masked rows: exp(-inf - -inf) -> nan without the select.
    safe_m = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
    p = jnp.exp(s - safe_m[..., None])                # [B, H, Tq, Tk]
    p = jnp.where(jnp.isneginf(s), 0.0, p)
    corr = jnp.exp(jnp.where(jnp.isneginf(m), m_new, m) - safe_m)
    corr = jnp.where(jnp.isneginf(m), 0.0, corr)
    l_new = l * corr + jnp.sum(p, axis=-1)
    o_new = (o * corr.transpose(0, 2, 1)[..., None] +
             jnp.einsum("bhqk,bkhd->bqhd", p, v))
    return m_new, l_new, o_new


def ring_attention(q, k, v, axis_name: str = "seq", causal: bool = True,
                   scale: Optional[float] = None, segment_ids=None):
    """Exact attention over a sequence sharded across ``axis_name``.

    q/k/v: [B, T_local, H, D] (this shard's chunk).  K/V blocks rotate
    around the ring via ``ppermute`` while each device accumulates its
    queries' online softmax; after axis_size steps every query has seen
    every key.  Returns [B, T_local, H, D].

    ``segment_ids`` ([B, T_local] int32, THIS shard's slice of the global
    packing layout) restricts attention to same-segment pairs: the K-side
    ids rotate around the ring with their K/V block, and the block mask is
    segment equality — the same composition the flash kernel uses.  The
    online-softmax state already tolerates fully-masked blocks (m stays
    -inf, l stays 0), so segments that live entirely on other shards cost
    only the masked matmul.
    """
    size = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b, t, h, d = q.shape
    scale = (d ** -0.5) if scale is None else scale

    m = jnp.full((b, h, t), -jnp.inf, q.dtype)
    l = jnp.zeros((b, h, t), q.dtype)
    o = jnp.zeros_like(q)

    # The scan carry's vma type must be stable: after one step the online
    # state varies over EVERY axis q/k/v vary over (e.g. 'model' too when
    # composed with tensor parallelism), not just the ring axis.  Pcast the
    # initial zeros up to the union of the inputs' vma sets.
    from horovod_tpu.parallel._vma import pin_to, vma_of
    _match_vma = pin_to(vma_of(q) | vma_of(k) | vma_of(v) | {axis_name})

    m, l, o = _match_vma(m), _match_vma(l), _match_vma(o)
    q_offset = idx * t
    perm = [(i, (i + 1) % size) for i in range(size)]

    def step(carry, s):
        if segment_ids is None:
            m, l, o, k_blk, v_blk = carry
            k_seg = None
        else:
            m, l, o, k_blk, v_blk, k_seg = carry
        # Block s arrived from rank (idx - s) mod size.
        k_offset = ((idx - s) % size) * t
        m, l, o = _block_attention(q, k_blk, v_blk, m, l, o,
                                   q_offset=q_offset, k_offset=k_offset,
                                   causal=causal, scale=scale,
                                   q_seg=segment_ids, k_seg=k_seg)
        # Rotate K/V (and their segment ids) to the right neighbor (ICI).
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        if segment_ids is None:
            return (m, l, o, k_blk, v_blk), None
        k_seg = lax.ppermute(k_seg, axis_name, perm)
        return (m, l, o, k_blk, v_blk, k_seg), None

    init = ((m, l, o, k, v) if segment_ids is None
            else (m, l, o, k, v, segment_ids))
    out = lax.scan(step, init, jnp.arange(size))[0]
    m, l, o = out[0], out[1], out[2]
    denom = jnp.where(l == 0.0, 1.0, l).transpose(0, 2, 1)[..., None]
    return o / denom


def _merge_online(m, l, acc, m_b, l_b, o_b):
    """Merge a block's (m_b, l_b, o_b-normalized) into the running
    (m, l, acc-unnormalized) online-softmax state.  All m/l are
    [bh, 1, T] fp32; acc/o_b are [bh, T, D]."""
    m_new = jnp.maximum(m, m_b)
    safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
    c1 = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - safe))
    c2 = jnp.where(jnp.isneginf(m_b), 0.0, jnp.exp(m_b - safe))
    l_new = l * c1 + l_b * c2
    row = lambda x: x[:, 0, :, None]                     # [bh, T, 1]
    acc_new = acc * row(c1) + o_b.astype(jnp.float32) * row(l_b * c2)
    return m_new, l_new, acc_new


def _lax_fwd_parts(qf, kf, vf, qsegf, ksegf, h, causal, scale, bq, bk,
                   interp):
    """Interpret-mode twin of ``flash_attention._fwd_parts``: the same
    (o, m, l) contract in plain lax ops.  Exists because the Pallas HLO
    interpreter traces kernel internals into the vma-checked jaxpr and
    rejects ppermuted operands under ``check_vma=True`` (CPU-only
    limitation; the compiled TPU path runs the kernel).  Doubles as an
    independent oracle of the kernel's formulas."""
    s = jnp.einsum("bqd,bkd->bqk", qf.astype(jnp.float32),
                   kf.astype(jnp.float32)) * scale
    t = qf.shape[1]
    if causal:
        pos = jnp.arange(t)
        s = jnp.where(pos[:, None] >= pos[None, :], s, -jnp.inf)
    if qsegf is not None:
        qs = jnp.repeat(qsegf[:, 0, :], h, axis=0)       # [bh, T]
        ks = jnp.repeat(ksegf[:, 0, :], h, axis=0)
        s = jnp.where(qs[:, :, None] == ks[:, None, :], s, -jnp.inf)
    m = jnp.max(s, axis=-1)                              # [bh, T]
    safe = jnp.where(jnp.isneginf(m), 0.0, m)
    p = jnp.where(jnp.isneginf(s), 0.0, jnp.exp(s - safe[..., None]))
    l = jnp.sum(p, axis=-1)
    denom = jnp.where(l == 0.0, 1.0, l)
    o = (jnp.einsum("bqk,bkd->bqd", p, vf.astype(jnp.float32)) /
         denom[..., None]).astype(qf.dtype)
    return o, m[:, None, :], l[:, None, :]


def _lax_bwd_parts(qf, kf, vf, of, dof, m, l, qsegf, ksegf, h, causal,
                   scale, bq, bk, interp):
    """Interpret-mode twin of ``flash_attention._bwd_parts`` (same
    global-(m, l) blockwise gradient formulas in plain lax ops)."""
    f32 = jnp.float32
    s = jnp.einsum("bqd,bkd->bqk", qf.astype(f32),
                   kf.astype(f32)) * scale
    t = qf.shape[1]
    if causal:
        pos = jnp.arange(t)
        s = jnp.where(pos[:, None] >= pos[None, :], s, -jnp.inf)
    if qsegf is not None:
        qs = jnp.repeat(qsegf[:, 0, :], h, axis=0)
        ks = jnp.repeat(ksegf[:, 0, :], h, axis=0)
        s = jnp.where(qs[:, :, None] == ks[:, None, :], s, -jnp.inf)
    safe = jnp.where(jnp.isneginf(m[:, 0, :]), 0.0, m[:, 0, :])
    denom = jnp.where(l[:, 0, :] == 0.0, 1.0, l[:, 0, :])
    p = jnp.where(jnp.isneginf(s), 0.0,
                  jnp.exp(s - safe[..., None])) / denom[..., None]
    do32, o32 = dof.astype(f32), of.astype(f32)
    di = jnp.sum(do32 * o32, axis=-1)                    # [bh, T]
    dv = jnp.einsum("bqk,bqd->bkd", p, do32)
    dp = jnp.einsum("bqd,bkd->bqk", do32, vf.astype(f32))
    ds = p * (dp - di[..., None])
    dq = jnp.einsum("bqk,bkd->bqd", ds, kf.astype(f32)) * scale
    dk = jnp.einsum("bqk,bqd->bkd", ds, qf.astype(f32)) * scale
    return dq.astype(qf.dtype), dk.astype(kf.dtype), dv.astype(vf.dtype)


def _exec_on_tpu(x) -> bool:
    """See :func:`horovod_tpu.ops.flash_attention._exec_on_tpu` — the
    mesh-executing-the-computation platform answer (not the host's
    default backend)."""
    from horovod_tpu.ops import flash_attention as fa
    return fa._exec_on_tpu(x)


def _interp_default_for(x) -> bool:
    """Operand-aware kernel interpret default — delegates to
    :func:`horovod_tpu.ops.flash_attention._interpret_default`."""
    from horovod_tpu.ops import flash_attention as fa
    return fa._interpret_default(x)


def _ring_use_kernel(interpret, interp) -> bool:
    """Kernel vs lax-twin selection for the ring parts: compiled (TPU)
    always runs the kernel; an EXPLICIT interpreter request — the
    ``interpret=True`` argument or ``HOROVOD_FLASH_INTERPRET=1`` —
    keeps the kernel in the Pallas interpreter (kernel-debug surface);
    only the implicit non-TPU default takes the lax twin."""
    import os
    return ((interpret is True) or not interp or
            os.environ.get("HOROVOD_FLASH_INTERPRET") == "1")


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def ring_flash_attention(q, k, v, axis_name: str = "seq",
                         causal: bool = True,
                         scale: Optional[float] = None,
                         interpret: Optional[bool] = None,
                         segment_ids=None):
    """Ring attention with the Pallas flash kernel as the per-step block
    math (Liu et al. 2023 structure; kernel from
    ``ops/flash_attention``).

    Identical semantics to :func:`ring_attention` — exact attention over
    a sequence sharded on ``axis_name``, K/V (and K-side segment ids)
    rotating via ``ppermute`` — but each ring step runs the flash
    forward kernel on the (local Q) x (arriving K/V) pair and merges the
    kernel's online-softmax state (m, l) across steps, so scores never
    materialize in HBM and the block math rides the measured-faster
    kernel (docs/kernels.md).  The DIAGONAL step (own block) uses the
    causal kernel with tile elision; off-diagonal steps are
    position-free (fully visible or fully masked by ring geometry), so
    they run the non-causal kernel and masked steps are zeroed at the
    merge — the same wasted-matmul cost profile as the lax route.

    The backward is a hand-scheduled second ring pass: per arriving
    block, the flash dq/dkv kernels run with the FINAL (m, l) rows —
    block contributions under the global softmax are exactly the global
    gradients — dq accumulates locally while dk/dv accumulate on the
    rotating block and arrive home after the full cycle.
    """
    out, _ = _ring_flash_fwd(q, k, v, axis_name, causal, scale,
                             interpret, segment_ids)
    return out


def _ring_flash_fwd(q, k, v, axis_name, causal, scale, interpret,
                    segment_ids):
    from horovod_tpu.ops import flash_attention as fa

    size = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    bq, bk = fa._eff_blocks(q.shape[1], None, None, q.shape[-1])
    b, t, h, d = fa._check_shapes(q, k, v, bq, bk)
    scale_ = (d ** -0.5) if scale is None else scale
    interp = _interp_default_for(q) if interpret is None else interpret

    if segment_ids is not None:
        if segment_ids.shape != (b, t):
            raise ValueError(
                f"segment_ids must be [B, T_local] = {(b, t)} matching "
                f"this shard's q/k/v, got {segment_ids.shape}")
        if not jnp.issubdtype(segment_ids.dtype, jnp.integer):
            raise ValueError(
                f"segment_ids must be integer, got {segment_ids.dtype}")
    qf, kf, vf = fa._fold(q), fa._fold(k), fa._fold(v)
    segf = (segment_ids.reshape(b, 1, t)
            if segment_ids is not None else None)

    from horovod_tpu.parallel._vma import pin_to, vma_of
    _pin = pin_to(vma_of(q) | vma_of(k) | vma_of(v) | {axis_name})

    # Parts selection: the compiled TPU path always runs the kernel; an
    # EXPLICIT interpreter request (interpret=True or
    # HOROVOD_FLASH_INTERPRET=1) keeps the kernel in the Pallas
    # interpreter (the kernel-debug/test surface; needs check_vma=False
    # — the interpreter traces kernel internals into the vma-checked
    # jaxpr and rejects ppermuted operands); the None-default on a
    # non-TPU backend takes the lax twin so user CPU runs work under
    # check_vma=True train steps.
    use_kernel = _ring_use_kernel(interpret, interp)
    fwd_parts = fa._fwd_parts if use_kernel else _lax_fwd_parts

    # Diagonal step: own K/V, standard causal kernel (tile elision on).
    o0, m, l = fwd_parts(qf, kf, vf, segf, segf, h, causal, scale_,
                         bq, bk, interp)
    row = lambda x: x[:, 0, :, None]
    acc = o0.astype(jnp.float32) * row(l)
    m, l, acc = _pin(m), _pin(l), _pin(acc)

    perm = [(i, (i + 1) % size) for i in range(size)]
    k_rot = lax.ppermute(kf, axis_name, perm)
    v_rot = lax.ppermute(vf, axis_name, perm)
    kseg_rot = (lax.ppermute(segf, axis_name, perm)
                if segf is not None else None)

    def step(carry, s):
        if segf is None:
            m, l, acc, k_rot, v_rot = carry
            kseg = None
        else:
            m, l, acc, k_rot, v_rot, kseg = carry
        o_b, m_b, l_b = fwd_parts(qf, k_rot, v_rot, segf, kseg, h,
                                  False, scale_, bq, bk, interp)
        if causal:
            # Block s arrived from rank (idx - s) mod size: fully
            # visible iff it sits strictly left of our chunk (s <= idx).
            vis = (s <= idx)
            m_b = jnp.where(vis, m_b, -jnp.inf)
            l_b = jnp.where(vis, l_b, 0.0)
        m, l, acc = _merge_online(m, l, acc, m_b, l_b, o_b)
        k_rot = lax.ppermute(k_rot, axis_name, perm)
        v_rot = lax.ppermute(v_rot, axis_name, perm)
        if segf is None:
            return (m, l, acc, k_rot, v_rot), None
        kseg = lax.ppermute(kseg, axis_name, perm)
        return (m, l, acc, k_rot, v_rot, kseg), None

    init = ((m, l, acc, k_rot, v_rot) if segf is None
            else (m, l, acc, k_rot, v_rot, kseg_rot))
    out = lax.scan(step, init, jnp.arange(1, size))[0]
    m, l, acc = out[0], out[1], out[2]
    denom = jnp.where(l == 0.0, 1.0, l)
    of = (acc / row(denom)).astype(q.dtype)
    return fa._unfold(of, b, h), (qf, kf, vf, segf, of, m, l, b, h)


def _ring_flash_bwd(axis_name, causal, scale, interpret, res, do):
    from horovod_tpu.ops import flash_attention as fa

    qf, kf, vf, segf, of, m, l, b, h = res
    size = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    bh, t, d = qf.shape
    scale_ = (d ** -0.5) if scale is None else scale
    interp = _interp_default_for(qf) if interpret is None else interpret
    bq, bk = fa._eff_blocks(t, None, None, d)
    dof = fa._fold(do)

    from horovod_tpu.parallel._vma import pin_to, vma_of
    _pin = pin_to(vma_of(qf) | vma_of(kf) | vma_of(vf) | {axis_name})

    use_kernel = _ring_use_kernel(interpret, interp)   # see forward
    bwd_parts = fa._bwd_parts if use_kernel else _lax_bwd_parts

    # Diagonal step with the causal kernels and GLOBAL m/l rows.
    dq0, dk0, dv0 = bwd_parts(qf, kf, vf, of, dof, m, l, segf, segf,
                              h, causal, scale_, bq, bk, interp)
    dq_acc = _pin(dq0.astype(jnp.float32))
    perm = [(i, (i + 1) % size) for i in range(size)]
    k_rot = lax.ppermute(kf, axis_name, perm)
    v_rot = lax.ppermute(vf, axis_name, perm)
    dk_rot = _pin(lax.ppermute(dk0.astype(jnp.float32), axis_name, perm))
    dv_rot = _pin(lax.ppermute(dv0.astype(jnp.float32), axis_name, perm))
    kseg_rot = (lax.ppermute(segf, axis_name, perm)
                if segf is not None else None)

    def step(carry, s):
        if segf is None:
            dq_acc, dk_rot, dv_rot, k_rot, v_rot = carry
            kseg = None
        else:
            dq_acc, dk_rot, dv_rot, k_rot, v_rot, kseg = carry
        dq_b, dk_b, dv_b = bwd_parts(qf, k_rot, v_rot, of, dof, m, l,
                                     segf, kseg, h, False, scale_,
                                     bq, bk, interp)
        if causal:
            vis = (s <= idx)
            z = lambda g: jnp.where(vis, g.astype(jnp.float32), 0.0)
        else:
            z = lambda g: g.astype(jnp.float32)
        dq_acc = dq_acc + z(dq_b)
        dk_rot = dk_rot + z(dk_b)
        dv_rot = dv_rot + z(dv_b)
        k_rot = lax.ppermute(k_rot, axis_name, perm)
        v_rot = lax.ppermute(v_rot, axis_name, perm)
        dk_rot = lax.ppermute(dk_rot, axis_name, perm)
        dv_rot = lax.ppermute(dv_rot, axis_name, perm)
        if segf is None:
            return (dq_acc, dk_rot, dv_rot, k_rot, v_rot), None
        kseg = lax.ppermute(kseg, axis_name, perm)
        return (dq_acc, dk_rot, dv_rot, k_rot, v_rot, kseg), None

    init = ((dq_acc, dk_rot, dv_rot, k_rot, v_rot) if segf is None
            else (dq_acc, dk_rot, dv_rot, k_rot, v_rot, kseg_rot))
    out = lax.scan(step, init, jnp.arange(1, size))[0]
    dq_acc, dk_fin, dv_fin = out[0], out[1], out[2]
    dq = fa._unfold(dq_acc.astype(qf.dtype), b, h)
    dk = fa._unfold(dk_fin.astype(kf.dtype), b, h)
    dv = fa._unfold(dv_fin.astype(vf.dtype), b, h)
    import numpy as np
    dseg = (np.zeros((b, t), jax.dtypes.float0)
            if segf is not None else None)
    return dq, dk, dv, dseg


ring_flash_attention.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def ulysses_attention(q, k, v, axis_name: str = "seq", causal: bool = True,
                      scale: Optional[float] = None, segment_ids=None,
                      use_flash: Optional[bool] = None):
    """DeepSpeed-Ulysses: all-to-all from sequence-sharded to head-sharded,
    full local attention, all-to-all back.  Heads must divide axis size.

    q/k/v: [B, T_local, H, D] -> returns [B, T_local, H, D].

    ``segment_ids`` ([B, T_local], this shard's slice) enables sequence
    packing: after the all-to-all each device holds the FULL sequence for
    its head subset, so the ids are all-gathered over the seq axis once
    (tiny: int32 per token) and applied as a dense segment-equality mask.

    ``use_flash``: the post-all-to-all attention is plain single-device
    attention over the FULL T_global, so the Pallas flash kernel applies
    directly — same exact math, O(block) instead of O(T_global²) score
    memory (r4).  ``None`` auto-selects it on a compiled TPU backend
    when T_global divides the kernel blocks; the lax route remains the
    CPU/oracle path (interpret-mode kernels need ``check_vma=False``,
    see :func:`_ring_use_kernel`).
    """
    size = lax.axis_size(axis_name)
    b, t, h, d = q.shape
    if h % size != 0:
        raise ValueError(f"heads ({h}) must be divisible by axis size "
                         f"({size}) for Ulysses attention")

    def scatter_heads(x):
        # [B, T_local, H, D] -> [B, T_global, H_local, D]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def gather_heads(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qg, kg, vg = scatter_heads(q), scatter_heads(k), scatter_heads(v)
    scale_ = (d ** -0.5) if scale is None else scale
    tg_ = qg.shape[1]
    # The kernel's own interpret default keys on the host's default
    # backend; answer it here from the EXECUTING mesh instead so a host
    # whose default backend disagrees with the mesh can neither select
    # the compiled TPU kernel for a CPU mesh (explicit use_flash=True)
    # nor flip into the interpreter-debug surface mid-gate (auto path).
    # HOROVOD_FLASH_INTERPRET=1 still wins inside _interp_default_for.
    flash_interpret = _interp_default_for(qg)
    if use_flash is None:
        import os
        on_tpu = _exec_on_tpu(qg)
        # Auto mirrors the model-level flash gate: COMPILED kernel only
        # (HOROVOD_FLASH_INTERPRET=1 means the interpreter-debug
        # surface, which needs check_vma=False — explicit use_flash
        # there), 128-divisible T_global, and above the measured
        # flash-vs-lax crossover (HOROVOD_FLASH_AUTO_MIN_T, same knob
        # as attention="auto").
        min_t = int(os.environ.get("HOROVOD_FLASH_AUTO_MIN_T", "1024"))
        use_flash = (on_tpu and
                     os.environ.get("HOROVOD_FLASH_INTERPRET") != "1" and
                     tg_ % 128 == 0 and tg_ >= min_t)
    if use_flash:
        from horovod_tpu.ops.flash_attention import flash_attention
        seg_g = (lax.all_gather(segment_ids, axis_name, axis=1,
                                tiled=True)
                 if segment_ids is not None else None)
        out = flash_attention(qg, kg, vg, causal, scale_,
                              interpret=flash_interpret,
                              segment_ids=seg_g)
        return gather_heads(out)
    s = jnp.einsum("bqhd,bkhd->bhqk", qg, kg) * scale_
    tg = qg.shape[1]
    allowed = None
    if causal:
        allowed = jnp.tril(jnp.ones((tg, tg), bool))[None, None]
    if segment_ids is not None:
        seg_g = lax.all_gather(segment_ids, axis_name, axis=1, tiled=True)
        seg_ok = seg_g[:, None, :, None] == seg_g[:, None, None, :]
        allowed = seg_ok if allowed is None else (allowed & seg_ok)
    if allowed is not None:
        s = jnp.where(allowed, s, -jnp.inf)
    if segment_ids is not None:
        # Pre-softmax guard for fully-masked rows: zeros with zero
        # gradients (see local_attention).
        row_valid = allowed.any(axis=-1, keepdims=True)
        s = jnp.where(row_valid, s, 0.0)
        p = jnp.where(row_valid, jax.nn.softmax(s, axis=-1), 0.0)
    else:
        p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, vg)
    return gather_heads(out)


def local_attention(q, k, v, causal: bool = True,
                    scale: Optional[float] = None, segment_ids=None):
    """Plain single-device attention (the no-SP reference path; also the
    numerical oracle the SP tests compare against).

    ``segment_ids`` ([B, T] int32) enables sequence packing: tokens
    attend only within their own segment (composes with ``causal``).
    """
    d = q.shape[-1]
    scale = (d ** -0.5) if scale is None else scale
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    t = q.shape[1]
    allowed = None
    if causal:
        allowed = jnp.tril(jnp.ones((t, t), bool))[None, None]
    if segment_ids is not None:
        seg_ok = (segment_ids[:, None, :, None] ==
                  segment_ids[:, None, None, :])
        allowed = seg_ok if allowed is None else (allowed & seg_ok)
    if allowed is not None:
        s = jnp.where(allowed, s, -jnp.inf)
    if segment_ids is not None:
        # Fully-masked rows (possible only with exotic segment layouts
        # under causal=False) must yield zeros with zero GRADIENTS: guard
        # BEFORE the softmax (softmax of an all -inf row is NaN in both
        # forward and backward; a post-hoc isnan patch fixes only the
        # forward), matching the flash kernel's l==0 denominator handling.
        row_valid = allowed.any(axis=-1, keepdims=True)   # [B,1,T,1]
        s = jnp.where(row_valid, s, 0.0)
        p = jnp.where(row_valid, jax.nn.softmax(s, axis=-1), 0.0)
    else:
        p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)

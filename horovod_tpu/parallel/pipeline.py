"""Pipeline parallelism: GPipe-style microbatching over a 'pipe' mesh axis.

Not in the reference (SURVEY §2.5: Horovod has no PP).  TPU-native design:
stage parameters are STACKED on a leading axis sharded over the pipe axis
(device p holds stage p's slice), microbatch activations flow stage-to-stage
with ``lax.ppermute`` over ICI, and the schedule is one ``lax.scan`` over
M + P - 1 ticks.  Because the whole schedule is a differentiable JAX
program, ``jax.grad`` through it yields the reverse (backward) pipeline
automatically — no hand-written 1F1B bookkeeping.

Layout inside shard_map:
* ``stage_params``: pytree whose leaves have leading dim = stages/axis_size
  (usually 1) — this device's stages.
* ``microbatches``: [M, mb, ...] — every device receives the SAME
  microbatch array (replicated over the pipe axis); stage 0 is the one that
  feeds it in.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax


def pipeline_apply(stage_fn: Callable, stage_params, microbatches,
                   axis_name: str = "pipe"):
    """Run ``stage_fn(params_slice, x) -> y`` as a pipeline over
    ``axis_name``.

    stage_fn must map activations of shape [mb, ...] to the SAME shape
    (uniform stages — e.g. a group of transformer blocks).

    Returns [M, mb, ...]: the last stage's outputs for every microbatch
    (valid on every device — results are broadcast from the last stage).
    """
    size = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    m = microbatches.shape[0]
    mb_shape = microbatches.shape[1:]
    ticks = m + size - 1

    right_perm = [(i, (i + 1) % size) for i in range(size)]

    def tick(carry, t):
        incoming, outputs = carry
        # Stage 0 injects microbatch t (zeros once the supply runs out);
        # other stages consume what arrived from the left.
        feed = lax.dynamic_index_in_dim(
            microbatches, jnp.minimum(t, m - 1), axis=0, keepdims=False)
        x = jnp.where(idx == 0, feed, incoming)
        y = stage_fn(stage_params, x)
        # Valid only when the wavefront has reached this stage: stage s
        # works on microbatch t - s for s <= t < s + m.
        mb_idx = t - idx
        valid = (mb_idx >= 0) & (mb_idx < m)
        # Last stage records its result.  A select, not lax.cond: the
        # updated array varies over the pipe axis (y depends on axis_index)
        # while the untouched one may not, and cond requires both branches
        # to have identical vma types — jnp.where unifies them.
        updated = lax.dynamic_update_index_in_dim(
            outputs, y, jnp.maximum(mb_idx, 0), axis=0)
        outputs = jnp.where(valid & (idx == size - 1), updated, outputs)
        incoming = lax.ppermute(y, axis_name, right_perm)
        return (incoming, outputs), None

    # Carry is varying over the pipe axis from tick 1 on — and over every
    # axis the inputs vary over (e.g. 'data' when composed with DP).  Pin
    # the union at init so the scan carry type is stable across iterations.
    def _vma(v):
        try:
            return set(jax.typeof(v).vma)
        except AttributeError:
            return set()

    target = {axis_name} | _vma(microbatches)
    for leaf in jax.tree_util.tree_leaves(stage_params):
        target |= _vma(leaf)

    def _pin(v):
        missing = tuple(sorted(target - _vma(v)))
        if not missing:
            return v
        try:
            return lax.pcast(v, missing, to="varying")
        except ValueError:  # no surrounding mesh context
            return v

    init = (_pin(jnp.zeros(mb_shape, microbatches.dtype)),
            _pin(jnp.zeros((m,) + mb_shape, microbatches.dtype)))
    (_, outputs), _ = lax.scan(tick, init, jnp.arange(ticks))
    # Broadcast final outputs from the last stage to every pipe rank so
    # downstream (loss) code is uniform SPMD.
    masked = jnp.where(idx == size - 1, outputs, jnp.zeros_like(outputs))
    return lax.psum(masked, axis_name)


def stack_stage_params(per_stage_params):
    """Stack a list of per-stage pytrees into leading-dim-stacked leaves
    (shard this output over the pipe axis with PartitionSpec('pipe', ...))."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_stage_params)

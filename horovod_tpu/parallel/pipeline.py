"""Pipeline parallelism: GPipe-style microbatching over a 'pipe' mesh axis.

Not in the reference (SURVEY §2.5: Horovod has no PP).  TPU-native design:
stage parameters are STACKED on a leading axis sharded over the pipe axis
(device p holds stage p's slice), microbatch activations flow stage-to-stage
with ``lax.ppermute`` over ICI, and the schedule is one ``lax.scan`` over
M + P - 1 ticks.  Because the whole schedule is a differentiable JAX
program, ``jax.grad`` through it yields the reverse (backward) pipeline
automatically — no hand-written 1F1B bookkeeping.

Layout inside shard_map:
* ``stage_params``: pytree whose leaves have leading dim = stages/axis_size
  (usually 1) — this device's stages.
* ``microbatches``: [M, mb, ...] — every device receives the SAME
  microbatch array (replicated over the pipe axis); stage 0 is the one that
  feeds it in.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax


def pipeline_apply(stage_fn: Callable, stage_params, microbatches,
                   axis_name: str = "pipe"):
    """Run ``stage_fn(params_slice, x) -> y`` as a pipeline over
    ``axis_name``.

    stage_fn must map activations of shape [mb, ...] to the SAME shape
    (uniform stages — e.g. a group of transformer blocks).

    Returns [M, mb, ...]: the last stage's outputs for every microbatch
    (valid on every device — results are broadcast from the last stage).
    """
    size = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    m = microbatches.shape[0]
    mb_shape = microbatches.shape[1:]
    ticks = m + size - 1

    right_perm = [(i, (i + 1) % size) for i in range(size)]

    def tick(carry, t):
        incoming, outputs = carry
        # Stage 0 injects microbatch t (zeros once the supply runs out);
        # other stages consume what arrived from the left.
        feed = lax.dynamic_index_in_dim(
            microbatches, jnp.minimum(t, m - 1), axis=0, keepdims=False)
        x = jnp.where(idx == 0, feed, incoming)
        y = stage_fn(stage_params, x)
        # Valid only when the wavefront has reached this stage: stage s
        # works on microbatch t - s for s <= t < s + m.
        mb_idx = t - idx
        valid = (mb_idx >= 0) & (mb_idx < m)
        # Last stage records its result.  A select, not lax.cond: the
        # updated array varies over the pipe axis (y depends on axis_index)
        # while the untouched one may not, and cond requires both branches
        # to have identical vma types — jnp.where unifies them.
        updated = lax.dynamic_update_index_in_dim(
            outputs, y, jnp.maximum(mb_idx, 0), axis=0)
        outputs = jnp.where(valid & (idx == size - 1), updated, outputs)
        incoming = lax.ppermute(y, axis_name, right_perm)
        return (incoming, outputs), None

    # Carry is varying over the pipe axis from tick 1 on — and over every
    # axis the inputs vary over (e.g. 'data' when composed with DP).  Pin
    # the union at init so the scan carry type is stable across iterations.
    from horovod_tpu.parallel._vma import pin_to, vma_of

    target = {axis_name} | vma_of(microbatches)
    for leaf in jax.tree_util.tree_leaves(stage_params):
        target |= vma_of(leaf)
    _pin = pin_to(target)

    init = (_pin(jnp.zeros(mb_shape, microbatches.dtype)),
            _pin(jnp.zeros((m,) + mb_shape, microbatches.dtype)))
    (_, outputs), _ = lax.scan(tick, init, jnp.arange(ticks))
    # Broadcast final outputs from the last stage to every pipe rank so
    # downstream (loss) code is uniform SPMD.
    masked = jnp.where(idx == size - 1, outputs, jnp.zeros_like(outputs))
    return lax.psum(masked, axis_name)


def stack_stage_params(per_stage_params):
    """Stack a list of per-stage pytrees into leading-dim-stacked leaves
    (shard this output over the pipe axis with PartitionSpec('pipe', ...))."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_stage_params)


def pipeline_apply_interleaved(stage_fn: Callable, stage_params,
                               microbatches, axis_name: str = "pipe",
                               virtual: int = 2):
    """Interleaved (virtual-stage) pipeline forward — Megatron's
    round-robin placement expressed as ONE lockstep ``lax.scan``.

    Device p holds the ``virtual`` chunks with GLOBAL stage ids
    ``{k·P + p : k < v}`` (local slot k = global stage k·P + p; use
    :func:`horovod_tpu.models.transformer.stack_layer_params_interleaved`
    for the layout).  Each chunk is 1/v of a stage, so each tick costs
    ``(t_f)/v`` — and the schedule below keeps consecutive global stages
    on consecutive ticks, so the pipeline FILL is ``P−1`` ticks of a
    1/v-size chunk: the bubble divides by v (the round-3 claim in
    docs/parallelism.md that the saving cancels was wrong — it assumed a
    v·P-tick fill; the round-robin wavefront only needs P−1).

    Schedule: at tick s, device p runs work unit ``u = s − p`` (valid for
    ``0 ≤ u < v·M``) with

    * chunk   ``k = (u // P) mod v``
    * microbatch ``m = (u // (P·v))·P + (u mod P)``  (requires M % P == 0)

    Stage ``g = k·P + p`` of microbatch m therefore runs at
    ``s = p + P·(v·(m//P) + k) + (m mod P)``; its predecessor ``g−1`` —
    device p−1 same k, or device P−1 chunk k−1 when p = 0 — runs at
    exactly ``s−1``, so one ppermute-right chain carries all the
    dataflow.  Differentiating through the scan yields the reverse
    interleaved backward with the same 1/v fill.  With ``virtual=1``
    this degenerates to :func:`pipeline_apply`'s schedule.

    Returns [M, mb, ...]: last-chunk outputs, broadcast to every device.
    """
    size = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    m = microbatches.shape[0]
    mb_shape = microbatches.shape[1:]
    v = virtual
    leads = {l.shape[0] for l in jax.tree_util.tree_leaves(stage_params)}
    if leads != {v}:
        raise ValueError(
            f"interleaved stage_params leaves must have leading dim "
            f"virtual={v}; got {sorted(leads)} — stack with "
            f"stack_layer_params_interleaved(params, pipe_size, virtual)")
    if m % size:
        raise ValueError(
            f"interleaved schedule needs n_microbatches ({m}) divisible "
            f"by the pipe axis size ({size})")
    ticks = v * m + size - 1

    right_perm = [(i, (i + 1) % size) for i in range(size)]

    def tick(carry, s):
        incoming, outputs = carry
        u = jnp.maximum(s - idx, 0)
        k = (u // size) % v
        mb_idx = (u // (size * v)) * size + (u % size)
        valid = (s - idx >= 0) & (u < v * m)
        feed = lax.dynamic_index_in_dim(
            microbatches, jnp.clip(mb_idx, 0, m - 1), axis=0,
            keepdims=False)
        x = jnp.where((idx == 0) & (k == 0), feed, incoming)
        params_k = jax.tree_util.tree_map(
            lambda l: lax.dynamic_index_in_dim(l, k, axis=0,
                                               keepdims=True),
            stage_params)
        y = stage_fn(params_k, x)
        updated = lax.dynamic_update_index_in_dim(
            outputs, y, jnp.clip(mb_idx, 0, m - 1), axis=0)
        outputs = jnp.where(
            valid & (idx == size - 1) & (k == v - 1), updated, outputs)
        incoming = lax.ppermute(y, axis_name, right_perm)
        return (incoming, outputs), None

    from horovod_tpu.parallel._vma import pin_to, vma_of

    target = {axis_name} | vma_of(microbatches)
    for leaf in jax.tree_util.tree_leaves(stage_params):
        target |= vma_of(leaf)
    _pin = pin_to(target)

    init = (_pin(jnp.zeros(mb_shape, microbatches.dtype)),
            _pin(jnp.zeros((m,) + mb_shape, microbatches.dtype)))
    (_, outputs), _ = lax.scan(tick, init, jnp.arange(ticks))
    masked = jnp.where(idx == size - 1, outputs, jnp.zeros_like(outputs))
    return lax.psum(masked, axis_name)


def pipeline_1f1b(stage_fn: Callable, loss_fn: Callable, stage_params, aux,
                  microbatches, targets, axis_name: str = "pipe"):
    """One-forward-one-backward (1F1B) pipeline schedule, hand-scheduled.

    GPipe here (:func:`pipeline_apply` + ``jax.grad``) runs all M forwards
    then all M backwards, so AD keeps **M microbatches of residuals** live
    per stage.  This schedule interleaves: each scan tick does one forward
    sub-step and one backward sub-step, saving only stage *inputs* in a
    ``2P``-slot ring buffer and rematerializing the stage forward inside
    the backward's VJP.  Peak activation state drops from O(M) to O(P)
    microbatches — the reason to pick 1F1B when M >> P (long-context
    training).  On a lockstep SPMD mesh the *bubble* is NOT smaller than
    GPipe's: every device executes both sub-steps every tick (masked when
    its wavefront hasn't arrived), and the schedule runs M + 2(P-1) ticks
    vs GPipe's 2(M+P-1) half-ticks; 1F1B's classic latency win assumes an
    async runtime (e.g. the per-device command queues of PipeDream /
    Megatron), which a single fused XLA program does not have.  See
    docs/parallelism.md for the measured comparison.

    Gradients are EXACT (same oracle as GPipe — tests/test_parallel.py).

    Args:
      stage_fn: ``(stage_params, x) -> y`` with ``y.shape == x.shape``.
      loss_fn: ``(y, target_mb, aux) -> scalar`` — applied at the LAST
        stage (e.g. final layernorm + logits head + xent, with ``aux``
        holding those replicated params).
      stage_params: this device's stage slice (leaves [1, ...]).
      aux: replicated pytree consumed by ``loss_fn``.
      microbatches: ``[M, mb...]`` (replicated over the pipe axis).
      targets: ``[M, ...]`` per-microbatch targets.

    Returns ``(loss, stage_grads, aux_grads, d_microbatches)``: the mean
    microbatch loss and exact gradients w.r.t. stage_params / aux /
    microbatches (use :func:`make_pipeline_1f1b_loss` to compose with
    outer AD for embedding parameters).
    """
    size = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    m = microbatches.shape[0]
    ticks = m + 2 * (size - 1)
    nbuf = 2 * size   # in-flight saved inputs <= 2(P-1)+1 < 2P

    right_perm, left_perm, _pin, init = _1f1b_setup(
        axis_name, size, stage_params, aux, microbatches, targets, nbuf)

    def tick(carry, t):
        fwd_in, bwd_in, buf, g_stage, g_aux, d_mb, loss_acc = carry

        # -- forward sub-step: stage p runs microbatch mf = t - p.
        mf = t - idx
        fwd_valid = (mf >= 0) & (mf < m)
        feed = lax.dynamic_index_in_dim(
            microbatches, jnp.clip(t, 0, m - 1), axis=0, keepdims=False)
        x = jnp.where(idx == 0, feed, fwd_in)
        y = stage_fn(stage_params, x)
        slot = jnp.maximum(mf, 0) % nbuf
        buf = jnp.where(
            fwd_valid,
            lax.dynamic_update_index_in_dim(buf, x, slot, axis=0), buf)
        fwd_out = lax.ppermute(y, axis_name, right_perm)

        # -- backward sub-step: stage p runs microbatch
        # mbk = t - 2(P-1) + p (at the last stage mbk == mf: it backwards
        # the microbatch it just forwarded, seeding from the loss).
        mbk = t - 2 * (size - 1) + idx
        bwd_valid = (mbk >= 0) & (mbk < m)
        x_saved = lax.dynamic_index_in_dim(
            buf, jnp.maximum(mbk, 0) % nbuf, axis=0, keepdims=False)
        tgt = lax.dynamic_index_in_dim(
            targets, jnp.clip(mbk, 0, m - 1), axis=0, keepdims=False)
        # Remat: recompute this stage's forward to get the pullback
        # (saving inputs, not residuals, is what makes the buffer small).
        y2, pull = jax.vjp(stage_fn, stage_params, x_saved)
        loss_val, (dy_loss, daux) = jax.value_and_grad(
            loss_fn, argnums=(0, 2))(y2, tgt, aux)
        dy = jnp.where(idx == size - 1, dy_loss, bwd_in)
        dparams, dx = pull(dy)

        def _acc(acc, g, valid):
            return jax.tree_util.tree_map(
                lambda a, b: a + jnp.where(valid, b, jnp.zeros_like(b)),
                acc, g)

        g_stage = _acc(g_stage, dparams, bwd_valid)
        g_aux = _acc(g_aux, daux, bwd_valid & (idx == size - 1))
        d_mb = jnp.where(
            bwd_valid & (idx == 0),
            lax.dynamic_update_index_in_dim(
                d_mb, dx.astype(d_mb.dtype), jnp.clip(mbk, 0, m - 1),
                axis=0),
            d_mb)
        loss_acc = loss_acc + jnp.where(
            bwd_valid & (idx == size - 1), loss_val, 0.0)
        bwd_out = lax.ppermute(dx, axis_name, left_perm)
        return (fwd_out, bwd_out, buf, g_stage, g_aux, d_mb,
                loss_acc), None

    (_, _, _, g_stage, g_aux, d_mb, loss_acc), _ = lax.scan(
        tick, init, jnp.arange(ticks))
    return _1f1b_finalize(axis_name, m, microbatches, g_stage, g_aux,
                          d_mb, loss_acc)


def _1f1b_setup(axis_name, size, stage_params, aux, microbatches,
                targets, nbuf):
    """Shared 1F1B scaffolding: ring permutations, the vma pin for the
    scan carry, and the 7-element init carry (fwd_in, bwd_in, buf,
    g_stage, g_aux, d_mb, loss_acc)."""
    m = microbatches.shape[0]
    mb_shape = microbatches.shape[1:]
    right_perm = [(i, (i + 1) % size) for i in range(size)]
    left_perm = [(i, (i - 1) % size) for i in range(size)]

    from horovod_tpu.parallel._vma import pin_to, vma_of

    target_vma = {axis_name} | vma_of(microbatches) | vma_of(targets)
    for leaf in jax.tree_util.tree_leaves((stage_params, aux)):
        target_vma |= vma_of(leaf)
    _pin = pin_to(target_vma)
    zeros_like_pinned = lambda t: jax.tree_util.tree_map(
        lambda l: _pin(jnp.zeros(l.shape, l.dtype)), t)
    init = (
        _pin(jnp.zeros(mb_shape, microbatches.dtype)),        # fwd_in
        _pin(jnp.zeros(mb_shape, microbatches.dtype)),        # bwd_in
        _pin(jnp.zeros((nbuf,) + mb_shape, microbatches.dtype)),
        zeros_like_pinned(stage_params),
        zeros_like_pinned(aux),
        _pin(jnp.zeros((m,) + mb_shape, jnp.float32)),        # d_mb
        _pin(jnp.zeros((), jnp.float32)),
    )
    return right_perm, left_perm, _pin, init


def _1f1b_finalize(axis_name, m, microbatches, g_stage, g_aux, d_mb,
                   loss_acc):
    """Shared 1F1B epilogue: mean over microbatches; loss/aux/d_mb live
    on single stages — psum broadcasts them SPMD-wide (stage grads stay
    local: each device owns its stage/chunk slice)."""
    inv_m = 1.0 / m
    scale = lambda t: jax.tree_util.tree_map(
        lambda l: (l * inv_m).astype(l.dtype), t)
    loss = lax.psum(loss_acc * inv_m, axis_name)
    g_aux = jax.tree_util.tree_map(
        lambda l: lax.psum(l * inv_m, axis_name), g_aux)
    d_mb = lax.psum(d_mb * inv_m, axis_name).astype(microbatches.dtype)
    return loss, scale(g_stage), g_aux, d_mb


def pipeline_1f1b_interleaved(stage_fn: Callable, loss_fn: Callable,
                              stage_params, aux, microbatches, targets,
                              axis_name: str = "pipe", virtual: int = 2):
    """Interleaved (virtual-stage) 1F1B — Megatron's full schedule as
    THREE lockstep scans over round-robin chunks.

    Device p holds chunks ``{k·P+p : k < v}`` (leaves [v, ...],
    :func:`horovod_tpu.models.transformer.stack_layer_params_interleaved`
    layout).  Work units per device: fwd unit ``uf`` at global fwd-time
    ``uf + p`` and bwd unit ``ub`` at global bwd-time ``ub + (P−1−p)``,
    with ``(chunk, microbatch) = ((u//P) mod v  [reversed for bwd],
    (u//(P·v))·P + u mod P)`` — consecutive stages land on consecutive
    times, so one ppermute-right chain carries activations and one
    ppermute-left chain carries cotangents (same invariant as
    :func:`pipeline_apply_interleaved`).

    The bubble win over the one-scan 1F1B needs PHASES (a uniform
    one-fwd-one-bwd tick pays full price for masked warmup sub-steps):

    * **warmup** — ``v·P`` fwd-only ticks of a 1/v-size chunk each
      (cost ``P·t_f`` total; exactly enough for microbatch 0 to clear
      all ``v·P`` stages),
    * **steady** — ``v·M − v·P + P − 1`` one-fwd-one-bwd ticks,
    * **drain** — ``v·P`` bwd-only ticks.

    Total = ``M(t_f+t_b) + (P−1)(t_f+t_b)/v`` EXACTLY (the warmup's
    ``P·t_f`` and drain's ``P·t_b`` cancel against the steady phase's
    deficit) — the full Megatron bubble ÷ v, while activation state
    stays O(P):
    a ``2vP``-slot ring buffer of saved chunk INPUTS (2× the plain
    1F1B buffer at v=2, still ≪ GPipe's O(M)).  Gradients are EXACT
    (chunk forwards recomputed in the backward from saved inputs, the
    same remat contract as :func:`pipeline_1f1b`).

    Requires ``M % P == 0`` and ``M >= P``.  Returns
    ``(loss, stage_grads [v, ...], aux_grads, d_microbatches)``.
    """
    size = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    m = microbatches.shape[0]
    v = virtual
    leads = {l.shape[0] for l in jax.tree_util.tree_leaves(stage_params)}
    if leads != {v}:
        raise ValueError(
            f"interleaved stage_params leaves must have leading dim "
            f"virtual={v}; got {sorted(leads)}")
    if m % size or m < size:
        raise ValueError(
            f"interleaved 1F1B needs n_microbatches ({m}) divisible by "
            f"and >= the pipe axis size ({size})")
    warmup = v * size                     # fwd-only ticks
    steady = v * m - v * size + size - 1  # 1f1b ticks
    drain = v * size                      # bwd-only ticks
    nbuf = 2 * v * size                   # max fwd->bwd slot gap

    right_perm, left_perm, _pin, init = _1f1b_setup(
        axis_name, size, stage_params, aux, microbatches, targets, nbuf)

    def chunk_of(params, k):
        return jax.tree_util.tree_map(
            lambda l: lax.dynamic_index_in_dim(l, k, axis=0,
                                               keepdims=True), params)

    def fwd_substep(carry, f_time):
        """One fwd chunk: uf = f_time − idx."""
        (fwd_in, bwd_in, buf, g_stage, g_aux, d_mb, loss_acc) = carry
        uf = jnp.maximum(f_time - idx, 0)
        k = (uf // size) % v
        mb_idx = (uf // (size * v)) * size + (uf % size)
        valid = (f_time - idx >= 0) & (uf < v * m)
        feed = lax.dynamic_index_in_dim(
            microbatches, jnp.clip(mb_idx, 0, m - 1), axis=0,
            keepdims=False)
        x = jnp.where((idx == 0) & (k == 0), feed, fwd_in)
        y = stage_fn(chunk_of(stage_params, k), x)
        # slot index is p-independent: P(v*(m//P)+k) + m%P  ==  uf
        buf = jnp.where(
            valid,
            lax.dynamic_update_index_in_dim(buf, x, uf % nbuf, axis=0),
            buf)
        fwd_out = lax.ppermute(y, axis_name, right_perm)
        return (fwd_out, bwd_in, buf, g_stage, g_aux, d_mb, loss_acc)

    def bwd_substep(carry, b_time):
        """One bwd chunk: ub = b_time − (P−1−idx)."""
        (fwd_in, bwd_in, buf, g_stage, g_aux, d_mb, loss_acc) = carry
        skew = size - 1 - idx
        ub = jnp.maximum(b_time - skew, 0)
        k_b = v - 1 - (ub // size) % v
        mb_idx = (ub // (size * v)) * size + (ub % size)
        valid = (b_time - skew >= 0) & (ub < v * m)
        # the consumed fwd unit shares the (m, k) coordinates: its slot
        # is P(v*(m//P)+k_b) + m%P
        slot = (size * (v * (mb_idx // size) + k_b) +
                (mb_idx % size)) % nbuf
        x_saved = lax.dynamic_index_in_dim(buf, slot, axis=0,
                                           keepdims=False)
        tgt = lax.dynamic_index_in_dim(
            targets, jnp.clip(mb_idx, 0, m - 1), axis=0, keepdims=False)
        params_k = chunk_of(stage_params, k_b)
        y2, pull = jax.vjp(stage_fn, params_k, x_saved)
        loss_val, (dy_loss, daux) = jax.value_and_grad(
            loss_fn, argnums=(0, 2))(y2, tgt, aux)
        last = (idx == size - 1) & (k_b == v - 1)
        dy = jnp.where(last, dy_loss, bwd_in)
        dparams, dx = pull(dy)

        def _acc_chunk(acc, g):
            # accumulate into chunk slot k_b (read-modify-write under
            # the validity mask)
            return jax.tree_util.tree_map(
                lambda a, b: jnp.where(
                    valid,
                    lax.dynamic_update_index_in_dim(
                        a,
                        lax.dynamic_index_in_dim(a, k_b, axis=0,
                                                 keepdims=True) + b,
                        k_b, axis=0),
                    a),
                acc, g)

        g_stage = _acc_chunk(g_stage, dparams)
        g_aux = jax.tree_util.tree_map(
            lambda a, b: a + jnp.where(valid & last, b,
                                       jnp.zeros_like(b)),
            g_aux, daux)
        d_mb = jnp.where(
            valid & (idx == 0) & (k_b == 0),
            lax.dynamic_update_index_in_dim(
                d_mb, dx.astype(d_mb.dtype), jnp.clip(mb_idx, 0, m - 1),
                axis=0),
            d_mb)
        loss_acc = loss_acc + jnp.where(valid & last, loss_val, 0.0)
        bwd_out = lax.ppermute(dx, axis_name, left_perm)
        return (fwd_in, bwd_out, buf, g_stage, g_aux, d_mb, loss_acc)

    # Phase A: warmup, fwd-only (fwd time 0..warmup-1).
    carry, _ = lax.scan(
        lambda c, t: (fwd_substep(c, t), None), init, jnp.arange(warmup))
    # Phase B: steady 1F1B (fwd time warmup+j, bwd time j).
    def steady_tick(c, j):
        c = fwd_substep(c, warmup + j)
        c = bwd_substep(c, j)
        return c, None
    carry, _ = lax.scan(steady_tick, carry, jnp.arange(steady))
    # Phase C: drain, bwd-only (bwd time steady..steady+drain-1).
    carry, _ = lax.scan(
        lambda c, t: (bwd_substep(c, t), None), carry,
        jnp.arange(steady, steady + drain))

    (_, _, _, g_stage, g_aux, d_mb, loss_acc) = carry
    return _1f1b_finalize(axis_name, m, microbatches, g_stage, g_aux,
                          d_mb, loss_acc)


def make_pipeline_1f1b_loss(stage_fn: Callable, loss_fn: Callable, mesh,
                            stage_spec, mb_spec, tgt_spec=None, aux_spec=None,
                            axis_name: str = "pipe", data_axes=(),
                            virtual: int = 1):
    """Differentiable scalar-loss wrapper around :func:`pipeline_1f1b`
    (or :func:`pipeline_1f1b_interleaved` when ``virtual > 1``).

    Returns ``f(stage_params, aux, microbatches, targets) -> loss``, a
    jit-level function whose ``jax.grad`` w.r.t. (stage_params, aux,
    microbatches) replays the 1F1B-computed exact gradients — so
    embedding layers upstream of the pipeline get their gradients through
    ordinary AD of ``d_microbatches``.

    The shard_map lives INSIDE the custom_vjp: outer AD never transposes
    the shard_map (the 1F1B schedule already computed every gradient), so
    the unmapped-output cotangent scaling of shard_map transposition
    cannot bite.  ``data_axes`` names mesh axes to gradient-average over
    (the Horovod DP allreduce, fused here as pmean).
    """
    from jax.sharding import PartitionSpec

    tgt_spec = tgt_spec if tgt_spec is not None else mb_spec
    aux_spec = aux_spec if aux_spec is not None else PartitionSpec()

    def body(stage_params, aux, microbatches, targets):
        if virtual > 1:
            loss, gs, ga, dmb = pipeline_1f1b_interleaved(
                stage_fn, loss_fn, stage_params, aux, microbatches,
                targets, axis_name, virtual)
        else:
            loss, gs, ga, dmb = pipeline_1f1b(
                stage_fn, loss_fn, stage_params, aux, microbatches,
                targets, axis_name)
        for ax in data_axes:
            loss = lax.pmean(loss, ax)
            gs = jax.tree_util.tree_map(lambda l: lax.pmean(l, ax), gs)
            ga = jax.tree_util.tree_map(lambda l: lax.pmean(l, ax), ga)
            # d_microbatches stays per-shard (each shard's embeddings),
            # but the global loss is the data-MEAN of per-shard losses —
            # scale the per-shard cotangent accordingly.
            dmb = dmb / lax.axis_size(ax)
        return loss, gs, ga, dmb

    def run(stage_params, aux, microbatches, targets):
        return jax.shard_map(
            body, mesh=mesh,
            in_specs=(stage_spec, aux_spec, mb_spec, tgt_spec),
            out_specs=(PartitionSpec(), stage_spec, aux_spec, mb_spec),
            check_vma=False)(stage_params, aux, microbatches, targets)

    @jax.custom_vjp
    def f(stage_params, aux, microbatches, targets):
        return run(stage_params, aux, microbatches, targets)[0]

    def f_fwd(stage_params, aux, microbatches, targets):
        loss, gs, ga, dmb = run(stage_params, aux, microbatches, targets)
        return loss, (gs, ga, dmb, targets)

    def f_bwd(res, ct):
        gs, ga, dmb, targets = res
        sc = lambda t: jax.tree_util.tree_map(lambda g: g * ct, t)
        # integer targets carry symbolic-zero cotangents (float0);
        # float targets get real zeros — d(loss)/d(targets) is NOT
        # computed by the 1F1B schedule (targets are training labels)
        dt = jax.tree_util.tree_map(
            lambda l: (jnp.zeros(l.shape, jax.dtypes.float0)
                       if not jnp.issubdtype(l.dtype, jnp.inexact)
                       else jnp.zeros_like(l)), targets)
        return (sc(gs), sc(ga), sc(dmb), dt)

    f.defvjp(f_fwd, f_bwd)
    return f

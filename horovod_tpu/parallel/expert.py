"""Expert parallelism: a Mixture-of-Experts layer over an 'expert' mesh axis.

Not in the reference — the v0.18 reference does not even have an alltoall
collective (SURVEY §2.5: ``message.h:47-49``).  TPU-native design: experts
shard over the expert axis (one or more per chip), tokens route to their
expert via ``lax.all_to_all`` over ICI, compute locally, and return the
same way — the standard Switch-Transformer dispatch expressed in pure SPMD.

Static shapes throughout (XLA requirement): routing uses fixed expert
capacity with drop-on-overflow, the standard TPU MoE trick.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax


def _slotify(pos, gate, capacity: int):
    """Queue positions [T, E] (-1 = not routed there) + per-token gate
    -> (dispatch [T, E, C] one-hot, combine = dispatch * gate); tokens
    whose position exceeds capacity are dropped.  Shared by both
    routers so capacity semantics cannot diverge."""
    in_cap = (pos >= 0) & (pos < capacity)
    dispatch = (jax.nn.one_hot(jnp.clip(pos, 0, capacity - 1), capacity) *
                in_cap[..., None]).astype(jnp.float32)        # [T, E, C]
    return dispatch, dispatch * gate[:, None, None]


def top1_routing(logits, capacity: int):
    """Switch-style top-1 routing with fixed capacity.

    logits: [T, E] router scores for T local tokens.
    Returns (dispatch [T, E, C] one-hot, combine [T, E, C] weights).
    Tokens beyond an expert's capacity are dropped (contribute zero).
    """
    t, e = logits.shape
    probs = jax.nn.softmax(logits, axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)                   # [T]
    gate = jnp.take_along_axis(probs, expert_idx[:, None],
                               axis=-1)[:, 0]                 # [T]
    onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.int32)   # [T, E]
    # Position of each token within its expert's queue.
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1             # [T, E]
    return _slotify(pos, gate, capacity)


def top2_routing(logits, capacity: int):
    """GShard-style top-2 routing with fixed capacity.

    logits: [T, E] router scores.  Each token goes to its best AND
    second-best expert; the two gates are renormalized to sum to 1
    (GShard eq. 4 — keeps the layer's output scale independent of how
    probability mass splits between the pair).  Capacity is assigned
    first-come-first-served with ALL first choices queued before any
    second choice at the same expert (the standard priority rule:
    dropping a token's backup hurts less than dropping its primary).
    Returns (dispatch [T, E, C], combine [T, E, C]); overflow drops.
    """
    t, e = logits.shape
    probs = jax.nn.softmax(logits, axis=-1)
    idx1 = jnp.argmax(probs, axis=-1)                          # [T]
    p1 = jnp.take_along_axis(probs, idx1[:, None], axis=-1)[:, 0]
    masked = probs * (1.0 - jax.nn.one_hot(idx1, e))
    idx2 = jnp.argmax(masked, axis=-1)
    p2 = jnp.take_along_axis(masked, idx2[:, None], axis=-1)[:, 0]
    denom = p1 + p2 + 1e-9
    g1, g2 = p1 / denom, p2 / denom

    oh1 = jax.nn.one_hot(idx1, e, dtype=jnp.int32)             # [T, E]
    oh2 = jax.nn.one_hot(idx2, e, dtype=jnp.int32)
    pos1 = jnp.cumsum(oh1, axis=0) * oh1 - 1                   # [T, E]
    # Second choices queue behind every first choice of that expert.
    count1 = oh1.sum(axis=0)                                   # [E]
    pos2 = (jnp.cumsum(oh2, axis=0) + count1[None, :]) * oh2 - 1

    d1, c1 = _slotify(pos1, g1, capacity)
    d2, c2 = _slotify(pos2, g2, capacity)
    # A token's two choices are distinct experts, so the slots never
    # collide and the sums stay one-hot per (token, choice).
    return d1 + d2, c1 + c2


def moe_layer(x, router_w, expert_fn: Callable, expert_params,
              axis_name: str = "expert", capacity_factor: float = 1.25,
              router: str = "top1"):
    """Apply a distributed MoE layer inside shard_map.

    x: [T_local, D] local tokens; router_w: [D, E_total];
    expert_params: this chip's expert parameters (leading dim =
    experts-per-chip, here fixed to 1 for clarity);
    expert_fn(params, tokens[C, D]) -> [C, D].

    Total experts = axis size.  ``router`` selects Switch top-1 or
    GShard top-2 (each token to its two best experts, renormalized
    gates — roughly doubles per-expert traffic at equal capacity
    factor, so top-2 users typically also raise ``capacity_factor``).
    Returns [T_local, D].
    """
    size = lax.axis_size(axis_name)
    t, d = x.shape
    e = size
    capacity = max(int(capacity_factor * t / e), 1)

    logits = x @ router_w                                     # [T, E]
    if router == "top1":
        dispatch, combine = top1_routing(logits, capacity)
    elif router == "top2":
        dispatch, combine = top2_routing(logits, capacity)
    else:
        raise ValueError(f"router={router!r}: expected 'top1' or 'top2'")

    # Gather this shard's tokens per expert: [E, C, D].
    buffers = jnp.einsum("td,tec->ecd", x, dispatch)
    # all_to_all: dim 0 (experts) scatters so each chip receives ITS
    # expert's buffer from every shard: [E_src=size, C, D] after exchange.
    received = lax.all_to_all(buffers, axis_name, split_axis=0,
                              concat_axis=0, tiled=True)
    # Run the local expert over all received tokens.
    flat = received.reshape(size * capacity, d)
    out = expert_fn(expert_params, flat).reshape(size, capacity, d)
    # Return results to their source shards.
    returned = lax.all_to_all(out, axis_name, split_axis=0, concat_axis=0,
                              tiled=True)                     # [E, C, D]
    # Un-dispatch: weight by gate and scatter back to token positions.
    return jnp.einsum("ecd,tec->td", returned, combine)


def load_balancing_loss(logits, axis_name: str = "expert"):
    """Switch-Transformer auxiliary loss: mean fraction routed per expert
    times mean router prob per expert, scaled by E (encourages balance)."""
    probs = jax.nn.softmax(logits, axis=-1)
    e = probs.shape[-1]
    hard = jax.nn.one_hot(jnp.argmax(probs, -1), e)
    frac = lax.pmean(hard.mean(0), axis_name)
    prob = lax.pmean(probs.mean(0), axis_name)
    return e * jnp.sum(frac * prob)


def moe_layer_ragged(x, router_w, expert_fn: Callable, expert_params,
                     axis_name: str = "expert",
                     capacity_factor: float = 1.25,
                     use_primitive=None):
    """Top-1 MoE layer whose dispatch is the RAGGED exchange
    (:func:`horovod_tpu.ops.collective.alltoall_ragged`) instead of the
    dense ``[T, E, C]`` one-hot einsum of :func:`moe_layer`.

    Same routing decision as ``moe_layer(router="top1")`` — argmax
    expert, softmax gate — but tokens travel as exactly the routed rows
    (sorted by destination, per-destination counts), so the dispatch
    memory is O(T·D) instead of the one-hot's O(T·E·C), and the wire
    moves only real tokens on TPU meshes (XLA ragged-all-to-all; an
    exact dense twin runs on CPU/virtual meshes).

    Capacity semantics differ from the dense layer at overflow: the
    expert's buffer (``size · capacity`` rows) is granted to SOURCE
    shards in rank order (lower ranks first), not per-source slices —
    when nothing overflows the two layers agree exactly (gated by
    ``test_moe_ragged_matches_dense``).  Dropped tokens contribute zero,
    like the dense layer.

    x: [T_local, D]; router_w: [D, E_total]; expert_params: this chip's
    expert parameters; expert_fn(params, tokens[N, D]) -> [N, D]
    (position-independent per row — it sees padded zero rows).
    ``use_primitive`` forwards to :func:`alltoall_ragged` (pass False
    under ``grad`` on a jax whose ragged primitive lacks a transpose
    rule — the dense twin differentiates everywhere).
    Returns [T_local, D].
    """
    from horovod_tpu.ops.collective import alltoall_ragged

    size = lax.axis_size(axis_name)
    me = lax.axis_index(axis_name)
    t, d = x.shape
    capacity = max(int(capacity_factor * t / size), 1)
    buf = size * capacity                   # the expert's static buffer

    logits = x @ router_w                                     # [T, E]
    probs = jax.nn.softmax(logits, axis=-1)
    dest = jnp.argmax(probs, axis=-1)                         # [T]
    gate = jnp.take_along_axis(probs, dest[:, None], axis=1)[:, 0]

    # Sort my tokens by destination (stable: ties keep token order, the
    # same FCFS the dense router's cumsum slots implement).
    order = jnp.argsort(dest)                                 # [T]
    splits = jnp.bincount(dest, length=size).astype(jnp.int32)
    x_sorted = x[order]

    out_buf, recv = alltoall_ragged(x_sorted, splits, buf,
                                    axis_name=axis_name,
                                    use_primitive=use_primitive)
    expert_out = expert_fn(expert_params, out_buf)            # [buf, D]

    # Return trip: rows go back grouped by source, counts clamped to
    # what actually landed (the capacity grant, in source-rank order).
    off_at_me = jnp.concatenate([jnp.zeros(1, jnp.int32),
                                 jnp.cumsum(recv)[:-1].astype(jnp.int32)])
    landed = jnp.clip(buf - off_at_me, 0, recv)               # [S]
    back, _ = alltoall_ragged(expert_out, landed, t,
                              axis_name=axis_name,
                              use_primitive=use_primitive)    # [T, D]

    # Which of MY sorted rows survived their expert's buffer?  My block
    # at expert j starts at sum_{k<me} M[k, j]; row i of the block
    # survives iff start + i < buf.  Returned rows arrive grouped by
    # expert in j order == my sorted order with dropped rows REMOVED,
    # so scatter them back to the surviving sorted slots.
    m = lax.all_gather(splits, axis_name, axis=0)             # [S, S]
    start = jnp.sum(m * (jnp.arange(size) < me)[:, None], axis=0)  # [S]
    in_off = jnp.concatenate([jnp.zeros(1, jnp.int32),
                              jnp.cumsum(splits)[:-1].astype(jnp.int32)])
    idx = jnp.arange(t)
    row_dest = dest[order]
    pos_in_block = idx - in_off[row_dest]
    survived = start[row_dest] + pos_in_block < buf
    # Position of each surviving sorted row within the returned stream.
    ret_pos = jnp.cumsum(survived.astype(jnp.int32)) - 1
    gathered = jnp.where(survived[:, None],
                         back[jnp.clip(ret_pos, 0, t - 1)], 0.0)
    # Back to token order, weighted by the gate.
    y = jnp.zeros((t, d), x.dtype).at[order].set(gathered)
    return y * gate[:, None].astype(x.dtype)

"""Expert parallelism: a Mixture-of-Experts layer over an 'expert' mesh axis.

Not in the reference — the v0.18 reference does not even have an alltoall
collective (SURVEY §2.5: ``message.h:47-49``).  TPU-native design: experts
shard over the expert axis (one or more per chip), tokens route to their
expert via ``lax.all_to_all`` over ICI, compute locally, and return the
same way — the standard Switch-Transformer dispatch expressed in pure SPMD.

Static shapes throughout (XLA requirement): routing uses fixed expert
capacity with drop-on-overflow, the standard TPU MoE trick.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax


def top1_routing(logits, capacity: int):
    """Switch-style top-1 routing with fixed capacity.

    logits: [T, E] router scores for T local tokens.
    Returns (dispatch [T, E, C] one-hot, combine [T, E, C] weights).
    Tokens beyond an expert's capacity are dropped (contribute zero).
    """
    t, e = logits.shape
    probs = jax.nn.softmax(logits, axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)                   # [T]
    gate = jnp.take_along_axis(probs, expert_idx[:, None],
                               axis=-1)[:, 0]                 # [T]
    onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.int32)   # [T, E]
    # Position of each token within its expert's queue.
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1             # [T, E]
    in_cap = (pos >= 0) & (pos < capacity)
    dispatch = (jax.nn.one_hot(jnp.clip(pos, 0, capacity - 1), capacity) *
                in_cap[..., None]).astype(jnp.float32)        # [T, E, C]
    combine = dispatch * gate[:, None, None]
    return dispatch, combine


def moe_layer(x, router_w, expert_fn: Callable, expert_params,
              axis_name: str = "expert", capacity_factor: float = 1.25):
    """Apply a distributed MoE layer inside shard_map.

    x: [T_local, D] local tokens; router_w: [D, E_total];
    expert_params: this chip's expert parameters (leading dim =
    experts-per-chip, here fixed to 1 for clarity);
    expert_fn(params, tokens[C, D]) -> [C, D].

    Total experts = axis size.  Returns [T_local, D].
    """
    size = lax.axis_size(axis_name)
    t, d = x.shape
    e = size
    capacity = max(int(capacity_factor * t / e), 1)

    logits = x @ router_w                                     # [T, E]
    dispatch, combine = top1_routing(logits, capacity)

    # Gather this shard's tokens per expert: [E, C, D].
    buffers = jnp.einsum("td,tec->ecd", x, dispatch)
    # all_to_all: dim 0 (experts) scatters so each chip receives ITS
    # expert's buffer from every shard: [E_src=size, C, D] after exchange.
    received = lax.all_to_all(buffers, axis_name, split_axis=0,
                              concat_axis=0, tiled=True)
    # Run the local expert over all received tokens.
    flat = received.reshape(size * capacity, d)
    out = expert_fn(expert_params, flat).reshape(size, capacity, d)
    # Return results to their source shards.
    returned = lax.all_to_all(out, axis_name, split_axis=0, concat_axis=0,
                              tiled=True)                     # [E, C, D]
    # Un-dispatch: weight by gate and scatter back to token positions.
    return jnp.einsum("ecd,tec->td", returned, combine)


def load_balancing_loss(logits, axis_name: str = "expert"):
    """Switch-Transformer auxiliary loss: mean fraction routed per expert
    times mean router prob per expert, scaled by E (encourages balance)."""
    probs = jax.nn.softmax(logits, axis=-1)
    e = probs.shape[-1]
    hard = jax.nn.one_hot(jnp.argmax(probs, -1), e)
    frac = lax.pmean(hard.mean(0), axis_name)
    prob = lax.pmean(probs.mean(0), axis_name)
    return e * jnp.sum(frac * prob)

"""Hierarchical (two-level) collectives: the ICI/DCN twin of Horovod's
LOCAL/CROSS communicator hierarchy.

Reference equivalent: ``NCCLHierarchicalAllreduce`` (intra-node
reduce-scatter -> cross-node allreduce -> intra-node allgather,
``nccl_operations.cc:151-346``) and ``MPIHierarchicalAllgather``
(``mpi_operations.cc:164-321``), built on the LOCAL/CROSS communicators of
``common.h:105-109``.

On TPU the hierarchy is two mesh axes: a fast intra-slice ICI axis and a
slow cross-slice DCN axis (built with
``mesh_utils.create_hybrid_device_mesh`` — see topology.build_mesh).  A
plain ``psum`` over both axes already lets XLA pick the schedule; the
explicit reduce-scatter/psum/all-gather decomposition below pins the
bandwidth-optimal pattern: each DCN link carries only 1/ici_size of the
payload.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def hierarchical_allreduce(x, ici_axis: str, dcn_axis: str,
                           average: bool = False):
    """reduce_scatter(ICI) -> psum(DCN) -> all_gather(ICI), flattened.

    Equivalent to ``psum(x, (ici_axis, dcn_axis))`` but with the cross-slice
    leg carrying 1/ici_size of the bytes (the reference's exact trick:
    nccl_operations.cc:151-346).
    """
    ici = lax.axis_size(ici_axis)
    flat = x.reshape(-1)
    n = flat.shape[0]
    pad = (-n) % ici
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    # Intra-slice reduce-scatter: each chip ends with 1/ici of the sum.
    shard = lax.psum_scatter(flat, ici_axis, scatter_dimension=0, tiled=True)
    # Cross-slice allreduce on the small shard (rides DCN).
    shard = lax.psum(shard, dcn_axis)
    # Intra-slice gather restores the full tensor.  Expressed as a masked
    # psum rather than lax.all_gather: the result is bitwise-replicated
    # over the ICI axis, and psum is the only collective whose output JAX's
    # vma inference marks *unvarying* — an all_gather output would be
    # "possibly varying over {ici}" and could not be returned through a
    # replicated out_spec (P()).  Cost note: if XLA does not fold the
    # one-hot into a gather, a ring lowering moves ~2(n-1)/n of the full
    # payload on ICI vs (n-1)/n for all_gather — an ICI-only overhead; the
    # DCN leg (the scarce link this decomposition optimizes) still carries
    # exactly 1/ici of the bytes.
    idx = lax.axis_index(ici_axis)
    buf = jnp.zeros((ici,) + shard.shape, shard.dtype).at[idx].set(shard)
    full = lax.psum(buf, ici_axis).reshape(-1)
    if pad:
        full = full[:n]
    out = full.reshape(x.shape)
    if average:
        out = out / (ici * lax.axis_size(dcn_axis))
    return out


def hierarchical_pytree_mean(tree, ici_axis: str, dcn_axis: str):
    """Gradient averaging over a 2-level mesh — the multi-slice form of
    :func:`horovod_tpu.ops.fusion.fused_pytree_mean`."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    sizes = [l.size for l in leaves]
    flat = jnp.concatenate([l.reshape(-1) for l in leaves]) if leaves else None
    if flat is None:
        return tree
    red = hierarchical_allreduce(flat, ici_axis, dcn_axis, average=True)
    out, off = [], 0
    for l, n in zip(leaves, sizes):
        out.append(red[off:off + n].reshape(l.shape))
        off += n
    return jax.tree_util.tree_unflatten(treedef, out)


def hierarchical_allgather(x, ici_axis: str, dcn_axis: str):
    """Two-level dim-0 allgather (reference ``MPIHierarchicalAllgather``,
    ``mpi_operations.cc:164-321``: node-local shared-memory gather + one
    cross-node allgather per node leader).

    Mesh form: gather over the fast ICI axis first, then over DCN.
    Concatenation order is (dcn, ici, local dim 0), matching a flat
    allgather over a mesh whose ICI axis is minor.

    Expressed as masked psums rather than ``lax.all_gather`` for the same
    reason as :func:`hierarchical_allreduce`'s gather leg: psum output is
    the one collective vma marks *unvarying*, so the result can flow out
    of a ``check_vma=True`` shard_map through a replicated ``P()`` spec.
    CAVEAT: the masked-psum form pays for that typing property with
    bandwidth — each gather leg reduces a zero-padded GLOBAL-size buffer,
    so every link carries O(global) bytes per level, NOT the
    each-byte-once traffic of the reference's leader scheme.  Semantics
    match; if XLA's psum-of-one-hot pattern matching does not rewrite it
    to a gather on your target, prefer ``lax.all_gather`` per level and
    handle the vma/replication annotation explicitly.
    """
    def gather(v, axis):
        n = lax.axis_size(axis)
        idx = lax.axis_index(axis)
        buf = jnp.zeros((n,) + v.shape, v.dtype).at[idx].set(v)
        out = lax.psum(buf, axis)
        return out.reshape((n * v.shape[0],) + v.shape[1:])

    return gather(gather(x, ici_axis), dcn_axis)

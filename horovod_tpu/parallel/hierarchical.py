"""Hierarchical (two-level) collectives: the ICI/DCN twin of Horovod's
LOCAL/CROSS communicator hierarchy.

Reference equivalent: ``NCCLHierarchicalAllreduce`` (intra-node
reduce-scatter -> cross-node allreduce -> intra-node allgather,
``nccl_operations.cc:151-346``) and ``MPIHierarchicalAllgather``
(``mpi_operations.cc:164-321``), built on the LOCAL/CROSS communicators of
``common.h:105-109``.

On TPU the hierarchy is two mesh axes: a fast intra-slice ICI axis and a
slow cross-slice DCN axis (built with
``mesh_utils.create_hybrid_device_mesh`` — see topology.build_mesh, which
derives the ``("dcn", "ici")`` shape from ``hvd.topology()`` when none is
given).  A plain ``psum`` over both axes already lets XLA pick the
schedule; the explicit reduce-scatter/psum/all-gather decomposition below
pins the bandwidth-optimal pattern: each DCN link carries only 1/ici_size
of the payload.

The gather legs use ``lax.all_gather`` (each-byte-once ring traffic, not
the O(global)-bytes-per-link masked psum this module used to carry) and
repair the vma annotation explicitly — see :func:`_gather_replicated`.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from horovod_tpu.parallel._vma import vma_of


def _gather_replicated(v, axis: str):
    """``lax.all_gather(v, axis)`` whose result is typed *replicated* over
    ``axis``, so it can flow out of a ``check_vma=True`` shard_map through
    a replicated ``P()`` out_spec.

    The bandwidth story: all_gather's ring moves each byte once
    ((n-1)/n of the output per link), while the masked-psum spelling —
    reduce a zero-padded full-size buffer — moves O(output) bytes per
    link per step unless XLA pattern-matches the one-hot away.  The typing
    story is the hard part: on vma-tracking JAX an all_gather output is
    "possibly varying over {axis}" even though every shard is bitwise
    identical.  We repair that with ``lax.pcast(..., to="unvarying")``
    where the primitive exists; if neither vma tracking nor pcast is
    present (jax 0.4.x, where check_vma is shimmed off) the raw all_gather
    is already fine; only when vma is tracked but unvarying-pcast is
    refused do we fall back to the masked psum, the one collective whose
    output vma inference marks unvarying.
    """
    n = lax.axis_size(axis)
    out = lax.all_gather(v, axis, axis=0, tiled=True)
    if axis not in vma_of(out):
        return out  # not varying (or vma untracked): already replicated
    try:
        return lax.pcast(out, (axis,), to="unvarying")
    except (TypeError, ValueError, NotImplementedError):
        pass
    # Fallback: masked psum — unvarying by construction, at ICI-bandwidth
    # cost (~2x the all_gather ring if XLA keeps the reduction).
    idx = lax.axis_index(axis)
    buf = jnp.zeros((n,) + v.shape, v.dtype).at[idx].set(v)
    return lax.psum(buf, axis).reshape((n * v.shape[0],) + v.shape[1:])


def _record(kind: str, nbytes: int, level: str) -> None:
    from horovod_tpu.ops.fusion import record_collective_bytes
    record_collective_bytes(kind, "none", nbytes, level=level)


def hierarchical_allreduce(x, ici_axis: str, dcn_axis: str,
                           average: bool = False):
    """reduce_scatter(ICI) -> psum(DCN) -> all_gather(ICI), flattened.

    Equivalent to ``psum(x, (ici_axis, dcn_axis))`` but with the cross-slice
    leg carrying 1/ici_size of the bytes (the reference's exact trick:
    nccl_operations.cc:151-346).

    ``average=True`` folds the two-level divide into one ``1/(ici*dcn)``
    multiply applied to the DCN-reduced *shard* — before the ICI gather —
    so the scaling touches 1/ici of the elements the reference's
    divide-after-allreduce would.
    """
    ici = lax.axis_size(ici_axis)
    dcn = lax.axis_size(dcn_axis)
    flat = x.reshape(-1)
    n = flat.shape[0]
    pad = (-n) % ici
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    esize = flat.dtype.itemsize
    # Intra-slice reduce-scatter: each chip ends with 1/ici of the sum.
    shard = lax.psum_scatter(flat, ici_axis, scatter_dimension=0, tiled=True)
    _record("hier_allreduce", flat.shape[0] * esize, "ici")
    # Cross-slice allreduce on the small shard (rides DCN).
    shard = lax.psum(shard, dcn_axis)
    _record("hier_allreduce", shard.size * esize, "dcn")
    if average:
        # Hoisted: one multiply on the 1/ici-size shard, covering both
        # levels.  Integer payloads fall back to the post-gather divide
        # (a 1/(ici*dcn) multiply would truncate to zero).
        if jnp.issubdtype(shard.dtype, jnp.inexact):
            shard = shard * (1.0 / (ici * dcn))
            average = False
    # Intra-slice gather restores the full tensor, replicated over ICI.
    full = _gather_replicated(shard, ici_axis).reshape(-1)
    if pad:
        full = full[:n]
    out = full.reshape(x.shape)
    if average:
        out = out / (ici * dcn)
    return out


def hierarchical_pytree_mean(tree, ici_axis: str, dcn_axis: str):
    """Gradient averaging over a 2-level mesh — the multi-slice form of
    :func:`horovod_tpu.ops.fusion.fused_pytree_mean`."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    sizes = [l.size for l in leaves]
    flat = jnp.concatenate([l.reshape(-1) for l in leaves]) if leaves else None
    if flat is None:
        return tree
    red = hierarchical_allreduce(flat, ici_axis, dcn_axis, average=True)
    out, off = [], 0
    for l, n in zip(leaves, sizes):
        out.append(red[off:off + n].reshape(l.shape))
        off += n
    return jax.tree_util.tree_unflatten(treedef, out)


def hierarchical_allgather(x, ici_axis: str, dcn_axis: str):
    """Two-level dim-0 allgather (reference ``MPIHierarchicalAllgather``,
    ``mpi_operations.cc:164-321``: node-local shared-memory gather + one
    cross-node allgather per node leader).

    Mesh form: gather over the fast ICI axis first, then over DCN.
    Concatenation order is (dcn, ici, local dim 0), matching a flat
    allgather over a mesh whose ICI axis is minor.

    Both legs are ``lax.all_gather`` rings (each byte crosses each link
    once) with the replication annotation handled by
    :func:`_gather_replicated` — the O(global)-bytes-per-link masked-psum
    caveat this function used to document is gone.
    """
    esize = x.dtype.itemsize
    local = _gather_replicated(x, ici_axis)
    _record("hier_allgather", local.size * esize, "ici")
    out = _gather_replicated(local, dcn_axis)
    _record("hier_allgather", out.size * esize, "dcn")
    return out

"""Sharded-update data parallelism (ZeRO stage 1) for the SPMD plane.

The classic Horovod recipe — and this framework's replicated
:func:`horovod_tpu.parallel.data.make_training_step` — allreduces every
gradient and then runs a fully **replicated** optimizer update on every
chip: update FLOPs and optimizer-state memory scale with 1, not 1/N.
ZeRO stage 1 (Rajbhandari et al., SC'20; automatic weight-update sharding
on TPUs, Xu et al. 2020) observes that a ring allreduce is already a
reduce-scatter followed by an all-gather, and slides the optimizer update
between the two phases:

1. **reduce-scatter** the fused gradient buckets — each rank keeps the
   mean of its 1/N slice (:func:`horovod_tpu.ops.fusion.fused_reduce_scatter`);
2. run the optimizer **only on this rank's slice** of the flat parameter /
   optimizer-state buckets — N-times less update compute, and the
   optimizer state (Adam's m/v, momentum) lives ONLY as the local shard:
   ~(2 + K)/N per-rank optimizer memory for a K-slot optimizer;
3. **all-gather** the resulting update slices back to full parameters
   (:func:`horovod_tpu.ops.fusion.fused_all_gather`).

Same total wire bytes as the allreduce it replaces; the training
trajectory is identical to the replicated path up to float reduction
order, because every element-wise optimizer commutes with the slicing.

The state layout is deliberately *global-array friendly*: each optimizer
state leaf that mirrors the parameters is ONE flat padded bucket vector
whose GLOBAL shape is the full bucket; sharding it ``P(axis)`` over the
data axis makes the local view exactly this rank's shard.  That means
``jax.device_put`` with :meth:`ShardedOptimizer.state_shardings` places
the 1/N shards, checkpoints can gather the global array transparently
(:func:`gather_full_state`), and the replicated path's checkpoints stay
interchangeable with the sharded path's (:func:`scatter_full_state`).

Restriction: the wrapped optax optimizer must be **element-wise** (SGD,
momentum, Adam/AdamW, RMSProp, Lion, ...).  Transforms that mix
information across elements of one tensor (e.g. per-layer norm clipping,
``optax.clip_by_global_norm``) would see only the local shard; compose
those *before* the sharded wrapper on the full gradients if needed.
"""

from __future__ import annotations

from typing import Any, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from horovod_tpu import telemetry
from horovod_tpu.ops import compression as compression_mod
from horovod_tpu.ops import fusion


@jax.tree_util.register_pytree_node_class
class ZeroShardedState:
    """Optimizer state over the flat bucket vectors (ZeRO-1 layout).

    ``inner`` is the wrapped optax optimizer's state with the *list of
    flat padded bucket vectors* playing the role of the params pytree.
    ``wire`` is the wire codec's error-feedback residual state
    (:class:`horovod_tpu.ops.compression.CodecState`, ``None`` for
    stateless codecs).  The bucketing plan, the params treedef, the
    wrapped optimizer and the codec ride along as static aux data so
    checkpointing can convert to/from the replicated per-leaf layout
    without out-of-band bookkeeping.
    """

    def __init__(self, inner: Any, plan: fusion.ReduceScatterPlan,
                 treedef, optimizer: optax.GradientTransformation,
                 wire: Any = None, codec: Any = None):
        self.inner = inner
        self.plan = plan
        self.treedef = treedef
        self.optimizer = optimizer
        self.wire = wire
        self.codec = codec

    def tree_flatten(self):
        return ((self.inner, self.wire),
                (self.plan, self.treedef, self.optimizer, self.codec))

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], aux[0], aux[1], aux[2],
                   wire=children[1], codec=aux[3])

    def __repr__(self):
        codec = getattr(self.codec, "name", None) or "none"
        return (f"ZeroShardedState(buckets={len(self.plan.buckets)}, "
                f"axis_size={self.plan.axis_size}, codec={codec})")


def is_zero_state(x) -> bool:
    return isinstance(x, ZeroShardedState)


def _map_param_subtrees(optimizer, f, state_inner):
    """Apply ``f`` to every whole params-shaped subtree inside an optax
    state (``is_leaf=always`` stops :func:`optax.tree_map_params`'s inner
    map at the subtree root, so ``f`` sees the list-of-buckets / the
    per-leaf tree in one piece)."""
    return optax.tree_map_params(optimizer, f, state_inner,
                                 is_leaf=lambda _: True)


class ShardedOptimizer:
    """ZeRO-1 wrapper around an element-wise optax optimizer.

    Follows the ``GradientTransformation`` calling convention —
    ``init(params) -> state`` and ``update(grads, state, params) ->
    (updates, state)`` — but ``update`` MUST run inside ``shard_map``
    with ``axis_name`` bound (it issues the reduce-scatter / all-gather
    pair), and ``params`` is required (the update slices this rank's
    parameter shard out of the replicated params).
    """

    def __init__(self, optimizer: optax.GradientTransformation,
                 axis_name: str = "data", *,
                 axis_size: Optional[int] = None,
                 threshold: Optional[int] = None,
                 mean: bool = True,
                 compression=None,
                 cross_axis_name: Optional[str] = None,
                 cross_compression=None):
        if not isinstance(axis_name, str):
            raise NotImplementedError(
                f"sharded_optimizer shards over ONE mesh axis; got "
                f"axis_name={axis_name!r}.  For dp x sp grids, shard over "
                f"the data axis and average the seq axis upstream.")
        self.inner = optimizer
        self.axis_name = axis_name
        self._axis_size = axis_size
        self.threshold = threshold
        self.mean = mean
        self.codec = compression_mod.resolve_codec(compression)
        # Hierarchical (two-level) mode: axis_name is the intra-slice ICI
        # axis; cross_axis_name the DCN axis.  Shards stay 1/ici per slice
        # (replicated over DCN) and only the reduce leg crosses hosts —
        # cross-host bytes drop to 1/ici of the flat scheme's.  The cross
        # codec is deliberately independent ("int8 on DCN, none on ICI")
        # and NEVER read from HOROVOD_COMPRESSION: quantizing the slow
        # link is an explicit choice.
        self.cross_axis_name = cross_axis_name
        self.cross_codec = (compression_mod.resolve_codec(
            cross_compression if cross_compression is not None else "none")
            if cross_axis_name is not None else None)

    # -- layout ------------------------------------------------------------
    def _resolve_axis_size(self) -> int:
        if self._axis_size is not None:
            return int(self._axis_size)
        from horovod_tpu import basics
        try:
            m = basics.mesh()
            self._axis_size = int(m.shape[self.axis_name])
        except Exception as e:
            raise ValueError(
                f"sharded_optimizer could not resolve the size of axis "
                f"{self.axis_name!r}: pass axis_size= (or mesh=) "
                f"explicitly, or hvd.init() first") from e
        return self._axis_size

    # -- GradientTransformation surface ------------------------------------
    def init(self, params) -> ZeroShardedState:
        """Build the sharded-layout state from (global, replicated) params.

        State leaves that mirror params come out as FULL flat padded
        bucket vectors — place them with :meth:`state_shardings` (or let
        the training step's ``shard_map`` in_specs shard them on entry)
        so each rank materializes only its 1/N shard.
        """
        leaves, treedef = jax.tree_util.tree_flatten(params)
        plan = fusion.make_reduce_scatter_plan(
            leaves, self._resolve_axis_size(), self.threshold,
            codec=self.codec)
        flats = plan.concat(leaves)
        return ZeroShardedState(self.inner.init(flats), plan, treedef,
                                self.inner,
                                wire=self.codec.init_state(plan),
                                codec=self.codec)

    def update(self, grads, state: ZeroShardedState, params=None):
        """The sharded update: reduce-scatter grads, step the optimizer on
        this rank's shard, all-gather the updates.  Returns the FULL
        updates pytree (feed to ``optax.apply_updates``) and the new
        sharded state."""
        if params is None:
            raise ValueError(
                "sharded_optimizer.update requires params: the update "
                "slices this rank's parameter shard out of them")
        plan = state.plan
        gleaves, gdef = jax.tree_util.tree_flatten(grads)
        if gdef != state.treedef:
            raise ValueError(
                f"gradient tree structure {gdef} does not match the "
                f"structure this state was initialized with "
                f"({state.treedef})")
        n = lax.axis_size(self.axis_name)
        if int(n) != plan.axis_size:
            raise ValueError(
                f"axis {self.axis_name!r} has size {n} here but the "
                f"optimizer state was sharded {plan.axis_size}-way — "
                f"re-init (or re-shard the checkpoint) for this mesh")
        self._record(plan)

        if self.cross_axis_name is not None:
            # Two-level: intra-slice RS (unscaled) -> per-shard DCN psum
            # (with the cross codec) -> one hoisted 1/(ici*dcn) multiply
            # on the shard.  The all-gather below stays intra-slice.
            grad_shards, wire = compression_mod.compressed_reduce_scatter(
                gleaves, self.axis_name, self.codec, plan=plan,
                state=state.wire, mean=False)
            dcn = lax.axis_size(self.cross_axis_name)
            grad_shards = [
                compression_mod.cross_level_psum(
                    s, self.cross_axis_name, self.cross_codec)
                for s in grad_shards]
            if self.mean:
                grad_shards = [
                    s * jnp.asarray(1.0 / (plan.axis_size * dcn), s.dtype)
                    for s in grad_shards]
        else:
            grad_shards, wire = compression_mod.compressed_reduce_scatter(
                gleaves, self.axis_name, self.codec, plan=plan,
                state=state.wire, mean=self.mean)
        idx = lax.axis_index(self.axis_name)
        param_shards = [plan.shard_slice(b, flat, idx)
                        for b, flat in enumerate(
                            plan.concat(jax.tree_util.tree_leaves(params)))]
        upd_shards, new_inner = self.inner.update(
            grad_shards, state.inner, param_shards)
        upd_leaves, wire = compression_mod.compressed_all_gather(
            upd_shards, plan, self.axis_name, self.codec, state=wire)
        updates = jax.tree_util.tree_unflatten(state.treedef, upd_leaves)
        return updates, ZeroShardedState(new_inner, plan, state.treedef,
                                         self.inner, wire=wire,
                                         codec=self.codec)

    def _record(self, plan: fusion.ReduceScatterPlan) -> None:
        if not telemetry.enabled():
            return
        telemetry.counter(
            "hvd_zero_updates_total",
            "Sharded (ZeRO-1) optimizer updates traced").inc()
        if self.cross_axis_name is not None:
            telemetry.counter(
                "hvd_zero_hier_updates_total",
                "ZeRO-1 updates using the two-level (ICI+DCN) reduce "
                "path").inc()
        telemetry.counter(
            "hvd_zero_buckets_total",
            "Flat buckets in sharded optimizer updates").inc(
            len(plan.buckets))
        hist = telemetry.histogram(
            "hvd_zero_shard_bytes",
            "Per-rank shard size of each sharded-update bucket",
            bounds=telemetry.DEFAULT_BYTE_BUCKETS)
        for b in range(len(plan.buckets)):
            hist.observe(float(plan.shard_size(b) *
                               plan.bucket_dtype(b).itemsize))

    # -- placement helpers -------------------------------------------------
    def state_specs(self, state: ZeroShardedState) -> ZeroShardedState:
        """PartitionSpec tree congruent to ``state``: flat bucket leaves
        sharded ``P(axis_name)`` on dim 0, scalar bookkeeping (step
        counts) replicated.  Usable directly as a ``shard_map``
        in/out_spec or mapped to ``NamedSharding`` for ``device_put``."""
        ax = self.axis_name
        specs = optax.tree_map_params(
            self.inner,
            lambda _leaf: P(ax),
            state.inner,
            transform_non_params=lambda _leaf: P())
        return ZeroShardedState(specs, state.plan, state.treedef,
                                self.inner,
                                wire=self.codec.state_specs(state.plan, ax),
                                codec=self.codec)

    def state_shardings(self, mesh, state: ZeroShardedState):
        """``NamedSharding`` tree for ``jax.device_put``-placing a freshly
        built (or checkpoint-restored) state as actual 1/N shards."""
        return jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s),
            self.state_specs(state),
            is_leaf=lambda x: isinstance(x, P))


def sharded_optimizer(optimizer: optax.GradientTransformation,
                      axis_name: str = "data", *,
                      axis_size: Optional[int] = None,
                      mesh=None,
                      threshold: Optional[int] = None,
                      mean: bool = True,
                      compression=None,
                      cross_axis_name: Optional[str] = None,
                      cross_compression=None) -> ShardedOptimizer:
    """Wrap an element-wise optax ``optimizer`` for ZeRO-1 sharded updates
    over ``axis_name`` (see the module docstring for the algorithm and
    restrictions).  ``axis_size`` (or ``mesh``) pins the shard count at
    init time; omitted, it is read from ``hvd.mesh()``.  ``compression``
    selects the wire codec applied per bucket inside the reduce-scatter /
    all-gather pair (:mod:`horovod_tpu.ops.compression`; default none,
    overridable via ``HOROVOD_COMPRESSION``).

    ``cross_axis_name`` enables the hierarchical mode on a two-level
    (``"dcn"``/``"ici"``) mesh: ``axis_name`` becomes the intra-slice ICI
    axis, state shards 1/ici-way per slice, and gradients cross hosts
    only as 1/ici-size shards through one DCN ``psum`` — optionally
    quantized by ``cross_compression`` (stateless: none/bf16/fp16/int8,
    see :func:`horovod_tpu.ops.compression.cross_level_psum`)."""
    if mesh is not None and axis_size is None:
        axis_size = int(mesh.shape[axis_name])
    return ShardedOptimizer(optimizer, axis_name, axis_size=axis_size,
                            threshold=threshold, mean=mean,
                            compression=compression,
                            cross_axis_name=cross_axis_name,
                            cross_compression=cross_compression)


# ---------------------------------------------------------------------------
# Checkpoint interchange: sharded layout <-> replicated per-leaf layout.
# ---------------------------------------------------------------------------

def gather_full_state(state: ZeroShardedState):
    """Convert a sharded-layout state into the equivalent REPLICATED optax
    state pytree — exactly what ``optimizer.init(params)`` would hold after
    the same training steps.  Checkpoints written in this layout are
    mesh-size-independent and interchangeable with the replicated path's.

    Reads the state leaves as GLOBAL arrays (a ``P(axis)``-sharded leaf's
    global shape is the full flat bucket), so on a fully-addressable mesh
    no explicit collective is needed.

    Wire-codec residual state (``state.wire``) is deliberately EXCLUDED:
    checkpoints stay byte-identical with and without compression, and a
    restore simply starts with zero residuals (error feedback loses at
    most one pending step of correction).  Elastic axis-size changes go
    through :func:`reshard_state`, which DOES carry the pending error.
    """
    plan, treedef = state.plan, state.treedef

    def expand(flats):
        leaves = plan.split(list(flats))
        return jax.tree_util.tree_unflatten(treedef, leaves)

    return _map_param_subtrees(state.optimizer, expand, state.inner)


def local_state_digest(state: ZeroShardedState) -> int:
    """Cheap deterministic digest of THIS process's optimizer-state
    bytes: crc32 chained over each inner leaf's addressable shards in
    device order.  The divergence sentinel (``horovod_tpu.resilience``)
    allreduces this per rank — under ZeRO-1 the state only exists as
    shards, and digesting the local bytes avoids gathering the full
    buckets just to hash them."""
    import zlib
    crc = 0
    for leaf in jax.tree_util.tree_leaves(state.inner):
        shards = getattr(leaf, "addressable_shards", None)
        if not shards:
            arr = np.ascontiguousarray(np.asarray(leaf))
            crc = zlib.crc32(arr.tobytes(), crc)
            continue
        for shard in sorted(shards,
                            key=lambda s: getattr(s.device, "id", 0)):
            arr = np.ascontiguousarray(np.asarray(shard.data))
            crc = zlib.crc32(arr.tobytes(), crc)
    return crc


def scatter_full_state(full_state, like: ZeroShardedState
                       ) -> ZeroShardedState:
    """Inverse of :func:`gather_full_state`: re-shard a replicated-layout
    optax state into ``like``'s flat-bucket layout (``like`` supplies the
    plan/treedef — typically the freshly ``init``-ed state the restore is
    about to replace).  The result's flat leaves are global full vectors;
    place them with :meth:`ShardedOptimizer.state_shardings` before
    training."""
    plan = like.plan

    def collapse(per_leaf_subtree):
        return plan.concat(jax.tree_util.tree_leaves(per_leaf_subtree))

    new_inner = _map_param_subtrees(like.optimizer, collapse, full_state)
    return ZeroShardedState(new_inner, plan, like.treedef, like.optimizer,
                            wire=like.wire, codec=like.codec)


def reshard_state(state: ZeroShardedState, like: ZeroShardedState
                  ) -> ZeroShardedState:
    """Re-bucket a sharded state for a DIFFERENT axis size: round-trip
    through the portable layout (``gather_full_state`` then
    ``scatter_full_state`` against ``like``'s plan).  This is the
    world-size-change path of an elastic warm restart — a state sharded
    for the old N becomes ``like``'s layout for the new N, bit-exactly
    (the element-wise moments are only re-arranged, never recomputed).
    ``like`` is the freshly ``init``-ed state on the new mesh; place the
    result with :meth:`ShardedOptimizer.state_shardings` before
    training.

    Wire-codec residual state rides along codec-aware: the pending
    error-feedback correction is re-bucketed for the new axis size
    (:meth:`horovod_tpu.ops.compression.BucketCodec.reshard_state`) so a
    shrink/grow does not silently drop the error a quantizing codec still
    owes the model."""
    if telemetry.enabled():
        telemetry.counter(
            "hvd_zero_reshards_total",
            "ZeRO-1 states re-bucketed for a different axis size").inc()
    out = scatter_full_state(gather_full_state(state), like=like)
    codec = like.codec if like.codec is not None else state.codec
    if codec is not None and codec.stateful and state.wire is not None:
        out = ZeroShardedState(
            out.inner, out.plan, out.treedef, out.optimizer,
            wire=codec.reshard_state(state.wire, state.plan, like.plan),
            codec=codec)
    return out

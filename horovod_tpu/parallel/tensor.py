"""Tensor (model) parallelism helpers for manual-SPMD (shard_map) code.

Not in the reference (SURVEY §2.5: Horovod is pure DP); provided because a
TPU framework's mesh makes TP nearly free: weights shard over a 'model'
axis, matmuls stay local, and one ``psum`` per parallel region rides ICI.

The two Megatron-style boundary operators map onto JAX's varying-manual-axes
(vma) calculus, which shard_map tracks when ``check_vma=True`` (the
default everywhere in this framework):
* "f" (identity forward, ``psum`` backward, on activations entering a TP
  region): JAX inserts this automatically — an invariant activation hitting
  a shard-varying weight is promoted varying, and the TRANSPOSE of that
  promotion is exactly the psum that merges branch gradients once.
  :func:`region_input` therefore only documents the boundary; adding an
  explicit backward psum would double-count (empirically: size x inflated
  dLoss/dx).
* "g" (sum forward, identity backward, on row-parallel outputs):
  ``lax.psum`` itself, whose vma-aware transpose is the identity.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def region_input(x, axis_name: str):
    """Marks the activation boundary of a tensor-parallel region.

    A no-op: under vma-tracked shard_map the invariant->varying promotion
    transpose performs Megatron's "f" backward all-reduce automatically.
    Kept as an explicit call site so TP regions are visible in model code
    (and as the hook where a check_vma=False fallback would psum).
    """
    del axis_name
    return x


def column_parallel(x, w_local, axis_name: str, bias_local=None):
    """Column-parallel matmul: weights split on the OUTPUT dim; result
    stays sharded (no communication forward).  Wrap the input with the
    region boundary so the backward reduces once."""
    y = region_input(x, axis_name) @ w_local
    if bias_local is not None:
        y = y + bias_local
    return y


def row_parallel(x_local, w_local, axis_name: str, bias=None):
    """Row-parallel matmul: weights split on the INPUT dim; partial results
    are summed across shards (``psum`` forward, identity backward)."""
    y = lax.psum(x_local @ w_local, axis_name)
    if bias is not None:
        y = y + bias
    return y


def shard_dim(shape, axis_size: int, dim: int):
    """Local shape for a weight sharded on ``dim`` over ``axis_size``."""
    if shape[dim] % axis_size != 0:
        raise ValueError(
            f"dim {dim} of {shape} not divisible by model-parallel size "
            f"{axis_size}")
    out = list(shape)
    out[dim] //= axis_size
    return tuple(out)

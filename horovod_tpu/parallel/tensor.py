"""Tensor (model) parallelism helpers for manual-SPMD (shard_map) code.

Not in the reference (SURVEY §2.5: Horovod is pure DP); provided because a
TPU framework's mesh makes TP nearly free: weights shard over a 'model'
axis, matmuls stay local, and one ``psum`` per parallel region rides ICI.

The two Megatron-style boundary operators map onto JAX's varying-manual-axes
(vma) calculus, which shard_map tracks when ``check_vma=True`` (the
default everywhere in this framework):
* "f" (identity forward, ``psum`` backward, on activations entering a TP
  region): JAX inserts this automatically — an invariant activation hitting
  a shard-varying weight is promoted varying, and the TRANSPOSE of that
  promotion is exactly the psum that merges branch gradients once.
  :func:`region_input` therefore only documents the boundary; adding an
  explicit backward psum would double-count (empirically: size x inflated
  dLoss/dx).
* "g" (sum forward, identity backward, on row-parallel outputs):
  ``lax.psum`` itself, whose vma-aware transpose is the identity.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def region_input(x, axis_name: str):
    """Marks the activation boundary of a tensor-parallel region.

    A no-op: under vma-tracked shard_map the invariant->varying promotion
    transpose performs Megatron's "f" backward all-reduce automatically.
    Kept as an explicit call site so TP regions are visible in model code
    (and as the hook where a check_vma=False fallback would psum).
    """
    del axis_name
    return x


def column_parallel(x, w_local, axis_name: str, bias_local=None):
    """Column-parallel matmul: weights split on the OUTPUT dim; result
    stays sharded (no communication forward).  Wrap the input with the
    region boundary so the backward reduces once."""
    y = region_input(x, axis_name) @ w_local
    if bias_local is not None:
        y = y + bias_local
    return y


def row_parallel(x_local, w_local, axis_name: str, bias=None):
    """Row-parallel matmul: weights split on the INPUT dim; partial results
    are summed across shards (``psum`` forward, identity backward)."""
    y = lax.psum(x_local @ w_local, axis_name)
    if bias is not None:
        y = y + bias
    return y


def shard_dim(shape, axis_size: int, dim: int):
    """Local shape for a weight sharded on ``dim`` over ``axis_size``."""
    if shape[dim] % axis_size != 0:
        raise ValueError(
            f"dim {dim} of {shape} not divisible by model-parallel size "
            f"{axis_size}")
    out = list(shape)
    out[dim] //= axis_size
    return tuple(out)


def clip_by_global_norm(max_norm: float, specs, mesh_axes=("model",)):
    """Sharding-aware global-norm gradient clipping (optax transform).

    ``optax.clip_by_global_norm`` inside a TP ``shard_map`` computes the
    norm of the LOCAL weight shards — a value that varies over the model
    axis, silently desynchronizing replicas (and tripping vma checks).
    This variant consults each leaf's ``PartitionSpec``: leaves sharded
    over any axis in ``mesh_axes`` contribute ``psum`` of their local
    square-sums (shards are disjoint), replicated leaves contribute once.
    The result is the true global norm, invariant over the mesh, so every
    shard scales identically.

    Use inside shard_map-jitted steps (the axes must be bound); pair with
    the same ``specs`` tree passed to the step's ``in_specs``.
    No reference equivalent (Horovod is DP-only; its torch binding defers
    clipping to the user after ``synchronize()``, reference
    ``test_torch.py:1266``).
    """
    import optax

    def spec_axes(spec):
        if spec is None:
            return ()
        out = []
        for entry in spec:
            if entry is None:
                continue
            for ax in (entry if isinstance(entry, tuple) else (entry,)):
                if ax in mesh_axes:
                    out.append(ax)
        return tuple(out)

    def init_fn(params):
        del params
        return optax.EmptyState()

    def update_fn(updates, state, params=None):
        del params
        leaves, treedef = jax.tree_util.tree_flatten(updates)
        spec_leaves = treedef.flatten_up_to(specs)
        # Accumulate local square-sums per axes-group, then ONE psum per
        # group (not one per leaf): a deep TP model has many sharded
        # leaves and per-leaf scalar collectives would dominate.
        by_axes = {}
        for g, spec in zip(leaves, spec_leaves):
            sq = jnp.sum(jnp.square(g.astype(jnp.float32)))
            axes = spec_axes(spec)
            by_axes[axes] = by_axes.get(axes, jnp.float32(0.0)) + sq
        total = jnp.float32(0.0)
        for axes, sq in by_axes.items():
            total = total + (lax.psum(sq, axes) if axes else sq)
        gnorm = jnp.sqrt(total)
        scale = jnp.minimum(1.0, max_norm / (gnorm + 1e-16)).astype(
            jnp.float32)
        clipped = jax.tree_util.tree_map(
            lambda g: (g.astype(jnp.float32) * scale).astype(g.dtype),
            updates)
        return clipped, state

    return optax.GradientTransformation(init_fn, update_fn)

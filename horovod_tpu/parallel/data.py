"""Data parallelism: the ``DistributedOptimizer`` / ``DistributedGradientTape``
surface and the SPMD training-step factory.

Horovod equivalents:
* TF ``_DistributedOptimizer`` wrapping ``compute_gradients`` with allreduce
  (reference ``horovod/tensorflow/__init__.py:230-320``).
* ``DistributedGradientTape`` (reference ``tensorflow/__init__.py:323-376``).
* torch ``_DistributedOptimizer`` with per-parameter backward hooks
  (reference ``horovod/torch/__init__.py:47-252``) — the torch twin lives in
  :mod:`horovod_tpu.torch`.
* ``broadcast_parameters`` / ``broadcast_optimizer_state``
  (reference ``torch/__init__.py:255-403``), ``broadcast_variables`` /
  ``BroadcastGlobalVariablesHook`` (``tensorflow/__init__.py:104-192``).

TPU-native redesign: in JAX the optimizer is a pure gradient transformation
(optax), so "wrap the optimizer" means composing a gradient-averaging
transform in front of it.  Inside ``shard_map`` the averaging is a fused
``pmean`` (bucketed, see :mod:`horovod_tpu.ops.fusion`); on concrete arrays it
is an eager runtime allreduce.  :func:`make_training_step` packages the whole
Horovod recipe — shard batch, replicate params, average grads, apply — as one
jitted SPMD step.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from horovod_tpu import basics
from horovod_tpu.topology import data_axis
from horovod_tpu.ops import collective
from horovod_tpu.ops import compression as compression_mod
from horovod_tpu.ops.compression import Compression

_warned_stateful_per_leaf = False


def _legacy_compression(compression):
    """Normalize a ``compression=`` kwarg for the PER-LEAF paths (eager
    allreduce, replicated fused pmean): legacy ``Compressor`` classes —
    including user subclasses — pass through; codec instances / name
    strings map to their legacy cast twins.  Stateful codecs (int8,
    powersgd) have no per-leaf form: they need the bucketed
    reduce-scatter wire, so they warn once and fall back to uncompressed
    — use ``make_training_step``/``DistributedOptimizer`` with the
    sharded update (or a stateful-codec training step) to engage them."""
    global _warned_stateful_per_leaf
    if (isinstance(compression, type)
            and issubclass(compression, compression_mod.Compressor)
            and compression is not compression_mod.NoneCompressor):
        return compression
    codec = compression_mod.resolve_codec(
        None if (isinstance(compression, type)
                 and issubclass(compression, compression_mod.NoneCompressor))
        else compression)
    legacy = compression_mod.as_legacy(codec)
    if legacy is None:
        if not _warned_stateful_per_leaf:
            _warned_stateful_per_leaf = True
            from horovod_tpu.utils.logging import get_logger
            get_logger(__name__).warning(
                "compression codec %r needs the bucketed reduce-scatter "
                "wire and does not apply to per-leaf allreduce; falling "
                "back to uncompressed here (use shard_optimizer=True / "
                "sharded_update=True, or make_training_step's stateful-"
                "codec path)", codec.name)
        return compression_mod.NoneCompressor
    return legacy


def _allreduce_tree(grads, axis_name: str, compression=Compression.none,
                    op=collective.Average):
    """Average a gradient pytree across workers — either plane."""
    compression = _legacy_compression(compression)
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    compressed = [compression.compress(l) for l in leaves]
    cleaves = [c[0] for c in compressed]
    ctxs = [c[1] for c in compressed]
    if collective._axis_bound(axis_name):
        if op is collective.Adasum:
            raise NotImplementedError(
                "op=Adasum is implemented on the eager plane only; see "
                "hvd.allreduce (ops/collective.py)")
        from horovod_tpu.ops.fusion import fused_psum
        reduced = fused_psum(cleaves, axis_name,
                             mean=op is collective.Average)
    elif cleaves and isinstance(cleaves[0], jax.core.Tracer):
        reduced = [collective._plain_jit_fallback(l, "DistributedOptimizer")
                   for l in cleaves]
    else:
        # Enqueue every leaf before waiting on any — restores the overlap
        # Horovod's background loop provides (grads stream to the runtime
        # while earlier ones are still reducing).
        handles = [
            collective.allreduce_async(l, op=op, name=f"DistributedGrad.{i}")
            for i, l in enumerate(cleaves)]
        reduced = [collective.synchronize(h) for h in handles]
    out = [compression.decompress(l, c) for l, c in zip(reduced, ctxs)]
    return jax.tree_util.tree_unflatten(treedef, out)


def distributed_gradients(compression=Compression.none,
                          axis_name: str = "data",
                          op=collective.Average) -> optax.GradientTransformation:
    """An optax transform that averages incoming gradients across the mesh
    axis (SPMD) or across processes (eager) — the TPU-native core of
    ``DistributedOptimizer``."""

    def init_fn(params):
        del params
        return optax.EmptyState()

    def update_fn(updates, state, params=None):
        del params
        return _allreduce_tree(updates, axis_name, compression, op), state

    return optax.GradientTransformation(init_fn, update_fn)


def DistributedOptimizer(optimizer: optax.GradientTransformation,
                         named_parameters=None,
                         compression=Compression.none,
                         backward_passes_per_step: int = 1,
                         op=collective.Average,
                         axis_name: str = "data",
                         sharded_update: bool = False,
                         mesh: Optional[Mesh] = None) -> optax.GradientTransformation:
    """Wrap an optax optimizer so gradients are averaged across all workers
    before the update — API parity with reference
    ``hvd.DistributedOptimizer`` (``tensorflow/__init__.py:230-320``,
    ``torch/__init__.py:47-252``).

    ``named_parameters`` and ``backward_passes_per_step`` are accepted for
    signature parity; gradient accumulation in JAX is expressed by the caller
    (e.g. ``optax.MultiSteps``) and is composed automatically when
    ``backward_passes_per_step > 1``.

    ``sharded_update=True`` returns the ZeRO-1 wrapper instead
    (:mod:`horovod_tpu.parallel.zero`): gradients are reduce-scattered, the
    optimizer steps only this rank's 1/N flat shard, and updates are
    all-gathered — same wire bytes, N-times less update compute and
    optimizer-state memory.  SPMD-plane only (``update`` must run inside
    ``shard_map``); pass ``mesh`` (or call under ``hvd.init``'s mesh) so the
    shard count is known at ``init``.
    """
    del named_parameters
    if sharded_update:
        from horovod_tpu.parallel import zero
        if backward_passes_per_step > 1:
            raise NotImplementedError(
                "sharded_update=True does not compose with "
                "backward_passes_per_step>1; accumulate with "
                "optax.MultiSteps around the loss instead")
        if op not in (collective.Average, collective.Sum):
            raise NotImplementedError(
                f"sharded_update=True supports op=Average or op=Sum, "
                f"got {op!r}")
        return zero.sharded_optimizer(optimizer, axis_name, mesh=mesh,
                                      mean=op is collective.Average,
                                      compression=compression)
    chain = optax.chain(
        distributed_gradients(compression=compression, axis_name=axis_name,
                              op=op),
        optimizer,
    )
    if backward_passes_per_step > 1:
        chain = optax.MultiSteps(chain, every_k_schedule=backward_passes_per_step)
    return chain


def DistributedGradientTape(grad_fn: Callable, *,
                            compression=Compression.none,
                            axis_name: str = "data",
                            op=collective.Average,
                            has_value: Optional[bool] = None) -> Callable:
    """Wrap a gradient function so its output pytree is averaged across
    workers — the JAX rendition of reference ``DistributedGradientTape``
    (``tensorflow/__init__.py:323-376``), where ``grad_fn`` is typically
    ``jax.grad(loss_fn)`` or ``jax.value_and_grad(loss_fn)``.

    ``has_value`` declares whether ``grad_fn`` returns ``(value, grads)``
    (``jax.value_and_grad``) or just ``grads`` (``jax.grad``).  When left
    unset it is inferred: a 2-tuple whose first element is a scalar array is
    treated as ``(value, grads)``.  Pass it explicitly for outputs where the
    inference is ambiguous (e.g. ``jax.grad(..., argnums=(0, 1))`` whose
    first gradient is itself a scalar).
    """

    def _looks_like_value(v) -> bool:
        try:
            return jnp.ndim(v) == 0
        except TypeError:
            return False

    @functools.wraps(grad_fn)
    def wrapped(*args, **kwargs):
        out = grad_fn(*args, **kwargs)
        is_pair = (has_value if has_value is not None
                   else isinstance(out, tuple) and len(out) == 2
                   and _looks_like_value(out[0]))
        if is_pair:
            value, grads = out
            return value, _allreduce_tree(grads, axis_name, compression, op)
        return _allreduce_tree(out, axis_name, compression, op)

    return wrapped


def broadcast_parameters(params, root_rank: int = 0):
    """Broadcast a parameter pytree from ``root_rank`` to all processes
    (reference ``torch/__init__.py:255-285``, ``broadcast_variables``
    ``tensorflow/__init__.py:104-125``).  Under SPMD, parameters are
    replicated arrays and stay consistent by construction; this is the
    checkpoint-restore / cold-start synchronization path (SURVEY §5.4)."""
    basics._check_initialized()
    leaves, treedef = jax.tree_util.tree_flatten(params)
    out = [collective.broadcast(l, root_rank=root_rank,
                                name=f"broadcast_parameters.{i}")
           for i, l in enumerate(leaves)]
    return jax.tree_util.tree_unflatten(treedef, out)


def broadcast_variables(variables, root_rank: int = 0):
    """TF-API-parity alias of :func:`broadcast_parameters`."""
    return broadcast_parameters(variables, root_rank=root_rank)


def broadcast_optimizer_state(opt_state, root_rank: int = 0):
    """Broadcast optimizer state from ``root_rank`` (reference
    ``torch/__init__.py:287-403``, which pickles non-tensor leaves — here the
    optax state is a pytree whose non-array leaves ride
    :func:`horovod_tpu.ops.collective.broadcast_object`)."""
    basics._check_initialized()
    leaves, treedef = jax.tree_util.tree_flatten(opt_state)
    out = []
    for i, l in enumerate(leaves):
        if isinstance(l, (jax.Array, np.ndarray)) or np.isscalar(l):
            arr = collective.broadcast(jnp.asarray(l), root_rank=root_rank,
                                       name=f"broadcast_opt_state.{i}")
            if np.isscalar(l) or (hasattr(l, "ndim") and l.ndim == 0):
                arr = arr.reshape(())
            out.append(arr)
        else:
            out.append(collective.broadcast_object(
                l, root_rank=root_rank, name=f"broadcast_opt_state.obj.{i}"))
    return jax.tree_util.tree_unflatten(treedef, out)


def make_training_step(loss_fn: Callable,
                       optimizer: optax.GradientTransformation,
                       mesh: Mesh,
                       axis_name: Optional[str] = None,
                       donate: bool = True,
                       compression=Compression.none,
                       shard_optimizer: bool = False):
    """Build the flagship SPMD training step.

    ``loss_fn(params, batch) -> scalar loss``.  The returned
    ``step(params, opt_state, batch) -> (params, opt_state, loss)`` is jitted
    over ``mesh`` with the batch sharded on the data axis and parameters
    replicated; gradients are averaged with fused ``pmean`` — the whole
    Horovod DP recipe (shard data / replicate model / allreduce grads /
    identical update) as one compiled program.

    ``shard_optimizer=True`` swaps the allreduce-then-replicated-update for
    the ZeRO-1 sharded update (:mod:`horovod_tpu.parallel.zero`):
    reduce-scatter gradients, step the optimizer on this rank's 1/N flat
    shard, all-gather the updates.  Same wire bytes, trajectory identical up
    to float reduction order, optimizer state sharded 1/N per device.  Use
    ``step.init(params)`` in both modes — in sharded mode it returns the
    flat-bucket state, placed 1/N per device on first ``step(...)`` call.
    """
    ax = axis_name or data_axis(mesh)
    if shard_optimizer:
        return _make_sharded_training_step(loss_fn, optimizer, mesh, ax,
                                           donate, compression)
    try:
        codec = compression_mod.resolve_codec(
            None if (isinstance(compression, type)
                     and issubclass(compression, compression_mod.NoneCompressor))
            else compression)
    except TypeError:
        codec = None   # custom legacy Compressor: per-leaf path below
    if codec is not None and codec.stateful:
        # int8 / powersgd need the bucketed reduce-scatter wire plus
        # rank-local residual state — a different step shape from the
        # stateless pmean chain below.
        return _make_compressed_training_step(loss_fn, optimizer, mesh, ax,
                                              donate, codec)
    dist_opt = optax.chain(
        distributed_gradients(compression=compression, axis_name=ax),
        optimizer)

    def _step(params, opt_state, batch):
        from horovod_tpu import resilience
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)

        def do_update():
            updates, new_opt_state = dist_opt.update(grads, opt_state,
                                                     params)
            return optax.apply_updates(params, updates), new_opt_state

        # loss is per-shard; the guard reports the global mean like the
        # reference's MetricAverageCallback (_keras/callbacks.py:46-72)
        # and, when HOROVOD_STEP_GUARD is set, keeps the old state on a
        # non-finite step (the mean loss comes back NaN as the signal).
        (new_params, new_opt_state), mean_loss = resilience.apply_step_guard(
            do_update, loss=loss, grads=grads,
            old_state=(params, opt_state), axes=(ax,))
        return new_params, new_opt_state, mean_loss

    replicated = P()
    sharded_batch = P(ax)
    smapped = jax.shard_map(
        _step, mesh=mesh,
        in_specs=(replicated, replicated, sharded_batch),
        out_specs=(replicated, replicated, replicated),
        check_vma=False)
    jitted = jax.jit(smapped, donate_argnums=(0, 1) if donate else ())

    # Expose the wrapped chain's init so callers don't have to rebuild the
    # distributed_gradients∘optimizer chain themselves:
    #   step = hvd.make_training_step(loss_fn, opt, mesh)
    #   opt_state = step.init(params)
    def step(params, opt_state, batch):
        return jitted(params, opt_state, batch)

    step.init = dist_opt.init
    step.jitted = jitted   # AOT access (.lower/.compile) when needed
    return step


def _make_sharded_training_step(loss_fn, optimizer, mesh, ax, donate,
                                compression):
    """The ZeRO-1 variant of :func:`make_training_step`.

    The opt-state in/out specs depend on the wrapped optimizer's state
    STRUCTURE, which is only known once a concrete state exists, so the
    ``shard_map`` is built lazily on the first call and cached (one build
    per state treedef — the treedef is fixed for a given optimizer)."""
    from horovod_tpu.parallel import zero
    zopt = zero.sharded_optimizer(optimizer, ax, mesh=mesh,
                                  compression=compression)

    def _step(params, opt_state, batch):
        from horovod_tpu import resilience
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)

        def do_update():
            updates, new_opt_state = zopt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), new_opt_state

        (new_params, new_opt_state), mean_loss = resilience.apply_step_guard(
            do_update, loss=loss, grads=grads,
            old_state=(params, opt_state), axes=(ax,))
        return new_params, new_opt_state, mean_loss

    cache = {}

    def _build(opt_state):
        opt_specs = zopt.state_specs(opt_state)
        smapped = jax.shard_map(
            _step, mesh=mesh,
            in_specs=(P(), opt_specs, P(ax)),
            out_specs=(P(), opt_specs, P()),
            check_vma=False)
        return jax.jit(smapped, donate_argnums=(0, 1) if donate else ())

    def step(params, opt_state, batch):
        if step.jitted is None:
            step.jitted = cache["fn"] = _build(opt_state)
        from horovod_tpu import faults
        if faults.drop_residual():
            opt_state = _drop_residuals(opt_state)
        return step.jitted(params, opt_state, batch)

    step.init = zopt.init
    step.optimizer = zopt            # the ShardedOptimizer (specs, gather)
    step.jitted = None               # built on first call (state-dependent)
    step.state_shardings = functools.partial(zopt.state_shardings, mesh)
    return step


def _drop_residuals(opt_state):
    """Zero every error-feedback residual inside an optimizer state —
    the payload of the ``residual_drop`` chaos fault.  Handles both the
    ZeRO wrapper state (``ZeroShardedState.wire``) and the bare
    ``(CodecState, inner)`` pair of the compressed replicated step."""
    from horovod_tpu.parallel import zero

    def is_leaf(x):
        return (isinstance(x, compression_mod.CodecState)
                or zero.is_zero_state(x))

    def fix(x):
        if isinstance(x, compression_mod.CodecState):
            return compression_mod.zero_residuals(x)
        if zero.is_zero_state(x) and x.wire is not None:
            return zero.ZeroShardedState(
                x.inner, x.plan, x.treedef, x.optimizer,
                wire=compression_mod.zero_residuals(x.wire), codec=x.codec)
        return x

    return jax.tree_util.tree_map(fix, opt_state, is_leaf=is_leaf)


def _make_compressed_training_step(loss_fn, optimizer, mesh, ax, donate,
                                   codec):
    """The stateful-codec (int8 / powersgd) variant of
    :func:`make_training_step` on the replicated-update path: gradients
    ride the bucketed compressed reduce-scatter/all-gather wire
    (:func:`horovod_tpu.ops.compression.compressed_allreduce`) and the
    rank-local error-feedback residuals live in the opt state as
    ``(CodecState, inner_optax_state)``.

    The bucket plan depends on the parameter treedef, so ``step.init``
    must run before the first ``step(...)`` call (it also builds the
    residual state); the ``shard_map`` specs depend on the plan and are
    built lazily like the ZeRO variant."""
    from horovod_tpu.ops import fusion
    holder = {}

    def init(params):
        leaves, _ = jax.tree_util.tree_flatten(params)
        plan = fusion.make_reduce_scatter_plan(
            leaves, int(mesh.shape[ax]), codec=codec)
        holder["plan"] = plan
        return codec.init_state(plan), optimizer.init(params)

    def _step(params, opt_state, batch):
        from horovod_tpu import resilience
        wire, inner = opt_state
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)

        def do_update():
            gleaves, gdef = jax.tree_util.tree_flatten(grads)
            reduced, new_wire = compression_mod.compressed_allreduce(
                gleaves, ax, codec, plan=holder["plan"], state=wire,
                mean=True)
            avg = jax.tree_util.tree_unflatten(gdef, list(reduced))
            updates, new_inner = optimizer.update(avg, inner, params)
            return (optax.apply_updates(params, updates),
                    (new_wire, new_inner))

        (new_params, new_opt_state), mean_loss = resilience.apply_step_guard(
            do_update, loss=loss, grads=grads,
            old_state=(params, opt_state), axes=(ax,))
        return new_params, new_opt_state, mean_loss

    def _build():
        opt_specs = (codec.state_specs(holder["plan"], ax), P())
        smapped = jax.shard_map(
            _step, mesh=mesh,
            in_specs=(P(), opt_specs, P(ax)),
            out_specs=(P(), opt_specs, P()),
            check_vma=False)
        return jax.jit(smapped, donate_argnums=(0, 1) if donate else ())

    def step(params, opt_state, batch):
        if step.jitted is None:
            if "plan" not in holder:
                raise RuntimeError(
                    "call step.init(params) before the first step: the "
                    "compressed wire's bucket plan is derived from the "
                    "parameter tree at init")
            step.jitted = _build()
        from horovod_tpu import faults
        if faults.drop_residual():
            opt_state = _drop_residuals(opt_state)
        return step.jitted(params, opt_state, batch)

    step.init = init
    step.codec = codec
    step.jitted = None               # built on first call (plan-dependent)
    return step


# ---------------------------------------------------------------------------
# Elastic world-size-change continuity (warm restart, layer 3)
# ---------------------------------------------------------------------------

ELASTIC_BATCH_POLICY_VAR = "HOROVOD_ELASTIC_BATCH_POLICY"
ELASTIC_BATCH_POLICIES = ("lr_scale", "accumulate")
_ELASTIC_PREV_SIZE_VAR = "HOROVOD_ELASTIC_PREV_SIZE"


def elastic_shard(num_items: int, global_step: int, world_size: int,
                  rank: int, seed: int = 0) -> np.ndarray:
    """Deterministic data-shard reassignment after a world-size change.

    Every rank computes the same seeded permutation of
    ``[0, num_items)`` from ``(global_step, world_size, seed)`` and
    takes the strided slice ``rank::world_size`` — no coordination
    needed; any two ranks derive the identical full assignment, so a
    shrink or grow re-partitions the remaining work without duplicating
    or dropping an example.  Re-deriving from the *recovered* committed
    step means a warm-restarted world picks up exactly where the old one
    left off."""
    if world_size < 1:
        raise ValueError(f"world_size={world_size} must be >= 1")
    if not 0 <= rank < world_size:
        raise ValueError(
            f"rank={rank} out of range for world_size={world_size}")
    mix = (int(global_step) * 1000003 + int(world_size) * 7919
           + int(seed)) % (2 ** 32)
    perm = np.random.RandomState(mix).permutation(int(num_items))
    return perm[rank::world_size]


def elastic_continuity(prev_size: int, new_size: int,
                       policy: Optional[str] = None):
    """Global-batch semantics across a world-size change.

    Returns ``(lr_scale, accum_steps)`` for the new world, per
    ``policy`` (default from ``HOROVOD_ELASTIC_BATCH_POLICY``, falling
    back to ``lr_scale``):

    * ``lr_scale`` — keep the per-rank batch; the global batch changes
      by ``new/prev``, so scale the learning rate linearly (the
      Goyal et al. 2017 rule): ``(new/prev, 1)``.
    * ``accumulate`` — preserve the global batch by accumulating
      ``ceil(prev/new)`` micro-steps per update (``optax.MultiSteps``);
      when ``prev`` is not a multiple of ``new`` the effective batch
      overshoots by ``new*accum/prev``, and the returned ``lr_scale``
      carries that residual so LR-per-example stays constant:
      ``(new*accum/prev, accum)``.
    """
    if prev_size < 1 or new_size < 1:
        raise ValueError(
            f"sizes must be >= 1 (prev={prev_size}, new={new_size})")
    if policy is None:
        import os
        policy = (os.environ.get(ELASTIC_BATCH_POLICY_VAR, "")
                  .strip().lower() or "lr_scale")
    if policy not in ELASTIC_BATCH_POLICIES:
        raise ValueError(
            f"{ELASTIC_BATCH_POLICY_VAR}={policy!r}: expected one of "
            f"{', '.join(ELASTIC_BATCH_POLICIES)}")
    if policy == "lr_scale" or new_size >= prev_size:
        return float(new_size) / float(prev_size), 1
    accum = -(-prev_size // new_size)  # ceil
    return float(new_size * accum) / float(prev_size), accum


def elastic_transition(new_size: Optional[int] = None,
                       policy: Optional[str] = None):
    """The launcher-facing wrapper: reads the previous attempt's world
    size (``HOROVOD_ELASTIC_PREV_SIZE``, injected by ``hvdrun`` on every
    elastic restart) and returns ``(prev_size, lr_scale, accum_steps)``.
    Identity — ``(new_size, 1.0, 1)`` — on a first launch or when the
    size did not change."""
    import os
    if new_size is None:
        new_size = basics.size()
    raw = os.environ.get(_ELASTIC_PREV_SIZE_VAR, "").strip()
    if not raw:
        return new_size, 1.0, 1
    try:
        prev = int(raw)
    except ValueError:
        raise ValueError(
            f"{_ELASTIC_PREV_SIZE_VAR}={raw!r} is not an integer")
    if prev < 1 or prev == new_size:
        return new_size, 1.0, 1
    lr_scale, accum = elastic_continuity(prev, new_size, policy)
    return prev, lr_scale, accum

"""Shared varying-manual-axes (vma) helpers for shard_map scan carries.

A scan carry's vma type must be stable across iterations: after one step
the online state varies over every axis the inputs vary over, so initial
zeros must be pcast up to the union of the inputs' vma sets.  Kept in one
place because the probe (jax.typeof) and the no-mesh fallback are
JAX-version-sensitive.
"""

from __future__ import annotations

import jax
from jax import lax


def vma_of(x) -> set:
    """The value's varying-manual-axes set ({} outside shard_map)."""
    try:
        return set(jax.typeof(x).vma)
    except AttributeError:   # outside shard_map / old tracer
        return set()


def pin_to(target: set):
    """Returns f(x) that pcasts ``x`` up to vary over ``target`` (no-op on
    axes it already varies over; tolerant of running without a mesh)."""
    def _pin(x):
        missing = tuple(sorted(target - vma_of(x)))
        if not missing:
            return x
        try:
            return lax.pcast(x, missing, to="varying")
        except ValueError:   # no surrounding mesh context (vma untracked)
            return x
    return _pin

"""Self-healing training loop: step guard, divergence sentinel, rollback.

PR 1's fault tolerance handles *loud* failures (crashes, hangs,
blacklisting, restart-from-disk).  This module is the defense-in-depth
layer for *silent* ones — NaN bursts, replica divergence / bit flips —
so a bad step costs one step, not a relaunch (the in-memory-snapshot
recovery idea of Gemini, SOSP'23, on top of CheckFreq's, FAST'21,
iteration-boundary checkpointing):

* **Step guard** (in-graph, wired into ``parallel/data.py``,
  ``models/transformer.py`` and the benchmark's step builder): every
  jitted step checks loss + grads for NaN/Inf with a global ``is_finite``
  psum.  Collectives may not sit inside a ``lax.cond`` branch under SPMD,
  so the "conditional skip" is realized as an unconditional update
  followed by a per-leaf ``jnp.where(ok, new, old)`` select — XLA fuses
  the select, and the optimizer update it may waste ran on garbage
  anyway.  A bad step returns the *old* state and a NaN mean loss (the
  host-visible signal).  Policy via ``HOROVOD_STEP_GUARD``:
  ``off | skip | rollback | abort``.

* **Last-known-good rollback** (:class:`LastKnownGood`,
  :class:`StepGuard`): a host-side, double-buffered snapshot of the last
  *validated* ``params/opt_state/step``.  The pull to host happens off
  the critical path (``copy_to_host_async`` first, staged into a standby
  buffer, committed only after the bytes validate finite), and
  :meth:`StepGuard.after_step` restores it in-process on a NaN burst —
  every rank coordinates on a global ok flag first, so they roll back
  together or not at all.

* **Divergence sentinel**: every ``HOROVOD_SENTINEL_INTERVAL`` steps,
  allreduce a cheap per-rank digest (chained crc32, exact in float64) of
  params and optimizer state (the local shard bytes under ZeRO-1) with
  ``Min`` and ``Max`` and compare min == max.  On mismatch, an allgather
  names the diverging rank(s) (minority digest vs the modal one), and
  policy ``rollback`` heals in-process by re-broadcasting state from the
  lowest healthy rank — a diverged rank's *own* snapshots are
  finite-but-wrong, so rollback alone cannot heal divergence.

* **Preemption protocol**: :func:`install_preemption_handler` turns
  SIGTERM into a request flag; :func:`maybe_save_and_exit` performs a
  coordinated checkpoint at the next step boundary and exits with
  :data:`PREEMPTION_RC` (75, ``EX_TEMPFAIL``), which the launcher treats
  as preemption — no blacklist, no backoff, immediate reschedule
  (``runner/launch.py`` / ``runner/run.py``).

* **Warm restart** (PR 5): every Nth :class:`LastKnownGood` commit is
  also spilled to a host-local file in ``HOROVOD_SPILL_DIR`` (a per-job
  scratch dir the launcher keeps stable across elastic restarts), in a
  CRC-framed, torn-write-tolerant format.  After an elastic restart,
  :func:`warm_restore` runs the recovery ladder: surviving ranks load
  their spill, elect the freshest committed step with an eager ``Max``
  allreduce (lowest rank holding it wins), re-broadcast that state to
  the new world — falling back to the disk checkpoint, then fresh init,
  only when no survivor holds a valid spill.  The spill stores the
  *portable* (replicated optax) optimizer layout, so a ZeRO-1 run
  re-shards for the new world size on the way in.  A heartbeat sender
  (:func:`start_heartbeat`, auto-started by ``hvd.init()`` when the
  launcher injected ``HOROVOD_HEALTH_RPC``) reports
  ``(global_step, last_progress_ts)`` so the launcher can tell *dead*
  from *hung* workers.

Env knobs: ``HOROVOD_STEP_GUARD`` (policy), ``HOROVOD_SENTINEL_INTERVAL``
(0 = off), ``HOROVOD_LKG_INTERVAL`` (snapshot every N validated steps,
default 1), ``HOROVOD_GUARD_NAN_BURST`` (consecutive bad steps before a
rollback fires, default 1), ``HOROVOD_SPILL_DIR`` /
``HOROVOD_SPILL_INTERVAL`` (warm-restart spill), ``HOROVOD_HEALTH_RPC``
/ ``HOROVOD_HEARTBEAT_INTERVAL`` (heartbeats).  Everything emits
``hvd_guard_*`` / ``hvd_rollback_*`` / ``hvd_sentinel_*`` /
``hvd_warm_restart_*`` / ``hvd_heartbeat_*`` telemetry
(``docs/metrics.md``) and is chaos-testable via the ``nan`` /
``corrupt`` / ``heartbeat_drop`` / ``spill_corrupt`` fault kinds
(``faults.py``).  See ``docs/fault_tolerance.md``.
"""

from __future__ import annotations

import functools
import os
import pickle
import signal
import struct
import sys
import threading
import zlib
from typing import Any, Dict, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from horovod_tpu import basics, faults, telemetry
from horovod_tpu.native.runtime import MembershipChangedError  # noqa: F401
from horovod_tpu.ops import collective as _c
from horovod_tpu.utils.logging import get_logger

log = get_logger(__name__)

# Distinct exit code for "preempted, please reschedule me" — 75 is BSD
# EX_TEMPFAIL ("temporary failure, user is invited to retry"), far from
# the launcher's operator-stop codes (130/143) and from any shell/signal
# encoding (128+N).
PREEMPTION_RC = 75

GUARD_POLICIES = ("off", "skip", "rollback", "abort")

_POLICY_VAR = "HOROVOD_STEP_GUARD"
_SENTINEL_VAR = "HOROVOD_SENTINEL_INTERVAL"
_LKG_VAR = "HOROVOD_LKG_INTERVAL"
_BURST_VAR = "HOROVOD_GUARD_NAN_BURST"
_SPILL_DIR_VAR = "HOROVOD_SPILL_DIR"
_SPILL_INTERVAL_VAR = "HOROVOD_SPILL_INTERVAL"
_HEALTH_RPC_VAR = "HOROVOD_HEALTH_RPC"
_HEARTBEAT_INTERVAL_VAR = "HOROVOD_HEARTBEAT_INTERVAL"


class GuardAbort(RuntimeError):
    """Raised by :meth:`StepGuard.after_step` under policy ``abort``."""


class DivergenceError(RuntimeError):
    """Raised by the sentinel when replicas diverge and the policy does
    not heal (anything but ``rollback``).  Carries ``.ranks``."""

    def __init__(self, message: str, ranks: Sequence[int]):
        super().__init__(message)
        self.ranks = tuple(ranks)


def guard_policy() -> str:
    """The step-guard policy from ``HOROVOD_STEP_GUARD`` (default
    ``off``).  Read at *trace* time by :func:`apply_step_guard` — set it
    before building the training step."""
    value = os.environ.get(_POLICY_VAR, "off").strip().lower() or "off"
    if value not in GUARD_POLICIES:
        raise ValueError(
            f"{_POLICY_VAR}={value!r}: expected one of "
            f"{', '.join(GUARD_POLICIES)}")
    return value


def _env_interval(var: str, default: int, minimum: int = 0) -> int:
    raw = os.environ.get(var, "")
    if not raw.strip():
        return default
    try:
        value = int(raw)
    except ValueError:
        raise ValueError(f"{var}={raw!r} is not an integer")
    if value < minimum:
        raise ValueError(f"{var}={value} must be >= {minimum}")
    return value


# ---------------------------------------------------------------------------
# In-graph step guard
# ---------------------------------------------------------------------------

def all_finite(axes, loss, *trees):
    """In-graph global finiteness flag: True iff ``loss`` and every
    inexact leaf of ``trees`` is finite on **every** shard of ``axes``.
    The local flag is an int32 min over leaves; the global agreement is
    ``psum(flag) == psum(1)`` (the product of the axis sizes), so all
    shards compute the same boolean."""
    flags = []
    for leaf in jax.tree_util.tree_leaves((loss,) + tuple(trees)):
        arr = jnp.asarray(leaf)
        if jnp.issubdtype(arr.dtype, jnp.inexact):
            flags.append(jnp.all(jnp.isfinite(arr)).astype(jnp.int32))
    local = (functools.reduce(jnp.minimum, flags) if flags
             else jnp.int32(1))
    axes = tuple(a for a in (axes or ()) if a)
    if not axes:
        return local == 1
    return lax.psum(local, axes) == lax.psum(jnp.int32(1), axes)


def apply_step_guard(do_update, *, loss, grads, old_state, axes=(),
                     agree_axes=None):
    """Wrap one optimizer update with the NaN/Inf step guard.

    ``do_update()`` (a closure over ``grads``) must return a new state
    pytree congruent with ``old_state``.  Returns ``(state, mean_loss)``
    where ``mean_loss = pmean(loss, axes)``.  Under policy ``off`` this
    is exactly ``(do_update(), pmean(loss))`` — zero overhead.  Under any
    other policy the update runs unconditionally and the guard selects
    per leaf between new and old state (collectives cannot live inside a
    ``lax.cond`` branch under SPMD — the select *is* the skip), and a bad
    step's mean loss is poisoned to NaN so the host can see it
    (:meth:`StepGuard.after_step` keys off exactly that).

    ``agree_axes`` (default: ``axes``) is where the finiteness verdict is
    psummed — pass *every* mesh axis the state is sharded over (e.g. the
    tensor-parallel model axis on top of the data axes), so all shards
    select the same branch.

    The policy is read at trace time: build the step *after* setting
    ``HOROVOD_STEP_GUARD``.
    """
    axes = tuple(a for a in (axes or ()) if a)
    agree_axes = (axes if agree_axes is None
                  else tuple(a for a in agree_axes if a))
    mean_loss = lax.pmean(loss, axes) if axes else loss
    policy = guard_policy()
    if policy == "off":
        return do_update(), mean_loss
    if telemetry.enabled():  # trace-time: counts guarded step *traces*
        telemetry.counter(
            "hvd_guard_traces_total",
            "training-step traces built with the step guard enabled",
            policy=policy).inc()
    ok = all_finite(agree_axes, loss, grads)
    new_state = do_update()
    guarded = jax.tree_util.tree_map(
        lambda new, old: jnp.where(ok, new, old), new_state, old_state)
    bad = jnp.asarray(jnp.nan, dtype=jnp.result_type(mean_loss))
    return guarded, jnp.where(ok, mean_loss, bad)


# ---------------------------------------------------------------------------
# Host-side helpers
# ---------------------------------------------------------------------------

def _host_finite(arr: np.ndarray) -> bool:
    """Finiteness of host bytes; ml_dtypes kinds (bf16 is 'V' to numpy)
    go through a float32 cast."""
    kind = getattr(arr.dtype, "kind", "")
    if kind in ("f", "c"):
        return bool(np.isfinite(arr).all())
    if kind == "V":  # bfloat16 & friends
        return bool(np.isfinite(np.asarray(arr, np.float32)).all())
    return True


def _pull_to_host(leaves):
    """Device->host for a list of leaves, overlapping the transfers:
    issue every async copy first, then materialize."""
    for leaf in leaves:
        copy_async = getattr(leaf, "copy_to_host_async", None)
        if copy_async is not None:
            copy_async()
    return [np.asarray(leaf) for leaf in leaves]


def _leaf_sharding(leaf):
    if isinstance(leaf, jax.Array):
        try:
            return leaf.sharding
        except Exception:  # pragma: no cover - deleted/donated buffers
            return None
    return None


def tree_digest(tree) -> int:
    """Cheap deterministic digest of a pytree: crc32 chained over the
    host bytes of every leaf in tree-flatten order.  crc32 < 2**32 is
    exactly representable in float64, so digests survive a float
    allreduce bit-exactly."""
    crc = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        arr = np.ascontiguousarray(np.asarray(leaf))
        crc = zlib.crc32(arr.tobytes(), crc)
    return crc


def _divergent_ranks(digests) -> list:
    """Name the diverging rank(s): rows of ``digests`` (one per rank)
    that differ from the modal row.  Ties break to the smallest row, so
    every rank computes the same answer from the same allgathered
    array."""
    rows = [tuple(np.asarray(row).ravel().tolist()) for row in digests]
    counts = {}
    for row in rows:
        counts[row] = counts.get(row, 0) + 1
    top = max(counts.values())
    modal = min(row for row, n in counts.items() if n == top)
    return [i for i, row in enumerate(rows) if row != modal]


class LastKnownGood:
    """Double-buffered host snapshot of the last validated training
    state.  :meth:`stage` pulls to the standby buffer and validates the
    bytes (nearly free — they are already on the host); :meth:`commit`
    flips it in only after the *global* verdict is in, so a poisoned or
    torn snapshot can never replace a good one.  Requires the state to
    be fully addressable from this process (true for this repo's
    per-process device meshes)."""

    def __init__(self):
        self._committed = None  # (step, treedef, host leaves, shardings)
        self._staged = None

    @property
    def available(self) -> bool:
        return self._committed is not None

    @property
    def step(self) -> Optional[int]:
        return self._committed[0] if self._committed else None

    def stage(self, params, opt_state, step: int) -> bool:
        """Pull ``(params, opt_state)`` into the standby buffer.  Returns
        False — and stages nothing — when the pulled bytes contain
        NaN/Inf (the live state is already poisoned)."""
        t0 = telemetry.clock()
        leaves, treedef = jax.tree_util.tree_flatten((params, opt_state))
        shardings = [_leaf_sharding(l) for l in leaves]
        host = _pull_to_host(leaves)
        ok = all(_host_finite(h) for h in host)
        if ok:
            self._staged = (int(step), treedef, host, shardings)
        else:
            self._staged = None
            if telemetry.enabled():
                telemetry.counter(
                    "hvd_rollback_snapshot_rejected_total",
                    "staged snapshots rejected for non-finite bytes").inc()
        if telemetry.enabled():
            telemetry.histogram(
                "hvd_rollback_snapshot_seconds",
                "host pull + validation time per staged snapshot",
            ).observe(telemetry.clock() - t0)
        return ok

    def commit(self) -> None:
        if self._staged is None:
            return
        self._committed, self._staged = self._staged, None
        if telemetry.enabled():
            telemetry.counter(
                "hvd_rollback_snapshots_total",
                "last-known-good snapshots committed").inc()

    def discard_stage(self) -> None:
        self._staged = None

    def restore(self) -> Tuple[Any, Any, int]:
        """Fresh device copies of the committed snapshot as
        ``(params, opt_state, step)``.  Explicit copies (``device_put``
        with the captured shardings) so the restored arrays never alias
        the host buffers — safe to feed straight back into a donating
        jitted step."""
        if self._committed is None:
            raise RuntimeError("no last-known-good snapshot available")
        step, treedef, host, shardings = self._committed
        leaves = [jax.device_put(h, s) if s is not None else jnp.array(h)
                  for h, s in zip(host, shardings)]
        params, opt_state = jax.tree_util.tree_unflatten(treedef, leaves)
        if telemetry.enabled():
            telemetry.counter(
                "hvd_rollback_restores_total",
                "in-process restores from last-known-good").inc()
        return params, opt_state, step


class GuardEvent(NamedTuple):
    """What :meth:`StepGuard.after_step` did.  ``action`` is one of
    ``ok | skip | rollback | heal``; ``step`` is the step the returned
    state corresponds to (the last-known-good step after a rollback)."""
    action: str
    step: int


class StepGuard:
    """Host-side coordinator for the in-graph guard: validates each
    step's outcome across ranks, maintains the last-known-good snapshot,
    runs the divergence sentinel, and decides skip/rollback/abort.

    Usage::

        guard = hvd.StepGuard()            # reads HOROVOD_STEP_GUARD etc.
        for step in range(n):
            params, opt_state, loss = train_step(params, opt_state, batch)
            params, opt_state, ev = guard.after_step(
                params, opt_state, step, loss)

    ``loss`` is the step's returned mean loss — NaN marks a guarded-bad
    step (see :func:`apply_step_guard`).  All ranks must call
    ``after_step`` for every step: the verdict is coordinated with an
    eager-plane ``Min`` allreduce of the local ok flag, so either every
    rank rolls back or none does (a NaN burst can hit one rank's shard
    only, but state must stay replicated)."""

    def __init__(self, policy: Optional[str] = None,
                 sentinel_interval: Optional[int] = None,
                 snapshot_interval: Optional[int] = None,
                 nan_burst: Optional[int] = None):
        self.policy = guard_policy() if policy is None else policy
        if self.policy not in GUARD_POLICIES:
            raise ValueError(
                f"policy {self.policy!r}: expected one of "
                f"{', '.join(GUARD_POLICIES)}")
        self.sentinel_interval = (
            _env_interval(_SENTINEL_VAR, 0)
            if sentinel_interval is None else int(sentinel_interval))
        self.snapshot_interval = (
            _env_interval(_LKG_VAR, 1, minimum=1)
            if snapshot_interval is None else max(1, int(snapshot_interval)))
        self.nan_burst = (
            _env_interval(_BURST_VAR, 1, minimum=1)
            if nan_burst is None else max(1, int(nan_burst)))
        self.lkg = LastKnownGood()
        self._bad_streak = 0
        self._warned_no_lkg = False
        # Warm-restart spill: every Nth commit is persisted host-locally
        # so a restarted world can recover the committed step from a
        # surviving peer instead of the (older) disk checkpoint.
        self._spill_dir = spill_dir()
        self.spill_interval = _env_interval(_SPILL_INTERVAL_VAR, 1,
                                            minimum=1)
        # Training loops may stash small host state here (RNG key, data
        # cursor) — it rides along in each spill and comes back from
        # warm_restore().
        self.spill_extra: Dict[str, Any] = {}
        self._commits = 0

    # -- coordination -----------------------------------------------------

    @staticmethod
    def _global_ok(local_ok: bool) -> bool:
        """Min-allreduce of the local verdict over the eager plane: the
        step is good only if it is good on *every* rank."""
        if basics.size() <= 1:
            return local_ok
        flag = np.array([1.0 if local_ok else 0.0], np.float32)
        out = _c._eager_allreduce(
            flag, _c.Min, "hvd.resilience.guard.ok", 1.0, 1.0)
        return bool(np.asarray(out)[0] >= 0.5)

    # -- sentinel ---------------------------------------------------------

    def _digests(self, params, opt_state) -> np.ndarray:
        opt_digest = None
        try:
            from horovod_tpu.parallel import zero
            if isinstance(opt_state, zero.ZeroShardedState):
                opt_digest = zero.local_state_digest(opt_state)
        except ImportError:  # pragma: no cover
            pass
        if opt_digest is None:
            opt_digest = tree_digest(opt_state)
        return np.array([float(tree_digest(params)), float(opt_digest)],
                        np.float64)

    def _sentinel(self, params, opt_state, step: int):
        """min/max digest agreement; on mismatch, name the diverging
        rank(s) and heal (policy ``rollback``) or raise."""
        if telemetry.enabled():
            telemetry.counter(
                "hvd_sentinel_checks_total",
                "divergence sentinel digest comparisons").inc()
        digest = self._digests(params, opt_state)
        lo = _c._eager_allreduce(
            digest, _c.Min, "hvd.resilience.sentinel.min", 1.0, 1.0)
        hi = _c._eager_allreduce(
            digest, _c.Max, "hvd.resilience.sentinel.max", 1.0, 1.0)
        if np.array_equal(np.asarray(lo), np.asarray(hi)):
            return params, opt_state, None
        gathered = _c._eager_allgather(
            digest.reshape(1, -1), "hvd.resilience.sentinel.digests")
        bad_ranks = _divergent_ranks(np.asarray(gathered))
        if telemetry.enabled():
            telemetry.counter(
                "hvd_sentinel_divergence_total",
                "sentinel checks that found diverged replicas").inc()
        message = (f"divergence sentinel at step {step}: replica digests "
                   f"disagree; diverging rank(s): {bad_ranks}")
        if self.policy != "rollback":
            log.error("%s", message)
            raise DivergenceError(message, bad_ranks)
        source = min(r for r in range(basics.size()) if r not in bad_ranks)
        log.error("%s — healing by re-broadcasting state from rank %d",
                  message, source)
        params, opt_state = _broadcast_state(params, opt_state, source)
        if telemetry.enabled():
            telemetry.counter(
                "hvd_sentinel_heals_total",
                "in-process divergence heals (state re-broadcast)").inc()
        return params, opt_state, GuardEvent("heal", step)

    # -- the step boundary -------------------------------------------------

    def after_step(self, params, opt_state, step: int, loss):
        """Validate one completed step.  Returns
        ``(params, opt_state, GuardEvent)`` — possibly the restored
        last-known-good state.  Must be called on every rank."""
        report_progress(step)  # feeds the heartbeat health plane
        if self.policy == "off" and self.sentinel_interval == 0:
            return params, opt_state, GuardEvent("ok", step)
        if telemetry.enabled():
            telemetry.counter(
                "hvd_guard_checks_total",
                "host-side step-boundary guard evaluations").inc()

        local_ok = bool(np.isfinite(np.asarray(loss, np.float64)).all())
        staged = False
        if (local_ok and self.policy == "rollback"
                and step % self.snapshot_interval == 0):
            staged = self.lkg.stage(params, opt_state, step)
            local_ok = staged  # a rejected pull means the state is bad
        ok = self._global_ok(local_ok)

        if ok:
            if staged:
                self.lkg.commit()
                if self._spill_dir:
                    self._commits += 1
                    if self._commits % self.spill_interval == 0:
                        self._spill(params, opt_state, step)
            self._bad_streak = 0
            if (self.sentinel_interval > 0 and step > 0
                    and step % self.sentinel_interval == 0
                    and basics.size() > 1):
                params, opt_state, event = self._sentinel(
                    params, opt_state, step)
                if event is not None:
                    return params, opt_state, event
            return params, opt_state, GuardEvent("ok", step)

        # Bad step (on at least one rank — all ranks agree it was bad).
        self.lkg.discard_stage()
        self._bad_streak += 1
        if telemetry.enabled():
            telemetry.counter(
                "hvd_guard_nonfinite_steps_total",
                "steps rejected by the guard (non-finite loss/grads)").inc()
        if self.policy == "abort":
            raise GuardAbort(
                f"step guard: non-finite loss/grads at step {step} "
                f"(policy abort)")
        if (self.policy == "rollback"
                and self._bad_streak >= self.nan_burst):
            if self.lkg.available:
                params, opt_state, good_step = self.lkg.restore()
                self._bad_streak = 0
                log.warning(
                    "step guard: non-finite step %d — rolled back to "
                    "last-known-good step %d", step, good_step)
                return params, opt_state, GuardEvent("rollback", good_step)
            if not self._warned_no_lkg:
                self._warned_no_lkg = True
                log.warning(
                    "step guard: rollback requested at step %d but no "
                    "last-known-good snapshot exists yet — skipping "
                    "instead", step)
        if telemetry.enabled():
            telemetry.counter(
                "hvd_guard_skipped_steps_total",
                "bad steps skipped (old state kept)").inc()
        log.warning("step guard: non-finite step %d skipped "
                    "(streak %d)", step, self._bad_streak)
        return params, opt_state, GuardEvent("skip", step)

    # -- warm-restart spill ------------------------------------------------

    def _spill(self, params, opt_state, step: int) -> None:
        """Persist the just-committed state host-locally.  Failures
        degrade (log + counter) — a broken scratch disk must not take
        down a healthy training loop."""
        try:
            write_spill(self._spill_dir, params, opt_state, step,
                        extra=self.spill_extra)
        except Exception as e:  # noqa: BLE001 — degrade, don't die
            log.warning("warm-restart spill at step %d FAILED (%s: %s); "
                        "continuing without it", step,
                        type(e).__name__, e)
            if telemetry.enabled():
                telemetry.counter(
                    "hvd_warm_restart_spill_failures_total",
                    "spill writes that raised (degraded, not fatal)").inc()


def _broadcast_state(params, opt_state, root_rank: int):
    """Re-broadcast ``(params, opt_state)`` from ``root_rank`` over the
    eager plane, re-placing each leaf with its original sharding —
    the divergence heal (a diverged rank's own snapshots are
    finite-but-wrong, so only a healthy rank's live state can heal
    it)."""
    leaves, treedef = jax.tree_util.tree_flatten((params, opt_state))
    out = []
    for i, leaf in enumerate(leaves):
        sharding = _leaf_sharding(leaf)
        host = np.ascontiguousarray(np.asarray(leaf))
        healed = _c._eager_broadcast(
            host, root_rank, f"hvd.resilience.heal.{i}")
        healed = np.asarray(healed, dtype=host.dtype)
        out.append(jax.device_put(healed, sharding)
                   if sharding is not None else jnp.array(healed))
    return jax.tree_util.tree_unflatten(treedef, out)


# ---------------------------------------------------------------------------
# Warm restart: host-local spill files + peer-recovery election
# ---------------------------------------------------------------------------

SPILL_MAGIC = b"HVDSPILL"
SPILL_VERSION = 1
# magic, version, step, world_size, rank, payload_len, payload_crc32
_SPILL_HEADER = struct.Struct("!8sIqIIQI")


def spill_dir() -> Optional[str]:
    """The per-job host-local scratch dir (``HOROVOD_SPILL_DIR``,
    injected by the launcher and stable across elastic restarts), or
    None when warm restart is not configured."""
    return os.environ.get(_SPILL_DIR_VAR, "").strip() or None


def _spill_path(directory: str, rank: int) -> str:
    return os.path.join(directory, f"rank{int(rank)}.spill")


def write_spill(directory: str, params, opt_state, step: int, *,
                extra: Optional[Dict[str, Any]] = None,
                rank: Optional[int] = None,
                world_size: Optional[int] = None) -> str:
    """Persist a committed training state to a host-local spill file.

    The optimizer state is converted to the *portable* (replicated
    optax) layout first — under ZeRO-1 each rank's shard alone could
    never reconstruct the full state after a peer died, and the portable
    layout is what lets :func:`warm_restore` re-shard for a different
    world size through ``gather_full_state``/``scatter_full_state``.

    Torn-write tolerance: bytes go to a temp file (flushed + fsynced)
    and land via ``os.replace``; the header frames the payload with its
    length and crc32 so :func:`read_spill` rejects anything short or
    mangled instead of loading garbage."""
    rank = basics.rank() if rank is None else int(rank)
    world_size = basics.size() if world_size is None else int(world_size)
    from horovod_tpu import checkpoint as _ckpt
    portable_opt = _ckpt._gather_zero(opt_state)
    t0 = telemetry.clock()
    # np.array(..., order="C") rather than ascontiguousarray: the latter
    # promotes 0-d leaves (optax's step count) to shape (1,), which would
    # poison the layout-signature agreement check on restore.
    payload = {
        "params": [np.array(np.asarray(l), order="C")
                   for l in jax.tree_util.tree_leaves(params)],
        "opt": [np.array(np.asarray(l), order="C")
                for l in jax.tree_util.tree_leaves(portable_opt)],
        "extra": dict(extra or {}),
    }
    os.makedirs(directory, exist_ok=True)
    blob = pickle.dumps(payload, protocol=pickle.HIGHEST_PROTOCOL)
    header = _SPILL_HEADER.pack(SPILL_MAGIC, SPILL_VERSION, int(step),
                                world_size, rank, len(blob),
                                zlib.crc32(blob))
    path = _spill_path(directory, rank)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(header)
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    faults.mangle_spill(path, rank)
    if telemetry.enabled():
        telemetry.counter(
            "hvd_warm_restart_spills_total",
            "warm-restart spill files written").inc()
        telemetry.histogram(
            "hvd_warm_restart_spill_seconds",
            "host serialization + fsync time per spill").observe(
            telemetry.clock() - t0)
    log.debug("spilled step %d (%d bytes) to %s", step, len(blob), path)
    return path


def read_spill(path: str) -> Optional[Dict[str, Any]]:
    """Load + validate one spill file.  Returns the record (``step`` /
    ``world_size`` / ``rank`` / ``params`` / ``opt`` / ``extra``) or
    None — a missing, torn, or corrupt file is rejected with a warning
    and a counter, never raised on: the recovery ladder just moves to
    the next rung."""

    def _reject(why: str) -> None:
        log.warning("rejecting spill %s: %s", path, why)
        if telemetry.enabled():
            telemetry.counter(
                "hvd_warm_restart_spill_rejected_total",
                "spill files rejected by validation (torn write / CRC / "
                "version mismatch)").inc()
        return None

    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError:
        return None
    if len(raw) < _SPILL_HEADER.size:
        return _reject(f"short header ({len(raw)} bytes)")
    magic, version, step, world, rank, plen, crc = \
        _SPILL_HEADER.unpack_from(raw)
    if magic != SPILL_MAGIC:
        return _reject("bad magic")
    if version != SPILL_VERSION:
        return _reject(f"unsupported version {version}")
    blob = raw[_SPILL_HEADER.size:]
    if len(blob) != plen:
        return _reject(f"torn payload ({len(blob)}/{plen} bytes)")
    if zlib.crc32(blob) != crc:
        return _reject("payload crc mismatch")
    try:
        payload = pickle.loads(blob)
    except Exception as e:  # noqa: BLE001 — reject-and-continue contract
        return _reject(f"unpicklable payload ({type(e).__name__}: {e})")
    return {"step": int(step), "world_size": int(world),
            "rank": int(rank), "path": path, **payload}


def best_local_spill(directory: str) -> Optional[Dict[str, Any]]:
    """The valid spill with the highest committed step on THIS host's
    scratch dir.  All ``*.spill`` files are scanned (not just this
    rank's): after a shrink the ranks renumber, and a host that ran two
    ranks may now run one — whichever surviving file is freshest
    wins."""
    try:
        entries = sorted(os.listdir(directory))
    except OSError:
        return None
    best = None
    for entry in entries:
        if not entry.endswith(".spill"):
            continue
        rec = read_spill(os.path.join(directory, entry))
        if rec is not None and (best is None or rec["step"] > best["step"]):
            best = rec
    return best


def _layout_signature(leaves) -> int:
    """crc32 over the (shape, dtype) of each leaf in order — cheap
    agreement check that a spilled state is congruent with the live
    template before any bytes go over the wire."""
    crc = 0
    for leaf in leaves:
        shape = tuple(np.shape(leaf))
        try:
            dtype = np.dtype(getattr(leaf, "dtype", None) or
                             np.result_type(leaf))
        except TypeError:
            dtype = np.dtype(object)
        crc = zlib.crc32(f"{shape}:{dtype.str};".encode(), crc)
    return crc


def _peer_recover(params, opt_state, local: Optional[Dict[str, Any]],
                  local_step: int, best: int):
    """Elect the spill source and re-broadcast its state to the world.

    Source = the LOWEST rank whose local spill holds the elected step
    ``best`` (eager ``Min`` allreduce over candidate ranks).  Before any
    state moves, the source's layout signature is broadcast and every
    rank checks it against its own live template — a globally
    coordinated ``Min`` verdict, so either everyone accepts the spill or
    everyone falls to the next ladder rung together.  Returns
    ``(params, opt_state, extra)`` or None on signature mismatch."""
    size, me = basics.size(), basics.rank()
    from horovod_tpu import checkpoint as _ckpt
    portable_opt = _ckpt._gather_zero(opt_state)
    p_leaves, p_def = jax.tree_util.tree_flatten(params)
    o_leaves, o_def = jax.tree_util.tree_flatten(portable_opt)
    template_sig = _layout_signature(p_leaves + o_leaves)

    if size > 1:
        cand = float(me) if (local is not None and local_step == best) \
            else float(size)
        src = int(np.asarray(_c._eager_allreduce(
            np.array([cand], np.float64), _c.Min,
            "hvd.resilience.warm.src", 1.0, 1.0))[0])
    else:
        src = 0
    i_am_src = me == src

    spill_sig = (_layout_signature(local["params"] + local["opt"])
                 if i_am_src else 0)
    sig = np.array([float(spill_sig)], np.float64)
    if size > 1:
        sig = _c._eager_broadcast(sig, src, "hvd.resilience.warm.sig")
    sig_ok = float(np.asarray(sig)[0]) == float(template_sig)
    if size > 1:
        sig_ok = StepGuard._global_ok(sig_ok)
    if not sig_ok:
        log.warning(
            "warm restart: spill at step %d (rank %d) does not match the "
            "live state layout — falling back down the recovery ladder",
            best, src)
        if telemetry.enabled():
            telemetry.counter(
                "hvd_warm_restart_layout_mismatch_total",
                "peer recoveries abandoned because the spilled layout "
                "disagreed with the live template").inc()
        return None

    spilled = (local["params"] + local["opt"]) if i_am_src else None
    out_leaves = []
    for i, leaf in enumerate(p_leaves + o_leaves):
        tmpl = np.asarray(leaf)
        host = (np.ascontiguousarray(np.asarray(spilled[i],
                                                dtype=tmpl.dtype))
                if i_am_src else np.ascontiguousarray(tmpl))
        if size > 1:
            host = _c._eager_broadcast(
                host, src, f"hvd.resilience.warm.state.{i}")
        got = np.asarray(host, dtype=tmpl.dtype).reshape(tmpl.shape)
        sharding = _leaf_sharding(leaf)
        out_leaves.append(jax.device_put(got, sharding)
                          if sharding is not None else jnp.asarray(got))
    n_p = len(p_leaves)
    new_params = jax.tree_util.tree_unflatten(p_def, out_leaves[:n_p])
    new_portable = jax.tree_util.tree_unflatten(o_def, out_leaves[n_p:])
    new_opt = _ckpt._scatter_zero(new_portable, opt_state)

    extra: Dict[str, Any] = dict(local["extra"]) if i_am_src else {}
    if size > 1:
        blob = pickle.dumps(extra, protocol=pickle.HIGHEST_PROTOCOL) \
            if i_am_src else b""
        ln = _c._eager_broadcast(np.array([len(blob)], np.int64), src,
                                 "hvd.resilience.warm.extra.len")
        n = int(np.asarray(ln)[0])
        if n:
            buf = (np.frombuffer(blob, np.uint8).copy() if i_am_src
                   else np.zeros(n, np.uint8))
            buf = _c._eager_broadcast(buf, src,
                                      "hvd.resilience.warm.extra")
            extra = pickle.loads(np.asarray(buf, np.uint8).tobytes())
        else:
            extra = {}
    return new_params, new_opt, extra


def warm_restore(params, opt_state, *, ckpt_dir: Optional[str] = None,
                 directory: Optional[str] = None):
    """The warm-restart recovery ladder, called on every rank of the new
    world right after (re)initializing the training state:

    1. **peer spill** — each rank loads its host's freshest valid spill;
       the highest committed step wins an eager ``Max`` allreduce
       election and the lowest rank holding it re-broadcasts that state;
    2. **disk checkpoint** — when no survivor holds a valid spill,
       restore the newest intact checkpoint under ``ckpt_dir`` (the
       repo-standard ``{"params", "opt_state", "step"}`` layout);
    3. **fresh init** — nothing to recover: train from the passed-in
       state.

    Returns ``(params, opt_state, step, source, extra)`` with ``source``
    in ``("spill", "disk", "fresh")``, ``step`` the recovered committed
    step (-1 for fresh), and ``extra`` the dict spilled via
    ``StepGuard.spill_extra`` (RNG key, data cursor; empty otherwise).
    ZeRO-1 optimizer states come back re-sharded for THIS world size —
    re-place them (``step.state_shardings`` / ``jax.device_put``) before
    training, exactly as after ``checkpoint.restore``."""
    directory = spill_dir() if directory is None else directory
    size = basics.size()
    local = best_local_spill(directory) if directory else None
    local_step = local["step"] if local is not None else -1

    if size > 1:
        best = int(np.asarray(_c._eager_allreduce(
            np.array([float(local_step)], np.float64), _c.Max,
            "hvd.resilience.warm.step", 1.0, 1.0))[0])
    else:
        best = local_step

    if best >= 0:
        recovered = _peer_recover(params, opt_state, local, local_step,
                                  best)
        if recovered is not None:
            new_params, new_opt, extra = recovered
            if telemetry.enabled():
                telemetry.counter(
                    "hvd_warm_restart_peer_recoveries_total",
                    "warm restarts recovered from a peer spill").inc()
            log.info("warm restart: recovered committed step %d from a "
                     "peer spill (no disk checkpoint read)", best)
            return new_params, new_opt, best, "spill", extra

    if ckpt_dir:
        from horovod_tpu import checkpoint
        found = np.zeros(1, np.int32)
        if basics.rank() == 0 and checkpoint.latest_step(ckpt_dir) \
                is not None:
            found[0] = 1
        if size > 1:
            found = _c._eager_broadcast(found, 0,
                                        "hvd.resilience.warm.disk")
        if int(np.asarray(found)[0]):
            template = {"params": params, "opt_state": opt_state,
                        "step": np.zeros((), np.int64)}
            state = checkpoint.restore(ckpt_dir, template)
            if telemetry.enabled():
                telemetry.counter(
                    "hvd_warm_restart_disk_fallbacks_total",
                    "warm restarts that fell back to the disk "
                    "checkpoint").inc()
            step = int(np.asarray(state["step"]))
            log.info("warm restart: no usable peer spill — restored "
                     "disk checkpoint step %d", step)
            return (state["params"], state["opt_state"], step, "disk",
                    {})

    if telemetry.enabled():
        telemetry.counter(
            "hvd_warm_restart_fresh_inits_total",
            "warm restarts with nothing to recover (fresh init)").inc()
    log.info("warm restart: nothing to recover — fresh init")
    return params, opt_state, -1, "fresh", {}


# ---------------------------------------------------------------------------
# Heartbeat sender (the worker half of the health plane)
# ---------------------------------------------------------------------------

_progress_lock = threading.Lock()
_progress_step = -1
_progress_ts = 0.0


def report_progress(step: int) -> None:
    """Record that training reached ``step`` (monotonic; older steps are
    ignored).  ``StepGuard.after_step`` calls this automatically; loops
    without a guard call it directly.  The heartbeat sender attaches the
    latest ``(step, ts)`` to every heartbeat so the launcher can tell a
    stalled step from a dead process."""
    global _progress_step, _progress_ts
    with _progress_lock:
        if step > _progress_step:
            _progress_step = int(step)
            _progress_ts = telemetry.clock()


def progress() -> Tuple[int, float]:
    with _progress_lock:
        return _progress_step, _progress_ts


class HeartbeatSender:
    """Daemon thread sending ``{"kind": "heartbeat", rank, step,
    progress_ts, epoch, seq}`` to the launcher's health plane every
    ``interval`` seconds over the authenticated RPC plane.  Single-shot
    dials with no retries and a short timeout — a slow or dead launcher
    must never stall training — and every failure is swallowed (counted,
    logged at debug).

    Two control-plane duties ride along (docs/control_plane.md):

    * Rank 0's successful sends are the coordinator lease renewals —
      counted as ``hvd_coord_lease_renewals_total`` and consumed by the
      launcher's ``_CoordinationPlane``.
    * The **partition fence**: a rank that cannot reach the launcher for
      ``HOROVOD_PARTITION_GRACE_SECONDS`` is the cut-off side of a
      partition (the launcher is a fixed point — its death kills local
      ranks anyway).  It exits with rc 75 (reschedule) rather than
      holding a stale gang hostage; 0 disables the fence.
    """

    def __init__(self, addr: str, port: int, key: bytes, rank: int,
                 interval: float):
        from horovod_tpu import config
        self.addr = addr
        self.port = int(port)
        self.key = key
        self.rank = int(rank)
        self.interval = max(0.05, float(interval))
        self.epoch = config.env_int("HOROVOD_COORD_EPOCH")
        # Membership epoch (fail-in-place): a fresh sender starts after
        # every reform_world() re-init, so reading the env once here is
        # enough for the launcher to tell old-world heartbeats (still
        # keyed by pre-reformation ranks) from reformed-world ones.
        self.world_epoch = config.env_int("HOROVOD_WORLD_EPOCH", 0) or 0
        self.partition_grace = config.env_float(
            "HOROVOD_PARTITION_GRACE_SECONDS")
        self._seq = 0
        self._last_ok: Optional[float] = None   # monotonic, None = never
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="hvd-heartbeat", daemon=True)

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _fence_check(self, now: float) -> None:
        """Self-fence (exit rc 75) after a full grace window with zero
        launcher contact.  Only armed once a first heartbeat landed —
        start-up misconfiguration belongs to the rendezvous timeout,
        not the fence."""
        if not self.partition_grace or self._last_ok is None:
            return
        if now - self._last_ok <= self.partition_grace:
            return
        msg = (f"rank {self.rank}: no launcher contact for "
               f"{now - self._last_ok:.0f}s (> partition grace "
               f"{self.partition_grace:g}s); self-fencing with rc "
               f"{PREEMPTION_RC}")
        log.error(msg)
        print(f"horovod_tpu: {msg}", file=sys.stderr, flush=True)
        if telemetry.enabled():
            telemetry.counter(
                "hvd_partition_fences_total",
                "Ranks that self-fenced after losing launcher contact "
                "past the partition grace").inc()
            telemetry.flush()
        os._exit(PREEMPTION_RC)

    def _run(self) -> None:
        import time as _time
        from horovod_tpu.runner import rpc
        while not self._stop.wait(self.interval):
            if faults.drop_heartbeat(self.rank):
                if telemetry.enabled():
                    telemetry.counter(
                        "hvd_heartbeat_dropped_total",
                        "heartbeats suppressed by fault injection").inc()
                continue
            step, ts = progress()
            self._seq += 1
            try:
                resp = rpc.rpc_call(
                    self.addr, self.port,
                    {"kind": "heartbeat", "rank": self.rank,
                     "step": step, "progress_ts": ts,
                     "epoch": self.epoch, "seq": self._seq,
                     "world_epoch": self.world_epoch},
                    self.key, timeout=max(1.0, self.interval),
                    retries=0)
                self._last_ok = _time.monotonic()
                if telemetry.enabled():
                    telemetry.counter(
                        "hvd_heartbeat_sent_total",
                        "heartbeats delivered to the launcher").inc()
                    if self.rank == 0:
                        telemetry.counter(
                            "hvd_coord_lease_renewals_total",
                            "Coordinator lease renewals (rank 0 "
                            "heartbeats that reached the launcher)").inc()
                if isinstance(resp, dict) and resp.get("reform"):
                    # Fail-in-place: the launcher computed the survivors'
                    # new world and delivers this rank's slice of it in
                    # the heartbeat reply (the same channel remote
                    # preemption rides — the launcher can't signal a
                    # remote rank directly).  reform_world() consumes it.
                    _deliver_reform_spec(resp["reform"])
                if isinstance(resp, dict) and resp.get("preempt") and \
                        not _preempt_event.is_set():
                    # The launcher can't SIGTERM a remote rank (only its
                    # ssh client) — the preemption arrives here instead,
                    # and the next guarded step runs the same deferred
                    # coordinated-save path as the signal handler.
                    log.warning("launcher requested preemption via the "
                                "health plane")
                    if telemetry.enabled():
                        telemetry.counter(
                            "hvd_preempt_requests_total",
                            "preemption signals received").inc()
                    request_preemption()
            except Exception as e:  # noqa: BLE001 — never stall training
                if telemetry.enabled():
                    telemetry.counter(
                        "hvd_heartbeat_send_failures_total",
                        "heartbeat sends that failed (launcher slow, "
                        "restarting, or gone)").inc()
                log.debug("heartbeat send failed: %s: %s",
                          type(e).__name__, e)
                self._fence_check(_time.monotonic())


_heartbeat_sender: Optional[HeartbeatSender] = None
_heartbeat_lock = threading.Lock()


def start_heartbeat(rank: Optional[int] = None
                    ) -> Optional[HeartbeatSender]:
    """Start the heartbeat sender when the launcher configured the
    health plane (``HOROVOD_HEALTH_RPC=addr:port`` in this rank's env).
    Idempotent; called automatically from ``hvd.init()``.  Returns the
    sender, or None when the health plane is not configured."""
    global _heartbeat_sender
    target = os.environ.get(_HEALTH_RPC_VAR, "").strip()
    if not target:
        return None
    with _heartbeat_lock:
        if _heartbeat_sender is not None:
            return _heartbeat_sender
        addr, _, port = target.rpartition(":")
        if not addr or not port.isdigit():
            log.warning("%s=%r is not addr:port — heartbeats disabled",
                        _HEALTH_RPC_VAR, target)
            return None
        try:
            interval = float(
                os.environ.get(_HEARTBEAT_INTERVAL_VAR, "") or 2.0)
        except ValueError:
            log.warning("%s=%r is not a number — using 2.0s",
                        _HEARTBEAT_INTERVAL_VAR,
                        os.environ.get(_HEARTBEAT_INTERVAL_VAR))
            interval = 2.0
        if rank is None:
            rank = int(os.environ.get("HOROVOD_RANK", "0") or 0)
        from horovod_tpu.runner import rpc
        key = rpc.job_key_bytes(os.environ.get("HOROVOD_SECRET_KEY"))
        sender = HeartbeatSender(addr, int(port), key, rank, interval)
        sender.start()
        _heartbeat_sender = sender
        log.debug("heartbeat sender started -> %s (interval %.2fs)",
                  target, interval)
        return sender


def stop_heartbeat() -> None:
    global _heartbeat_sender
    with _heartbeat_lock:
        if _heartbeat_sender is not None:
            _heartbeat_sender.stop()
            _heartbeat_sender = None


# ---------------------------------------------------------------------------
# Fail-in-place: in-process world reformation on rank death
# (HOROVOD_ON_RANK_FAILURE=shrink|shrink-then-restart)
# ---------------------------------------------------------------------------

_reform_lock = threading.Lock()
_reform_event = threading.Event()
_reform_spec: Optional[dict] = None


def _deliver_reform_spec(spec) -> None:
    """Latch a launcher-delivered reformation spec (heartbeat reply).

    Stale specs — epoch not beyond the world this process is already
    running under — are dropped: after a reformation the heartbeat keys
    collide with the OLD rank numbering for a reply or two until the
    launcher's pending table clears, and re-applying the same spec would
    tear down the freshly reformed world."""
    global _reform_spec
    if not isinstance(spec, dict):
        return
    from horovod_tpu import config
    current = config.env_int("HOROVOD_WORLD_EPOCH", 0) or 0
    if int(spec.get("epoch", 0)) <= current:
        return
    with _reform_lock:
        _reform_spec = dict(spec)
        _reform_event.set()
    log.info("reformation spec received: epoch %s, new rank %s of %s",
             spec.get("epoch"), spec.get("rank"), spec.get("size"))


def _take_reform_spec(timeout: float) -> Optional[dict]:
    global _reform_spec
    if not _reform_event.wait(timeout):
        return None
    with _reform_lock:
        spec, _reform_spec = _reform_spec, None
        _reform_event.clear()
    return spec


def reform_world(params, opt_state, *, ckpt_dir: Optional[str] = None,
                 timeout: Optional[float] = None):
    """Reform the collective world in-process after a peer death.

    The recovery rung ABOVE transport self-healing and BELOW the elastic
    relaunch (docs/fault_tolerance.md): called from the training loop's
    ``except MembershipChangedError`` handler when
    ``HOROVOD_ON_RANK_FAILURE`` is ``shrink`` / ``shrink-then-restart``.
    Sequence:

    1. **wait for the spec** — the launcher detects the death, computes
       the survivors' contiguous re-ranking and delivers each rank its
       slice via the heartbeat reply (the sender is still running — the
       old world is broken, not this process);
    2. **tear down** the old world (``hvd.shutdown()``: drains the
       queue, closes transport links, stops the heartbeat);
    3. **adopt** the spec: new rank/size/local topology, the fresh
       rendezvous port, ``HOROVOD_WORLD_EPOCH`` and
       ``HOROVOD_ELASTIC_PREV_SIZE`` (so PR 5's elastic-continuity
       lr/accumulate policy sees the N->N-1 shrink);
    4. **re-init** (``hvd.init()``: new rendezvous among survivors, flat
       ring + hierarchical levels + shm/striped links rebuilt against
       the new peer set; heartbeat restarts under the new rank);
    5. **recover state** with the :func:`warm_restore` ladder (Max-step
       election, peer-spill re-broadcast, ZeRO re-shard for N-1).

    Returns ``(params, opt_state, step, source, extra)`` exactly like
    :func:`warm_restore`.  Raises ``TimeoutError`` when no spec arrives
    within ``timeout`` (default ``HOROVOD_REFORM_TIMEOUT``, 60s) — the
    caller re-raises the original failure and the job falls back to the
    relaunch path (shrink-then-restart) or dies (shrink)."""
    import time as _time
    from horovod_tpu import config
    if timeout is None:
        timeout = config.env_float("HOROVOD_REFORM_TIMEOUT", 60.0)
    t0 = _time.monotonic()
    pre_step, _ = progress()
    spec = _take_reform_spec(float(timeout))
    if spec is None:
        raise TimeoutError(
            f"no reformation spec from the launcher within {timeout:g}s "
            f"(HOROVOD_REFORM_TIMEOUT) — falling back to the restart "
            f"path")
    basics.shutdown()
    os.environ["HOROVOD_ELASTIC_PREV_SIZE"] = str(
        spec.get("prev_size", int(spec["size"]) + 1))
    os.environ["HOROVOD_WORLD_EPOCH"] = str(spec["epoch"])
    os.environ["HOROVOD_RANK"] = str(spec["rank"])
    os.environ["HOROVOD_SIZE"] = str(spec["size"])
    os.environ["HOROVOD_LOCAL_RANK"] = str(spec["local_rank"])
    os.environ["HOROVOD_LOCAL_SIZE"] = str(spec["local_size"])
    # Overwrite unconditionally: the launch-time values are stale for
    # the reformed world and basics.init() would otherwise read them.
    os.environ["HOROVOD_CROSS_RANK"] = str(spec.get(
        "cross_rank", int(spec["rank"]) // max(int(spec["local_size"]), 1)))
    os.environ["HOROVOD_CROSS_SIZE"] = str(spec.get("cross_size", 1))
    os.environ["HOROVOD_RENDEZVOUS_ADDR"] = str(spec["rendezvous_addr"])
    os.environ["HOROVOD_RENDEZVOUS_PORT"] = str(spec["rendezvous_port"])
    if spec.get("topology"):
        os.environ["HOROVOD_TOPOLOGY"] = str(spec["topology"])
    basics.init()
    new_params, new_opt, step, source, extra = warm_restore(
        params, opt_state, ckpt_dir=ckpt_dir)
    seconds = _time.monotonic() - t0
    if telemetry.enabled():
        telemetry.histogram(
            "hvd_failinplace_reformation_seconds",
            "Wall time from membership-change detection to the reformed "
            "world's state recovery completing",
            bounds=telemetry.DEFAULT_TIME_BUCKETS).observe(seconds)
        telemetry.gauge(
            "hvd_failinplace_world_epoch",
            "Membership epoch this rank is running under (0 = never "
            "reformed)").set(int(spec["epoch"]))
        if basics.rank() == 0 and pre_step >= 0 and step >= 0:
            # New rank 0 only, so the merged summary books the loss once.
            telemetry.counter(
                "hvd_failinplace_steps_lost_total",
                "Steps rolled back by in-process reformations (progress "
                "high-water minus the recovered committed step)").inc(
                    max(pre_step - step, 0))
    log.info("fail-in-place: reformed world epoch %s as rank %d/%d in "
             "%.2fs (recovered step %d from %s)", spec["epoch"],
             basics.rank(), basics.size(), seconds, step, source)
    return new_params, new_opt, step, source, extra


# ---------------------------------------------------------------------------
# Preemption protocol
# ---------------------------------------------------------------------------

_preempt_event = threading.Event()
_handler_lock = threading.Lock()
_handler_installed = False


def install_preemption_handler(signum: int = signal.SIGTERM) -> None:
    """Turn ``signum`` (default SIGTERM — what schedulers send on
    preemption) into a deferred request: the handler only sets a flag;
    the training loop acts on it at the next step boundary via
    :func:`maybe_save_and_exit`.  Idempotent; main thread only (signal
    module constraint)."""
    global _handler_installed
    with _handler_lock:
        if _handler_installed:
            return

        def _on_signal(sig, frame):  # noqa: ARG001
            _preempt_event.set()
            if telemetry.enabled():
                telemetry.counter(
                    "hvd_preempt_requests_total",
                    "preemption signals received").inc()

        signal.signal(signum, _on_signal)
        _handler_installed = True
        log.debug("preemption handler installed for signal %d", signum)


def preemption_requested() -> bool:
    return _preempt_event.is_set()


def request_preemption() -> None:
    """Programmatic equivalent of receiving the preemption signal (used
    by tests and embedding frameworks with their own signal plumbing)."""
    _preempt_event.set()


def exit_preempted() -> "None":
    """Exit with :data:`PREEMPTION_RC` via ``sys.exit`` so atexit hooks
    (telemetry dumps, async-checkpoint drain) still run."""
    log.warning("exiting with preemption rc %d (reschedule, do not "
                "blacklist)", PREEMPTION_RC)
    # Kill the heartbeat first: a sender racing the interpreter teardown
    # can otherwise push one last beat AFTER the launcher's monitor was
    # reset for the next attempt, haunting the new world's bookkeeping.
    stop_heartbeat()
    sys.exit(PREEMPTION_RC)


def maybe_save_and_exit(ckpt_dir: str, state, step: int) -> bool:
    """Call at every step boundary.  No-op (returns False) unless a
    preemption was requested; then every rank performs the coordinated
    synchronous save (the signal is delivered process-group-wide, so all
    ranks reach this together), drains any in-flight async write first,
    and exits with :data:`PREEMPTION_RC`."""
    if not _preempt_event.is_set():
        return False
    from horovod_tpu import checkpoint
    log.warning("preemption requested — coordinated save at step %d "
                "to %s", step, ckpt_dir)
    # The save below can take a while on big states; keep the health
    # plane fed so the watchdog never mistakes a rank mid-coordinated-
    # save for a hung one and SIGKILLs it out of its own rescue.
    report_progress(step)
    checkpoint.wait_for_async_save()
    checkpoint.save(ckpt_dir, state, step=step)
    if telemetry.enabled():
        telemetry.counter(
            "hvd_preempt_saves_total",
            "coordinated preemption saves completed").inc()
    exit_preempted()
    return True  # pragma: no cover — sys.exit above


def _reset_for_tests() -> None:
    """Clear module state (preemption flag + handler marker + heartbeat
    sender + progress)."""
    global _handler_installed, _progress_step, _progress_ts
    _preempt_event.clear()
    with _handler_lock:
        _handler_installed = False
    stop_heartbeat()
    with _progress_lock:
        _progress_step = -1
        _progress_ts = 0.0

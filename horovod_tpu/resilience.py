"""Self-healing training loop: step guard, divergence sentinel, rollback.

PR 1's fault tolerance handles *loud* failures (crashes, hangs,
blacklisting, restart-from-disk).  This module is the defense-in-depth
layer for *silent* ones — NaN bursts, replica divergence / bit flips —
so a bad step costs one step, not a relaunch (the in-memory-snapshot
recovery idea of Gemini, SOSP'23, on top of CheckFreq's, FAST'21,
iteration-boundary checkpointing):

* **Step guard** (in-graph, wired into ``parallel/data.py``,
  ``models/transformer.py`` and the benchmark's step builder): every
  jitted step checks loss + grads for NaN/Inf with a global ``is_finite``
  psum.  Collectives may not sit inside a ``lax.cond`` branch under SPMD,
  so the "conditional skip" is realized as an unconditional update
  followed by a per-leaf ``jnp.where(ok, new, old)`` select — XLA fuses
  the select, and the optimizer update it may waste ran on garbage
  anyway.  A bad step returns the *old* state and a NaN mean loss (the
  host-visible signal).  Policy via ``HOROVOD_STEP_GUARD``:
  ``off | skip | rollback | abort``.

* **Last-known-good rollback** (:class:`LastKnownGood`,
  :class:`StepGuard`): a host-side, double-buffered snapshot of the last
  *validated* ``params/opt_state/step``.  The pull to host happens off
  the critical path (``copy_to_host_async`` first, staged into a standby
  buffer, committed only after the bytes validate finite), and
  :meth:`StepGuard.after_step` restores it in-process on a NaN burst —
  every rank coordinates on a global ok flag first, so they roll back
  together or not at all.

* **Divergence sentinel**: every ``HOROVOD_SENTINEL_INTERVAL`` steps,
  allreduce a cheap per-rank digest (chained crc32, exact in float64) of
  params and optimizer state (the local shard bytes under ZeRO-1) with
  ``Min`` and ``Max`` and compare min == max.  On mismatch, an allgather
  names the diverging rank(s) (minority digest vs the modal one), and
  policy ``rollback`` heals in-process by re-broadcasting state from the
  lowest healthy rank — a diverged rank's *own* snapshots are
  finite-but-wrong, so rollback alone cannot heal divergence.

* **Preemption protocol**: :func:`install_preemption_handler` turns
  SIGTERM into a request flag; :func:`maybe_save_and_exit` performs a
  coordinated checkpoint at the next step boundary and exits with
  :data:`PREEMPTION_RC` (75, ``EX_TEMPFAIL``), which the launcher treats
  as preemption — no blacklist, no backoff, immediate reschedule
  (``runner/launch.py`` / ``runner/run.py``).

Env knobs: ``HOROVOD_STEP_GUARD`` (policy), ``HOROVOD_SENTINEL_INTERVAL``
(0 = off), ``HOROVOD_LKG_INTERVAL`` (snapshot every N validated steps,
default 1), ``HOROVOD_GUARD_NAN_BURST`` (consecutive bad steps before a
rollback fires, default 1).  Everything emits ``hvd_guard_*`` /
``hvd_rollback_*`` / ``hvd_sentinel_*`` telemetry (``docs/metrics.md``)
and is chaos-testable via the ``nan`` / ``corrupt`` fault kinds
(``faults.py``).  See ``docs/fault_tolerance.md``.
"""

from __future__ import annotations

import functools
import os
import signal
import sys
import threading
import zlib
from typing import Any, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from horovod_tpu import basics, telemetry
from horovod_tpu.ops import collective as _c
from horovod_tpu.utils.logging import get_logger

log = get_logger(__name__)

# Distinct exit code for "preempted, please reschedule me" — 75 is BSD
# EX_TEMPFAIL ("temporary failure, user is invited to retry"), far from
# the launcher's operator-stop codes (130/143) and from any shell/signal
# encoding (128+N).
PREEMPTION_RC = 75

GUARD_POLICIES = ("off", "skip", "rollback", "abort")

_POLICY_VAR = "HOROVOD_STEP_GUARD"
_SENTINEL_VAR = "HOROVOD_SENTINEL_INTERVAL"
_LKG_VAR = "HOROVOD_LKG_INTERVAL"
_BURST_VAR = "HOROVOD_GUARD_NAN_BURST"


class GuardAbort(RuntimeError):
    """Raised by :meth:`StepGuard.after_step` under policy ``abort``."""


class DivergenceError(RuntimeError):
    """Raised by the sentinel when replicas diverge and the policy does
    not heal (anything but ``rollback``).  Carries ``.ranks``."""

    def __init__(self, message: str, ranks: Sequence[int]):
        super().__init__(message)
        self.ranks = tuple(ranks)


def guard_policy() -> str:
    """The step-guard policy from ``HOROVOD_STEP_GUARD`` (default
    ``off``).  Read at *trace* time by :func:`apply_step_guard` — set it
    before building the training step."""
    value = os.environ.get(_POLICY_VAR, "off").strip().lower() or "off"
    if value not in GUARD_POLICIES:
        raise ValueError(
            f"{_POLICY_VAR}={value!r}: expected one of "
            f"{', '.join(GUARD_POLICIES)}")
    return value


def _env_interval(var: str, default: int, minimum: int = 0) -> int:
    raw = os.environ.get(var, "")
    if not raw.strip():
        return default
    try:
        value = int(raw)
    except ValueError:
        raise ValueError(f"{var}={raw!r} is not an integer")
    if value < minimum:
        raise ValueError(f"{var}={value} must be >= {minimum}")
    return value


# ---------------------------------------------------------------------------
# In-graph step guard
# ---------------------------------------------------------------------------

def all_finite(axes, loss, *trees):
    """In-graph global finiteness flag: True iff ``loss`` and every
    inexact leaf of ``trees`` is finite on **every** shard of ``axes``.
    The local flag is an int32 min over leaves; the global agreement is
    ``psum(flag) == psum(1)`` (the product of the axis sizes), so all
    shards compute the same boolean."""
    flags = []
    for leaf in jax.tree_util.tree_leaves((loss,) + tuple(trees)):
        arr = jnp.asarray(leaf)
        if jnp.issubdtype(arr.dtype, jnp.inexact):
            flags.append(jnp.all(jnp.isfinite(arr)).astype(jnp.int32))
    local = (functools.reduce(jnp.minimum, flags) if flags
             else jnp.int32(1))
    axes = tuple(a for a in (axes or ()) if a)
    if not axes:
        return local == 1
    return lax.psum(local, axes) == lax.psum(jnp.int32(1), axes)


def apply_step_guard(do_update, *, loss, grads, old_state, axes=(),
                     agree_axes=None):
    """Wrap one optimizer update with the NaN/Inf step guard.

    ``do_update()`` (a closure over ``grads``) must return a new state
    pytree congruent with ``old_state``.  Returns ``(state, mean_loss)``
    where ``mean_loss = pmean(loss, axes)``.  Under policy ``off`` this
    is exactly ``(do_update(), pmean(loss))`` — zero overhead.  Under any
    other policy the update runs unconditionally and the guard selects
    per leaf between new and old state (collectives cannot live inside a
    ``lax.cond`` branch under SPMD — the select *is* the skip), and a bad
    step's mean loss is poisoned to NaN so the host can see it
    (:meth:`StepGuard.after_step` keys off exactly that).

    ``agree_axes`` (default: ``axes``) is where the finiteness verdict is
    psummed — pass *every* mesh axis the state is sharded over (e.g. the
    tensor-parallel model axis on top of the data axes), so all shards
    select the same branch.

    The policy is read at trace time: build the step *after* setting
    ``HOROVOD_STEP_GUARD``.
    """
    axes = tuple(a for a in (axes or ()) if a)
    agree_axes = (axes if agree_axes is None
                  else tuple(a for a in agree_axes if a))
    mean_loss = lax.pmean(loss, axes) if axes else loss
    policy = guard_policy()
    if policy == "off":
        return do_update(), mean_loss
    if telemetry.enabled():  # trace-time: counts guarded step *traces*
        telemetry.counter(
            "hvd_guard_traces_total",
            "training-step traces built with the step guard enabled",
            policy=policy).inc()
    ok = all_finite(agree_axes, loss, grads)
    new_state = do_update()
    guarded = jax.tree_util.tree_map(
        lambda new, old: jnp.where(ok, new, old), new_state, old_state)
    bad = jnp.asarray(jnp.nan, dtype=jnp.result_type(mean_loss))
    return guarded, jnp.where(ok, mean_loss, bad)


# ---------------------------------------------------------------------------
# Host-side helpers
# ---------------------------------------------------------------------------

def _host_finite(arr: np.ndarray) -> bool:
    """Finiteness of host bytes; ml_dtypes kinds (bf16 is 'V' to numpy)
    go through a float32 cast."""
    kind = getattr(arr.dtype, "kind", "")
    if kind in ("f", "c"):
        return bool(np.isfinite(arr).all())
    if kind == "V":  # bfloat16 & friends
        return bool(np.isfinite(np.asarray(arr, np.float32)).all())
    return True


def _pull_to_host(leaves):
    """Device->host for a list of leaves, overlapping the transfers:
    issue every async copy first, then materialize."""
    for leaf in leaves:
        copy_async = getattr(leaf, "copy_to_host_async", None)
        if copy_async is not None:
            copy_async()
    return [np.asarray(leaf) for leaf in leaves]


def _leaf_sharding(leaf):
    if isinstance(leaf, jax.Array):
        try:
            return leaf.sharding
        except Exception:  # pragma: no cover - deleted/donated buffers
            return None
    return None


def tree_digest(tree) -> int:
    """Cheap deterministic digest of a pytree: crc32 chained over the
    host bytes of every leaf in tree-flatten order.  crc32 < 2**32 is
    exactly representable in float64, so digests survive a float
    allreduce bit-exactly."""
    crc = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        arr = np.ascontiguousarray(np.asarray(leaf))
        crc = zlib.crc32(arr.tobytes(), crc)
    return crc


def _divergent_ranks(digests) -> list:
    """Name the diverging rank(s): rows of ``digests`` (one per rank)
    that differ from the modal row.  Ties break to the smallest row, so
    every rank computes the same answer from the same allgathered
    array."""
    rows = [tuple(np.asarray(row).ravel().tolist()) for row in digests]
    counts = {}
    for row in rows:
        counts[row] = counts.get(row, 0) + 1
    top = max(counts.values())
    modal = min(row for row, n in counts.items() if n == top)
    return [i for i, row in enumerate(rows) if row != modal]


class LastKnownGood:
    """Double-buffered host snapshot of the last validated training
    state.  :meth:`stage` pulls to the standby buffer and validates the
    bytes (nearly free — they are already on the host); :meth:`commit`
    flips it in only after the *global* verdict is in, so a poisoned or
    torn snapshot can never replace a good one.  Requires the state to
    be fully addressable from this process (true for this repo's
    per-process device meshes)."""

    def __init__(self):
        self._committed = None  # (step, treedef, host leaves, shardings)
        self._staged = None

    @property
    def available(self) -> bool:
        return self._committed is not None

    @property
    def step(self) -> Optional[int]:
        return self._committed[0] if self._committed else None

    def stage(self, params, opt_state, step: int) -> bool:
        """Pull ``(params, opt_state)`` into the standby buffer.  Returns
        False — and stages nothing — when the pulled bytes contain
        NaN/Inf (the live state is already poisoned)."""
        t0 = telemetry.clock()
        leaves, treedef = jax.tree_util.tree_flatten((params, opt_state))
        shardings = [_leaf_sharding(l) for l in leaves]
        host = _pull_to_host(leaves)
        ok = all(_host_finite(h) for h in host)
        if ok:
            self._staged = (int(step), treedef, host, shardings)
        else:
            self._staged = None
            if telemetry.enabled():
                telemetry.counter(
                    "hvd_rollback_snapshot_rejected_total",
                    "staged snapshots rejected for non-finite bytes").inc()
        if telemetry.enabled():
            telemetry.histogram(
                "hvd_rollback_snapshot_seconds",
                "host pull + validation time per staged snapshot",
            ).observe(telemetry.clock() - t0)
        return ok

    def commit(self) -> None:
        if self._staged is None:
            return
        self._committed, self._staged = self._staged, None
        if telemetry.enabled():
            telemetry.counter(
                "hvd_rollback_snapshots_total",
                "last-known-good snapshots committed").inc()

    def discard_stage(self) -> None:
        self._staged = None

    def restore(self) -> Tuple[Any, Any, int]:
        """Fresh device copies of the committed snapshot as
        ``(params, opt_state, step)``.  Explicit copies (``device_put``
        with the captured shardings) so the restored arrays never alias
        the host buffers — safe to feed straight back into a donating
        jitted step."""
        if self._committed is None:
            raise RuntimeError("no last-known-good snapshot available")
        step, treedef, host, shardings = self._committed
        leaves = [jax.device_put(h, s) if s is not None else jnp.array(h)
                  for h, s in zip(host, shardings)]
        params, opt_state = jax.tree_util.tree_unflatten(treedef, leaves)
        if telemetry.enabled():
            telemetry.counter(
                "hvd_rollback_restores_total",
                "in-process restores from last-known-good").inc()
        return params, opt_state, step


class GuardEvent(NamedTuple):
    """What :meth:`StepGuard.after_step` did.  ``action`` is one of
    ``ok | skip | rollback | heal``; ``step`` is the step the returned
    state corresponds to (the last-known-good step after a rollback)."""
    action: str
    step: int


class StepGuard:
    """Host-side coordinator for the in-graph guard: validates each
    step's outcome across ranks, maintains the last-known-good snapshot,
    runs the divergence sentinel, and decides skip/rollback/abort.

    Usage::

        guard = hvd.StepGuard()            # reads HOROVOD_STEP_GUARD etc.
        for step in range(n):
            params, opt_state, loss = train_step(params, opt_state, batch)
            params, opt_state, ev = guard.after_step(
                params, opt_state, step, loss)

    ``loss`` is the step's returned mean loss — NaN marks a guarded-bad
    step (see :func:`apply_step_guard`).  All ranks must call
    ``after_step`` for every step: the verdict is coordinated with an
    eager-plane ``Min`` allreduce of the local ok flag, so either every
    rank rolls back or none does (a NaN burst can hit one rank's shard
    only, but state must stay replicated)."""

    def __init__(self, policy: Optional[str] = None,
                 sentinel_interval: Optional[int] = None,
                 snapshot_interval: Optional[int] = None,
                 nan_burst: Optional[int] = None):
        self.policy = guard_policy() if policy is None else policy
        if self.policy not in GUARD_POLICIES:
            raise ValueError(
                f"policy {self.policy!r}: expected one of "
                f"{', '.join(GUARD_POLICIES)}")
        self.sentinel_interval = (
            _env_interval(_SENTINEL_VAR, 0)
            if sentinel_interval is None else int(sentinel_interval))
        self.snapshot_interval = (
            _env_interval(_LKG_VAR, 1, minimum=1)
            if snapshot_interval is None else max(1, int(snapshot_interval)))
        self.nan_burst = (
            _env_interval(_BURST_VAR, 1, minimum=1)
            if nan_burst is None else max(1, int(nan_burst)))
        self.lkg = LastKnownGood()
        self._bad_streak = 0
        self._warned_no_lkg = False

    # -- coordination -----------------------------------------------------

    @staticmethod
    def _global_ok(local_ok: bool) -> bool:
        """Min-allreduce of the local verdict over the eager plane: the
        step is good only if it is good on *every* rank."""
        if basics.size() <= 1:
            return local_ok
        flag = np.array([1.0 if local_ok else 0.0], np.float32)
        out = _c._eager_allreduce(
            flag, _c.Min, "hvd.resilience.guard.ok", 1.0, 1.0)
        return bool(np.asarray(out)[0] >= 0.5)

    # -- sentinel ---------------------------------------------------------

    def _digests(self, params, opt_state) -> np.ndarray:
        opt_digest = None
        try:
            from horovod_tpu.parallel import zero
            if isinstance(opt_state, zero.ZeroShardedState):
                opt_digest = zero.local_state_digest(opt_state)
        except ImportError:  # pragma: no cover
            pass
        if opt_digest is None:
            opt_digest = tree_digest(opt_state)
        return np.array([float(tree_digest(params)), float(opt_digest)],
                        np.float64)

    def _sentinel(self, params, opt_state, step: int):
        """min/max digest agreement; on mismatch, name the diverging
        rank(s) and heal (policy ``rollback``) or raise."""
        if telemetry.enabled():
            telemetry.counter(
                "hvd_sentinel_checks_total",
                "divergence sentinel digest comparisons").inc()
        digest = self._digests(params, opt_state)
        lo = _c._eager_allreduce(
            digest, _c.Min, "hvd.resilience.sentinel.min", 1.0, 1.0)
        hi = _c._eager_allreduce(
            digest, _c.Max, "hvd.resilience.sentinel.max", 1.0, 1.0)
        if np.array_equal(np.asarray(lo), np.asarray(hi)):
            return params, opt_state, None
        gathered = _c._eager_allgather(
            digest.reshape(1, -1), "hvd.resilience.sentinel.digests")
        bad_ranks = _divergent_ranks(np.asarray(gathered))
        if telemetry.enabled():
            telemetry.counter(
                "hvd_sentinel_divergence_total",
                "sentinel checks that found diverged replicas").inc()
        message = (f"divergence sentinel at step {step}: replica digests "
                   f"disagree; diverging rank(s): {bad_ranks}")
        if self.policy != "rollback":
            log.error("%s", message)
            raise DivergenceError(message, bad_ranks)
        source = min(r for r in range(basics.size()) if r not in bad_ranks)
        log.error("%s — healing by re-broadcasting state from rank %d",
                  message, source)
        params, opt_state = _broadcast_state(params, opt_state, source)
        if telemetry.enabled():
            telemetry.counter(
                "hvd_sentinel_heals_total",
                "in-process divergence heals (state re-broadcast)").inc()
        return params, opt_state, GuardEvent("heal", step)

    # -- the step boundary -------------------------------------------------

    def after_step(self, params, opt_state, step: int, loss):
        """Validate one completed step.  Returns
        ``(params, opt_state, GuardEvent)`` — possibly the restored
        last-known-good state.  Must be called on every rank."""
        if self.policy == "off" and self.sentinel_interval == 0:
            return params, opt_state, GuardEvent("ok", step)
        if telemetry.enabled():
            telemetry.counter(
                "hvd_guard_checks_total",
                "host-side step-boundary guard evaluations").inc()

        local_ok = bool(np.isfinite(np.asarray(loss, np.float64)).all())
        staged = False
        if (local_ok and self.policy == "rollback"
                and step % self.snapshot_interval == 0):
            staged = self.lkg.stage(params, opt_state, step)
            local_ok = staged  # a rejected pull means the state is bad
        ok = self._global_ok(local_ok)

        if ok:
            if staged:
                self.lkg.commit()
            self._bad_streak = 0
            if (self.sentinel_interval > 0 and step > 0
                    and step % self.sentinel_interval == 0
                    and basics.size() > 1):
                params, opt_state, event = self._sentinel(
                    params, opt_state, step)
                if event is not None:
                    return params, opt_state, event
            return params, opt_state, GuardEvent("ok", step)

        # Bad step (on at least one rank — all ranks agree it was bad).
        self.lkg.discard_stage()
        self._bad_streak += 1
        if telemetry.enabled():
            telemetry.counter(
                "hvd_guard_nonfinite_steps_total",
                "steps rejected by the guard (non-finite loss/grads)").inc()
        if self.policy == "abort":
            raise GuardAbort(
                f"step guard: non-finite loss/grads at step {step} "
                f"(policy abort)")
        if (self.policy == "rollback"
                and self._bad_streak >= self.nan_burst):
            if self.lkg.available:
                params, opt_state, good_step = self.lkg.restore()
                self._bad_streak = 0
                log.warning(
                    "step guard: non-finite step %d — rolled back to "
                    "last-known-good step %d", step, good_step)
                return params, opt_state, GuardEvent("rollback", good_step)
            if not self._warned_no_lkg:
                self._warned_no_lkg = True
                log.warning(
                    "step guard: rollback requested at step %d but no "
                    "last-known-good snapshot exists yet — skipping "
                    "instead", step)
        if telemetry.enabled():
            telemetry.counter(
                "hvd_guard_skipped_steps_total",
                "bad steps skipped (old state kept)").inc()
        log.warning("step guard: non-finite step %d skipped "
                    "(streak %d)", step, self._bad_streak)
        return params, opt_state, GuardEvent("skip", step)


def _broadcast_state(params, opt_state, root_rank: int):
    """Re-broadcast ``(params, opt_state)`` from ``root_rank`` over the
    eager plane, re-placing each leaf with its original sharding —
    the divergence heal (a diverged rank's own snapshots are
    finite-but-wrong, so only a healthy rank's live state can heal
    it)."""
    leaves, treedef = jax.tree_util.tree_flatten((params, opt_state))
    out = []
    for i, leaf in enumerate(leaves):
        sharding = _leaf_sharding(leaf)
        host = np.ascontiguousarray(np.asarray(leaf))
        healed = _c._eager_broadcast(
            host, root_rank, f"hvd.resilience.heal.{i}")
        healed = np.asarray(healed, dtype=host.dtype)
        out.append(jax.device_put(healed, sharding)
                   if sharding is not None else jnp.array(healed))
    return jax.tree_util.tree_unflatten(treedef, out)


# ---------------------------------------------------------------------------
# Preemption protocol
# ---------------------------------------------------------------------------

_preempt_event = threading.Event()
_handler_lock = threading.Lock()
_handler_installed = False


def install_preemption_handler(signum: int = signal.SIGTERM) -> None:
    """Turn ``signum`` (default SIGTERM — what schedulers send on
    preemption) into a deferred request: the handler only sets a flag;
    the training loop acts on it at the next step boundary via
    :func:`maybe_save_and_exit`.  Idempotent; main thread only (signal
    module constraint)."""
    global _handler_installed
    with _handler_lock:
        if _handler_installed:
            return

        def _on_signal(sig, frame):  # noqa: ARG001
            _preempt_event.set()
            if telemetry.enabled():
                telemetry.counter(
                    "hvd_preempt_requests_total",
                    "preemption signals received").inc()

        signal.signal(signum, _on_signal)
        _handler_installed = True
        log.debug("preemption handler installed for signal %d", signum)


def preemption_requested() -> bool:
    return _preempt_event.is_set()


def request_preemption() -> None:
    """Programmatic equivalent of receiving the preemption signal (used
    by tests and embedding frameworks with their own signal plumbing)."""
    _preempt_event.set()


def exit_preempted() -> "None":
    """Exit with :data:`PREEMPTION_RC` via ``sys.exit`` so atexit hooks
    (telemetry dumps, async-checkpoint drain) still run."""
    log.warning("exiting with preemption rc %d (reschedule, do not "
                "blacklist)", PREEMPTION_RC)
    sys.exit(PREEMPTION_RC)


def maybe_save_and_exit(ckpt_dir: str, state, step: int) -> bool:
    """Call at every step boundary.  No-op (returns False) unless a
    preemption was requested; then every rank performs the coordinated
    synchronous save (the signal is delivered process-group-wide, so all
    ranks reach this together), drains any in-flight async write first,
    and exits with :data:`PREEMPTION_RC`."""
    if not _preempt_event.is_set():
        return False
    from horovod_tpu import checkpoint
    log.warning("preemption requested — coordinated save at step %d "
                "to %s", step, ckpt_dir)
    checkpoint.wait_for_async_save()
    checkpoint.save(ckpt_dir, state, step=step)
    if telemetry.enabled():
        telemetry.counter(
            "hvd_preempt_saves_total",
            "coordinated preemption saves completed").inc()
    exit_preempted()
    return True  # pragma: no cover — sys.exit above


def _reset_for_tests() -> None:
    """Clear module state (preemption flag + handler marker)."""
    global _handler_installed
    _preempt_event.clear()
    with _handler_lock:
        _handler_installed = False

"""Declarative registry of every ``HOROVOD_*`` environment variable.

The reference configures itself through dozens of ad-hoc ``getenv``
calls scattered across Python and C++ (env_parser.cc plus per-module
reads); after nine PRs this rebuild had grown ~70 of its own.  This
module is the single source of truth: one entry per variable with its
type, documented default, one-line doc and whether the native runtime
(``native/cc``) also reads it.  ``basics.py``, ``runner/`` and
``native/runtime.py`` read the environment through the typed accessors
below, and ``tools/hvdlint``'s env-registry checker fails the build on

* any ``os.environ``/``getenv`` read of a ``HOROVOD_*`` name that has
  no entry here,
* any entry whose name appears nowhere in the code (orphan), and
* drift between the ``native=True`` flags and the actual
  ``EnvInt``/``EnvStr``/``EnvBool``/``EnvDouble`` reads in
  ``native/cc/src``.

Run it with ``python -m tools.hvdlint`` (or ``make lint``); rule docs in
``docs/static_analysis.md``.

This module is imported by ``tools/hvdlint`` standalone (via
``importlib`` file loading, without executing ``horovod_tpu/__init__``),
so it must stay stdlib-only: no jax, no sibling imports.

``python -m horovod_tpu.config`` prints the registry as a reference
table.
"""

from __future__ import annotations

import os
from typing import Any, Dict, NamedTuple, Optional


class EnvVar(NamedTuple):
    name: str
    type: str          # "str" | "int" | "float" | "bool"
    default: Any       # documented default; None = unset / derived
    doc: str           # one-line description (keep it one line: hvdlint
    #                    and the --describe table both render it as one)
    native: bool = False   # also read by native/cc (EnvInt/EnvStr/...)


REGISTRY: Dict[str, EnvVar] = {}


def _var(name: str, type_: str, default: Any, doc: str,
         native: bool = False) -> None:
    assert name not in REGISTRY, f"duplicate registry entry {name}"
    REGISTRY[name] = EnvVar(name, type_, default, doc, native)


# ---------------------------------------------------------------------------
# Rank / topology contract (exported by the hvdrun launcher; reference
# run/gloo_run.py:211-254)
# ---------------------------------------------------------------------------
_var("HOROVOD_RANK", "int", None,
     "This process's global rank; unset falls back to jax.process_index()",
     native=True)
_var("HOROVOD_SIZE", "int", None,
     "World size; unset falls back to jax.process_count()")
_var("HOROVOD_LOCAL_RANK", "int", None,
     "Rank within this host (default: the global rank)")
_var("HOROVOD_LOCAL_SIZE", "int", None,
     "Ranks on this host (default: the world size)")
_var("HOROVOD_CROSS_RANK", "int", None,
     "This host's index among hosts (default: rank // local_size)")
_var("HOROVOD_CROSS_SIZE", "int", None,
     "Number of hosts (default: ceil(size / local_size))")
_var("HOROVOD_HOSTNAME", "str", "",
     "Launcher-assigned host name used in topology and stall reports",
     native=True)
_var("HOROVOD_TOPOLOGY", "str", "",
     "host:slots,... map exported per elastic attempt; drives "
     "hvd.topology(), hierarchical routing and the native tree-"
     "coordination host blocks", native=True)
_var("HOROVOD_CONTROLLER", "str", "tcp",
     "Reference-compat marker exported by the launcher (always tcp here)")
_var("HOROVOD_CPU_OPERATIONS", "str", "tcp",
     "Reference-compat marker exported by the launcher (always tcp here)")

# ---------------------------------------------------------------------------
# Bootstrap / rendezvous / security
# ---------------------------------------------------------------------------
_var("HOROVOD_COORDINATOR_ADDR", "str", None,
     "host:port of the jax.distributed coordinator (multi-host SPMD "
     "bootstrap)")
_var("HOROVOD_JAX_DISTRIBUTED", "bool", False,
     "1 = call jax.distributed.initialize() inside hvd.init()")
_var("HOROVOD_RENDEZVOUS_ADDR", "str", "127.0.0.1",
     "Native control-plane rendezvous address (rank 0 listens here)")
_var("HOROVOD_RENDEZVOUS_PORT", "int", 0,
     "Native rendezvous port; 0 lets rank 0 bind an ephemeral port")
_var("HOROVOD_SECRET_KEY", "str", None,
     "Base64 HMAC key authenticating the RPC + native control planes",
     native=True)
_var("HOROVOD_SSH_CMD", "str", "ssh",
     "Remote-shell command used to spawn ranks (CI points it at "
     "ci/fake_ssh.sh)")
_var("HOROVOD_NETWORK_INTERFACE", "str", "",
     "Comma-separated NIC allowlist for the native data plane",
     native=True)
_var("HOROVOD_SOCKET_BUFFER", "int", -1,
     "SO_SNDBUF/SO_RCVBUF request for ring sockets; -1 keeps the OS "
     "default", native=True)
_var("HOROVOD_TPU_NATIVE_LIB", "str", None,
     "Absolute path overriding the built libhorovod_tpu.so")

# ---------------------------------------------------------------------------
# Eager plane behavior
# ---------------------------------------------------------------------------
_var("HOROVOD_EAGER_OP_TIMEOUT", "float", None,
     "Seconds after which a blocked eager wait raises EagerStallError "
     "(unset = wait forever, watchdog still warns)")
_var("HOROVOD_EAGER_OP_WARN_SECONDS", "float", 60.0,
     "Python-side wait warning cadence for slow eager ops")
_var("HOROVOD_EAGER_ZERO_COPY", "bool", True,
     "0 restores the copying hvd_read_output result path")
_var("HOROVOD_EAGER_CHUNK_BYTES", "int", 1024 * 1024,
     "Pipelined-transport granule for oversized ring exchanges; 0 "
     "disables chunking", native=True)
_var("HOROVOD_STALL_CHECK_TIME_SECONDS", "float", 60.0,
     "Coordinator stall-inspector warning deadline; 0 disables",
     native=True)
_var("HOROVOD_STALL_SHUTDOWN_TIME_SECONDS", "float", 0.0,
     "Coordinator stall deadline after which the job aborts; 0 disables",
     native=True)
_var("HOROVOD_SCHEDULE_CHECK", "bool", False,
     "1 arms the collective-schedule contract verifier: the coordinator "
     "matches every rank's submission records by name and aborts at the "
     "first divergence (rank, call index, field) instead of stalling",
     native=True)
_var("HOROVOD_SCHEDULE_CHECK_QUIET_SECONDS", "float", 2.0,
     "schedule-verifier quiet window: with the check armed, abort when "
     "every rank has an unmatched submission and no rank has announced "
     "anything for this long (raise on very bursty async pipelines)",
     native=True)
_var("HOROVOD_CYCLE_TIME", "float", 1.0,
     "Coordination loop cycle time in ms (autotune may override)",
     native=True)
_var("HOROVOD_CACHE_CAPACITY", "int", 1024,
     "Response-cache capacity in entries; 0 disables the steady-state "
     "fast path", native=True)

# ---------------------------------------------------------------------------
# Fusion / compression / hierarchical routing
# ---------------------------------------------------------------------------
_var("HOROVOD_FUSION_THRESHOLD", "int", 64 * 1024 * 1024,
     "Fusion bucket byte threshold (size grammar: 64mb/32MiB/0.5; "
     "autotune may override)", native=True)
_var("HOROVOD_MAX_BUCKET_BYTES", "int", 32 * 1024 * 1024,
     "Cap above which fusion-v2 buckets are chunked; 0 disables")
_var("HOROVOD_COMPRESSION", "str", "none",
     "Wire codec: none|bf16|fp16|int8|powersgd[:rank]")
_var("HOROVOD_HIERARCHICAL_ALLREDUCE", "bool", False,
     "1 routes eager allreduces through the 2-level "
     "local-RS/leader-ring/local-AG plane", native=True)
_var("HOROVOD_HIERARCHICAL_ALLGATHER", "bool", False,
     "1 routes eager allgathers through the 2-level plane", native=True)
_var("HOROVOD_HIERARCHICAL_ALLREDUCE_THRESHOLD", "int", 262144,
     "Payload bytes below which hier-routed allreduces stay on the flat "
     "ring", native=True)

# ---------------------------------------------------------------------------
# Transport backends (native/cc/src/{shm,striped}_transport.cc,
# docs/performance.md "Transport backends")
# ---------------------------------------------------------------------------
_var("HOROVOD_TRANSPORT", "str", "auto",
     "Data-plane backend selection: auto (shm intra-host, striped "
     "cross-host when stripes>1, else socket) | shm | striped | socket",
     native=True)
_var("HOROVOD_TRANSPORT_STRIPES", "int", 0,
     "Parallel TCP connections per cross-host peer link (0/1 = single "
     "socket; capped at 16; autotune may lower the active count)",
     native=True)
_var("HOROVOD_SHM_DIR", "str", "",
     "Per-job shared-memory namespace for intra-host rings (provisioned "
     "and swept by hvdrun; empty disables the shm backend)", native=True)
_var("HOROVOD_SHM_SLOTS", "int", 16,
     "Slots per shm ring direction (min 2)", native=True)
_var("HOROVOD_SHM_SLOT_BYTES", "int", 1024 * 1024,
     "Payload bytes per shm ring slot (min 4096)", native=True)
_var("HOROVOD_SHM_GRANULE_BYTES", "int", 0,
     "Shm push granule; 0 = whole-slot pushes (autotune may override)",
     native=True)
_var("HOROVOD_TRANSPORT_CODECS", "str", "",
     "Per-link-level codec overrides, e.g. 'cross:fp16,local:none' — "
     "cross-host traffic may compress harder than intra-host shm")
_var("HOROVOD_TRANSPORT_CHECKSUM", "str", "auto",
     "CRC32C wire integrity on data-plane frames and shm slots: "
     "auto (on) | on | off (off restores the unframed fast path)",
     native=True)
_var("HOROVOD_LINK_RETRIES", "int", 4,
     "Bounded retransmits per corrupted frame offset before the link "
     "fails hard instead of looping", native=True)
_var("HOROVOD_SHM_STALL_MS", "int", 5000,
     "Shm ring progress silence past this degrades the link to the "
     "socket backend mid-job", native=True)
_var("HOROVOD_LINK_PROBE_SECONDS", "float", 30.0,
     "Seconds a degraded link waits before probing a rebuild of its "
     "preferred backend", native=True)

# ---------------------------------------------------------------------------
# Autotuner
# ---------------------------------------------------------------------------
_var("HOROVOD_AUTOTUNE", "bool", False,
     "1 enables the online Bayesian autotuner", native=True)
_var("HOROVOD_AUTOTUNE_LOG", "str", None,
     "CSV trace path for autotune trials (phase column: "
     "explore/pin/reopen)", native=True)
_var("HOROVOD_AUTOTUNE_WARMUP_SAMPLES", "int", 3,
     "Discarded warm-up samples before scoring starts", native=True)
_var("HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE", "int", 10,
     "Coordination cycles folded into one autotune sample", native=True)
_var("HOROVOD_AUTOTUNE_SAMPLES", "int", 5,
     "Samples per Bayesian trial", native=True)
_var("HOROVOD_AUTOTUNE_BAYES_TRIALS", "int", 20,
     "Bayesian trials before pinning the best configuration",
     native=True)
_var("HOROVOD_AUTOTUNE_DRIFT_RATIO", "float", 0.5,
     "Monitored-score ratio vs the pin anchor that re-opens exploration",
     native=True)
_var("HOROVOD_AUTOTUNE_DRIFT_WINDOWS", "int", 2,
     "Consecutive drifted monitoring windows required to re-open",
     native=True)

# ---------------------------------------------------------------------------
# Telemetry / timeline
# ---------------------------------------------------------------------------
_var("HOROVOD_METRICS", "bool", False,
     "1 turns metric collection on without any export path")
_var("HOROVOD_METRICS_PORT", "int", None,
     "Prometheus scrape port base (per-rank = base + local_rank; 0 = "
     "ephemeral)")
_var("HOROVOD_METRICS_FILE", "str", None,
     "Per-rank at-exit JSON dump path; under hvdrun also the merged "
     "summary")
_var("HOROVOD_METRICS_RPC", "str", None,
     "launcher host:port the at-exit snapshot is pushed to (set by "
     "hvdrun)")
_var("HOROVOD_EAGER_TIMELINE", "str", None,
     "Chrome-tracing JSON path for the eager-plane timeline")
_var("HOROVOD_TRACE", "bool", False,
     "1 turns cross-rank distributed tracing on (set by hvdrun --trace)",
     native=True)
_var("HOROVOD_TRACE_DIR", "str", None,
     "Directory for the per-rank span-log file fallback "
     "(spans.rank<k>.json)")
_var("HOROVOD_TRACE_RPC", "str", None,
     "launcher host:port span documents are pushed to (set by hvdrun)")
_var("HOROVOD_TRACE_SAMPLE", "int", 1,
     "Trace 1-in-N collective occurrences (1 = every one); pure in the "
     "occurrence index, so sampling stays rank-consistent", native=True)
_var("HOROVOD_TRACE_BUFFER", "int", 65536,
     "Per-rank span buffer capacity; overflow drops spans and counts "
     "hvd_trace_spans_dropped_total", native=True)
_var("HOROVOD_TIMELINE", "str", "",
     "Native coordinator timeline path (rank 0)", native=True)
_var("HOROVOD_TIMELINE_MARK_CYCLES", "bool", False,
     "1 adds per-cycle markers to the native timeline", native=True)
_var("HOROVOD_LOG_LEVEL", "str", "warning",
     "Log severity: trace|debug|info|warning|error", native=True)
_var("HOROVOD_LOG_HIDE_TIME", "bool", False,
     "1 strips timestamps from log lines (stable test output)",
     native=True)

# ---------------------------------------------------------------------------
# Resilience / elastic / fleet
# ---------------------------------------------------------------------------
_var("HOROVOD_FAULT_SPEC", "str", None,
     "Deterministic chaos injection spec "
     "(rank=,site=,after=,kind=[,attempt=]); site=transport kinds are "
     "consumed natively by the data plane", native=True)
_var("HOROVOD_STEP_GUARD", "str", "off",
     "In-graph NaN/Inf step-guard policy: off|skip|rollback|abort")
_var("HOROVOD_GUARD_NAN_BURST", "int", 1,
     "Consecutive bad steps before the guard restores last-known-good")
_var("HOROVOD_LKG_INTERVAL", "int", 1,
     "Steps between last-known-good snapshot commits")
_var("HOROVOD_SENTINEL_INTERVAL", "int", 0,
     "Steps between divergence-sentinel digest checks; 0 disables")
_var("HOROVOD_SPILL_DIR", "str", None,
     "Host-local scratch dir for warm-restart peer spills (provisioned "
     "by hvdrun)")
_var("HOROVOD_SPILL_INTERVAL", "int", 1,
     "LKG commits between peer-spill writes")
_var("HOROVOD_ELASTIC_BATCH_POLICY", "str", "lr_scale",
     "World-size-change continuity policy: lr_scale|accumulate")
_var("HOROVOD_ELASTIC_PREV_SIZE", "int", None,
     "Previous world size injected by the launcher across an elastic "
     "restart")
_var("HOROVOD_RESTART_ATTEMPT", "int", 0,
     "Elastic attempt counter injected by the launcher", native=True)
_var("HOROVOD_ON_RANK_FAILURE", "str", "restart",
     "Rank-death policy: restart (today's elastic relaunch), shrink "
     "(survivors reform the world in-process), shrink-then-restart "
     "(fall back to relaunch if reformation fails or the world would "
     "drop below --min-np)", native=True)
_var("HOROVOD_WORLD_EPOCH", "int", 0,
     "Membership epoch, bumped by the launcher once per in-process "
     "reformation; stale reformation specs are discarded against it",
     native=True)
_var("HOROVOD_REFORM_TIMEOUT", "float", 60.0,
     "Seconds a survivor waits for the launcher's reformation spec "
     "before falling back to the restart path")
_var("HOROVOD_TERMINATE_GRACE_SECONDS", "float", 30.0,
     "Grace between SIGTERM and SIGKILL when tearing ranks down")
_var("HOROVOD_HEALTH_RPC", "str", None,
     "launcher host:port of the heartbeat health plane (set by hvdrun)")
_var("HOROVOD_HEARTBEAT_INTERVAL", "float", 2.0,
     "Rank-side heartbeat push cadence; unset disables the health plane")
_var("HOROVOD_HEARTBEAT_DEADLINE", "float", None,
     "Silence past this marks a rank dead (default 5x the interval)")
_var("HOROVOD_HANG_DEADLINE", "float", 0.0,
     "Step-progress stall past this marks a rank hung; 0 disables")
_var("HOROVOD_FLEET_JOB", "str", None,
     "Job name injected by the fleet controller (labels metric exports)")

# ---------------------------------------------------------------------------
# Coordination plane (horovod_tpu/coordination.py, docs/control_plane.md)
# ---------------------------------------------------------------------------
_var("HOROVOD_COORD_TREE", "bool", False,
     "1 coordinates through the two-level host/leader tree instead of "
     "the flat rank-0 star (O(log N) control fan-in)", native=True)
_var("HOROVOD_COORD_EPOCH", "int", 0,
     "Coordinator lease epoch, bumped by the launcher on each "
     "re-election; stale-epoch control messages are discarded",
     native=True)
_var("HOROVOD_COORD_RANK", "int", 0,
     "Global rank currently holding the coordinator lease (injected by "
     "the launcher after failover)", native=True)
_var("HOROVOD_COORD_ELECTIONS", "int", 0,
     "Coordinator elections so far this job (launcher-injected; "
     "surfaces in stall reports and hvd_coord_elections_total)",
     native=True)
_var("HOROVOD_COORD_LEASE_SECONDS", "float", 10.0,
     "Coordinator lease term: heartbeats renew it, expiry triggers the "
     "deterministic re-election of the lowest healthy leader host")
_var("HOROVOD_COORD_MSG_RETRIES", "int", 4,
     "Bounded retransmits per control message (jittered exponential "
     "backoff between attempts)")
_var("HOROVOD_COORD_MSG_DEADLINE", "float", 10.0,
     "Total per-control-message deadline across all retransmits")
_var("HOROVOD_PARTITION_GRACE_SECONDS", "float", 30.0,
     "Launcher silence past this fences the rank (exit 75) as the "
     "partitioned side rather than a re-election trigger")
_var("HOROVOD_RPC_CONNECT_DEADLINE", "float", 60.0,
     "Total cap across all connect_with_retry dials; per-dial retries "
     "alone could otherwise stretch unbounded under chaos")

# ---------------------------------------------------------------------------
# Kernels / frameworks / misc knobs
# ---------------------------------------------------------------------------
_var("HOROVOD_FLASH_INTERPRET", "bool", False,
     "1 runs the flash-attention Pallas kernel in interpret mode")
_var("HOROVOD_FLASH_AUTO_MIN_T", "int", 1024,
     "Sequence length above which attention='auto' picks the flash "
     "kernel")
_var("HOROVOD_FUSED_STEM_INTERPRET", "bool", False,
     "1 runs the fused conv-stem Pallas kernel in interpret mode")
_var("HOROVOD_TF1_ASYNC", "bool", False,
     "1 enables TF1-session async collectives with pruned-sync reaping")
_var("HOROVOD_TF_SYNC_COLLECTIVES", "bool", False,
     "1 forces synchronous execution of the TF binding's collectives")
_var("HOROVOD_HIER_GATE_DIR", "str", None,
     "Scratch dir handshake for the np=4 hierarchical CI gate "
     "(tests/distributed/hierarchical_np4.py only)")

# ---------------------------------------------------------------------------
# Serving plane (horovod_tpu/serving/, docs/serving.md)
# ---------------------------------------------------------------------------
_var("HOROVOD_SERVING_MAX_BATCH", "int", 8,
     "Continuous-batching cap: max sequences per replica decode step")
_var("HOROVOD_SERVING_QUOTA", "int", 64,
     "Default per-tenant quota (queued + in-flight requests) when the "
     "TenantConfig leaves it unset")
_var("HOROVOD_SERVING_SLO_MS", "float", 0.0,
     "Default per-tenant SLO for admission control: reject when the "
     "estimated queue wait exceeds this; 0 disables")
_var("HOROVOD_SERVING_STATS", "str", None,
     "Path where the router publishes its stats snapshot (injected by "
     "the fleet controller for type=serving jobs; drives autoscaling)")
_var("HOROVOD_SERVING_STATS_INTERVAL", "float", 1.0,
     "Seconds between router stats-file publishes in Router.serve")
_var("HOROVOD_SERVING_GATE_DIR", "str", None,
     "Scratch dir handshake for the serving CI gates "
     "(tests/distributed/serving_*.py only)")


# ---------------------------------------------------------------------------
# Typed accessors: the read path basics.py / runner/ / native/runtime.py
# use.  Reading an unregistered name raises — the runtime complement of
# the hvdlint env-registry rule.
# ---------------------------------------------------------------------------

_UNSET = object()


class UnknownEnvVar(KeyError):
    """Raised when code reads a HOROVOD_* name absent from REGISTRY."""


def _entry(name: str) -> EnvVar:
    try:
        return REGISTRY[name]
    except KeyError:
        raise UnknownEnvVar(
            f"{name} is not in the horovod_tpu.config registry; add an "
            f"entry (python -m tools.hvdlint enforces this)") from None


def env_raw(name: str) -> Optional[str]:
    """The raw environment string, or None when unset (registered names
    only)."""
    _entry(name)
    return os.environ.get(name)


def env_str(name: str, default: Any = _UNSET) -> Any:
    e = _entry(name)
    v = os.environ.get(name)
    return (e.default if default is _UNSET else default) if v is None else v


def env_int(name: str, default: Any = _UNSET) -> Any:
    e = _entry(name)
    v = os.environ.get(name)
    if v is None or v.strip() == "":
        return e.default if default is _UNSET else default
    return int(v)


def env_float(name: str, default: Any = _UNSET) -> Any:
    e = _entry(name)
    v = os.environ.get(name)
    if v is None or v.strip() == "":
        return e.default if default is _UNSET else default
    return float(v)


def env_bool(name: str, default: Any = _UNSET) -> Any:
    """Mirror of the native EnvBool: unset/empty -> default, then "0"
    and case-insensitive "false" are False, anything else True."""
    e = _entry(name)
    v = os.environ.get(name)
    if v is None or v.strip() == "":
        return e.default if default is _UNSET else default
    return v.strip() not in ("0",) and v.strip().lower() != "false"


def describe() -> str:
    """The registry as a fixed-width reference table (also the
    ``python -m horovod_tpu.config`` output)."""
    rows = [(e.name, e.type, "native" if e.native else "py",
             "" if e.default is None else repr(e.default), e.doc)
            for e in sorted(REGISTRY.values())]
    w0 = max(len(r[0]) for r in rows)
    w3 = max(len(r[3]) for r in rows)
    out = []
    for name, type_, scope, dflt, doc in rows:
        out.append(f"{name:<{w0}}  {type_:<5} {scope:<6} "
                   f"{dflt:<{w3}}  {doc}")
    return "\n".join(out)


if __name__ == "__main__":
    print(describe())

"""horovod_tpu — a TPU-native distributed training framework.

A ground-up rebuild of the capabilities of Horovod (reference:
``/root/reference``, see ``SURVEY.md``) designed for TPU hardware:

* Collectives (``allreduce`` / ``allgather`` / ``broadcast`` /
  ``reducescatter`` / ``alltoall``) execute as XLA collectives
  (``lax.psum`` / ``lax.all_gather`` / ``lax.ppermute`` / ``lax.all_to_all``)
  over a :class:`jax.sharding.Mesh` spanning ICI (intra-slice) and DCN
  (cross-slice) axes — not NCCL/MPI rings.
* Under ``jit`` / ``shard_map`` the coordination problem Horovod solves with a
  C++ background thread (reference ``horovod/common/operations.cc:303-498``)
  disappears: SPMD guarantees every device issues the same collectives in the
  same order.  The asynchronous, name-negotiated eager path (for op-by-op
  frameworks like PyTorch) survives as a native C++ runtime with a TCP
  controller — see ``horovod_tpu/native``.
* The user-facing API keeps Horovod's contract
  (reference ``horovod/tensorflow/__init__.py``, ``horovod/torch/__init__.py``):
  ``init``/``rank``/``size``/``local_rank``/``local_size``,
  named collectives, ``DistributedOptimizer``, ``broadcast_parameters``,
  ``Compression`` — so a Horovod user can switch with minimal edits.

Quick start (single host, all local TPU chips)::

    import horovod_tpu as hvd
    hvd.init()
    mesh = hvd.mesh()                       # 1-D 'data' mesh over all chips
    step = hvd.make_training_step(loss_fn, optimizer, mesh)
"""

from horovod_tpu import _jax_compat  # noqa: F401  (must run before SPMD imports)
from horovod_tpu import basics as _basics
from horovod_tpu.basics import (
    init,
    shutdown,
    is_initialized,
    rank,
    size,
    local_rank,
    local_size,
    cross_rank,
    cross_size,
    world_epoch,
    num_devices,
    local_devices,
    mesh,
    topology,
    Topology,
    coordinator,
    CoordinatorInfo,
    mpi_threads_supported,
    mpi_built,
    mpi_enabled,
    gloo_built,
    gloo_enabled,
    nccl_built,
    ddl_built,
    mlsl_built,
    tpu_built,
    tpu_enabled,
)
from horovod_tpu.ops.collective import (
    Average,
    Sum,
    Adasum,
    Min,
    Max,
    allreduce,
    allreduce_,
    allreduce_async,
    allreduce_async_,
    grouped_allreduce,
    allgather,
    allgather_async,
    allgather_object,
    broadcast,
    broadcast_,
    broadcast_async,
    broadcast_async_,
    broadcast_object,
    reducescatter,
    alltoall,
    alltoall_ragged,
    synchronize,
    poll,
    join,
    barrier,
    ProcessSet,
    add_process_set,
    global_process_set,
)
from horovod_tpu.ops.compression import Compression, resolve_codec
from horovod_tpu import checkpoint  # noqa: F401  (hvd.checkpoint.save/restore)
from horovod_tpu import telemetry  # noqa: F401  (hvd.telemetry.counter/...)
from horovod_tpu.telemetry import metrics_snapshot
from horovod_tpu.parallel.data import (
    DistributedOptimizer,
    DistributedGradientTape,
    make_training_step,
    broadcast_parameters,
    broadcast_optimizer_state,
    broadcast_variables,
)
from horovod_tpu.parallel.data import (
    elastic_shard,
    elastic_continuity,
    elastic_transition,
)
from horovod_tpu.parallel.zero import sharded_optimizer, reshard_state
from horovod_tpu import resilience  # noqa: F401  (hvd.resilience.StepGuard/...)
from horovod_tpu.resilience import StepGuard, warm_restore, report_progress

# Importing the `horovod_tpu.topology` SUBMODULE (here or anywhere) sets the
# package attribute "topology" to the module, shadowing the hvd.topology()
# accessor imported above.  Import the submodule once, then rebind the
# accessor LAST: later `from horovod_tpu.topology import ...` statements
# resolve through sys.modules and do not re-set the attribute.
from horovod_tpu import topology as _topology_mod  # noqa: F401
from horovod_tpu.basics import topology  # noqa: F811

__version__ = "0.5.0"

__all__ = [
    # lifecycle / topology
    "init", "shutdown", "is_initialized",
    "rank", "size", "local_rank", "local_size", "cross_rank", "cross_size",
    "world_epoch",
    "num_devices", "local_devices", "mesh", "topology", "Topology",
    "coordinator", "CoordinatorInfo",
    "mpi_threads_supported",
    "mpi_built", "mpi_enabled", "gloo_built", "gloo_enabled",
    "nccl_built", "ddl_built", "mlsl_built", "tpu_built", "tpu_enabled",
    # collectives
    "Average", "Sum", "Adasum", "Min", "Max",
    "allreduce", "allreduce_", "allreduce_async", "allreduce_async_",
    "grouped_allreduce",
    "allgather", "allgather_async", "allgather_object",
    "broadcast", "broadcast_", "broadcast_async", "broadcast_async_",
    "broadcast_object",
    "reducescatter", "alltoall", "alltoall_ragged",
    "synchronize", "poll", "join",
    # observability
    "telemetry", "metrics_snapshot",
    # training
    "Compression", "resolve_codec", "checkpoint",
    "DistributedOptimizer", "DistributedGradientTape", "make_training_step",
    "sharded_optimizer", "reshard_state",
    "broadcast_parameters", "broadcast_optimizer_state", "broadcast_variables",
    # elastic continuity
    "elastic_shard", "elastic_continuity", "elastic_transition",
    # resilience
    "resilience", "StepGuard", "warm_restore", "report_progress",
]

"""Replica worker: one model instance served over the RPC plane.

A replica is the unit the fleet autoscales — one rank of a
``type=serving`` fleet job, holding one copy of the weights and
answering token-level ``decode`` requests from the router
(:mod:`horovod_tpu.serving.router`).  Three properties carry the whole
serving story:

* **Authenticated transport** — the worker attaches to the PR-1 RPC
  plane (:class:`horovod_tpu.runner.rpc.RpcServer`) under the per-job
  HMAC secret, with ``serialize=False`` so ``ping``/``stats`` probes
  answer while a decode step runs; weight swaps take the worker's own
  lock instead.
* **Hot weight updates** — :func:`broadcast_weights` distributes a new
  weight generation through the eager broadcast plane (every rank of
  the serving job calls it collectively; non-root ranks block in the
  collective while their RPC threads keep serving).  The update is
  *staged* (:meth:`ReplicaWorker.stage_update`) and applied atomically
  at the next decode-step boundary — never mid-step, never with a
  replica restart, so no in-flight request is dropped.
* **Chaos surface** — every decode step polls
  :func:`horovod_tpu.faults.crash_replica` (site ``serving``, kind
  ``replica_crash``); a firing kills the replica mid-request exactly
  like a real crash (the in-flight RPC gets no response), which is what
  the router's idempotent retry path is tested against.

Weights load via :func:`horovod_tpu.checkpoint.load_local` — the
non-collective local-disk half of the checkpoint plane — so a replica
can come up from the same directory a concurrently-training job
checkpoints into.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional

import numpy as np

from horovod_tpu import faults, telemetry
from horovod_tpu.serving.model import DecodeModel

DECODE_TIME_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                       0.1, 0.25, 0.5, 1.0, 2.5)


class ReplicaCrashed(RuntimeError):
    """Raised inside the RPC handler when a ``replica_crash`` chaos rule
    fires: the connection closes without a response, so the router sees
    exactly what a real crash looks like."""


class ReplicaWorker:
    """One serving replica: model + staged-update slot + RPC handler.

    ``step_time`` adds a simulated per-step cost (benchmark rigs);
    ``on_crash`` runs after a chaos crash marked the worker dead
    (standalone processes pass ``os._exit``; embedded workers leave the
    default, which also shuts down an attached RPC server).
    """

    def __init__(self, model: DecodeModel, *, replica_id: str = "r0",
                 step_time: float = 0.0,
                 on_crash: Optional[Callable[[], None]] = None):
        self.model = model
        self.replica_id = replica_id
        self.step_time = float(step_time)
        self._on_crash = on_crash
        self._lock = threading.Lock()
        self._pending = None          # staged (weights, generation)
        self._decode_steps = 0
        self._dead = False
        self._server = None

    # -- hot updates -------------------------------------------------------

    def stage_update(self, weights, generation: int) -> int:
        """Stage a new weight generation; it becomes live at the next
        decode-step boundary (or immediately if the worker is idle
        between steps).  Returns the staged generation."""
        gen = int(generation)
        with self._lock:
            self._pending = (np.asarray(weights, np.float32), gen)
        if telemetry.enabled():
            telemetry.counter(
                "hvd_serving_weight_updates_total",
                "Hot weight updates staged on this replica").inc()
        return gen

    def _apply_pending_locked(self) -> None:
        if self._pending is None:
            return
        weights, gen = self._pending
        self._pending = None
        self.model.set_weights(weights, gen)
        if telemetry.enabled():
            telemetry.gauge(
                "hvd_serving_weight_generation",
                "Live weight generation on this replica").set(float(gen))

    # -- decode ------------------------------------------------------------

    def _crash(self) -> None:
        with self._lock:
            self._dead = True
        if telemetry.enabled():
            telemetry.counter(
                "hvd_serving_replica_crashes_total",
                "Chaos replica_crash firings on this replica").inc()
        if self._on_crash is not None:
            self._on_crash()
        elif self._server is not None:
            # Shut the listener down from a helper thread: shutdown()
            # joins the serve_forever loop, and this may run on one of
            # its request threads.
            srv = self._server
            threading.Thread(target=srv.shutdown, daemon=True).start()
        raise ReplicaCrashed(f"replica {self.replica_id} chaos crash")

    def decode(self, seqs) -> Dict[str, Any]:
        """One continuous-batching step: ``seqs`` is a list of
        ``(request_id, last_token, position)``; returns per-request next
        tokens.  Pending weight updates apply here, at the boundary."""
        if faults.crash_replica():
            self._crash()
        with self._lock:
            if self._dead:
                raise ReplicaCrashed(
                    f"replica {self.replica_id} is dead")
            self._apply_pending_locked()
            t0 = telemetry.clock()
            tokens = self.model.decode_step(
                [(tok, pos) for _, tok, pos in seqs])
            if self.step_time:
                time.sleep(self.step_time)
            self._decode_steps += 1
            gen = self.model.generation
        if telemetry.enabled():
            telemetry.counter(
                "hvd_serving_decode_steps_total",
                "Token-level decode steps executed by this replica").inc()
            telemetry.histogram(
                "hvd_serving_decode_seconds",
                "Wall time of one batched decode step",
                bounds=DECODE_TIME_BUCKETS).observe(
                telemetry.clock() - t0)
        sp = telemetry.spans()
        if sp is not None:
            sp.event(f"serving/decode.{self.replica_id}", "decode", t0,
                     telemetry.clock())
        return {"ok": True, "generation": gen,
                "tokens": {rid: tok for (rid, _, _), tok
                           in zip(seqs, tokens)}}

    # -- RPC surface -------------------------------------------------------

    def handle(self, req: Any) -> Any:
        """RPC dispatch (request = ``{"kind": ...}``).  Kinds: ``ping``,
        ``stats``, ``decode``, ``update_weights``."""
        kind = req.get("kind") if isinstance(req, dict) else None
        if kind == "ping":
            return {"ok": True, "replica": self.replica_id,
                    "generation": self.model.generation}
        if kind == "stats":
            with self._lock:
                return {"ok": True, "replica": self.replica_id,
                        "generation": self.model.generation,
                        "decode_steps": self._decode_steps,
                        "dead": self._dead}
        if kind == "decode":
            return self.decode(req["seqs"])
        if kind == "update_weights":
            gen = self.stage_update(req["weights"], req["generation"])
            return {"ok": True, "replica": self.replica_id,
                    "generation": gen}
        return {"ok": False, "error": f"unknown kind {kind!r}"}

    def attach(self, key: bytes, bind: str = "127.0.0.1"):
        """Serve :meth:`handle` on an authenticated
        :class:`~horovod_tpu.runner.rpc.RpcServer` (concurrent handlers:
        probes must answer while a decode runs).  Returns the server."""
        from horovod_tpu.runner import rpc
        self._server = rpc.RpcServer(key, self.handle, bind=bind,
                                     serialize=False)
        return self._server


def broadcast_weights(weights, generation: int, root_rank: int = 0,
                      name: str = "hvd.serving.weights"):
    """Distribute a weight generation through the broadcast plane.

    Collective: EVERY rank of the serving job calls this with
    same-shaped ``weights`` (non-root ranks pass their current copy and
    receive the root's).  Returns ``(weights, generation)`` as seen by
    ``root_rank`` — stage the result with
    :meth:`ReplicaWorker.stage_update`.  The collective doubles as the
    synchronization barrier of the hot-update protocol: non-root ranks
    may sit in it while their RPC threads keep serving decode steps.
    """
    import horovod_tpu as hvd
    sp = telemetry.spans()
    t0 = telemetry.clock() if sp is not None else 0.0
    gen = np.asarray([int(generation)], np.int64)
    gen = np.asarray(hvd.broadcast(gen, root_rank=root_rank,
                                   name=f"{name}.gen"))
    live_gen = int(gen[0])
    out = np.asarray(hvd.broadcast(
        np.asarray(weights, np.float32), root_rank=root_rank,
        name=f"{name}.g{live_gen}"))
    if sp is not None:
        # Umbrella span over the hot-update protocol; the per-collective
        # spans of the two broadcasts nest under it in the merged trace.
        sp.event(f"serving/weights.g{live_gen}", "broadcast", t0,
                 telemetry.clock(), int(out.nbytes))
    return out, live_gen


def load_replica_model(ckpt_dir: str, weights_template=None):
    """Build a :class:`~horovod_tpu.serving.model.ToyModel` from the
    newest intact checkpoint in ``ckpt_dir`` (local read, no collective
    — see :func:`horovod_tpu.checkpoint.load_local`); falls back to the
    template/seed weights when no checkpoint exists.  The checkpoint
    step becomes the starting weight generation, so continuous
    deployment from a training job is monotonic."""
    from horovod_tpu import checkpoint
    from horovod_tpu.serving.model import ToyModel
    if weights_template is None:
        weights_template = np.arange(8, dtype=np.float32)
    template = {"w": np.asarray(weights_template, np.float32)}
    state, step = checkpoint.load_local(ckpt_dir, template)
    return ToyModel(state["w"], generation=0 if step is None else step)

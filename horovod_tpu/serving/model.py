"""Decode models for the serving plane.

The serving plane is model-agnostic: a replica worker drives anything
implementing the three-method :class:`DecodeModel` contract below.
:class:`ToyModel` is the contract's reference implementation — a
deterministic next-token function of (previous token, position, weight
checksum) — chosen so every serving test can assert exact tokens AND
observe a hot weight update: changing the weight generation visibly
changes every subsequent token, which is how the np=2 CI gate proves an
update landed mid-stream without dropping a request.

Real deployments subclass :class:`DecodeModel` with a jitted forward
pass; the router/replica layers never look inside ``decode_step``.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np


class DecodeModel:
    """Contract a serving replica drives.

    ``decode_step`` consumes one ``(last_token, position)`` pair per
    live sequence and returns the next token for each — one token-level
    step of the whole running batch, the granularity continuous
    batching joins and leaves at (Orca, OSDI '22).

    ``weights``/``generation`` expose the hot-update surface: the
    replica swaps both atomically at a step boundary, never mid-step.
    """

    #: Monotonic deployment counter; bumped by every hot weight update.
    generation: int = 0

    def decode_step(self, batch: Sequence[Tuple[int, int]]) -> List[int]:
        raise NotImplementedError

    def set_weights(self, weights, generation: int) -> None:
        raise NotImplementedError

    def get_weights(self):
        raise NotImplementedError


class ToyModel(DecodeModel):
    """Deterministic decode: ``next = (31*token + 7*pos + checksum(w))
    % vocab``.

    Properties the serving tests lean on:

    * fully deterministic — a retried step on another replica yields the
      SAME token, which is what makes router-side crash retry idempotent;
    * generation-sensitive — the weight checksum feeds every token, so a
      hot update flips the stream observably;
    * stateless across steps — a sequence is just its last token and
      position, so it can migrate between replicas freely.
    """

    VOCAB = 50257

    def __init__(self, weights=None, generation: int = 0):
        if weights is None:
            weights = np.arange(8, dtype=np.float32)
        self._weights = np.asarray(weights, np.float32)
        self.generation = int(generation)

    def _checksum(self) -> int:
        # Integer-valued float32 sums are exact at this scale, so the
        # checksum is bit-stable across replicas and retries.
        return int(abs(float(self._weights.sum()))) % self.VOCAB

    def decode_step(self, batch: Sequence[Tuple[int, int]]) -> List[int]:
        c = self._checksum()
        return [(31 * int(tok) + 7 * int(pos) + c) % self.VOCAB
                for tok, pos in batch]

    def set_weights(self, weights, generation: int) -> None:
        self._weights = np.asarray(weights, np.float32)
        self.generation = int(generation)

    def get_weights(self):
        return self._weights

"""Multi-tenant serving plane: continuous-batching inference on the
fleet with hot weight updates.

Architecture (``docs/serving.md``):

* :mod:`~horovod_tpu.serving.model` — the three-method decode contract
  replicas drive, plus the deterministic :class:`ToyModel` the CI gates
  assert exact tokens against;
* :mod:`~horovod_tpu.serving.replica` — one weight copy served over the
  authenticated RPC plane, hot weight updates staged via the broadcast
  plane and applied at decode-step boundaries (no restart, no drops);
* :mod:`~horovod_tpu.serving.router` — per-tenant queues, token-level
  continuous batching, quota/SLO admission, idempotent crash retry, and
  the stats handshake the fleet autoscaler
  (``runner/fleet.py``, job type ``serving``) scales replicas on.
"""

from horovod_tpu.serving.model import DecodeModel, ToyModel  # noqa: F401
from horovod_tpu.serving.replica import (  # noqa: F401
    ReplicaCrashed, ReplicaWorker, broadcast_weights, load_replica_model,
)
from horovod_tpu.serving.router import (  # noqa: F401
    LocalReplicaHandle, ReplicaHandle, RequestHandle, Router,
    RpcReplicaHandle, TenantConfig, stats_path_from_env,
)

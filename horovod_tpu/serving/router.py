"""Request router: multi-tenant continuous batching over replicas.

The router owns the request plane of the serving story
(``docs/serving.md``): per-tenant FIFO queues in front of a pool of
replicas, scheduled with token-level **continuous batching** (Orca,
OSDI '22) — a sequence joins a replica's running batch at any decode-
step boundary and leaves the moment it completes, so short requests
never wait for long ones and batch occupancy stays high under mixed
lengths.  One ``step()`` call is one token step across every replica;
``serve()`` loops it on a thread for deployments, tests drive it
synchronously with an injected clock.

Admission is enforced per tenant at submit time:

* **quota** — a tenant may hold at most ``quota`` requests queued +
  in flight (``HOROVOD_SERVING_QUOTA`` default); beyond that, reject
  with reason ``quota``;
* **SLO** — with ``slo_ms`` set, a request whose *estimated* queue wait
  (queue depth ahead over healthy decode slots, times the measured
  per-step EWMA) already exceeds the SLO is rejected with reason
  ``slo`` instead of being admitted to miss it.

Crash recovery: a replica whose decode fails mid-step is marked
unhealthy and every sequence it was running is re-queued at the FRONT
of its tenant queue with its token state intact.  Decode is
deterministic in (token, position, weights), so the retried step on a
healthy replica yields the same token — retry is **idempotent by
request id** (chaos-verified in ``tests/test_chaos.py``).

The router also feeds the fleet autoscaler: :meth:`Router.stats`
summarizes queue depth / p99 latency / healthy replicas, and
:meth:`Router.write_stats` publishes it atomically to the path the
fleet controller injects via ``HOROVOD_SERVING_STATS``
(``runner/fleet.py``).  Chaos: every scheduler pass polls
:func:`horovod_tpu.faults.storm_requests` (site ``serving``, kind
``request_storm``) and floods the queues with synthetic burst traffic.
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
from collections import OrderedDict, deque
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence

from horovod_tpu import config, faults, telemetry

OCCUPANCY_BUCKETS = (1, 2, 4, 8, 16, 32, 64)
LATENCY_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0)

#: Implicit tenant chaos request_storm traffic is booked under.
STORM_TENANT = "storm"


@dataclass
class TenantConfig:
    """Per-tenant admission policy.  ``quota``/``slo_ms`` left ``None``
    resolve to the ``HOROVOD_SERVING_QUOTA`` / ``HOROVOD_SERVING_SLO_MS``
    defaults at router construction."""
    name: str
    quota: Optional[int] = None
    slo_ms: Optional[float] = None


class RequestHandle:
    """What :meth:`Router.submit` returns: terminal state is exactly one
    of completed (``tokens`` full), ``rejected`` (reason string, never
    admitted) or ``dropped`` (admitted, then lost with no healthy
    replica left)."""

    def __init__(self, request_id: str, tenant: str):
        self.request_id = request_id
        self.tenant = tenant
        self.tokens: List[int] = []
        self.rejected: Optional[str] = None
        self.dropped = False
        self.done = threading.Event()

    @property
    def completed(self) -> bool:
        return self.done.is_set() and not self.dropped and \
            self.rejected is None

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self.done.wait(timeout)


class _Seq:
    """One admitted request's decode state (migrates between replicas on
    crash retry — the state IS the idempotency token)."""

    __slots__ = ("handle", "last_token", "pos", "max_new_tokens",
                 "submitted_at", "first_token_at")

    def __init__(self, handle: RequestHandle, prompt_token: int,
                 max_new_tokens: int, submitted_at: float):
        self.handle = handle
        self.last_token = int(prompt_token)
        self.pos = 0
        self.max_new_tokens = int(max_new_tokens)
        self.submitted_at = submitted_at
        self.first_token_at: Optional[float] = None


class ReplicaHandle:
    """Router-side view of one replica."""

    healthy: bool = True

    def decode(self, seqs: Sequence[tuple]) -> dict:
        raise NotImplementedError

    def update_weights(self, weights, generation: int) -> None:
        raise NotImplementedError


class LocalReplicaHandle(ReplicaHandle):
    """In-process replica (unit tests, benchmarks, single-rank jobs)."""

    def __init__(self, worker):
        self.worker = worker
        self.healthy = True

    def decode(self, seqs):
        return self.worker.decode(list(seqs))

    def update_weights(self, weights, generation):
        self.worker.stage_update(weights, generation)


class RpcReplicaHandle(ReplicaHandle):
    """Replica across the authenticated RPC plane.  ``retries=0`` on
    decode: a dead replica must surface as a failure immediately so the
    router can fail the batch over, not stall in dial backoff."""

    def __init__(self, addr: str, port: int, key: bytes,
                 timeout: float = 30.0):
        from horovod_tpu.runner import rpc
        self._rpc = rpc
        self.addr, self.port, self.key = addr, int(port), key
        self.timeout = timeout
        self.healthy = True

    def _call(self, request: dict, retries: int = 0):
        resp = self._rpc.rpc_call(self.addr, self.port, request, self.key,
                                  timeout=self.timeout, retries=retries)
        if not (isinstance(resp, dict) and resp.get("ok")):
            raise RuntimeError(f"replica {self.addr}:{self.port} "
                               f"error: {resp!r}")
        return resp

    def decode(self, seqs):
        return self._call({"kind": "decode", "seqs": list(seqs)})

    def update_weights(self, weights, generation):
        self._call({"kind": "update_weights", "weights": weights,
                    "generation": int(generation)}, retries=2)

    def ping(self) -> dict:
        return self._call({"kind": "ping"}, retries=4)


def stats_path_from_env() -> Optional[str]:
    """The autoscaler handshake path the fleet controller injected for
    this job (``HOROVOD_SERVING_STATS``), or None outside a fleet."""
    return config.env_str("HOROVOD_SERVING_STATS")


class Router:
    """See the module docstring.  ``clock`` is injectable so the unit
    suite drives whole episodes without sleeping."""

    def __init__(self, replicas: Sequence[ReplicaHandle],
                 tenants: Sequence[TenantConfig], *,
                 max_batch: Optional[int] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.replicas = list(replicas)
        if not self.replicas:
            raise ValueError("router needs at least one replica")
        self.max_batch = int(max_batch if max_batch is not None
                             else config.env_int("HOROVOD_SERVING_MAX_BATCH"))
        if self.max_batch < 1:
            raise ValueError(f"max_batch must be >= 1 (got "
                             f"{self.max_batch})")
        self._clock = clock
        self._lock = threading.RLock()
        self._tenants: Dict[str, TenantConfig] = OrderedDict()
        default_quota = config.env_int("HOROVOD_SERVING_QUOTA")
        default_slo = config.env_float("HOROVOD_SERVING_SLO_MS")
        for t in tenants:
            if t.name in self._tenants:
                raise ValueError(f"duplicate tenant {t.name!r}")
            self._tenants[t.name] = TenantConfig(
                t.name,
                quota=default_quota if t.quota is None else int(t.quota),
                slo_ms=default_slo if t.slo_ms is None
                else float(t.slo_ms))
        self._queues: Dict[str, deque] = {name: deque()
                                          for name in self._tenants}
        self._rr: List[str] = list(self._tenants)   # round-robin order
        self._assigned: List[Dict[str, _Seq]] = [
            {} for _ in self.replicas]
        self._latencies: deque = deque(maxlen=512)  # seconds, completed
        self._step_ewma = 0.0        # seconds per decode step
        self.generation = 0          # last generation pushed
        self.completed = 0
        self.dropped = 0
        self._storm_seq = 0
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- admission ---------------------------------------------------------

    def _ensure_storm_tenant(self) -> None:
        if STORM_TENANT not in self._tenants:
            self._tenants[STORM_TENANT] = TenantConfig(
                STORM_TENANT, quota=1 << 30, slo_ms=0.0)
            self._queues[STORM_TENANT] = deque()
            self._rr.append(STORM_TENANT)

    def _tenant_load(self, tenant: str) -> int:
        return len(self._queues[tenant]) + sum(
            1 for batch in self._assigned for s in batch.values()
            if s.handle.tenant == tenant)

    def _healthy(self) -> List[int]:
        return [i for i, r in enumerate(self.replicas) if r.healthy]

    def _estimated_wait_ms(self) -> float:
        slots = len(self._healthy()) * self.max_batch
        if slots <= 0 or self._step_ewma <= 0.0:
            return 0.0
        depth = sum(len(q) for q in self._queues.values())
        return (depth / slots) * self._step_ewma * 1000.0

    def submit(self, tenant: str, prompt_token: int,
               max_new_tokens: int = 8,
               request_id: Optional[str] = None) -> RequestHandle:
        """Admit (or reject) one request; never blocks on capacity."""
        with self._lock:
            if tenant not in self._tenants:
                raise KeyError(f"unknown tenant {tenant!r}")
            rid = request_id or uuid.uuid4().hex
            handle = RequestHandle(rid, tenant)
            telemetry.counter(
                "hvd_serving_requests_total",
                "Requests submitted to the router", tenant=tenant).inc()
            cfg = self._tenants[tenant]
            if not self._healthy():
                return self._reject(handle, "capacity")
            if self._tenant_load(tenant) >= cfg.quota:
                return self._reject(handle, "quota")
            if cfg.slo_ms and self._estimated_wait_ms() > cfg.slo_ms:
                return self._reject(handle, "slo")
            self._queues[tenant].append(
                _Seq(handle, prompt_token, max_new_tokens, self._clock()))
            return handle

    def _reject(self, handle: RequestHandle, reason: str) -> RequestHandle:
        handle.rejected = reason
        handle.done.set()
        telemetry.counter(
            "hvd_serving_rejects_total",
            "Requests rejected at admission",
            tenant=handle.tenant, reason=reason).inc()
        return handle

    # -- scheduling --------------------------------------------------------

    def _fill(self) -> None:
        """Continuous batching join: top every healthy replica's batch
        up to ``max_batch`` from the tenant queues, round-robin across
        tenants so no tenant monopolizes the step."""
        for idx in self._healthy():
            batch = self._assigned[idx]
            while len(batch) < self.max_batch:
                seq = self._next_queued()
                if seq is None:
                    return
                batch[seq.handle.request_id] = seq

    def _next_queued(self) -> Optional[_Seq]:
        for _ in range(len(self._rr)):
            name = self._rr.pop(0)
            self._rr.append(name)
            q = self._queues[name]
            if q:
                return q.popleft()
        return None

    def step(self) -> int:
        """One token-level step across every replica; returns the number
        of tokens produced.  Sequences join before the step and leave
        the moment they complete — the continuous-batching boundary."""
        with self._lock:
            storm = faults.storm_requests()
            if storm:
                self._ensure_storm_tenant()
                telemetry.counter(
                    "hvd_serving_storm_requests_total",
                    "Synthetic requests injected by chaos "
                    "request_storm").inc(storm)
                for i in range(storm):
                    self._storm_seq += 1
                    self.submit(STORM_TENANT, prompt_token=i,
                                max_new_tokens=4,
                                request_id=f"storm-{self._storm_seq}")
            self._fill()
            produced = 0
            for idx in self._healthy():
                batch = self._assigned[idx]
                if not batch:
                    continue
                seqs = [(rid, batch[rid].last_token, batch[rid].pos)
                        for rid in sorted(batch)]
                t0 = self._clock()
                # Request-scoped trace span: the router->replica leg of a
                # decode step, on the real monotonic clock (the injectable
                # self._clock may be synthetic in tests).
                sp = telemetry.spans()
                t0m = time.monotonic() if sp is not None else 0.0
                try:
                    resp = self.replicas[idx].decode(seqs)
                except Exception as e:                # noqa: BLE001
                    self._failover(idx, e)
                    continue
                if sp is not None:
                    sp.event(f"serving/route.replica{idx}", "route",
                             t0m, time.monotonic())
                dt = max(0.0, self._clock() - t0)
                self._step_ewma = dt if self._step_ewma == 0.0 else \
                    0.8 * self._step_ewma + 0.2 * dt
                telemetry.histogram(
                    "hvd_serving_batch_occupancy",
                    "Sequences per executed decode step",
                    bounds=OCCUPANCY_BUCKETS).observe(float(len(seqs)))
                produced += self._advance(idx, resp["tokens"])
            self._update_gauges()
            return produced

    def _advance(self, idx: int, tokens: Dict[str, int]) -> int:
        batch = self._assigned[idx]
        now = self._clock()
        n = 0
        for rid, tok in tokens.items():
            seq = batch.get(rid)
            if seq is None:
                continue
            seq.last_token = int(tok)
            seq.pos += 1
            seq.handle.tokens.append(int(tok))
            n += 1
            tenant = seq.handle.tenant
            telemetry.counter(
                "hvd_serving_tokens_total",
                "Tokens generated", tenant=tenant).inc()
            if seq.first_token_at is None:
                seq.first_token_at = now
                telemetry.histogram(
                    "hvd_serving_ttft_seconds",
                    "Submit-to-first-token latency",
                    bounds=LATENCY_BUCKETS, tenant=tenant).observe(
                    max(0.0, now - seq.submitted_at))
            if len(seq.handle.tokens) >= seq.max_new_tokens:
                del batch[rid]
                self.completed += 1
                latency = max(0.0, now - seq.submitted_at)
                self._latencies.append(latency)
                telemetry.counter(
                    "hvd_serving_completed_total",
                    "Requests completed", tenant=tenant).inc()
                telemetry.histogram(
                    "hvd_serving_latency_seconds",
                    "Submit-to-completion latency",
                    bounds=LATENCY_BUCKETS, tenant=tenant).observe(latency)
                sp = telemetry.spans()
                if sp is not None:
                    # End-to-end request span, unique by request id.  The
                    # end sits on the monotonic clock; the start is backed
                    # off by the measured latency (exact whenever
                    # self._clock IS time.monotonic, the production case).
                    now_m = time.monotonic()
                    sp.event(f"request/{rid}", "route", now_m - latency,
                             now_m)
                seq.handle.done.set()
        return n

    def _failover(self, idx: int, error: Exception) -> None:
        """A replica's decode failed mid-step: mark it unhealthy and
        re-queue its whole running batch, token state intact, at the
        front of each tenant queue.  Deterministic decode makes the
        retried step idempotent by request id."""
        self.replicas[idx].healthy = False
        batch = self._assigned[idx]
        retried = list(batch.values())
        batch.clear()
        if retried:
            telemetry.counter(
                "hvd_serving_retries_total",
                "In-flight requests re-queued after a replica "
                "failure").inc(len(retried))
        if not self._healthy():
            for seq in retried:
                self._drop(seq)
            for q in self._queues.values():
                while q:
                    self._drop(q.popleft())
            return
        for seq in reversed(retried):
            self._queues[seq.handle.tenant].appendleft(seq)

    def _drop(self, seq: _Seq) -> None:
        self.dropped += 1
        seq.handle.dropped = True
        telemetry.counter(
            "hvd_serving_dropped_total",
            "Admitted requests lost with no healthy replica left",
            tenant=seq.handle.tenant).inc()
        seq.handle.done.set()

    def _update_gauges(self) -> None:
        if not telemetry.enabled():
            return
        for name, q in self._queues.items():
            telemetry.gauge(
                "hvd_serving_queue_depth",
                "Requests queued per tenant", tenant=name).set(
                float(len(q)))
        telemetry.gauge(
            "hvd_serving_inflight",
            "Sequences currently assigned to replica batches").set(
            float(sum(len(b) for b in self._assigned)))
        telemetry.gauge(
            "hvd_serving_replicas_healthy",
            "Replicas the router considers healthy").set(
            float(len(self._healthy())))

    # -- hot updates -------------------------------------------------------

    def push_weights(self, weights, generation: int) -> int:
        """Stage a weight generation on every healthy replica (applied
        at each replica's next step boundary — zero requests dropped).
        Returns the number of replicas that accepted the update."""
        pushed = 0
        with self._lock:
            targets = self._healthy()
        for idx in targets:
            try:
                self.replicas[idx].update_weights(weights,
                                                  int(generation))
                pushed += 1
            except Exception as e:                    # noqa: BLE001
                with self._lock:
                    self._failover(idx, e)
        self.generation = int(generation)
        return pushed

    # -- draining / serving ------------------------------------------------

    def pending(self) -> int:
        with self._lock:
            return sum(len(q) for q in self._queues.values()) + \
                sum(len(b) for b in self._assigned)

    def drain(self, max_steps: int = 100000) -> None:
        """Step until nothing is queued or in flight (tests/benchmarks)."""
        for _ in range(max_steps):
            if not self.pending():
                return
            self.step()
        raise RuntimeError(f"router did not drain in {max_steps} steps")

    def serve(self, stats_path: Optional[str] = None,
              idle_sleep: float = 0.005) -> None:
        """Run the scheduler on a background thread until
        :meth:`close`; with ``stats_path`` (or the fleet-injected
        ``HOROVOD_SERVING_STATS``), publish :meth:`stats` every
        ``HOROVOD_SERVING_STATS_INTERVAL`` seconds for the autoscaler."""
        path = stats_path or stats_path_from_env()
        interval = config.env_float("HOROVOD_SERVING_STATS_INTERVAL")
        self._stop.clear()

        def loop():
            last_stats = 0.0
            while not self._stop.is_set():
                if not self.step():
                    time.sleep(idle_sleep)
                if path and time.monotonic() - last_stats >= interval:
                    last_stats = time.monotonic()
                    self.write_stats(path)
            if path:
                self.write_stats(path)

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="hvd-serving-router")
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    # -- autoscaler handshake ----------------------------------------------

    def p99_ms(self) -> float:
        with self._lock:
            lat = sorted(self._latencies)
        if not lat:
            return 0.0
        return lat[min(len(lat) - 1, int(0.99 * len(lat)))] * 1000.0

    def stats(self) -> dict:
        """The queue-pressure summary the fleet autoscaler scales on
        (schema: ``horovod_tpu.serving.stats.v1``)."""
        with self._lock:
            depth = sum(len(q) for q in self._queues.values())
            inflight = sum(len(b) for b in self._assigned)
            slos = [t.slo_ms for t in self._tenants.values() if t.slo_ms]
        return {
            "schema": "horovod_tpu.serving.stats.v1",
            "queue_depth": depth,
            "inflight": inflight,
            "healthy_replicas": len(self._healthy()),
            "p99_ms": round(self.p99_ms(), 3),
            "slo_ms": min(slos) if slos else 0.0,
            "completed": self.completed,
            "dropped": self.dropped,
        }

    def write_stats(self, path: str) -> None:
        """Atomic publish (write-then-rename): the autoscaler polling
        mid-write must see the previous snapshot, never a torn one."""
        doc = self.stats()
        dirname = os.path.dirname(os.path.abspath(path))
        os.makedirs(dirname, exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)

"""Shared Keras integration impl (reference ``horovod/_keras/__init__.py``).

The reference targets Keras 2, whose optimizers expose ``get_gradients``;
it overrides that to allreduce (``_keras/__init__.py:20-80``).  Keras 3
(this image) removed ``get_gradients`` — the single choke point every
training path goes through is ``Optimizer.apply_gradients`` (both
``model.fit``'s train_step and custom loops call it), so the distributed
wrapper intercepts there: allreduce the gradients, then hand the averaged
set to the wrapped class.

Works with any Keras 3 backend: with the TensorFlow backend the allreduce
rides ``horovod_tpu.tensorflow`` (py_function inside the traced train
step); with the JAX backend Keras runs the step jitted and per-op
collectives cannot be injected mid-graph, so wrapping raises with a
pointer at the native JAX API (``horovod_tpu.DistributedOptimizer`` /
``make_training_step``), which is the TPU-idiomatic path anyway.
"""

from __future__ import annotations


def make_distributed_optimizer_class(keras, base_cls, name=None,
                                     compression=None,
                                     sparse_as_dense=False):
    """Build a distributed subclass of ``base_cls`` with the same class
    name, so saved models restore without horovod installed (reference
    trick, ``_keras/__init__.py:75-82``) — and, being a real class with
    ``from_config``, it can be registered as a Keras 3 custom object for
    ``load_model``."""
    backend = keras.backend.backend()
    if backend != "tensorflow":
        raise ValueError(
            f"horovod_tpu.keras.DistributedOptimizer supports the "
            f"TensorFlow Keras backend (got {backend!r}). For the JAX "
            f"backend use the native API: horovod_tpu.DistributedOptimizer "
            f"/ horovod_tpu.make_training_step, which jits collectives "
            f"into the step instead of injecting them per-op.")

    import tensorflow as tf
    import horovod_tpu.tensorflow as hvd

    if compression is None:
        compression = hvd.Compression.none

    class _DistributedOptimizer(keras.optimizers.Optimizer):
        _hvd_wrapped = True

        def apply_gradients(self, grads_and_vars, *args, **kwargs):
            grads_and_vars = list(grads_and_vars)
            if hvd.size() > 1 and grads_and_vars:
                grads, variables = zip(*grads_and_vars)
                scope = name or "Distributed%s" % self.__class__.__name__
                with tf.name_scope(scope + "_Allreduce"):
                    avg = []
                    for i, g in enumerate(grads):
                        if g is None:
                            avg.append(None)
                            continue
                        if sparse_as_dense and isinstance(g, tf.IndexedSlices):
                            g = tf.convert_to_tensor(g)
                        avg.append(hvd.allreduce(
                            g, compression=compression,
                            name=f"{scope}.grad.{i}"))
                grads_and_vars = list(zip(avg, variables))
            return super(self.__class__, self).apply_gradients(
                grads_and_vars, *args, **kwargs)

    return type(base_cls.__name__, (base_cls,),
                dict(_DistributedOptimizer.__dict__))


def create_distributed_optimizer(keras, optimizer, name=None,
                                 compression=None, sparse_as_dense=False):
    """Wrap an optimizer *instance*: subclass its class, rebuild from its
    config (reference ``_keras/__init__.py:75-82``)."""
    cls = make_distributed_optimizer_class(
        keras, optimizer.__class__, name=name, compression=compression,
        sparse_as_dense=sparse_as_dense)
    return cls.from_config(optimizer.get_config())


def load_model(keras, wrap_optimizer, filepath, custom_optimizers=None,
               custom_objects=None, **kwargs):
    """Load a model saved with a wrapped optimizer (reference
    ``_keras/__init__.py:107-123``): register distributed wrappers for all
    built-in (and user-supplied) optimizer classes as custom objects so the
    deserialized optimizer comes back wrapped."""
    def _all_subclasses(cls):
        # AdamW subclasses Adam, not Optimizer directly — walk transitively.
        out = set()
        for sub in cls.__subclasses__():
            out.add(sub)
            out |= _all_subclasses(sub)
        return out

    horovod_objects = {}
    for subclass in _all_subclasses(keras.optimizers.Optimizer):
        if subclass.__module__.startswith("keras"):
            wrapped = wrap_optimizer(subclass)
            # Keras 3 deserializes by class name; the reference era used
            # lowercase registrations — accept both.
            horovod_objects[subclass.__name__] = wrapped
            horovod_objects[subclass.__name__.lower()] = wrapped
    if custom_optimizers is not None:
        horovod_objects.update({
            cls.__name__: wrap_optimizer(cls) for cls in custom_optimizers})
    if custom_objects is not None:
        horovod_objects.update(custom_objects)
    return keras.models.load_model(filepath,
                                   custom_objects=horovod_objects, **kwargs)

"""Shared Keras integration impl (reference ``horovod/_keras/__init__.py``).

The reference targets Keras 2, whose optimizers expose ``get_gradients``;
it overrides that to allreduce (``_keras/__init__.py:20-80``).  Keras 3
(this image) removed ``get_gradients`` — the single choke point every
training path goes through is ``Optimizer.apply_gradients`` (both
``model.fit``'s train_step and custom loops call it), so the distributed
wrapper intercepts there: allreduce the gradients, then hand the averaged
set to the wrapped class.

Works with either Keras 3 backend this image ships: with the TensorFlow
backend the allreduce rides ``horovod_tpu.tensorflow`` (py_function
inside the traced train step); with the JAX backend the allreduce is
injected into the jitted train step with ``jax.experimental.io_callback``
pairs — a non-blocking native enqueue per gradient (data-chained so every
rank submits in the same order) and a blocking sync per gradient.  The
chain makes the schedule deadlock-free: a rank blocked in sync_i has
already enqueued 1..i, so the smallest-index blocked sync anywhere always
has every rank's contribution and completes (same argument as the TF
binding's enqueue chain).  For TPU-scale training prefer the native JAX
API (``horovod_tpu.make_training_step``) — it lowers the averaging to
XLA collectives instead of host callbacks; this wrapper is the
drop-in-compatibility path.
"""

from __future__ import annotations


def make_distributed_optimizer_class(keras, base_cls, name=None,
                                     compression=None,
                                     sparse_as_dense=False):
    """Build a distributed subclass of ``base_cls`` with the same class
    name, so saved models restore without horovod installed (reference
    trick, ``_keras/__init__.py:75-82``) — and, being a real class with
    ``from_config``, it can be registered as a Keras 3 custom object for
    ``load_model``."""
    if getattr(base_cls, "_hvd_wrapped", False):
        # Idempotent: re-wrapping (e.g. DistributedOptimizer around a
        # load_model-restored optimizer that is already wrapped) would
        # double-allreduce and, with the dynamic subclassing below,
        # recurse at the super() hop.
        return base_cls
    backend = keras.backend.backend()
    if backend == "jax":
        # sparse_as_dense is a no-op on JAX (gradients arrive dense —
        # there is no IndexedSlices analogue); compression is honored.
        return _make_jax_distributed_class(keras, base_cls, name,
                                           compression=compression)
    if backend != "tensorflow":
        raise ValueError(
            f"horovod_tpu.keras.DistributedOptimizer supports the "
            f"tensorflow and jax Keras backends (got {backend!r}).")

    import tensorflow as tf
    import horovod_tpu.tensorflow as hvd

    if compression is None:
        compression = hvd.Compression.none

    class _DistributedOptimizer(keras.optimizers.Optimizer):
        _hvd_wrapped = True

        def apply_gradients(self, grads_and_vars, *args, **kwargs):
            grads_and_vars = list(grads_and_vars)
            if hvd.size() > 1 and grads_and_vars:
                grads, variables = zip(*grads_and_vars)
                scope = name or "Distributed%s" % self.__class__.__name__
                with tf.name_scope(scope + "_Allreduce"):
                    avg = []
                    for i, g in enumerate(grads):
                        if g is None:
                            avg.append(None)
                            continue
                        if sparse_as_dense and isinstance(g, tf.IndexedSlices):
                            g = tf.convert_to_tensor(g)
                        avg.append(hvd.allreduce(
                            g, compression=compression,
                            name=f"{scope}.grad.{i}"))
                grads_and_vars = list(zip(avg, variables))
            # super(_cls[0], ...) not super(self.__class__, ...): the
            # latter recurses under further subclassing/wrapping.
            return super(_cls[0], self).apply_gradients(
                grads_and_vars, *args, **kwargs)

    _cls = [None]
    _cls[0] = type(base_cls.__name__, (base_cls,),
                   dict(_DistributedOptimizer.__dict__))
    return _cls[0]


def _make_jax_distributed_class(keras, base_cls, name=None,
                                compression=None):
    """JAX-backend distributed subclass: intercepts ``Optimizer.apply``
    (the Keras-3 choke point both ``apply_gradients`` and the JAX
    trainer's ``stateless_apply`` funnel through) and averages gradients
    over the eager plane via io_callback pairs (module docstring).

    ``compression`` (fp16/bf16 wire compression) is applied numpy-side
    inside the enqueue callback; the decompression context rides the
    token table to the matching sync callback."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import io_callback

    from horovod_tpu import basics
    from horovod_tpu.ops import collective as _c

    if compression is None:
        from horovod_tpu.ops.compression import Compression
        compression = Compression.none

    import threading
    tokens: dict = {}
    lock = threading.Lock()
    counter = [0]

    def _allreduce_all(grads, tag):
        n = basics.size()
        # int32 keys: x64 is disabled by default in JAX
        chain = jnp.zeros((), jnp.int32)
        keys = {}
        for i, g in enumerate(grads):
            if g is None:
                continue

            def enq(gv, _tok, nm=f"{tag}.grad.{i}"):
                wire, ctx = compression.compress(np.asarray(gv))
                tok = _c._eager_allreduce_submit(np.asarray(wire), _c.Sum,
                                                 nm, 1.0)
                with lock:
                    key = counter[0]
                    counter[0] += 1
                    tokens[key] = (tok, ctx)
                return np.int32(key)

            chain = io_callback(
                enq, jax.ShapeDtypeStruct((), jnp.int32), g, chain,
                ordered=False)
            keys[i] = chain

        out = list(grads)
        for i, key in keys.items():
            g = grads[i]

            def syn(k, _shape=g.shape, _dtype=g.dtype):
                with lock:
                    tok, ctx = tokens.pop(int(k))
                o = _c._eager_allreduce_finish(tok, _c.Sum, 1.0)
                o = compression.decompress(o, ctx)
                return np.asarray(o).astype(_dtype).reshape(_shape)

            summed = io_callback(
                syn, jax.ShapeDtypeStruct(g.shape, g.dtype), key,
                ordered=False)
            out[i] = summed / n
        return out

    class _DistributedOptimizer(base_cls):
        _hvd_wrapped = True

        def apply(self, grads, trainable_variables=None):
            grads = list(grads)
            if basics.size() > 1 and grads:
                tag = name or "Distributed%s" % self.__class__.__name__
                grads = _allreduce_all(grads, tag)
            # super(_cls[0], ...): self.__class__ would recurse under
            # further subclassing/wrapping.
            return super(_cls[0], self).apply(grads, trainable_variables)

    _cls = [None]
    _cls[0] = type(base_cls.__name__, (base_cls,),
                   dict(_DistributedOptimizer.__dict__))
    return _cls[0]


def create_distributed_optimizer(keras, optimizer, name=None,
                                 compression=None, sparse_as_dense=False):
    """Wrap an optimizer *instance*: subclass its class, rebuild from its
    config (reference ``_keras/__init__.py:75-82``)."""
    cls = make_distributed_optimizer_class(
        keras, optimizer.__class__, name=name, compression=compression,
        sparse_as_dense=sparse_as_dense)
    return cls.from_config(optimizer.get_config())


def load_model(keras, wrap_optimizer, filepath, custom_optimizers=None,
               custom_objects=None, **kwargs):
    """Load a model saved with a wrapped optimizer (reference
    ``_keras/__init__.py:107-123``): register distributed wrappers for all
    built-in (and user-supplied) optimizer classes as custom objects so the
    deserialized optimizer comes back wrapped."""
    def _all_subclasses(cls):
        # AdamW subclasses Adam, not Optimizer directly — walk transitively.
        out = set()
        for sub in cls.__subclasses__():
            out.add(sub)
            out |= _all_subclasses(sub)
        return out

    horovod_objects = {}
    for subclass in _all_subclasses(keras.optimizers.Optimizer):
        if subclass.__module__.startswith("keras"):
            wrapped = wrap_optimizer(subclass)
            # Keras 3 deserializes by class name; the reference era used
            # lowercase registrations — accept both.
            horovod_objects[subclass.__name__] = wrapped
            horovod_objects[subclass.__name__.lower()] = wrapped
    if custom_optimizers is not None:
        horovod_objects.update({
            cls.__name__: wrap_optimizer(cls) for cls in custom_optimizers})
    if custom_objects is not None:
        horovod_objects.update(custom_objects)
    return keras.models.load_model(filepath,
                                   custom_objects=horovod_objects, **kwargs)

"""Keras callback implementations (reference ``horovod/_keras/callbacks.py``).

Backend-agnostic redesign: the reference impls drive TF session/eager ops;
these operate on the numpy plane (``model.get_weights`` / variable
``assign``) and call the eager runtime directly, so they work with the
TensorFlow *and* JAX Keras 3 backends — weight broadcast and metric
averaging happen between steps, outside any traced graph, which is exactly
where Horovod's callbacks run anyway (``on_batch_end`` / ``on_epoch_end``).
"""

from __future__ import annotations

import numpy as np

from horovod_tpu import basics
from horovod_tpu.ops import collective as _c


def _bcast_np(arr, root_rank, name):
    return _c._eager_broadcast(np.asarray(arr), root_rank, name)


class BroadcastGlobalVariablesCallbackImpl:
    """Broadcast model + optimizer state from root after the first batch
    (reference ``_keras/callbacks.py:20-43``): run once, after any
    deferred variable creation, so restored/random init is consistent."""

    def __init__(self, root_rank=0, device='', *args):
        super().__init__(*args)
        self.root_rank = root_rank
        self.device = device   # parity-only; placement is XLA's job on TPU
        self.broadcast_done = False

    def on_batch_end(self, batch, logs=None):
        if self.broadcast_done:
            return
        weights = self.model.get_weights()
        self.model.set_weights([
            _bcast_np(w, self.root_rank, f"keras.bcast.model.{i}")
            for i, w in enumerate(weights)])
        opt = getattr(self.model, "optimizer", None)
        variables = getattr(opt, "variables", None)
        if callable(variables):   # Keras 2 style method
            variables = variables()
        if variables:
            for i, v in enumerate(variables):
                v.assign(_bcast_np(np.asarray(v), self.root_rank,
                                   f"keras.bcast.opt.{i}"))
        self.broadcast_done = True


class MetricAverageCallbackImpl:
    """Average epoch-end metric logs across ranks in place (reference
    ``_keras/callbacks.py:45-82``), sorted by name for deterministic
    cross-rank wire order."""

    def __init__(self, device='', *args):
        super().__init__(*args)
        self.device = device

    def _average_metrics_in_place(self, logs):
        logs = logs or {}
        for metric, value in sorted(logs.items()):
            if not np.isscalar(value) and not isinstance(value, np.ndarray):
                continue
            out = _c._eager_allreduce(
                np.asarray(value, dtype=np.float64), _c.Average,
                f"keras.metric.{metric}", 1.0, 1.0)
            logs[metric] = float(np.asarray(out))

    def on_epoch_end(self, epoch, logs=None):
        self._average_metrics_in_place(logs)


class LearningRateScheduleCallbackImpl:
    """Scale the optimizer LR by ``multiplier(epoch)`` inside
    [start_epoch, end_epoch), with momentum correction (reference
    ``_keras/callbacks.py:85-160``)."""

    def __init__(self, multiplier, start_epoch=0, end_epoch=None,
                 staircase=True, momentum_correction=True,
                 steps_per_epoch=None, *args):
        super().__init__(*args)
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase
        self.momentum_correction = momentum_correction
        self.initial_lr = None
        self.restore_momentum = None
        self.steps_per_epoch = steps_per_epoch
        self.current_epoch = 0
        if not callable(multiplier):
            self.staircase = True
            self.multiplier = lambda epoch: multiplier
        else:
            self.multiplier = multiplier

    # -- LR plumbing (Keras 3 exposes learning_rate as a Variable) --------
    def _get_lr(self):
        return float(np.asarray(self.model.optimizer.learning_rate))

    def _set_lr(self, value):
        self.model.optimizer.learning_rate = value

    def _autodetect_steps_per_epoch(self):
        if self.params.get("steps"):
            return self.params["steps"]
        if self.params.get("samples") and self.params.get("batch_size"):
            return self.params["samples"] // self.params["batch_size"]
        raise ValueError(
            "Could not autodetect the number of steps per epoch. Please "
            "specify the steps_per_epoch parameter to %s()"
            % self.__class__.__name__)

    def _adjust_learning_rate(self, epoch):
        old_lr = self._get_lr()
        new_lr = self.initial_lr * self.multiplier(epoch)
        self._set_lr(new_lr)
        opt = self.model.optimizer
        if self.momentum_correction and hasattr(opt, "momentum"):
            # Momentum correction (Goyal et al. 2017, as in the reference):
            # rescale accumulated momentum when LR changes mid-run.
            self.restore_momentum = float(np.asarray(opt.momentum))
            opt.momentum = self.restore_momentum * new_lr / old_lr

    def _restore_momentum_if_needed(self):
        if self.restore_momentum:
            self.model.optimizer.momentum = self.restore_momentum
            self.restore_momentum = None

    def on_train_begin(self, logs=None):
        self.initial_lr = self._get_lr()
        if not self.staircase and not self.steps_per_epoch:
            self.steps_per_epoch = self._autodetect_steps_per_epoch()

    def on_epoch_begin(self, epoch, logs=None):
        self.current_epoch = epoch

    def on_batch_begin(self, batch, logs=None):
        if (self.current_epoch < self.start_epoch or
                (self.end_epoch is not None and
                 self.current_epoch >= self.end_epoch)):
            return
        if self.staircase and batch == 0:
            self._adjust_learning_rate(self.current_epoch)
        elif not self.staircase:
            epoch = self.current_epoch + float(batch) / self.steps_per_epoch
            self._adjust_learning_rate(epoch)

    def on_batch_end(self, batch, logs=None):
        self._restore_momentum_if_needed()

    def on_epoch_end(self, epoch, logs=None):
        if logs is not None:
            logs["lr"] = self._get_lr()


class LearningRateWarmupCallbackImpl(LearningRateScheduleCallbackImpl):
    """Gradual warmup from lr/size to lr over ``warmup_epochs`` (reference
    ``_keras/callbacks.py:163-185``, Goyal et al. 2017)."""

    def __init__(self, warmup_epochs=5, momentum_correction=True,
                 steps_per_epoch=None, verbose=0, *args):
        def multiplier(epoch):
            epoch += 1.0 / self.steps_per_epoch
            return 1.0 / basics.size() * (
                epoch * (basics.size() - 1) / warmup_epochs + 1)
        super().__init__(multiplier, start_epoch=0, end_epoch=warmup_epochs,
                         staircase=False,
                         momentum_correction=momentum_correction,
                         steps_per_epoch=steps_per_epoch, *args)
        self.verbose = verbose

    def on_epoch_end(self, epoch, logs=None):
        super().on_epoch_end(epoch, logs)
        if epoch == self.end_epoch - 1 and self.verbose > 0:
            print("\nEpoch %d: finished gradual learning rate warmup to %g."
                  % (epoch + 1, self._get_lr()))

"""Training-loop callbacks and schedules — Keras-callback parity for JAX.

Reference equivalents: ``horovod/_keras/callbacks.py`` (shared by
``horovod.keras`` and ``horovod.tensorflow.keras``):
* ``BroadcastGlobalVariablesCallback`` (:20-43) — rank-0 state broadcast at
  training start (the checkpoint-restore consistency pattern, SURVEY §5.4).
* ``MetricAverageCallback`` (:46-72) — average epoch metrics over ranks.
* ``LearningRateScheduleCallback`` (:75-130) — multiplier schedules.
* ``LearningRateWarmupCallback`` (:133-185) — gradual warmup to
  ``initial_lr * hvd.size()`` with momentum correction, per the linear
  scaling rule (Goyal et al.).

In JAX the optimizer is an optax schedule, so the LR callbacks are exposed
both as optax schedules (the idiomatic form) and as callback objects with
``on_epoch_begin``/``on_epoch_end`` hooks for hand-rolled training loops.
"""

from __future__ import annotations

import math
from typing import Callable, Dict, Optional

import numpy as np

import horovod_tpu as hvd


class Callback:
    """Minimal callback protocol for custom training loops."""

    def on_train_begin(self, state=None):
        return state

    def on_epoch_begin(self, epoch: int, state=None):
        return state

    def on_batch_begin(self, batch: int, state=None):
        return state

    def on_batch_end(self, batch: int, state=None):
        return state

    def on_epoch_end(self, epoch: int, logs: Optional[Dict] = None,
                     state=None):
        return state


class BroadcastGlobalVariablesCallback(Callback):
    """Broadcast rank-0 model/optimizer state to all ranks at train start
    (reference _keras/callbacks.py:20-43: on_batch_end fires once)."""

    def __init__(self, root_rank: int = 0):
        self.root_rank = root_rank
        self.broadcast_done = False

    def on_batch_end(self, batch: int, state=None):
        if not self.broadcast_done:
            state = hvd.broadcast_parameters(state, root_rank=self.root_rank)
            self.broadcast_done = True
        return state

    def on_train_begin(self, state=None):
        return self.on_batch_end(0, state)


class MetricAverageCallback(Callback):
    """Average metric dicts across ranks at epoch end (reference
    _keras/callbacks.py:46-72)."""

    def on_epoch_end(self, epoch: int, logs: Optional[Dict] = None,
                     state=None):
        if logs:
            for key in sorted(logs):
                value = np.asarray(logs[key], np.float64)
                logs[key] = float(np.asarray(hvd.allreduce(
                    value, op=hvd.Average,
                    name=f"metric.{key}.{epoch}")))
        return state


class LearningRateScheduleCallback(Callback):
    """Multiply the base LR by ``multiplier(epoch)`` within
    [start_epoch, end_epoch) (reference _keras/callbacks.py:75-130).

    ``set_lr`` is how the schedule reaches the optimizer: a callable
    receiving the new LR (for optax inject_hyperparams, mutate
    ``opt_state.hyperparams['learning_rate']``).
    """

    def __init__(self, initial_lr: float, multiplier, start_epoch: int = 0,
                 end_epoch: Optional[int] = None, staircase: bool = True,
                 momentum_correction: bool = True,
                 steps_per_epoch: Optional[int] = None,
                 set_lr: Optional[Callable[[float], None]] = None):
        self.initial_lr = initial_lr
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase
        self.momentum_correction = momentum_correction
        self.steps_per_epoch = steps_per_epoch
        self.set_lr = set_lr
        self.current_lr = initial_lr
        self._epoch = 0   # tracked from on_epoch_begin (protocol-driven
        # loops pass no epoch to on_batch_begin)
        if isinstance(multiplier, (int, float)):
            self.multiplier = lambda epoch: multiplier
        else:
            self.multiplier = multiplier

    def _in_range(self, epoch: float) -> bool:
        return (epoch >= self.start_epoch and
                (self.end_epoch is None or epoch < self.end_epoch))

    def _adjust(self, epoch: float):
        if not self._in_range(epoch):
            return
        self.current_lr = self.initial_lr * self.multiplier(epoch)
        if self.set_lr is not None:
            self.set_lr(self.current_lr)

    def on_epoch_begin(self, epoch: int, state=None):
        self._epoch = epoch
        if self.staircase:
            self._adjust(epoch)
        return state

    def on_batch_begin(self, batch: int, state=None):
        # Per-batch (non-staircase) schedules use the epoch recorded by
        # on_epoch_begin — the Callback protocol passes only the batch
        # index, so requiring an extra kwarg here would silently pin
        # epoch=0 in any protocol-driven training loop.
        if not self.staircase and self.steps_per_epoch:
            self._adjust(self._epoch + batch / self.steps_per_epoch)
        return state


class LearningRateWarmupCallback(LearningRateScheduleCallback):
    """Warm up from ``initial_lr`` to ``initial_lr * hvd.size()`` over
    ``warmup_epochs`` (reference _keras/callbacks.py:133-185)."""

    def __init__(self, initial_lr: float, warmup_epochs: int = 5,
                 momentum_correction: bool = True,
                 steps_per_epoch: Optional[int] = None,
                 set_lr: Optional[Callable[[float], None]] = None,
                 verbose: bool = False, size: Optional[int] = None):
        self.warmup_epochs = warmup_epochs
        self.verbose = verbose
        # ``size`` is the factor the global batch grew by.  Default is the
        # process count (the reference's world), but a single-process SPMD
        # job scales its batch by the MESH size — pass size=mesh_size(mesh)
        # there, or the warmup target won't match the linear-scaling rule.
        if size is None:
            size = hvd.size() if hvd.is_initialized() else 1

        def multiplier(epoch):
            if warmup_epochs <= 0:
                return size
            progress = min(epoch / warmup_epochs, 1.0)
            return 1.0 + progress * (size - 1.0)

        super().__init__(initial_lr, multiplier, start_epoch=0,
                         end_epoch=warmup_epochs + 1, staircase=False,
                         momentum_correction=momentum_correction,
                         steps_per_epoch=steps_per_epoch, set_lr=set_lr)

    def on_epoch_begin(self, epoch: int, state=None):
        self._epoch = epoch
        self._adjust(epoch)
        return state

    def on_epoch_end(self, epoch: int, logs=None, state=None):
        if self.verbose and epoch == self.warmup_epochs and hvd.rank() == 0:
            print(f"Epoch {epoch}: finished gradual learning rate warmup to "
                  f"{self.current_lr}.")
        return state


# ---------------------------------------------------------------------------
# optax-native forms (the idiomatic JAX spelling of the same callbacks)
# ---------------------------------------------------------------------------

def warmup_schedule(base_lr: float, warmup_epochs: int,
                    steps_per_epoch: int, size: Optional[int] = None):
    """optax schedule: linear warmup from base_lr to base_lr*size, then
    flat — compose with optax.join_schedules for decay phases."""
    import optax
    size = size if size is not None else (
        hvd.size() if hvd.is_initialized() else 1)
    return optax.linear_schedule(
        init_value=base_lr, end_value=base_lr * size,
        transition_steps=max(warmup_epochs * steps_per_epoch, 1))


def scaled_lr(base_lr: float, size: Optional[int] = None) -> float:
    """The linear scaling rule: lr * world size (reference examples scale
    lr by hvd.size(), e.g. examples/keras_imagenet_resnet50.py)."""
    size = size if size is not None else (
        hvd.size() if hvd.is_initialized() else 1)
    return base_lr * size

"""Driver-side job coordination for Spark (and any task-based cluster).

Reference equivalent: ``horovod/spark/driver/driver_service.py`` +
the rank-assignment logic of ``spark/__init__.py:171-188`` (host-hash
grouping with rank 0's host first) — minus the mpirun_rsh tunneling,
which the TPU rebuild does not need: the native runtime rendezvouses
over TCP by env contract alone, so the driver only has to assign ranks
and hand every task its environment.

Pyspark-independent by design: the protocol is exercised in unit tests
with plain threads standing in for Spark tasks.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from horovod_tpu.runner import rpc


class JobDriver:
    """Collects task registrations, assigns ranks, distributes env maps,
    and gathers per-rank results."""

    def __init__(self, num_proc: int, key: bytes,
                 base_env: Optional[Dict[str, str]] = None,
                 keepalive_timeout: float = 60.0):
        self.num_proc = num_proc
        self.key = key
        self.base_env = dict(base_env or {})
        self._registrations: Dict[int, Dict[str, Any]] = {}
        self._results: Dict[int, Any] = {}
        self._failures: Dict[int, str] = {}
        self._env_maps: Optional[Dict[int, Dict[str, str]]] = None
        self._cv = threading.Condition()
        self._monitor = rpc.KeepaliveMonitor(timeout=keepalive_timeout)
        self._server = rpc.RpcServer(key, self._handle)

    # -- wire ----------------------------------------------------------------

    @property
    def port(self) -> int:
        return self._server.port

    def addresses(self) -> List[str]:
        return rpc.local_addresses()

    def _handle(self, req):
        kind = req.get("kind")
        if kind == "register":
            idx = int(req["index"])
            self._registrations[idx] = {
                "host": req["host"], "port": int(req["port"])}
            self._monitor.ping(idx)
            with self._cv:
                if (len(self._registrations) == self.num_proc and
                        self._env_maps is None):
                    self._assign()
                self._cv.notify_all()
            return {"ok": True}
        if kind == "env":
            self._monitor.ping(int(req["index"]))
            if self._env_maps is None:
                return {"ready": False}
            return {"ready": True,
                    "env": self._env_maps[int(req["index"])]}
        if kind == "result":
            idx = int(req["index"])
            if req.get("error"):
                self._failures[idx] = str(req["error"])
            else:
                self._results[idx] = req.get("value")
            # A finished task stops pinging; without this it would read
            # as dead the moment the keepalive timeout elapses.
            self._monitor.forget(idx)
            with self._cv:
                self._cv.notify_all()
            return {"ok": True}
        if kind == "ping":
            self._monitor.ping(int(req["index"]))
            return {"ok": True}
        return {"error": f"unknown request {kind!r}"}

    # -- rank assignment (reference spark/__init__.py:171-188) ---------------

    def _assign(self):
        # Group task indices by host; hosts ordered by first appearance of
        # their lowest task index (deterministic), tasks within a host by
        # index → contiguous local ranks, rank 0 on the first host.
        by_host: Dict[str, List[int]] = {}
        for idx in sorted(self._registrations):
            by_host.setdefault(self._registrations[idx]["host"],
                               []).append(idx)
        hosts = sorted(by_host, key=lambda h: by_host[h][0])
        rank = 0
        order: List[int] = []          # task index per rank
        locals_: Dict[int, int] = {}   # task index -> local rank
        cross: Dict[int, int] = {}     # task index -> cross rank
        for hi, h in enumerate(hosts):
            for li, idx in enumerate(by_host[h]):
                order.append(idx)
                locals_[idx] = li
                cross[idx] = hi
                rank += 1
        rank0 = self._registrations[order[0]]
        self._env_maps = {}
        for r, idx in enumerate(order):
            reg = self._registrations[idx]
            env = dict(self.base_env)
            env.update({
                "HOROVOD_RANK": str(r),
                "HOROVOD_SIZE": str(self.num_proc),
                "HOROVOD_LOCAL_RANK": str(locals_[idx]),
                "HOROVOD_LOCAL_SIZE": str(
                    len(by_host[reg["host"]])),
                "HOROVOD_CROSS_RANK": str(cross[idx]),
                "HOROVOD_CROSS_SIZE": str(len(hosts)),
                "HOROVOD_HOSTNAME": reg["host"],
                "HOROVOD_RENDEZVOUS_ADDR": rank0["host"],
                "HOROVOD_RENDEZVOUS_PORT": str(rank0["port"]),
                "HOROVOD_CONTROLLER": "tcp",
                "HOROVOD_CPU_OPERATIONS": "tcp",
            })
            self._env_maps[idx] = env

    # -- driver-side waiting -------------------------------------------------

    def wait_for_results(self, timeout: float = 600.0) -> List[Any]:
        """Block until every task reported; returns results in RANK order.
        Raises on task failure, keepalive loss, or timeout (reference
        gloo_run kills the job when any rank fails, gloo_run.py:256-262;
        the keepalive check is the failure-detection half of the
        reference's task services — without it a task whose executor
        died takes the full ``timeout`` to surface)."""
        deadline = time.monotonic() + timeout
        with self._cv:
            while True:
                if self._failures:
                    idx, err = sorted(self._failures.items())[0]
                    raise RuntimeError(
                        f"task {idx} failed: {err}")
                if len(self._results) == self.num_proc:
                    break
                dead = sorted(self._monitor.dead_tasks())
                if dead:
                    raise RuntimeError(
                        f"task(s) {dead} stopped sending keepalives "
                        f"(executor lost?); failing the job instead of "
                        f"waiting out the full {timeout}s timeout")
                left = deadline - time.monotonic()
                if left <= 0:
                    missing = sorted(set(range(self.num_proc)) -
                                     set(self._results))
                    raise TimeoutError(
                        f"tasks {missing} did not report within "
                        f"{timeout}s")
                self._cv.wait(min(left, 1.0))
        # Results keyed by task index; map to rank order via env maps.
        rank_of = {idx: int(env["HOROVOD_RANK"])
                   for idx, env in (self._env_maps or {}).items()}
        out: List[Any] = [None] * self.num_proc
        for idx, value in self._results.items():
            out[rank_of.get(idx, idx)] = value
        return out

    def shutdown(self):
        self._server.shutdown()


def run_task(index: int, driver_addr: str, driver_port: int, key: bytes,
             fn, args=(), kwargs=None, poll_interval: float = 0.3,
             start_timeout: float = 600.0, ping_interval: float = 15.0):
    """Task-side protocol: register → await env → run ``fn`` → report.

    Runs inside a Spark executor (or a test thread).  Returns fn's result
    so map-style callers can also collect through their own channel.
    While ``fn`` runs, a background thread pings the driver every
    ``ping_interval`` seconds so the driver's keepalive monitor can tell
    a long-running task from a dead executor."""
    import os
    import socket

    kwargs = kwargs or {}
    host = rpc.local_addresses()[0]
    with socket.socket() as s:
        s.bind(("", 0))
        port = s.getsockname()[1]   # rendezvous port candidate (rank 0)
    rpc.rpc_call(driver_addr, driver_port,
                 {"kind": "register", "index": index, "host": host,
                  "port": port}, key)
    deadline = time.monotonic() + start_timeout
    while True:
        resp = rpc.rpc_call(driver_addr, driver_port,
                            {"kind": "env", "index": index}, key)
        if resp.get("ready"):
            env = resp["env"]
            break
        if time.monotonic() > deadline:
            raise TimeoutError("timed out waiting for rank assignment")
        time.sleep(poll_interval)
    ping_stop = threading.Event()

    def _ping_loop():
        while not ping_stop.wait(ping_interval):
            try:
                rpc.rpc_call(driver_addr, driver_port,
                             {"kind": "ping", "index": index}, key,
                             retries=0)
            except (OSError, rpc.AuthError):
                # The driver decides liveness; a task never dies because
                # one ping missed (the driver may be restarting).
                pass

    # Start the pinger BEFORE touching os.environ: in the threaded test
    # simulation every task shares the process env, and thread startup
    # latency between update and fn() would widen that documented race.
    pinger = threading.Thread(target=_ping_loop, daemon=True,
                              name=f"hvd-task-{index}-keepalive")
    pinger.start()
    os.environ.update(env)
    try:
        value = fn(*args, **kwargs)
    except BaseException as e:  # noqa: BLE001 — reported, then re-raised
        ping_stop.set()
        rpc.rpc_call(driver_addr, driver_port,
                     {"kind": "result", "index": index,
                      "error": f"{type(e).__name__}: {e}"}, key)
        raise
    ping_stop.set()
    rpc.rpc_call(driver_addr, driver_port,
                 {"kind": "result", "index": index, "value": value}, key)
    return value

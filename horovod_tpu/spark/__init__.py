"""Spark integration: run a training fn in Spark tasks, Horovod-style.

Reference equivalent: ``horovod/spark/__init__.py:98-233`` —
``horovod.spark.run(fn)`` executes ``fn`` in ``num_proc`` Spark tasks,
registers the tasks with a driver service, groups ranks by host hash and
drives mpirun through Spark-task RPC tunneling (``mpirun_rsh``).

TPU-native redesign: no mpirun and no rsh tunneling.  The native runtime
rendezvouses over TCP purely from the ``HOROVOD_*`` env contract, so the
Spark layer reduces to: (1) a driver-side RPC service (HMAC-authenticated,
``runner/rpc.py``) that collects task registrations and assigns ranks by
host grouping, and (2) a task-side shim that registers, receives its env,
runs ``fn`` and reports the result.  The coordination logic lives in
``horovod_tpu.spark.driver`` and is pyspark-independent (unit-tested with
threads); this module is the thin pyspark veneer.

Execution evidence: ``tests/test_spark_veneer_shim.py`` runs this
``run()`` end to end — two SPAWNED task processes (own interpreters,
the local-mode worker contract) register over HMAC RPC, receive rank
env, ``hvd.init`` and allreduce — against a pyspark-API shim
(``tests/pyspark_local_shim.py``); only the JVM/py4j transport is
simulated there.  ``tests/distributed/test_spark_veneer.py`` is the
real-pyspark twin (Docker image; the authoring host has no JVM).
"""

from __future__ import annotations

import base64
import os
import secrets as _secrets
from typing import Any, Dict, List, Optional

from horovod_tpu.spark.driver import JobDriver, run_task  # noqa: F401


def run(fn, args=(), kwargs=None, num_proc: Optional[int] = None,
        env: Optional[Dict[str, str]] = None, start_timeout: float = 600.0,
        verbose: int = 1) -> List[Any]:
    """Run ``fn(*args, **kwargs)`` in ``num_proc`` Spark tasks as one
    distributed job; returns the per-rank results in rank order
    (reference ``horovod.spark.run``, ``spark/__init__.py:98-233``)."""
    try:
        import pyspark
    except ImportError as e:
        raise ImportError(
            "horovod_tpu.spark.run requires pyspark (pip install pyspark)"
        ) from e

    kwargs = kwargs or {}
    spark = pyspark.sql.SparkSession.builder.getOrCreate()
    sc = spark.sparkContext
    if num_proc is None:
        num_proc = max(sc.defaultParallelism, 1)

    key_b64 = os.environ.get("HOROVOD_SECRET_KEY") or \
        base64.urlsafe_b64encode(_secrets.token_bytes(32)).decode()
    from horovod_tpu.runner.rpc import job_key_bytes
    key = job_key_bytes(key_b64)

    base_env = dict(env or {})
    base_env["HOROVOD_SECRET_KEY"] = key_b64
    driver = JobDriver(num_proc, key, base_env=base_env)
    driver_addr = driver.addresses()[0]
    driver_port = driver.port
    if verbose:
        print(f"horovod_tpu.spark: driver service at "
              f"{driver_addr}:{driver_port}, num_proc={num_proc}")

    def _task(index, _iterator):
        result = run_task(index, driver_addr, driver_port, key, fn,
                          args=args, kwargs=kwargs,
                          start_timeout=start_timeout)
        yield result

    try:
        # The job RDD: num_proc empty partitions; results come back over
        # the driver service (the RDD collect is just the barrier).
        rdd = sc.parallelize(range(num_proc), num_proc)
        collect_thread = __import__("threading").Thread(
            target=lambda: rdd.mapPartitionsWithIndex(_task).collect(),
            daemon=True)
        collect_thread.start()
        results = driver.wait_for_results(timeout=start_timeout)
        collect_thread.join(timeout=60)
        return results
    finally:
        driver.shutdown()

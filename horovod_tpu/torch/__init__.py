"""PyTorch binding: Horovod's torch API over the TPU-native eager runtime.

Reference equivalents: ``horovod/torch/__init__.py`` (DistributedOptimizer
with per-parameter backward hooks :47-252, broadcast_parameters /
broadcast_optimizer_state :255-403), ``horovod/torch/mpi_ops.py`` (async
handle model :58-445) and the pybind layer ``torch/mpi_ops_v2.cc``.

TPU-native redesign: torch tensors live in host memory here (the TPU compute
path is JAX/XLA; torch rides the eager plane), so the binding moves data
zero-copy via numpy views into the native TCP runtime.  The handle/poll
model, hook-driven gradient averaging, and state-broadcast semantics match
the reference exactly — a Horovod-torch user changes only the import.
"""

from __future__ import annotations

import contextlib
import io
from typing import Optional

import numpy as np
import torch

from horovod_tpu import basics
from horovod_tpu.basics import (  # noqa: F401  (API parity re-exports)
    init, shutdown, is_initialized, rank, size, local_rank, local_size,
    cross_rank, cross_size, mpi_threads_supported, mpi_built, mpi_enabled,
    gloo_built, gloo_enabled, nccl_built, ddl_built, mlsl_built,
    tpu_built, tpu_enabled,
)
from horovod_tpu.ops import collective as _c
from horovod_tpu.ops.collective import (  # noqa: F401
    Average, Sum, Adasum, Min, Max, poll, synchronize as _synchronize,
    ProcessSet, add_process_set, global_process_set,
)


class Compression:
    """fp16 wire compression for torch tensors (reference
    ``torch/compression.py``)."""

    class none:
        @staticmethod
        def compress(t):
            return t, None

        @staticmethod
        def decompress(t, ctx):
            return t

    class fp16:
        @staticmethod
        def compress(t):
            if t.dtype in (torch.float32, torch.float64):
                return t.half(), t.dtype
            return t, None

        @staticmethod
        def decompress(t, ctx):
            return t if ctx is None else t.to(ctx)


def _to_numpy(tensor: torch.Tensor) -> np.ndarray:
    return tensor.detach().contiguous().cpu().numpy()


def _from_numpy(arr: np.ndarray, like: torch.Tensor) -> torch.Tensor:
    # np.ascontiguousarray promotes 0-dim to 1-d; reshape restores it so
    # scalar tensors (e.g. BatchNorm num_batches_tracked) round-trip.
    shape = np.shape(arr)
    return torch.from_numpy(
        np.ascontiguousarray(arr).reshape(shape)).to(like.dtype)


def synchronize(handle) -> torch.Tensor:
    """Wait for an async op; returns the torch result (reference
    ``torch/mpi_ops.py:429-445``).  A list/tuple of handles (e.g. from
    :func:`grouped_allreduce_async`) synchronizes each and returns the
    list of results."""
    if isinstance(handle, (list, tuple)):
        return [synchronize(h) for h in handle]
    out = _synchronize(handle)
    if isinstance(out, torch.Tensor):
        return out
    return torch.from_numpy(np.ascontiguousarray(np.asarray(out)))


def join() -> int:
    return _c.join()


# ---------------------------------------------------------------------------
# Collectives on torch tensors (reference torch/mpi_ops.py:58-445)
# ---------------------------------------------------------------------------

def allreduce_async(tensor, average=None, name=None, op=None,
                    prescale_factor=1.0, postscale_factor=1.0,
                    process_set=None):
    basics._check_initialized()
    rop = _c._resolve_op(op, average)
    set_id, set_size = _c._set_args(process_set)
    nm = _c._auto_name("allreduce", name)
    arr = _to_numpy(tensor)

    def work():
        out = _c._eager_allreduce(arr, rop, nm, prescale_factor,
                                  postscale_factor, set_id=set_id,
                                  set_size=set_size)
        return _from_numpy(out, tensor)

    return _c._async_dispatch(work, "allreduce", nm, to_jnp=False)


def allreduce(tensor, average=None, name=None, op=None, compression=None,
              prescale_factor=1.0, postscale_factor=1.0, process_set=None):
    compression = compression or Compression.none
    wire, ctx = compression.compress(tensor)
    h = allreduce_async(wire, average=average, name=name, op=op,
                        prescale_factor=prescale_factor,
                        postscale_factor=postscale_factor,
                        process_set=process_set)
    return compression.decompress(synchronize(h), ctx)


def grouped_allreduce_async(tensors, average=None, name=None, op=None,
                            process_set=None):
    """Async-enqueue every tensor of the group at once (so the runtime
    batches their negotiations into shared cycles); returns a list of
    handles for :func:`synchronize` (later-Horovod grouped_allreduce
    contract, expressed over this binding's handle model)."""
    nm = _c._auto_name("grouped_allreduce", name)
    return [allreduce_async(t, average=average, name=f"{nm}.{i}", op=op,
                            process_set=process_set)
            for i, t in enumerate(tensors)]


def grouped_allreduce(tensors, average=None, name=None, op=None,
                      compression=None, process_set=None):
    """Allreduce a LIST of tensors as one group: all in flight together,
    one synchronize sweep."""
    compression = compression or Compression.none
    if not tensors:
        return []
    wires, ctxs = zip(*[compression.compress(t) for t in tensors])
    hs = grouped_allreduce_async(list(wires), average=average, name=name,
                                 op=op, process_set=process_set)
    return [compression.decompress(o, c)
            for o, c in zip(synchronize(hs), ctxs)]


def allreduce_async_(tensor, average=None, name=None, op=None):
    """In-place async: the handle's result is copied into ``tensor`` at
    synchronize time (reference semantics of ``allreduce_async_``)."""
    basics._check_initialized()
    rop = _c._resolve_op(op, average)
    nm = _c._auto_name("allreduce", name)
    arr = _to_numpy(tensor)

    def work():
        out = _c._eager_allreduce(arr, rop, nm, 1.0, 1.0)
        with torch.no_grad():
            tensor.copy_(_from_numpy(out, tensor))
        return tensor

    return _c._async_dispatch(work, "allreduce", nm, to_jnp=False)


def allreduce_(tensor, average=None, name=None, op=None):
    return synchronize(allreduce_async_(tensor, average=average, name=name,
                                        op=op))


def allgather_async(tensor, name=None):
    basics._check_initialized()
    nm = _c._auto_name("allgather", name)
    arr = _to_numpy(tensor)

    def work():
        return _from_numpy(_c._eager_allgather(arr, nm), tensor)

    return _c._async_dispatch(work, "allgather", nm, to_jnp=False)


def allgather(tensor, name=None, process_set=None):
    if process_set is not None:
        basics._check_initialized()
        set_id, _ = _c._set_args(process_set)
        nm = _c._auto_name("allgather", name)
        return _from_numpy(
            _c._eager_allgather(_to_numpy(tensor), nm, set_id=set_id),
            tensor)
    return synchronize(allgather_async(tensor, name=name))


def broadcast_async(tensor, root_rank, name=None):
    basics._check_initialized()
    nm = _c._auto_name("broadcast", name)
    arr = _to_numpy(tensor)

    def work():
        return _from_numpy(_c._eager_broadcast(arr, root_rank, nm), tensor)

    return _c._async_dispatch(work, "broadcast", nm, to_jnp=False)


def broadcast(tensor, root_rank, name=None, process_set=None):
    if process_set is not None:
        basics._check_initialized()
        set_id, _ = _c._set_args(process_set)
        nm = _c._auto_name("broadcast", name)
        return _from_numpy(
            _c._eager_broadcast(_to_numpy(tensor), root_rank, nm,
                                set_id=set_id), tensor)
    return synchronize(broadcast_async(tensor, root_rank, name=name))


def broadcast_async_(tensor, root_rank, name=None):
    basics._check_initialized()
    nm = _c._auto_name("broadcast", name)
    arr = _to_numpy(tensor)

    def work():
        out = _c._eager_broadcast(arr, root_rank, nm)
        with torch.no_grad():
            tensor.copy_(_from_numpy(out, tensor))
        return tensor

    return _c._async_dispatch(work, "broadcast", nm, to_jnp=False)


def broadcast_(tensor, root_rank, name=None):
    return synchronize(broadcast_async_(tensor, root_rank, name=name))


def alltoall(tensor, splits=None, name=None, process_set=None):
    basics._check_initialized()
    set_id, _ = _c._set_args(process_set)
    nm = _c._auto_name("alltoall", name)
    if splits is not None and torch.is_tensor(splits):
        splits = splits.detach().cpu().numpy()
    out, received = _c._eager_alltoall(_to_numpy(tensor), splits, nm,
                                       set_id=set_id)
    if splits is not None:
        # Later-Horovod contract: (output, received_splits) with splits.
        return _from_numpy(out, tensor), torch.as_tensor(received)
    return _from_numpy(out, tensor)


def reducescatter(tensor, op=None, name=None):
    basics._check_initialized()
    rop = _c._resolve_op(op, None)
    nm = _c._auto_name("reducescatter", name)
    out = _c._eager_reducescatter(_to_numpy(tensor), rop, nm)
    return _from_numpy(out, tensor)


def broadcast_object(obj, root_rank=0, name=None):
    return _c.broadcast_object(obj, root_rank=root_rank, name=name)


def allgather_object(obj, name=None):
    return _c.allgather_object(obj, name=name)


# ---------------------------------------------------------------------------
# DistributedOptimizer (reference torch/__init__.py:47-252)
# ---------------------------------------------------------------------------

class _DistributedOptimizer(torch.optim.Optimizer):
    def __init__(self, params, named_parameters, compression,
                 backward_passes_per_step=1, op=Average):
        super(self.__class__, self).__init__(params)
        self._compression = compression
        self._op = op
        self.backward_passes_per_step = backward_passes_per_step

        if named_parameters is not None:
            named_parameters = list(named_parameters)
            # Validation (reference torch/__init__.py:70-93 /
            # test_torch.py:1331-1381): entries must be (str, Tensor) pairs,
            # unique names, covering all optimizer params.
            if any(not isinstance(nv, tuple) or len(nv) != 2 or
                   not isinstance(nv[0], str)
                   for nv in named_parameters):
                raise ValueError(
                    "named_parameters should be a sequence of (name, "
                    "parameter) tuples, e.g. model.named_parameters()")
            names = [n for n, _ in named_parameters]
            if len(names) != len(set(names)):
                dups = sorted({n for n in names if names.count(n) > 1})
                raise ValueError(
                    f"parameter names must be unique, found duplicates: "
                    f"{dups}")
            all_params = {id(p) for group in self.param_groups
                          for p in group["params"]}
            named = {id(p) for _, p in named_parameters}
            if len(all_params - named) > 0:
                raise ValueError(
                    "named_parameters was specified but it does not cover "
                    "all optimizer parameters")
            self._param_names = {id(p): n for n, p in named_parameters}
        else:
            self._param_names = {
                id(p): f"allreduce.noname.{gi}.{pi}"
                for gi, group in enumerate(self.param_groups)
                for pi, p in enumerate(group["params"])}

        self._handles = {}
        self._grad_accs = []
        self._passes = {}
        self._requires_update = set()
        if basics.size() > 1:
            self._register_hooks()

    def _register_hooks(self):
        # Reference builds a grad_acc hook chain via expand_as
        # (torch/__init__.py:108-143); torch >= 2.1 exposes the same fire
        # point directly.
        for group in self.param_groups:
            for p in group["params"]:
                if p.requires_grad:
                    self._requires_update.add(id(p))
                    self._passes[id(p)] = 0
                    self._grad_accs.append(
                        p.register_post_accumulate_grad_hook(
                            self._make_hook()))

    def _make_hook(self):
        def hook(p):
            self._passes[id(p)] += 1
            if self._passes[id(p)] == self.backward_passes_per_step:
                self._passes[id(p)] = 0
                self._handles[id(p)] = self._allreduce_grad_async(p)
        return hook

    def _allreduce_grad_async(self, p):
        name = self._param_names[id(p)]
        wire, ctx = self._compression.compress(p.grad)
        handle = allreduce_async_(wire, name=name, op=self._op)
        return handle, wire, ctx, p

    def synchronize(self):
        """Wait for outstanding gradient allreduces (reference
        torch/__init__.py:145-162)."""
        for pid, (handle, wire, ctx, p) in list(self._handles.items()):
            synchronize(handle)
            with torch.no_grad():
                p.grad.copy_(self._compression.decompress(wire, ctx))
        self._handles.clear()
        self._synchronized = True

    @contextlib.contextmanager
    def skip_synchronize(self):
        """Context manager for the explicit-synchronize recipe (reference
        torch/__init__.py: gradient clipping interplay, test_torch.py:1266):

            optimizer.synchronize()
            torch.nn.utils.clip_grad_norm_(model.parameters(), 1.0)
            with optimizer.skip_synchronize():
                optimizer.step()

        Without it, ``step()`` would fire a second (numerically idempotent
        but wasteful) force-allreduce pass over the already-averaged grads.

        DELIBERATE deviation from the reference: the reference silently
        skips synchronization for ANY ``step()`` inside this context,
        even when a backward pass enqueued fresh un-averaged gradients
        after the last ``synchronize()`` — silently applying per-rank
        gradients and diverging the replicas.  Here such a ``step()``
        raises (see the three guards in :meth:`step`); code ported from
        the reference that relied on the silent skip must call
        ``synchronize()`` first, which is the recipe's contract anyway.
        """
        self._should_skip_synchronize = True
        try:
            yield
        finally:
            self._should_skip_synchronize = False

    def step(self, closure=None):
        if getattr(self, "_should_skip_synchronize", False):
            # All three guards matter: _synchronized proves synchronize()
            # ran since the last step; empty _handles proves no backward
            # enqueued new allreduces after it; zero _passes proves no
            # partial gradient accumulation is pending (with
            # backward_passes_per_step > 1 a mid-accumulation backward
            # fires no handle, so synchronize() would be a no-op and the
            # step would apply raw un-averaged local gradients).
            if (not getattr(self, "_synchronized", False) or self._handles
                    or any(self._passes.values())):
                raise AssertionError(
                    "optimizer.step() inside skip_synchronize() requires a "
                    "prior optimizer.synchronize() call (with no backward "
                    "pass or partial gradient accumulation in between)")
            self._synchronized = False
            return super(self.__class__, self).step(closure)
        if basics.size() > 1:
            # Any parameter whose hook never fired (e.g. frozen this step
            # but updated before) still needs a matching allreduce on all
            # ranks; fire for everything missing (reference
            # torch/__init__.py:168-183 force-allreduce).
            for group in self.param_groups:
                for p in group["params"]:
                    if (id(p) in self._requires_update and
                            id(p) not in self._handles and
                            p.grad is not None):
                        self._handles[id(p)] = self._allreduce_grad_async(p)
            self.synchronize()
        # A normal step consumes the synchronized state — skip_synchronize
        # on the NEXT step requires its own explicit synchronize() call.
        self._synchronized = False
        return super(self.__class__, self).step(closure)

    def zero_grad(self, set_to_none: bool = True):
        if self._handles:
            raise AssertionError(
                "optimizer.zero_grad() was called after loss.backward() but "
                "before optimizer.step() or optimizer.synchronize(). This is "
                "prohibited as it can cause a race condition.")
        return super(self.__class__, self).zero_grad(set_to_none)


def DistributedOptimizer(optimizer, named_parameters=None,
                         compression=Compression.none,
                         backward_passes_per_step=1, op=Average):
    """Wrap a torch optimizer so ``step()`` applies cross-rank-averaged
    gradients (reference ``torch/__init__.py:205-252``: dynamically subclass
    the optimizer's own class)."""
    cls = type(optimizer.__class__.__name__, (optimizer.__class__,),
               dict(_DistributedOptimizer.__dict__))
    cls._hvd_wrapped = True   # lets state-fill paths reach the base step
    return cls(optimizer.param_groups, named_parameters, compression,
               backward_passes_per_step, op)


# ---------------------------------------------------------------------------
# Parameter / optimizer-state broadcast (reference torch/__init__.py:255-403)
# ---------------------------------------------------------------------------

def broadcast_parameters(params, root_rank=0):
    """Broadcast a ``state_dict()`` or ``named_parameters`` iterable,
    in place."""
    if isinstance(params, dict):
        items = sorted(params.items())
    else:
        items = list(params)
    for name, p in items:
        if torch.is_tensor(p):
            broadcast_(p.data, root_rank, name=f"broadcast_parameters.{name}")


def broadcast_optimizer_state(optimizer, root_rank=0):
    """Broadcast optimizer state (momenta, step counters...) from root.

    The reference wraps non-tensor scalars into tensors with pickled
    callbacks (torch/__init__.py:287-403); here the whole non-tensor residue
    rides one pickled broadcast and tensors ride the wire natively.
    """
    if isinstance(optimizer, torch.optim.LBFGS):
        raise ValueError("cannot broadcast torch.optim.LBFGS state")
    state_dict = optimizer.state_dict()

    # Fill missing per-param state by running a zero-grad step, so
    # state_dicts line up (reference torch/__init__.py:300-317).  The
    # empty-state check is per-rank (on checkpoint resume only the root has
    # state), so the dummy step must be purely LOCAL: for a wrapped
    # DistributedOptimizer, step() would allreduce on the subset of ranks
    # with empty state and deadlock — call the base class's step instead.
    if not state_dict.get("state"):
        for group in optimizer.param_groups:
            for p in group["params"]:
                if p.requires_grad and p.grad is None:
                    p.grad = torch.zeros_like(p)
        if getattr(type(optimizer), "_hvd_wrapped", False):
            type(optimizer).__mro__[1].step(optimizer)
        else:
            optimizer.step()
        state_dict = optimizer.state_dict()

    tensors = {}
    meta = {"param_groups": state_dict["param_groups"], "state_scalars": {}}
    for pid, pstate in state_dict.get("state", {}).items():
        for key, value in pstate.items():
            if torch.is_tensor(value):
                tensors[f"{pid}.{key}"] = value
            else:
                meta["state_scalars"][f"{pid}.{key}"] = value

    meta = broadcast_object(meta, root_rank=root_rank,
                            name="broadcast_opt_state.meta")
    for name in sorted(tensors):
        broadcast_(tensors[name], root_rank,
                   name=f"broadcast_opt_state.{name}")

    if basics.rank() != root_rank:
        state_dict["param_groups"] = meta["param_groups"]
        for flat, value in meta["state_scalars"].items():
            pid, key = flat.split(".", 1)
            pid = int(pid) if pid.isdigit() else pid
            state_dict["state"].setdefault(pid, {})[key] = value
        optimizer.load_state_dict(state_dict)


def load_state_dict_from_bytes(data: bytes):
    """Helper for checkpoint flows: torch.load from broadcast bytes."""
    return torch.load(io.BytesIO(data), weights_only=False)

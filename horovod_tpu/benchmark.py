"""Synthetic training benchmark — the measurement harness of record.

Faithful to the reference harness (``examples/tensorflow2_synthetic_benchmark.py``:
synthetic fixed batch, ``--num-warmup-batches`` then ``num_iters`` rounds of
``num_batches_per_iter`` steps, img/sec mean ± 1.96σ over rounds,
``:86-132``), rebuilt as one jitted SPMD program over the device mesh.

The whole Horovod DP recipe — shard the batch over chips, replicate
parameters, allreduce (fused ``pmean``) gradients, identical update — is a
single XLA program here; the gradient averaging that the reference performs
with its background thread + NCCL rings lowers to ICI collectives that XLA
overlaps with backprop compute.
"""

from __future__ import annotations

import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.ops.fusion import fused_pytree_mean
from horovod_tpu.topology import data_axis, mesh_size


def make_train_step(model, optimizer, mesh, axis_name: Optional[str] = None):
    """One SPMD training step for a flax model with BatchNorm state.

    Returns ``step(params, batch_stats, opt_state, images, labels) ->
    (params, batch_stats, opt_state, loss)`` jitted over ``mesh`` with the
    batch sharded on the data axis, everything else replicated.
    """
    ax = axis_name or data_axis(mesh)

    def _step(params, batch_stats, opt_state, images, labels):
        def loss_fn(p):
            logits, mutated = model.apply(
                {"params": p, "batch_stats": batch_stats}, images,
                train=True, mutable=["batch_stats"])
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits, labels).mean()
            return loss, mutated["batch_stats"]

        (loss, new_stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        # The Horovod step: average gradients across the mesh (fused psum —
        # reference fusion_buffer_manager + NCCLAllreduce, here one bf16-safe
        # bucketed pmean riding ICI).
        grads = fused_pytree_mean(grads, ax)
        updates, new_opt_state = optimizer.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        return new_params, new_stats, new_opt_state, lax.pmean(loss, ax)

    repl, shard = P(), P(ax)
    smapped = jax.shard_map(
        _step, mesh=mesh,
        in_specs=(repl, repl, repl, shard, shard),
        out_specs=(repl, repl, repl, repl),
        check_vma=False)
    return jax.jit(smapped, donate_argnums=(0, 1, 2))


def run_synthetic_benchmark(model_name: str = "resnet50",
                            batch_size: int = 64,
                            image_size: int = 224,
                            num_classes: int = 1000,
                            num_warmup_batches: int = 5,
                            num_batches_per_iter: int = 10,
                            num_iters: int = 10,
                            learning_rate: float = 0.01,
                            mesh=None,
                            verbose: bool = True) -> dict:
    """Run the ResNet synthetic benchmark; returns a result dict.

    ``batch_size`` is per chip, as in the reference (``--batch-size`` is per
    worker, ``tensorflow2_synthetic_benchmark.py:20``).
    """
    from horovod_tpu.models import get_model

    if not hvd.is_initialized():
        hvd.init()
    mesh = mesh if mesh is not None else hvd.mesh()
    ax = data_axis(mesh)
    n_chips = mesh_size(mesh)
    global_bs = batch_size * n_chips

    model = get_model(model_name, num_classes=num_classes)
    rng = jax.random.PRNGKey(0)
    variables = model.init(rng, jnp.zeros((1, image_size, image_size, 3),
                                          jnp.float32), train=False)
    params, batch_stats = variables["params"], variables["batch_stats"]
    optimizer = optax.sgd(learning_rate, momentum=0.9)
    opt_state = optimizer.init(params)

    # Fixed synthetic batch, placed sharded on the data axis (reference keeps
    # one random batch for the whole run, :40-43).
    images = jax.device_put(
        np.random.default_rng(0).standard_normal(
            (global_bs, image_size, image_size, 3), dtype=np.float32),
        NamedSharding(mesh, P(ax)))
    labels = jax.device_put(
        np.random.default_rng(1).integers(0, num_classes, (global_bs,),
                                          dtype=np.int32),
        NamedSharding(mesh, P(ax)))
    repl = NamedSharding(mesh, P())
    params, batch_stats, opt_state = jax.device_put(
        (params, batch_stats, opt_state), repl)

    step = make_train_step(model, optimizer, mesh, ax)

    if verbose:
        print(f"Model: {model_name}", flush=True)
        print(f"Batch size: {batch_size} per chip, {global_bs} global "
              f"({n_chips} chips)", flush=True)

    # Sync point: a tiny scalar D2H transfer of the loss.  On tunneled/remote
    # PJRT platforms `block_until_ready` can return before device execution
    # finishes; fetching the scalar output is the reliable barrier (and the
    # loss of step N depends on every prior step's params, so it fences the
    # whole round).
    for _ in range(num_warmup_batches):
        params, batch_stats, opt_state, loss = step(
            params, batch_stats, opt_state, images, labels)
    float(np.asarray(loss))

    img_secs = []
    for i in range(num_iters):
        t0 = time.perf_counter()
        for _ in range(num_batches_per_iter):
            params, batch_stats, opt_state, loss = step(
                params, batch_stats, opt_state, images, labels)
        float(np.asarray(loss))
        dt = time.perf_counter() - t0
        img_sec = global_bs * num_batches_per_iter / dt
        img_secs.append(img_sec)
        if verbose:
            print(f"Iter #{i}: {img_sec:.1f} img/sec total", flush=True)

    img_sec_mean = float(np.mean(img_secs))
    img_sec_conf = float(1.96 * np.std(img_secs))
    if verbose:
        print(f"Img/sec per chip: {img_sec_mean / n_chips:.1f} "
              f"+-{img_sec_conf / n_chips:.1f}", flush=True)
        print(f"Total img/sec on {n_chips} chip(s): "
              f"{img_sec_mean:.1f} +-{img_sec_conf:.1f}", flush=True)
    return {
        "model": model_name,
        "batch_size_per_chip": batch_size,
        "n_chips": n_chips,
        "img_sec_total": img_sec_mean,
        "img_sec_conf": img_sec_conf,
        "img_sec_per_chip": img_sec_mean / n_chips,
        "loss": float(np.asarray(loss)),
    }

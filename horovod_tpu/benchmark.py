"""Synthetic training benchmark — the measurement harness of record.

Faithful to the reference harness (``examples/tensorflow2_synthetic_benchmark.py``:
synthetic fixed batch, ``--num-warmup-batches`` then ``num_iters`` rounds of
``num_batches_per_iter`` steps, img/sec mean ± 1.96σ over rounds,
``:86-132``), rebuilt as one jitted SPMD program over the device mesh.

The whole Horovod DP recipe — shard the batch over chips, replicate
parameters, allreduce (fused ``pmean``) gradients, identical update — is a
single XLA program here; the gradient averaging that the reference performs
with its background thread + NCCL rings lowers to ICI collectives that XLA
overlaps with backprop compute.
"""

from __future__ import annotations

import os
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.ops.fusion import fused_pytree_mean
from horovod_tpu.topology import build_mesh, data_axis, mesh_size

# Peak dense bf16 FLOP/s per chip by device kind (public TPU spec sheet
# numbers), for MFU accounting.  Override with BENCH_PEAK_TFLOPS.
PEAK_TFLOPS_BY_KIND = {
    "TPU v2": 45.0,
    "TPU v3": 123.0,
    "TPU v4": 275.0,
    "TPU v5 lite": 197.0,   # v5e
    "TPU v5e": 197.0,
    "TPU v5": 459.0,        # v5p
    "TPU v5p": 459.0,
    "TPU v6 lite": 918.0,   # v6e (Trillium)
    "TPU v6e": 918.0,
}

# Forward-pass GFLOPs per 224x224 image (standard analytic counts, 2 FLOPs
# per MAC); training step ~= 3x forward.  Fallback when XLA cost analysis
# is unavailable on the backend.
_FWD_GFLOPS_224 = {
    "resnet18": 1.82, "resnet34": 3.67, "resnet50": 4.09,
    "resnet101": 7.80, "resnet152": 11.52,
    # VGG-BN conv stacks (GAP head; the convs are >99% of FLOPs).
    "vgg11": 7.6, "vgg13": 11.3, "vgg16": 15.5, "vgg19": 19.6,
    # Inception V3 is 5.7 GFLOPs at its canonical 299x299 => ~3.2 at 224
    # under the quadratic spatial scaling the fallback applies.
    "inception3": 3.2, "inceptionv3": 3.2,
}


def device_peak_tflops(device) -> Optional[float]:
    """Peak bf16 TFLOP/s of `device`, or None when unknown (e.g. the CPU
    simulation mesh, where MFU is not meaningful)."""
    import os
    env = os.environ.get("BENCH_PEAK_TFLOPS")
    if env:
        return float(env)
    kind = getattr(device, "device_kind", "")
    for prefix, peak in sorted(PEAK_TFLOPS_BY_KIND.items(),
                               key=lambda kv: -len(kv[0])):
        if kind.startswith(prefix):
            return peak
    return None


def _step_flops(compiled, model_name: str, global_bs: int,
                image_size: int, n_chips: int) -> Optional[float]:
    """GLOBAL FLOPs of one training step.

    XLA's cost analysis reports the PER-DEVICE SPMD module (verified: an
    8-way-sharded program reports 1/8 of the single-device figure), so the
    count is scaled by n_chips; the analytic fallback is global already.
    ``compiled=None`` requests the analytic estimate directly."""
    if compiled is not None:
        try:
            ca = compiled.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            flops = float(ca.get("flops", 0.0))
            if flops > 0:
                return flops * n_chips
        except Exception:
            pass
    fwd = _FWD_GFLOPS_224.get(model_name)
    if fwd is None:
        return None
    scale = (image_size / 224.0) ** 2
    return 3.0 * fwd * 1e9 * scale * global_bs


def make_train_step(model, optimizer, mesh, axis_name: Optional[str] = None,
                    steps_per_call: int = 1):
    """One SPMD training step for a flax model with BatchNorm state.

    Returns ``step(params, batch_stats, opt_state, images, labels) ->
    (params, batch_stats, opt_state, loss)`` jitted over ``mesh`` with the
    batch sharded on the data axis, everything else replicated.

    ``steps_per_call > 1`` runs that many steps inside ONE compiled
    program via ``lax.scan`` (same batch each step, like the reference's
    fixed synthetic batch).  This amortizes host dispatch: on a tunneled
    PJRT backend a dispatch+fetch round trip costs ~100 ms (measured),
    which at ~60 ms of device work per ResNet-50 step would otherwise BE
    the benchmark.  Local backends dispatch in microseconds and the
    reference's per-step ``session.run`` loop loses nothing; ours must
    not pay per-step round trips it can compile away.
    """
    ax = axis_name or data_axis(mesh)

    def _step(params, batch_stats, opt_state, images, labels):
        def loss_fn(p):
            logits, mutated = model.apply(
                {"params": p, "batch_stats": batch_stats}, images,
                train=True, mutable=["batch_stats"])
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits, labels).mean()
            return loss, mutated["batch_stats"]

        (loss, new_stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)

        def do_update():
            # The Horovod step: average gradients across the mesh (fused
            # psum — reference fusion_buffer_manager + NCCLAllreduce,
            # here one bf16-safe bucketed pmean riding ICI).
            g = fused_pytree_mean(grads, ax)
            updates, new_opt_state = optimizer.update(g, opt_state,
                                                      params)
            return (optax.apply_updates(params, updates), new_stats,
                    new_opt_state)

        from horovod_tpu import resilience
        ((new_params, out_stats, new_opt_state),
         mean_loss) = resilience.apply_step_guard(
            do_update, loss=loss, grads=grads,
            old_state=(params, batch_stats, opt_state), axes=(ax,))
        return new_params, out_stats, new_opt_state, mean_loss

    if steps_per_call > 1:
        def _loop(params, batch_stats, opt_state, images, labels):
            def body(carry, _):
                p, s, o = carry
                p, s, o, loss = _step(p, s, o, images, labels)
                return (p, s, o), loss
            (p, s, o), losses = lax.scan(
                body, (params, batch_stats, opt_state), None,
                length=steps_per_call)
            return p, s, o, losses[-1]
        fn = _loop
    else:
        fn = _step

    repl, shard = P(), P(ax)
    smapped = jax.shard_map(
        fn, mesh=mesh,
        in_specs=(repl, repl, repl, shard, shard),
        out_specs=(repl, repl, repl, repl),
        check_vma=False)
    return jax.jit(smapped, donate_argnums=(0, 1, 2))


def make_bench_state(model_name: str = "resnet50", batch_size: int = 64,
                     image_size: int = 224, num_classes: int = 1000,
                     input_dtype: str = "float32", stem: str = "conv7",
                     remat: Optional[str] = None, mesh=None,
                     learning_rate: float = 0.01):
    """The ONE benchmark-state recipe, shared by the throughput run, the
    --profile path and the standalone profiling tools so they always
    measure the same program.  Returns ``(mesh, ax, model, optimizer,
    s2d, (params, batch_stats, opt_state), (images, labels))`` with the
    batch sharded over the data axis and state replicated.
    """
    from horovod_tpu.models import get_model

    if not hvd.is_initialized():
        hvd.init()
    mesh = mesh if mesh is not None else hvd.mesh()
    ax = data_axis(mesh)
    global_bs = batch_size * mesh_size(mesh)

    # "s2d": space-to-depth input pipeline + exact 4x4/s1 stem
    # reparameterization (models/resnet.py:space_to_depth) — input arrives
    # packed [B, H/2, W/2, 12], a pure relayout done once host-side.
    # "s2d_fused" additionally runs BN-apply+relu+maxpool as one fused
    # pass (ops/fused_stem.py) — same packed input pipeline.
    if stem not in ("conv7", "s2d", "s2d_fused"):
        raise ValueError(f"stem={stem!r}: expected 'conv7', 's2d' or "
                         f"'s2d_fused'")
    s2d = stem in ("s2d", "s2d_fused") and model_name.startswith("resnet")
    extra = {}
    if s2d:
        extra["stem"] = stem
    if remat and model_name.startswith("resnet"):
        extra["remat"] = remat
    model = get_model(model_name, num_classes=num_classes, **extra)
    init_shape = ((1, image_size // 2, image_size // 2, 12) if s2d
                  else (1, image_size, image_size, 3))
    rng = jax.random.PRNGKey(0)
    variables = model.init(rng, jnp.zeros(init_shape, jnp.float32),
                           train=False)
    params, batch_stats = variables["params"], variables["batch_stats"]
    optimizer = optax.sgd(learning_rate, momentum=0.9)
    opt_state = optimizer.init(params)

    # Fixed synthetic batch, placed sharded on the data axis (reference keeps
    # one random batch for the whole run, :40-43).  ``input_dtype="bfloat16"``
    # feeds the batch in the model's compute dtype — the TPU-idiomatic input
    # pipeline (halves the first conv's HBM read; training semantics are
    # unchanged since the model casts to bf16 anyway).
    images_np = np.random.default_rng(0).standard_normal(
        (global_bs, image_size, image_size, 3), dtype=np.float32)
    if s2d:
        from horovod_tpu.models.resnet import space_to_depth
        images_np = space_to_depth(images_np)
    # Cast host-side (ml_dtypes handles bf16 in numpy) so device_put still
    # uploads only per-shard slices; a jnp cast would stage the full
    # global batch on one device first.
    images = jax.device_put(
        images_np.astype(jnp.dtype(input_dtype)),
        NamedSharding(mesh, P(ax)))
    labels = jax.device_put(
        np.random.default_rng(1).integers(0, num_classes, (global_bs,),
                                          dtype=np.int32),
        NamedSharding(mesh, P(ax)))
    repl = NamedSharding(mesh, P())
    params, batch_stats, opt_state = jax.device_put(
        (params, batch_stats, opt_state), repl)
    return (mesh, ax, model, optimizer, s2d,
            (params, batch_stats, opt_state), (images, labels))


def run_synthetic_benchmark(model_name: str = "resnet50",
                            batch_size: int = 64,
                            image_size: int = 224,
                            num_classes: int = 1000,
                            num_warmup_batches: int = 5,
                            num_batches_per_iter: int = 10,
                            num_iters: int = 10,
                            learning_rate: float = 0.01,
                            mesh=None,
                            per_step_dispatch: bool = False,
                            input_dtype: str = "float32",
                            stem: str = "conv7",
                            remat: Optional[str] = None,
                            verbose: bool = True) -> dict:
    """Run the ResNet synthetic benchmark; returns a result dict.

    ``batch_size`` is per chip, as in the reference (``--batch-size`` is per
    worker, ``tensorflow2_synthetic_benchmark.py:20``).
    """
    (mesh, ax, model, optimizer, s2d,
     (params, batch_stats, opt_state),
     (images, labels)) = make_bench_state(
        model_name, batch_size, image_size=image_size,
        num_classes=num_classes, input_dtype=input_dtype, stem=stem,
        remat=remat, mesh=mesh, learning_rate=learning_rate)
    n_chips = mesh_size(mesh)
    global_bs = batch_size * n_chips

    # Fused dispatch (default): each timed round is ONE compiled program
    # of num_batches_per_iter scanned steps, so host->device dispatch
    # latency (~100 ms round trip on tunneled PJRT) is paid once per
    # round, not once per step.  ``per_step_dispatch`` restores the
    # reference's per-step dispatch shape for comparison.
    steps_per_call = 1 if per_step_dispatch else max(num_batches_per_iter,
                                                     1)
    step = make_train_step(model, optimizer, mesh, ax,
                           steps_per_call=steps_per_call)

    # AOT-compile and execute through the compiled object: one compile
    # (shapes are fixed for the whole run), and XLA's own FLOP count comes
    # with it for MFU accounting.  This backend's cost analysis counts a
    # scan body ONCE (verified: the scanned module reports the same flops
    # as a single step), so the module figure already IS per-step; guard
    # against an XLA that multiplies by trip count by comparing with the
    # analytic estimate.
    flops_per_step = None
    try:
        compiled = step.lower(params, batch_stats, opt_state, images,
                              labels).compile()
        flops_per_step = _step_flops(compiled, model_name, global_bs,
                                     image_size, n_chips)
        analytic = _step_flops(None, model_name, global_bs, image_size,
                               n_chips)
        if (flops_per_step and analytic and steps_per_call > 1 and
                flops_per_step > 2.5 * analytic):
            flops_per_step /= steps_per_call
        if flops_per_step and s2d:
            # XLA counts the 45 structurally-zero tap-channels of the
            # reparameterized 4x4x(4*3) stem (conv7_to_s2d_weights zeroes
            # them) as FLOPs; subtract so MFU stays comparable with the
            # conv7 stem (fwd+bwd(dX)+bwd(dW) ~= 3x fwd).
            out_hw = (image_size // 2) ** 2
            flops_per_step -= 3 * 2 * global_bs * out_hw * 45 * 64
        step = compiled
    except Exception:
        flops_per_step = _step_flops(None, model_name, global_bs,
                                     image_size, n_chips)

    if verbose:
        print(f"Model: {model_name}", flush=True)
        print(f"Batch size: {batch_size} per chip, {global_bs} global "
              f"({n_chips} chips)", flush=True)

    # Sync point: a tiny scalar D2H transfer of the loss.  On tunneled/remote
    # PJRT platforms `block_until_ready` can return before device execution
    # finishes; fetching the scalar output is the reliable barrier (and the
    # loss of step N depends on every prior step's params, so it fences the
    # whole round).
    # Fused mode rounds warmup UP to whole calls; 0 stays 0 (the timed
    # loop runs the already-compiled object either way).
    warmup_calls = (num_warmup_batches if steps_per_call == 1 else
                    -(-num_warmup_batches // steps_per_call))
    for _ in range(warmup_calls):
        params, batch_stats, opt_state, loss = step(
            params, batch_stats, opt_state, images, labels)
    if warmup_calls > 0:
        float(np.asarray(loss))

    calls_per_iter = (num_batches_per_iter if steps_per_call == 1 else 1)
    img_secs = []
    for i in range(num_iters):
        t0 = time.perf_counter()
        for _ in range(calls_per_iter):
            params, batch_stats, opt_state, loss = step(
                params, batch_stats, opt_state, images, labels)
        float(np.asarray(loss))
        dt = time.perf_counter() - t0
        img_sec = global_bs * num_batches_per_iter / dt
        img_secs.append(img_sec)
        if verbose:
            print(f"Iter #{i}: {img_sec:.1f} img/sec total", flush=True)

    img_sec_mean = float(np.mean(img_secs))
    img_sec_conf = float(1.96 * np.std(img_secs))

    # Achieved TFLOP/s + MFU (BASELINE.md asks for utilization, not just
    # throughput: 2260 img/sec that is 10% MFU is unfinished work).
    tflops_per_chip = None
    mfu = None
    if flops_per_step:
        steps_per_sec = img_sec_mean / global_bs
        tflops_per_chip = flops_per_step * steps_per_sec / n_chips / 1e12
        peak = device_peak_tflops(mesh.devices.ravel()[0])
        if peak:
            mfu = tflops_per_chip / peak

    if verbose:
        print(f"Img/sec per chip: {img_sec_mean / n_chips:.1f} "
              f"+-{img_sec_conf / n_chips:.1f}", flush=True)
        print(f"Total img/sec on {n_chips} chip(s): "
              f"{img_sec_mean:.1f} +-{img_sec_conf:.1f}", flush=True)
        if tflops_per_chip is not None:
            mfu_s = f", MFU {mfu * 100:.1f}%" if mfu is not None else ""
            print(f"Achieved {tflops_per_chip:.1f} TFLOP/s per chip"
                  f"{mfu_s}", flush=True)
    return {
        "model": model_name,
        "batch_size_per_chip": batch_size,
        "stem": stem if s2d else "conv7",
        "n_chips": n_chips,
        "img_sec_total": img_sec_mean,
        "img_sec_conf": img_sec_conf,
        "img_sec_per_chip": img_sec_mean / n_chips,
        "flops_per_step": flops_per_step,
        "tflops_per_chip": tflops_per_chip,
        "mfu": mfu,
        "loss": float(np.asarray(loss)),
    }


def _device_memory_report(verbose: bool = True) -> list:
    """Per-device live/peak HBM bytes from ``device.memory_stats()``.

    The PJRT CPU backend reports no memory stats — entries carry ``None``
    there (the benchmark still runs; only the numbers are TPU-only)."""
    rows = []
    for d in jax.local_devices():
        try:
            ms = d.memory_stats() or {}
        except Exception:
            ms = {}
        rows.append({
            "device": str(d),
            "bytes_in_use": ms.get("bytes_in_use"),
            "peak_bytes_in_use": ms.get("peak_bytes_in_use"),
        })
    if verbose:
        for r in rows:
            if r["bytes_in_use"] is None:
                print(f"  {r['device']}: memory_stats unavailable "
                      f"(CPU backend)", flush=True)
            else:
                peak = r["peak_bytes_in_use"]
                peak_s = (f", peak {peak / 2**20:,.1f} MiB"
                          if peak is not None else "")
                print(f"  {r['device']}: live "
                      f"{r['bytes_in_use'] / 2**20:,.1f} MiB{peak_s}",
                      flush=True)
    return rows


def _tree_bytes_per_device(tree) -> Optional[int]:
    """Bytes one device holds for ``tree``: per-leaf, the first addressable
    shard's size (a ``P()`` leaf contributes its full size, a ``P(ax)``
    leaf 1/N — exactly the ZeRO memory story the benchmark reports)."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        try:
            total += leaf.addressable_shards[0].data.nbytes
        except (AttributeError, IndexError):
            try:
                total += leaf.nbytes
            except AttributeError:
                return None
    return total


def lm_train_flops(cfg, global_bs: int) -> float:
    """Analytic GLOBAL FLOPs of one LM training step — the standard MFU
    accounting (PaLM appendix-B convention): ``6·N·tokens`` for every
    matmul parameter (2 fwd + 4 bwd FLOPs per param per token; embedding
    LOOKUP excluded, tied logits head included) plus causal attention
    ``6·B·T²·d·L`` (QKᵀ and PV are 4·B·T²·d per layer fwd, 3x for
    train, halved by causality).  Rematerialization recompute is NOT
    counted (MFU counts model FLOPs, not hardware FLOPs)."""
    d, f, v = cfg.d_model, cfg.d_ff, cfg.vocab_size
    l, t = cfg.n_layers, cfg.max_seq
    n_matmul = l * (4 * d * d + 2 * d * f) + d * v
    tokens = global_bs * t
    return 6.0 * n_matmul * tokens + 6.0 * global_bs * t * t * d * l


def run_lm_benchmark(d_model: int = 2048, n_layers: int = 8,
                     n_heads: int = 16, d_ff: Optional[int] = None,
                     vocab_size: int = 32768, seq_len: int = 2048,
                     batch_size: int = 8,
                     attention: str = "flash", remat: str = "none",
                     num_warmup_batches: int = 2,
                     num_batches_per_iter: int = 8, num_iters: int = 5,
                     learning_rate: float = 1e-4, mesh=None,
                     shard_optimizer: bool = False,
                     compression: Optional[str] = None,
                     verbose: bool = True) -> dict:
    """Transformer-LM synthetic training benchmark (single chip by
    default) — the compute-bound counterpart to the ResNet harness:
    same protocol (fixed synthetic batch, scanned rounds, loss-fetch
    sync barrier), flash attention + optional remat, fp32 master
    weights with ``cfg.dtype`` (bf16 on TPU) matmuls.

    MFU here uses the ANALYTIC model-FLOPs count (:func:`lm_train_flops`)
    — XLA's cost analysis cannot see inside the Pallas flash kernel, and
    counting remat recompute would inflate the number; the dict carries
    the raw cost-analysis figure too so the two can be compared.

    ``shard_optimizer=True`` runs the ZeRO-1 sharded-update lane
    (:mod:`horovod_tpu.parallel.zero`; defaults the mesh to ALL devices —
    sharding the update on one chip buys nothing) and reports per-device
    live-memory bytes next to MFU, since memory headroom is half the
    point of sharding the optimizer state.  ``compression`` selects a
    gradient wire codec (``"none"``, ``"bf16"``, ``"fp16"``, ``"int8"``,
    ``"powersgd[:rank]"``) riding that wire — see
    :mod:`horovod_tpu.ops.compression`."""
    from horovod_tpu.models import transformer as tfm

    if mesh is None:
        devices = jax.devices() if shard_optimizer else jax.devices()[:1]
        mesh = build_mesh(axes=("data",), shape=(len(devices),),
                          devices=devices)
    n_chips = mesh_size(mesh)
    global_bs = batch_size * n_chips
    on_cpu = mesh.devices.ravel()[0].platform == "cpu"
    cfg = tfm.TransformerConfig(
        vocab_size=vocab_size, d_model=d_model, n_heads=n_heads,
        n_layers=n_layers, d_ff=d_ff or 4 * d_model, max_seq=seq_len,
        dtype=jnp.float32 if on_cpu else jnp.bfloat16)

    # SGD+momentum (the ResNet harness's optimizer): one slot per param —
    # adam's two would displace ~4 GB of batch/activations at the
    # compute-bound sizes this harness exists to measure.  BENCH_LM
    # protocol keeps the slot bf16 (halves optimizer HBM so batch 8 fits
    # at d4096; fp32 master weights unchanged).
    acc_dtype = os.environ.get("BENCH_LM_MOMENTUM_DTYPE", "bfloat16")
    optimizer = optax.sgd(learning_rate, momentum=0.9,
                          accumulator_dtype=jnp.dtype(acc_dtype).type
                          if acc_dtype != "float32" else None)
    steps_per_call = max(num_batches_per_iter, 1)
    step, specs, opt_specs = tfm.make_train_step(
        cfg, optimizer, mesh, data_axis="data", attention=attention,
        remat=remat, steps_per_call=steps_per_call,
        shard_optimizer=shard_optimizer, compression=compression)

    params = tfm.init_params(jax.random.PRNGKey(0), cfg)
    params = jax.device_put(params, jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), specs))
    init_state = step.init if shard_optimizer else optimizer.init
    opt_state = jax.device_put(
        init_state(params), jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s), opt_specs,
            is_leaf=lambda x: isinstance(x, P)))

    rng = np.random.default_rng(0)
    data_sh = NamedSharding(mesh, P("data"))
    toks = rng.integers(0, vocab_size, (global_bs, seq_len + 1),
                        dtype=np.int32)
    tokens = jax.device_put(toks[:, :-1], data_sh)
    labels = jax.device_put(toks[:, 1:], data_sh)

    flops_per_step = lm_train_flops(cfg, global_bs)
    xla_flops = None
    try:
        compiled = step.lower(params, opt_state, tokens, labels).compile()
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        xla_flops = float(ca.get("flops", 0.0)) * n_chips or None
        step = compiled
    except Exception:
        pass

    if verbose:
        comp_s = f" compression={compression}" if compression else ""
        print(f"LM: d_model={d_model} n_layers={n_layers} d_ff="
              f"{cfg.d_ff} vocab={vocab_size} T={seq_len} "
              f"batch={global_bs} attention={attention} remat={remat} "
              f"shard_optimizer={shard_optimizer}{comp_s} "
              f"chips={n_chips}", flush=True)
        print(f"Analytic {flops_per_step / 1e12:.2f} TFLOP/step "
              f"({flops_per_step / (global_bs * seq_len) / 1e6:.1f} "
              f"MFLOP/token)", flush=True)

    # Same sync protocol as the ResNet harness: the loss scalar fetch is
    # the reliable barrier on tunneled PJRT backends.
    for _ in range(max(1, -(-num_warmup_batches // steps_per_call))):
        params, opt_state, loss = step(params, opt_state, tokens, labels)
    float(np.asarray(loss))

    tok_secs = []
    for i in range(num_iters):
        t0 = time.perf_counter()
        params, opt_state, loss = step(params, opt_state, tokens, labels)
        float(np.asarray(loss))
        dt = time.perf_counter() - t0
        tok_sec = global_bs * seq_len * steps_per_call / dt
        tok_secs.append(tok_sec)
        if verbose:
            print(f"Iter #{i}: {tok_sec:,.0f} tok/sec", flush=True)

    tok_sec_mean = float(np.mean(tok_secs))
    steps_per_sec = tok_sec_mean / (global_bs * seq_len)
    tflops_per_chip = flops_per_step * steps_per_sec / n_chips / 1e12
    peak = device_peak_tflops(mesh.devices.ravel()[0])
    mfu = tflops_per_chip / peak if peak else None
    opt_bytes = _tree_bytes_per_device(opt_state)
    if verbose:
        mfu_s = f", MFU {mfu * 100:.1f}%" if mfu is not None else ""
        print(f"{tok_sec_mean / n_chips:,.0f} tok/sec/chip, "
              f"{tflops_per_chip:.1f} TFLOP/s per chip{mfu_s}",
              flush=True)
        if opt_bytes is not None:
            print(f"Optimizer state per device: {opt_bytes / 2**20:,.1f} "
                  f"MiB" + (" (ZeRO-1 sharded 1/%d)" % n_chips
                            if shard_optimizer else " (replicated)"),
                  flush=True)
        print("Per-device memory:", flush=True)
    memory = _device_memory_report(verbose=verbose)
    return {
        "d_model": d_model, "n_layers": n_layers, "d_ff": cfg.d_ff,
        "n_heads": n_heads, "vocab_size": vocab_size,
        "seq_len": seq_len, "batch_size": global_bs,
        "attention": attention, "remat": remat,
        "shard_optimizer": shard_optimizer,
        "compression": compression, "n_chips": n_chips,
        "tok_sec_per_chip": tok_sec_mean / n_chips,
        "tok_sec_conf": float(1.96 * np.std(tok_secs)) / n_chips,
        "flops_per_step_analytic": flops_per_step,
        "flops_per_step_xla": xla_flops,
        "tflops_per_chip": tflops_per_chip,
        "mfu": mfu,
        "opt_state_bytes_per_device": opt_bytes,
        "memory": memory,
        "loss": float(np.asarray(loss)),
    }


def run_decode_benchmark(d_model: int = 2048, n_layers: int = 8,
                         n_heads: int = 16, vocab_size: int = 32768,
                         batch_size: int = 8, prompt_len: int = 16,
                         total_len: int = 512, num_iters: int = 3,
                         verbose: bool = True) -> dict:
    """Greedy-decode (KV-cache) throughput: new tokens/sec and ms/step.

    Decode is HBM-bandwidth-bound (every step reads the full weight
    set); the scanned ``generate`` loop compiles to one program, so the
    measured ms/step is the device cost.  bf16 on TPU."""
    from horovod_tpu.models import transformer as tfm

    if prompt_len >= total_len:
        raise ValueError(f"prompt_len ({prompt_len}) must be < "
                         f"total_len ({total_len}) to decode anything")
    on_cpu = jax.devices()[0].platform == "cpu"
    cfg = tfm.TransformerConfig(
        vocab_size=vocab_size, d_model=d_model, n_heads=n_heads,
        n_layers=n_layers, d_ff=4 * d_model, max_seq=total_len,
        dtype=jnp.float32 if on_cpu else jnp.bfloat16)
    params = tfm.init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(
        rng.integers(0, vocab_size, (batch_size, prompt_len)), jnp.int32)
    gen = jax.jit(lambda p, pr: tfm.generate(p, pr, total_len, cfg))
    out = gen(params, prompt)
    int(np.asarray(out)[0, -1])           # sync barrier (scalar fetch)
    t0 = time.perf_counter()
    for _ in range(num_iters):
        out = gen(params, prompt)
    int(np.asarray(out)[0, -1])
    dt = (time.perf_counter() - t0) / num_iters
    new_tokens = batch_size * (total_len - prompt_len)
    # generate's scan runs total_len - 1 decode steps (prompt positions
    # are teacher-forced but still stepped); per-step latency divides
    # by the STEPS, tok/s by the NEW tokens.
    res = {
        "d_model": d_model, "n_layers": n_layers,
        "batch_size": batch_size, "total_len": total_len,
        "decode_tok_sec": new_tokens / dt,
        "ms_per_step": dt / (total_len - 1) * 1e3,
    }
    if verbose:
        print(f"decode d{d_model} L{n_layers} B{batch_size}: "
              f"{res['decode_tok_sec']:,.0f} tok/s, "
              f"{res['ms_per_step']:.2f} ms/step", flush=True)
    return res


def run_scaling_efficiency(model_name: str = "resnet50",
                           batch_size: int = 64,
                           n_devices: Optional[int] = None,
                           verbose: bool = True,
                           **bench_kwargs) -> dict:
    """Weak-scaling efficiency: img_sec_N / (N * img_sec_1).

    The reference's headline metric (README.rst:75 — 90% on 512 GPUs,
    measured by the same synthetic harness).  Per-chip batch is fixed
    (weak scaling), so perfect scaling doubles total img/sec per doubling
    of chips.  On a single-chip host this runs over the virtual CPU mesh —
    the efficiency *plumbing* is identical; real numbers need real chips.
    """
    # init() first: on multi-host it runs jax.distributed.initialize, which
    # must precede any backend-initializing call like jax.devices().
    if not hvd.is_initialized():
        hvd.init()
    devices = list(jax.devices())
    n = n_devices or len(devices)
    if n < 2:
        raise ValueError(f"scaling efficiency needs >= 2 devices, have {n}")

    # Baseline mesh: the FIRST device of every process.  On a single host
    # that is one device; on a multi-host pod every process keeps an
    # addressable device in the baseline mesh (a devices[:1] mesh would
    # strand the other hosts — jax.device_put rejects shardings with no
    # local device).  Efficiency is then img_sec_n / (growth * img_sec_base)
    # where growth = n / len(baseline): weak scaling from one chip per host
    # to all chips per host.
    by_process: dict = {}
    for d in devices[:n]:
        by_process.setdefault(getattr(d, "process_index", 0), d)
    base_devices = [by_process[k] for k in sorted(by_process)]
    n_base = len(base_devices)
    if n_base >= n:
        raise ValueError(
            f"scaling efficiency needs more total devices ({n}) than "
            f"baseline devices ({n_base}; one per process)")

    mesh_1 = build_mesh(axes=("data",), shape=(n_base,),
                        devices=base_devices)
    mesh_n = build_mesh(axes=("data",), shape=(n,), devices=devices[:n])

    res_1 = run_synthetic_benchmark(model_name, batch_size, mesh=mesh_1,
                                    verbose=False, **bench_kwargs)
    res_n = run_synthetic_benchmark(model_name, batch_size, mesh=mesh_n,
                                    verbose=False, **bench_kwargs)

    growth = n / n_base
    efficiency = res_n["img_sec_total"] / (growth * res_1["img_sec_total"])
    if verbose:
        print(f"{n_base} device(s): {res_1['img_sec_total']:.1f} img/sec",
              flush=True)
        print(f"{n} devices: {res_n['img_sec_total']:.1f} img/sec "
              f"(perfect: {growth * res_1['img_sec_total']:.1f})", flush=True)
        print(f"Scaling efficiency: {efficiency * 100:.1f}%", flush=True)
    return {
        "model": model_name,
        "n_devices": n,
        "n_baseline_devices": n_base,
        "img_sec_1": res_1["img_sec_total"],
        "img_sec_n": res_n["img_sec_total"],
        "scaling_efficiency": efficiency,
    }


def run_profile(model_name: str = "resnet50", batch_size: int = 64,
                image_size: int = 224, steps: int = 10,
                input_dtype: str = "bfloat16", stem: str = "conv7",
                remat: Optional[str] = None, mesh=None) -> None:
    """Trace ``steps`` scanned training steps with jax.profiler and print
    the per-fusion-category and per-layer device-time breakdown — the
    device-side complement of the native runtime's chrome timeline
    (docs/benchmarks.md's roofline section was produced with this).
    Same state recipe as the throughput benchmark (make_bench_state), so
    the profile explains exactly the program the benchmark measures."""
    from horovod_tpu.utils import profiling

    (mesh, ax, model, optimizer, _s2d,
     (params, batch_stats, opt_state),
     (images, labels)) = make_bench_state(
        model_name, batch_size, image_size=image_size,
        input_dtype=input_dtype, stem=stem, remat=remat, mesh=mesh)

    step = make_train_step(model, optimizer, mesh, ax,
                           steps_per_call=steps)
    compiled = step.lower(params, batch_stats, opt_state, images,
                          labels).compile()
    # The step donates its state buffers — rethread them through each call.
    state = compiled(params, batch_stats, opt_state, images, labels)
    float(np.asarray(state[3]))    # warm + real barrier

    def run():
        nonlocal state
        state = compiled(state[0], state[1], state[2], images, labels)
        float(np.asarray(state[3]))

    trace = profiling.trace_once(run)
    profiling.print_profile(trace, compiled.as_text(), steps=steps)


def run_step_guard_benchmark(model_name: str = "resnet50",
                             batch_size: int = 64,
                             verbose: bool = True,
                             **kwargs) -> dict:
    """Measure the step-guard overhead (docs/fault_tolerance.md): run the
    synthetic benchmark twice — once with ``HOROVOD_STEP_GUARD`` unset
    (baseline) and once with policy ``skip`` (the in-graph finiteness
    psum + per-leaf select compiled into the step) — and report the
    throughput delta.  The policy is read at trace time, so each run
    builds and compiles a fresh step.  Target: < 2% step time.

    Prints one BENCH JSON line
    (``{"metric": "step_guard_overhead_pct", ...}``) and returns the same
    dict."""
    import json

    prev = os.environ.pop("HOROVOD_STEP_GUARD", None)
    try:
        base = run_synthetic_benchmark(model_name, batch_size,
                                       verbose=False, **kwargs)
        os.environ["HOROVOD_STEP_GUARD"] = "skip"
        guarded = run_synthetic_benchmark(model_name, batch_size,
                                          verbose=False, **kwargs)
    finally:
        if prev is None:
            os.environ.pop("HOROVOD_STEP_GUARD", None)
        else:
            os.environ["HOROVOD_STEP_GUARD"] = prev
    overhead_pct = ((base["img_sec_total"] - guarded["img_sec_total"])
                    / base["img_sec_total"] * 100.0)
    result = {
        "metric": "step_guard_overhead_pct",
        "value": round(overhead_pct, 3),
        "unit": "%",
        "target_pct": 2.0,
        "model": model_name,
        "baseline_img_sec": round(base["img_sec_total"], 1),
        "guarded_img_sec": round(guarded["img_sec_total"], 1),
    }
    if verbose:
        print(f"Step guard overhead: {overhead_pct:.2f}% "
              f"({base['img_sec_total']:.1f} -> "
              f"{guarded['img_sec_total']:.1f} img/sec; target < 2%)",
              flush=True)
    print("BENCH " + json.dumps(result), flush=True)
    return result


def run_compression_benchmark(codec: str = "int8", verbose: bool = True,
                              **lm_kwargs) -> dict:
    """Gradient-compression A/B on the LM ZeRO lane (docs/performance.md):
    run :func:`run_lm_benchmark` twice from identical seeds — once with
    the uncompressed wire (``compression="none"``) and once with
    ``codec`` — and report the loss delta at equal steps next to the
    logical wire-byte ratio from ``hvd_collective_bytes_total``
    (reduce-scatter + all-gather planes, diffed per run so repeated
    invocations don't pollute each other).

    The bytes counters are recorded at trace time, so the ratio is the
    codec's logical transport saving, independent of host speed; the
    loss delta is the error-feedback quality gate (target < 1%).

    Prints one BENCH JSON line
    (``{"metric": "compression_wire_ratio", ...}``) and returns the same
    dict."""
    import json

    from horovod_tpu import telemetry
    from horovod_tpu.ops import compression as compression_mod
    from horovod_tpu.telemetry import aggregate

    name = compression_mod.resolve_codec(codec).name
    if name == "none":
        raise ValueError(
            "--compression needs a real codec (bf16, fp16, int8, "
            "powersgd[:rank]); the lane already compares against 'none'")
    # The codec rides the ZeRO reduce-scatter wire; force the sharded
    # lane regardless of what the caller passed.
    lm_kwargs["shard_optimizer"] = True
    was_enabled = telemetry.enabled()
    telemetry.configure(enabled_flag=True)

    def _wire_bytes(before, after, codec_name):
        return sum(
            aggregate.counter_total(after, "hvd_collective_bytes_total",
                                    {"kind": kind, "codec": codec_name})
            - aggregate.counter_total(before, "hvd_collective_bytes_total",
                                      {"kind": kind, "codec": codec_name})
            for kind in ("reduce_scatter", "all_gather"))

    try:
        snap0 = telemetry.metrics_snapshot()
        base = run_lm_benchmark(compression="none", verbose=verbose,
                                **lm_kwargs)
        snap1 = telemetry.metrics_snapshot()
        comp = run_lm_benchmark(compression=codec, verbose=verbose,
                                **lm_kwargs)
        snap2 = telemetry.metrics_snapshot()
    finally:
        telemetry.configure(enabled_flag=was_enabled)

    bytes_none = _wire_bytes(snap0, snap1, "none")
    bytes_codec = _wire_bytes(snap1, snap2, name)
    ratio = (bytes_none / bytes_codec) if bytes_codec else float("inf")
    loss_delta_pct = (abs(comp["loss"] - base["loss"])
                      / max(abs(base["loss"]), 1e-12) * 100.0)
    # Acceptance floors (docs/performance.md): int8 packs 4 fp32 bytes
    # into ~1 wire byte (minus per-bucket qparams), casts halve them.
    target = {"int8": 3.0, "bf16": 1.9, "fp16": 1.9}.get(name)
    result = {
        "metric": "compression_wire_ratio",
        "codec": name,
        "value": round(ratio, 3),
        "target_ratio": target,
        "wire_bytes_none": int(bytes_none),
        "wire_bytes_codec": int(bytes_codec),
        "loss_none": round(base["loss"], 6),
        "loss_codec": round(comp["loss"], 6),
        "loss_delta_pct": round(loss_delta_pct, 4),
        "loss_target_pct": 1.0,
        "n_chips": base["n_chips"],
        "d_model": base["d_model"],
        "n_layers": base["n_layers"],
        "tok_sec_per_chip_none": round(base["tok_sec_per_chip"], 1),
        "tok_sec_per_chip_codec": round(comp["tok_sec_per_chip"], 1),
    }
    if verbose:
        tgt = f" (target >= {target}x)" if target else ""
        print(f"Compression {name}: wire bytes {int(bytes_none):,} -> "
              f"{int(bytes_codec):,} ({ratio:.2f}x{tgt}); loss "
              f"{base['loss']:.5f} -> {comp['loss']:.5f} "
              f"({loss_delta_pct:.3f}% delta, target < 1%)", flush=True)
    print("BENCH " + json.dumps(result), flush=True)
    return result


def run_hierarchical_worker(sizes=(1 << 16, 1 << 20),
                            iters: int = 8) -> None:
    """Worker half of ``--hierarchical`` (spawned by the driver under
    ``hvdrun -np 4``; detected by ``HOROVOD_RANK`` being set).

    Simulates a 2x2 host split on loopback (the
    tests/distributed/hier_check_np4.py trick: override
    ``HOROVOD_LOCAL_*`` before init so the bootstrap agreement sees two
    2-slot hosts), asserts the ``hier_allreduce`` knob is observed LIVE
    in ``runtime.tuned_config()`` in exactly the mode the driver
    requested, then times eager allreduces of each payload size.  Rank 0
    prints one ``HIERBENCH {json}`` line per size for the driver to
    parse."""
    import json

    rank = int(os.environ["HOROVOD_RANK"])
    size = int(os.environ["HOROVOD_SIZE"])
    local = max(size // 2, 1)
    # Override unconditionally: the loopback launcher exports
    # LOCAL_SIZE=np (one host), which makes the topology ineligible.
    os.environ["HOROVOD_LOCAL_SIZE"] = str(local)
    os.environ["HOROVOD_LOCAL_RANK"] = str(rank % local)
    hvd.init()
    from horovod_tpu import basics

    rt = basics.runtime()
    hier = os.environ.get("HOROVOD_HIERARCHICAL_ALLREDUCE", "0") == "1"
    cfg = rt.tuned_config()
    assert cfg.get("hier_allreduce") is hier, \
        f"tuned_config() does not reflect the requested routing: {cfg}"
    if hier:
        assert rt.hierarchical_enabled(), \
            "hierarchical allreduce did not engage"
    rows = []
    for n in sizes:
        x = np.random.default_rng(rank).standard_normal(n).astype(
            np.float32)
        for i in range(2):
            hvd.allreduce(x, average=False, name=f"hb.warm{i}.{n}")
        t0 = time.perf_counter()
        for i in range(iters):
            hvd.allreduce(x, average=False, name=f"hb.{i}.{n}")
        dt = (time.perf_counter() - t0) / iters
        rows.append({"size": n, "sec_per_op": dt,
                     "mb_per_sec": n * 4 / dt / 2**20})
    # Rank-agreed view — the collective the fusion bucketer follows.
    agreed = rt.sync_tuned_config()
    assert agreed.get("hier_allreduce") is hier, agreed
    hvd.shutdown()
    if rank == 0:
        for r in rows:
            print("HIERBENCH " + json.dumps(r), flush=True)


def run_hierarchical_benchmark(np_ranks: int = 4,
                               out: Optional[str] = None,
                               verbose: bool = True) -> dict:
    """Hierarchical-vs-flat eager allreduce A/B (docs/performance.md,
    'Hierarchical collectives'): spawn two ``hvdrun -np 4`` loopback
    runs of :func:`run_hierarchical_worker` — flat ring vs the 2-level
    local-RS / leader-ring / local-AG path — and report per-size
    latency side by side.

    On the loopback rig both levels ride the same TCP stack, so the
    latency delta only bounds the SOFTWARE overhead of the extra local
    phases; the transport win (cross-"host" bytes shrink by
    1/local_size, asserted exactly by the CI np=4 telemetry gate) pays
    off where DCN is the bottleneck.  Each worker asserts the
    ``hier_allreduce`` knob is observed live in ``tuned_config()`` and
    in the rank-agreed ``sync_tuned_config()`` view, so a passing run
    certifies the knob plumbing end to end.

    Prints one BENCH JSON line and (with ``out``) writes the same dict
    as a JSON artifact (CI commits ``BENCH_hier.json``)."""
    import json
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    def launch(hier: bool) -> list:
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        env["HOROVOD_HIERARCHICAL_ALLREDUCE"] = "1" if hier else "0"
        env["HOROVOD_HIERARCHICAL_ALLREDUCE_THRESHOLD"] = "0"
        cmd = [sys.executable, "-m", "horovod_tpu.runner",
               "-np", str(np_ranks),
               sys.executable, "-m", "horovod_tpu.benchmark",
               "--hierarchical"]
        p = subprocess.run(cmd, env=env, capture_output=True, text=True,
                           timeout=600)
        if p.returncode != 0:
            raise RuntimeError(
                f"hierarchical bench run (hier={hier}) failed rc="
                f"{p.returncode}\n{p.stdout[-2000:]}\n{p.stderr[-2000:]}")
        rows = [json.loads(line.split("HIERBENCH ", 1)[1])
                for line in p.stdout.splitlines() if "HIERBENCH " in line]
        if not rows:
            raise RuntimeError(
                f"hierarchical bench run (hier={hier}) printed no "
                f"HIERBENCH rows:\n{p.stdout[-2000:]}")
        return rows

    flat = {r["size"]: r for r in launch(False)}
    hier = {r["size"]: r for r in launch(True)}
    assert flat.keys() == hier.keys(), (flat, hier)
    sizes = []
    for n in sorted(flat):
        sizes.append({
            "size": n,
            "flat_sec_per_op": round(flat[n]["sec_per_op"], 6),
            "hier_sec_per_op": round(hier[n]["sec_per_op"], 6),
            "speedup": round(flat[n]["sec_per_op"]
                             / hier[n]["sec_per_op"], 3),
        })
    result = {
        "metric": "hierarchical_allreduce_latency",
        "np": np_ranks,
        "local_size": max(np_ranks // 2, 1),
        "knob_observed_live": True,   # every worker asserted it
        "cross_bytes_ratio": "1/local_size (asserted exactly by the "
                             "np=4 CI telemetry gate)",
        "sizes": sizes,
        "note": "loopback CPU rig: both levels share one TCP stack, so "
                "this bounds software overhead only; DCN wins need "
                "real pods",
    }
    if verbose:
        for s in sizes:
            print(f"allreduce {s['size']:>8} floats: flat "
                  f"{s['flat_sec_per_op'] * 1e3:.2f} ms, hier "
                  f"{s['hier_sec_per_op'] * 1e3:.2f} ms "
                  f"({s['speedup']:.2f}x)", flush=True)
    print("BENCH " + json.dumps(result), flush=True)
    if out:
        with open(out, "w") as f:
            json.dump(result, f, indent=2)
            f.write("\n")
    return result


def _transport_backend_totals(rt) -> dict:
    """Sum ``Runtime.transport_counters()`` across levels into one
    ``{backend: {bytes, seconds, ops}}`` dict (zero-filled)."""
    totals = {b: {"bytes": 0, "seconds": 0.0, "ops": 0}
              for b in ("socket", "shm", "striped")}
    for (backend, _level), kinds in rt.transport_counters().items():
        row = totals[backend]
        row["bytes"] += kinds["bytes"]
        row["seconds"] += kinds["seconds"]
        row["ops"] += kinds["ops"]
    return totals


def run_transport_worker(sizes=(1 << 20, 1 << 24),
                         iters: int = 6) -> None:
    """Worker half of ``--transport`` (spawned by the driver under
    ``hvdrun -np 2``; detected by ``HOROVOD_RANK`` being set).

    Times eager allreduces per payload size under whatever transport the
    driver forced via ``HOROVOD_TRANSPORT``/``HOROVOD_TRANSPORT_STRIPES``,
    asserts the expected backend actually carried the bytes
    (``TRANSPORT_BENCH_EXPECT``; a silent fallback would invalidate the
    A/B), and snapshots the transport counters around each timed loop so
    every row also reports link-level pump bandwidth — the end-to-end
    number folds in submit/fusion/reduce costs shared by all lanes, the
    link number isolates the wire.  Rank 0 prints one
    ``TRANSBENCH {json}`` line per row for the driver to parse."""
    import json

    rank = int(os.environ["HOROVOD_RANK"])
    hvd.init()
    from horovod_tpu import basics

    rt = basics.runtime()
    expect = os.environ.get("TRANSPORT_BENCH_EXPECT", "socket")
    cfg = rt.tuned_config()
    if expect == "shm":
        assert cfg.get("transport_shm"), \
            f"rank {rank}: no shm links negotiated: {cfg}"
    elif expect == "striped":
        want = int(os.environ.get("HOROVOD_TRANSPORT_STRIPES", "0"))
        assert cfg.get("transport_striped"), \
            f"rank {rank}: no striped links negotiated: {cfg}"
        assert cfg.get("transport_stripes") == want, \
            f"rank {rank}: negotiated {cfg.get('transport_stripes')} " \
            f"stripes, wanted {want}"

    rng = np.random.default_rng(rank)
    rows = []
    streams = (int(os.environ.get("HOROVOD_TRANSPORT_STRIPES", "0"))
               if expect == "striped" else 1)

    def timed(label, tensors, names):
        before = _transport_backend_totals(rt)
        t0 = time.perf_counter()
        for x, name in zip(tensors, names):
            hvd.allreduce(x, average=False, name=name)
        wall = time.perf_counter() - t0
        after = _transport_backend_totals(rt)
        nbytes = sum(int(x.nbytes) for x in tensors)
        link_bytes = sum(after[b]["bytes"] - before[b]["bytes"]
                         for b in after)
        # Link seconds are THREAD-CPU seconds (transport::PumpClockUs),
        # so bytes/seconds is per-stream bandwidth on a dedicated core —
        # stable under scheduler pressure — and the aggregate (x streams)
        # is what concurrent stripes deliver with cores/NIC queues of
        # their own.
        link_secs = sum(after[b]["seconds"] - before[b]["seconds"]
                        for b in after)
        link_bw = (link_bytes / link_secs / 2**20
                   if link_secs > 0 else 0.0)
        rows.append({
            "label": label,
            "payload_bytes": nbytes,
            "streams": streams,
            "sec_per_op": wall / len(tensors),
            "algbw_mb_per_sec": nbytes / wall / 2**20,
            "link_mb_per_sec": link_bw,
            "aggregate_link_mb_per_sec": link_bw * streams,
        })

    for n in sizes:
        x = rng.standard_normal(n).astype(np.float32)
        for i in range(2):
            hvd.allreduce(x, average=False, name=f"tb.warm{i}.{n}")
        timed(f"{n * 4 // 2**20}MB",
              [x] * iters, [f"tb.{i}.{n}" for i in range(iters)])
    # Sub-granule burst: 64 x 4 KiB ops measure per-op overhead on the
    # small-tensor path (ring slot reuse / stripe frame headers).
    small = [rng.standard_normal(1024).astype(np.float32)
             for _ in range(64)]
    for i, x in enumerate(small):
        hvd.allreduce(x, average=False, name=f"tb.smallwarm.{i}")
    timed("64x4KB", small, [f"tb.small.{i}" for i in range(64)])

    totals = _transport_backend_totals(rt)
    by_bytes = {b: totals[b]["bytes"] for b in totals}
    if expect == "shm":
        assert by_bytes["shm"] > 0 and by_bytes["socket"] == 0, \
            f"rank {rank}: shm lane leaked to sockets: {by_bytes}"
    elif expect == "striped":
        assert by_bytes["striped"] > 0 and by_bytes["shm"] == 0, \
            f"rank {rank}: striped lane engagement wrong: {by_bytes}"
    else:
        assert by_bytes["socket"] > 0 and by_bytes["shm"] == 0 \
            and by_bytes["striped"] == 0, \
            f"rank {rank}: socket lane engagement wrong: {by_bytes}"
    hvd.shutdown()
    if rank == 0:
        for r in rows:
            print("TRANSBENCH " + json.dumps(r), flush=True)


def run_transport_benchmark(out: Optional[str] = None,
                            verbose: bool = True) -> dict:
    """Transport-backend A/B (docs/performance.md, 'Transport
    backends'): spawn one ``hvdrun -np 2`` loopback run of
    :func:`run_transport_worker` per lane — single TCP socket, the
    shared-memory intra-host ring, and the striped multi-socket
    transport at 1/2/4 stripes — and report per-payload algorithm
    bandwidth side by side.

    ``stripes=1`` deliberately resolves to the plain socket backend
    (``transport::Enabled``), so the striped ratio is measured against
    an identical code path minus the frame/reassembly machinery.  Each
    worker asserts the forced backend actually carried the bytes, so a
    passing run certifies both the numbers and the selection plumbing.

    Targets (checked into the emitted dict, not enforced here): shm
    >= 1.5x single-socket algbw at 64 MB loopback; striped x4 >= 1.2x
    vs stripes=1; CRC32C framing (the ``socket`` vs ``socket_nocrc``
    A/B) < 5% link-bandwidth overhead at 64 MB.  Prints one BENCH JSON
    line and (with ``out``) writes the same dict as a JSON artifact (CI
    commits ``BENCH_transport.json``)."""
    import json
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    lanes = [
        ("socket", "socket", {"HOROVOD_TRANSPORT": "socket"}),
        # Checksum A/B: `socket` above rides the default CRC32C-framed
        # engine (HOROVOD_TRANSPORT_CHECKSUM=auto -> on); this lane is
        # the unframed fast path, so socket/socket_nocrc isolates the
        # wire-integrity overhead (docs/performance.md target < 5%).
        ("socket_nocrc", "socket", {"HOROVOD_TRANSPORT": "socket",
                                    "HOROVOD_TRANSPORT_CHECKSUM": "off"}),
        ("shm", "shm", {"HOROVOD_TRANSPORT": "shm"}),
        ("striped1", "socket", {"HOROVOD_TRANSPORT": "striped",
                                "HOROVOD_TRANSPORT_STRIPES": "1"}),
        ("striped2", "striped", {"HOROVOD_TRANSPORT": "striped",
                                 "HOROVOD_TRANSPORT_STRIPES": "2"}),
        ("striped4", "striped", {"HOROVOD_TRANSPORT": "striped",
                                 "HOROVOD_TRANSPORT_STRIPES": "4"}),
    ]

    def launch(name, expect, knobs) -> list:
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        env["TRANSPORT_BENCH_EXPECT"] = expect
        env.update(knobs)
        cmd = [sys.executable, "-m", "horovod_tpu.runner", "-np", "2",
               sys.executable, "-m", "horovod_tpu.benchmark",
               "--transport"]
        p = subprocess.run(cmd, env=env, capture_output=True, text=True,
                           timeout=600)
        if p.returncode != 0:
            raise RuntimeError(
                f"transport bench lane {name} failed rc={p.returncode}\n"
                f"{p.stdout[-2000:]}\n{p.stderr[-2000:]}")
        rows = [json.loads(line.split("TRANSBENCH ", 1)[1])
                for line in p.stdout.splitlines()
                if "TRANSBENCH " in line]
        if not rows:
            raise RuntimeError(
                f"transport bench lane {name} printed no TRANSBENCH "
                f"rows:\n{p.stdout[-2000:]}")
        return rows

    by_lane = {}
    for name, expect, knobs in lanes:
        by_lane[name] = {r["label"]: r for r in launch(name, expect,
                                                       knobs)}
        if verbose:
            for label, r in by_lane[name].items():
                print(f"{name:>8} {label:>7}: "
                      f"{r['algbw_mb_per_sec']:8.1f} MB/s algbw, "
                      f"{r['link_mb_per_sec']:8.1f} MB/s link, "
                      f"{r['sec_per_op'] * 1e3:7.2f} ms/op", flush=True)

    big = "64MB"
    # Headline ratios come from the link counters (thread-CPU seconds,
    # see run_transport_worker): per-stream pump bandwidth for the
    # shm-vs-socket A/B (one stream each), aggregate across stripes for
    # the striping A/B.  Wall-clock algbw ratios ride along for context
    # but on a single-core CI rig they measure the scheduler, not the
    # transport: every pump thread timeshares one core, so stripe
    # parallelism can never show up in wall time there.
    shm_vs_socket = (by_lane["shm"][big]["link_mb_per_sec"]
                     / by_lane["socket"][big]["link_mb_per_sec"])
    striped4_vs_1 = (by_lane["striped4"][big]["aggregate_link_mb_per_sec"]
                     / by_lane["striped1"][big]["aggregate_link_mb_per_sec"])
    # CRC overhead = lost link bandwidth fraction vs the unframed fast
    # path (clamped at 0: on a noisy rig the framed lane can win).
    checksum_overhead = max(
        0.0, 1.0 - (by_lane["socket"][big]["link_mb_per_sec"]
                    / by_lane["socket_nocrc"][big]["link_mb_per_sec"]))
    result = {
        "metric": "transport_backend_algbw",
        "np": 2,
        "rig": "loopback CPU",
        "cores": os.cpu_count(),
        "lanes": {name: sorted(rows.values(),
                               key=lambda r: r["payload_bytes"])
                  for name, rows in by_lane.items()},
        "shm_vs_socket_64mb": round(shm_vs_socket, 3),
        "shm_target": 1.5,
        "shm_vs_socket_64mb_wall": round(
            by_lane["shm"][big]["algbw_mb_per_sec"]
            / by_lane["socket"][big]["algbw_mb_per_sec"], 3),
        "striped4_vs_striped1_64mb": round(striped4_vs_1, 3),
        "striped_target": 1.2,
        "striped4_vs_striped1_64mb_wall": round(
            by_lane["striped4"][big]["algbw_mb_per_sec"]
            / by_lane["striped1"][big]["algbw_mb_per_sec"], 3),
        "checksum_overhead_64mb": round(checksum_overhead, 4),
        "checksum_overhead_target": 0.05,
        "backend_engagement_asserted": True,   # every worker asserted it
        "note": "link bandwidth = bytes / thread-CPU pump seconds, i.e. "
                "per-dedicated-core throughput; aggregate = x streams. "
                "Wall ratios are scheduler-bound on single-core rigs.",
    }
    if verbose:
        print(f"shm vs socket @64MB: {shm_vs_socket:.2f}x link "
              f"(target >= 1.5x); striped x4 vs x1 @64MB: "
              f"{striped4_vs_1:.2f}x aggregate link (target >= 1.2x); "
              f"CRC overhead @64MB: {checksum_overhead * 100:.1f}% "
              f"(target < 5%)", flush=True)
    print("BENCH " + json.dumps(result), flush=True)
    if out:
        with open(out, "w") as f:
            json.dump(result, f, indent=2)
            f.write("\n")
    return result


def run_serving_benchmark(out: Optional[str] = None, *,
                          num_requests: int = 64,
                          tokens_per_request: int = 8,
                          step_time: float = 0.002,
                          verbose: bool = False):
    """Offered load vs latency for the continuous-batching router
    (``horovod_tpu/serving/``), A/B-ing two batch policies: no batching
    (``max_batch=1``, one sequence per replica step) against continuous
    batching at ``max_batch=8``.

    The rig runs on a virtual clock — two in-process replicas, zero real
    sleeps, time advanced by a fixed simulated decode-step cost — so the
    lane is deterministic and finishes in milliseconds while still
    exercising the real router (queues, round-robin fill, join/leave at
    step boundaries).  Reported tokens/s and latencies are therefore
    properties of the BATCHING POLICY under the modeled step cost, not
    of any accelerator."""
    import json
    from horovod_tpu.serving import (LocalReplicaHandle, ReplicaWorker,
                                     Router, TenantConfig, ToyModel)

    rows = []
    for policy in (1, 8):
        for offered_rps in (50.0, 200.0, 800.0):
            vt = [0.0]  # virtual seconds; advanced per decode step
            replicas = [
                LocalReplicaHandle(ReplicaWorker(ToyModel(),
                                                 replica_id=f"r{i}"))
                for i in range(2)]
            router = Router(replicas,
                            [TenantConfig("bench", quota=1 << 30,
                                          slo_ms=0.0)],
                            max_batch=policy, clock=lambda: vt[0])
            arrivals = [i / offered_rps for i in range(num_requests)]
            pending = {}
            lats = []
            done = 0
            nxt = 0
            while done < num_requests:
                while nxt < num_requests and arrivals[nxt] <= vt[0]:
                    h = router.submit("bench", prompt_token=nxt,
                                      max_new_tokens=tokens_per_request)
                    assert h.rejected is None, h.rejected
                    pending[h.request_id] = (h, arrivals[nxt])
                    nxt += 1
                router.step()
                vt[0] += step_time
                for rid, (h, t0) in list(pending.items()):
                    if h.completed:
                        lats.append(vt[0] - t0)
                        done += 1
                        del pending[rid]
            router.close()
            lats.sort()
            rows.append({
                "policy_max_batch": policy,
                "offered_rps": offered_rps,
                "p50_ms": round(lats[len(lats) // 2] * 1e3, 3),
                "p99_ms": round(
                    lats[min(len(lats) - 1,
                             int(0.99 * len(lats)))] * 1e3, 3),
                "tokens_per_s": round(
                    num_requests * tokens_per_request / vt[0], 1),
            })
            if verbose:
                r = rows[-1]
                print(f"serving max_batch={policy} "
                      f"{offered_rps:g} req/s: p50 {r['p50_ms']} ms, "
                      f"p99 {r['p99_ms']} ms, "
                      f"{r['tokens_per_s']} tok/s", flush=True)
    result = {
        "metric": "serving_continuous_batching",
        "replicas": 2,
        "num_requests": num_requests,
        "tokens_per_request": tokens_per_request,
        "step_time_ms": step_time * 1e3,
        "rows": rows,
        "note": "virtual-clock rig: two in-process replicas with a "
                "fixed modeled decode-step cost; numbers compare "
                "batching policies, not hardware",
    }
    print("BENCH " + json.dumps(result), flush=True)
    if out:
        with open(out, "w") as f:
            json.dump(result, f, indent=2)
            f.write("\n")
    return result


def run_coordsim_benchmark(out: Optional[str] = None, *,
                           sizes=(8, 64, 256, 1024), ticks: int = 60,
                           verbose: bool = True) -> dict:
    """Control-plane message complexity: tree vs flat coordination
    (docs/control_plane.md) measured on the deterministic protocol
    simulator — no accelerator, no sockets, one process.

    For each world size the same fault-free episode runs twice: flat
    (every rank a direct child of the coordinator — the reference
    O(world) shape) and tree (host blocks + k-ary leader tree).  Two
    numbers per run: the worst per-tick fan-in any single node ingested
    (the hot-spot the coordinator's accept loop serializes) and the
    mean messages per tick across the whole fabric.  Tree must keep the
    fan-in bounded by ``arity + slots - 1`` — effectively O(log N) in
    depth — while flat grows linearly.

    Prints one BENCH JSON line and (with ``out``) writes the same dict;
    also publishes the ``hvd_coord_tick_messages`` gauge per (mode, n)
    when telemetry is on."""
    import json

    from horovod_tpu import telemetry
    from tools.coordsim.sim import Simulation

    rows = []
    for n in sizes:
        row = {"n": n}
        for mode, tree in (("flat", False), ("tree", True)):
            sim = Simulation(n, tree=tree, seed=7)
            stats = sim.run(ticks)
            fan_in = (stats["observed_coord_fan_in"] if mode == "flat"
                      else stats["observed_max_fan_in"])
            per_tick = round(stats["net"]["sent"] / max(stats["ticks"], 1),
                             1)
            row[f"{mode}_max_fan_in"] = fan_in
            row[f"{mode}_msgs_per_tick"] = per_tick
            if mode == "tree":
                row["tree_depth"] = stats["tree_depth"]
            telemetry.gauge(
                "hvd_coord_tick_messages",
                "Worst per-tick control-message fan-in any node ingested "
                "(coordsim benchmark lane)", mode=mode, n=str(n)
            ).set(float(fan_in))
        # Every round still takes one full sweep of announcements, so
        # total traffic is O(N) in both modes; the win is the HOT SPOT —
        # no node ever serializes more than the bounded tree fan-in.
        row["fan_in_ratio"] = round(
            row["flat_max_fan_in"] / max(row["tree_max_fan_in"], 1), 2)
        rows.append(row)
        if verbose:
            print(f"coordsim n={n:5d}: flat fan-in "
                  f"{row['flat_max_fan_in']:4d} -> tree "
                  f"{row['tree_max_fan_in']:3d} "
                  f"(depth {row['tree_depth']}, "
                  f"ratio {row['fan_in_ratio']:.1f}x)", flush=True)
    result = {
        "metric": "coord_tree_vs_flat_fan_in",
        "ticks": ticks,
        "rows": rows,
    }
    print("BENCH " + json.dumps(result), flush=True)
    if out:
        with open(out, "w") as f:
            json.dump(result, f, indent=2)
            f.write("\n")
    return result


def _main():
    import argparse
    parser = argparse.ArgumentParser(
        description="Synthetic benchmark (reference "
                    "examples/tensorflow2_synthetic_benchmark.py)")
    parser.add_argument("--model", default="resnet50")
    parser.add_argument("--batch-size", type=int, default=64,
                        help="per-chip batch size")
    parser.add_argument("--image-size", type=int, default=224)
    parser.add_argument("--num-warmup-batches", type=int, default=5)
    parser.add_argument("--num-batches-per-iter", type=int, default=10)
    parser.add_argument("--num-iters", type=int, default=10)
    parser.add_argument("--efficiency", action="store_true",
                        help="weak-scaling efficiency: 1 device vs all")
    parser.add_argument("--profile", action="store_true",
                        help="trace one round and print the per-op/"
                             "per-layer device-time breakdown")
    parser.add_argument("--stem", default="conv7",
                        choices=("conv7", "s2d", "s2d_fused"))
    parser.add_argument("--lm", action="store_true",
                        help="run the transformer-LM lane instead of the "
                             "ResNet harness")
    parser.add_argument("--step-guard", action="store_true",
                        help="measure the NaN/Inf step-guard overhead: "
                             "baseline vs HOROVOD_STEP_GUARD=skip "
                             "(target < 2%% step time)")
    parser.add_argument("--shard-optimizer", action="store_true",
                        help="LM lane with the ZeRO-1 sharded update over "
                             "all devices (reports MFU + per-device "
                             "live-memory bytes)")
    parser.add_argument("--compression", default=None, metavar="CODEC",
                        help="A/B the LM ZeRO lane with gradient codec "
                             "CODEC (bf16, fp16, int8, powersgd[:rank]) "
                             "against the uncompressed wire; prints a "
                             "BENCH JSON row with the wire-byte ratio "
                             "and loss delta")
    parser.add_argument("--hierarchical", action="store_true",
                        help="A/B the 2-level eager allreduce vs the "
                             "flat ring over two hvdrun -np 4 loopback "
                             "runs; prints a BENCH JSON row (inside a "
                             "launched rank this flag selects the "
                             "worker half instead)")
    parser.add_argument("--transport", action="store_true",
                        help="A/B the transport backends (single socket "
                             "vs shm ring vs striped x1/x2/x4) over "
                             "hvdrun -np 2 loopback runs; prints a "
                             "BENCH JSON row (inside a launched rank "
                             "this flag selects the worker half "
                             "instead)")
    parser.add_argument("--serving", action="store_true",
                        help="offered load vs p50/p99 latency and "
                             "tokens/s for the continuous-batching "
                             "router at max_batch 1 vs 8 (virtual-clock "
                             "rig, no accelerator needed)")
    parser.add_argument("--coordsim", action="store_true",
                        help="tree vs flat coordination message "
                             "complexity at N in {8,64,256,1024} on the "
                             "protocol simulator (no accelerator, no "
                             "sockets)")
    parser.add_argument("--out", default=None, metavar="FILE",
                        help="also write the BENCH result dict to FILE "
                             "(e.g. BENCH_hier.json)")
    parser.add_argument("--d-model", type=int, default=None)
    parser.add_argument("--n-layers", type=int, default=None)
    parser.add_argument("--seq-len", type=int, default=None)
    parser.add_argument("--vocab-size", type=int, default=None)
    args = parser.parse_args()

    kwargs = dict(image_size=args.image_size,
                  num_warmup_batches=args.num_warmup_batches,
                  num_batches_per_iter=args.num_batches_per_iter,
                  num_iters=args.num_iters)
    if args.coordsim:
        run_coordsim_benchmark(out=args.out, verbose=True)
        return
    if args.serving:
        run_serving_benchmark(out=args.out, verbose=True)
        return
    if args.hierarchical:
        if "HOROVOD_RANK" in os.environ:
            run_hierarchical_worker()
        else:
            run_hierarchical_benchmark(out=args.out)
        return
    if args.transport:
        if "HOROVOD_RANK" in os.environ:
            run_transport_worker()
        else:
            run_transport_benchmark(out=args.out)
        return
    if args.lm or args.shard_optimizer or args.compression:
        lm_kwargs = dict(num_warmup_batches=args.num_warmup_batches,
                         num_batches_per_iter=args.num_batches_per_iter,
                         num_iters=args.num_iters,
                         shard_optimizer=args.shard_optimizer)
        if jax.devices()[0].platform == "cpu":
            # CPU run = plumbing smoke (MFU needs real chips): downsize to
            # a config the interpreter finishes in seconds, dense
            # attention (no Pallas on CPU).
            lm_kwargs.update(d_model=128, n_layers=2, n_heads=4,
                             d_ff=256, vocab_size=512, seq_len=64,
                             batch_size=2, attention="dense",
                             num_batches_per_iter=min(
                                 args.num_batches_per_iter, 2),
                             num_iters=min(args.num_iters, 3))
        for k, v in (("d_model", args.d_model),
                     ("n_layers", args.n_layers),
                     ("seq_len", args.seq_len),
                     ("vocab_size", args.vocab_size)):
            if v is not None:
                lm_kwargs[k] = v
        # --batch-size is the ResNet knob (default 64); the LM lane keeps
        # its own default of 8/chip unless the flag was set explicitly.
        bs = lm_kwargs.pop("batch_size",
                           args.batch_size if args.batch_size != 64 else 8)
        if args.compression:
            run_compression_benchmark(args.compression, batch_size=bs,
                                      **lm_kwargs)
        else:
            run_lm_benchmark(batch_size=bs, **lm_kwargs)
    elif args.step_guard:
        sg_kwargs = dict(kwargs, stem=args.stem)
        model, bs = args.model, args.batch_size
        if jax.devices()[0].platform == "cpu":
            # CPU run = plumbing smoke: the lane compiles the step TWICE
            # (baseline + guarded), so downsize to finish in seconds.
            model = "resnet18" if args.model == "resnet50" else args.model
            bs = min(bs, 4)
            sg_kwargs.update(image_size=min(args.image_size, 64),
                             num_warmup_batches=1,
                             num_batches_per_iter=min(
                                 args.num_batches_per_iter, 2),
                             num_iters=min(args.num_iters, 3))
        run_step_guard_benchmark(model, bs, **sg_kwargs)
    elif args.profile:
        run_profile(args.model, args.batch_size, args.image_size,
                    steps=args.num_batches_per_iter, stem=args.stem)
    elif args.efficiency:
        run_scaling_efficiency(args.model, args.batch_size, **kwargs)
    else:
        run_synthetic_benchmark(args.model, args.batch_size, stem=args.stem,
                                **kwargs)


if __name__ == "__main__":
    _main()

#include "stall_inspector.h"

#include <sstream>

#include "transport.h"

namespace hvd {

StallInspector::StallInspector()
    : warn_s_(EnvDouble("HOROVOD_STALL_CHECK_TIME_SECONDS", 60.0)),
      shutdown_s_(EnvDouble("HOROVOD_STALL_SHUTDOWN_TIME_SECONDS", 0.0)),
      last_report_(std::chrono::steady_clock::now()) {}

bool StallInspector::Check(const std::string& name,
                           const std::vector<bool>& submitted,
                           std::chrono::steady_clock::time_point first_seen) {
  auto now = std::chrono::steady_clock::now();
  double age = std::chrono::duration<double>(now - first_seen).count();
  if (age < warn_s_) return false;
  // Rate-limit warnings to one batch per warning interval.
  if (std::chrono::duration<double>(now - last_report_).count() >= warn_s_) {
    last_report_ = now;
    std::ostringstream ready, missing;
    for (size_t r = 0; r < submitted.size(); ++r)
      (submitted[r] ? ready : missing) << r << " ";
    const bool sched_check = EnvBool("HOROVOD_SCHEDULE_CHECK", false);
    // Name the coordination plane: after a failover the coordinator is no
    // longer rank 0, and a stall right after an election usually means
    // some rank is still talking to the dead epoch.
    const int64_t coord_rank = EnvInt("HOROVOD_COORD_RANK", 0);
    const int64_t coord_epoch = EnvInt("HOROVOD_COORD_EPOCH", 0);
    const int64_t elections = EnvInt("HOROVOD_COORD_ELECTIONS", 0);
    // Per-link transport state (backend + bytes still in flight each
    // way): a stall with one link mid-exchange names the wedged peer
    // and backend directly, instead of leaving it to rank arithmetic.
    const std::string links = transport::DescribeAll();
    LOG(Warning) << "One or more tensors were submitted to be reduced, "
                 << "gathered or broadcasted by subset of ranks and are "
                 << "waiting for remainder of ranks for more than "
                 << warn_s_ << " seconds. Tensor: " << name
                 << " ready ranks: [" << ready.str() << "] missing ranks: ["
                 << missing.str() << "] Coordinator: rank " << coord_rank
                 << ", lease epoch " << coord_epoch << ", elections so far "
                 << elections << "."
                 << (sched_check ? "" :
                     " Rerun with HOROVOD_SCHEDULE_CHECK=1 to catch the "
                     "first diverging submission (rank, call index, "
                     "mismatched field) instead of waiting out the stall.")
                 << (links.empty() ? "" : "\n" + links);
  }
  return shutdown_s_ > 0 && age >= shutdown_s_;
}

}  // namespace hvd

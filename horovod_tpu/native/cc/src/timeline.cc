#include "timeline.h"

#include <chrono>

namespace hvd {

namespace {

// Tensor names are user-controlled; escape them or one quote corrupts the
// whole trace.
std::string JsonEscape(const std::string& s) {
  std::string out;
  out.reserve(s.size());
  for (char c : s) {
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\n': out += "\\n"; break;
      case '\t': out += "\\t"; break;
      case '\r': out += "\\r"; break;
      default:
        if (static_cast<unsigned char>(c) < 0x20) {
          char buf[8];
          std::snprintf(buf, sizeof(buf), "\\u%04x", c);
          out += buf;
        } else {
          out += c;
        }
    }
  }
  return out;
}

}  // namespace

void Timeline::Initialize(const std::string& filename, int rank) {
  if (filename.empty() || rank != 0 || initialized_.load()) return;
  file_ = std::fopen(filename.c_str(), "w");
  if (file_ == nullptr) {
    LOG(Error) << "could not open timeline file " << filename;
    return;
  }
  std::fputs("[\n", file_);
  mark_cycles_ = EnvBool("HOROVOD_TIMELINE_MARK_CYCLES", false);
  start_ = std::chrono::steady_clock::now();
  stop_.store(false);
  writer_ = std::thread(&Timeline::WriterLoop, this);
  initialized_.store(true);
}

Timeline::~Timeline() { Shutdown(); }

void Timeline::Shutdown() {
  if (!initialized_.load()) return;
  initialized_.store(false);
  {
    std::lock_guard<std::mutex> lk(mu_);
    stop_.store(true);
  }
  cv_.notify_all();
  if (writer_.joinable()) writer_.join();
  if (file_ != nullptr) {
    // Terminal no-comma event keeps the file valid JSON.
    std::fputs("{\"name\": \"SHUTDOWN\", \"ph\": \"i\", \"pid\": 0, "
               "\"tid\": 0, \"ts\": 0, \"s\": \"g\"}\n]\n", file_);
    std::fclose(file_);
    file_ = nullptr;
  }
}

int64_t Timeline::TidFor(const std::string& tensor) {
  auto it = tids_.find(tensor);
  if (it != tids_.end()) return it->second;
  int64_t tid = next_tid_++;
  tids_[tensor] = tid;
  // Name the row after the tensor (reference emits the same metadata event).
  std::fprintf(file_,
               "{\"name\": \"thread_name\", \"ph\": \"M\", \"pid\": 0, "
               "\"tid\": %lld, \"args\": {\"name\": \"%s\"}},\n",
               static_cast<long long>(tid), JsonEscape(tensor).c_str());
  return tid;
}

void Timeline::Emit(char phase, const std::string& name,
                    const std::string& tensor) {
  if (!initialized_.load()) return;
  auto ts = std::chrono::duration_cast<std::chrono::microseconds>(
                std::chrono::steady_clock::now() - start_).count();
  {
    std::lock_guard<std::mutex> lk(mu_);
    queue_.push_back(Event{phase, name, tensor, ts});
  }
  cv_.notify_one();
}

void Timeline::WriterLoop() {
  std::unique_lock<std::mutex> lk(mu_);
  while (!stop_.load() || !queue_.empty()) {
    cv_.wait(lk, [&] { return stop_.load() || !queue_.empty(); });
    while (!queue_.empty()) {
      Event e = std::move(queue_.front());
      queue_.pop_front();
      lk.unlock();
      int64_t tid = e.tensor.empty() ? 0 : TidFor(e.tensor);
      std::string ename = JsonEscape(e.name);
      if (e.phase == 'i') {
        std::fprintf(file_,
                     "{\"name\": \"%s\", \"ph\": \"i\", \"pid\": 0, "
                     "\"tid\": %lld, \"ts\": %lld, \"s\": \"g\"},\n",
                     ename.c_str(), static_cast<long long>(tid),
                     static_cast<long long>(e.ts_us));
      } else {
        std::fprintf(file_,
                     "{\"name\": \"%s\", \"ph\": \"%c\", \"pid\": 0, "
                     "\"tid\": %lld, \"ts\": %lld},\n",
                     ename.c_str(), e.phase, static_cast<long long>(tid),
                     static_cast<long long>(e.ts_us));
      }
      lk.lock();
    }
    std::fflush(file_);
  }
}

void Timeline::NegotiateStart(const std::string& tensor, OpType op) {
  Emit('B', std::string("NEGOTIATE_") +
                [&] {
                  std::string s = OpTypeName(op);
                  for (auto& c : s) c = std::toupper(c);
                  return s;
                }(),
       tensor);
}

void Timeline::NegotiateEnd(const std::string& tensor) { Emit('E', "", tensor); }

void Timeline::Start(const std::string& tensor, const std::string& op_name) {
  Emit('B', op_name, tensor);
}

void Timeline::ActivityStart(const std::string& tensor,
                             const std::string& activity) {
  Emit('B', activity, tensor);
}

void Timeline::ActivityEnd(const std::string& tensor) { Emit('E', "", tensor); }

void Timeline::End(const std::string& tensor) { Emit('E', "", tensor); }

void Timeline::MarkCycleStart() {
  if (mark_cycles_) Emit('i', "CYCLE_START", "");
}

}  // namespace hvd
